"""Host-platform forcing helpers for driver/test entry points.

The container's sitecustomize registers an axon TPU-tunnel PJRT plugin at
interpreter start and sets the ``jax_platforms`` CONFIG to the tunnel
(config beats the ``JAX_PLATFORMS`` env var), and the tunnel admits one
process at a time — so any process that should run on the host CPU (tests,
dryruns, bench fallbacks) must force the config back before first backend
use. Shared by ``bench.py``, ``__graft_entry__.py`` and
``tests/conftest.py`` so the workaround lives in exactly one place. Lives
at the repo root (not inside ``mxnet_tpu``) because it must be importable
before the package's heavy ``__init__`` touches jax.
"""
from __future__ import annotations

import os
import re

__all__ = ["force_cpu_platform"]


def force_cpu_platform(num_devices=None):
    """Force jax onto the host CPU platform, optionally with ``num_devices``
    virtual devices (``--xla_force_host_platform_device_count``).

    Safe to call more than once; a no-op (best effort) if a backend was
    already initialized.
    """
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    if num_devices is not None:
        flag = f"--xla_force_host_platform_device_count={num_devices}"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" in flags:
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", flag, flags)
        else:
            flags = (flags + " " + flag).strip()
        os.environ["XLA_FLAGS"] = flags
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # backend already initialized; use whatever devices exist
