"""Flash attention + ring attention + TransformerLM.

NEW capability vs the reference (SURVEY §5.7) — long-context/sequence
parallel is first-class in the TPU build, so it gets first-class tests.
"""
import numpy as onp
import jax
import jax.numpy as jnp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon
from mxnet_tpu.ops.flash_attention import flash_attention, _ref_attention
from mxnet_tpu import parallel

B, H, S, D = 2, 3, 64, 16


@pytest.fixture(scope="module")
def qkv():
    rs = onp.random.RandomState(0)
    return tuple(jnp.asarray(rs.randn(B, H, S, D).astype("f"))
                 for _ in range(3))


def test_flash_matches_reference(qkv):
    q, k, v = qkv
    ref = flash_attention(q, k, v, use_pallas=False)
    pal = flash_attention(q, k, v, use_pallas=True)  # interpret off-TPU
    assert float(jnp.abs(ref - pal).max()) < 1e-5


def test_flash_causal(qkv):
    q, k, v = qkv
    ref = flash_attention(q, k, v, causal=True, use_pallas=False)
    pal = flash_attention(q, k, v, causal=True, use_pallas=True)
    assert float(jnp.abs(ref - pal).max()) < 1e-5
    # causality: output at position t must not depend on k/v beyond t
    k2 = k.at[:, :, S // 2:].set(999.0)
    v2 = v.at[:, :, S // 2:].set(999.0)
    ref2 = flash_attention(q, k2, v2, causal=True, use_pallas=False)
    assert float(jnp.abs(ref[:, :, :S // 2] - ref2[:, :, :S // 2]).max()) \
        < 1e-6


def test_flash_ragged_shapes():
    rs = onp.random.RandomState(1)
    q = jnp.asarray(rs.randn(1, 2, 100, 24).astype("f"))  # not /128
    k = jnp.asarray(rs.randn(1, 2, 70, 24).astype("f"))
    v = jnp.asarray(rs.randn(1, 2, 70, 24).astype("f"))
    ref = flash_attention(q, k, v, use_pallas=False)
    pal = flash_attention(q, k, v, use_pallas=True)
    assert float(jnp.abs(ref - pal).max()) < 1e-5


def test_flash_causal_decode_alignment():
    """S_q=1 against a long KV cache must attend to the WHOLE prefix
    (bottom-right causal alignment), matching full-sequence attention."""
    rs = onp.random.RandomState(3)
    S_k = 40
    q_full = jnp.asarray(rs.randn(1, 2, S_k, 8).astype("f"))
    k = jnp.asarray(rs.randn(1, 2, S_k, 8).astype("f"))
    v = jnp.asarray(rs.randn(1, 2, S_k, 8).astype("f"))
    full = flash_attention(q_full, k, v, causal=True, use_pallas=False)
    last = flash_attention(q_full[:, :, -1:], k, v, causal=True,
                           use_pallas=False)
    assert float(jnp.abs(full[:, :, -1:] - last).max()) < 1e-5
    last_p = flash_attention(q_full[:, :, -1:], k, v, causal=True,
                             use_pallas=True)
    assert float(jnp.abs(full[:, :, -1:] - last_p).max()) < 1e-5


def test_flash_grad(qkv):
    q, k, v = qkv
    gq = jax.grad(lambda q: flash_attention(q, k, v, causal=True).sum())(q)
    gref = jax.grad(lambda q: _ref_attention(
        q, k, v, 1.0 / (D ** 0.5), True, S).sum())(q)
    assert float(jnp.abs(gq - gref).max()) < 1e-5


def test_ring_attention_matches(qkv):
    q, k, v = qkv
    mesh = parallel.make_mesh({"sp": 8})
    for causal in (False, True):
        ref = flash_attention(q, k, v, causal=causal, use_pallas=False)
        ring = parallel.ring_attention(q, k, v, mesh=mesh, causal=causal)
        assert float(jnp.abs(ref - ring).max()) < 1e-5, causal


def test_nd_flash_attention_op_tape():
    rs = onp.random.RandomState(2)
    q = nd.array(rs.randn(1, 2, 32, 8).astype("f"))
    k = nd.array(rs.randn(1, 2, 32, 8).astype("f"))
    v = nd.array(rs.randn(1, 2, 32, 8).astype("f"))
    q.attach_grad()
    with autograd.record():
        out = nd.flash_attention(q, k, v, causal=True)
        loss = nd.sum(out)
    loss.backward()
    assert q.grad.shape == q.shape
    assert float(nd.sum(nd.abs(q.grad)).asnumpy()) > 0


def test_transformer_lm_trains():
    from mxnet_tpu.models import TransformerLM

    mx.random.seed(0)
    net = TransformerLM(vocab_size=40, embed_dim=32, num_layers=1,
                        num_heads=4, max_len=32, tie_weights=True)
    net.initialize(mx.init.Xavier())
    toks = nd.array(onp.random.RandomState(0).randint(0, 40, (4, 12))
                    .astype("f"))
    lf = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 1e-2})
    first = None
    for _ in range(10):
        with autograd.record():
            logits = net(toks)
            l = lf(logits[:, :-1].reshape(4 * 11, 40),
                   toks[:, 1:].reshape(4 * 11)).mean()
        l.backward()
        tr.step(4)
        first = first if first is not None else float(l.asscalar())
    assert float(l.asscalar()) < first
    net.hybridize()
    assert net(toks).shape == (4, 12, 40)


def test_ring_attention_eager_grads():
    """Regression: every upstream param must receive gradient through the
    eager tape when attention runs as the ring variant."""
    from mxnet_tpu.models import TransformerLM

    mesh = parallel.make_mesh({"sp": 8})
    mx.random.seed(0)
    net = TransformerLM(vocab_size=20, embed_dim=16, num_layers=1,
                        num_heads=2, max_len=16, ring_axis="sp")
    net.initialize(mx.init.Xavier())
    toks = nd.array(onp.random.RandomState(0).randint(0, 20, (2, 16))
                    .astype("f"))
    lf = gluon.loss.SoftmaxCrossEntropyLoss()
    with parallel.mesh_scope(mesh):
        with autograd.record():
            logits = net(toks)
            l = lf(logits.reshape(-1, 20), nd.zeros((32,))).mean()
        l.backward()
    for name, p in sorted(net.collect_params().items()):
        if p.grad_req != "null":
            g = float(nd.sum(nd.abs(p.grad())).asnumpy())
            assert g > 0, f"zero grad for {name}"


def test_transformer_lm_ring_parity():
    from mxnet_tpu.models import TransformerLM

    mesh = parallel.make_mesh({"sp": 8})
    mx.random.seed(1)
    net = TransformerLM(vocab_size=30, embed_dim=16, num_layers=1,
                        num_heads=2, max_len=32)
    net.initialize(mx.init.Xavier())
    toks = nd.array(onp.random.RandomState(1).randint(0, 30, (2, 16))
                    .astype("f"))
    ref = net(toks).asnumpy()
    # same params, ring attention over the 8-way sequence mesh
    for blk in net.blocks._children.values():
        blk.attn._ring_axis = "sp"
    with parallel.mesh_scope(mesh):
        ring = net(toks).asnumpy()
    assert onp.abs(ref - ring).max() < 1e-4


def test_ulysses_attention_matches(qkv):
    """All-to-all sequence parallelism: same math as single-device
    attention (H=3 not divisible by 8 → use a 2-way sp axis... H must
    divide; build H=8-compatible shapes here)."""
    rs = onp.random.RandomState(3)
    q, k, v = (jnp.asarray(rs.randn(2, 8, 64, 16).astype("f"))
               for _ in range(3))
    mesh = parallel.make_mesh({"sp": 8})
    for causal in (False, True):
        ref = flash_attention(q, k, v, causal=causal, use_pallas=False)
        uly = parallel.ulysses_attention(q, k, v, mesh=mesh,
                                         causal=causal)
        assert float(jnp.abs(ref - uly).max()) < 1e-5, causal


def test_ulysses_matches_ring(qkv):
    rs = onp.random.RandomState(4)
    q, k, v = (jnp.asarray(rs.randn(1, 8, 32, 8).astype("f"))
               for _ in range(3))
    mesh = parallel.make_mesh({"sp": 8})
    ring = parallel.ring_attention(q, k, v, mesh=mesh, causal=True)
    uly = parallel.ulysses_attention(q, k, v, mesh=mesh, causal=True)
    assert float(jnp.abs(ring - uly).max()) < 1e-5


def test_ulysses_head_divisibility_error():
    rs = onp.random.RandomState(5)
    q = jnp.asarray(rs.randn(1, 3, 32, 8).astype("f"))  # 3 heads, sp=8
    mesh = parallel.make_mesh({"sp": 8})
    with pytest.raises(ValueError, match="not divisible"):
        parallel.ulysses_attention(q, q, q, mesh=mesh)


def test_ulysses_dp_sp_mesh_and_grads():
    """dp x sp mesh + eager tape gradients through the all-to-all."""
    rs = onp.random.RandomState(6)
    mesh = parallel.make_mesh({"dp": 2, "sp": 4})
    q = nd.array(rs.randn(2, 4, 32, 8).astype("f"))
    k = nd.array(rs.randn(2, 4, 32, 8).astype("f"))
    v = nd.array(rs.randn(2, 4, 32, 8).astype("f"))
    q.attach_grad()
    with autograd.record():
        out = parallel.ulysses_attention(q, k, v, mesh=mesh,
                                         batch_axis="dp", causal=True)
        loss = nd.sum(out)
    loss.backward()
    assert q.grad.shape == q.shape
    assert float(nd.sum(nd.abs(q.grad)).asnumpy()) > 0
    ref = flash_attention(q.data, k.data, v.data, causal=True,
                          use_pallas=False)
    assert float(jnp.abs(ref - out.data).max()) < 1e-5


def test_transformer_lm_ulysses_parity():
    from mxnet_tpu.models import TransformerLM

    mesh = parallel.make_mesh({"sp": 8})
    mx.random.seed(2)
    net = TransformerLM(vocab_size=30, embed_dim=32, num_layers=1,
                        num_heads=8, max_len=32)
    net.initialize(mx.init.Xavier())
    toks = nd.array(onp.random.RandomState(2).randint(0, 30, (2, 16))
                    .astype("f"))
    ref = net(toks).asnumpy()
    for blk in net.blocks._children.values():
        blk.attn._ring_axis = "sp"
        blk.attn._sp_mode = "ulysses"
    with parallel.mesh_scope(mesh):
        uly = net(toks).asnumpy()
    assert onp.abs(ref - uly).max() < 1e-4
