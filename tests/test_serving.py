"""mxnet_tpu.serving — in-process tier-1 coverage (no sockets here; the
HTTP end-to-end test lives in test_serving_http.py, marked slow).

Covers: InferenceSession bucket padding/chunking bitwise-correctness,
warm-start (second session resolves every bucket from disk with ZERO
retraces), the export -> SymbolBlock.imports loader path with and
without AMP, DynamicBatcher coalescing / per-request failure isolation /
backpressure / timeout / graceful drain / pass-through, and the
profiler + runtime observability surface."""
import os
import threading
import time

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, serving
from mxnet_tpu.gluon import nn
from mxnet_tpu.utils import compile_cache as cc

nd = mx.nd


def _mlp(in_dim=8, out_dim=4, seed=0):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(out_dim))
    net.initialize()
    with autograd.pause(train_mode=False):
        net(nd.zeros((1, in_dim)))
    return net


def _session(net=None, buckets=(1, 2, 4, 8), **kw):
    return serving.InferenceSession(net or _mlp(),
                                    input_shapes=[(1, 8)],
                                    buckets=list(buckets), **kw)


def _ref(net, x):
    with autograd.pause(train_mode=False):
        return net(nd.array(x)).asnumpy()


@pytest.fixture(autouse=True)
def _fresh_counters():
    serving.reset_serving_counters()
    yield
    serving.reset_serving_counters()


# ---------------------------------------------------------------------------
# InferenceSession

def test_session_bitwise_vs_eager_across_buckets():
    net = _mlp()
    sess = _session(net)
    for batch in (1, 2, 3, 5, 8):
        x = onp.random.RandomState(batch).rand(batch, 8).astype("float32")
        out = sess.predict(x).asnumpy()
        assert out.shape == (batch, 4)
        assert onp.array_equal(out, _ref(net, x)), \
            f"padding not row-bitwise at batch {batch}"


def test_session_chunks_oversized_batches():
    net = _mlp()
    sess = _session(net, buckets=(1, 4))
    x = onp.random.RandomState(0).rand(11, 8).astype("float32")
    assert onp.array_equal(sess.predict(x).asnumpy(), _ref(net, x))


def test_session_validation():
    sess = _session()
    with pytest.raises(ValueError):
        sess.predict(onp.zeros((2, 5), dtype="float32"))  # row shape
    with pytest.raises(ValueError):
        sess.predict(onp.zeros((2, 8)), onp.zeros((2, 8)))  # arity
    with pytest.raises(ValueError):
        sess.predict(onp.zeros((0, 8), dtype="float32"))  # empty


def test_session_rejects_wrong_dtype_ndarray():
    """A mismatched-dtype DEVICE array must fail validation per-request:
    past it, the aval mismatch would raise inside the AOT executable and
    permanently degrade that bucket to the jit path (GuardedCompiled
    nulls its Compiled on error) — losing the zero-retrace contract."""
    net = _mlp()
    sess = _session(net)
    with pytest.raises(ValueError, match="dtype"):
        sess.predict(nd.zeros((2, 8), dtype="int32"))
    # the right dtype sails through on the device-native path
    x = onp.random.RandomState(9).rand(2, 8).astype("float32")
    assert onp.array_equal(sess.predict(nd.array(x)).asnumpy(),
                           _ref(net, x))


def test_session_accepts_plain_lists():
    net = _mlp()
    sess = _session(net)
    x = [[float(i + j) for j in range(8)] for i in range(2)]
    assert onp.array_equal(
        sess.predict(x).asnumpy(),
        _ref(net, onp.asarray(x, dtype="float32")))


def test_session_refresh_params_tracks_weight_updates():
    net = _mlp()
    sess = _session(net, buckets=(2,))
    x = onp.random.RandomState(1).rand(2, 8).astype("float32")
    before = sess.predict(x).asnumpy()
    for _, p in net.collect_params().items():
        p.set_data(p.data() * 2.0)
    # stale snapshot until refreshed — then bitwise with the new weights
    sess.refresh_params()
    after = sess.predict(x).asnumpy()
    assert not onp.array_equal(before, after)
    assert onp.array_equal(after, _ref(net, x))


def test_session_requires_exactly_one_input_spec_source():
    with pytest.raises(mx.MXNetError):
        serving.InferenceSession(_mlp())
    with pytest.raises(mx.MXNetError):
        serving.InferenceSession(_mlp(), example=nd.zeros((1, 8)),
                                 input_shapes=[(1, 8)])


def test_parse_buckets():
    assert serving.parse_buckets(None, 32) == [1, 2, 4, 8, 16, 32]
    assert serving.parse_buckets("pow2", 6) == [1, 2, 4, 6]
    assert serving.parse_buckets("mult:3", 12) == [3, 6, 9, 12]
    assert serving.parse_buckets("1, 5,9", 16) == [1, 5, 9, 16]
    with pytest.raises(mx.MXNetError):
        serving.parse_buckets("nope", 8)
    with pytest.raises(mx.MXNetError):
        serving.parse_buckets("0,4", 8)
    # explicit entries above max_batch fail fast, never silently drop
    with pytest.raises(mx.MXNetError):
        serving.parse_buckets("1,4,16,64", 32)


def test_warm_start_zero_retraces(tmp_path, monkeypatch):
    """The round-10 acceptance criterion: a second session over the
    same model resolves every bucket executable from the disk tier —
    zero traces, zero XLA compiles before the first request."""
    monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR", str(tmp_path))
    net = _mlp(seed=3)
    cold = _session(net, buckets=(1, 4))
    x = onp.random.RandomState(5).rand(3, 8).astype("float32")
    cold_out = cold.predict(x).asnumpy()
    cold_stats = serving.serving_stats()
    assert cold_stats["warm_compiles"] == 2

    serving.reset_serving_counters()
    cc.reset_compile_cache_counters()
    warm = _session(net, buckets=(1, 4))
    warm_out = warm.predict(x).asnumpy()
    st = cc.compile_cache_stats()
    assert st["retraces"] == 0, "warm session must not trace"
    assert st["disk_hits"] == 2
    assert serving.serving_stats()["warm_disk_hits"] == 2
    assert warm.warm
    assert onp.array_equal(cold_out, warm_out)


def test_unstable_graph_falls_back_to_memory_only(tmp_path, monkeypatch):
    """A block that cannot symbol-trace still serves — it just compiles
    per process instead of hitting the disk tier."""
    monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR", str(tmp_path))

    class Opaque(mx.gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            with self.name_scope():
                self.fc = nn.Dense(4)

        def forward(self, x):
            # Symbol has no .shape — the sym trace fails here, the
            # jit eval trace (NDArray in) sails through
            assert x.shape[0] >= 0
            return self.fc(x)

    net = Opaque()
    net.initialize()
    with autograd.pause(train_mode=False):
        net(nd.zeros((1, 8)))
    sess = serving.InferenceSession(net, input_shapes=[(1, 8)],
                                    buckets=[2])
    assert sess._graph_sig is None
    x = onp.random.RandomState(2).rand(2, 8).astype("float32")
    assert onp.array_equal(sess.predict(x).asnumpy(), _ref(net, x))
    # nothing persisted under an unstable fingerprint
    assert not [f for f in os.listdir(str(tmp_path))
                if f.endswith(".mxc")]


# ---------------------------------------------------------------------------
# export -> imports loader path (satellite: with and without AMP)

def test_export_imports_roundtrip_bitwise(tmp_path):
    net = _mlp(seed=11)
    net.hybridize()
    x = onp.random.RandomState(4).rand(3, 8).astype("float32")
    ref = _ref(net, x)
    net.export(str(tmp_path / "model"), epoch=0)

    loaded = mx.gluon.SymbolBlock.imports(
        str(tmp_path / "model-symbol.json"), None,
        str(tmp_path / "model-0000.params"))
    # inferred data inputs: exactly the non-parameter free variable
    assert [i.name for i in loaded._inputs] == ["data"]
    assert onp.array_equal(_ref(loaded, x), ref)

    sess = serving.InferenceSession.load(
        str(tmp_path / "model"), input_shapes=[(1, 8)], buckets=[1, 4])
    assert onp.array_equal(sess.predict(x).asnumpy(), ref)


def test_export_imports_roundtrip_bitwise_with_amp(tmp_path):
    from mxnet_tpu.contrib import amp

    net = _mlp(seed=13)
    net.hybridize()
    x = onp.random.RandomState(6).rand(4, 8).astype("float32")
    net.export(str(tmp_path / "amp_model"), epoch=0)
    amp.init("bfloat16")
    try:
        ref = _ref(net, x)
        sess = serving.InferenceSession.load(
            str(tmp_path / "amp_model"), input_shapes=[(1, 8)],
            buckets=[1, 4])
        out = sess.predict(x).asnumpy()
        assert out.dtype == ref.dtype
        assert onp.array_equal(out, ref), \
            "AMP casts must bake identically into serving executables"
    finally:
        amp.disable()
    # AMP-off entries are keyed separately: same session re-resolves
    # and matches the fp32 reference bitwise
    post = sess.predict(x).asnumpy()
    assert onp.array_equal(post, _ref(net, x))


def test_imports_input_inference_requires_params(tmp_path):
    net = _mlp()
    net.hybridize()
    net.export(str(tmp_path / "m"), epoch=0)
    with pytest.raises(mx.MXNetError):
        mx.gluon.SymbolBlock.imports(str(tmp_path / "m-symbol.json"),
                                     None, None)


def test_load_missing_params_file_names_the_mistake(tmp_path):
    """A wrong prefix/epoch must raise naming the missing params file,
    not limp into a session over uninitialized parameters."""
    net = _mlp()
    net.hybridize()
    net.export(str(tmp_path / "m"), epoch=0)
    with pytest.raises(mx.MXNetError, match=r"m-0003\.params"):
        serving.InferenceSession.load(str(tmp_path / "m"), epoch=3,
                                      input_shapes=[(1, 8)])


# ---------------------------------------------------------------------------
# DynamicBatcher

class _FakeSession:
    """Duck-typed session: records execution batches; optional delay
    to force queueing."""

    max_batch = 8

    def __init__(self, delay_s=0.0):
        self.delay_s = delay_s
        self.batches = []
        self._lock = threading.Lock()

    def validate(self, *inputs):
        x = inputs[0]
        arr = x.asnumpy() if isinstance(x, mx.NDArray) else \
            onp.asarray(x, dtype="float32")
        if tuple(arr.shape[1:]) != (2,):
            raise ValueError("row shape")
        return [arr], arr.shape[0]

    def predict(self, x):
        if self.delay_s:
            time.sleep(self.delay_s)
        with self._lock:
            self.batches.append(x.shape[0])
        return x * 2.0


def test_batcher_coalesces_and_slices_per_request():
    net = _mlp()
    sess = _session(net)
    bat = serving.DynamicBatcher(sess, max_latency_ms=20, num_workers=1)
    try:
        xs = {i: onp.random.RandomState(i).rand(1, 8).astype("float32")
              for i in range(10)}
        futs = {}
        results = {}

        def client(i):
            futs[i] = bat.submit(xs[i])

        threads = [threading.Thread(target=client, args=(i,))
                   for i in xs]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, f in futs.items():
            results[i] = f.result(timeout=30)  # host numpy arrays
        for i, x in xs.items():
            assert onp.array_equal(results[i], _ref(net, x))
        st = serving.serving_stats()
        assert st["responses"] == 10
        assert st["batches"] < 10, "no coalescing happened"
    finally:
        bat.close()


def test_batcher_malformed_request_fails_alone():
    sess = _FakeSession()
    bat = serving.DynamicBatcher(sess, max_latency_ms=10)
    try:
        good = bat.submit(onp.ones((1, 2), dtype="float32"))
        with pytest.raises(ValueError):
            bat.submit(onp.ones((1, 3), dtype="float32"))
        also_good = bat.submit(onp.ones((2, 2), dtype="float32"))
        assert good.result(timeout=10).shape == (1, 2)
        assert also_good.result(timeout=10).shape == (2, 2)
        st = serving.serving_stats()
        assert st["invalid"] == 1
        assert st["failures"] == 0
    finally:
        bat.close()


def test_batcher_oversized_request_rejected():
    bat = serving.DynamicBatcher(_FakeSession(), max_batch_size=8)
    try:
        with pytest.raises(ValueError):
            bat.submit(onp.ones((9, 2), dtype="float32"))
    finally:
        bat.close()


def test_batcher_backpressure():
    sess = _FakeSession(delay_s=0.2)
    bat = serving.DynamicBatcher(sess, max_queue=2, max_batch_size=1,
                                 max_latency_ms=1)
    try:
        futs = [bat.submit(onp.ones((1, 2), dtype="float32"))]
        rejected = 0
        for _ in range(20):
            try:
                futs.append(
                    bat.submit(onp.ones((1, 2), dtype="float32")))
            except serving.ServerBusy:
                rejected += 1
        assert rejected > 0, "queue bound never engaged"
        assert serving.serving_stats()["rejected"] == rejected
        for f in futs:
            f.result(timeout=30)
    finally:
        bat.close()


def test_batcher_request_timeout_fails_alone():
    sess = _FakeSession(delay_s=0.3)
    bat = serving.DynamicBatcher(sess, max_batch_size=1,
                                 max_latency_ms=1, timeout_ms=50)
    try:
        # first request occupies the worker; the second expires queued
        slow = bat.submit(onp.ones((1, 2), dtype="float32"),
                          timeout_ms=10_000)
        doomed = bat.submit(onp.ones((1, 2), dtype="float32"),
                            timeout_ms=50)
        with pytest.raises(serving.RequestTimeout):
            doomed.result(timeout=10)
        assert slow.result(timeout=10) is not None
        assert serving.serving_stats()["timeouts"] == 1
    finally:
        bat.close()


def test_batcher_graceful_close_drains_then_runs_inline():
    sess = _FakeSession(delay_s=0.05)
    bat = serving.DynamicBatcher(sess, max_latency_ms=1)
    futs = [bat.submit(onp.ones((1, 2), dtype="float32"))
            for _ in range(6)]
    bat.close()
    for f in futs:
        assert f.done(), "close() must drain accepted requests"
        f.result(timeout=0)
    bat.close()  # idempotent
    # post-close submits run inline (engine.close() semantics)
    post = bat.submit(onp.ones((1, 2), dtype="float32"))
    assert post.done()
    assert serving.serving_stats()["inline"] == 1


def test_batcher_pass_through_when_serving_disabled(monkeypatch):
    monkeypatch.setenv("MXNET_SERVING", "0")
    assert not serving.serving_enabled()
    sess = _FakeSession()
    bat = serving.DynamicBatcher(sess)
    try:
        fut = bat.submit(onp.ones((3, 2), dtype="float32"))
        assert fut.done(), "pass-through must execute inline"
        assert fut.result().shape == (3, 2)
        assert serving.serving_stats()["inline"] == 1
    finally:
        bat.close()


def test_batcher_drain_honors_request_deadlines():
    """The per-request deadline contract ('fails alone, without
    executing') must hold on the drain paths too, not just at worker
    batch formation."""
    from mxnet_tpu.serving.batcher import _Request

    sess = _FakeSession()
    bat = serving.DynamicBatcher(sess, max_latency_ms=1)
    bat.close()
    expired = _Request([onp.ones((1, 2), dtype="float32")], 1,
                       time.monotonic() - 1.0)
    live = _Request([onp.ones((1, 2), dtype="float32")], 1,
                    time.monotonic() + 60.0)
    bat._queue.put(expired)
    bat._queue.put(live)
    bat._drain_queue()
    with pytest.raises(serving.RequestTimeout):
        expired.future.result(timeout=0)
    assert live.future.result(timeout=0).shape == (1, 2)
    assert sess.batches == [1], "expired request must never execute"
    assert serving.serving_stats()["timeouts"] == 1


def test_batcher_non_row_aligned_output_fails_batch_never_leaks():
    """An output that is not batch-major over the coalesced rows cannot
    be sliced per request — the batch must fail loudly rather than hand
    any request the full (cross-request) array."""
    from mxnet_tpu.serving.batcher import _Request

    class Pooled(_FakeSession):
        def predict(self, x):
            super().predict(x)
            return (x * 2.0, x.sum(axis=0))  # second: batch-reduced

    bat = serving.DynamicBatcher(Pooled(), max_latency_ms=1)
    try:
        # 3 coalesced rows != the pooled output's feature dim (2), so
        # the row-alignment check cannot be fooled by a shape collision
        r1 = _Request([onp.ones((1, 2), dtype="float32")], 1, None)
        r2 = _Request([onp.full((2, 2), 3.0, dtype="float32")], 2, None)
        bat._execute([r1, r2])
        for r in (r1, r2):
            with pytest.raises(mx.MXNetError, match="batch-major"):
                r.future.result(timeout=0)
        # a single-request batch owns its whole output: passes through
        r3 = _Request([onp.ones((2, 2), dtype="float32")], 2, None)
        bat._execute([r3])
        out = r3.future.result(timeout=0)
        assert out[1].shape == (2,)
    finally:
        bat.close()


def test_batcher_execution_failure_propagates_per_future():
    class Exploding(_FakeSession):
        def predict(self, x):
            raise RuntimeError("kaboom")

    bat = serving.DynamicBatcher(Exploding(), max_latency_ms=5)
    try:
        fut = bat.submit(onp.ones((1, 2), dtype="float32"))
        with pytest.raises(RuntimeError, match="kaboom"):
            fut.result(timeout=10)
        assert serving.serving_stats()["failures"] == 1
    finally:
        bat.close()


# ---------------------------------------------------------------------------
# observability

def test_metrics_histogram_quantiles():
    h = serving.metrics.LatencyHistogram()
    for v in (0.001,) * 50 + (0.1,) * 49 + (100.0,):
        h.observe(v)
    assert h.quantile(0.5) <= 0.0025
    assert 0.025 <= h.quantile(0.95) <= 0.25
    assert h.quantile(0.99) <= 60.0  # overflow clamps to last bound
    assert serving.metrics.LatencyHistogram().quantile(0.5) == 0.0


def test_serving_counters_in_profiler_and_dump(tmp_path):
    from mxnet_tpu import profiler

    sess = _session(buckets=(2,))
    sess.predict(onp.ones((2, 8), dtype="float32"))
    counters = profiler.serving_counters()
    assert counters["batches"] >= 1
    assert "latency_p99_ms" in counters and "qps_60s" in counters
    fname = str(tmp_path / "prof.json")
    profiler.set_config(filename=fname)
    try:
        path = profiler.dump()
        import json

        with open(path) as f:
            names = {e["name"] for e in json.load(f)["traceEvents"]}
        assert any(n.startswith("serving/") for n in names)
    finally:
        profiler.set_config(filename="profile.json")


def test_runtime_serving_feature(monkeypatch):
    from mxnet_tpu import runtime

    feats = runtime.Features()
    assert feats.is_enabled("SERVING")
    monkeypatch.setenv("MXNET_SERVING", "0")
    assert not runtime.Features().is_enabled("SERVING")


def test_prometheus_text_renders():
    sess = _session(buckets=(1,))
    sess.predict(onp.ones((1, 8), dtype="float32"))
    text = serving.prometheus_text()
    assert "mxnet_serving_batches_total" in text
    assert "mxnet_serving_request_latency_seconds_bucket" in text
    assert text.endswith("\n")
