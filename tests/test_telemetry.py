"""mxnet_tpu.telemetry — span tracer, Chrome-trace exporter, unified
metrics registry (round 18).

Covers the six contract surfaces: span nesting/causality across
threads, ring wraparound (drop-oldest + ``dropped_spans``),
Chrome-trace JSON schema, trace-id propagation end-to-end through the
DynamicBatcher, the unified Prometheus exposition (training families
scrapeable next to the serving block), and the ``MXNET_TELEMETRY=0``
zero-emission guarantee."""
import json
import threading

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, serving, telemetry
from mxnet_tpu.gluon import nn

nd = mx.nd


@pytest.fixture(autouse=True)
def _clean_ring():
    telemetry.reset_trace()
    yield
    telemetry.reset_trace()


def _mlp(in_dim=8, out_dim=4, seed=0):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(out_dim))
    net.initialize()
    with autograd.pause(train_mode=False):
        net(nd.zeros((1, in_dim)))
    return net


# ---------------------------------------------------------------------------
# span nesting + cross-thread causality

def test_span_nesting_and_cross_thread_causality(monkeypatch):
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    telemetry.reset_trace()
    with telemetry.trace_context("t-abc") as tid:
        assert tid == "t-abc"
        with telemetry.span("outer", cat="test"):
            with telemetry.span("inner", cat="test") as sp:
                sp.set(marker=7)

            def work():
                # another thread has its own span stack; causality
                # crosses via the explicitly-carried trace id
                with telemetry.span("worker", cat="test",
                                    trace_id=tid):
                    pass

            th = threading.Thread(target=work, name="test-worker")
            th.start()
            th.join()
    evs = {e["name"]: e for e in telemetry.events()}
    assert set(evs) == {"outer", "inner", "worker"}
    # same-thread nesting: inner's parent is outer's span id
    assert evs["inner"]["args"]["parent"] == \
        evs["outer"]["args"]["span_id"]
    assert evs["inner"]["args"]["marker"] == 7
    # the worker span has no lexical parent but shares the trace id
    assert "parent" not in evs["worker"]["args"]
    for name in ("outer", "inner", "worker"):
        assert evs[name]["args"]["trace_id"] == "t-abc", name
    assert evs["worker"]["tid"] != evs["outer"]["tid"]
    assert telemetry.thread_names()[evs["worker"]["tid"]] == \
        "test-worker"


def test_span_records_error_type(monkeypatch):
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    telemetry.reset_trace()
    with pytest.raises(ValueError):
        with telemetry.span("doomed", cat="test"):
            raise ValueError("boom")
    (ev,) = telemetry.events()
    assert ev["args"]["error"] == "ValueError"


# ---------------------------------------------------------------------------
# ring wraparound

def test_ring_wraparound_drops_oldest(monkeypatch):
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    telemetry.reset_trace(capacity=8)
    for i in range(12):
        telemetry.instant(f"ev{i}", cat="test")
    evs = telemetry.events()
    assert len(evs) == 8 == telemetry.buffer_capacity()
    # drop-oldest: the first four are gone, order is preserved
    assert [e["name"] for e in evs] == [f"ev{i}" for i in range(4, 12)]
    assert telemetry.dropped_spans() == 4
    # the drop count rides the export payload
    assert telemetry.build_trace(counters=False)["otherData"] == \
        {"dropped_spans": 4}


# ---------------------------------------------------------------------------
# Chrome-trace schema

def test_chrome_trace_json_schema(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    telemetry.reset_trace()
    with telemetry.span("alpha", cat="test", k=1):
        telemetry.instant("mark", cat="test")
    path = tmp_path / "trace.json"
    telemetry.dump_trace(str(path))
    doc = json.load(open(str(path)))  # the acceptance bar: json.load
    assert doc["displayTimeUnit"] == "ms"
    events = doc["traceEvents"]
    phs = {e["ph"] for e in events}
    assert {"X", "i", "M", "C"} <= phs, phs
    for e in events:
        assert {"name", "ph", "pid"} <= set(e), e
        if e["ph"] in ("X", "i", "M"):
            assert "tid" in e, e  # counter samples are process-scoped
        if e["ph"] == "X":
            assert e["ts"] >= 0 and e["dur"] >= 0 and "cat" in e
        elif e["ph"] == "i":
            assert e["s"] == "t"
    # thread metadata labels the lanes
    mnames = [e for e in events if e["ph"] == "M"]
    assert mnames and all(e["name"] == "thread_name" and
                          "name" in e["args"] for e in mnames)
    # counter samples keep the legacy profiler "<family>/<counter>"
    # naming, so existing dump() consumers parse the same series
    csamples = [e for e in events if e["ph"] == "C"]
    assert csamples and all("/" in e["name"] for e in csamples)
    assert any(e["name"].startswith("compile_cache/")
               for e in csamples)


# ---------------------------------------------------------------------------
# trace-id propagation through the batcher (the serving lifecycle)

def test_trace_id_propagates_through_dynamic_batcher(monkeypatch):
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    sess = serving.InferenceSession(_mlp(), input_shapes=[(1, 8)],
                                    buckets=[1, 2])
    bat = serving.DynamicBatcher(sess, max_latency_ms=5, num_workers=1)
    telemetry.reset_trace()  # drop construction/compile spans
    try:
        x = onp.random.RandomState(0).rand(1, 8).astype("float32")
        with telemetry.trace_context("req-42"):
            out = bat.predict(x)
    finally:
        bat.close()
    assert out.shape == (1, 4)
    mine = [e for e in telemetry.events()
            if e.get("args", {}).get("trace_id") == "req-42"]
    names = {e["name"] for e in mine}
    # the documented lifecycle, all stamped with ONE trace id
    assert {"serving.admission", "serving.queue_wait",
            "serving.execute", "serving.respond"} <= names, names
    # ...across at least two lanes: the submitting thread and the
    # batch-formation worker
    assert len({e["tid"] for e in mine}) >= 2, mine


# ---------------------------------------------------------------------------
# unified Prometheus exposition

def test_prometheus_exposition_unifies_training_and_serving():
    text = telemetry.prometheus_text()
    # the serving block survives verbatim...
    assert "mxnet_serving_requests_total" in text
    assert "mxnet_serving_request_latency_seconds" in text
    # ...and training-side families are scrapeable for the first time
    assert "mxnet_pipeline_" in text
    assert "mxnet_compile_cache_" in text
    # internal (underscore-prefixed) families stay out of the scrape
    assert "mxnet__graph_opt_passes" not in text


def test_registry_counter_family_roundtrip():
    fam = telemetry.counter_family("test_roundtrip", {"hits": 0})
    fam.reset()
    fam.add("hits")
    fam.add("hits", 2)
    fam.set("gauge", 7)
    assert telemetry.family_snapshot("test_roundtrip") == \
        {"hits": 3, "gauge": 7}
    # idempotent create-or-fetch: same live family, not a new one
    assert telemetry.counter_family("test_roundtrip") is fam
    assert "mxnet_test_roundtrip_hits 3" in telemetry.prometheus_text()
    fam.reset()
    assert telemetry.family_snapshot("test_roundtrip")["hits"] == 0


# ---------------------------------------------------------------------------
# MXNET_TELEMETRY=0: nothing is emitted

def test_disabled_level_emits_nothing(monkeypatch):
    monkeypatch.setenv("MXNET_TELEMETRY", "0")
    telemetry.reset_trace()
    assert not telemetry.tracing()
    sp = telemetry.span("nope", cat="test")
    # the disabled path is ONE shared null span — no allocation
    assert sp is telemetry.span("nope2", cat="test")
    with sp:
        sp.set(k=1)
    telemetry.instant("nope3", cat="test")
    # trace-id plumbing still works (X-Request-Id echo never breaks)
    with telemetry.trace_context("rid-1"):
        assert telemetry.current_trace_id() == "rid-1"
    assert telemetry.current_trace_id() is None
    assert telemetry.events() == []
    assert telemetry.dropped_spans() == 0
