"""Ranked-lock witness (utils/locks.py): out-of-rank detection, the
AB/BA cycle witness, error-mode semantics, condition re-entry, and an
8-thread store+batcher stress under ``MXNET_LOCK_CHECK=error``.

Witness tests drive violations on purpose, so they wrap the violating
region in ``locks.capture_violations()`` — assertions run against the
captured list and the tier-1 conftest zero-violation gate never sees
them."""
import threading
import time

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import serving
from mxnet_tpu.utils import locks


@pytest.fixture
def error_mode():
    prev = locks.set_check_mode("error")
    yield
    locks.set_check_mode(prev)


def test_ascending_acquire_is_clean():
    a = locks.RankedLock("repository")
    b = locks.RankedLock("serving.session")
    with locks.capture_violations() as got:
        with a:
            assert locks.held_locks() == [("repository", 10)]
            with b:
                assert locks.held_locks() == [
                    ("repository", 10), ("serving.session", 40)]
    assert got == []


def test_out_of_rank_counts_under_warn():
    hi = locks.RankedLock("serving.session")
    lo = locks.RankedLock("repository")
    before = locks.lock_check_counters()["out_of_rank"]
    with locks.capture_violations() as got:
        with hi:
            with lo:  # rank 10 under rank 40: out of declared order
                pass
    kinds = [v["kind"] for v in got]
    assert "out_of_rank" in kinds, got
    v = got[kinds.index("out_of_rank")]
    assert "repository" in v["message"]
    assert "serving.session" in v["message"]
    assert locks.lock_check_counters()["out_of_rank"] > before


def test_error_mode_raises_before_acquiring(error_mode):
    hi = locks.RankedLock("serving.session")
    lo = locks.RankedLock("repository")
    with locks.capture_violations():
        with hi:
            with pytest.raises(locks.LockOrderError):
                lo.acquire()
    # the raise happened BEFORE the raw acquire: nothing to release,
    # nothing leaked on the held stack
    assert not lo._raw.locked()
    assert locks.held_locks() == []


def test_self_deadlock_on_nonreentrant_lock(error_mode):
    a = locks.RankedLock("repository")
    with locks.capture_violations() as got:
        with a:
            with pytest.raises(locks.LockOrderError):
                a.acquire()
    assert [v["kind"] for v in got] == ["self_deadlock"]


def test_rlock_reentry_is_one_stack_entry():
    m = locks.RankedRLock("repository.model")
    with locks.capture_violations() as got:
        with m:
            with m:  # re-entry: no violation, no second stack entry
                assert locks.held_locks() == [("repository.model", 20)]
    assert got == []


def test_ab_ba_cycle_witness_without_deadlocking():
    """The lockdep payoff: thread 1 records edge A->B, thread 2 then
    takes B->A — the witness reports the potential deadlock from the
    ORDER GRAPH alone, with both acquisitions strictly sequential (no
    actual contention, so the test can never hang)."""
    a = locks.RankedLock("batcher")        # rank 30
    b = locks.RankedLock("batcher.queue")  # rank 35
    t1_done = threading.Event()
    captured = []

    def t1():
        with a:
            with b:  # clean ascending acquire: edge batcher->queue
                pass
        t1_done.set()

    def t2():
        t1_done.wait(10)
        with locks.capture_violations() as got:
            with b:
                with a:  # closes the cycle (and is out of rank)
                    pass
        captured.extend(got)

    th1 = threading.Thread(target=t1)
    th2 = threading.Thread(target=t2)
    th1.start(), th2.start()
    th1.join(10), th2.join(10)
    kinds = [v["kind"] for v in captured]
    assert "cycle" in kinds, captured
    cyc = captured[kinds.index("cycle")]["message"]
    assert "potential deadlock" in cyc
    assert "batcher" in cyc and "batcher.queue" in cyc
    graph = locks.order_graph()
    assert "batcher.queue" in graph.get("batcher", set())


def test_condition_wait_releases_held_stack():
    """engine pattern: a RankedCondition sharing its lock; wait() must
    drop the held-stack entry (the raw lock IS released) and restore
    it on wakeup, so the witness never sees a phantom hold."""
    lock = locks.RankedLock("engine.waiters")
    cond = locks.RankedCondition(lock=lock)
    seen = []

    def waiter():
        with cond:
            seen.append(locks.held_locks())
            cond.wait(10)
            seen.append(locks.held_locks())

    t = threading.Thread(target=waiter)
    with locks.capture_violations() as got:
        t.start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with cond:
                if len(seen) == 1:
                    cond.notify_all()
                    break
            time.sleep(0.005)
        t.join(10)
    assert not t.is_alive()
    assert seen == [[("engine.waiters", 0)], [("engine.waiters", 0)]]
    assert got == []


def test_exempt_requires_reason_and_suppresses():
    with pytest.raises(ValueError):
        with locks.exempt(""):
            pass
    hi = locks.RankedLock("serving.session")
    lo = locks.RankedLock("repository")
    with locks.capture_violations() as got:
        with locks.exempt("test: deliberate inversion"):
            with hi:
                with lo:
                    pass
    assert got == []


def test_level0_factories_return_raw_primitives():
    prev = locks.set_check_mode("0")
    try:
        lk = locks.RankedLock("repository")
        rl = locks.RankedRLock("repository.model")
        cv = locks.RankedCondition("batcher.queue")
        assert type(lk) is type(threading.Lock())
        assert type(rl) is type(threading.RLock())
        assert isinstance(cv, threading.Condition)
    finally:
        locks.set_check_mode(prev)


def test_unknown_lock_name_is_rejected():
    with pytest.raises(KeyError):
        locks.RankedLock("no.such.lock")


# -- 8-thread stress under MXNET_LOCK_CHECK=error -----------------------

class _EchoSession:
    """Duck-typed session for the batcher: echoes 2*x per row."""

    max_batch = 8

    def validate(self, *inputs):
        arr = onp.asarray(inputs[0], dtype="float32")
        return [arr], arr.shape[0]

    def predict(self, x):
        return x * 2.0


@pytest.mark.slow
def test_stress_store_and_batcher_under_error_mode(error_mode):
    """8 threads hammer a SessionStateStore (open/acquire/scatter/
    release/evict, with eviction pressure) while 8 more drive a
    DynamicBatcher submit storm through close — in ``error`` mode,
    where ANY out-of-rank acquire or cycle raises at the violating
    site. Zero violations and zero lost responses expected."""
    from mxnet_tpu.serving.state import SessionStateStore

    store = SessionStateStore([(4,)], max_sessions=16)
    sess = _EchoSession()
    bat = serving.DynamicBatcher(sess, max_batch_size=8,
                                 max_latency_ms=2, num_workers=2)
    errors = []
    n_iters = 25

    def store_worker(tid):
        try:
            for i in range(n_iters):
                sid = f"s{tid}-{i % 4}"
                try:
                    if not store.has(sid):
                        store.open(sid)
                    rec = store.acquire(sid)
                    states = store.gather([rec])
                    store.scatter([rec], [s + 1.0 for s in states])
                    store.release(rec)
                    if i % 5 == 4:
                        store.evict(sid, reason="stress churn")
                except mx.base.MXNetError:
                    pass  # evicted by a neighbour under pressure: fine
        except Exception as e:  # pragma: no cover - failure path
            errors.append(e)

    def batcher_worker(tid):
        try:
            futs = [bat.submit(onp.full((1, 2), float(tid * n_iters + i),
                                        dtype="float32"))
                    for i in range(n_iters)]
            for i, f in enumerate(futs):
                out = f.result(timeout=30)
                assert float(out[0, 0]) == 2.0 * (tid * n_iters + i)
        except Exception as e:  # pragma: no cover - failure path
            errors.append(e)

    threads = [threading.Thread(target=store_worker, args=(t,))
               for t in range(4)]
    threads += [threading.Thread(target=batcher_worker, args=(t,))
                for t in range(4)]
    before = len(locks.violations())
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not any(t.is_alive() for t in threads)
    bat.close()
    store.close()
    assert errors == [], errors
    assert locks.violations()[before:] == []
