"""Legacy output ops, spatial-transformer family, ROI pooling, control flow.

Mirrors reference coverage: tests/python/unittest/test_operator.py
(test_regression, test_svmoutput, test_roipooling, test_stn,
test_correlation) and test_contrib_control_flow.py.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def test_regression_outputs():
    x = nd.array([[1., 2.], [3., 4.]])
    lbl = nd.array([[0., 1.], [1., 0.]])
    x.attach_grad()
    with autograd.record():
        y = nd.LinearRegressionOutput(x, lbl, grad_scale=2.0)
    y.backward()
    # grad = grad_scale/num_output * (pred - label), num_output = 2
    # per-sample features (reference regression_output-inl.h:201)
    assert onp.allclose(x.grad.asnumpy(), (x.asnumpy() - lbl.asnumpy()))
    assert onp.allclose(y.asnumpy(), x.asnumpy())

    x.grad[:] = 0
    with autograd.record():
        y = nd.MAERegressionOutput(x, lbl)
    y.backward()
    assert onp.allclose(x.grad.asnumpy(),
                        onp.sign(x.asnumpy() - lbl.asnumpy()) / 2)

    x.grad[:] = 0
    with autograd.record():
        y = nd.LogisticRegressionOutput(x, lbl)
    y.backward()
    sig = 1 / (1 + onp.exp(-x.asnumpy()))
    assert onp.allclose(y.asnumpy(), sig, atol=1e-6)
    assert onp.allclose(x.grad.asnumpy(), (sig - lbl.asnumpy()) / 2,
                        atol=1e-6)


def test_svm_output():
    x = nd.array([[0.5, -0.2, 0.3]])
    lbl = nd.array([0.])
    x.attach_grad()
    with autograd.record():
        y = nd.SVMOutput(x, lbl, margin=1.0, use_linear=True)
    y.backward()
    assert onp.allclose(y.asnumpy(), x.asnumpy())
    # violated iff margin - signed_score > 0; signed = x for the true
    # class, -x otherwise (reference svm_output-inl.h L1-margin backward)
    g = x.grad.asnumpy()
    assert onp.allclose(g, [[-1., 1., 1.]])
    # true-class margin satisfied -> all zeros
    x2 = nd.array([[2., -2., -2.]])
    x2.attach_grad()
    with autograd.record():
        y2 = nd.SVMOutput(x2, lbl, margin=1.0, use_linear=True)
    y2.backward()
    assert onp.allclose(x2.grad.asnumpy(), 0.0)


def test_smooth_l1_moments_batch_take():
    out = nd.smooth_l1(nd.array([-3., 0.1, 3.]), scalar=1.0).asnumpy()
    assert onp.allclose(out, [2.5, 0.005, 2.5], atol=1e-6)
    m, v = nd.moments(nd.array([[1., 2.], [3., 4.]]), axes=[0])
    assert onp.allclose(m.asnumpy(), [2., 3.])
    assert onp.allclose(v.asnumpy(), [1., 1.])
    bt = nd.batch_take(nd.array([[1., 2.], [3., 4.]]), nd.array([1, 0]))
    assert onp.allclose(bt.asnumpy(), [2., 3.])


def test_roi_pooling():
    data = nd.array(onp.arange(36, dtype='f').reshape(1, 1, 6, 6))
    rois = nd.array([[0, 0, 0, 2, 2], [0, 1, 1, 4, 4]])
    out = nd.ROIPooling(data, rois, pooled_size=(2, 2), spatial_scale=1.0)
    assert out.shape == (2, 1, 2, 2)
    assert onp.allclose(out.asnumpy().reshape(2, 4),
                        [[7, 8, 13, 14], [14, 16, 26, 28]])


def test_spatial_transformer_identity():
    data = nd.array(onp.random.RandomState(0).rand(2, 3, 5, 7).astype('f'))
    theta = nd.array(onp.tile([1., 0, 0, 0, 1., 0], (2, 1)))
    out = nd.SpatialTransformer(data, theta, target_shape=(5, 7))
    assert onp.allclose(out.asnumpy(), data.asnumpy(), atol=1e-4)


def test_bilinear_sampler_grad_flows():
    data = nd.array(onp.random.RandomState(1).rand(1, 2, 4, 4).astype('f'))
    grid = nd.array(onp.zeros((1, 2, 3, 3), dtype='f'))
    data.attach_grad()
    with autograd.record():
        out = nd.BilinearSampler(data, grid)
    out.backward()
    assert out.shape == (1, 2, 3, 3)
    assert float(nd.sum(nd.abs(data.grad)).asnumpy()) > 0


def test_correlation_shape():
    a = nd.array(onp.random.rand(1, 2, 6, 6).astype('f'))
    out = nd.Correlation(a, a, kernel_size=1, max_displacement=2,
                         stride1=1, stride2=1, pad_size=2)
    assert out.shape[1] == 25
    # zero-displacement channel of self-correlation == mean over channels sq
    c12 = out.asnumpy()[0, 12]
    expect = (a.asnumpy()[0] ** 2).mean(axis=0)
    assert onp.allclose(c12[:6, :6], expect, atol=1e-4)


def test_foreach():
    def body(x, s):
        return x + s, x + s
    outs, fin = nd.contrib.foreach(body, nd.array([1., 2., 3.]), nd.array(0.))
    assert onp.allclose(outs.asnumpy(), [1., 3., 6.])
    assert float(fin.asnumpy()) == 6.0


def test_foreach_grad():
    x = nd.array([1., 2., 3.])
    x.attach_grad()
    with autograd.record():
        outs, fin = nd.contrib.foreach(lambda xi, s: (xi * s, s + xi),
                                       x, nd.array(1.))
        loss = nd.sum(outs)
    loss.backward()
    assert onp.isfinite(x.grad.asnumpy()).all()
    # d/dx of [x0, x1(1+x0), x2(1+x0+x1)] summed
    g = x.grad.asnumpy()
    xs = [1., 2., 3.]
    expect = [1 + xs[1] + xs[2], (1 + xs[0]) + xs[2], 1 + xs[0] + xs[1]]
    assert onp.allclose(g, expect)


def test_while_loop_eager():
    outs, st = nd.contrib.while_loop(
        lambda i, s: i < 3,
        lambda i, s: ([i * 2], [i + 1, s + i]),
        [nd.array(0.), nd.array(1.)], max_iterations=10)
    assert onp.allclose(outs[0].asnumpy(), [0., 2., 4.])
    assert float(st[1].asnumpy()) == 4.0


def test_while_loop_traced():
    import jax

    def run(i0, s0):
        outs, st = nd.contrib.while_loop(
            lambda i, s: i < 3,
            lambda i, s: ([i * 2], [i + 1, s + i]),
            [nd.NDArray(i0), nd.NDArray(s0)], max_iterations=5)
        return outs[0].data, st[1].data

    buf, s = jax.jit(run)(0.0, 1.0)
    assert onp.allclose(onp.asarray(buf), [0., 2., 4., 0., 0.])
    assert float(s) == 4.0


def test_cond():
    r = nd.contrib.cond(nd.array(1.), lambda: nd.array(10.),
                        lambda: nd.array(20.))
    assert float(r.asnumpy()) == 10.0
    import jax

    def f(p):
        return nd.contrib.cond(nd.NDArray(p),
                               lambda: nd.NDArray(p.astype('float32')) * 2,
                               lambda: nd.NDArray(p.astype('float32')) - 1).data

    assert float(jax.jit(f)(onp.bool_(True))) == 2.0
    assert float(jax.jit(f)(onp.bool_(False))) == -1.0
