"""mx.image namespace: decode/resize/crop ops, augmenters (seeded
determinism), ImageIter and ImageDetIter.

Reference coverage model: tests/python/unittest/test_image.py
(TestImage.test_imdecode/test_resize_short/test_augmenters/
test_image_iter/test_image_detiter).
"""
import os
import random as pyrandom

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, image, recordio

rs = onp.random.RandomState(5)


def _jpeg_bytes(arr):
    import io

    from PIL import Image

    b = io.BytesIO()
    Image.fromarray(arr).save(b, format="JPEG", quality=95)
    return b.getvalue()


@pytest.fixture(scope="module")
def img_dataset(tmp_path_factory):
    """8 random images on disk + a .rec/.idx pair + an imglist."""
    d = tmp_path_factory.mktemp("imgs")
    files, labels = [], []
    rec = recordio.MXIndexedRecordIO(str(d / "data.idx"),
                                     str(d / "data.rec"), "w")
    for i in range(8):
        arr = rs.randint(0, 255, (80 + 4 * i, 100, 3)).astype("uint8")
        fname = f"im{i}.jpg"
        with open(d / fname, "wb") as f:
            f.write(_jpeg_bytes(arr))
        files.append(fname)
        labels.append(float(i % 4))
        header = recordio.IRHeader(0, float(i % 4), i, 0)
        rec.write_idx(i, recordio.pack(header, _jpeg_bytes(arr)))
    rec.close()
    return d, files, labels


def test_imdecode_imread(img_dataset):
    d, files, _ = img_dataset
    img = image.imread(str(d / files[0]))
    assert img.dtype == onp.uint8 or str(img.dtype) == "uint8"
    assert img.shape == (80, 100, 3)
    gray = image.imread(str(d / files[0]), flag=0)
    assert gray.shape == (80, 100, 1)
    with open(d / files[0], "rb") as f:
        img2 = image.imdecode(f.read())
    onp.testing.assert_array_equal(img.asnumpy(), img2.asnumpy())
    bgr = image.imdecode(open(d / files[0], "rb").read(), to_rgb=0)
    onp.testing.assert_array_equal(bgr.asnumpy()[:, :, ::-1],
                                   img.asnumpy())


def test_resize_short_and_crops():
    arr = rs.randint(0, 255, (60, 120, 3)).astype("uint8")
    out = image.resize_short(nd.array(arr, dtype="uint8"), 30)
    assert out.shape == (30, 60, 3)  # aspect preserved, short edge 30
    c, (x0, y0, w, h) = image.center_crop(arr, (40, 20))
    assert c.shape == (20, 40, 3) and (w, h) == (40, 20)
    assert x0 == (120 - 40) // 2 and y0 == (60 - 20) // 2
    f = image.fixed_crop(arr, 5, 10, 30, 20)
    onp.testing.assert_array_equal(f.asnumpy(), arr[10:30, 5:35])
    rc, rect = image.random_crop(arr, (32, 24))
    assert rc.shape == (24, 32, 3)
    rsz, _ = image.random_size_crop(arr, (32, 24), (0.3, 1.0),
                                    (0.8, 1.2))
    assert rsz.shape == (24, 32, 3)


def test_color_normalize_and_border():
    arr = rs.randint(0, 255, (8, 8, 3)).astype("uint8")
    mean = onp.array([1.0, 2.0, 3.0], "f")
    std = onp.array([2.0, 2.0, 2.0], "f")
    out = image.color_normalize(arr, mean, std)
    onp.testing.assert_allclose(out.asnumpy(),
                                (arr.astype("f") - mean) / std, rtol=1e-5)
    padded = image.copyMakeBorder(arr, 1, 2, 3, 4, value=7)
    assert padded.shape == (11, 15, 3)
    assert (padded.asnumpy()[0] == 7).all()


def test_create_augmenter_composition():
    augs = image.CreateAugmenter((3, 24, 24), resize=30, rand_crop=True,
                                 rand_mirror=True, mean=True, std=True,
                                 brightness=0.1, hue=0.1, pca_noise=0.1,
                                 rand_gray=0.1)
    names = [type(a).__name__ for a in augs]
    assert names == ["ResizeAug", "RandomCropAug", "HorizontalFlipAug",
                     "CastAug", "ColorJitterAug", "HueJitterAug",
                     "LightingAug", "RandomGrayAug", "ColorNormalizeAug"]
    for a in augs:
        assert a.dumps()  # serializable


def test_augmenter_seeded_determinism():
    arr = rs.randint(0, 255, (50, 50, 3)).astype("uint8")
    augs = image.CreateAugmenter((3, 32, 32), rand_crop=True,
                                 rand_mirror=True, brightness=0.3,
                                 contrast=0.3, saturation=0.3, hue=0.3)

    def run():
        pyrandom.seed(42)
        onp.random.seed(42)
        out = nd.array(arr, dtype="uint8")
        for a in augs:
            out = a(out)
        return out.asnumpy()

    onp.testing.assert_array_equal(run(), run())
    pyrandom.seed(7)
    different = False
    for _ in range(4):  # different seed → (almost surely) different crop
        out = nd.array(arr, dtype="uint8")
        for a in augs:
            out = a(out)
        if not onp.array_equal(out.asnumpy(), run()):
            different = True
            break
    assert different


def test_image_iter_rec(img_dataset):
    d, _, labels = img_dataset
    it = image.ImageIter(batch_size=4, data_shape=(3, 32, 32),
                         path_imgrec=str(d / "data.rec"),
                         path_imgidx=str(d / "data.idx"))
    batch = it.next()
    assert batch.data[0].shape == (4, 3, 32, 32)
    assert batch.label[0].shape == (4,)
    onp.testing.assert_allclose(batch.label[0].asnumpy(), labels[:4])
    batch2 = it.next()
    assert batch2.pad == 0
    with pytest.raises(StopIteration):
        it.next()
    it.reset()
    assert it.next().data[0].shape == (4, 3, 32, 32)


def test_image_iter_imglist(img_dataset):
    d, files, labels = img_dataset
    imglist = [[lab, f] for lab, f in zip(labels, files)]
    it = image.ImageIter(batch_size=3, data_shape=(3, 28, 28),
                         imglist=imglist, path_root=str(d),
                         shuffle=False)
    n = 0
    for batch in it:
        assert batch.data[0].shape == (3, 3, 28, 28)
        n += 1
    assert n == 3  # 8 imgs → 2 full + 1 padded batch
    assert batch.pad == 1
    assert it.provide_data[0].shape == (3, 3, 28, 28)


def test_image_det_iter(img_dataset):
    d, _, _ = img_dataset
    rec = recordio.MXIndexedRecordIO(str(d / "det.idx"),
                                     str(d / "det.rec"), "w")
    for i in range(6):
        arr = rs.randint(0, 255, (64, 64, 3)).astype("uint8")
        # header: [header_width=2, obj_width=5, (cls, x0, y0, x1, y1) x2]
        label = [2, 5, 1, 0.1, 0.2, 0.5, 0.6, 2, 0.3, 0.3, 0.9, 0.8]
        header = recordio.IRHeader(0, label, i, 0)
        rec.write_idx(i, recordio.pack(header, _jpeg_bytes(arr)))
    rec.close()
    it = image.ImageDetIter(batch_size=2, data_shape=(3, 48, 48),
                            path_imgrec=str(d / "det.rec"),
                            path_imgidx=str(d / "det.idx"))
    batch = it.next()
    assert batch.data[0].shape == (2, 3, 48, 48)
    assert batch.label[0].shape == (2, 2, 5)
    lab = batch.label[0].asnumpy()
    onp.testing.assert_allclose(
        lab[0], [[1, 0.1, 0.2, 0.5, 0.6], [2, 0.3, 0.3, 0.9, 0.8]],
        rtol=1e-5)


def test_det_flip_updates_boxes():
    aug = image.DetHorizontalFlipAug(p=1.0)
    arr = nd.array(rs.randint(0, 255, (10, 10, 3)).astype("uint8"),
                   dtype="uint8")
    label = onp.array([[1, 0.1, 0.2, 0.4, 0.6]], "f")
    out, lab2 = aug(arr, label)
    onp.testing.assert_allclose(lab2, [[1, 0.6, 0.2, 0.9, 0.6]],
                                rtol=1e-5)
    onp.testing.assert_array_equal(out.asnumpy(),
                                   arr.asnumpy()[:, ::-1])


def test_det_random_crop_keeps_valid_boxes():
    pyrandom.seed(0)
    aug = image.DetRandomCropAug(min_object_covered=0.1,
                                 area_range=(0.5, 1.0))
    arr = nd.array(rs.randint(0, 255, (40, 40, 3)).astype("uint8"),
                   dtype="uint8")
    label = onp.array([[0, 0.25, 0.25, 0.75, 0.75]], "f")
    out, lab2 = aug(arr, label)
    assert lab2.shape[1] == 5
    assert (lab2[:, 1:5] >= -1e-6).all() and (lab2[:, 1:5] <= 1 + 1e-6).all()


def test_transform_random_hue_preserves_gray():
    """Hue rotation leaves achromatic (gray) pixels unchanged."""
    from mxnet_tpu.gluon.data.vision import transforms

    t = transforms.RandomHue(0.4)
    x = nd.full((4, 4, 3), 120.0)
    out = t(x).asnumpy()
    onp.testing.assert_allclose(out, 120.0, rtol=1e-3, atol=0.5)


def test_transform_random_color_jitter_runs():
    from mxnet_tpu.gluon.data.vision import transforms

    t = transforms.RandomColorJitter(brightness=0.2, contrast=0.2,
                                     saturation=0.2, hue=0.2)
    x = nd.array(onp.random.RandomState(0).randint(
        0, 255, (8, 8, 3)).astype("f"))
    out = t(x)
    assert out.shape == (8, 8, 3)
    assert onp.isfinite(out.asnumpy()).all()


def test_transform_crop_resize():
    from mxnet_tpu.gluon.data.vision import transforms

    x = nd.array(onp.arange(6 * 6 * 3, dtype="f").reshape(6, 6, 3))
    t = transforms.CropResize(1, 2, 4, 3)
    out = t(x)
    assert out.shape == (3, 4, 3)
    onp.testing.assert_allclose(out.asnumpy(),
                                x.asnumpy()[2:5, 1:5, :])
    xu = nd.array(onp.random.RandomState(0).randint(
        0, 255, (6, 6, 3)), dtype="uint8")
    t2 = transforms.CropResize(0, 0, 4, 4, size=8)
    assert t2(xu).shape == (8, 8, 3)


def test_transform_crop_resize_batched_and_bounds():
    from mxnet_tpu.gluon.data.vision import transforms

    xb = nd.array(onp.arange(2 * 6 * 6 * 3, dtype="f").reshape(2, 6, 6, 3))
    out = transforms.CropResize(1, 2, 4, 3)(xb)
    assert out.shape == (2, 3, 4, 3)
    onp.testing.assert_allclose(out.asnumpy(), xb.asnumpy()[:, 2:5, 1:5, :])
    with pytest.raises(ValueError, match="exceeds"):
        transforms.CropResize(5, 5, 4, 4)(xb)
