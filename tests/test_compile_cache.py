"""Persistent compile cache + shape bucketing (utils/compile_cache.py).

Covers the disk second tier behind the eager-dispatch and fused-step
executable caches (warm start without recompiling, corrupt/mismatched
entries as misses, the MXNET_COMPILE_CACHE=0 knob), automatic shape
bucketing (MXNET_SHAPE_BUCKETS: retrace reduction + bitwise row
identity), the AOT warmup APIs (Trainer.warmup, Module.warmup,
BucketingModule.warmup_buckets), tier-1 hermeticity of the cache dir,
and thread-safety of the shared CountedLRUCache.
"""
import os
import pickle
import threading

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, profiler
from mxnet_tpu.gluon import fused_step as fs
from mxnet_tpu.gluon import nn
from mxnet_tpu.ndarray import registry
from mxnet_tpu.utils import compile_cache as cc
from mxnet_tpu.utils.lru import CountedLRUCache

nd = mx.nd


@pytest.fixture(autouse=True)
def _fresh(monkeypatch, tmp_path):
    """Per-test cache dir + zeroed counters + empty in-memory caches,
    so disk hits/retraces in one test can't leak into another."""
    monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR", str(tmp_path / "cc"))
    monkeypatch.setenv("MXNET_EAGER_JIT", "1")
    monkeypatch.delenv("MXNET_SHAPE_BUCKETS", raising=False)
    registry.reset_dispatch_cache(maxsize=512)
    fs.reset_fused_step_cache()
    cc.reset_compile_cache_counters()
    yield
    registry.reset_dispatch_cache(maxsize=512)
    fs.reset_fused_step_cache()
    cc.reset_compile_cache_counters()


def _mxc_files():
    d = cc.cache_dir()
    if not os.path.isdir(d):
        return []
    return [f for f in os.listdir(d) if f.endswith(".mxc")]


# ---------------------------------------------------------------------------
# hermeticity (conftest satellite)

def test_tier1_cache_dir_is_hermetic():
    """The session conftest pins MXNET_COMPILE_CACHE_DIR into pytest's
    tmpdir (this test's fixture narrows it further): nothing the suite
    compiles may land in — or be served from — $MXNET_HOME."""
    d = cc.cache_dir()
    home_cache = os.path.join(
        os.environ.get("MXNET_HOME",
                       os.path.join(os.path.expanduser("~"), ".mxnet")),
        "compile_cache")
    assert d != home_cache
    assert "compile_cache" not in os.path.commonprefix([d, home_cache]) \
        or not d.startswith(home_cache)
    before = set(os.listdir(home_cache)) if os.path.isdir(home_cache) \
        else set()
    x = nd.ones((3, 5))
    nd.tanh(x)
    nd.tanh(x)  # first hit: AOT compile + disk write
    assert _mxc_files(), "executable was not persisted into the tmpdir"
    after = set(os.listdir(home_cache)) if os.path.isdir(home_cache) \
        else set()
    assert after == before, "suite leaked cache entries into $MXNET_HOME"


# ---------------------------------------------------------------------------
# dispatch-cache disk tier

def test_dispatch_warm_start_skips_retrace():
    x = nd.ones((4, 8))
    w = nd.ones((8, 8))
    r_cold = nd.dot(x, w)
    nd.dot(x, w)  # first hit: AOT compile, serialize, write
    s = cc.compile_cache_stats()
    assert s["disk_writes"] == 1 and s["retraces"] == 1, s

    # simulated restart: in-memory cache gone, disk survives
    registry.reset_dispatch_cache()
    cc.reset_compile_cache_counters()
    r_warm = nd.dot(x, w)
    s = cc.compile_cache_stats()
    assert s["disk_hits"] == 1, s
    assert s["retraces"] == 0, "warm start must not trace"
    assert onp.array_equal(r_cold.asnumpy(), r_warm.asnumpy())
    # and the promoted entry keeps serving hits
    r2 = nd.dot(x, w)
    assert onp.array_equal(r2.asnumpy(), r_cold.asnumpy())
    assert registry.dispatch_cache_stats()["hits"] >= 1


def test_dispatch_eager_persist_stores_at_compile_time(monkeypatch):
    """MXNET_DISPATCH_EAGER_PERSIST=1 (round 23, fleet replicas): the
    dispatch executable is AOT-compiled and written to the disk tier
    on the very first call — a one-shot construction op that never
    hits again in its process still leaves an artifact, so a
    bundle-warm replica truly starts at zero compiles."""
    x = nd.ones((4, 8))
    w = nd.ones((8, 8))
    cc.reset_compile_cache_counters()
    monkeypatch.setenv("MXNET_DISPATCH_EAGER_PERSIST", "1")
    r_cold = nd.dot(x, w)  # ONE call — no in-process hit ever happens
    s = cc.compile_cache_stats()
    assert s["disk_writes"] == 1, s
    assert _mxc_files(), "eager persist left no disk entry"
    # simulated restart: the single warm call serves from disk
    registry.reset_dispatch_cache()
    cc.reset_compile_cache_counters()
    r_warm = nd.dot(x, w)
    s = cc.compile_cache_stats()
    assert s["disk_hits"] == 1 and s["retraces"] == 0, s
    assert onp.array_equal(r_cold.asnumpy(), r_warm.asnumpy())
    # default (off): a single call persists nothing — eager AOT is an
    # exporting-replica tax the common path must not pay
    monkeypatch.delenv("MXNET_DISPATCH_EAGER_PERSIST")
    registry.reset_dispatch_cache()
    cc.reset_compile_cache_counters()
    nd.tanh(x)
    assert cc.compile_cache_stats()["disk_writes"] == 0


def test_recording_entries_are_not_persisted():
    """vjp pullbacks carry live functions in their output pytree — they
    cannot serialize and must count as serialize_skips, not break."""
    x = nd.ones((4, 8))
    x.attach_grad()
    for _ in range(3):
        with autograd.record():
            y = nd.tanh(x)
        y.backward()
    s = cc.compile_cache_stats()
    assert s["disk_writes"] == 0
    # grads still flow through the in-memory compiled path
    assert x.grad.shape == (4, 8)


def test_corrupt_entry_is_a_miss_and_removed():
    x = nd.ones((2, 3))
    nd.exp(x)
    nd.exp(x)
    files = _mxc_files()
    assert len(files) == 1
    path = os.path.join(cc.cache_dir(), files[0])
    with open(path, "wb") as f:
        f.write(b"not a pickle")
    registry.reset_dispatch_cache()
    cc.reset_compile_cache_counters()
    r = nd.exp(x)
    s = cc.compile_cache_stats()
    assert s["disk_corrupt"] == 1 and s["disk_hits"] == 0, s
    assert not os.path.exists(path), "corrupt entry must be removed"
    assert onp.allclose(r.asnumpy(), onp.exp(onp.ones((2, 3))))


def test_version_mismatch_is_a_miss():
    x = nd.ones((2, 3))
    nd.log(x)
    nd.log(x)
    files = _mxc_files()
    assert len(files) == 1
    path = os.path.join(cc.cache_dir(), files[0])
    with open(path, "rb") as f:
        env = pickle.load(f)
    env["salt"] = ("different",)  # jax/jaxlib/backend/format drifted
    with open(path, "wb") as f:
        pickle.dump(env, f)
    registry.reset_dispatch_cache()
    cc.reset_compile_cache_counters()
    nd.log(x)
    s = cc.compile_cache_stats()
    assert s["disk_corrupt"] == 1 and s["disk_hits"] == 0, s


def test_knob_disables_disk_tier(monkeypatch):
    monkeypatch.setenv("MXNET_COMPILE_CACHE", "0")
    x = nd.ones((2, 3))
    nd.sqrt(x)
    nd.sqrt(x)
    assert _mxc_files() == []
    s = cc.compile_cache_stats()
    assert s["disk_writes"] == 0 and s["disk_misses"] == 0
    assert s["enabled"] is False
    # dispatch cache itself still works
    assert registry.dispatch_cache_stats()["hits"] >= 1


def test_fingerprint_stability_and_unstable_keys():
    k = ("dot", (("a", 0),), (), (), (((4, 8), "float32", False),), 0)
    assert cc.fingerprint("dispatch", k) == cc.fingerprint("dispatch", k)
    assert cc.fingerprint("dispatch", k) != cc.fingerprint("fused", k)
    k2 = ("dot", (("a", 0),), (), (), (((4, 9), "float32", False),), 0)
    assert cc.fingerprint("dispatch", k) != cc.fingerprint("dispatch", k2)
    # live functions have no process-stable form: no fingerprint, and
    # the entry simply stays memory-only
    assert cc.fingerprint("dispatch", (lambda: 1,)) is None
    # floats are type-tagged apart from ints, hex-exact
    assert cc.fingerprint("d", (1,)) != cc.fingerprint("d", (1.0,))


# ---------------------------------------------------------------------------
# shape bucketing

_STREAM = (5, 6, 7, 9, 11, 13, 15, 8)


def _stream_outputs():
    w = nd.ones((8, 8))
    outs = {}
    for _ in range(2):  # sizes repeat: unbucketed pays one trace per size
        for b in _STREAM:
            x = nd.array(onp.arange(b * 8, dtype="float32").reshape(b, 8)
                         / 100.0)
            outs[b] = nd.tanh(nd.broadcast_add(nd.dot(x, w),
                                               nd.ones((8,))))
    return outs


def test_bucketing_cuts_retraces_bitwise(monkeypatch):
    monkeypatch.setenv("MXNET_SHAPE_BUCKETS", "pow2")
    bucketed = _stream_outputs()
    s = cc.compile_cache_stats()
    retr_bucketed = s["retraces"]
    assert s["bucketed_calls"] > 0
    assert 0.0 < s["pad_ratio"] < 1.0

    monkeypatch.setenv("MXNET_SHAPE_BUCKETS", "0")
    registry.reset_dispatch_cache()
    cc.reset_compile_cache_counters()
    plain = _stream_outputs()
    retr_plain = cc.compile_cache_stats()["retraces"]

    assert retr_bucketed < retr_plain, (retr_bucketed, retr_plain)
    for b in plain:
        assert bucketed[b].shape == plain[b].shape
        assert onp.array_equal(bucketed[b].asnumpy(), plain[b].asnumpy()), \
            f"batch {b} not bitwise identical under bucketing"


def test_bucketing_mult_policy(monkeypatch):
    monkeypatch.setenv("MXNET_SHAPE_BUCKETS", "mult:4")
    assert cc.bucket_size(5, cc.bucket_spec()) == 8
    assert cc.bucket_size(8, cc.bucket_spec()) == 8
    assert cc.bucket_size(9, cc.bucket_spec()) == 12
    x5 = nd.array(onp.arange(5 * 4, dtype="float32").reshape(5, 4))
    x7 = nd.array(onp.arange(7 * 4, dtype="float32").reshape(7, 4))
    r5, r7 = nd.relu(x5), nd.relu(x7)
    assert r5.shape == (5, 4) and r7.shape == (7, 4)
    assert cc.compile_cache_stats()["bucketed_calls"] == 2
    assert onp.array_equal(r5.asnumpy(), onp.maximum(x5.asnumpy(), 0))


def test_non_whitelisted_ops_never_bucketed(monkeypatch):
    monkeypatch.setenv("MXNET_SHAPE_BUCKETS", "pow2")
    x = nd.array(onp.arange(5 * 4, dtype="float32").reshape(5, 4))
    # sum reduces over the batch axis: padding would be silently wrong
    s = nd.sum(x, axis=0)
    assert onp.array_equal(s.asnumpy(), x.asnumpy().sum(axis=0))
    # softmax over axis 0 mixes rows: the guard must veto it
    sm = nd.softmax(x, axis=0)
    ref = onp.exp(x.asnumpy()) / onp.exp(x.asnumpy()).sum(0)
    assert onp.allclose(sm.asnumpy(), ref, atol=1e-6)
    assert cc.compile_cache_stats()["bucketed_calls"] == 0


def test_bucketing_resolves_negative_and_positional_axis(monkeypatch):
    """Regression: the softmax guard must resolve the axis against the
    operand rank (axis=-2 on 2-D aliases axis 0) and must see
    POSITIONALLY-passed config — both previously bucketed a
    normalization over the batch axis and returned wrong values."""
    monkeypatch.setenv("MXNET_SHAPE_BUCKETS", "pow2")
    x = nd.array(onp.arange(3 * 2, dtype="float32").reshape(3, 2) / 10.0)
    ref = onp.exp(x.asnumpy()) / onp.exp(x.asnumpy()).sum(0)
    assert onp.allclose(nd.softmax(x, axis=-2).asnumpy(), ref, atol=1e-6)
    # axis passed positionally: softmax(data, length, axis)
    assert onp.allclose(nd.softmax(x, None, 0).asnumpy(), ref, atol=1e-6)
    # dot with transpose_a positional: rows mix; must not be bucketed
    a = nd.array(onp.arange(3 * 2, dtype="float32").reshape(3, 2))
    b = nd.array(onp.arange(3 * 2, dtype="float32").reshape(3, 2))
    got = nd.dot(a, b, True)
    assert onp.array_equal(got.asnumpy(),
                           a.asnumpy().T @ b.asnumpy())
    assert cc.compile_cache_stats()["bucketed_calls"] == 0


def test_bucketing_skips_rank1_row_operands(monkeypatch):
    """Regression: on a 1-D dot lhs (or softmax vector) axis 0 is the
    contraction/data axis — padding it raised a dot_general shape
    TypeError before the rank>=2 precondition."""
    monkeypatch.setenv("MXNET_SHAPE_BUCKETS", "pow2")
    v = nd.array(onp.array([0.0, 1.0, 2.0], dtype="float32"))
    m = nd.ones((3, 2))
    r = nd.dot(v, m)
    assert onp.array_equal(r.asnumpy(), v.asnumpy() @ m.asnumpy())
    sm = nd.softmax(v)
    assert onp.allclose(sm.asnumpy(),
                        onp.exp(v.asnumpy())
                        / onp.exp(v.asnumpy()).sum(), atol=1e-6)
    assert cc.compile_cache_stats()["bucketed_calls"] == 0


def test_disk_cache_prunes_to_size_cap(monkeypatch, tmp_path):
    monkeypatch.setenv("MXNET_COMPILE_CACHE_MAX_MB", "1")
    monkeypatch.setattr(cc, "_PRUNE_EVERY", 1)
    d = cc.cache_dir()
    os.makedirs(d, exist_ok=True)
    # simulate an overgrown cache from previous runs: ~1.5 MB of stale
    # entries, distinct mtimes so eviction order is deterministic
    for i in range(12):
        p = os.path.join(d, f"stale{i:02d}.mxc")
        with open(p, "wb") as f:
            f.write(b"x" * (128 * 1024))
        os.utime(p, (1000 + i, 1000 + i))
    x = nd.ones((2, 3))
    nd.exp(x)
    nd.exp(x)  # first hit: AOT compile + write -> prune pass
    files = _mxc_files()
    total = sum(os.path.getsize(os.path.join(d, f)) for f in files)
    assert total <= 1024 * 1024, (total, files)
    # oldest entries went first; the fresh real entry survived
    assert not os.path.exists(os.path.join(d, "stale00.mxc"))
    assert any(not f.startswith("stale") for f in files)


def test_prune_survives_concurrent_pruner(monkeypatch, tmp_path):
    """Regression: two replicas sharing one cache dir prune
    concurrently — entries the other pruner already deleted vanish
    between scandir/stat and stat/remove. The sweep must tolerate the
    per-entry races (not abort on the first ghost) and still enforce
    the cap on what remains."""
    import contextlib

    monkeypatch.setenv("MXNET_COMPILE_CACHE_MAX_MB", "1")
    monkeypatch.setattr(cc, "_PRUNE_EVERY", 1)
    d = str(tmp_path)
    for i in range(12):
        p = os.path.join(d, f"stale{i:02d}.mxc")
        with open(p, "wb") as f:
            f.write(b"x" * (256 * 1024))
        os.utime(p, (1000 + i, 1000 + i))

    real_scandir = os.scandir
    # the "other pruner" takes these mid-sweep: two before our stat,
    # one after our stat but before our remove
    vanish = {"stale00.mxc": "pre-stat", "stale01.mxc": "pre-stat",
              "stale02.mxc": "pre-remove"}

    class _RacyEntry:
        def __init__(self, e, race):
            self._e, self._race = e, race
            self.name, self.path = e.name, e.path

        def stat(self):
            if self._race == "pre-stat":
                os.remove(self.path)
                raise FileNotFoundError(self.path)
            st = self._e.stat()
            if self._race == "pre-remove":
                os.remove(self.path)
            return st

    @contextlib.contextmanager
    def racy_scandir(path):
        with real_scandir(path) as it:
            yield (_RacyEntry(e, vanish.get(e.name)) for e in it)

    monkeypatch.setattr(cc.os, "scandir", racy_scandir)
    before = cc.compile_cache_stats()
    cc._maybe_prune(d)  # must not raise
    monkeypatch.setattr(cc.os, "scandir", real_scandir)
    stats = cc.compile_cache_stats()
    assert stats["prunes"] - before["prunes"] == 1
    assert stats["disk_evicted"] > before["disk_evicted"]
    left = [f for f in os.listdir(d) if f.endswith(".mxc")]
    total = sum(os.path.getsize(os.path.join(d, f)) for f in left)
    assert total <= 1024 * 1024, (total, left)
    # newest entries survived the sweep
    assert "stale11.mxc" in left


def test_bucketing_skips_recording(monkeypatch):
    monkeypatch.setenv("MXNET_SHAPE_BUCKETS", "pow2")
    x = nd.array(onp.ones((5, 4), dtype="float32"))
    x.attach_grad()
    with autograd.record():
        y = nd.tanh(x)
    y.backward()
    assert cc.compile_cache_stats()["bucketed_calls"] == 0
    assert x.grad.shape == (5, 4)


# ---------------------------------------------------------------------------
# fused-step disk tier + Trainer.warmup

def _make_net(seed=7, materialize=True):
    mx.random.seed(seed)
    net = nn.Sequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    if materialize:
        with autograd.pause(train_mode=False):
            net(nd.zeros((8, 10)))
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9})
    return net, tr


def _train(net, tr, steps=3):
    for i in range(steps):
        x = nd.array(onp.random.RandomState(i).rand(8, 10)
                     .astype("float32"))
        with autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        tr.step(8)
    return [p.data().asnumpy()
            for _, p in sorted(net.collect_params().items())]


def test_fused_step_warm_start_bitwise():
    net, tr = _make_net()
    p_cold = _train(net, tr)
    s = cc.compile_cache_stats()
    assert s["disk_writes"] >= 1  # the fused-step executable persisted

    fs.reset_fused_step_cache()
    registry.reset_dispatch_cache()
    cc.reset_compile_cache_counters()
    net, tr = _make_net()
    p_warm = _train(net, tr)
    s = cc.compile_cache_stats()
    assert s["disk_hits"] >= 1, s
    for a, b in zip(p_cold, p_warm):
        assert onp.array_equal(a, b)


def test_trainer_warmup_resolves_before_first_step():
    net, tr = _make_net()
    assert tr.warmup() == 0  # no block/shapes: fused AOT resolve only
    r0 = cc.compile_cache_stats()["retraces"]
    assert r0 >= 1  # the fused step traced during warmup, not mid-epoch
    st = fs.fused_step_stats()
    assert st["size"] == 1
    _train(net, tr, steps=1)
    assert fs.fused_step_stats()["hits"] >= 1


def test_trainer_warmup_block_is_bitwise_neutral():
    net, tr = _make_net()
    p_cold = _train(net, tr)

    fs.reset_fused_step_cache()
    registry.reset_dispatch_cache()
    cc.reset_compile_cache_counters()
    net, tr = _make_net()
    before = [p.data().asnumpy()
              for _, p in sorted(net.collect_params().items())]
    assert tr.warmup(shapes=[(8, 10)], block=net) == 1
    after = [p.data().asnumpy()
             for _, p in sorted(net.collect_params().items())]
    for a, b in zip(before, after):
        assert onp.array_equal(a, b), "warmup mutated parameters"
    assert tr._optimizer.num_update == 0

    p_warm = _train(net, tr)
    for a, b in zip(p_cold, p_warm):
        assert onp.array_equal(a, b), "training after warmup diverged"
    # the warmed shapes step without new fused traces
    r0 = cc.compile_cache_stats()["retraces"]
    _train(net, tr, steps=1)
    assert cc.compile_cache_stats()["retraces"] == r0


def test_fingerprint_salts_function_bodies():
    """Editing an op body (or optimizer kernel) must invalidate its
    disk entries even though the cache key only carries the op NAME."""
    def body_a(x):
        return x + 1

    def body_b(x):
        return x + 2

    def body_a2(x):
        return x + 1

    key = ("someop", (((4,), "float32", False),))
    fa = cc.fingerprint("dispatch", key, code_of=(body_a,))
    fb = cc.fingerprint("dispatch", key, code_of=(body_b,))
    fa2 = cc.fingerprint("dispatch", key, code_of=(body_a2,))
    assert fa != fb, "changed body must change the fingerprint"
    assert fa == fa2, "identical source must fingerprint identically"


def test_knob_disables_fused_disk_layer(monkeypatch):
    """MXNET_COMPILE_CACHE=0 must mean the plain jit path on the fused
    step too — not a no-op GuardedCompiled layer."""
    monkeypatch.setenv("MXNET_COMPILE_CACHE", "0")
    net, tr = _make_net()
    _train(net, tr, steps=1)
    entry = next(iter(fs._CACHE._d.values()))
    assert entry._artifact is None
    assert not isinstance(entry._call, cc.GuardedCompiled)
    assert _mxc_files() == []


def test_warmup_half_specified_raises():
    net, tr = _make_net()
    with pytest.raises(ValueError, match="BOTH shapes and block"):
        tr.warmup(shapes=[(8, 10)])
    with pytest.raises(ValueError, match="BOTH shapes and block"):
        tr.warmup(block=net)


# ---------------------------------------------------------------------------
# BucketingModule: switch-back reuse + AOT precompile (satellite)

def _bucketing_module():
    from mxnet_tpu import io, symbol as sym
    from mxnet_tpu.module import BucketingModule

    def gen(bucket_key):
        data = sym.Variable("data")
        pooled = sym.mean(data, axis=1, keepdims=True)
        fc = sym.FullyConnected(pooled, name="bk_fc", num_hidden=2)
        out = sym.SoftmaxOutput(fc, sym.Variable("softmax_label"),
                                name="softmax")
        return out, ("data",), ("softmax_label",)

    bm = BucketingModule(gen, default_bucket_key=8, context=mx.cpu())
    bm.bind(data_shapes=[("data", (4, 8))],
            label_shapes=[("softmax_label", (4,))])
    bm.init_params()
    return bm, io


def _bucket_batch(io, width, rs):
    return io.DataBatch(
        data=[nd.array(rs.rand(4, width).astype("f"))],
        label=[nd.array(rs.randint(0, 2, 4).astype("f"))],
        bucket_key=width,
        provide_data=[io.DataDesc("data", (4, width))],
        provide_label=[io.DataDesc("softmax_label", (4,))])


def test_switch_bucket_reuses_compiled_executor():
    """Regression: switching BACK to a previously-seen bucket must reuse
    its bound module and compiled executor — no re-bind, no retrace —
    asserted through the profiler's compile-cache counters."""
    bm, io = _bucketing_module()
    rs = onp.random.RandomState(3)
    bm.forward(_bucket_batch(io, 8, rs), is_train=True)
    bm.forward(_bucket_batch(io, 4, rs), is_train=True)
    mod8 = bm._buckets[8]
    exec8 = mod8._exec
    fwd8 = exec8._fwd_jit
    retr = profiler.compile_cache_counters()["retraces"]
    bm.forward(_bucket_batch(io, 8, rs), is_train=True)  # back to 8
    assert bm._buckets[8] is mod8, "bucket module was re-created"
    assert mod8._exec is exec8, "executor was re-bound"
    assert mod8._exec._fwd_jit is fwd8, "forward jit was rebuilt"
    assert profiler.compile_cache_counters()["retraces"] == retr, \
        "switching back to a seen bucket retraced"


def test_warmup_buckets_precompiles_all_buckets():
    bm, io = _bucketing_module()
    buckets = [(8, [("data", (4, 8))], [("softmax_label", (4,))]),
               (4, [("data", (4, 4))], [("softmax_label", (4,))]),
               (6, [("data", (4, 6))], [("softmax_label", (4,))])]
    assert bm.warmup_buckets(buckets, is_train=True) == 3
    assert set(bm._buckets) == {8, 4, 6}
    assert bm._curr_bucket_key == 8  # switched back to the entry bucket
    retr = profiler.compile_cache_counters()["retraces"]
    assert retr >= 3
    rs = onp.random.RandomState(3)
    for width in (4, 8, 6, 4, 8):
        bm.forward(_bucket_batch(io, width, rs), is_train=True)
        bm.backward()
    assert profiler.compile_cache_counters()["retraces"] == retr, \
        "a warmed bucket retraced mid-epoch"


# ---------------------------------------------------------------------------
# observability

def test_profiler_and_runtime_surfaces():
    from mxnet_tpu import runtime

    x = nd.ones((2, 2))
    nd.tanh(x)
    nd.tanh(x)
    counters = profiler.compile_cache_counters()
    for k in ("disk_hits", "disk_misses", "disk_writes", "disk_corrupt",
              "serialize_skips", "retraces", "bucketed_calls",
              "pad_ratio", "enabled"):
        assert k in counters, k
    feats = runtime.Features()
    assert feats.is_enabled("COMPILE_CACHE")


def test_profiler_dump_includes_compile_cache_samples(tmp_path):
    profiler.set_config(filename=str(tmp_path / "prof.json"))
    profiler.start()
    nd.tanh(nd.ones((2, 2)))
    profiler.stop()
    out = profiler.dump()
    import json

    with open(out) as f:
        names = {e["name"] for e in json.load(f)["traceEvents"]}
    assert any(n.startswith("compile_cache/") for n in names)
    profiler.set_config(filename="profile.json")


# ---------------------------------------------------------------------------
# CountedLRUCache thread-safety (satellite): three caches now share it

def test_lru_cache_thread_safety():
    cache = CountedLRUCache(maxsize=32)
    errors = []
    barrier = threading.Barrier(8)
    N = 400

    def worker(tid):
        try:
            barrier.wait()
            for i in range(N):
                k = (tid * 7 + i) % 48  # cross-thread key overlap + evict
                if cache.lookup(k) is None:
                    cache.insert(k, ("v", tid, i))
                if i % 97 == 0:
                    cache.remove((tid + i) % 48)
                if i % 131 == 0:
                    cache.stats()
        except Exception as e:  # pragma: no cover - failure surface
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []
    s = cache.stats()
    assert s["size"] <= 32
    assert s["hits"] + s["misses"] == 8 * N
    # the OrderedDict survived concurrent mutation: lookups still work
    cache.insert("probe", 1)
    assert cache.lookup("probe") == 1
