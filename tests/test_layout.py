"""NHWC layout support: ops, gluon layers, and model-zoo equivalence.

Reference: layout="NHWC" convs/pooling on the reference's GPU path
(convolution-inl.h layout param, cudnn NHWC filters); here NHWC exists
because it keeps channels in XLA:TPU's preferred minor dimension.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.model_zoo import vision

RTOL, ATOL = 2e-4, 2e-4


def test_conv_op_nhwc_matches_nchw():
    rng = onp.random.RandomState(0)
    x = rng.rand(2, 5, 8, 8).astype("f")
    w = (rng.rand(7, 5, 3, 3).astype("f") - 0.5) * 0.2
    b = rng.rand(7).astype("f")
    ref = nd.convolution(nd.array(x), nd.array(w), nd.array(b),
                         kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                         num_filter=7).asnumpy()
    got = nd.convolution(
        nd.array(x.transpose(0, 2, 3, 1)),
        nd.array(w.transpose(0, 2, 3, 1)), nd.array(b),
        kernel=(3, 3), stride=(2, 2), pad=(1, 1), num_filter=7,
        layout="NHWC").asnumpy()
    onp.testing.assert_allclose(got.transpose(0, 3, 1, 2), ref,
                                rtol=RTOL, atol=ATOL)


def test_grouped_conv_nhwc():
    rng = onp.random.RandomState(1)
    x = rng.rand(2, 6, 4, 4).astype("f")
    w = rng.rand(6, 3, 3, 3).astype("f") * 0.2
    ref = nd.convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                         pad=(1, 1), num_filter=6, num_group=2,
                         no_bias=True).asnumpy()
    got = nd.convolution(
        nd.array(x.transpose(0, 2, 3, 1)),
        nd.array(w.transpose(0, 2, 3, 1)), kernel=(3, 3), pad=(1, 1),
        num_filter=6, num_group=2, no_bias=True,
        layout="NHWC").asnumpy()
    onp.testing.assert_allclose(got.transpose(0, 3, 1, 2), ref,
                                rtol=RTOL, atol=ATOL)


@pytest.mark.parametrize("pool_type", ["max", "avg"])
def test_pooling_nhwc_matches_nchw(pool_type):
    rng = onp.random.RandomState(2)
    x = rng.rand(2, 3, 9, 9).astype("f")
    ref = nd.pooling(nd.array(x), kernel=(3, 3), stride=(2, 2),
                     pad=(1, 1), pool_type=pool_type,
                     pooling_convention="full").asnumpy()
    got = nd.pooling(nd.array(x.transpose(0, 2, 3, 1)), kernel=(3, 3),
                     stride=(2, 2), pad=(1, 1), pool_type=pool_type,
                     pooling_convention="full",
                     layout="NHWC").asnumpy()
    onp.testing.assert_allclose(got.transpose(0, 3, 1, 2), ref,
                                rtol=RTOL, atol=ATOL)


def test_global_pool_nhwc():
    rng = onp.random.RandomState(3)
    x = rng.rand(2, 4, 5, 5).astype("f")
    ref = nd.pooling(nd.array(x), global_pool=True,
                     pool_type="avg").asnumpy()
    got = nd.pooling(nd.array(x.transpose(0, 2, 3, 1)),
                     global_pool=True, pool_type="avg",
                     layout="NHWC").asnumpy()
    onp.testing.assert_allclose(got.transpose(0, 3, 1, 2), ref,
                                rtol=RTOL, atol=ATOL)


def test_conv2d_layer_nhwc_shapes_and_grad():
    net = nn.Conv2D(8, kernel_size=3, padding=1, layout="NHWC",
                    activation="relu")
    net.initialize(mx.init.Xavier())
    x = nd.array(onp.random.RandomState(4).rand(2, 6, 6, 5).astype("f"))
    with autograd.record():
        out = net(x)
    out.mean().backward()
    assert out.shape == (2, 6, 6, 8)
    assert net.weight.shape == (8, 3, 3, 5)  # (O, kh, kw, I)
    assert net.weight.grad().shape == (8, 3, 3, 5)


def _transplant(src, dst):
    """Copy NCHW-net params into the NHWC net (conv weights transposed
    (O,I,kh,kw) -> (O,kh,kw,I); everything else verbatim)."""
    # identical architecture ⇒ identical parameter creation order; names
    # carry per-class instance counters that differ between the two nets
    sp = list(src.collect_params().values())
    dp = list(dst.collect_params().values())
    assert len(sp) == len(dp)
    for p, tgt in zip(sp, dp):
        v = p._ndarray.asnumpy()
        if v.ndim == 4 and tuple(tgt.shape) != v.shape:
            v = v.transpose(0, 2, 3, 1)
        assert tuple(tgt.shape) == v.shape, (p.name, tgt.shape, v.shape)
        tgt._ndarray[:] = nd.array(v)


def test_resnet18_nhwc_equivalent_logits():
    mx.random.seed(0)
    a = vision.resnet18_v1(classes=10)
    a.initialize(mx.init.Xavier())
    b = vision.resnet18_v1(classes=10, layout="NHWC")
    b.initialize(mx.init.Xavier())
    rng = onp.random.RandomState(0)
    x = rng.rand(2, 3, 32, 32).astype("f")
    ref = a(nd.array(x)).asnumpy()  # also finishes a's deferred init
    _ = b(nd.array(x.transpose(0, 2, 3, 1)))  # finish deferred init
    _transplant(a, b)
    got = b(nd.array(x.transpose(0, 2, 3, 1))).asnumpy()
    onp.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-3)


def test_resnet_nhwc_trains_under_spmd():
    from mxnet_tpu import parallel, gluon
    import jax

    mx.random.seed(0)
    net = vision.resnet18_v1(classes=10, layout="NHWC", thumbnail=True)
    net.initialize(mx.init.Xavier())
    mesh = parallel.make_mesh({"dp": min(2, len(jax.devices()))})
    tr = parallel.SPMDTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), optimizer="sgd",
        optimizer_params={"learning_rate": 0.1}, mesh=mesh,
        compute_dtype="bfloat16")
    rng = onp.random.RandomState(0)
    x = nd.array(rng.rand(8, 16, 16, 3).astype("f"))
    y = nd.array(rng.randint(0, 10, 8).astype("f"))
    losses = [float(tr.step(x, y).asscalar()) for _ in range(4)]
    assert onp.isfinite(losses).all()


def test_mobilenet_nhwc_equivalent_logits():
    mx.random.seed(1)
    a = vision.mobilenet_v2_0_25(classes=10)
    a.initialize(mx.init.Xavier())
    b = vision.mobilenet_v2_0_25(classes=10, layout="NHWC")
    b.initialize(mx.init.Xavier())
    rng = onp.random.RandomState(1)
    x = rng.rand(2, 3, 32, 32).astype("f")
    ref = a(nd.array(x)).asnumpy()
    _ = b(nd.array(x.transpose(0, 2, 3, 1)))
    _transplant(a, b)
    got = b(nd.array(x.transpose(0, 2, 3, 1))).asnumpy()
    onp.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-3)


def test_mobilenet_v1_nhwc_builds_and_trains():
    mx.random.seed(0)
    net = vision.mobilenet0_25(classes=5, layout="NHWC")
    net.initialize(mx.init.Xavier())
    x = nd.array(onp.random.RandomState(0).rand(2, 32, 32, 3).astype("f"))
    with autograd.record():
        out = net(x)
    out.mean().backward()
    assert out.shape == (2, 5)
