"""Accuracy-parity proxy (VERDICT r4 item 9, zero-egress variant):
train on sklearn's REAL digits dataset through the full gluon stack and
match the published classical baseline (~97%). The committed artifact is
ACCURACY_r05.json (examples/train_digits_accuracy.py)."""
import os
import subprocess
import sys


def test_digits_accuracy_beats_published_baseline(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = tmp_path / "acc.json"
    r = subprocess.run(
        [sys.executable,
         os.path.join(repo, "examples", "train_digits_accuracy.py"),
         "--json", str(out), "--epochs", "30"],
        env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, (r.stdout[-800:], r.stderr[-800:])
    import json

    payload = json.loads(out.read_text())
    assert payload["value"] >= 0.97, payload
