"""NumPy dispatch-protocol interoperability (reference:
tests/python/unittest/test_numpy_interoperability.py — onp functions
called ON mx.np arrays route to device ops and return mx.np arrays)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np as mxnp


def test_array_protocol():
    a = mxnp.array([[1.0, 2.0], [3.0, 4.0]])
    host = onp.asarray(a)
    assert isinstance(host, onp.ndarray)
    onp.testing.assert_allclose(host, [[1, 2], [3, 4]])
    assert onp.asarray(a, dtype="float64").dtype == onp.float64


def test_ufunc_dispatch_stays_on_device():
    a = mxnp.array([1.0, 2.0, 3.0])
    out = onp.add(a, 1)
    assert isinstance(out, mxnp.ndarray)
    onp.testing.assert_allclose(out.asnumpy(), [2, 3, 4])
    s = onp.sin(a)
    assert isinstance(s, mxnp.ndarray)
    onp.testing.assert_allclose(s.asnumpy(), onp.sin([1.0, 2.0, 3.0]),
                                rtol=1e-6)
    m = onp.multiply(a, a)
    assert isinstance(m, mxnp.ndarray)
    onp.testing.assert_allclose(m.asnumpy(), [1, 4, 9])


def test_array_function_dispatch():
    a = mxnp.array([[1.0, 2.0], [3.0, 4.0]])
    assert float(onp.mean(a)) == 2.5
    c = onp.concatenate([a, a], axis=0)
    assert isinstance(c, mxnp.ndarray) and c.shape == (4, 2)
    d = onp.dot(a, a)
    assert isinstance(d, mxnp.ndarray)
    onp.testing.assert_allclose(d.asnumpy(), onp.dot(a.asnumpy(),
                                                     a.asnumpy()))
    w = onp.where(a > 2, a, mxnp.zeros_like(a))
    onp.testing.assert_allclose(w.asnumpy(), [[0, 0], [3, 4]])


def test_host_fallback_for_unregistered_functions():
    a = mxnp.array([3.0, 1.0, 2.0])
    # functions with no mx.np counterpart run on host and wrap back
    out = onp.partition(a, 1)
    onp.testing.assert_allclose(onp.asarray(out)[:2], [1, 2])


def test_ufunc_kwargs_and_methods_via_host():
    a = mxnp.array([1.0, 2.0, 3.0, 4.0])
    # where= must not be silently dropped
    mask = onp.array([True, False, True, False])
    out = onp.add(a, 10.0, where=mask)
    got = onp.asarray(out)
    assert got[0] == 11.0 and got[2] == 13.0
    # ufunc methods route through the host fallback
    assert float(onp.asarray(onp.add.reduce(a))) == 10.0
    acc = onp.asarray(onp.maximum.accumulate(mxnp.array([1.0, 3.0, 2.0])))
    onp.testing.assert_allclose(acc, [1, 3, 3])
    outer = onp.multiply.outer(mxnp.array([1.0, 2.0]),
                               mxnp.array([3.0, 4.0]))
    onp.testing.assert_allclose(onp.asarray(outer), [[3, 4], [6, 8]])


def test_multi_output_and_order_fallbacks():
    a = mxnp.array([1.5, 2.25])
    frac, whole = onp.modf(a)  # tuple preserved, not stacked
    onp.testing.assert_allclose(onp.asarray(frac), [0.5, 0.25])
    onp.testing.assert_allclose(onp.asarray(whole), [1.0, 2.0])
    m = mxnp.array([[1.0, 2.0], [3.0, 4.0]])
    # order='F' must not silently produce a C-order reshape
    onp.testing.assert_allclose(onp.asarray(onp.ravel(m, order="F")),
                                [1, 3, 2, 4])


def test_array_function_reduce_kwargs_go_host():
    a = mxnp.array([1.0, 2.0])
    # initial= must not be silently swallowed by the device wrapper
    assert float(onp.asarray(onp.sum(a, initial=10.0))) == 13.0
    # array-valued where= must neither crash the guard nor be dropped
    m = mxnp.array([[1.0, 2.0], [3.0, 4.0]])
    mask = onp.array([[True, False], [True, True]])
    got = float(onp.asarray(onp.mean(m, where=mask)))
    assert abs(got - (1 + 3 + 4) / 3) < 1e-6
    # out= host array routes through numpy and fills the buffer
    buf = onp.empty((), "f")
    onp.mean(a, out=buf)
    assert float(buf) == 1.5
    # out= mx array: payload rebinding honors the in-place contract
    mbuf = mxnp.zeros(())
    ret = onp.mean(a, out=mbuf)
    assert ret is mbuf and float(onp.asarray(mbuf)) == 1.5
    # ...with numpy's OWN validation and casting rules (the out= call
    # runs on host into a matching buffer, so shape errors and the
    # unsafe reduction cast are numpy's verbatim behavior)
    with pytest.raises(ValueError):
        onp.mean(a, out=mxnp.zeros((5,)))
    ibuf = mxnp.zeros((), dtype="int32")
    onp.mean(a, out=ibuf)
    assert int(onp.asarray(ibuf)) == 1  # truncated, numpy-style


def test_asarray_copy_false_raises():
    a = mxnp.array([1.0])
    with pytest.raises(ValueError):
        onp.asarray(a, copy=False)


def test_infer_type_through_quantize_consumer():
    from mxnet_tpu import sym

    q = sym.quantize(sym.Variable("x"), sym.Variable("mn"),
                     sym.Variable("mx"))
    deq = sym.dequantize(q, sym.Variable("mn2"), sym.Variable("mx2"))
    _, out_t, _ = deq.infer_type(x=onp.float32, mn=onp.float32,
                                 mx=onp.float32, mn2=onp.float32,
                                 mx2=onp.float32)
    assert out_t == [onp.float32]


def test_mixed_operands_and_testing_helpers():
    a = mxnp.array([1.0, 2.0])
    b = onp.array([10.0, 20.0], "f")
    out = onp.add(a, b)
    onp.testing.assert_allclose(onp.asarray(out), [11, 22])
    # assert_allclose works directly on mx arrays via __array__
    onp.testing.assert_allclose(a, [1.0, 2.0])


def test_numpy_interop_sweep_69_functions():
    """Broad onp-function-over-mx.np-array sweep (reference:
    test_numpy_interoperability.py's 175-function battery, condensed to
    the widely-used surface). Every call must succeed via the dispatch
    protocols (device path or host fallback)."""
    import numpy as onp

    from mxnet_tpu import np as mnp

    a = mnp.array([[1., 2.], [3., 4.]])
    b = mnp.array([[5., 6.], [7., 8.]])
    v = mnp.array([1., 2., 3.])
    cases = [
        lambda: onp.concatenate([a, b]), lambda: onp.stack([a, b]),
        lambda: onp.vstack([a, b]), lambda: onp.hstack([a, b]),
        lambda: onp.mean(a), lambda: onp.sum(a), lambda: onp.std(a),
        lambda: onp.var(a), lambda: onp.median(a), lambda: onp.ptp(a),
        lambda: onp.argmax(a), lambda: onp.argsort(v),
        lambda: onp.sort(v), lambda: onp.unique(v),
        lambda: onp.clip(a, 1.5, 3.5), lambda: onp.transpose(a),
        lambda: onp.reshape(a, (4,)), lambda: onp.ravel(a),
        lambda: onp.squeeze(a[None]), lambda: onp.expand_dims(a, 0),
        lambda: onp.split(v, 3), lambda: onp.where(a > 2, a, b),
        lambda: onp.dot(a, b), lambda: onp.matmul(a, b),
        lambda: onp.einsum("ij,jk->ik", a, b), lambda: onp.tensordot(a, b),
        lambda: onp.inner(a, b), lambda: onp.outer(v, v),
        lambda: onp.cross(v, v), lambda: onp.kron(a, b),
        lambda: onp.trace(a), lambda: onp.diag(v), lambda: onp.tril(a),
        lambda: onp.cumsum(a), lambda: onp.diff(v),
        lambda: onp.gradient(v),
        lambda: onp.interp(mnp.array([1.5]), v, v),
        lambda: onp.histogram(v),
        lambda: onp.bincount(mnp.array([0., 1., 1.]).astype("int32")),
        lambda: onp.percentile(a, 50), lambda: onp.quantile(a, 0.5),
        lambda: onp.average(a), lambda: onp.round(a),
        lambda: onp.floor_divide(a, b), lambda: onp.isclose(a, a),
        lambda: onp.allclose(a, a), lambda: onp.array_equal(a, a),
        lambda: onp.atleast_2d(v), lambda: onp.broadcast_to(v, (2, 3)),
        lambda: onp.tile(v, 2), lambda: onp.repeat(v, 2),
        lambda: onp.roll(v, 1), lambda: onp.flip(v), lambda: onp.rot90(a),
        lambda: onp.meshgrid(v, v), lambda: onp.linalg.norm(a),
        lambda: onp.linalg.inv(a), lambda: onp.linalg.det(a),
        lambda: onp.linalg.svd(a), lambda: onp.fft.fft(v),
        lambda: onp.pad(v, 1),
        lambda: onp.take(v, mnp.array([0., 2.]).astype("int32")),
        lambda: onp.searchsorted(v, 1.5),
        lambda: onp.apply_along_axis(lambda r: r.sum(), 1, a),
        lambda: onp.nanmean(a), lambda: onp.corrcoef(a),
        lambda: onp.cov(a), lambda: onp.polyfit(v, v, 1),
        lambda: onp.digitize(v, v),
    ]
    failures = []
    for i, fn in enumerate(cases):
        try:
            fn()
        except Exception as e:  # pragma: no cover - failure reporting
            failures.append((i, type(e).__name__, str(e)[:80]))
    assert not failures, failures
