"""Statistical validation of the random samplers (reference:
tests/python/unittest/test_random.py — KS / chi-square goodness-of-fit
per distribution, not just moments). Seeds are fixed; alpha=1e-3 keeps
the false-failure rate negligible.
"""
import numpy as onp
import pytest
import scipy.stats as st

import mxnet_tpu as mx
from mxnet_tpu import nd

N = 20000
ALPHA = 1e-3


def _sample(fn, **kw):
    mx.random.seed(1234)
    return onp.asarray(fn(shape=(N,), **kw).asnumpy())


def test_uniform_ks():
    s = _sample(nd.random_uniform, low=-2.0, high=3.0)
    p = st.kstest(s, st.uniform(loc=-2.0, scale=5.0).cdf).pvalue
    assert p > ALPHA, p
    assert s.min() >= -2.0 and s.max() <= 3.0


def test_normal_ks():
    s = _sample(nd.random_normal, loc=1.5, scale=2.0)
    p = st.kstest(s, st.norm(loc=1.5, scale=2.0).cdf).pvalue
    assert p > ALPHA, p


def test_exponential_ks():
    s = _sample(nd.random_exponential, lam=2.5)
    p = st.kstest(s, st.expon(scale=1 / 2.5).cdf).pvalue
    assert p > ALPHA, p


def test_gamma_ks():
    s = _sample(nd.random_gamma, alpha=3.0, beta=2.0)
    p = st.kstest(s, st.gamma(a=3.0, scale=2.0).cdf).pvalue
    assert p > ALPHA, p


def test_gumbel_ks():
    s = _sample(nd.random_gumbel, loc=0.5, scale=1.5)
    p = st.kstest(s, st.gumbel_r(loc=0.5, scale=1.5).cdf).pvalue
    assert p > ALPHA, p


def test_poisson_chisquare():
    lam = 4.0
    s = _sample(nd.random_poisson, lam=lam).astype(int)
    kmax = 15
    obs = onp.bincount(onp.clip(s, 0, kmax), minlength=kmax + 1)
    pmf = st.poisson(lam).pmf(onp.arange(kmax))
    exp = onp.append(pmf, 1 - pmf.sum()) * N
    keep = exp > 5
    chi = ((obs[keep] - exp[keep]) ** 2 / exp[keep]).sum()
    p = 1 - st.chi2(keep.sum() - 1).cdf(chi)
    assert p > ALPHA, p


def test_randint_chisquare():
    s = _sample(nd.random_randint, low=0, high=10).astype(int)
    obs = onp.bincount(s, minlength=10)
    p = st.chisquare(obs).pvalue
    assert p > ALPHA, p
    assert s.min() >= 0 and s.max() <= 9


def test_negative_binomial_moments():
    k, prob = 5, 0.4
    s = _sample(nd.random_negative_binomial, k=k, p=prob)
    want_mean = k * (1 - prob) / prob
    want_var = k * (1 - prob) / prob ** 2
    assert abs(s.mean() - want_mean) < 0.05 * want_mean
    assert abs(s.var() - want_var) < 0.1 * want_var


def test_multinomial_chisquare():
    mx.random.seed(99)
    probs = nd.array(onp.array([0.1, 0.2, 0.3, 0.4], "f"))
    s = onp.asarray(nd.sample_multinomial(probs, shape=(N,)).asnumpy())
    obs = onp.bincount(s.astype(int).ravel(), minlength=4)
    p = st.chisquare(obs, f_exp=onp.array([0.1, 0.2, 0.3, 0.4]) * obs.sum()
                     ).pvalue
    assert p > ALPHA, p


def test_sample_normal_per_row_ks():
    mx.random.seed(5)
    mu = nd.array(onp.array([0.0, 10.0], "f"))
    sig = nd.array(onp.array([1.0, 3.0], "f"))
    s = onp.asarray(nd.sample_normal(mu=mu, sigma=sig,
                                     shape=(N // 2,)).asnumpy())
    for row, (m, sd) in enumerate([(0.0, 1.0), (10.0, 3.0)]):
        p = st.kstest(s[row], st.norm(loc=m, scale=sd).cdf).pvalue
        assert p > ALPHA, (row, p)


def test_dropout_keep_fraction():
    from mxnet_tpu import autograd

    mx.random.seed(3)
    x = nd.ones((200, 200))
    with autograd.record(train_mode=True):
        out = nd.dropout(x, p=0.3)
    o = onp.asarray(out.asnumpy())
    kept = (o != 0).mean()
    assert abs(kept - 0.7) < 0.02
    # kept values are scaled by 1/(1-p)
    onp.testing.assert_allclose(o[o != 0], 1 / 0.7, rtol=1e-5)


def test_seed_reproducibility_and_divergence():
    mx.random.seed(42)
    a = onp.asarray(nd.random_normal(shape=(100,)).asnumpy())
    mx.random.seed(42)
    b = onp.asarray(nd.random_normal(shape=(100,)).asnumpy())
    onp.testing.assert_array_equal(a, b)
    c = onp.asarray(nd.random_normal(shape=(100,)).asnumpy())
    assert not onp.array_equal(b, c)  # stream advances
