"""Quantization, custom ops, rtc (reference:
tests/python/quantization/test_quantization.py, unittest/test_operator.py
custom-op cases, unittest/test_rtc.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
import mxnet_tpu.operator as mxop
from mxnet_tpu import nd, autograd, rtc
from mxnet_tpu.contrib import quantization as qz
from mxnet_tpu.gluon import nn


def test_quantize_dequantize_roundtrip():
    x = nd.array(onp.linspace(-3, 3, 20).astype("f"))
    q, mn, mx_ = nd.quantize_v2(x, out_type="int8")
    assert str(q.dtype) == "int8"
    deq = nd.dequantize(q, mn, mx_)
    assert float(nd.max(nd.abs(deq - x)).asnumpy()) < 3.0 / 127 + 1e-6
    # uint8 affine
    x2 = nd.array(onp.linspace(0, 6, 20).astype("f"))
    q2, mn2, mx2 = nd.quantize(x2, nd.array(0.0), nd.array(6.0),
                               out_type="uint8")
    assert str(q2.dtype) == "uint8"
    deq2 = nd.dequantize(q2, mn2, mx2)
    assert float(nd.max(nd.abs(deq2 - x2)).asnumpy()) < 6.0 / 255 + 1e-6


def test_quantize_net_mlp():
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(8))
    net.initialize(mx.init.Xavier())
    X = onp.random.RandomState(0).randn(64, 16).astype("f")
    ref = net(nd.array(X)).asnumpy()
    qz.quantize_net(net, calib_data=[nd.array(X)], calib_mode="naive")
    out = net(nd.array(X)).asnumpy()
    rel = onp.abs(out - ref).max() / onp.abs(ref).max()
    assert rel < 0.05, rel


def test_quantize_net_conv_entropy():
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1, activation="relu"),
            nn.GlobalAvgPool2D(), nn.Flatten(), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    Xi = onp.random.RandomState(1).rand(16, 3, 12, 12).astype("f")
    ref = net(nd.array(Xi)).asnumpy()
    qz.quantize_net(net, calib_data=[nd.array(Xi)], calib_mode="entropy")
    out = net(nd.array(Xi)).asnumpy()
    rel = onp.abs(out - ref).max() / onp.abs(ref).max()
    assert rel < 0.1, rel


def test_calib_entropy_sane_threshold():
    rs = onp.random.RandomState(0)
    t = qz.calib_entropy(*onp.histogram(onp.abs(rs.randn(100000)),
                                        bins=2048))
    assert 2.0 < t < 5.0  # high-coverage threshold for a gaussian


def test_quantize_net_exclude_layers():
    net = nn.HybridSequential()
    net.add(nn.Dense(8), nn.Dense(4))
    net.initialize()
    X = onp.random.RandomState(0).randn(8, 6).astype("f")
    net(nd.array(X))
    names = [c.name for c in net._children.values()]
    qz.quantize_net(net, calib_data=[nd.array(X)], exclude_layers=[names[0]])
    kids = list(net._children.values())
    assert isinstance(kids[0], nn.Dense)  # excluded, untouched
    assert not isinstance(kids[1], nn.Dense)  # swapped


def test_quantize_net_subclassed_block():
    from mxnet_tpu.gluon.block import Block

    class M(Block):
        def __init__(self):
            super().__init__()
            self.fc = nn.Dense(8)

        def forward(self, x):
            return self.fc(x)

    m = M()
    m.initialize()
    X = onp.random.RandomState(0).randn(16, 6).astype("f")
    ref = m(nd.array(X)).asnumpy()
    qz.quantize_net(m, calib_data=[nd.array(X)])
    out = m(nd.array(X)).asnumpy()
    d = onp.abs(out - ref).max() / onp.abs(ref).max()
    assert 1e-7 < d < 0.05, d  # actually quantized AND close


def test_quantize_net_dilated_conv():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, 3, padding=2, dilation=2))
    net.initialize()
    Xi = onp.random.RandomState(1).rand(2, 3, 10, 10).astype("f")
    ref = net(nd.array(Xi)).asnumpy()
    qz.quantize_net(net, calib_data=[nd.array(Xi)])
    out = net(nd.array(Xi)).asnumpy()
    assert out.shape == ref.shape
    assert onp.abs(out - ref).max() / onp.abs(ref).max() < 0.05


def test_quantize_net_hybridized_then_save(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(8), nn.Dense(4))
    net.initialize()
    net.hybridize()
    X = onp.random.RandomState(2).randn(8, 6).astype("f")
    ref = net(nd.array(X)).asnumpy()
    qz.quantize_net(net, calib_data=[nd.array(X)])
    out = net(nd.array(X)).asnumpy()
    assert not onp.allclose(out, ref)  # int8 path actually ran
    f = str(tmp_path / "q.params")
    net.save_parameters(f)  # fp32 originals still exportable
    fresh = nn.HybridSequential()
    fresh.add(nn.Dense(8), nn.Dense(4))
    fresh.load_parameters(f)
    assert onp.allclose(fresh(nd.array(X)).asnumpy(), ref, atol=1e-5)


class _Sigmoid(mxop.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        self.assign(out_data[0], req[0], nd.sigmoid(in_data[0]))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0]
        self.assign(in_grad[0], req[0], out_grad[0] * y * (1 - y))


@mxop.register("test_sigmoid")
class _SigmoidProp(mxop.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def create_operator(self, ctx, shapes, dtypes):
        return _Sigmoid()


def test_custom_op_forward_backward():
    a = nd.array([0.5, -1.0, 2.0])
    a.attach_grad()
    with autograd.record():
        out = nd.Custom(a, op_type="test_sigmoid")
        loss = nd.sum(out)
    loss.backward()
    sig = 1 / (1 + onp.exp(-a.asnumpy()))
    assert onp.allclose(out.asnumpy(), sig, atol=1e-6)
    assert onp.allclose(a.grad.asnumpy(), sig * (1 - sig), atol=1e-6)


def test_custom_op_unregistered():
    with pytest.raises(ValueError):
        nd.Custom(nd.ones(3), op_type="never_registered")


def test_rtc_pallas_module():
    def double_kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0

    mod = rtc.PallasModule(double=double_kernel)
    out = mod.get_kernel("double").launch([nd.array([1., 2., 3.])])
    assert onp.allclose(out.asnumpy(), [2., 4., 6.])
    with pytest.raises(ValueError):
        mod.get_kernel("nope")
    with pytest.raises(NotImplementedError):
        rtc.CudaModule("__global__ void f(){}")
