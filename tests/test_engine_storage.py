"""Engine lanes + race/stress, pooled-storage strategies, shm NDArray.

Reference: src/engine/threaded_engine_perdevice.cc (per-device pools +
copy workers), tests/python/unittest/test_engine.py +
test_tlocal_racecondition.py (engine stress), src/storage/
pooled_storage_manager.h (Round/Naive/Unpooled strategies +
MXNET_GPU_MEM_POOL_*), src/storage/cpu_shared_storage_manager.h +
gluon dataloader reduce_ndarray (cross-process shm NDArray).
"""
import multiprocessing as mp
import pickle
import threading
import time

import numpy as onp
import pytest

from mxnet_tpu import engine as eng
from mxnet_tpu import nd
from mxnet_tpu.context import Context
from mxnet_tpu.ndarray.shared_mem import SharedNDArray, shared_empty, to_shared


def _native_engine(**kw):
    try:
        return eng.Engine(**kw)
    except RuntimeError:
        pytest.skip("native engine unavailable")


# ---------------------------------------------------------------- engine ---

def test_engine_write_serialization_stress():
    """500 read-modify-write ops on one var from the pool must serialize
    (writer exclusivity) — a lost update means the mutex is broken."""
    e = _native_engine(nthreads=8)
    v = e.new_variable()
    state = {"x": 0}

    def bump():
        cur = state["x"]
        time.sleep(0)  # widen the race window
        state["x"] = cur + 1

    for _ in range(500):
        e.push(bump, mutable_vars=(v,))
    e.wait_for_var(v)
    assert state["x"] == 500


def test_engine_concurrent_push_threads():
    """Pushing from many Python threads at once (the
    test_tlocal_racecondition analog): all ops run exactly once."""
    e = _native_engine(nthreads=4)
    v = e.new_variable()
    lock = threading.Lock()
    count = [0]

    def bump():
        with lock:
            count[0] += 1

    def producer():
        for _ in range(100):
            e.push(bump, mutable_vars=(v,))

    threads = [threading.Thread(target=producer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    e.wait_all()
    assert count[0] == 800


def test_engine_readers_parallel_writers_exclusive():
    e = _native_engine(nthreads=8)
    data = e.new_variable()
    active = [0]
    peak = [0]
    lock = threading.Lock()

    def reader():
        with lock:
            active[0] += 1
            peak[0] = max(peak[0], active[0])
        time.sleep(0.02)
        with lock:
            active[0] -= 1

    for _ in range(8):
        e.push(reader, const_vars=(data,))
    e.wait_all()
    assert peak[0] > 1, "readers never overlapped — engine is serializing reads"


def test_engine_io_lane_does_not_starve_compute():
    """A slow op on the IO lane must not block compute-lane ops — the
    ThreadedEnginePerDevice property (separate pools per lane)."""
    e = _native_engine(nthreads=2, nlanes=2)
    io_var = e.new_variable()
    cpu_var = e.new_variable()
    done = []

    def slow_io():
        time.sleep(1.0)
        done.append("io")

    def fast_compute():
        done.append("c")

    # saturate the IO lane first
    e.push(slow_io, mutable_vars=(io_var,), lane=eng.LANE_IO)
    e.push(slow_io, mutable_vars=(io_var,), lane=eng.LANE_IO)
    t0 = time.perf_counter()
    for _ in range(20):
        e.push(fast_compute, mutable_vars=(cpu_var,),
               lane=eng.LANE_COMPUTE)
    e.wait_for_var(cpu_var)
    elapsed = time.perf_counter() - t0
    assert elapsed < 0.9, \
        f"compute waited {elapsed:.2f}s behind IO-lane work"
    assert done.count("c") == 20
    e.wait_all()


def test_engine_lane_shares_dependency_state():
    """Ops on different lanes touching the SAME var still order."""
    e = _native_engine(nthreads=2, nlanes=2)
    v = e.new_variable()
    order = []

    e.push(lambda: (time.sleep(0.1), order.append("first"))[-1],
           mutable_vars=(v,), lane=eng.LANE_IO)
    e.push(lambda: order.append("second"), mutable_vars=(v,),
           lane=eng.LANE_COMPUTE)
    e.wait_for_var(v)
    assert order == ["first", "second"]


# --------------------------------------------------------------- storage ---

def _fresh_storage(monkeypatch, **env):
    from mxnet_tpu import storage as st

    for k, v in env.items():
        monkeypatch.setenv(k, v)
    s = st.Storage()
    if not s.native:
        pytest.skip("native storage unavailable")
    return s


@pytest.mark.parametrize("pool_type", ["Naive", "Round", "Unpooled"])
def test_storage_strategies_roundtrip(monkeypatch, pool_type):
    s = _fresh_storage(monkeypatch, MXNET_GPU_MEM_POOL_TYPE=pool_type)
    hs = [s.alloc(n) for n in (100, 5000, 100000, 100)]
    for h in hs:
        assert h.ptr
        s.free(h)
    h2 = s.alloc(100)
    assert h2.ptr
    s.direct_free(h2)
    s.release_all()


def test_storage_round_strategy_reuses_pow2_bucket(monkeypatch):
    s = _fresh_storage(monkeypatch, MXNET_GPU_MEM_POOL_TYPE="Round")
    h1 = s.alloc(70000)  # rounds to 128KiB bucket
    p1 = h1.ptr
    s.free(h1)
    h2 = s.alloc(90000)  # same pow2 bucket -> same pointer back
    assert h2.ptr == p1
    s.direct_free(h2)


def test_storage_reserve_cap_returns_memory(monkeypatch):
    # reserve=100 -> cap 0 pooled bytes -> frees go straight to the OS
    s = _fresh_storage(monkeypatch, MXNET_GPU_MEM_POOL_TYPE="Naive",
                       MXNET_GPU_MEM_POOL_RESERVE="100")
    h = s.alloc(4096)
    s.free(h)
    stats = s.stats() if hasattr(s, "stats") else None
    if stats is not None:
        assert stats["pooled_bytes"] == 0


# ------------------------------------------------------------------- shm ---

def test_shared_ndarray_roundtrip():
    a = to_shared(onp.arange(12, dtype="f").reshape(3, 4))
    assert isinstance(a, SharedNDArray)
    assert a.context.device_type == "cpu_shared"
    onp.testing.assert_array_equal(
        a.asnumpy(), onp.arange(12, dtype="f").reshape(3, 4))
    # interops with regular NDArrays through the op layer
    out = (a + nd.ones((3, 4))).asnumpy()
    onp.testing.assert_array_equal(
        out, onp.arange(12, dtype="f").reshape(3, 4) + 1)


def test_shared_ndarray_ctx_api():
    a = nd.array([[1.0, 2.0]], ctx=Context("cpu_shared"))
    assert isinstance(a, SharedNDArray)
    assert a.context == Context("cpu_shared", 0)


def test_shared_ndarray_inplace_write_visible_through_pickle():
    a = shared_empty((4,), "float32")
    a[:] = onp.array([1, 2, 3, 4], "f")
    b = pickle.loads(pickle.dumps(a))  # descriptor transfer, same segment
    onp.testing.assert_array_equal(b.asnumpy(), [1, 2, 3, 4])
    a[1] = 99.0
    onp.testing.assert_array_equal(b.asnumpy(), [1, 99, 3, 4])


def _child_reads_and_writes(payload, q):
    arr = pickle.loads(payload)
    q.put(arr.asnumpy().tolist())
    arr[0] = 42.0  # visible to the parent: same physical pages


def test_shared_ndarray_cross_process(monkeypatch):
    # spawned child re-imports this module; pin it to the CPU backend so
    # it never dials a TPU tunnel
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.delenv("PALLAS_AXON_POOL_IPS", raising=False)
    ctx = mp.get_context("spawn")
    a = to_shared(onp.array([7.0, 8.0, 9.0], "f"))
    q = ctx.Queue()
    p = ctx.Process(target=_child_reads_and_writes,
                    args=(pickle.dumps(a), q))
    p.start()
    got = q.get(timeout=60)
    p.join(60)
    assert got == [7.0, 8.0, 9.0]
    assert a.asnumpy()[0] == 42.0
