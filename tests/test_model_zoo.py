"""Model zoo coverage (reference: tests/python/unittest/test_gluon_model_zoo.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon.model_zoo import vision


def test_get_model_listing():
    with pytest.raises(ValueError):
        vision.get_model("no_such_model")
    for name in ["resnet18_v1", "alexnet", "vgg11", "vgg16_bn",
                 "squeezenet1_0", "squeezenet1_1", "mobilenet1_0",
                 "mobilenet_v2_1_0", "densenet121", "densenet201",
                 "inception_v3"]:
        net = vision.get_model(name, classes=7)
        assert net is not None, name


@pytest.mark.parametrize("name,size", [("vgg11", 32),
                                       ("mobilenet0_25", 32),
                                       ("mobilenet_v2_0_25", 32)])
def test_zoo_forward(name, size):
    net = vision.get_model(name, classes=5)
    net.initialize(mx.init.Xavier())
    out = net(nd.zeros((2, 3, size, size)))
    assert out.shape == (2, 5)


def test_zoo_hybridize_parity():
    mx.random.seed(42)
    net = vision.get_model("mobilenet_v2_0_25", classes=4)
    net.initialize(mx.init.Xavier())
    x = nd.array(onp.random.RandomState(0).rand(2, 3, 32, 32).astype("f"))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    assert onp.allclose(eager, hybrid, atol=1e-5), \
        onp.abs(eager - hybrid).max()


def test_zoo_save_load(tmp_path):
    net = vision.get_model("squeezenet1_1", classes=4)
    net.initialize(mx.init.Xavier())
    x = nd.zeros((1, 3, 224, 224))
    ref = net(x).asnumpy()
    f = str(tmp_path / "params")
    net.save_parameters(f)
    net2 = vision.get_model("squeezenet1_1", classes=4)
    net2.load_parameters(f)
    assert onp.allclose(net2(x).asnumpy(), ref, atol=1e-6)
