"""Paged KV-cache decode (round 21): DecoderBlockLM as a stateful
serving workload over the paged SessionStateStore.

Covers: continuous-batching decode bitwise vs the explicit-state step
loop across page boundaries (and after an eviction + clean re-open),
the `_attention_decode` lax vs interpreted-flash parity, lazy page
allocation + stats/headroom, page-pressure reclaiming whole LRU
sessions (blast radius: exactly one client, survivors bitwise),
checkpoint restore across page geometries (page size flips and
paged -> row-slot) continuing bitwise, canary promote migrating live
paged sessions with zero drops, the `paged_state` artifact salt
re-keying per geometry while row-slot keys stay byte-stable, warm
process start resolving the paged step executable with zero retraces,
and int8 KV pages (accuracy bound + counters + the unbacked-page
scatter guard)."""
import pickle

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, serving
from mxnet_tpu.analysis import quantize
from mxnet_tpu.models import DecoderBlockLM
from mxnet_tpu.resilience.checkpoint import CheckpointManager
from mxnet_tpu.serving import SessionEvicted, SessionStateStore
from mxnet_tpu.utils import compile_cache as cc

nd = mx.nd

VOCAB, EMBED, HEADS, LAYERS, MAXLEN, PT = 32, 16, 2, 1, 16, 4


def _decoder(seed=21, impl="lax"):
    mx.random.seed(seed)
    net = DecoderBlockLM(VOCAB, embed_dim=EMBED, num_layers=LAYERS,
                         num_heads=HEADS, max_len=MAXLEN, impl=impl)
    net.initialize()
    with autograd.pause(train_mode=False):
        net(nd.zeros((1, 1), dtype="int32"), *_zero_states(net))
    return net


def _zero_states(net):
    return [nd.zeros((1,) + s, dtype=dt)
            for s, dt in zip(net.state_row_shapes(),
                             net.state_row_dtypes())]


_OPEN_STORES = []


def _store(net, page_tokens=PT, **kw):
    kw.setdefault("max_sessions", 8)
    kw.setdefault("ttl_s", 0)
    store = SessionStateStore(net.state_row_shapes(),
                              net.state_row_dtypes(),
                              pageable=net.state_row_pageable(),
                              page_tokens=page_tokens, **kw)
    _OPEN_STORES.append(store)
    return store


def _session(net, store, **kw):
    kw.setdefault("buckets", [1, 2, 4])
    return serving.InferenceSession(
        net, input_shapes=[(1, 1)], input_dtypes=["int32"],
        state_store=store, **kw)


def _toks(seed, n):
    return [onp.random.RandomState(seed + t).randint(
        0, VOCAB, size=(1, 1)).astype("int32") for t in range(n)]


def _oracle(sess, toks):
    """Explicit-state step loop — the client-side state-threading
    contract over the SAME executable; server-side paged storage must
    be bitwise transparent to it."""
    states = _zero_states(sess._block)
    out = None
    for x in toks:
        out, states = sess.step(nd.array(x), states=states)
    return onp.asarray(out.data), [onp.asarray(s.data) for s in states]


@pytest.fixture(autouse=True)
def _fresh_counters():
    serving.reset_serving_counters()
    quantize.reset_counters()
    yield
    # sessions never own an explicitly-passed store: close them here
    # or their occupancy probes leak into later tests' gauges
    while _OPEN_STORES:
        _OPEN_STORES.pop().close()
    serving.reset_serving_counters()
    quantize.reset_counters()


@pytest.fixture(scope="module")
def net():
    return _decoder()


# ---------------------------------------------------------------------------
# decode through the batcher, page boundaries, eviction + re-open

def test_paged_decode_bitwise_across_page_boundaries(net):
    """Streams whose prefixes cross page boundaries (lengths 3/6/11
    over 4-token pages) must decode bitwise vs the explicit-state loop;
    page allocation stays lazy (footprint = ceil(prefix / page))."""
    store = _store(net)
    sess = _session(net, store)
    bat = serving.DynamicBatcher(sess, max_batch_size=4,
                                 max_latency_ms=2.0,
                                 timeout_ms=120000.0, admission=False)
    lengths = {"s0": 3, "s1": 6, "s2": 11}
    toks = {sid: _toks(i * 100, n)
            for i, (sid, n) in enumerate(lengths.items())}
    try:
        futs = {sid: [bat.submit(x, session_id=sid, block=True)
                      for x in seq] for sid, seq in toks.items()}
        for sid, fs in futs.items():
            final = onp.asarray(fs[-1].result(timeout=120))
            ref_o, ref_s = _oracle(sess, toks[sid])
            assert onp.array_equal(final, ref_o), \
                f"stream {sid} not bitwise vs explicit-state loop"
            # the server-side dense rows ARE the chain's states
            for row, ref in zip(store.read(sid), ref_s):
                assert onp.array_equal(row, ref[0]), sid
        st = store.stats()
        assert st["page_tokens"] == PT
        # lazy allocation: 1 + 2 + 3 pages, never ceil(16/4) each
        assert st["pages_used"] == 6
        assert store.page_headroom() == pytest.approx(
            (st["pages_total"] - 6) / st["pages_total"])
        # eviction tears down the WHOLE session...
        store.evict("s2", reason="test")
        assert store.stats()["pages_used"] == 3
        with pytest.raises(SessionEvicted, match="re-open"):
            bat.submit(toks["s2"][0], session_id="s2",
                       block=True).result(timeout=120)
        # ...and an explicit re-open restarts clean: null pages gather
        # as exact zeros, so the replayed stream is bitwise again
        store.open("s2")
        fs = [bat.submit(x, session_id="s2", block=True)
              for x in toks["s2"]]
        ref_o, _ = _oracle(sess, toks["s2"])
        assert onp.array_equal(onp.asarray(fs[-1].result(timeout=120)),
                               ref_o)
    finally:
        bat.close()
        sess.close()


def test_attention_decode_lax_vs_interpret_parity():
    """The decode flash kernel (interpreted off-TPU) matches the lax
    reference within documented-ulp, including partial prefixes."""
    from mxnet_tpu.ndarray import registry

    op = registry.get_op("_attention_decode")
    rs = onp.random.RandomState(7)
    B, S, E = 3, MAXLEN, EMBED
    q = nd.array(rs.randn(B, E).astype("f"))
    kc = nd.array(rs.randn(B, S, E).astype("f"))
    vc = nd.array(rs.randn(B, S, E).astype("f"))
    pos = nd.array(onp.array([[0], [5], [S - 1]], "int32"))
    kw = {"num_heads": HEADS, "sm_scale": 1.0 / (E // HEADS) ** 0.5}
    lax = registry.invoke(op, (q, kc, vc, pos),
                          {**kw, "impl": "lax"}).asnumpy()
    itp = registry.invoke(op, (q, kc, vc, pos),
                          {**kw, "impl": "interpret"}).asnumpy()
    assert onp.abs(lax - itp).max() < 1e-5
    # causality: garbage beyond the visible prefix must not leak
    kc2 = nd.array(onp.where(onp.arange(S)[None, :, None] > 5, 999.0,
                             kc.asnumpy()).astype("f"))
    lax2 = registry.invoke(op, (q, kc2, vc, pos),
                           {**kw, "impl": "lax"}).asnumpy()
    assert onp.array_equal(lax[1], lax2[1])


# ---------------------------------------------------------------------------
# page-pool pressure: whole-session LRU reclaim

def test_page_pressure_evicts_whole_lru_session(net):
    """3 slots x 6 pages: a 4th stream's page demand reclaims the LRU
    session ENTIRELY (never a torn cache) and only that one client
    sees SessionEvicted; survivors stay bitwise."""
    store = _store(net, max_sessions=3, byte_budget=3200)
    assert store.num_slots == 3 and store.num_pages == 6
    rs = onp.random.RandomState(11)
    rows = {sid: [rs.randn(*s).astype(dt) for s, dt in
                  zip(net.state_row_shapes(), net.state_row_dtypes())]
            for sid in ("a", "b", "c")}
    for sid in ("a", "b", "c"):  # 2 pages each: the pool is full
        store.open(sid, init_states=rows[sid], tokens=8)
    assert store.page_headroom() == 0.0
    store.open("d", init_states=rows["a"], tokens=4)  # reclaims "a"
    assert sorted(store.live_sessions()) == ["b", "c", "d"]
    with pytest.raises(SessionEvicted, match="re-open"):
        store.acquire("a")
    assert serving.serving_stats()["evictions"] == 1
    pageable = net.state_row_pageable()
    for i, row in enumerate(store.read("b")):  # survivor untouched
        if pageable[i]:  # tokens=8 seeded 2 pages; the rest is null
            assert onp.array_equal(row[:8], rows["b"][i][:8])
            assert not row[8:].any()
        else:
            assert onp.array_equal(row, rows["b"][i])


# ---------------------------------------------------------------------------
# checkpoint mid-stream, restore across geometries

def test_checkpoint_mid_stream_restores_across_geometries(net, tmp_path):
    """A checkpoint taken mid-page under 4-token pages must resume
    bitwise under 8-token pages AND under row-slot storage — the
    payload is dense rows, geometry is a server detail."""
    toks = _toks(31, 8)
    sess = _session(net, _store(net))
    mgr = CheckpointManager(str(tmp_path), session_state=sess.state_store,
                            async_mode=False)
    bat = serving.DynamicBatcher(sess, max_batch_size=2,
                                 max_latency_ms=2.0,
                                 timeout_ms=120000.0, admission=False,
                                 state_checkpoint=mgr)
    for x in toks[:6]:  # 6 steps: page 1 full, page 2 half-written
        bat.submit(x, session_id="u", block=True).result(timeout=120)
    bat.close()  # drains to the boundary and checkpoints
    sess.close()
    ref_o, _ = _oracle_fresh(net, toks)

    for page_tokens in (8, 0):  # coarser pages, then row-slot
        serving.reset_serving_counters()
        sess2 = _session(net, _store(net, page_tokens=page_tokens))
        CheckpointManager(str(tmp_path), session_state=sess2.state_store,
                          async_mode=False).restore()
        assert sess2.state_store.live_sessions() == ["u"]
        assert serving.serving_stats()["resumed_sessions"] == 1
        bat2 = serving.DynamicBatcher(sess2, max_batch_size=2,
                                      max_latency_ms=2.0,
                                      timeout_ms=120000.0,
                                      admission=False)
        try:
            for x in toks[6:]:
                out = onp.asarray(bat2.submit(
                    x, session_id="u", block=True).result(timeout=120))
            assert onp.array_equal(out, ref_o), \
                f"restore into page_tokens={page_tokens} not bitwise"
        finally:
            bat2.close()
            sess2.close()


def _oracle_fresh(net, toks):
    sess = _session(net, _store(net))
    try:
        return _oracle(sess, toks)
    finally:
        sess.close()


def test_fleet_migration_page16_restores_into_page64_int8():
    """Round-23 fleet drain wire form: a session exported from a
    replica paging KV at PAGE_TOKENS=16 restores onto a replica
    running page size 64 with int8 KV pages on. The payload is dense
    rows, so the 16 -> 64 crossing itself is bitwise: an fp32
    destination reads back the exported rows byte-for-byte and
    continues bitwise vs the offline oracle; the int8 destination
    keeps every NON-pageable row bitwise and its KV pages inside the
    documented quantization bound (its own storage choice, not a
    migration loss)."""
    mx.random.seed(23)
    net64 = DecoderBlockLM(VOCAB, embed_dim=EMBED, num_layers=LAYERS,
                           num_heads=HEADS, max_len=64, impl="lax")
    net64.initialize()
    with autograd.pause(train_mode=False):
        net64(nd.zeros((1, 1), dtype="int32"), *_zero_states(net64))
    toks = _toks(47, 12)
    sess = _session(net64, _store(net64, page_tokens=16))
    bat = serving.DynamicBatcher(sess, max_batch_size=2,
                                 max_latency_ms=2.0,
                                 timeout_ms=120000.0, admission=False)
    try:
        for x in toks[:6]:
            bat.submit(x, session_id="u", block=True).result(timeout=120)
    finally:
        bat.close()
    # the exact bytes a FleetRouter drain moves between replicas
    wire = pickle.dumps(sess.state_store.export_state(),
                        protocol=pickle.HIGHEST_PROTOCOL)
    sess.close()
    payload = pickle.loads(wire)
    assert list(payload["sessions"]) == ["u"]
    src_rows = payload["sessions"]["u"]["states"]
    pageable = net64.state_row_pageable()
    ref_o, _ = _oracle_fresh(net64, toks)

    # fp32 page-64 destination: dense rows land bitwise, decode
    # continues bitwise
    sess64 = _session(net64, _store(net64, page_tokens=64))
    bat64 = serving.DynamicBatcher(sess64, max_batch_size=2,
                                   max_latency_ms=2.0,
                                   timeout_ms=120000.0, admission=False)
    try:
        assert sess64.state_store.restore_state(
            pickle.loads(wire)) == 1
        for got, want in zip(sess64.state_store.read("u"), src_rows):
            assert onp.array_equal(onp.asarray(got), onp.asarray(want))
        for x in toks[6:]:
            out = onp.asarray(bat64.submit(
                x, session_id="u", block=True).result(timeout=120))
        assert onp.array_equal(out, ref_o), \
            "page 16 -> 64 migration not bitwise"
    finally:
        bat64.close()
        sess64.close()

    # page-64 + int8-KV destination: non-pageable rows stay bitwise,
    # KV pages and the continued decode stay inside the int8 bound
    quantize.reset_counters()
    sess8 = _session(net64, _store(net64, page_tokens=64,
                                   kv_int8=True))
    bat8 = serving.DynamicBatcher(sess8, max_batch_size=2,
                                  max_latency_ms=2.0,
                                  timeout_ms=120000.0, admission=False)
    try:
        assert sess8.state_store.restore_state(
            pickle.loads(wire)) == 1
        assert quantize.counters()["kv_pages_quantized"] > 0
        for got, want, paged in zip(sess8.state_store.read("u"),
                                    src_rows, pageable):
            got, want = onp.asarray(got), onp.asarray(want)
            if paged:
                denom = max(float(onp.abs(want).max()), 1e-6)
                assert float(onp.abs(got - want).max()) / denom < 0.1
            else:
                assert onp.array_equal(got, want)
        for x in toks[6:]:
            out8 = onp.asarray(bat8.submit(
                x, session_id="u", block=True).result(timeout=120))
        denom = max(float(onp.abs(ref_o).max()), 1e-6)
        assert float(onp.abs(out8 - ref_o).max()) / denom < 0.1, \
            "int8 destination drifted past the KV accuracy bound"
    finally:
        bat8.close()
        sess8.close()


# ---------------------------------------------------------------------------
# canary promote migrates paged sessions — zero drops

def test_canary_promote_migrates_paged_sessions(net):
    repo = serving.ModelRepository(max_latency_ms=2.0, admission=False)
    toks = {sid: _toks(i * 50 + 7, 5) for i, sid in
            enumerate(("u1", "u2"))}
    try:
        repo.deploy("m", _session(net, _store(net)))
        for sid, seq in toks.items():
            for x in seq[:3]:
                repo.submit("m", x, session_id=sid).result(timeout=120)
        # v2 stores KV under a DIFFERENT page size: migration is dense
        v2 = _session(net, _store(net, page_tokens=8))
        repo.deploy("m", v2)
        serving.reset_serving_counters()
        repo.promote("m")
        assert sorted(v2.state_store.live_sessions()) == ["u1", "u2"]
        assert serving.serving_stats()["resumed_sessions"] == 2
        for sid, seq in toks.items():
            for x in seq[3:]:
                out = repo.submit("m", x,
                                  session_id=sid).result(timeout=120)
            ref_o, _ = _oracle_fresh(net, seq)
            assert onp.array_equal(onp.asarray(out), ref_o), sid
    finally:
        repo.close()


# ---------------------------------------------------------------------------
# artifact identity + warm start

def test_paged_salt_rekeys_per_geometry_row_slot_stable(net):
    """Page geometry and int8-KV re-key step artifacts; row-slot keys
    ignore the paged knobs entirely (byte-stable across flips)."""
    sess_row = _session(net, _store(net, page_tokens=0))
    sess_p4 = _session(net, _store(net, page_tokens=4))
    sess_p8 = _session(net, _store(net, page_tokens=8))
    sess_i8 = _session(net, _store(net, page_tokens=4, kv_int8=True))
    try:
        fps = [s._step_artifact(1, 0).fingerprint
               for s in (sess_row, sess_p4, sess_p8, sess_i8)]
        assert all(fp is not None for fp in fps)
        assert len(set(fps)) == 4, "each geometry must key its own"
        sess_row2 = _session(net, _store(net, page_tokens=0))
        try:
            assert sess_row2._step_artifact(1, 0).fingerprint == fps[0]
        finally:
            sess_row2.close()
    finally:
        for s in (sess_row, sess_p4, sess_p8, sess_i8):
            s.close()


def test_warm_start_paged_step_zero_retraces(net, tmp_path, monkeypatch):
    """A second process's paged decode session resolves its step
    executable from the disk tier — zero traces before serving."""
    monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR", str(tmp_path))
    cold = _session(net, _store(net), buckets=[1])
    x = _toks(3, 1)[0]
    out_c, _ = _oracle(cold, [x])
    cold.close()

    serving.reset_serving_counters()
    cc.reset_compile_cache_counters()
    warm = _session(net, _store(net), buckets=[1])
    try:
        out_w, _ = _oracle(warm, [x])
        st = cc.compile_cache_stats()
        assert st["retraces"] == 0, "warm paged session must not trace"
        assert st["disk_hits"] >= 1
        assert onp.array_equal(out_c, out_w)
    finally:
        warm.close()


# ---------------------------------------------------------------------------
# int8 KV pages

def test_int8_kv_pages_accuracy_and_counters(net):
    store = _store(net, kv_int8=True)
    assert store.stats()["kv_int8"] is True
    sess = _session(net, store)
    bat = serving.DynamicBatcher(sess, max_batch_size=2,
                                 max_latency_ms=2.0,
                                 timeout_ms=120000.0, admission=False)
    toks = _toks(91, 10)
    try:
        for x in toks:
            out = onp.asarray(bat.submit(
                x, session_id="q", block=True).result(timeout=120))
        ref_o, _ = _oracle(sess, toks)  # fp32 client-side states
        denom = max(float(onp.abs(ref_o).max()), 1e-6)
        assert float(onp.abs(out - ref_o).max()) / denom < 0.1, \
            "int8 KV pages drifted past the accuracy bound"
        assert quantize.counters()["kv_pages_quantized"] > 0
    finally:
        bat.close()
        sess.close()


def test_scatter_into_unbacked_page_is_refused(net):
    """scatter() without the acquire() that backs the step's page must
    raise — silently writing the null page would corrupt every
    session."""
    store = _store(net)
    store.open("s")  # fresh table: all null pages
    rec = store._slots["s"]
    rows = [onp.zeros((1,) + s, dt) for s, dt in
            zip(net.state_row_shapes(), net.state_row_dtypes())]
    with pytest.raises(mx.MXNetError, match="unbacked"):
        store.scatter([rec], rows)
