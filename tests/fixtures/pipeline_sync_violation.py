"""Seeded graft_lint L401 violation fixture (NOT imported by the
package). graft-lint: scope(step-loop)

The marker comment above opts this file into the step-loop host-sync
discipline that ``mxnet_tpu/pipeline/`` and ``gluon/trainer.py`` get
automatically; the tier-1 lint test asserts every violation species
below is flagged. Keep this file OUTSIDE mxnet_tpu/ so
``python -m tools.graft_lint mxnet_tpu`` stays clean on the shipped
tree.
"""
import numpy as onp


def bad_step_loop(feed, net, trainer):
    for xb, yb in feed:
        loss = ((net(xb) - yb) ** 2).mean()
        loss.backward()
        trainer.step(xb.shape[0])
        # L401: per-step metric readback — serializes the pipeline
        total = float(loss.asnumpy())
        # L401: device→host transfer mid-loop
        host = onp.asarray(loss)
        # L401: explicit device barrier in the hot path
        loss.data.block_until_ready()
        # L401: scalar sync
        s = loss.item()
        # L401: reference-style wait
        loss.wait_to_read()
    return total, host, s


def whitelisted_epoch_end(losses):
    # epoch-end readback is the blessed pattern: one sync per epoch
    return [float(l.asnumpy()) for l in losses]  # graft-lint: allow(L401)
