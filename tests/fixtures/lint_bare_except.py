"""Seeded graft_lint L501 fixture: bare/silently-swallowed excepts.

NOT part of the framework — tests/test_graft_lint.py lints this file
and asserts the rule catches every violation (and honors the pragma'd
site). Keep the violation inventory in sync with the test.
"""


def bare_clause():
    """Violation: a bare except eats SystemExit/KeyboardInterrupt."""
    try:
        return 1 / 0
    except:  # noqa: E722 — the violation under test
        return None


def swallowed_broad():
    """Violation: broad handler whose body is only pass."""
    try:
        return open("/nonexistent")
    except Exception:
        pass


def swallowed_base_tuple():
    """Violation: BaseException inside a tuple, still swallowed."""
    try:
        return open("/nonexistent")
    except (ValueError, BaseException):
        ...


def narrow_swallow_ok():
    """NOT a violation: a narrow type may be deliberately ignored."""
    try:
        return open("/nonexistent")
    except FileNotFoundError:
        pass


def broad_but_handled_ok():
    """NOT a violation: the broad handler does something."""
    try:
        return open("/nonexistent")
    except Exception as e:
        return repr(e)


def pragma_ok():
    """NOT a finding: the deliberate site carries the pragma."""
    try:
        return open("/nonexistent")
    except Exception:  # graft-lint: allow(L501)
        pass
