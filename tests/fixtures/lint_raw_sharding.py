# graft-lint: scope(sharding-plan)
"""Seeded graft_lint L701 fixture: raw sharding construction.

NOT part of the framework — tests/test_graft_lint.py lints this file
and asserts the rule catches every construction form (direct, aliased,
module-dotted) and honors the pragma'd site. Keep the violation
inventory in sync with the test.
"""
import jax.sharding
import jax.sharding as js
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P


def bad_direct(mesh):
    """Violation: direct NamedSharding construction."""
    return NamedSharding(mesh, P())  # two violations: both classes


def bad_module_dotted(mesh):
    """Violation: fully-dotted and module-aliased forms."""
    spec = jax.sharding.PartitionSpec("dp")
    return js.NamedSharding(mesh, spec)


def allowed_site(mesh):
    """A deliberate pre-plan site, pragma'd — must stay clean."""
    return NamedSharding(mesh, P("dp"))  # graft-lint: allow(L701)


def not_a_construction(arr, other):
    """Reads and same-named attrs on OTHER modules must stay clean."""
    spec = arr.sharding.spec  # attribute read, not a call
    return other.PartitionSpec(spec)  # not jax.sharding's class
