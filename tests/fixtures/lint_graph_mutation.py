"""Seeded graft_lint L601 violation fixture (NOT imported by the
package). graft-lint: scope(symbol-graph)

The marker comment above opts this file into the no-graph-mutation
discipline that ``mxnet_tpu/`` (outside ``analysis/`` and ``symbol/``)
gets automatically; the tier-1 lint test asserts every mutation species
below is flagged. Keep this file OUTSIDE mxnet_tpu/ so
``python -m tools.graft_lint mxnet_tpu`` stays clean on the shipped
tree.
"""


def bad_rewire(node, other):
    # L601: re-pointing a node's op in place
    node._op = "identity"
    # L601: splicing an input edge under a shared DAG
    node._inputs.append(other)
    # L601: attr write through a subscript
    node._attrs["__shape__"] = "(1,)"
    # L601: mutating call on the kwargs dict
    node._kwargs.update({"axes": (1, 0)})
    return node


def good_reads(node):
    # reads are fine — only mutation rewires the graph
    op = node._op
    fan_in = len(node._inputs)
    declared = node._attrs.get("__shape__")
    return op, fan_in, declared


class OwnFields:
    """A class managing its OWN fields named like node attrs is not a
    graph rewrite — self/cls receivers are exempt."""

    def __init__(self):
        self._inputs = []
        self._attrs = {}

    def add(self, x):
        self._inputs.append(x)
        self._attrs["n"] = len(self._inputs)


def whitelisted_builder(node, attrs):
    # constructor-adjacent sites (quantization/AMP/ONNX import) carry
    # the pragma so the exemption is explicit and reviewable
    node._attrs.update(attrs)  # graft-lint: allow(L601)
    return node
