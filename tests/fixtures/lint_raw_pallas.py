# graft-lint: scope(pallas-kernels)
"""Seeded graft_lint L801 fixture: raw Pallas imports.

NOT part of the framework — tests/test_graft_lint.py lints this file
and asserts the rule catches every import form (module import, dotted
tpu submodule, from-experimental, from-pallas) and honors the pragma'd
site. Keep the violation inventory in sync with the test.
"""
import jax.experimental.pallas
import jax.experimental.pallas.tpu as pltpu
from jax.experimental import pallas as pl
from jax.experimental.pallas import BlockSpec


def allowed_site():
    """A deliberate non-kernels Pallas site, pragma'd — stays clean."""
    from jax.experimental import pallas  # graft-lint: allow(L801)
    return pallas


def not_pallas():
    """Sibling experimental imports must stay clean."""
    from jax.experimental import mesh_utils
    import jax.experimental.shard_map as sm
    return mesh_utils, sm
