"""Seeded graft_lint L1201 violation fixture (NOT imported by the
package). graft-lint: scope(policy-literal)

The marker comment above opts this file into the decision-point
discipline the fusion cost-model files (``kernels/cost_model.py``,
``analysis/fusion.py``) get automatically; the tier-1 lint test
asserts every policy-literal species below is flagged. Keep this file
OUTSIDE mxnet_tpu/ so ``python -m tools.graft_lint mxnet_tpu`` stays
clean on the shipped tree.
"""
from mxnet_tpu.autotune import declare_decision, lookup

# -- species 1: module-constant numeric policy literals -------------------

_BAD_THRESHOLD = 64                  # L1201: bare numeric constant
BAD_BYTES_CAP = 1 << 22              # L1201: literal shift expression
_BAD_NEGATIVE = -4                   # L1201: unary-minus literal
_BAD_PRODUCT = 4 * 1024              # L1201: literal product

# hardware geometry is not tunable policy: the pragma is the exit
_TILE_FLOOR = 128  # graft-lint: allow(L1201)

# the sanctioned form: the constant IS the registry declaration
_GOOD_THRESHOLD = declare_decision(
    "fixture.threshold", candidates=(16, 64, 4096), default=64)

# non-numeric and non-constant bindings are out of scope
_NAME = "attention"
_ALIAS = _GOOD_THRESHOLD
lowercase_number = 9999  # not a module CONSTANT: no finding


# -- species 2: inline comparisons against policy literals ----------------

def bad_inline_compare(seq, size):
    if seq >= 64:                    # L1201: inline threshold
        return False
    return size > (1 << 22)          # L1201: literal-shift comparator


def good_structural_compares(shape, n_nodes):
    # small structural constants stay exempt (|n| <= 8)
    if len(shape) >= 2 and n_nodes != 0 and shape[-1] % 8 == 0:
        tuned = lookup("fixture.threshold", ("cpu",))
        bound = tuned if tuned is not None else _GOOD_THRESHOLD
        return shape[-2] >= bound    # named threshold: no finding
    return False


def whitelisted_inline(size):
    # a deliberate non-policy constant carries the pragma
    return size > 65535  # graft-lint: allow(L1201) — wire-format bound
