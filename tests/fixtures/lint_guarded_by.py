"""Seeded graft_lint L1102 violation fixture (NOT imported by the
package). graft-lint: scope(ranked-locks)

A ``# guards: a, b`` annotation on a ranked-lock assignment is a
machine-checked contract: every access to a guarded attribute outside
the lock is flagged. The tier-1 lint test asserts each violation
species below fires while the sanctioned holding idioms — ``with``
block, acquire/release, ``lock = getattr(self, "_lock", ...)`` alias,
``*_locked`` helper, ``__init__``, shared-lock condition — stay clean.
"""
from mxnet_tpu.utils.locks import RankedCondition, RankedLock, RankedRLock

# guards: _REGISTRY
_MODULE_LOCK = RankedLock("artifact.salts")
_REGISTRY = {}


def bad_module_read(name):
    return _REGISTRY.get(name)  # L1102: module-global read, no lock


def good_module_write(name, value):
    with _MODULE_LOCK:
        _REGISTRY[name] = value


class Store:
    def __init__(self):
        # guards: _slots, _closed
        self._lock = RankedRLock("serving.store")
        self._cond = RankedCondition(lock=self._lock)
        self._slots = {}   # __init__ is exempt: no concurrency yet
        self._closed = False

    def bad_unlocked_read(self, sid):
        return self._slots.get(sid)  # L1102: guarded attr, no lock

    def bad_unlocked_write(self):
        self._closed = True  # L1102: guarded attr, no lock

    def good_with_lock(self, sid, slot):
        with self._lock:
            self._slots[sid] = slot

    def good_with_shared_condition(self):
        # the condition was built over self._lock: holding it IS
        # holding the lock
        with self._cond:
            return len(self._slots)

    def good_acquire_release(self):
        self._lock.acquire()
        try:
            return dict(self._slots)
        finally:
            self._lock.release()

    def good_alias_via_getattr(self):
        lock = getattr(self, "_lock", None)
        with lock:
            return self._closed

    def _evict_locked(self, sid):
        # *_locked suffix: caller holds the lock by convention
        self._slots.pop(sid, None)

    def good_whitelisted_fast_path(self):
        # a deliberate unlocked read carries the pragma and a reason
        return self._closed  # graft-lint: allow(L1102)
