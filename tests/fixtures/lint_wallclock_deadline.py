"""Seeded graft_lint L602 violation fixture (NOT imported by the
package). graft-lint: scope(serving-deadline)

The marker comment above opts this file into the monotonic-clock
deadline discipline that ``mxnet_tpu/serving/`` gets automatically;
the tier-1 lint test asserts every wall-clock species below is
flagged. Keep this file OUTSIDE mxnet_tpu/ so
``python -m tools.graft_lint mxnet_tpu`` stays clean on the shipped
tree.
"""
import time
from time import time as now


def bad_deadline_math(timeout_s, queue):
    # L602: wall-clock deadline — one NTP step expires every request
    deadline = time.time() + timeout_s
    while queue:
        req = queue.pop()
        # L602: wall-clock comparison at a queue exit
        if time.time() > deadline:
            return req
    return None


def bad_aliased_read():
    # L602: `from time import time` must not hide the wall clock
    return now()


def good_monotonic(timeout_s):
    deadline = time.monotonic() + timeout_s
    return deadline - time.monotonic()


def whitelisted_log_stamp():
    # log/record timestamps are the blessed wall-clock use
    return time.time()  # graft-lint: allow(L602)
