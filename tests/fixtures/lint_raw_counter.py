"""Seeded graft_lint L901 violation fixture (NOT imported by the
package). graft-lint: scope(counter-registry)

The marker comment above opts this file into the counter-registry
discipline that ``mxnet_tpu/`` (outside ``telemetry/``) gets
automatically; the tier-1 lint test asserts every raw-mutation
species below is flagged. Keep this file OUTSIDE mxnet_tpu/ so
``python -m tools.graft_lint mxnet_tpu`` stays clean on the shipped
tree.
"""
import threading


def _zero_counters():
    return {"hits": 0, "misses": 0}


_COUNTERS = _zero_counters()
_STATS = {"evictions": 0}
_LOCK = threading.Lock()


def bad_increment(name):
    # L901: subscript write to a module-level raw counter dict
    _COUNTERS[name] = _COUNTERS.get(name, 0) + 1


def bad_augassign():
    # L901: augmented in-place bump
    _STATS["evictions"] += 1


def bad_bulk_update(snapshot):
    # L901: mutating call
    _COUNTERS.update(snapshot)


def bad_clear():
    # L901: mutating call (a lock does not make it registry-visible)
    with _LOCK:
        _COUNTERS.clear()


def good_read(name):
    # reads are fine — the rule is about writes bypassing the registry
    return dict(_COUNTERS), _COUNTERS.get(name, 0)


def whitelisted_bootstrap():
    # a deliberate seed/bootstrap site carries the pragma
    _STATS["evictions"] = 0  # graft-lint: allow(L901)
