"""Seeded graft_lint L1001 violation fixture (NOT imported by the
package). graft-lint: scope(salt-providers)

The marker comment above opts this file into the salt discipline that
``mxnet_tpu/`` (outside ``artifact/``, ``utils/compile_cache.py`` and
provider-defining files) gets automatically; the tier-1 lint test
asserts every ad-hoc-assembly species below is flagged. Keep this
file OUTSIDE mxnet_tpu/ so ``python -m tools.graft_lint mxnet_tpu``
stays clean on the shipped tree.
"""
from mxnet_tpu.analysis.graph_opt import fingerprint_salt
from mxnet_tpu.utils import compile_cache as cc
from mxnet_tpu.utils.compile_cache import fingerprint as _fp


def bad_method_salt(plan, mesh):
    # L1001: folding a subsystem salt into a cache key by hand
    return plan.fingerprint_salt(mesh) + ("zero1", True)


def bad_name_salt(level):
    # L1001: direct provider-function call at a consumer site
    return ("graph", fingerprint_salt(level))


def bad_raw_fingerprint(key):
    # L1001: raw fingerprint composition (module-alias form)
    return cc.fingerprint("dispatch", key)


def bad_raw_fingerprint_from_import(key):
    # L1001: raw fingerprint composition (from-import alias form)
    return _fp("serving", key)


def good_artifact(key):
    # the sanctioned path: declarative salts resolved by the layer
    from mxnet_tpu.artifact import CompiledArtifact

    return CompiledArtifact("dispatch", key, salts=("graph_opt",))


def whitelisted_legacy(plan, mesh):
    # a deliberate legacy site carries the pragma
    return plan.fingerprint_salt(mesh)  # graft-lint: allow(L1001)
