"""Seeded graft_lint violation fixture (NOT imported by the package).

Each block below violates one lint invariant on purpose; the tier-1
lint test asserts graft_lint flags every one of them. Keep this file
OUTSIDE mxnet_tpu/ so ``python -m tools.graft_lint mxnet_tpu`` stays
clean on the shipped tree.
"""
import os
import time

import jax
import numpy as onp

from mxnet_tpu.ndarray.registry import register


def bad_env_reads():
    # L101: direct environment read of an MXNET_* knob
    a = os.environ.get("MXNET_EAGER_JIT", "1")
    # L101 + L102: direct read of a knob that is not even registered
    b = os.environ["MXNET_TOTALLY_BOGUS_KNOB"]
    # L101 via os.getenv
    c = os.getenv("MXNET_FUSED_STEP")
    return a, b, c


def registered_knob_check():
    from mxnet_tpu import env

    # L102: blessed helper, but the knob is not in KNOBS
    return env.get_int("MXNET_NOT_A_REAL_KNOB", 3)


def bad_raw_jit():
    # jit-nocache: raw jax.jit bypasses the compile-cache helpers
    # (counting_jit retrace accounting + persistent disk tier)
    return jax.jit(lambda x: x + 1)


@register("lint_fixture_bad_op")
def lint_fixture_bad_op(data):  # L301: no docstring
    t = time.perf_counter()           # L201: host clock in a jit body
    seed = onp.random.randint(0, 7)   # L201: host numpy RNG
    key = jax.random.PRNGKey(seed)    # L202: constant key baked in
    print("tracing", t)               # L201: print in a jit body
    return data + jax.random.uniform(key, data.shape)
