"""Seeded graft_lint L1101/L1103 violation fixture (NOT imported by
the package). graft-lint: scope(ranked-locks)

The marker comment above opts this file into the ranked-lock
discipline that ``mxnet_tpu/`` (outside ``utils/locks.py``) gets
automatically; the tier-1 lint test asserts every raw-construction
species and every blocking-under-lock species below is flagged. Keep
this file OUTSIDE mxnet_tpu/ so ``python -m tools.graft_lint
mxnet_tpu`` stays clean on the shipped tree.
"""
import threading
import threading as _t
import time
from threading import Condition, RLock
from urllib.request import urlopen

from mxnet_tpu.utils.locks import RankedLock
from mxnet_tpu.resilience.retry import RetryPolicy

# -- L1101: raw lock construction ----------------------------------------

_BAD_LOCK = threading.Lock()          # L1101: module-attr Lock
_BAD_RLOCK = RLock()                  # L1101: from-imported RLock
_BAD_COND = Condition()               # L1101: from-imported Condition
_BAD_ALIASED = _t.Lock()              # L1101: aliased module attr


def bad_local_condition():
    # L1101: raw Condition over a raw lock, inside a function
    return threading.Condition(threading.Lock())


# a deliberately unranked site carries the pragma and a reason
_HARNESS_LOCK = threading.Lock()  # graft-lint: allow(L1101) — bench harness

# the ranked factory is the sanctioned form
_GOOD_LOCK = RankedLock("profiler")

# -- L1103: blocking calls inside a ranked-lock body ---------------------


def bad_blocking_under_lock(arr, retry):
    with _GOOD_LOCK:
        arr.asnumpy()                         # L1103: host sync
        time.sleep(0.1)                       # L1103: sleep
        fh = open("/tmp/x")                   # L1103: file IO
        urlopen("http://example.com")         # L1103: HTTP
        RetryPolicy(max_attempts=3)           # L1103: retry machinery
        retry.run(lambda: None)               # L1103: retry loop
    return fh


def good_blocking_outside_lock(arr):
    # the same calls OUTSIDE the locked region are fine
    host = arr.asnumpy()
    time.sleep(0.0)
    with _GOOD_LOCK:
        n = len(host)  # pure in-memory work under the lock is fine
    return n


def whitelisted_block_under_lock():
    with _GOOD_LOCK:
        # a deliberate site (cold path, documented) carries the pragma
        time.sleep(0.0)  # graft-lint: allow(L1103)
