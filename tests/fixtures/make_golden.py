"""Generate golden interop fixtures BYTE-BY-BYTE from the reference
format specs — deliberately independent of mxnet_tpu's own writers, so a
bug shared by this repo's writer+reader cannot hide (the reference pins
its own loader the same way with tests/python/unittest/legacy_ndarray.v0).

Specs transcribed from:
- .params: src/ndarray/ndarray.cc NDArray::Save (V2 magic 0xF993fac9,
  int32 stype, TShape = int32 ndim + int64 dims per include/mxnet/
  tuple.h:704 with dim_t = int64 per c_api.h:62, Context = int32
  dev_type + int32 dev_id per base.h:157, int32 type_flag, raw LE data),
  list container ndarray.cc:1840 (uint64 0x112, uint64 reserved,
  uint64 count, arrays, uint64 nnames, {uint64 len, bytes} names).
- symbol JSON: nnvm graph JSON as written by 1.x-era mxnet (CamelCase op
  names, stringified attrs) — docs/architecture note + legacy_json_util.cc.
- .rec/.idx: dmlc recordio (magic 0xced7230a, lrec = cflag<<29 | len,
  4-byte record padding, split records at magic collisions) +
  python/mxnet/recordio.py IRHeader '<IfQQ'.

Run from the repo root:  python tests/fixtures/make_golden.py
"""
import json
import os
import struct

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))

# ---------------------------------------------------------------- params ---

TYPE_FLAGS = {"float32": 0, "float64": 1, "float16": 2, "uint8": 3,
              "int32": 4, "int8": 5, "int64": 6}


def nd_v2_bytes(arr):
    out = [struct.pack("<I", 0xF993FAC9),          # NDARRAY_V2_MAGIC
           struct.pack("<i", 0),                   # kDefaultStorage
           struct.pack("<i", arr.ndim)]
    out += [struct.pack("<q", int(d)) for d in arr.shape]
    out += [struct.pack("<ii", 1, 0),              # Context cpu(0)
            struct.pack("<i", TYPE_FLAGS[str(arr.dtype)]),
            arr.astype(arr.dtype.newbyteorder("<")).tobytes("C")]
    return b"".join(out)


def params_bytes(named):
    return container_bytes([nd_v2_bytes(a) for _, a in named],
                           [n for n, _ in named])


def container_bytes(entries, names):
    """The 0x112 list container (ndarray.cc:1840) — the ONE framing
    implementation every era shares."""
    out = [struct.pack("<QQ", 0x112, 0),           # list magic, reserved
           struct.pack("<Q", len(entries))]
    out += entries
    out.append(struct.pack("<Q", len(names)))
    for n in names:
        b = n.encode()
        out.append(struct.pack("<Q", len(b)) + b)
    return b"".join(out)


def golden_arrays():
    return [
        ("arg:fc_weight", np.arange(12, dtype=np.float32).reshape(4, 3)
         * 0.25 - 1.0),
        ("arg:fc_bias", np.array([0.5, -0.5, 1.25, 0.0], np.float32)),
        # int64 payload with values past 2^32 — catches width bugs in
        # both the dims and the data
        ("aux:counters", np.array([2**40 + 7, -3, 1, 2**33], np.int64)),
        ("arg:half", np.array([[1.5, -2.0]], np.float16)),
        ("arg:bytes", np.arange(24, dtype=np.uint8).reshape(2, 3, 4)),
    ]


# ---------------------------------------------------------------- symbol ---

def golden_symbol_json():
    """A 1.x-style exported graph: data -> FullyConnected -> Activation,
    CamelCase ops, stringified attrs, a user __lr_mult__ on the weight."""
    nodes = [
        {"op": "null", "name": "data", "inputs": []},
        {"op": "null", "name": "fc_weight", "inputs": [],
         "attrs": {"__lr_mult__": "2.0"}},
        {"op": "null", "name": "fc_bias", "inputs": []},
        {"op": "FullyConnected", "name": "fc",
         "attrs": {"num_hidden": "4", "no_bias": "False"},
         "inputs": [[0, 0, 0], [1, 0, 0], [2, 0, 0]]},
        {"op": "Activation", "name": "act",
         "attrs": {"act_type": "relu"},
         "inputs": [[3, 0, 0]]},
    ]
    return json.dumps({
        "nodes": nodes,
        "arg_nodes": [0, 1, 2],
        "node_row_ptr": [0, 1, 2, 3, 4, 5],
        "heads": [[4, 0, 0]],
        "attrs": {"mxnet_version": ["int", 10700]}}, indent=2)


# ------------------------------------------------------------- recordio ---

RIO_MAGIC = 0xCED7230A
RIO_MAGIC_BYTES = struct.pack("<I", RIO_MAGIC)


def rio_record(payload):
    """One recordio frame stream for a payload, split at embedded magics
    (dmlc/recordio.h: cflag 0 whole, 1 start, 2 middle, 3 end)."""
    hits = []
    start = 0
    while True:
        i = payload.find(RIO_MAGIC_BYTES, start)
        if i < 0:
            break
        hits.append(i)
        start = i + 4

    def frame(cflag, part):
        pad = (-len(part)) % 4
        return (RIO_MAGIC_BYTES +
                struct.pack("<I", (cflag << 29) | len(part)) +
                part + b"\x00" * pad)

    if not hits:
        return frame(0, payload)
    bounds = [0] + hits + [len(payload)]
    out = []
    n = len(hits) + 1
    for k in range(n):
        lo = bounds[k] + (4 if k else 0)
        part = payload[lo:bounds[k + 1]]
        out.append(frame(1 if k == 0 else (3 if k == n - 1 else 2), part))
    return b"".join(out)


def ir_pack(flag, label, rec_id, payload):
    return struct.pack("<IfQQ", flag, label, rec_id, 0) + payload


def golden_records():
    return [
        ir_pack(0, 3.0, 0, b"first record payload"),
        # payload CONTAINING the magic word: forces the split encoding
        ir_pack(0, 7.5, 1, b"AB" + RIO_MAGIC_BYTES + b"tail" +
                RIO_MAGIC_BYTES),
        ir_pack(0, -1.0, 2, b""),
    ]


# -------------------------------------------------- legacy .params eras ---

def nd_v1_bytes(arr):
    """V1 (0xF993fac8): no stype field; TShape still int32 ndim + int64
    dims (ndarray.cc:1596 'with int64_t mxnet::TShape',
    LegacyTShapeLoad -> shape->Load)."""
    out = [struct.pack("<I", 0xF993FAC8),
           struct.pack("<i", arr.ndim)]
    out += [struct.pack("<q", int(d)) for d in arr.shape]
    out += [struct.pack("<ii", 1, 0),
            struct.pack("<i", TYPE_FLAGS[str(arr.dtype)]),
            arr.astype(arr.dtype.newbyteorder("<")).tobytes("C")]
    return b"".join(out)


def nd_ancient_bytes(arr):
    """Oldest era: the leading uint32 IS the ndim, dims are uint32
    (LegacyTShapeLoad default branch, ndarray.cc:1683-1697)."""
    out = [struct.pack("<I", arr.ndim)]
    out += [struct.pack("<I", int(d)) for d in arr.shape]
    out += [struct.pack("<ii", 1, 0),
            struct.pack("<i", TYPE_FLAGS[str(arr.dtype)]),
            arr.astype(arr.dtype.newbyteorder("<")).tobytes("C")]
    return b"".join(out)





def write_legacy():
    a = np.arange(6, dtype=np.float32).reshape(2, 3) * 0.5
    b = np.array([7, 8, 9], np.int32)
    with open(os.path.join(HERE, "golden_v1.params"), "wb") as f:
        f.write(container_bytes(
            [nd_v1_bytes(a), nd_v1_bytes(b)], ["w", "idx"]))
    with open(os.path.join(HERE, "golden_legacy.params"), "wb") as f:
        f.write(container_bytes(
            [nd_ancient_bytes(a), nd_ancient_bytes(b)], ["w", "idx"]))
    # bare LIST file (no names): reference NDArray::Load permits
    # keys.size()==0 (ndarray.cc:1864)
    with open(os.path.join(HERE, "golden_list.params"), "wb") as f:
        f.write(container_bytes([nd_v2_bytes(a)], []))
    print("wrote golden_v1.params, golden_legacy.params, "
          "golden_list.params")


def main():
    with open(os.path.join(HERE, "golden_v2.params"), "wb") as f:
        f.write(params_bytes([(n, a) for n, a in golden_arrays()]))
    with open(os.path.join(HERE, "golden-symbol.json"), "w") as f:
        f.write(golden_symbol_json())
    offsets = []
    blob = b""
    for rec in golden_records():
        offsets.append(len(blob))
        blob += rio_record(rec)
    with open(os.path.join(HERE, "golden.rec"), "wb") as f:
        f.write(blob)
    with open(os.path.join(HERE, "golden.rec.idx"), "w") as f:
        for i, off in enumerate(offsets):
            f.write(f"{i}\t{off}\n")
    print("wrote golden_v2.params, golden-symbol.json, golden.rec(.idx)")
    write_legacy()


if __name__ == "__main__":
    main()
