"""Per-dtype op sweep: every family's representative ops run at
fp32/fp16/bf16 through the eager<->jit check_consistency oracle, with
half-precision results checked against the fp32 run within the dtype
tolerance ladder (reference: tests/python/gpu/test_operator_gpu.py
re-importing the CPU suite through check_consistency + test_utils get_tols)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import check_consistency, default_tols, with_seed

# (case name, fn(*NDArrays), input generators) — grouped by SURVEY §2.2
# family rows. Each runs at every dtype in DTYPES.
CASES = {
    # elemwise / broadcast
    "add": (lambda a, b: a + b, [(4, 5), (4, 5)]),
    "broadcast_mul": (lambda a, b: nd.broadcast_mul(a, b), [(4, 5), (1, 5)]),
    "broadcast_minimum": (lambda a, b: nd.broadcast_minimum(a, b),
                          [(3, 4), (3, 1)]),
    "exp": (lambda a: nd.exp(a), [(6,)]),
    "sqrt_abs": (lambda a: nd.sqrt(nd.abs(a)), [(3, 3)]),
    "tanh": (lambda a: nd.tanh(a), [(2, 7)]),
    "sigmoid": (lambda a: nd.sigmoid(a), [(5, 2)]),
    "relu": (lambda a: nd.relu(a), [(4, 4)]),
    "clip": (lambda a: nd.clip(a, -0.5, 0.5), [(8,)]),
    "where": (lambda c, a, b: nd.where(c, a, b), [(4,), (4,), (4,)]),
    # reductions + indexing
    "sum_axis": (lambda a: nd.sum(a, axis=1), [(4, 6)]),
    "mean_keepdims": (lambda a: nd.mean(a, axis=0, keepdims=True),
                      [(5, 3)]),
    "max_all": (lambda a: nd.max(a), [(3, 4)]),
    "argmax": (lambda a: nd.argmax(a, axis=1), [(4, 5)]),
    "norm": (lambda a: nd.norm(a), [(6,)]),
    "take": (lambda a: nd.take(a, nd.array([1.0, 0.0, 2.0])), [(4, 3)]),
    "slice_axis": (lambda a: nd.slice_axis(a, axis=1, begin=1, end=3),
                   [(2, 5)]),
    "reverse": (lambda a: nd.reverse(a, axis=0), [(4, 2)]),
    # matrix / linalg
    "dot": (lambda a, b: nd.dot(a, b), [(4, 3), (3, 5)]),
    "batch_dot": (lambda a, b: nd.batch_dot(a, b), [(2, 3, 4), (2, 4, 2)]),
    "transpose": (lambda a: nd.transpose(a, (1, 0)), [(3, 5)]),
    "linalg_gemm2": (lambda a, b: nd.linalg.gemm2(a, b),
                     [(3, 4), (4, 3)]),
    # NN core
    "fully_connected": (
        lambda x, w, b: nd.FullyConnected(x, w, b, num_hidden=6),
        [(4, 8), (6, 8), (6,)]),
    "convolution": (
        lambda x, w, b: nd.Convolution(x, w, b, kernel=(3, 3),
                                       num_filter=4, pad=(1, 1)),
        [(2, 3, 8, 8), (4, 3, 3, 3), (4,)]),
    "pooling_max": (
        lambda x: nd.Pooling(x, kernel=(2, 2), pool_type="max",
                             stride=(2, 2)),
        [(2, 3, 8, 8)]),
    "softmax": (lambda a: nd.softmax(a, axis=-1), [(4, 7)]),
    "log_softmax": (lambda a: nd.log_softmax(a, axis=-1), [(4, 7)]),
    "batch_norm_infer": (
        lambda x, g, b, m, v: nd.batch_norm(
            x, g, b, m, v, use_batch_stats=False, use_global_stats=True),
        [(4, 3, 5, 5), (3,), (3,), (3,), (3,)]),
    "layer_norm": (
        lambda x, g, b: nd.LayerNorm(x, g, b, axis=-1), [(4, 6), (6,), (6,)]),
    "dropout_eval": (lambda a: nd.Dropout(a, p=0.5, mode="training"),
                     [(5, 5)]),  # eval mode: identity
    "leaky_relu": (lambda a: nd.LeakyReLU(a, slope=0.1), [(3, 6)]),
    "embedding": (
        lambda idx, w: nd.Embedding(idx, w, input_dim=10, output_dim=4),
        [(6,), (10, 4)]),
    # sequence / legacy
    "sequence_mask": (
        lambda x, l: nd.SequenceMask(x, l, use_sequence_length=True),
        [(5, 3, 2), (3,)]),
    "sequence_reverse": (
        lambda x: nd.SequenceReverse(x), [(5, 3, 2)]),
    "concat": (lambda a, b: nd.concat(a, b, dim=1), [(3, 2), (3, 4)]),
    "stack": (lambda a, b: nd.stack(a, b, axis=0), [(4,), (4,)]),
    "tile": (lambda a: nd.tile(a, (2, 3)), [(2, 2)]),
    "pad_const": (
        lambda a: nd.Pad(a, mode="constant",
                         pad_width=(0, 0, 0, 0, 1, 1, 2, 2)),
        [(1, 1, 3, 3)]),
    # numpy namespace
    "np_matmul": (lambda a, b: mx.np.matmul(a, b), [(3, 4), (4, 2)]),
    "np_einsum": (lambda a, b: mx.np.einsum("ij,jk->ik", a, b),
                  [(2, 3), (3, 2)]),
    # more elemwise/special
    "hypot": (lambda a, b: nd.hypot(a, b), [(3, 4), (3, 4)]),
    "erf": (lambda a: nd.erf(a), [(5,)]),
    "log1p": (lambda a: nd.log1p(nd.abs(a)), [(6,)]),
    "sign": (lambda a: nd.sign(a), [(4, 4)]),
    "square": (lambda a: nd.square(a), [(4, 4)]),
    "smooth_l1": (lambda a: nd.smooth_l1(a, scalar=1.0), [(3, 5)]),
    "hard_sigmoid": (lambda a: nd.hard_sigmoid(a), [(2, 6)]),
    "softsign": (lambda a: nd.softsign(a), [(2, 6)]),
    # more reductions/shape
    "prod": (lambda a: nd.prod(nd.abs(a) + 0.5, axis=1), [(3, 4)]),
    "min_axis": (lambda a: nd.min(a, axis=0), [(4, 3)]),
    "repeat": (lambda a: nd.repeat(a, repeats=3, axis=0), [(2, 3)]),
    "expand_squeeze": (
        lambda a: nd.squeeze(nd.expand_dims(a, axis=1), axis=1),
        [(4, 5)]),
    "flip": (lambda a: nd.flip(a, axis=1), [(3, 4)]),
    "depth_to_space": (lambda a: nd.depth_to_space(a, block_size=2),
                       [(1, 8, 3, 3)]),
    "one_hot": (lambda i: nd.one_hot(i, depth=5), [(6,)]),
    "pick": (lambda a, i: nd.pick(a, i, axis=1), [(4, 5), (4,)]),
    "gather_nd": (lambda a, i: nd.gather_nd(a, i), [(4, 5), (2, 3)]),
    "diag": (lambda a: nd.diag(a), [(4, 4)]),
    # more NN
    "global_avg_pool": (
        lambda x: nd.Pooling(x, pool_type="avg", global_pool=True,
                             kernel=(1, 1)),
        [(2, 3, 6, 6)]),
    "instance_norm": (
        lambda x, g, b: nd.InstanceNorm(x, g, b), [(2, 3, 7), (3,), (3,)]),
    "l2_normalization": (
        lambda x: nd.L2Normalization(x, mode="instance"), [(4, 6)]),
    "group_norm": (
        lambda x, g, b: nd.GroupNorm(x, g, b, num_groups=2),
        [(2, 4, 5), (2,), (2,)]),
}

DTYPES = ["float32", "float16", "bfloat16"]


def _gen(rng, shape, name):
    if name in ("take", "embedding") and shape == (6,):
        return rng.randint(0, 10, shape).astype("f")
    if name == "one_hot" and shape == (6,):
        return rng.randint(0, 5, shape).astype("f")
    if name == "pick" and shape == (4,):
        return rng.randint(0, 5, shape).astype("f")
    if name == "gather_nd" and shape == (2, 3):
        # row 0: indices into dim0 (<4), row 1: into dim1 (<5)
        return onp.stack([rng.randint(0, 4, 3),
                          rng.randint(0, 5, 3)]).astype("f")
    if name == "sequence_mask" and shape == (3,):
        return onp.array([2.0, 5.0, 1.0], "f")
    return rng.randn(*shape).astype("f")


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("case", sorted(CASES))
@with_seed(0)
def test_op_dtype(case, dtype):
    fn, shapes = CASES[case]
    # per-case deterministic inputs: identical whether the test runs in
    # isolation or inside the full sweep (crc32, NOT hash() — str hash
    # is randomized per process so failures wouldn't reproduce)
    import zlib

    rng = onp.random.RandomState(zlib.crc32(case.encode()) % (2**31))
    inputs = []
    for i, shp in enumerate(shapes):
        if case == "where" and i == 0:
            inputs.append((rng.rand(*shp) > 0.5).astype("f"))
        elif case == "batch_norm_infer" and i == 4:
            # running VARIANCE must be positive (sqrt)
            inputs.append(rng.rand(*shp).astype("f") + 0.5)
        else:
            inputs.append(_gen(rng, shp, case))
    if case == "dropout_eval":
        # Dropout at eval is identity; under record it samples — compare
        # only the deterministic eval path
        from mxnet_tpu import autograd

        with autograd.pause(train_mode=False):
            check_consistency(fn, inputs, dtype=dtype)
        return
    kwargs = {}
    if case in ("argmax",):
        # index outputs: eager/jit must agree EXACTLY, but rounding to
        # half precision can legitimately reorder near-ties vs fp32
        kwargs = {"rtol": 0, "atol": 0, "compare_with_fp32": False}
    # contraction ops: operand rounding alone injects ~eps error per
    # product term, so the half-precision-vs-fp32 check needs an abs
    # floor of K*eps (reference loosens the same families in
    # test_operator_gpu.py check_consistency tol tables)
    contraction = {"dot", "batch_dot", "linalg_gemm2", "fully_connected",
                   "convolution", "np_matmul", "np_einsum",
                   "batch_norm_infer", "layer_norm", "instance_norm",
                   "group_norm", "l2_normalization", "prod"}
    if case in contraction and dtype in ("float16", "bfloat16"):
        kwargs = {"rtol": 6e-2, "atol": 2e-2} if dtype == "bfloat16" \
            else {"rtol": 2e-2, "atol": 5e-3}
    check_consistency(fn, inputs, dtype=dtype, **kwargs)


def test_tolerance_ladder_is_monotonic():
    rungs = [default_tols(d) for d in ("float64", "float32", "float16",
                                      "bfloat16")]
    rtols = [r for r, _ in rungs]
    assert rtols == sorted(rtols), "ladder must loosen as precision drops"


@with_seed(123)
def test_with_seed_restores_determinism():
    a = onp.random.rand(4)
    mxa = mx.nd.random.uniform(shape=(4,)).asnumpy()
    onp.random.seed(123)
    mx.random.seed(123)
    onp.testing.assert_allclose(onp.random.rand(4), a)
    onp.testing.assert_allclose(
        mx.nd.random.uniform(shape=(4,)).asnumpy(), mxa)


# ---- backward at reduced precision ---------------------------------------

BWD_CASES = {
    "fully_connected": (
        lambda x, w, b: nd.FullyConnected(x, w, b, num_hidden=6),
        [(4, 8), (6, 8), (6,)]),
    "convolution": (
        lambda x, w, b: nd.Convolution(x, w, b, kernel=(3, 3),
                                       num_filter=4, pad=(1, 1)),
        [(2, 3, 8, 8), (4, 3, 3, 3), (4,)]),
    "tanh": (lambda a: nd.tanh(a), [(3, 5)]),
    "softmax": (lambda a: nd.softmax(a, axis=-1), [(4, 7)]),
    "layer_norm": (
        lambda x, g, b: nd.LayerNorm(x, g, b, axis=-1),
        [(4, 6), (6,), (6,)]),
    "dot": (lambda a, b: nd.dot(a, b), [(4, 3), (3, 5)]),
}


@pytest.mark.parametrize("dtype", ["float16", "bfloat16"])
@pytest.mark.parametrize("case", sorted(BWD_CASES))
@with_seed(0)
def test_op_backward_dtype(case, dtype):
    """Gradients computed at half precision track the fp32 gradients
    within the contraction rung (reference: fp16 training tests,
    tests/python/train/test_dtype.py — backward is where precision
    loss actually bites)."""
    import zlib

    import jax
    import jax.numpy as jnp

    fn, shapes = BWD_CASES[case]
    rng = onp.random.RandomState(zlib.crc32(case.encode()) % (2**31))
    inputs = [rng.randn(*s).astype("f") for s in shapes]

    def grads_at(cast):
        def scalar(*ds):
            out = fn(*[nd.NDArray(d) for d in ds])
            return jnp.sum(out.data.astype(jnp.float32) ** 2)

        datas = [jnp.asarray(a).astype(cast) for a in inputs]
        gs = jax.jit(jax.grad(scalar, argnums=tuple(
            range(len(datas)))))(*datas)
        return [onp.asarray(g.astype(jnp.float32)) for g in gs]

    ref = grads_at(jnp.float32)
    got = grads_at(jnp.dtype(dtype))
    rtol, atol = (6e-2, 2e-2) if dtype == "bfloat16" else (2e-2, 5e-3)
    for i, (g, r) in enumerate(zip(got, ref)):
        onp.testing.assert_allclose(
            g, r, rtol=rtol, atol=atol * max(1.0, onp.abs(r).max()),
            err_msg=f"{case} grad[{i}] at {dtype}")
