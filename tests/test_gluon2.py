"""Gluon Block semantics, second suite (reference:
tests/python/unittest/test_gluon.py, 115 fns — parameter sharing and
scoping, hybridize caching, save/load edge cases, hooks, SymbolBlock,
grad_req, deferred init)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.test_utils import assert_almost_equal, with_seed


def _x(*shape):
    return nd.array(onp.random.RandomState(0).randn(*shape).astype("f"))


def test_parameter_sharing_via_params():
    """Reference: test_gluon.py test_parameter_sharing."""
    d1 = nn.Dense(4, in_units=3)
    d2 = nn.Dense(4, in_units=3, params=d1.collect_params())
    d1.initialize()
    x = _x(2, 3)
    assert_almost_equal(d2(x), d1(x).asnumpy())
    # updating through one handle is visible through the other
    for _, p in d1.collect_params().items():
        p.set_data(p.data() * 0 + 1.0)
    assert_almost_equal(d2(x), d1(x).asnumpy())


def test_name_scope_prefixes():
    class Net(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.fc = nn.Dense(2)

        def hybrid_forward(self, F, x):
            return self.fc(x)

    n = Net(prefix="outer_")
    names = list(n.collect_params().keys())
    assert all(k.startswith("outer_") for k in names), names


def test_hybridize_caches_and_matches_eager():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(3))
    net.initialize()
    x = _x(4, 5)
    eager = net(x).asnumpy()
    net.hybridize()
    jit1 = net(x).asnumpy()
    jit2 = net(x).asnumpy()
    assert_almost_equal(jit1, eager, rtol=1e-5)
    assert_almost_equal(jit2, eager, rtol=1e-5)


def test_save_load_parameters_roundtrip(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(6, activation="tanh"), nn.BatchNorm(), nn.Dense(2))
    net.initialize()
    x = _x(3, 4)
    with autograd.pause(train_mode=False):
        want = net(x).asnumpy()
    p = str(tmp_path / "p.params")
    net.save_parameters(p)
    net2 = nn.HybridSequential()
    net2.add(nn.Dense(6, activation="tanh"), nn.BatchNorm(), nn.Dense(2))
    net2.load_parameters(p)
    with autograd.pause(train_mode=False):
        assert_almost_equal(net2(x).asnumpy(), want, rtol=1e-6)


def test_load_parameters_errors(tmp_path):
    net = nn.Dense(3, in_units=2)
    net.initialize()
    p = str(tmp_path / "d.params")
    net.save_parameters(p)
    other = nn.Dense(5, in_units=2)
    with pytest.raises(Exception):
        other.load_parameters(p)  # shape mismatch must not pass silently


def test_forward_hooks_fire():
    net = nn.Dense(2, in_units=3)
    net.initialize()
    calls = []
    h1 = net.register_forward_pre_hook(
        lambda blk, inp: calls.append("pre"))
    h2 = net.register_forward_hook(
        lambda blk, inp, out: calls.append("post"))
    net(_x(1, 3))
    assert calls == ["pre", "post"]
    h1.detach()
    h2.detach()
    calls.clear()
    net(_x(1, 3))
    assert calls == []


def test_grad_req_null_excludes_from_step():
    net = nn.Dense(2, in_units=3)
    net.initialize()
    for _, p in net.collect_params().items():
        if p.name.endswith("bias"):
            p.grad_req = "null"
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 1.0})
    before = {k: p.data().asnumpy().copy()
              for k, p in net.collect_params().items()}
    with autograd.record():
        loss = net(_x(4, 3)).sum()
    loss.backward()
    trainer.step(1)
    for k, p in net.collect_params().items():
        if k.endswith("bias"):
            assert_almost_equal(p.data(), before[k])  # untouched
        else:
            assert not onp.allclose(p.data().asnumpy(), before[k])


def test_deferred_init_infers_in_units():
    net = nn.Dense(4)  # in_units unknown
    net.initialize()
    out = net(_x(5, 7))
    assert out.shape == (5, 4)
    assert net.weight.shape == (4, 7)


def test_uninitialized_forward_raises():
    net = nn.Dense(4, in_units=3)
    with pytest.raises(Exception):
        net(_x(1, 3))


def test_constant_parameter():
    class Net(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.c = self.params.get_constant(
                "c", onp.array([2.0, 3.0], "f"))

        def hybrid_forward(self, F, x, c):
            return x * c

    n = Net()
    n.initialize()
    out = n(nd.array(onp.ones((2, 2), "f")))
    assert_almost_equal(out, onp.array([[2, 3], [2, 3]], "f"))
    # constants take no gradient step
    with autograd.record():
        loss = n(nd.array(onp.ones((1, 2), "f"))).sum()
    loss.backward()


def test_symbolblock_imports_exported(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(5, activation="relu"), nn.Dense(2))
    net.initialize()
    net.hybridize()
    x = _x(2, 3)
    want = net(x).asnumpy()
    net.export(str(tmp_path / "m"), epoch=0)
    sb = gluon.SymbolBlock.imports(
        str(tmp_path / "m-symbol.json"), ["data"],
        str(tmp_path / "m-0000.params"))
    assert_almost_equal(sb(x), want, rtol=1e-5)


def test_children_and_named_iteration():
    net = nn.HybridSequential()
    net.add(nn.Dense(2), nn.Dense(3))
    kids = list(net._children.values())
    assert len(kids) == 2
    assert isinstance(kids[1], nn.Dense)


def test_block_repr_and_summary_run():
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3))
    net.initialize()
    net(_x(1, 3))
    net.summary()  # prints; must not raise


def test_trainer_learning_rate_set():
    net = nn.Dense(2, in_units=2)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.5})
    assert tr.learning_rate == 0.5
    tr.set_learning_rate(0.125)
    assert tr.learning_rate == 0.125


def test_trainer_save_load_states(tmp_path):
    net = nn.Dense(2, in_units=2)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9})
    for _ in range(3):
        with autograd.record():
            loss = net(_x(4, 2)).sum()
        loss.backward()
        tr.step(1)
    p = str(tmp_path / "tr.states")
    tr.save_states(p)
    tr2 = gluon.Trainer(net.collect_params(), "sgd",
                        {"learning_rate": 0.1, "momentum": 0.9})
    tr2.load_states(p)
    # momentum buffers restored: one step from each must agree
    with autograd.record():
        loss = net(_x(4, 2)).sum()
    loss.backward()
    tr2.step(1)


@with_seed(9)
def test_dropout_train_vs_eval():
    net = nn.Dropout(0.5)
    x = nd.array(onp.ones((200,), "f"))
    with autograd.pause(train_mode=False):
        assert_almost_equal(net(x), onp.ones(200))  # identity at eval
    with autograd.record(train_mode=True):
        y = net(x).asnumpy()
    assert (y == 0).any() and (y > 1.0).any()  # dropped + rescaled


def test_embedding_block_grad_sparse_rows():
    emb = nn.Embedding(10, 4)
    emb.initialize()
    idx = nd.array(onp.array([1.0, 3.0, 1.0], "f"))
    with autograd.record():
        out = emb(idx)
        loss = out.sum()
    loss.backward()
    g = emb.weight.grad().asnumpy()
    assert (g[1] == 2.0).all() and (g[3] == 1.0).all()
    assert (g[0] == 0).all()


def test_sequential_getitem_len():
    net = nn.HybridSequential()
    net.add(nn.Dense(2), nn.Dense(3), nn.Dense(4))
    assert len(net) == 3
    assert isinstance(net[1], nn.Dense)


def test_apply_and_cast():
    net = nn.HybridSequential()
    net.add(nn.Dense(2, in_units=2))
    net.initialize()
    seen = []
    net.apply(lambda b: seen.append(type(b).__name__))
    assert "Dense" in seen
    net.cast("float16")
    assert "float16" in str(net[0].weight.dtype)


def test_parameter_sharing_nested_prefixes(tmp_path):
    """The reference's own sharing scenario (test_gluon.py:227): blocks
    with DIFFERENT prefixes share via params=; the sharing net creates
    its params under the SHARED dict's prefix, and checkpoints load
    across prefixes by structure."""
    class Net(gluon.Block):
        def __init__(self, in_units=0, **kwargs):
            super().__init__(**kwargs)
            with self.name_scope():
                self.dense0 = nn.Dense(5, in_units=in_units)
                self.dense1 = nn.Dense(5, in_units=in_units)

        def forward(self, x):
            return self.dense1(self.dense0(x))

    net1 = Net(prefix="net1_", in_units=5)
    net2 = Net(prefix="net2_", params=net1.collect_params())
    net1.collect_params().initialize()
    x = _x(3, 5)
    out2 = net2(x)
    assert_almost_equal(out2, net1(x).asnumpy())
    # param names of net2 live under net1_'s prefix (true sharing)
    assert set(net2.collect_params().keys()) == \
        set(net1.collect_params().keys())
    # structure-based load across prefixes
    p = str(tmp_path / "net1.params")
    net1.save_parameters(p)
    net3 = Net(prefix="net3_", in_units=5)
    net3.load_parameters(p)
    assert_almost_equal(net3(x), net1(x).asnumpy())


def test_register_op_hook_taps_and_detaches():
    """Reference: block.py register_op_hook — per-op output taps in
    eager AND hybridized execution, detachable."""
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import nd, gluon

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(4, activation="relu"), gluon.nn.Dense(2))
    net.initialize(mx.init.Xavier())
    seen = []
    handle = net.register_op_hook(lambda name, arr: seen.append(name))
    x = nd.array(onp.ones((2, 3), "f"))
    net(x)
    assert any("dense" in s for s in seen), seen
    assert any(s.endswith("_output") for s in seen)
    n_eager = len(seen)
    net.hybridize()
    net(x)  # hooks force the eager path: taps fire...
    assert len(seen) > n_eager
    n1 = len(seen)
    net(x)  # ...on EVERY call, not just the trace
    assert len(seen) > n1
    handle.detach()
    before = len(seen)
    net(x)  # cached path resumes, tap-free
    net(x)
    assert len(seen) == before  # taps removed


def test_register_op_hook_nested_hybrid_and_order():
    """Hooks see concrete values through independently hybridized
    children on every call, and handles detach safely in any order."""
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import nd, gluon

    inner = gluon.nn.HybridSequential()
    inner.add(gluon.nn.Dense(4, activation="relu"))
    outer = gluon.nn.HybridSequential()
    outer.add(inner, gluon.nn.Dense(2))
    outer.initialize(mx.init.Xavier())
    inner.hybridize()  # child has its own cache
    x = nd.array(onp.ones((2, 3), "f"))
    outer(x)  # build caches
    values = []
    h1 = outer.register_op_hook(
        lambda name, arr: values.append(float(arr.asnumpy().max())))
    names2 = []
    h2 = outer.register_op_hook(lambda name, arr: names2.append(name))
    outer(x)
    outer(x)  # concrete values BOTH calls (no tracer leak via caches)
    assert len(values) >= 4 and all(
        isinstance(v, float) for v in values)
    n2 = len(names2)
    # out-of-order detach: h1 first, h2 keeps firing
    h1.detach()
    nv = len(values)
    outer(x)
    assert len(values) == nv  # h1 gone
    assert len(names2) > n2  # h2 alive
    h2.detach()
    n2 = len(names2)
    outer(x)
    assert len(names2) == n2  # fully detached, cache path restored


def test_parameter_reset_ctx():
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import context, nd, gluon

    net = gluon.nn.Dense(3, in_units=2)
    net.initialize(mx.init.Xavier())
    out_before = net(nd.array(onp.ones((1, 2), "f"))).asnumpy()
    ctx = context.cpu(0)
    net.collect_params().reset_ctx(ctx)
    # the buffers really moved: committed to exactly the requested device
    for _, p in net.collect_params().items():
        devs = p.data().data.sharding.device_set
        assert devs == {ctx.jax_device}, devs
    out_after = net(nd.array(onp.ones((1, 2), "f"))).asnumpy()
    onp.testing.assert_allclose(out_after, out_before, rtol=1e-6)
    # uninitialized parameters refuse loudly instead of silently
    # materializing on the wrong device later
    lazy = gluon.nn.Dense(2)
    lazy.initialize()
    import pytest as _pytest

    with _pytest.raises(ValueError, match="not been initialized"):
        lazy.collect_params().reset_ctx(ctx)
