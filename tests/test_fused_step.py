"""Fused compiled train-step (gluon/fused_step.py + Trainer wiring).

Covers the compiled-executable step against the eager per-param loop:
bitwise parity (incl. an AMP skip-step episode), dynamic-scalar
hyperparameters (no retrace on set_learning_rate / loss-scale motion),
save/load round-trips before and after compilation, the coalesced
fallback allreduce, and the counter/feature surfaces."""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon, profiler, runtime
from mxnet_tpu.contrib.amp.loss_scaler import LossScaler
from mxnet_tpu.gluon import fused_step
from mxnet_tpu.gluon.parameter import Parameter


@pytest.fixture(autouse=True)
def _fresh_cache():
    saved = {k: os.environ.pop(k, None)
             for k in ("MXNET_FUSED_STEP", "MXNET_FUSED_STEP_DONATE")}
    fused_step.reset_fused_step_cache()
    yield
    for k, v in saved.items():
        os.environ.pop(k, None)
        if v is not None:
            os.environ[k] = v
    fused_step.reset_fused_step_cache()


def _make_params(n=6, dim=4, seed=0, dtype="float32"):
    rs = onp.random.RandomState(seed)
    params = []
    for i in range(n):
        shape = (dim, dim) if i % 2 == 0 else (dim,)
        p = Parameter(f"p{i}", shape=shape, dtype=dtype)
        p.initialize()
        p.set_data(nd.array(rs.randn(*shape).astype("f")))
        params.append(p)
    return params


def _set_grads(params, step, seed=100, poison=False):
    rs = onp.random.RandomState(seed + step)
    for p in params:
        g = rs.randn(*p.shape).astype("f") * 0.1
        if poison:
            g = onp.full(p.shape, onp.inf, "f")
        p.grad()._data = nd.array(g).astype(
            str(p.data().data.dtype)).data


def _run(optimizer, opt_args, fused, steps=6, scaler=None, inf_at=None,
         lr_at=None, multi_precision=False, dtype="float32"):
    os.environ["MXNET_FUSED_STEP"] = "1" if fused else "0"
    params = _make_params(dtype=dtype)
    args = dict(opt_args)
    if multi_precision:
        args["multi_precision"] = True
    tr = gluon.Trainer(params, optimizer, args)
    if scaler is not None:
        tr._amp_loss_scaler = scaler
    for s in range(steps):
        if lr_at is not None and s == lr_at:
            tr.set_learning_rate(0.01)
        _set_grads(params, s, poison=(inf_at is not None and s == inf_at))
        tr.step(2)
    return [p.data().asnumpy() for p in params], tr


def _bitwise(ws1, ws2):
    return all(a.tobytes() == b.tobytes() for a, b in zip(ws1, ws2))


@pytest.mark.parametrize("opt,args", [
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9}),
    ("sgd", {"learning_rate": 0.05, "clip_gradient": 0.02}),
    ("nag", {"learning_rate": 0.05, "momentum": 0.9}),
    ("signum", {"learning_rate": 0.01, "momentum": 0.9}),
])
def test_fused_matches_eager_bitwise(opt, args):
    we, _ = _run(opt, args, fused=False)
    wf, _ = _run(opt, args, fused=True)
    assert _bitwise(we, wf)


@pytest.mark.parametrize("opt,args", [
    ("adagrad", {"learning_rate": 0.05, "wd": 0.01}),
    ("rmsprop", {"learning_rate": 0.01}),
    ("rmsprop", {"learning_rate": 0.01, "centered": True}),
    ("adadelta", {}),
    ("ftrl", {"learning_rate": 0.1}),
    ("adam", {"learning_rate": 0.01}),
])
def test_fused_matches_eager_ulp(opt, args):
    """Optimizers whose update contains a division by sqrt match to a
    few ulps but not bitwise: XLA's algebraic simplifier rewrites
    a/sqrt(b) into a*rsqrt(b) (or not) depending on the fusion context,
    which differs between one whole-step executable and the eager
    per-op executables. Adam additionally computes its bias-correction
    coefficient in device float32 (t is device-resident for skip-step
    parity) vs host float64 on the eager path."""
    we, _ = _run(opt, args, fused=False)
    wf, _ = _run(opt, args, fused=True)
    assert all(onp.allclose(a, b, rtol=1e-4, atol=1e-6)
               for a, b in zip(we, wf))


def test_fused_amp_skip_episode_bitwise():
    """An all-inf gradient step must be skipped on device (lax.cond),
    halve the scale, and leave the trajectory bitwise equal to eager."""
    we, tre = _run("sgd", {"learning_rate": 0.05, "momentum": 0.9},
                   fused=False, inf_at=2,
                   scaler=LossScaler(init_scale=2.0 ** 8, scale_window=3))
    wf, trf = _run("sgd", {"learning_rate": 0.05, "momentum": 0.9},
                   fused=True, inf_at=2,
                   scaler=LossScaler(init_scale=2.0 ** 8, scale_window=3))
    assert _bitwise(we, wf)
    # grow (window=3) and backoff both happened; property read syncs the
    # device-resident state back to the host
    assert trf._amp_loss_scaler.loss_scale == \
        tre._amp_loss_scaler.loss_scale
    assert fused_step.fused_step_stats()["skipped_steps"] == 1
    # skipped step did not advance the update count
    trf._sync_fused_state()
    assert trf._optimizer.num_update == tre._optimizer.num_update


def test_set_learning_rate_no_retrace():
    """lr enters the executable as a dynamic scalar: changing it
    mid-training takes effect on the very next step with the miss
    counter flat (regression test for the tentpole contract)."""
    os.environ["MXNET_FUSED_STEP"] = "1"
    params = _make_params()
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.05})
    _set_grads(params, 0)
    tr.step(1)
    misses = fused_step.fused_step_stats()["misses"]
    w_before = params[0].data().asnumpy().copy()
    tr.set_learning_rate(0.0)  # next step must be a no-op update
    _set_grads(params, 1)
    tr.step(1)
    st = fused_step.fused_step_stats()
    assert st["misses"] == misses  # no recompilation
    assert st["hits"] >= 1
    assert onp.array_equal(params[0].data().asnumpy(), w_before)
    tr.set_learning_rate(0.5)  # and takes effect immediately again
    _set_grads(params, 2)
    tr.step(1)
    assert fused_step.fused_step_stats()["misses"] == misses
    assert not onp.array_equal(params[0].data().asnumpy(), w_before)


def test_loss_scale_growth_no_retrace():
    """Scale grow/backoff moves entirely on device; no recompilation."""
    os.environ["MXNET_FUSED_STEP"] = "1"
    params = _make_params()
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.05})
    tr._amp_loss_scaler = LossScaler(init_scale=4.0, scale_window=2)
    _set_grads(params, 0)
    tr.step(1)
    misses = fused_step.fused_step_stats()["misses"]
    for s in range(1, 4):
        _set_grads(params, s)
        tr.step(1)
    assert tr._amp_loss_scaler.loss_scale == 16.0  # grew twice (window 2)
    assert fused_step.fused_step_stats()["misses"] == misses


def test_external_loss_scale_write_reseeds():
    os.environ["MXNET_FUSED_STEP"] = "1"
    params = _make_params()
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.05})
    tr._amp_loss_scaler = LossScaler(init_scale=2.0 ** 8)
    _set_grads(params, 0)
    tr.step(1)
    tr._amp_loss_scaler.loss_scale = 2.0  # external write
    _set_grads(params, 1)
    tr.step(1)
    assert tr._amp_loss_scaler.loss_scale == 2.0  # device re-seeded


def test_fused_cache_shared_across_trainers():
    os.environ["MXNET_FUSED_STEP"] = "1"
    params = _make_params()
    tr1 = gluon.Trainer(params, "sgd", {"learning_rate": 0.05})
    _set_grads(params, 0)
    tr1.step(1)
    misses = fused_step.fused_step_stats()["misses"]
    tr2 = gluon.Trainer(params, "sgd", {"learning_rate": 0.05})
    tr2.step(1)  # same signature -> same executable, no new compile
    st = fused_step.fused_step_stats()
    assert st["misses"] == misses
    assert st["size"] == 1


def test_env_fallback_matches_and_bypasses_cache():
    os.environ["MXNET_FUSED_STEP"] = "0"
    we, _ = _run("sgd", {"learning_rate": 0.05, "momentum": 0.9},
                 fused=False)
    st = fused_step.fused_step_stats()
    assert st["size"] == 0 and st["misses"] == 0


def test_unsupported_optimizer_bypasses_to_eager():
    os.environ["MXNET_FUSED_STEP"] = "1"
    params = _make_params()
    tr = gluon.Trainer(params, "adamax", {"learning_rate": 0.01})
    w0 = params[0].data().asnumpy().copy()
    _set_grads(params, 0)
    tr.step(1)
    st = fused_step.fused_step_stats()
    assert st["bypasses"] >= 1 and st["size"] == 0
    assert not onp.array_equal(params[0].data().asnumpy(), w0)


def test_multi_precision_fused_matches_eager():
    """bf16 params with fp32 masters: fused mp update == eager mp."""
    we, _ = _run("sgd", {"learning_rate": 0.05, "momentum": 0.9},
                 fused=False, multi_precision=True, dtype="bfloat16")
    wf, _ = _run("sgd", {"learning_rate": 0.05, "momentum": 0.9},
                 fused=True, multi_precision=True, dtype="bfloat16")
    assert _bitwise(we, wf)


def test_param_donation_opt_in():
    os.environ["MXNET_FUSED_STEP_DONATE"] = "1"
    os.environ["MXNET_FUSED_STEP"] = "1"
    params = _make_params()
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.05,
                                       "momentum": 0.9})
    for s in range(3):
        _set_grads(params, s)
        tr.step(1)
    # params stay readable through the rebinding despite donation
    assert onp.isfinite(params[0].data().asnumpy()).all()


def test_save_load_states_roundtrip_before_compile(tmp_path):
    """save/load before the fused step ever compiled (fresh trainer)."""
    os.environ["MXNET_FUSED_STEP"] = "1"
    params = _make_params()
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.05,
                                       "momentum": 0.9})
    tr._amp_loss_scaler = LossScaler(init_scale=2.0 ** 6)
    fname = str(tmp_path / "pre.states")
    tr.save_states(fname)
    tr2 = gluon.Trainer(params, "sgd", {"learning_rate": 0.05,
                                        "momentum": 0.9})
    tr2._amp_loss_scaler = LossScaler()
    tr2.load_states(fname)
    assert tr2._amp_loss_scaler.loss_scale == 2.0 ** 6
    _set_grads(params, 0)
    tr2.step(1)  # compiles cleanly from restored state
    assert onp.isfinite(params[0].data().asnumpy()).all()


def test_save_load_states_roundtrip_after_compile(tmp_path):
    """After fused steps (incl. a skip), the device-resident update
    count and scaler state are synced into the checkpoint; a fresh
    trainer continues bitwise-identically with the eager path."""
    os.environ["MXNET_FUSED_STEP"] = "1"
    params = _make_params()
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.05,
                                       "momentum": 0.9})
    tr._amp_loss_scaler = LossScaler(init_scale=2.0 ** 8, scale_window=3)
    for s in range(4):
        _set_grads(params, s, poison=(s == 1))
        tr.step(1)
    fname = str(tmp_path / "post.states")
    tr.save_states(fname)
    assert tr._optimizer.num_update == 3  # skipped step not counted
    tr2 = gluon.Trainer(params, "sgd", {"learning_rate": 0.05,
                                        "momentum": 0.9})
    tr2._amp_loss_scaler = LossScaler()
    tr2.load_states(fname)
    assert tr2._optimizer.num_update == 3
    assert tr2._amp_loss_scaler.loss_scale == 2.0 ** 7  # halved once
    # momentum buffers restored: one more identical step from tr / tr2
    # must produce identical weights
    s1 = {k: (v[0].asnumpy() if isinstance(v, tuple) else v.asnumpy())
          for k, v in enumerate(tr._states) if v is not None}
    s2 = {k: (v[0].asnumpy() if isinstance(v, tuple) else v.asnumpy())
          for k, v in enumerate(tr2._states) if v is not None}
    for k in s1:
        assert onp.array_equal(s1[k], s2[k])


def test_eager_toggle_mid_training_syncs_state():
    """Flipping MXNET_FUSED_STEP off mid-run pulls the device state back
    so the eager path continues from the right scale/count."""
    os.environ["MXNET_FUSED_STEP"] = "1"
    params = _make_params()
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.05})
    tr._amp_loss_scaler = LossScaler(init_scale=8.0, scale_window=2)
    for s in range(2):
        _set_grads(params, s)
        tr.step(1)
    os.environ["MXNET_FUSED_STEP"] = "0"
    _set_grads(params, 2)
    tr.step(1)
    # grew once on device (window 2), then one clean eager step
    assert tr._amp_loss_scaler._unskipped == 1
    assert tr._amp_loss_scaler._loss_scale == 16.0


def test_runtime_feature_and_profiler_counters():
    os.environ["MXNET_FUSED_STEP"] = "1"
    feats = runtime.Features()
    assert feats.is_enabled("FUSED_STEP")
    os.environ["MXNET_FUSED_STEP"] = "0"
    assert not runtime.Features().is_enabled("FUSED_STEP")
    ctr = profiler.fused_step_counters()
    for k in ("hits", "misses", "evictions", "bypasses", "fallbacks",
              "size", "maxsize", "skipped_steps"):
        assert k in ctr


def test_coalesced_allreduce_one_collective_per_dtype():
    from mxnet_tpu import parallel

    rs = onp.random.RandomState(0)
    values = [nd.array(rs.randn(3, 4).astype("f")),
              nd.array(rs.randn(5).astype("f")),
              nd.array((rs.rand(6) * 10).astype("int32")),
              nd.array(rs.randn(7).astype("f"))]
    calls = []

    def counting_reduce(flat):
        calls.append(flat.shape)
        return flat * 2

    out = parallel.all_reduce_coalesced(values, reduce_fn=counting_reduce)
    assert len(calls) == 2  # one float32 bucket + one int32 bucket
    for v, o in zip(values, out):
        assert o.shape == v.shape
        assert onp.array_equal(o.asnumpy(), v.asnumpy() * 2)


def test_coalesced_allreduce_single_process_identity():
    vals = [nd.ones((2, 2)), nd.ones((3,))]
    out = __import__("mxnet_tpu").parallel.all_reduce_coalesced(vals)
    assert out[0] is vals[0] and out[1] is vals[1]


def test_distributed_trainer_allreduce_noop_single_process():
    os.environ["MXNET_FUSED_STEP"] = "1"
    params = _make_params()
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.05},
                       kvstore="dist_sync")
    assert tr._distributed
    _set_grads(params, 0)
    g0 = params[0].grad().asnumpy().copy()
    tr.allreduce_grads()
    assert onp.array_equal(params[0].grad().asnumpy(), g0)
    tr.step(1)  # fused path with the distributed flag in the cache key
    assert onp.isfinite(params[0].data().asnumpy()).all()


def test_fused_in_training_loop_end_to_end():
    """Whole net forward/backward/step loop converges under the fused
    path and matches the eager loop bitwise."""
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon import nn

    def train(fused):
        os.environ["MXNET_FUSED_STEP"] = "1" if fused else "0"
        mx.random.seed(7)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dense(2))
        net.initialize(mx.init.Xavier())
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9})
        lf = gluon.loss.SoftmaxCrossEntropyLoss()
        rs = onp.random.RandomState(0)
        X = rs.randn(32, 8).astype("f")
        y = (X.sum(1) > 0).astype("f")
        for _ in range(10):
            with autograd.record():
                loss = lf(net(nd.array(X)), nd.array(y)).mean()
            loss.backward()
            tr.step(1)
        return [p.data().asnumpy()
                for p in net.collect_params().values()], float(
                    loss.asscalar())

    we, le = train(False)
    wf, lw = train(True)
    assert _bitwise(we, wf)
    assert le == lw
