"""Higher-order autograd (reference: tests/python/unittest/
test_higher_order_grad.py) and DLPack interop (test_dlpack in
test_ndarray.py) — the torch-CPU bridge is the external consumer.
"""
import math

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.test_utils import assert_almost_equal


def _np(x):
    return onp.asarray(x.asnumpy())


def test_second_order_polynomial():
    x = nd.array(onp.array([2.0, -1.0, 0.5], "f"))
    x.attach_grad()
    with autograd.record():
        y = x * x * x
        g = autograd.grad(y, x, create_graph=True)  # 3x^2
        g.backward(nd.ones_like(g))
    assert_almost_equal(_np(x.grad), 6 * _np(x), rtol=1e-5, atol=1e-6)


def test_third_order_via_nested_grad():
    x = nd.array(onp.array([1.5], "f"))
    x.attach_grad()
    with autograd.record():
        y = x * x * x * x
        g1 = autograd.grad(y, x, create_graph=True)   # 4x^3
        g2 = autograd.grad(g1, x, create_graph=True)  # 12x^2
        g2.backward()
    assert_almost_equal(_np(x.grad), [24 * 1.5], rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("op,d2", [
    ("sigmoid", lambda v: (lambda s: s * (1 - s) * (1 - 2 * s))(
        1 / (1 + math.exp(-v)))),
    ("tanh", lambda v: -2 * math.tanh(v) * (1 - math.tanh(v) ** 2)),
    ("log", lambda v: -1.0 / v ** 2),
    ("exp", lambda v: math.exp(v)),
])
def test_second_order_unary_ops(op, d2):
    # reference test_higher_order_grad runs exactly this family
    v = 0.7
    x = nd.array(onp.array([v], "f"))
    x.attach_grad()
    with autograd.record():
        y = getattr(nd, op)(x)
        g = autograd.grad(y, x, create_graph=True)
        g.backward()
    assert_almost_equal(_np(x.grad), [d2(v)], rtol=1e-4, atol=1e-5)


def test_second_order_through_matmul_loss():
    # hessian-vector-product style: d/dw of ||dL/dw||^2
    rng = onp.random.RandomState(0)
    w = nd.array(rng.rand(3, 3).astype("f"))
    x = nd.array(rng.rand(4, 3).astype("f"))
    w.attach_grad()
    with autograd.record():
        loss = nd.sum(nd.dot(x, w) ** 2)
        g = autograd.grad(loss, w, create_graph=True)
        gnorm = nd.sum(g * g)
        gnorm.backward()
    # analytic: L = ||Xw||^2, g = 2 X^T X w, d||g||^2/dw = 8 (X^T X)^2 w
    A = _np(x).T @ _np(x)
    want = 8 * A @ A @ _np(w)
    assert_almost_equal(_np(w.grad), want, rtol=1e-3, atol=1e-4)


def test_second_order_through_hybridized_block():
    # cached-op tape nodes carry their primal: Hessian-vector products
    # work through net.hybridize() (reference higher_order through
    # CachedOp)
    from mxnet_tpu import gluon

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(4, activation="tanh"), gluon.nn.Dense(1))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    x = nd.array(onp.random.RandomState(0).rand(3, 2).astype("f"))
    with autograd.record():
        y = net(x)  # build cache
    dense0_w = net[0].weight
    dense0_w_nd = dense0_w._ndarray
    dense0_w_nd.attach_grad()
    with autograd.record():
        y = net(x)
        loss = nd.sum(y * y)
        g = autograd.grad(loss, dense0_w_nd, create_graph=True)
        gn = nd.sum(g * g)
        gn.backward()
    hvp = _np(dense0_w_nd.grad)
    assert onp.isfinite(hvp).all() and (hvp != 0).any()
    # finite-difference check of d||g||^2/dw along one coordinate
    eps = 1e-3
    wv = _np(dense0_w_nd)

    def gnorm_at(delta):
        dense0_w.set_data(nd.array(wv + delta))
        xx = nd.array(_np(x))
        with autograd.record():
            yy = net(xx)
            ll = nd.sum(yy * yy)
            gg = autograd.grad(ll, dense0_w._ndarray, create_graph=True)
        return float(nd.sum(gg * gg).asscalar())

    d = onp.zeros_like(wv)
    d[0, 0] = eps
    fd = (gnorm_at(d) - gnorm_at(-d)) / (2 * eps)
    dense0_w.set_data(nd.array(wv))
    assert abs(hvp[0, 0] - fd) < 0.05 * max(1.0, abs(fd)), (hvp[0, 0], fd)


def test_second_order_through_hybridized_batchnorm():
    # BN running-stat write-back rebinds aux buffers after recording;
    # the create_graph walk must still differentiate through the WEIGHTS
    # (stale stats replay as record-time constants), with no truncation
    import warnings

    from mxnet_tpu import gluon

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(4), gluon.nn.BatchNorm(axis=-1),
            gluon.nn.Dense(1))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    x = nd.array(onp.random.RandomState(1).rand(6, 3).astype("f"))
    with autograd.record():
        net(x)  # build cache
    w_nd = net[0].weight._ndarray
    w_nd.attach_grad()
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # any truncation warning fails
        with autograd.record():
            y = net(x)
            loss = nd.sum(y * y)
            g = autograd.grad(loss, w_nd, create_graph=True)
            gn = nd.sum(g * g)
            gn.backward()
    hvp = _np(w_nd.grad)
    assert onp.isfinite(hvp).all() and (hvp != 0).any()


def test_create_graph_outside_record_scope_keeps_tape():
    x = nd.array(onp.array([3.0], "f"))
    x.attach_grad()
    with autograd.record():
        y = x * x
    # grad AFTER the scope closed: the retained tape must survive
    g = autograd.grad(y, x, create_graph=True)
    assert_almost_equal(_np(g), [6.0], rtol=1e-6, atol=1e-7)
    g.backward()
    assert_almost_equal(_np(x.grad), [2.0], rtol=1e-6, atol=1e-7)


def test_create_graph_retain_graph_false_clears_tape():
    from mxnet_tpu.autograd import _STATE

    x = nd.array(onp.array([2.0], "f"))
    with autograd.record():
        y = x * x
    g = autograd.grad(y, x, create_graph=True, retain_graph=False)
    assert_almost_equal(_np(g), [4.0], rtol=1e-6, atol=1e-7)
    assert _STATE.tape == []  # explicit release honored


def test_create_graph_warns_on_custom_function():
    class Square(autograd.Function):
        def forward(self, x):
            self.save_for_backward(x)
            return x * x

        def backward(self, dy):
            (x,) = self.saved_tensors
            return 2 * x * dy

    f = Square()
    x = nd.array(onp.array([3.0], "f"))
    x.attach_grad()
    with autograd.record():
        y = f(x)
        with pytest.warns(UserWarning, match="truncated"):
            g = autograd.grad(y, x, create_graph=True)
    # first order still exact through the opaque backward
    assert_almost_equal(_np(g), [6.0], rtol=1e-6, atol=1e-7)


def test_grad_without_create_graph_unchanged():
    x = nd.array(onp.array([3.0], "f"))
    with autograd.record():
        y = x * x
        g = autograd.grad(y, x)
    assert_almost_equal(_np(g), [6.0], rtol=1e-6, atol=1e-7)


# ------------------------------------------------------------------ dlpack

def test_dlpack_to_torch_and_back():
    torch = pytest.importorskip("torch")

    a = nd.array(onp.arange(6, dtype="f").reshape(2, 3))
    cap = nd.to_dlpack_for_read(a)
    t = torch.utils.dlpack.from_dlpack(cap)
    assert tuple(t.shape) == (2, 3)
    onp.testing.assert_allclose(t.numpy(), _np(a))
    # torch -> mx via the protocol object
    tt = torch.arange(4, dtype=torch.float32).reshape(2, 2) + 1
    b = nd.from_dlpack(tt)
    assert isinstance(b, nd.NDArray)
    onp.testing.assert_allclose(_np(b), tt.numpy())
    # torch -> capsule -> mx (reference API shape)
    cap2 = torch.utils.dlpack.to_dlpack(
        torch.full((3,), 7.0))
    c = nd.from_dlpack(cap2)
    onp.testing.assert_allclose(_np(c), [7.0] * 3)


def test_dlpack_write_capsule_is_isolated():
    torch = pytest.importorskip("torch")

    a = nd.array(onp.ones((2, 2), "f"))
    t = torch.utils.dlpack.from_dlpack(nd.to_dlpack_for_write(a))
    t.zero_()  # consumer writes land in the COPY, not the XLA buffer
    onp.testing.assert_allclose(_np(a), onp.ones((2, 2)))
    assert float(t.sum()) == 0.0


def test_from_numpy_locks_shared_source():
    src = onp.arange(8, dtype="f").reshape(2, 4)
    b = nd.from_numpy(src)
    onp.testing.assert_allclose(_np(b), onp.arange(8).reshape(2, 4))
    if not src.flags.writeable:
        # zero-copy path taken: mutation of the source must be refused
        with pytest.raises(ValueError):
            src[0, 0] = 99.0
    c = nd.from_numpy(onp.ones(3, "f"), zero_copy=False)
    onp.testing.assert_allclose(_np(c), [1, 1, 1])
    # results feed straight into ops
    assert nd.sum(b).asscalar() == 28.0
