"""mx.np semantics, second suite (reference:
tests/python/unittest/test_numpy_op.py, 71 fns — the de-facto spec for
the numpy-compatible namespace: dispatch, dtype promotion, shape
semantics, ufuncs, manipulation, linalg, random)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal, with_seed

np = mx.np
RS = onp.random.RandomState(7)


def _a(*shape):
    return RS.randn(*shape).astype("f")


def test_array_creation_matches_numpy():
    for src in ([1, 2, 3], [[1.5, 2.5]], 3.0):
        assert_almost_equal(np.array(src), onp.array(src, dtype="f"))
    assert np.zeros((2, 3)).shape == (2, 3)
    assert (np.ones(4).asnumpy() == 1).all()
    assert_almost_equal(np.full((2,), 9.0), onp.full(2, 9.0, "f"))


def test_arange_linspace_eye():
    assert_almost_equal(np.arange(2, 10, 3), onp.arange(2, 10, 3, "f"))
    assert_almost_equal(np.linspace(0, 1, 5), onp.linspace(0, 1, 5),
                        rtol=1e-6)
    assert_almost_equal(np.eye(3), onp.eye(3))


def test_ufunc_binary_broadcast():
    a, b = _a(3, 1), _a(1, 4)
    assert_almost_equal(np.add(np.array(a), np.array(b)), a + b)
    assert_almost_equal(np.multiply(np.array(a), np.array(b)), a * b)
    assert_almost_equal(np.subtract(np.array(a), np.array(b)), a - b)


def test_power_mod_floor_divide():
    a = onp.abs(_a(5)) + 0.5
    b = onp.abs(_a(5)) + 0.5
    assert_almost_equal(np.power(np.array(a), np.array(b)), a ** b,
                        rtol=1e-5)
    assert_almost_equal(np.mod(np.array(a), np.array(b)),
                        onp.mod(a, b), rtol=1e-5)
    assert_almost_equal(np.floor_divide(np.array(a), np.array(b)),
                        onp.floor_divide(a, b))


def test_trig_family():
    x = _a(6)
    for name in ("sin", "cos", "tan", "arctan", "sinh", "cosh"):
        assert_almost_equal(getattr(np, name)(np.array(x)),
                            getattr(onp, name)(x), rtol=1e-5, atol=1e-6)
    y = onp.clip(x, -0.99, 0.99)
    assert_almost_equal(np.arcsin(np.array(y)), onp.arcsin(y), rtol=1e-5)


def test_reductions_axis_keepdims():
    x = _a(3, 4, 5)
    a = np.array(x)
    assert_almost_equal(np.sum(a, axis=(0, 2)), x.sum(axis=(0, 2)),
                        rtol=1e-5)
    assert_almost_equal(np.mean(a, axis=1, keepdims=True),
                        x.mean(axis=1, keepdims=True), rtol=1e-5)
    assert_almost_equal(np.var(a, axis=0), x.var(axis=0), rtol=1e-4,
                        atol=1e-5)
    assert_almost_equal(np.std(a), x.std(), rtol=1e-4)
    assert float(np.max(a)) == x.max()
    assert int(np.argmin(a.reshape(-1))) == int(x.argmin())


def test_manipulation_suite():
    x = _a(2, 3, 4)
    a = np.array(x)
    assert_almost_equal(np.transpose(a, (2, 0, 1)),
                        x.transpose(2, 0, 1))
    assert_almost_equal(np.swapaxes(a, 0, 2), x.swapaxes(0, 2))
    assert_almost_equal(np.moveaxis(a, 0, -1), onp.moveaxis(x, 0, -1))
    assert np.ravel(a).shape == (24,)
    assert_almost_equal(np.stack([a, a], axis=1).asnumpy()[:, 0], x)
    got = np.concatenate([a, a], axis=2)
    assert got.shape == (2, 3, 8)


def test_split_array_functions():
    x = _a(6, 4)
    parts = np.split(np.array(x), 3, axis=0)
    assert len(parts) == 3 and parts[1].shape == (2, 4)
    v = np.vsplit(np.array(x), 2)
    assert v[0].shape == (3, 4)
    h = np.hsplit(np.array(x), 2)
    assert h[0].shape == (6, 2)


def test_where_and_comparisons_bool_dtype():
    a, b = _a(5), _a(5)
    cond = np.array(a) > np.array(b)
    assert "bool" in str(cond.dtype)
    got = np.where(cond, np.array(a), np.array(b))
    assert_almost_equal(got, onp.where(a > b, a, b))


def test_dtype_promotion_f32_wins():
    a = np.array([1, 2], dtype="int32")
    b = np.array([0.5, 0.5], dtype="float32")
    assert "float" in str((a + b).dtype)


def test_linalg_namespace():
    x = _a(4, 4)
    spd = x @ x.T + 4 * onp.eye(4, dtype="f")
    assert_almost_equal(np.linalg.norm(np.array(x)),
                        onp.linalg.norm(x), rtol=1e-5)
    L = np.linalg.cholesky(np.array(spd)).asnumpy()
    assert_almost_equal(L @ L.T, spd, rtol=1e-4, atol=1e-4)
    inv = np.linalg.inv(np.array(spd)).asnumpy()
    assert_almost_equal(inv @ spd, onp.eye(4), rtol=1e-3, atol=1e-3)
    sign, logdet = onp.linalg.slogdet(spd.astype("float64"))
    got = np.linalg.slogdet(np.array(spd))
    assert_almost_equal(float(got[1].asnumpy()
                              if hasattr(got[1], "asnumpy") else got[1]),
                        logdet, rtol=1e-4)


@with_seed(3)
def test_random_namespace_statistics():
    u = np.random.uniform(0, 1, size=(20000,)).asnumpy()
    assert 0.47 < u.mean() < 0.53
    n = np.random.normal(2.0, 0.5, size=(20000,)).asnumpy()
    assert 1.95 < n.mean() < 2.05 and 0.45 < n.std() < 0.55
    r = np.random.randint(0, 5, size=(1000,)).asnumpy()
    assert set(onp.unique(r)) <= {0, 1, 2, 3, 4}


def test_boolean_mask_indexing():
    x = _a(6)
    a = np.array(x)
    m = a > 0
    got = a[m].asnumpy()
    assert_almost_equal(got, x[x > 0])


def test_np_ndarray_methods():
    x = _a(3, 4)
    a = np.array(x)
    assert_almost_equal(a.T, x.T)
    assert_almost_equal(a.flatten(), x.flatten())
    assert a.astype("int32").dtype == onp.int32
    assert_almost_equal(a.clip(-0.2, 0.2), x.clip(-0.2, 0.2))
    assert abs(float(a.mean()) - x.mean()) < 1e-5


def test_interop_with_nd():
    from mxnet_tpu import nd
    from mxnet_tpu.numpy import ndarray as np_ndarray_cls

    a = nd.array(_a(2, 2))
    b = a.as_np_ndarray()
    assert isinstance(b, np_ndarray_cls)      # a REAL np ndarray
    assert not type(b) is type(a)             # not the legacy nd type
    c = b.as_nd_ndarray()
    assert isinstance(c, nd.NDArray)
    assert_almost_equal(c, a.asnumpy())
    # gradients flow across the view boundary
    a2 = nd.array(_a(3))
    a2.attach_grad()
    with mx.autograd.record():
        loss = np.sum(a2.as_np_ndarray() * 2.0)
    loss.backward()
    assert_almost_equal(a2.grad, onp.full(3, 2.0))


def test_np_tile_repeat_roll():
    x = _a(2, 3)
    a = np.array(x)
    assert_almost_equal(np.tile(a, (2, 1)), onp.tile(x, (2, 1)))
    assert_almost_equal(np.repeat(a, 2, axis=1), onp.repeat(x, 2, 1))
    assert_almost_equal(np.roll(a, 1, axis=0), onp.roll(x, 1, 0))


def test_np_sort_argsort_unique():
    x = onp.array([3.0, 1.0, 2.0, 1.0], "f")
    assert_almost_equal(np.sort(np.array(x)), onp.sort(x))
    got = np.unique(np.array(x))
    assert_almost_equal(got, onp.unique(x))


def test_np_einsum_paths():
    a, b = _a(3, 4), _a(4, 5)
    assert_almost_equal(np.einsum("ij,jk->ik", np.array(a), np.array(b)),
                        a @ b, rtol=1e-5)
    c = _a(3, 4)
    assert_almost_equal(np.einsum("ij,ij->", np.array(a), np.array(c)),
                        (a * c).sum(), rtol=1e-4)


def test_np_outer_inner_dotfamily():
    a, b = _a(4), _a(4)
    assert_almost_equal(np.outer(np.array(a), np.array(b)),
                        onp.outer(a, b), rtol=1e-5)
    assert_almost_equal(np.dot(np.array(a), np.array(b)),
                        onp.dot(a, b), rtol=1e-5)


def test_np_pad_and_flip():
    x = _a(2, 3)
    assert_almost_equal(np.pad(np.array(x), ((1, 1), (0, 0))),
                        onp.pad(x, ((1, 1), (0, 0))))
    assert_almost_equal(np.flip(np.array(x), axis=1), x[:, ::-1])


def test_np_gradient_through_ops():
    from mxnet_tpu import autograd

    a = np.array(_a(3))
    a.attach_grad()
    with autograd.record():
        y = np.sum(np.exp(a) * a)
    y.backward()
    want = onp.exp(a.asnumpy()) * (1 + a.asnumpy())
    assert_almost_equal(a.grad, want, rtol=1e-5)


def test_np_float_index_raises_unlike_nd():
    """numpy semantics: float indexers raise; the legacy nd namespace
    coerces them (reference behavior split)."""
    a = np.array(_a(4))
    with pytest.raises(IndexError, match="integer or boolean"):
        a[np.array([0.5, 1.0])]
    with pytest.raises(IndexError, match="integer or boolean"):
        a[np.array([0.0])] = 1.0
    with pytest.raises(IndexError):
        a[1.5]
    with pytest.raises(IndexError):
        a[[0.5, 1.0]]
    # integer indexers fine
    assert a[np.array([1], dtype="int32")].shape == (1,)
