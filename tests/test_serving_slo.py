"""SLO-aware serving: priority queues, deadline enforcement at every
queue exit, and admission control (tier-1, no sockets).

Covers: _ClassQueues priority ordering + per-class bounds + sentinel
semantics, RollingHistogram window recovery, AdmissionController
graduated shed thresholds (queue and latency signals), the
``serving_admission`` fault seam (forces the shed path, never for
critical), ShedLoad's Retry-After surface, and per-class
counter/latency observability."""
import queue
import threading
import time

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, serving
from mxnet_tpu.gluon import nn
from mxnet_tpu.resilience import faults
from mxnet_tpu.serving import admission as adm
from mxnet_tpu.serving import batcher as bat_mod
from mxnet_tpu.serving import metrics as met

nd = mx.nd


def _mlp(seed=0):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    with autograd.pause(train_mode=False):
        net(nd.zeros((1, 8)))
    return net


def _session(net=None, **kw):
    return serving.InferenceSession(net or _mlp(),
                                    input_shapes=[(1, 8)],
                                    buckets=[1, 2, 4], **kw)


def _ref(net, x):
    with autograd.pause(train_mode=False):
        return net(nd.array(x)).asnumpy()


@pytest.fixture(autouse=True)
def _fresh_counters():
    serving.reset_serving_counters()
    yield
    serving.reset_serving_counters()


def _req(cls, deadline=None):
    return bat_mod._Request([onp.zeros((1, 8), "float32")], 1,
                            deadline, cls)


# ---------------------------------------------------------------------------
# _ClassQueues

def test_class_queue_pops_highest_priority_first():
    q = bat_mod._ClassQueues(4)
    q.put_nowait(_req("best_effort"))
    q.put_nowait(_req("standard"))
    q.put_nowait(_req("critical"))
    q.put_nowait(_req("best_effort"))
    order = [q.get_nowait().slo_class for _ in range(4)]
    assert order == ["critical", "standard", "best_effort",
                     "best_effort"]
    with pytest.raises(queue.Empty):
        q.get_nowait()


def test_class_queue_bounds_are_per_class():
    q = bat_mod._ClassQueues(2)
    assert q.maxsize == 2
    assert q.capacity() == 2 * len(met.SLO_CLASSES)
    q.put_nowait(_req("best_effort"))
    q.put_nowait(_req("best_effort"))
    with pytest.raises(queue.Full):
        q.put_nowait(_req("best_effort"))
    # a full best_effort lane does not block the protected class
    q.put_nowait(_req("critical"))
    assert q.qsize() == 3
    assert q.qsize_by_class() == {"critical": 1, "standard": 0,
                                  "best_effort": 2}


def test_class_queue_sentinel_waits_for_data_lanes():
    """Control-lane sentinels (close()) are delivered only once every
    data lane is empty — accepted work always drains first."""
    q = bat_mod._ClassQueues(4)
    q.put_nowait(_req("best_effort"))
    q.put(bat_mod._STOP)  # control lane is unbounded, never Full
    assert q.get_nowait().slo_class == "best_effort"
    assert q.get_nowait() is bat_mod._STOP


# ---------------------------------------------------------------------------
# RollingHistogram

def test_rolling_histogram_forgets_an_aged_spike():
    h = met.RollingHistogram(window_s=20.0)
    t = 1000.0
    for _ in range(100):
        h.observe(0.9, now=t)  # the overload spike
    assert h.quantile(0.99, now=t) > 0.5
    # spike ages out: two frame rotations later only fresh traffic
    # remains — a cumulative histogram would report ~0.9 forever
    t += 25.0
    for _ in range(100):
        h.observe(0.002, now=t)
    assert h.quantile(0.99, now=t) < 0.01


def test_rolling_histogram_merges_adjacent_frames():
    h = met.RollingHistogram(window_s=20.0)
    t = 50.0
    h.observe(0.9, now=t)
    # one rotation (< a full frame late): previous frame still counts
    t += 11.0
    h.observe(0.001, now=t)
    assert h.total == 2
    assert h.quantile(0.99, now=t) > 0.5


# ---------------------------------------------------------------------------
# admission control

def test_normalize_class():
    assert adm.normalize_class(None) == "standard"
    assert adm.normalize_class("critical") == "critical"
    with pytest.raises(ValueError, match="unknown SLO class"):
        adm.normalize_class("vip")


class _FakeBatcher:
    def __init__(self, depth=0, capacity=100):
        self._depth, self._cap = depth, capacity

    def qsize(self):
        return self._depth

    def queue_capacity(self):
        return self._cap


def test_admission_graduated_shed_thresholds():
    """Queue signal: best_effort sheds at the full knob, standard at
    half, critical never — and ShedLoad is a ServerBusy carrying
    Retry-After."""
    fake = _FakeBatcher(depth=95, capacity=100)  # headroom 0.05
    ctl = adm.AdmissionController(fake, slo_ms=100.0,
                                  shed_headroom=0.15,
                                  retry_after_ms=400.0, enabled=True)
    try:
        ctl.check("critical")  # protected: backpressure only
        with pytest.raises(serving.ShedLoad) as ei:
            ctl.check("best_effort")
        assert isinstance(ei.value, serving.ServerBusy)
        assert ei.value.retry_after_s == pytest.approx(0.4)
        with pytest.raises(serving.ShedLoad):
            ctl.check("standard")  # 0.05 < 0.075 too
        # half-full: only best_effort is at risk
        fake._depth = 90  # headroom 0.10: best_effort sheds
        with pytest.raises(serving.ShedLoad):
            ctl.check("best_effort")
        ctl.check("standard")
        snap = ctl.snapshot()
        assert snap["enabled"] and snap["shedding"] == ["best_effort"]
        assert snap["queue_headroom"] == pytest.approx(0.10)
        assert set(snap["p99_ms"]) == set(met.SLO_CLASSES)
    finally:
        ctl.close()


def test_admission_latency_signal_protects_top_class():
    """Latency signal: the rolling p99 of the highest-priority class
    WITH TRAFFIC drives headroom — a blown critical p99 sheds
    best_effort even with empty queues."""
    for _ in range(50):
        met.METRICS.observe_request(0.098, slo_class="critical")
    ctl = adm.AdmissionController(_FakeBatcher(), slo_ms=100.0,
                                  shed_headroom=0.15, enabled=True)
    try:
        assert ctl.headroom() < 0.15
        with pytest.raises(serving.ShedLoad):
            ctl.check("best_effort")
        ctl.check("critical")
        assert met.METRICS.slo_headroom() == ctl.headroom()
    finally:
        ctl.close()


def test_admission_fault_forces_shed_but_never_critical():
    """The serving_admission seam: an armed plan forces the shed path
    for sheddable classes; the protected class never force-sheds."""
    sess = _session()
    bat = serving.DynamicBatcher(sess, max_batch_size=4,
                                 max_latency_ms=1.0)
    x = onp.random.RandomState(0).rand(1, 8).astype("float32")
    try:
        with faults.inject("serving_admission", every=1):
            with pytest.raises(serving.ShedLoad, match="fault-injected"):
                bat.submit(x, slo_class="best_effort")
            with pytest.raises(serving.ShedLoad):
                bat.submit(x, slo_class="standard")
            out = bat.submit(x, slo_class="critical").result(timeout=30)
        assert out.shape == (1, 4)
        stats = serving.serving_stats()
        assert stats["shed"] == 2
        assert stats["shed:best_effort"] == 1
        assert stats["shed:standard"] == 1
        assert stats["shed_rate"] == pytest.approx(2 / 3, abs=1e-3)
    finally:
        bat.close()


def test_admission_disabled_is_plain_backpressure():
    """admission=False: no shed even with the fault armed — the
    round-10 FIFO-with-backpressure behavior."""
    bat = serving.DynamicBatcher(_session(), max_batch_size=4,
                                 max_latency_ms=1.0, admission=False)
    x = onp.random.RandomState(1).rand(1, 8).astype("float32")
    try:
        with faults.inject("serving_admission", every=1):
            out = bat.submit(x, slo_class="best_effort").result(
                timeout=30)
        assert out.shape == (1, 4)
        assert serving.serving_stats()["shed"] == 0
    finally:
        bat.close()


# ---------------------------------------------------------------------------
# deadlines at the queue exits

class _GatedSession:
    """Real session whose predict blocks on a gate — pins the worker
    so queued requests age deterministically."""

    def __init__(self, inner):
        self._inner = inner
        self.gate = threading.Event()
        self.gate.set()
        self.exec_rows = []

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def predict(self, *arrs):
        self.gate.wait(30)
        self.exec_rows.append(sum(a.shape[0] for a in arrs[:1]))
        return self._inner.predict(*arrs)


def test_expired_request_never_occupies_a_batch_slot():
    """A request that out-waits its deadline in the queue gets
    RequestTimeout at the queue exit and is NEVER executed — the batch
    slot goes to live work."""
    net = _mlp()
    sess = _GatedSession(_session(net))
    bat = serving.DynamicBatcher(sess, max_batch_size=4,
                                 max_latency_ms=1.0)
    xs = [onp.random.RandomState(i).rand(1, 8).astype("float32")
          for i in range(3)]
    try:
        sess.gate.clear()
        fa = bat.submit(xs[0], timeout_ms=30_000, slo_class="critical")
        time.sleep(0.15)  # worker is now pinned inside predict(a)
        fb = bat.submit(xs[1], timeout_ms=40, slo_class="standard")
        fc = bat.submit(xs[2], timeout_ms=30_000,
                        slo_class="best_effort")
        time.sleep(0.15)  # b expires while queued behind the gate
        sess.gate.set()
        assert onp.array_equal(fa.result(timeout=30), _ref(net, xs[0]))
        with pytest.raises(serving.RequestTimeout, match="expired"):
            fb.result(timeout=30)
        assert onp.array_equal(fc.result(timeout=30), _ref(net, xs[2]))
    finally:
        bat.close()
    assert sess.exec_rows == [1, 1], \
        "the expired request must never reach the session"
    stats = serving.serving_stats()
    assert stats["timeouts"] == 1
    assert stats["timeouts:standard"] == 1
    assert stats["deadline_met"] == 2
    assert stats["failures:standard"] == 1
    assert stats["responses:critical"] == 1


def test_close_drain_honors_deadlines_per_class():
    """The close() drain path is also a queue exit: expired requests
    fail with RequestTimeout, live ones still execute."""
    net = _mlp()
    sess = _GatedSession(_session(net))
    bat = serving.DynamicBatcher(sess, max_batch_size=4,
                                 max_latency_ms=1.0)
    x = onp.random.RandomState(7).rand(1, 8).astype("float32")
    try:
        sess.gate.clear()
        fa = bat.submit(x, timeout_ms=30_000, slo_class="critical")
        time.sleep(0.15)
        fb = bat.submit(x, timeout_ms=40, slo_class="best_effort")
        fc = bat.submit(x, timeout_ms=30_000, slo_class="standard")
        time.sleep(0.15)
    finally:
        sess.gate.set()
        bat.close()  # drains every accepted request
    assert onp.array_equal(fa.result(timeout=1), _ref(net, x))
    with pytest.raises(serving.RequestTimeout):
        fb.result(timeout=1)
    assert onp.array_equal(fc.result(timeout=1), _ref(net, x))


# ---------------------------------------------------------------------------
# observability

def test_per_class_counters_and_snapshot_keys():
    bat = serving.DynamicBatcher(_session(), max_batch_size=4,
                                 max_latency_ms=1.0)
    x = onp.random.RandomState(3).rand(1, 8).astype("float32")
    try:
        bat.submit(x, slo_class="critical").result(timeout=30)
        bat.submit(x).result(timeout=30)  # defaults to standard
        stats = serving.serving_stats()
        assert stats["requests:critical"] == 1
        assert stats["requests:standard"] == 1
        assert stats["responses:critical"] == 1
        assert stats["latency_p99_ms:critical"] > 0
        assert stats["goodput_rps"] > 0
        assert stats["shed_rate"] == 0.0
        assert 0.0 <= stats["slo_headroom"] <= 1.0
        text = met.prometheus_text()
        assert 'mxnet_serving_class_requests_total{slo_class=' \
            '"critical"} 1' in text
        assert "mxnet_serving_slo_headroom" in text
        assert "mxnet_serving_class_latency_p99_seconds" in text
    finally:
        bat.close()


def test_bump_class_unknown_folds_to_standard():
    met.METRICS.bump_class("requests", "not-a-class")
    assert serving.serving_stats()["requests:standard"] == 1
