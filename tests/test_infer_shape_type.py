"""Symbol shape/type inference (reference:
tests/python/unittest/test_infer_shape.py + test_infer_type.py)."""
import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import sym


def test_mlp_infer_shape_fills_parameters():
    data = sym.Variable("data")
    fc1 = sym.FullyConnected(data, num_hidden=10, name="fc1")
    out = sym.FullyConnected(fc1, num_hidden=3, name="fc2")
    arg_shapes, out_shapes, aux_shapes = out.infer_shape(data=(4, 7))
    shapes = dict(zip(out.list_arguments(), arg_shapes))
    assert shapes["fc1_weight"] == (10, 7)
    assert shapes["fc1_bias"] == (10,)
    assert shapes["fc2_weight"] == (3, 10)
    assert out_shapes == [(4, 3)]
    assert aux_shapes == []


def test_conv_bn_infer_shape_with_aux():
    data = sym.Variable("data")
    c = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                        name="conv")
    b = sym.BatchNorm(c, name="bn")
    arg_shapes, out_shapes, aux_shapes = b.infer_shape(data=(2, 3, 8, 8))
    shapes = dict(zip(b.list_arguments(), arg_shapes))
    assert shapes["conv_weight"] == (8, 3, 3, 3)
    assert shapes["bn_gamma"] == (8,)
    assert out_shapes == [(2, 8, 8, 8)]
    aux = dict(zip(b.list_auxiliary_states(), aux_shapes))
    assert aux["bn_moving_mean"] == (8,)


def test_infer_shape_partial_tolerates_unknowns():
    a = sym.Variable("a")
    b = sym.Variable("b")
    out = sym.broadcast_add(a, b)
    arg_shapes, out_shapes, _ = out.infer_shape_partial(a=(2, 3))
    shapes = dict(zip(out.list_arguments(), arg_shapes))
    assert shapes["a"] == (2, 3)
    assert shapes.get("b") is None
    assert out_shapes == [None]


def test_infer_type_propagates_through_mlp():
    data = sym.Variable("data")
    out = sym.FullyConnected(data, num_hidden=4, name="fc")
    arg_types, out_types, aux_types = out.infer_type(data=onp.float16)
    types = dict(zip(out.list_arguments(), arg_types))
    # parameters take the data dtype (reference same-type constraint)
    assert types["fc_weight"] == onp.float16
    assert types["fc_bias"] == onp.float16
    assert out_types == [onp.float16]


def test_infer_type_cast_and_promotion():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = sym.cast(a, dtype="float16")
    out = sym.broadcast_add(c, b)
    arg_types, out_types, _ = out.infer_type(a=onp.float32, b=onp.float16)
    assert out_types == [onp.float16]  # f16 + f16
    mixed = sym.broadcast_add(sym.cast(a, dtype="float16"), b)
    _, tm, _ = mixed.infer_type(a=onp.float16, b=onp.float32)
    assert tm == [onp.float32]  # f16 + f32 promotes to f32
    # runtime-truthful: with jax x64 off, cast-to-f64 executes as f32,
    # and inference reports the executed dtype
    _, t64, _ = sym.cast(a, dtype="float64").infer_type(a=onp.float32)
    assert t64 == [onp.float32]
    _, t16, _ = sym.cast(out, dtype="float16").infer_type(
        a=onp.float32, b=onp.float16)
    assert t16 == [onp.float16]


def test_infer_type_defaults_and_indices():
    data = sym.Variable("data")
    am = sym.argmax(data, axis=1)
    _, out_types, _ = am.infer_type(data=onp.float16)
    assert out_types == [onp.float32]  # reference: indices as fp32
    fc = sym.FullyConnected(data, num_hidden=2)
    arg_types, _, _ = fc.infer_type()
    assert all(t == onp.float32 for t in arg_types)  # default


def test_infer_type_embedding_and_quantize_outputs():
    data = sym.Variable("data")
    emb = sym.Embedding(data, input_dim=10, output_dim=4, name="emb")
    arg_types, out_types, _ = emb.infer_type(data=onp.int32)
    types = dict(zip(emb.list_arguments(), arg_types))
    # integer indices do NOT drag the weight to int: fp32 default
    assert types["emb_weight"] == onp.float32
    assert out_types == [onp.float32]
    # quantize family: one dtype per listed output, uint8 payload default
    q = sym.quantize(sym.Variable("x"), sym.Variable("mn"),
                     sym.Variable("mx"))
    _, qt, _ = q.infer_type(x=onp.float32, mn=onp.float32, mx=onp.float32)
    # one dtype per list_outputs entry, payload dtype first (uint8 is
    # the reference quantize default out_type)
    assert len(qt) == len(q.list_outputs())
    assert qt[0] == onp.uint8
