"""Profiler, monitor, visualization, runtime, test_utils, estimator.

Reference coverage model: tests/python/unittest/test_profiler.py,
test_metric.py + the estimator tests under tests/python/unittest/gluon/.
"""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon, profiler
from mxnet_tpu.gluon import nn


def test_profiler_scoped_objects(tmp_path):
    profiler.set_config(filename=str(tmp_path / "prof.json"),
                        aggregate_stats=True)
    profiler.start()
    dom = profiler.Domain("unit")
    with dom.new_task("work"):
        nd.waitall()
    ev = dom.new_event("ev")
    ev.start()
    ev.stop()
    c = dom.new_counter("ctr", 5)
    c += 3
    dom.new_marker("m").mark()
    profiler.stop()
    table = profiler.dumps()
    assert "work" in table and "ev" in table
    f = profiler.dump()
    assert os.path.isfile(f)
    import json

    evts = json.load(open(f))["traceEvents"]
    assert any(e["name"] == "work" for e in evts)
    js = profiler.dumps(format="json", reset=True)
    assert "work" in js
    assert profiler.dumps(format="json") == "[]"


def test_monitor_on_block():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(2))
    net.initialize()
    mon = mx.Monitor(interval=1, pattern=".*")
    mon.install(net)
    mon.tic()
    net(nd.ones((2, 4)))
    stats = mon.toc()
    assert len(stats) >= 2  # both Dense outputs tapped
    names = [s[1] for s in stats]
    assert any("dense" in n for n in names)
    mon.uninstall()
    mon.tic()
    net(nd.ones((2, 4)))
    assert mon.toc() == []


def test_visualization_print_summary(capsys):
    data = mx.sym.Variable("data")
    w = mx.sym.Variable("fc_weight")
    b = mx.sym.Variable("fc_bias")
    out = mx.sym.FullyConnected(data, w, b, num_hidden=10, name="fc")
    out = mx.sym.softmax(out, name="sm")
    total = mx.viz.print_summary(out, shape={"data": (1, 20)})
    printed = capsys.readouterr().out
    assert "fc" in printed and "Total params" in printed
    assert total == 20 * 10 + 10


def test_runtime_features():
    feats = mx.runtime.Features()
    assert feats.is_enabled("XLA")
    assert not feats.is_enabled("CUDA")
    assert isinstance(mx.runtime.feature_list(), list)


def test_test_utils_assert_and_grad():
    tu = mx.test_utils
    tu.assert_almost_equal(onp.ones(3), onp.ones(3))
    with pytest.raises(AssertionError):
        tu.assert_almost_equal(onp.ones(3), 2 * onp.ones(3))
    assert tu.almost_equal([1.0], [1.0 + 1e-7], rtol=1e-5)
    # numeric vs analytic gradient of a tanh·square chain
    tu.check_numeric_gradient(
        lambda x: nd.tanh(x) * nd.square(x),
        [onp.random.RandomState(0).randn(3, 2) * 0.5])
    tu.check_consistency(lambda x: nd.relu(x) + 1,
                         [onp.random.RandomState(1).randn(4)])
    arr = tu.rand_ndarray((6, 4), stype="csr", density=0.3)
    assert arr.stype == "csr"


def test_estimator_fit(tmp_path):
    from mxnet_tpu.gluon.contrib.estimator import (Estimator,
                                                   CheckpointHandler,
                                                   EarlyStoppingHandler)
    from mxnet_tpu.metric import Accuracy

    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(2))
    net.initialize(mx.init.Xavier())
    rs = onp.random.RandomState(0)
    X = rs.randn(64, 8).astype("f")
    y = (X.sum(1) > 0).astype("f")
    train = mx.io.NDArrayIter(X, y, batch_size=16, shuffle=True)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 5e-3})
    est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                    metrics=[Accuracy()], trainer=trainer)
    ckpt = CheckpointHandler(str(tmp_path), monitor=est.train_metrics[0],
                             save_best=True)
    est.fit(train, epochs=4, event_handlers=[ckpt])
    acc = est.train_metrics[0].get()[1]
    assert acc > 0.8, acc
    assert any(f.endswith(".params") for f in os.listdir(tmp_path))


def test_estimator_early_stopping():
    from mxnet_tpu.gluon.contrib.estimator import (Estimator,
                                                   EarlyStoppingHandler)
    from mxnet_tpu.metric import Accuracy

    net = nn.Dense(2)
    net.initialize()
    X = onp.zeros((32, 4), "f")
    y = onp.zeros(32, "f")
    train = mx.io.NDArrayIter(X, y, batch_size=8)
    est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                    metrics=[Accuracy()])
    stop = EarlyStoppingHandler(est.train_metrics[0], patience=1)
    est.fit(train, epochs=50, event_handlers=[stop])
    # constant data → accuracy flat → early stop long before 50 epochs
    assert stop.stop_training


def test_fork_reinitializes_engine():
    """A forked child must not inherit dead engine worker threads
    (reference: initialize.cc atfork handlers)."""
    import os

    import mxnet_tpu  # noqa: F401 — installs the fork handler
    from mxnet_tpu import engine as eng

    e = eng.get()
    v = e.new_variable()
    done = []
    e.push(lambda: done.append(1), mutable_vars=(v,))
    e.wait_for_var(v)
    if not hasattr(os, "fork"):
        pytest.skip("no fork")
    pid = os.fork()
    if pid == 0:  # child: the singleton must have been reset + rebuilt
        rc = 1
        try:
            ce = eng.get()
            assert ce is not e or isinstance(ce, eng.NaiveEngine)
            cv = ce.new_variable()
            got = []
            ce.push(lambda: got.append(1), mutable_vars=(cv,))
            ce.wait_for_var(cv)
            rc = 0 if got == [1] else 2
        finally:
            os._exit(rc)
    _, status = os.waitpid(pid, 0)
    assert os.waitstatus_to_exitcode(status) == 0


def test_signal_handler_knob_installed():
    import faulthandler

    import mxnet_tpu  # noqa: F401

    assert faulthandler.is_enabled()
