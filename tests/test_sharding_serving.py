"""Tensor-parallel serving from one sharded AOT executable.

``InferenceSession.shard_params`` re-places the parameter snapshot per
plan and salts the AOT fingerprint so sharded and unsharded
executables never collide in the compile cache.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, parallel, serving, sharding
from mxnet_tpu.base import MXNetError
from mxnet_tpu.sharding import ShardingPlan

DIM, OUT, BATCH = 16, 8, 4


def _session(seed=21, buckets=(BATCH,)):
    from mxnet_tpu.gluon import nn

    mx.random.seed(seed)
    net = nn.HybridSequential(prefix="net_")
    net.add(nn.Dense(32, activation="relu", prefix="d0_"))
    net.add(nn.Dense(OUT, prefix="d1_"))
    net.initialize()
    net(nd.zeros((1, DIM)))
    return net, serving.InferenceSession(
        net, example=nd.zeros((1, DIM)), buckets=list(buckets))


def _probes(n=3, seed=33):
    rs = onp.random.RandomState(seed)
    return [rs.rand(BATCH, DIM).astype("f") for _ in range(n)]


def _plan():
    # last-layer tensor parallelism: no cross-shard contraction feeds
    # a later layer, so outputs stay bitwise
    return ShardingPlan({r"d1_weight$": ("mp", None)})


def test_sharded_predict_bitwise():
    net, sess = _session()
    probes = _probes()
    base = [sess.predict(x).asnumpy() for x in probes]
    mesh = parallel.make_mesh({"mp": 4})
    assert not sess.sharded
    sess.shard_params(plan=_plan(), mesh=mesh)
    assert sess.sharded
    for x, ref in zip(probes, base):
        got = sess.predict(x).asnumpy()
        assert got.tobytes() == ref.tobytes()


def test_shard_params_uses_scope_and_counts():
    net, sess = _session(seed=23)
    mesh = parallel.make_mesh({"mp": 4})
    sharding.reset_sharding_counters()
    with sharding.plan_scope(_plan(), mesh):
        sess.shard_params()
    assert sess.sharded
    assert sharding.sharding_counters()["serving_sharded_sessions"] == 1


def test_shard_params_without_plan_raises():
    net, sess = _session(seed=25)
    with pytest.raises(MXNetError, match="needs a plan"):
        sess.shard_params()


def test_fingerprint_salted_by_plan():
    net, sess = _session(seed=27)
    x = _probes(1)[0]
    sess.predict(x)
    plain = sess._fingerprint(BATCH, 0)
    mesh = parallel.make_mesh({"mp": 4})
    sess.shard_params(plan=_plan(), mesh=mesh)
    assert sess._fingerprint(BATCH, 0) != plain
    # executables rebuilt under the new fingerprint still serve
    assert sess.predict(x).shape == (BATCH, OUT)


def test_refresh_params_keeps_layout():
    net, sess = _session(seed=29)
    x = _probes(1)[0]
    mesh = parallel.make_mesh({"mp": 4})
    sess.shard_params(plan=_plan(), mesh=mesh)
    before = sess.predict(x).asnumpy()
    # an in-place training-side write, then refresh: output changes,
    # session stays sharded, layouts re-placed
    w = net.collect_params()["d1_bias"]
    w.set_data(w.data() + 1.0)
    sess.refresh_params()
    assert sess.sharded
    after = sess.predict(x).asnumpy()
    assert not onp.allclose(before, after)
    onp.testing.assert_allclose(after, before + 1.0, rtol=0, atol=1e-6)
