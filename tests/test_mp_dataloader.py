"""Cross-process DataLoader workers (spawn + shared-memory transfer).

Reference coverage model: tests/python/unittest/test_gluon_data.py
test_multi_worker / test_multi_worker_shape.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

rs = onp.random.RandomState(0)


@pytest.mark.parametrize("num_workers", [2])
def test_process_workers_match_sync(num_workers):
    X = rs.rand(40, 5).astype("f")
    y = onp.arange(40, dtype="f")
    ds = ArrayDataset(X, y)
    sync = DataLoader(ds, batch_size=8, shuffle=False, num_workers=0)
    procs = DataLoader(ds, batch_size=8, shuffle=False,
                       num_workers=num_workers, thread_pool=False)
    got_sync = [(d.asnumpy(), l.asnumpy()) for d, l in sync]
    got_proc = [(d.asnumpy(), l.asnumpy()) for d, l in procs]
    assert len(got_sync) == len(got_proc) == 5
    for (ds_, ls_), (dp_, lp_) in zip(got_sync, got_proc):
        onp.testing.assert_allclose(dp_, ds_, rtol=1e-6)
        onp.testing.assert_allclose(lp_, ls_, rtol=1e-6)


def test_process_workers_multiple_epochs():
    X = rs.rand(16, 3).astype("f")
    ds = ArrayDataset(X, onp.arange(16, dtype="f"))
    dl = DataLoader(ds, batch_size=4, shuffle=False, num_workers=2,
                    thread_pool=False)
    for _ in range(2):  # pool survives epochs
        n = sum(1 for _ in dl)
        assert n == 4


def test_shm_codec_roundtrip():
    from mxnet_tpu.gluon.data import _mp_worker as w

    arr = rs.rand(4, 3).astype("f")
    desc = w._to_shm(arr)
    back = w._from_shm(desc)
    onp.testing.assert_array_equal(back, arr)
    nested = w._encode([arr, {"k": arr[0]}, 3])
    dec = w.decode(nested)
    onp.testing.assert_allclose(dec[0].asnumpy(), arr, rtol=1e-6)
    onp.testing.assert_allclose(dec[1]["k"].asnumpy(), arr[0],
                                rtol=1e-6)
    assert dec[2] == 3
