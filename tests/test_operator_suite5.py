"""Operator spec suite 5: edge-of-spec behaviors from the reference's
test_operator.py — duplicate-input gradients, dilated-conv impulse
response, deconv bias, zero-size tensors, fp16 extremes, large-input
softmax, monitor hooks.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.test_utils import assert_almost_equal, with_seed


def _np(x):
    return onp.asarray(x.asnumpy() if hasattr(x, "asnumpy") else x)


def test_binary_op_duplicate_input_gradient():
    # reference test_binary_op_duplicate_input: d(x*x)/dx = 2x
    x = nd.array(onp.array([1.0, -2.0, 3.0], "f"))
    x.attach_grad()
    with autograd.record():
        y = x * x
        y.backward(nd.ones_like(y))
    assert_almost_equal(_np(x.grad), 2 * _np(x), rtol=1e-6, atol=1e-7)
    x.attach_grad()
    with autograd.record():
        z = x + x
        z.backward(nd.ones_like(z))
    assert_almost_equal(_np(x.grad), onp.full(3, 2.0), rtol=0, atol=0)


def test_convolution_dilated_impulse_response():
    # reference test_convolution_dilated_impulse_response: a unit impulse
    # convolved with an all-ones 3x3 kernel at dilation d lights up taps
    # exactly at offsets {-d, 0, d} in each axis
    for dil in (1, 2, 3):
        x = onp.zeros((1, 1, 15, 15), "f")
        x[0, 0, 7, 7] = 1.0
        w = onp.ones((1, 1, 3, 3), "f")
        pad = dil
        out = nd.convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                             num_filter=1, dilate=(dil, dil),
                             pad=(pad, pad), no_bias=True)
        got = _np(out)[0, 0]
        assert got.shape == (15, 15)
        nzy, nzx = onp.nonzero(got)
        want = sorted([7 + dy * dil for dy in (-1, 0, 1)])
        assert sorted(set(nzy)) == want and sorted(set(nzx)) == want
        assert got.sum() == 9.0


def test_deconvolution_forward_with_bias():
    rng = onp.random.RandomState(0)
    x = rng.rand(2, 3, 5, 5).astype("f")
    w = rng.rand(3, 4, 3, 3).astype("f")
    b = rng.rand(4).astype("f")
    no_b = nd.deconvolution(nd.array(x), nd.array(w), kernel=(3, 3),
                            num_filter=4, no_bias=True)
    with_b = nd.deconvolution(nd.array(x), nd.array(w), nd.array(b),
                              kernel=(3, 3), num_filter=4, no_bias=False)
    assert_almost_equal(_np(with_b), _np(no_b) + b.reshape(1, 4, 1, 1),
                        rtol=1e-5, atol=1e-5)


def test_upsampling_bilinear_gradient_flows():
    x = nd.array(onp.random.RandomState(1).rand(1, 2, 4, 4).astype("f"))
    x.attach_grad()
    with autograd.record():
        out = nd.contrib.bilinear_resize2d(x, height=8, width=8)
        out.backward(nd.ones_like(out))
    g = _np(x.grad)
    # gradient mass is conserved-ish: each output pixel distributes
    # weight 1 across its source taps
    assert abs(g.sum() - 8 * 8 * 2) < 1e-3
    assert (g > 0).all()


def test_zero_size_tensor_creation_and_ops():
    z = nd.zeros((0, 4))
    assert z.shape == (0, 4) and _np(z).size == 0
    s = nd.sum(z)
    assert float(_np(s)) == 0.0
    c = nd.concat(nd.array(onp.ones((2, 4), "f")), z, dim=0)
    assert c.shape == (2, 4)
    e = nd.array(onp.ones((3, 0), "f"))
    assert e.shape == (3, 0)


def test_zero_size_min_max_raise_or_identity():
    z = nd.zeros((0,))
    # reference: min/max over an empty tensor is an error
    with pytest.raises(Exception):
        nd.max(z).wait_to_read()


def test_float16_min_max():
    # reference test_float16_min_max: fp16 handles its extreme values
    big = onp.array([65504.0, -65504.0, 1.0], "f")
    h = nd.array(big).astype("float16")
    assert float(_np(nd.max(h))) == 65504.0
    assert float(_np(nd.min(h))) == -65504.0


def test_min_max_with_inf():
    x = nd.array(onp.array([1.0, onp.inf, -onp.inf, 2.0], "f"))
    assert onp.isposinf(float(_np(nd.max(x))))
    assert onp.isneginf(float(_np(nd.min(x))))


def test_scalar_tensor_creation():
    a = nd.array(3.5)
    assert a.shape == () and float(_np(a)) == 3.5
    b = nd.full((), 2.0)
    assert float(_np(a * b)) == 7.0


def test_softmax_with_large_inputs():
    # reference test_softmax_with_large_inputs: no overflow at 1e30-scale
    x = nd.array(onp.array([[1e30, 1e30 - 1e14, 0.0]], "f"))
    out = _np(nd.softmax(x))
    assert onp.isfinite(out).all()
    assert abs(out.sum() - 1.0) < 1e-5
    y = nd.array(onp.array([[-1e30, 0.0]], "f"))
    outy = _np(nd.softmax(y))
    assert_almost_equal(outy, [[0.0, 1.0]], rtol=1e-6, atol=1e-6)


def test_softmax_temperature_flattens():
    x = nd.array(onp.array([[1.0, 2.0, 3.0]], "f"))
    hot = _np(nd.softmax(x, temperature=0.1))
    cold = _np(nd.softmax(x, temperature=10.0))
    assert hot.max() > 0.99
    assert cold.max() < 0.4  # nearly uniform


def test_image_normalize_gradient():
    # reference registers _backward_image_normalize — the op must be
    # differentiable through the mean/std affine
    x = nd.array(onp.random.RandomState(2).rand(3, 4, 4).astype("f"))
    x.attach_grad()
    with autograd.record():
        out = nd.image.normalize(x, mean=(0.4, 0.5, 0.6),
                                 std=(0.2, 0.25, 0.5))
        out.backward(nd.ones_like(out))
    g = _np(x.grad)
    want = onp.zeros((3, 4, 4)) + 1.0 / onp.array(
        [0.2, 0.25, 0.5]).reshape(3, 1, 1)
    assert_almost_equal(g, want, rtol=1e-5, atol=1e-6)


@with_seed(9)
def test_monitor_sees_op_outputs():
    # reference test_op_output_names_monitor (Module.install_monitor)
    from mxnet_tpu import sym, io
    from mxnet_tpu.module import Module

    data = sym.Variable("data")
    fc = sym.FullyConnected(data, num_hidden=4, name="fc")
    out = sym.softmax(fc, name="sm")
    mod = Module(out, data_names=["data"], label_names=None)
    mod.bind(data_shapes=[("data", (2, 3))], for_training=False)
    mod.init_params()
    seen = []
    mod.install_monitor(lambda name, arr: seen.append(name))
    mod.forward(io.DataBatch(data=[nd.array(onp.ones((2, 3), "f"))]))
    mod.get_outputs()[0].wait_to_read()
    assert any("fc" in s for s in seen), seen
    assert any("sm" in s for s in seen), seen


def test_monitor_protocol_tic_toc():
    # reference monitor.py usage: Monitor(interval, stat) + install +
    # tic/toc around forward, pattern-filtered
    from mxnet_tpu import sym, io
    from mxnet_tpu.module import Module
    from mxnet_tpu.monitor import Monitor

    data = sym.Variable("data")
    fc = sym.FullyConnected(data, num_hidden=4, name="fc")
    out = sym.softmax(fc, name="sm")
    mod = Module(out, data_names=["data"], label_names=None)
    mod.bind(data_shapes=[("data", (2, 3))], for_training=False)
    mod.init_params()
    mon = Monitor(interval=2, pattern="fc.*")
    mod.install_monitor(mon)
    batch = io.DataBatch(data=[nd.array(onp.ones((2, 3), "f"))])
    mon.tic()
    mod.forward(batch)
    rows = mon.toc()
    names = [r[1] for r in rows]
    assert any(n.startswith("fc") for n in names), names
    assert not any(n.startswith("sm") for n in names), names  # filtered
    # interval gate: the next tic (step 1, interval 2) stays closed
    mon.tic()
    mod.forward(batch)
    assert mon.toc() == []
    # uninstall detaches the executor tap
    mon.uninstall()
    mon.tic()
    mod.forward(batch)
    assert mon.toc() == []


def test_large_reduction_accumulation():
    # fp32 accumulate over 1M elements stays accurate (XLA pairwise sums)
    x = nd.array(onp.full((1 << 20,), 0.1, "f"))
    got = float(_np(nd.sum(x)))
    assert abs(got - 0.1 * (1 << 20)) / (0.1 * (1 << 20)) < 1e-5


def test_broadcast_binary_zero_size():
    a = nd.zeros((0, 3))
    b = nd.array(onp.ones((1, 3), "f"))
    out = nd.broadcast_add(a, b)
    assert out.shape == (0, 3)
