"""nd.linalg la_op family: value + numeric-gradient coverage.

Reference test model: tests/python/unittest/test_operator.py
test_laop / test_laop_2 / test_laop_3 (value checks against numpy and
gradient checks via check_numeric_gradient for every la_op).
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.test_utils import assert_almost_equal, check_numeric_gradient

rs = onp.random.RandomState(42)


def _spd(n, batch=()):
    """Random symmetric positive-definite batch."""
    a = rs.randn(*batch, n, n).astype("f")
    return a @ onp.swapaxes(a, -1, -2) + n * onp.eye(n, dtype="f")


def test_gemm_value_and_grad():
    A = rs.randn(2, 3, 4).astype("f")
    B = rs.randn(2, 4, 5).astype("f")
    C = rs.randn(2, 3, 5).astype("f")
    out = nd.linalg.gemm(nd.array(A), nd.array(B), nd.array(C),
                         alpha=2.0, beta=0.5)
    assert_almost_equal(out.asnumpy(), 2.0 * (A @ B) + 0.5 * C, rtol=1e-4)
    outT = nd.linalg.gemm(nd.array(onp.swapaxes(A, -1, -2)), nd.array(B),
                          nd.array(C), transpose_a=True)
    assert_almost_equal(outT.asnumpy(), A @ B + C, rtol=1e-4)
    check_numeric_gradient(
        lambda a, b, c: nd.linalg.gemm(a, b, c, alpha=1.5, beta=2.0),
        [A, B, C])


def test_gemm2_value_and_grad():
    A = rs.randn(3, 4).astype("f")
    B = rs.randn(5, 4).astype("f")
    out = nd.linalg.gemm2(nd.array(A), nd.array(B), transpose_b=True,
                          alpha=3.0)
    assert_almost_equal(out.asnumpy(), 3.0 * (A @ B.T), rtol=1e-4)
    check_numeric_gradient(
        lambda a, b: nd.linalg.gemm2(a, b, transpose_b=True), [A, B])


def test_syrk():
    A = rs.randn(2, 3, 4).astype("f")
    assert_almost_equal(nd.linalg.syrk(nd.array(A), alpha=2.0).asnumpy(),
                        2.0 * A @ onp.swapaxes(A, -1, -2), rtol=1e-4)
    assert_almost_equal(
        nd.linalg.syrk(nd.array(A), transpose=True).asnumpy(),
        onp.swapaxes(A, -1, -2) @ A, rtol=1e-4)
    check_numeric_gradient(lambda a: nd.linalg.syrk(a), [A[0]])


def test_potrf_and_potri():
    A = _spd(4, (2,))
    L = nd.linalg.potrf(nd.array(A))
    assert_almost_equal(L.asnumpy() @ onp.swapaxes(L.asnumpy(), -1, -2),
                        A, rtol=1e-3, atol=1e-3)
    # potri: (L Lᵀ)⁻¹ from the factor
    Ainv = nd.linalg.potri(L)
    assert_almost_equal(Ainv.asnumpy() @ A,
                        onp.broadcast_to(onp.eye(4, dtype="f"), A.shape),
                        rtol=1e-2, atol=1e-2)
    check_numeric_gradient(lambda a: nd.linalg.potrf(a), [_spd(3)],
                           rtol=5e-2, atol=1e-2)


def test_trmm():
    # own RandomState: the module-level `rs` makes these operands depend
    # on how many draws earlier tests consumed, and one such ordering
    # lands on a marginal finite-difference comparison (rel err 1.2e-2
    # vs rtol 1e-2). Local seeding keeps the operands identical no
    # matter which subset of the file runs.
    rs_local = onp.random.RandomState(7)
    A = onp.tril(rs_local.randn(4, 4)).astype("f") + 4 * onp.eye(4, dtype="f")
    B = rs_local.randn(4, 5).astype("f")
    out = nd.linalg.trmm(nd.array(A), nd.array(B), alpha=2.0)
    assert_almost_equal(out.asnumpy(), 2.0 * onp.tril(A) @ B, rtol=1e-4)
    out = nd.linalg.trmm(nd.array(A), nd.array(B.T), rightside=True)
    assert_almost_equal(out.asnumpy(), B.T @ onp.tril(A), rtol=1e-4)
    out = nd.linalg.trmm(nd.array(A), nd.array(B), transpose=True)
    assert_almost_equal(out.asnumpy(), onp.tril(A).T @ B, rtol=1e-4)
    check_numeric_gradient(lambda a, b: nd.linalg.trmm(a, b), [A, B])


@pytest.mark.parametrize("transpose,rightside",
                         [(False, False), (True, False),
                          (False, True), (True, True)])
def test_trsm(transpose, rightside):
    A = (onp.tril(rs.randn(4, 4)) + 5 * onp.eye(4)).astype("f")
    tri = onp.tril(A)
    op = tri.T if transpose else tri
    if rightside:
        B = rs.randn(3, 4).astype("f")
        X = nd.linalg.trsm(nd.array(A), nd.array(B), transpose=transpose,
                           rightside=True, alpha=2.0)
        assert_almost_equal(X.asnumpy() @ op, 2.0 * B, rtol=1e-3,
                            atol=1e-4)
    else:
        B = rs.randn(4, 3).astype("f")
        X = nd.linalg.trsm(nd.array(A), nd.array(B), transpose=transpose,
                           alpha=2.0)
        assert_almost_equal(op @ X.asnumpy(), 2.0 * B, rtol=1e-3,
                            atol=1e-4)


def test_trsm_grad():
    A = (onp.tril(rs.randn(3, 3)) + 4 * onp.eye(3)).astype("f")
    B = rs.randn(3, 2).astype("f")
    check_numeric_gradient(lambda a, b: nd.linalg.trsm(a, b), [A, B],
                           rtol=3e-2, atol=1e-3)


def test_gelqf():
    A = rs.randn(3, 5).astype("f")
    L, Q = nd.linalg.gelqf(nd.array(A))
    Ln, Qn = L.asnumpy(), Q.asnumpy()
    assert_almost_equal(Ln @ Qn, A, rtol=1e-3, atol=1e-4)
    assert_almost_equal(Qn @ Qn.T, onp.eye(3, dtype="f"), rtol=1e-3,
                        atol=1e-4)
    assert onp.allclose(onp.triu(Ln, 1), 0, atol=1e-5)  # lower triangular
    assert (onp.diag(Ln) > 0).all()


def test_syevd():
    A = _spd(4)
    U, L = nd.linalg.syevd(nd.array(A))
    Un, Ln = U.asnumpy(), L.asnumpy()
    # A = Uᵀ diag(L) U with rows of U the eigenvectors
    assert_almost_equal(Un.T @ onp.diag(Ln) @ Un, A, rtol=1e-3, atol=1e-3)


def test_inverse_det_slogdet():
    A = _spd(3, (2,))
    Ainv = nd.linalg.inverse(nd.array(A))
    assert_almost_equal(Ainv.asnumpy() @ A,
                        onp.broadcast_to(onp.eye(3, dtype="f"), A.shape),
                        rtol=1e-3, atol=1e-3)
    d = nd.linalg.det(nd.array(A))
    assert_almost_equal(d.asnumpy(), onp.linalg.det(A), rtol=1e-3)
    sign, logabs = nd.linalg.slogdet(nd.array(A))
    sn, ln = onp.linalg.slogdet(A)
    assert_almost_equal(sign.asnumpy(), sn.astype("f"), rtol=1e-5)
    assert_almost_equal(logabs.asnumpy(), ln.astype("f"), rtol=1e-4)
    check_numeric_gradient(lambda a: nd.linalg.slogdet(a)[1], [_spd(3)],
                           rtol=3e-2, atol=1e-3)


def test_sumlogdiag():
    A = _spd(4)
    out = nd.linalg.sumlogdiag(nd.array(A))
    assert_almost_equal(out.asnumpy(),
                        onp.sum(onp.log(onp.diag(A))).astype("f"),
                        rtol=1e-4)
    check_numeric_gradient(lambda a: nd.linalg.sumlogdiag(a), [A],
                           rtol=3e-2, atol=1e-3)


def test_extractdiag_makediag_roundtrip():
    A = rs.randn(2, 4, 4).astype("f")
    d = nd.linalg.extractdiag(nd.array(A))
    assert_almost_equal(d.asnumpy(),
                        onp.diagonal(A, axis1=-2, axis2=-1), rtol=1e-6)
    d1 = nd.linalg.extractdiag(nd.array(A), offset=1)
    assert_almost_equal(d1.asnumpy(),
                        onp.diagonal(A, offset=1, axis1=-2, axis2=-1),
                        rtol=1e-6)
    v = rs.randn(3).astype("f")
    M = nd.linalg.makediag(nd.array(v))
    assert_almost_equal(M.asnumpy(), onp.diag(v), rtol=1e-6)
    M1 = nd.linalg.makediag(nd.array(v), offset=-1)
    assert_almost_equal(M1.asnumpy(), onp.diag(v, k=-1), rtol=1e-6)


def test_extracttrian_maketrian_roundtrip():
    A = rs.randn(4, 4).astype("f")
    v = nd.linalg.extracttrian(nd.array(A))
    assert v.shape == (10,)
    back = nd.linalg.maketrian(v)
    assert_almost_equal(back.asnumpy(), onp.tril(A), rtol=1e-6)
    vu = nd.linalg.extracttrian(nd.array(A), lower=False)
    backu = nd.linalg.maketrian(vu, lower=False)
    assert_almost_equal(backu.asnumpy(), onp.triu(A), rtol=1e-6)


def test_linalg_multi_output_symbolic():
    import mxnet_tpu.symbol as sym

    a = sym.Variable("a")
    U, L = sym.linalg.syevd(a)
    A = _spd(4)
    ex = (U * 1).bind(mx.cpu(), {"a": nd.array(A)})
    (Un,) = ex.forward()
    w = onp.linalg.eigvalsh(A)
    Ln, Qn = sym.linalg.gelqf(sym.Variable("x"))
    assert Un.shape == (4, 4) and w.shape == (4,)
    s, ld = sym.linalg.slogdet(sym.Variable("y"))
    ex2 = ld.bind(mx.cpu(), {"y": nd.array(A)})
    (ldv,) = ex2.forward()
    assert_almost_equal(ldv.asnumpy(), onp.linalg.slogdet(A)[1], rtol=1e-3)


def test_linalg_under_symbol_and_autograd():
    # la_ops work through the symbolic executor (registered ops, not
    # jnp delegates) and record on the tape
    import mxnet_tpu.symbol as sym

    a = sym.Variable("a")
    out = sym.linalg.sumlogdiag(sym.linalg.potrf(a))
    A = _spd(3)
    ex = out.bind(mx.cpu(), {"a": nd.array(A)})
    (res,) = ex.forward()
    # sum(log(diag(chol(A)))) == 0.5*logdet(A)
    assert_almost_equal(res.asnumpy(), 0.5 * onp.linalg.slogdet(A)[1],
                        rtol=1e-3)
    x = nd.array(A)
    x.attach_grad()
    with autograd.record():
        y = nd.linalg.sumlogdiag(nd.linalg.potrf(x))
    y.backward()
    # d(0.5 logdet A)/dA = 0.5 A^{-T}; tape grad should match
    assert_almost_equal(x.grad.asnumpy(), 0.5 * onp.linalg.inv(A).T,
                        rtol=2e-2, atol=1e-3)
