"""ModelRepository: multi-model routing, canary rollout, auto-rollback
and the model_swap fault seam (tier-1, no sockets).

The canary e2e here is the round-13 acceptance scenario: a bad canary
version (every execution raises InjectedFault) is detected through the
circuit breaker and rolled back automatically, clients see ZERO
failures at any point (transparent incumbent fallback), and the
healthz / Prometheus surfaces record the transition."""
import time

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, serving
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.resilience import faults
from mxnet_tpu.serving import metrics as met

nd = mx.nd


def _mlp(seed=0):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    with autograd.pause(train_mode=False):
        net(nd.zeros((1, 8)))
    return net


def _session(net=None, **kw):
    return serving.InferenceSession(net or _mlp(),
                                    input_shapes=[(1, 8)],
                                    buckets=[1, 2, 4], **kw)


def _ref(net, x):
    with autograd.pause(train_mode=False):
        return net(nd.array(x)).asnumpy()


def _x(seed, rows=1):
    return onp.random.RandomState(seed).rand(rows, 8).astype("float32")


@pytest.fixture(autouse=True)
def _fresh_counters():
    serving.reset_serving_counters()
    yield
    serving.reset_serving_counters()


class _BadSession:
    """A deployable version whose every execution fails — the
    fault-injected bad rollout."""

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def predict(self, *arrs):
        raise faults.InjectedFault("canary executes into a wall")


class _SlowSession:
    """A deployable version that works — at a latency regression."""

    def __init__(self, inner, delay_s):
        self._inner = inner
        self._delay_s = delay_s

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def predict(self, *arrs):
        time.sleep(self._delay_s)
        return self._inner.predict(*arrs)


def _wait_state(repo, name, state, timeout_s=10.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        st = repo.model_states()[name]
        if st["state"] == state:
            return st
        time.sleep(0.01)
    raise AssertionError(
        f"model {name} never reached {state!r}: "
        f"{repo.model_states()[name]}")


# ---------------------------------------------------------------------------
# multi-model routing

def test_two_models_concurrently_bitwise_vs_eager():
    net_a, net_b = _mlp(1), _mlp(2)
    with serving.ModelRepository(max_latency_ms=1.0) as repo:
        assert repo.deploy("alpha", _session(net_a)) == 1
        assert repo.add("beta", _session(net_b)) == 1
        assert repo.models() == ["alpha", "beta"]
        assert repo.default_model == "alpha"  # first deploy wins
        futs = []
        for i in range(8):
            x = _x(10 + i)
            futs.append(("alpha", x, repo.submit("alpha", x)))
            futs.append(("beta", x, repo.submit(
                "beta", x, slo_class="critical")))
        for name, x, f in futs:
            ref = _ref(net_a if name == "alpha" else net_b, x)
            assert onp.array_equal(f.result(timeout=30), ref), name
    assert serving.serving_stats()["model_swaps"] == 2


def test_unknown_model_and_duplicate_version_raise():
    with serving.ModelRepository(max_latency_ms=1.0) as repo:
        repo.deploy("m", _session(), version=3)
        with pytest.raises(MXNetError, match="unknown model"):
            repo.submit("ghost", _x(0))
        with pytest.raises(MXNetError, match="already deployed"):
            repo.deploy("m", _session(), version=3)


# ---------------------------------------------------------------------------
# canary rollout

def test_canary_auto_rollback_e2e():
    """The acceptance scenario: bad canary -> breaker trips ->
    automatic rollback; zero client-visible failures throughout; the
    healthz and Prometheus surfaces reflect the transition."""
    net = _mlp(5)
    repo = serving.ModelRepository(canary_threshold=3,
                                   canary_fraction=1.0,
                                   max_latency_ms=1.0)
    try:
        repo.deploy("m", _session(net))
        assert repo.deploy("m", _BadSession(_session(net))) == 2
        st = repo.model_states()["m"]
        assert st["state"] == "canary"
        assert st["canary"]["version"] == 2
        assert st["canary"]["breaker"] == "closed"

        # every non-critical request rides the canary (fraction=1.0),
        # fails there, and transparently falls back to the incumbent —
        # the client never sees an error
        for i in range(3):
            out = repo.submit("m", _x(i),
                              slo_class="best_effort").result(timeout=30)
            assert onp.array_equal(out, _ref(net, _x(i)))
        st = _wait_state(repo, "m", "rolled_back")
        assert st["active_version"] == 1
        assert "canary" not in st
        assert "breaker tripped" in st["last_transition"]

        # after rollback: the protected class is untouched — zero
        # failed critical requests, bitwise vs eager
        for i in range(4):
            out = repo.submit("m", _x(20 + i),
                              slo_class="critical").result(timeout=30)
            assert onp.array_equal(out, _ref(net, _x(20 + i)))
        stats = serving.serving_stats()
        assert stats["canary_rollbacks"] == 1
        assert stats["canary_failures"] == 3
        assert stats["canary_fallbacks"] == 3
        assert stats["failures:critical"] == 0
        # the 3 canary-lane failures ARE in the metrics (that's how
        # the operator sees the bad rollout) — but every client-held
        # future above resolved with the incumbent's answer
        assert stats["failures:best_effort"] == 3

        hz = repo.healthz()
        assert hz["status"] == "degraded"  # rolled_back is a signal
        assert hz["models"]["m"]["state"] == "rolled_back"
        assert set(hz["queue_depths"]) == set(met.SLO_CLASSES)
        assert hz["slo"] is not None and 0 <= hz["slo"]["headroom"] <= 1
        text = met.prometheus_text()
        assert "mxnet_serving_canary_rollbacks_total 1" in text
        assert "mxnet_serving_canary_fallbacks_total 3" in text
    finally:
        repo.close()


def test_critical_never_rides_the_canary():
    net = _mlp(6)
    with serving.ModelRepository(canary_fraction=1.0,
                                 max_latency_ms=1.0) as repo:
        repo.deploy("m", _session(net))
        repo.deploy("m", _BadSession(_session(net)))
        # fraction=1.0: every ELIGIBLE request would ride the canary —
        # critical is not eligible, so none of these ever fail
        for i in range(5):
            out = repo.submit("m", _x(i),
                              slo_class="critical").result(timeout=30)
            assert onp.array_equal(out, _ref(net, _x(i)))
        assert serving.serving_stats()["canary_requests"] == 0
        assert repo.model_states()["m"]["state"] == "canary"


def test_canary_auto_promote_after_clean_run():
    net1, net2 = _mlp(7), _mlp(8)
    repo = serving.ModelRepository(canary_min_requests=10,
                                   canary_fraction=1.0,
                                   max_latency_ms=1.0)
    try:
        repo.deploy("m", _session(net1))
        repo.deploy("m", _session(net2))
        for i in range(10):
            repo.submit("m", _x(i),
                        slo_class="standard").result(timeout=30)
        st = _wait_state(repo, "m", "serving")
        assert st["active_version"] == 2
        assert "promoted" in st["last_transition"]
        assert serving.serving_stats()["canary_promotions"] == 1
        # post-promote traffic is the NEW version, bitwise
        out = repo.submit("m", _x(50)).result(timeout=30)
        assert onp.array_equal(out, _ref(net2, _x(50)))
    finally:
        repo.close()


def test_canary_latency_regression_rolls_back():
    """A canary that answers correctly but 10x slower is a failed
    rollout: the EMA comparison routes through the breaker and rolls
    back."""
    net = _mlp(9)
    # admission off: the 50 ms canary latencies would otherwise erode
    # the process-wide latency headroom and shed the very traffic this
    # test routes (regression detection, not admission, is under test)
    repo = serving.ModelRepository(canary_min_requests=10_000,
                                   canary_threshold=2,
                                   canary_latency_x=3.0,
                                   canary_fraction=0.5,
                                   max_latency_ms=1.0,
                                   admission=False)
    try:
        repo.deploy("m", _session(net))
        repo.deploy("m", _SlowSession(_session(net), delay_s=0.05))
        for i in range(40):
            repo.submit("m", _x(i),
                        slo_class="standard").result(timeout=30)
            if repo.model_states()["m"]["state"] == "rolled_back":
                break
        st = _wait_state(repo, "m", "rolled_back")
        assert "latency regression" in st["last_transition"]
        assert st["active_version"] == 1
    finally:
        repo.close()


# ---------------------------------------------------------------------------
# the model_swap seam

def test_model_swap_fault_aborts_first_deploy_cleanly():
    repo = serving.ModelRepository(max_latency_ms=1.0)
    try:
        with faults.inject("model_swap", at=1):
            with pytest.raises(faults.InjectedFault):
                repo.deploy("m", _session())
        # the failed swap left no half-registered model behind
        assert repo.models() == []
        assert repo.default_model is None
        repo.deploy("m", _session())
        assert repo.model_states()["m"]["state"] == "serving"
    finally:
        repo.close()


def test_model_swap_fault_aborts_promote_incumbent_stays():
    net1, net2 = _mlp(3), _mlp(4)
    repo = serving.ModelRepository(max_latency_ms=1.0)
    try:
        repo.deploy("m", _session(net1))
        repo.deploy("m", _session(net2))
        with faults.inject("model_swap", at=1):
            with pytest.raises(faults.InjectedFault):
                repo.promote("m")
        st = repo.model_states()["m"]
        assert st["active_version"] == 1  # incumbent untouched
        assert st["state"] == "canary" and st["canary"]["version"] == 2
        out = repo.submit("m", _x(1),
                          slo_class="critical").result(timeout=30)
        assert onp.array_equal(out, _ref(net1, _x(1)))
        repo.promote("m")  # seam disarmed: the swap lands
        st = repo.model_states()["m"]
        assert st["active_version"] == 2 and st["state"] == "serving"
    finally:
        repo.close()


def test_operator_rollback_is_seam_free():
    """rollback() is the escape hatch: it works even with the
    model_swap seam armed to fire on every call."""
    repo = serving.ModelRepository(max_latency_ms=1.0)
    try:
        repo.deploy("m", _session(_mlp(1)))
        repo.deploy("m", _session(_mlp(2)))
        with faults.inject("model_swap", every=1):
            repo.rollback("m", reason="operator says no")
        st = repo.model_states()["m"]
        assert st["state"] == "rolled_back"
        assert "operator says no" in st["last_transition"]
        assert st["active_version"] == 1
    finally:
        repo.close()


# ---------------------------------------------------------------------------
# lifecycle

def test_refresh_tracks_weight_updates_on_active_version():
    net = _mlp(11)
    with serving.ModelRepository(max_latency_ms=1.0) as repo:
        repo.deploy("m", _session(net))
        x = _x(2, rows=2)
        before = repo.predict("m", x)
        for _, p in net.collect_params().items():
            p.set_data(p.data() * 2.0)
        repo.refresh("m")
        after = repo.predict("m", x)
        assert not onp.array_equal(before, after)
        assert onp.array_equal(after, _ref(net, x))


def test_healthz_ok_and_closed_repo_rejects_deploys():
    repo = serving.ModelRepository(max_latency_ms=1.0)
    repo.deploy("m", _session())
    hz = repo.healthz()
    assert hz["status"] == "ok" and hz["warm"]
    assert hz["queue_depth"] == 0
    assert hz["models"]["m"]["active_version"] == 1
    repo.close()
    repo.close()  # idempotent
    with pytest.raises(MXNetError, match="closed"):
        repo.deploy("n", _session())
