"""64-bit tensor-size story (reference: include/mxnet/libinfo.h:126
INT64_TENSOR_SIZE; tests/nightly/test_large_vector.py). The knob is
MXNET_INT64_TENSOR_SIZE=1 → JAX x64 mode. These tests exercise both sides:
the loud truncation warning when off, and real int64 arithmetic when on
(in a subprocess, since x64 must be set before first jax use).
"""
import os
import subprocess
import sys
import warnings

import numpy as onp
import pytest


def test_int64_request_warns_loudly():
    import mxnet_tpu as mx
    from mxnet_tpu.ndarray import ndarray as nd_mod

    if mx.nd.array([1]).data.dtype == onp.int64:
        pytest.skip("x64 already enabled in this process")
    nd_mod._warned_int64 = False
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        a = mx.nd.array([7], dtype="int64")
    msgs = [str(x.message) for x in w]
    assert any("MXNET_INT64_TENSOR_SIZE" in m for m in msgs), msgs
    # out-of-range values fail loudly rather than silently wrapping
    with pytest.raises(OverflowError):
        mx.nd.array([2 ** 40], dtype="int64")
    # warned once, not per call
    with warnings.catch_warnings(record=True) as w2:
        warnings.simplefilter("always")
        mx.nd.array([1], dtype="int64")
    assert not any("MXNET_INT64_TENSOR_SIZE" in str(x.message) for x in w2)


_CHILD = r"""
import os
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["MXNET_INT64_TENSOR_SIZE"] = "1"
import numpy as onp
import mxnet_tpu as mx

# int64 values beyond 2**31 survive round trips (large-vector analog)
a = mx.nd.array([2 ** 40, 2 ** 41], dtype="int64")
assert a.dtype == onp.int64, a.dtype
v = a.asnumpy()
assert v.tolist() == [2 ** 40, 2 ** 41], v
b = (a + a)
assert b.asnumpy().tolist() == [2 ** 41, 2 ** 42]
# arange/indexing keep int64 semantics
idx = mx.nd.array([1], dtype="int64")
took = a.take(idx)
assert took.asnumpy().tolist() == [2 ** 41]
# float64 honored too
f = mx.nd.array([1.0], dtype="float64")
assert f.dtype == onp.float64
# mx.np side
from mxnet_tpu import np as mnp
z = mnp.array([2 ** 40], dtype="int64")
assert int(z.asnumpy()[0]) == 2 ** 40
print("INT64-OK")
"""


def test_int64_mode_end_to_end():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run([sys.executable, "-c", _CHILD], env=env,
                       capture_output=True, text=True, timeout=300)
    assert "INT64-OK" in r.stdout, r.stdout + r.stderr
