"""Compiled eager-dispatch cache (ndarray/registry.py).

Covers the cache contract: hits on repeated same-shape dispatch, misses on
shape/dtype/AMP-version changes, the LRU bound, the MXNET_EAGER_JIT=0
bypass, and byte-for-byte equivalence (values, gradients, out=, PRNG
streams, create_graph replay) between the cached and uncached paths.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, profiler
from mxnet_tpu.ndarray import registry

nd = mx.nd


@pytest.fixture(autouse=True)
def _fresh_cache(monkeypatch):
    monkeypatch.setenv("MXNET_EAGER_JIT", "1")
    registry.reset_dispatch_cache(maxsize=512)
    yield
    registry.reset_dispatch_cache(maxsize=512)


def test_hit_on_repeated_same_shape():
    x = nd.ones((4, 8))
    w = nd.ones((8, 8))
    r = [nd.dot(x, w) for _ in range(3)]
    s = registry.dispatch_cache_stats()
    assert s["misses"] == 1
    assert s["hits"] == 2
    for ri in r[1:]:
        assert onp.array_equal(ri.asnumpy(), r[0].asnumpy())


def test_miss_on_shape_dtype_and_amp_change():
    w32 = nd.ones((8, 8))
    nd.dot(nd.ones((4, 8)), w32)
    nd.dot(nd.ones((2, 8)), w32)                       # shape change
    nd.dot(nd.ones((4, 8), dtype="float16"),
           nd.ones((8, 8), dtype="float16"))           # dtype change
    assert registry.dispatch_cache_stats()["misses"] == 3
    registry.set_amp(None)                             # bumps AMP version
    nd.dot(nd.ones((4, 8)), w32)
    assert registry.dispatch_cache_stats()["misses"] == 4


def test_eviction_bound_respected():
    registry.reset_dispatch_cache(maxsize=2)
    for n in (2, 3, 4, 5):
        nd.tanh(nd.ones((n,)))
    s = registry.dispatch_cache_stats()
    assert s["size"] <= 2
    assert s["evictions"] >= 2
    # the most recent entry survived and still hits
    nd.tanh(nd.ones((5,)))
    assert registry.dispatch_cache_stats()["hits"] == 1


def test_eager_jit_env_bypass(monkeypatch):
    monkeypatch.setenv("MXNET_EAGER_JIT", "0")
    x = nd.ones((4, 4))
    for _ in range(3):
        nd.tanh(x)
    s = registry.dispatch_cache_stats()
    assert s["hits"] == 0 and s["misses"] == 0
    assert not registry.eager_jit_enabled()


def _grad_chain(a, w):
    with autograd.record():
        y = nd.dot(a, w)
        z = nd.sum(nd.tanh(y))
    z.backward()
    return a.grad.asnumpy().copy()


def test_gradient_bitwise_equivalence(monkeypatch):
    a = nd.array(onp.linspace(-1, 1, 32).reshape(4, 8).astype("float32"))
    w = nd.array(onp.linspace(0, 2, 64).reshape(8, 8).astype("float32"))
    a.attach_grad()
    monkeypatch.setenv("MXNET_EAGER_JIT", "0")
    g_un = _grad_chain(a, w)
    monkeypatch.setenv("MXNET_EAGER_JIT", "1")
    g_miss = _grad_chain(a, w)   # first pass populates the cache
    g_hit = _grad_chain(a, w)    # second pass runs compiled executables
    assert registry.dispatch_cache_stats()["hits"] > 0
    assert onp.array_equal(g_un, g_miss)
    assert onp.array_equal(g_un, g_hit)


@pytest.mark.parametrize("donate", ["0", "1"])
def test_out_equivalence(monkeypatch, donate):
    # donate=1 opts into out=-buffer donation (entry compiled with
    # donate_argnums; a no-op alias hint on the CPU backend)
    monkeypatch.setenv("MXNET_EAGER_JIT_DONATE", donate)

    def run():
        registry.reset_dispatch_cache()
        w = nd.array(onp.arange(8, dtype="float32"))
        g = nd.ones((8,))
        for _ in range(3):
            nd.sgd_update(w, g, 0.1, out=w)
        return w.asnumpy().copy()

    monkeypatch.setenv("MXNET_EAGER_JIT", "0")
    expect = run()
    monkeypatch.setenv("MXNET_EAGER_JIT", "1")
    got = run()
    assert registry.dispatch_cache_stats()["hits"] >= 2
    assert onp.array_equal(expect, got)
    # out= must return the same handle, updated in place
    w = nd.ones((8,))
    r = nd.sgd_update(w, nd.ones((8,)), 0.1, out=w)
    assert r is w


def test_prng_stream_equivalence(monkeypatch):
    def draw():
        mx.random.seed(11)
        return [nd.random_uniform(shape=(5,)).asnumpy() for _ in range(4)]

    monkeypatch.setenv("MXNET_EAGER_JIT", "0")
    expect = draw()
    monkeypatch.setenv("MXNET_EAGER_JIT", "1")
    got = draw()     # call 1 = miss, calls 2-4 = cached hits
    assert registry.dispatch_cache_stats()["hits"] >= 1
    for e, g in zip(expect, got):
        assert onp.array_equal(e, g)


def test_stochastic_op_grad_equivalence(monkeypatch):
    def run():
        mx.random.seed(3)
        x = nd.ones((16, 16))
        x.attach_grad()
        outs = []
        for _ in range(2):
            with autograd.record():
                y = nd.sum(nd.dropout(x, p=0.5))
            y.backward()
            outs.append((y.asnumpy().copy(), x.grad.asnumpy().copy()))
        return outs

    monkeypatch.setenv("MXNET_EAGER_JIT", "0")
    expect = run()
    monkeypatch.setenv("MXNET_EAGER_JIT", "1")
    got = run()
    for (ey, eg), (gy, gg) in zip(expect, got):
        assert onp.array_equal(ey, gy)
        assert onp.array_equal(eg, gg)


def test_create_graph_replay_equivalence(monkeypatch):
    def second_order():
        x = nd.array(onp.array([0.3, -0.7, 1.2], dtype="float32"))
        x.attach_grad()
        with autograd.record():
            y = nd.sum(nd.tanh(x) * nd.tanh(x))
        (g,) = autograd.grad(y, [x], create_graph=True)
        autograd.backward(nd.sum(g))
        return x.grad.asnumpy().copy()

    monkeypatch.setenv("MXNET_EAGER_JIT", "0")
    expect = second_order()
    monkeypatch.setenv("MXNET_EAGER_JIT", "1")
    second_order()                 # populate
    got = second_order()           # cached forward, replayed backward
    assert onp.array_equal(expect, got)


def test_profiler_cached_flag_and_counters(tmp_path):
    x = nd.ones((4, 4))
    nd.tanh(x)          # miss outside the profiled window
    profiler.set_config(filename="", profile_imperative=True)
    profiler.start()
    try:
        nd.tanh(x)      # hit
    finally:
        profiler.stop()
        profiler.set_config(filename="profile.json",
                            profile_imperative=False)
    evs = [e for e in profiler._events
           if e.get("name") == "tanh" and "cached" in e.get("args", {})]
    assert evs and evs[-1]["args"]["cached"] is True
    counters = profiler.dispatch_cache_counters()
    assert counters["hits"] >= 1
    # dump() carries the counters as chrome counter samples
    import json

    profiler.set_config(filename=str(tmp_path / "prof.json"))
    try:
        f = profiler.dump()
    finally:
        profiler.set_config(filename="profile.json")
    evts = json.load(open(f))["traceEvents"]
    assert any(e["name"] == "eager_jit_cache/hits" for e in evts)
    # dumps() keeps its empty-after-reset contract
    profiler.dumps(format="json", reset=True)
    assert profiler.dumps(format="json") == "[]"


def test_tracer_and_adhoc_bypass():
    # numpy frontend _call dispatches ad-hoc OpDefs: must bypass, and two
    # different closures under one name must not collide
    np = mx.np
    xi, yi = np.meshgrid(np.arange(3), np.arange(4), indexing="ij")
    xx, yy = np.meshgrid(np.arange(3), np.arange(4))
    assert xi.shape == (3, 4) and xx.shape == (4, 3)
    assert registry.dispatch_cache_stats()["bypasses"] >= 1


def test_smoke_bench_runs(tmp_path):
    from mxnet_tpu.benchmark import dispatch_bench

    out = tmp_path / "bench.json"
    doc = dispatch_bench.run(smoke=True, iters=20, out_path=str(out))
    assert out.exists()
    assert set(doc["results"]) == {"nograd", "recorded"}
    for r in doc["results"].values():
        assert r["speedup"] > 0
    assert doc["counters"]["hits"] > 0
