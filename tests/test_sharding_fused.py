"""Plan-driven fused train step: parity, ZeRO-1, cache salting.

Contract under test (docs/SHARDING.md): enter ``plan_scope``, call
``sharding.place_params`` on the initialized params, mesh-place every
batch (``parallel.replicate``/``shard_batch``) — then ``Trainer.step``
runs the ONE donated executable with plan-matching in/out shardings.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd, parallel, sharding
from mxnet_tpu.gluon import nn
from mxnet_tpu.sharding import ShardingPlan


def _build(dim, out, layers=1, hidden=32, seed=0, optimizer="adam"):
    mx.random.seed(seed)
    net = nn.HybridSequential(prefix="net_")
    for i in range(layers):
        last = i == layers - 1
        net.add(nn.Dense(out if last else hidden,
                         activation=None if last else "relu",
                         prefix=f"d{i}_"))
    net.initialize()
    net(nd.zeros((1, dim)))
    trainer = mx.gluon.Trainer(net.collect_params(), optimizer,
                               {"learning_rate": 0.02})
    return net, trainer


def _train(net, trainer, batches, mesh=None):
    for x, y in batches:
        xb, yb = nd.array(x), nd.array(y)
        if mesh is not None:
            xb = parallel.replicate(xb, mesh)
            yb = parallel.replicate(yb, mesh)
        with autograd.record():
            loss = ((net(xb) - yb) ** 2).mean()
        loss.backward()
        trainer.step(x.shape[0])
    return float(loss.asnumpy())


def _batches(n, batch, dim, out, seed=5):
    rs = onp.random.RandomState(seed)
    return [(rs.rand(batch, dim).astype("f"),
             rs.rand(batch, out).astype("f")) for _ in range(n)]


def _params(net):
    return {p.name: p.data().asnumpy()
            for p in net.collect_params().values()}


def _plan():
    return ShardingPlan({r"weight$": ("mp", None)})


def test_place_params_lays_out_buffers():
    mesh = parallel.make_mesh({"mp": 4})
    net, _ = _build(8, 16, seed=3)
    with sharding.plan_scope(_plan(), mesh):
        sharding.place_params(net.collect_params())
    w = net.collect_params()["d0_weight"]
    assert not w.data().data.sharding.is_fully_replicated
    assert tuple(w.data().data.sharding.spec) == ("mp", None)
    assert tuple(w.grad().data.sharding.spec) == ("mp", None)
    b = net.collect_params()["d0_bias"]
    assert b.data().data.sharding.is_fully_replicated


def test_place_params_needs_plan_outside_scope():
    net, _ = _build(8, 16, seed=3)
    with pytest.raises(ValueError, match="needs a plan"):
        sharding.place_params(net.collect_params())


def test_fused_step_parity_under_plan():
    """Single layer, so no cross-shard contraction feeds the backward:
    the sharded run tracks the unsharded one to float32 ulp."""
    batches = _batches(3, 16, 8, 16)
    net1, tr1 = _build(8, 16, seed=7)
    _train(net1, tr1, batches)

    mesh = parallel.make_mesh({"mp": 4})
    net2, tr2 = _build(8, 16, seed=7)
    with sharding.plan_scope(_plan(), mesh):
        sharding.place_params(net2.collect_params())
        sharding.reset_sharding_counters()
        _train(net2, tr2, batches, mesh=mesh)
    assert sharding.sharding_counters()["fused_sharded_groups"] >= 1
    a, b = _params(net1), _params(net2)
    for k in a:
        onp.testing.assert_allclose(a[k], b[k], rtol=0, atol=1e-6)
    assert tr1._optimizer.num_update == tr2._optimizer.num_update


def test_zero1_state_bytes_and_parity(monkeypatch):
    """ZeRO-1: per-device optimizer-state bytes ~ 1/N, same training
    trajectory (to ulp)."""
    import jax

    batches = _batches(3, 16, 8, 16)
    net1, tr1 = _build(8, 16, seed=9)
    _train(net1, tr1, batches)

    monkeypatch.setenv("MXNET_SHARDING_ZERO1", "1")
    mesh = parallel.make_mesh({"mp": 4})
    net2, tr2 = _build(8, 16, seed=9)
    with sharding.plan_scope(ShardingPlan({}), mesh):
        # empty plan: params replicated, so ZeRO-1 itself must shard
        # the state's leading dim over the mesh
        sharding.place_params(net2.collect_params())
        sharding.reset_sharding_counters()
        _train(net2, tr2, batches, mesh=mesh)
    assert sharding.sharding_counters()["zero1_groups"] >= 1
    a, b = _params(net1), _params(net2)
    for k in a:
        onp.testing.assert_allclose(a[k], b[k], rtol=0, atol=1e-6)

    dev0 = jax.devices()[0]
    per_dev = total = 0
    for leaf in jax.tree_util.tree_leaves(tr2._states):
        arr = leaf.data if hasattr(leaf, "asnumpy") else leaf
        if not hasattr(arr, "addressable_shards"):
            continue
        total += int(arr.size)
        for s in arr.addressable_shards:
            if s.device == dev0:
                per_dev += int(s.data.size)
    assert total > 0
    assert per_dev / total == pytest.approx(0.25, abs=0.05)


def test_cache_key_salted_by_plan():
    """Entering/leaving a plan scope (or changing the plan) rebuilds
    the fused group instead of reusing the other layout's executable."""
    mesh = parallel.make_mesh({"mp": 4})
    net, tr = _build(8, 16, seed=11)
    batches = _batches(1, 16, 8, 16)
    _train(net, tr, batches)
    key_plain = tr._fused["token"]
    with sharding.plan_scope(_plan(), mesh):
        sharding.place_params(net.collect_params())
        _train(net, tr, batches, mesh=mesh)
        key_plan = tr._fused["token"]
        cfg = tr._fused["shard_cfg"]
    assert key_plain != key_plan
    assert cfg is not None and cfg.zero1 is False
    # scope exited: the next step goes back to the unsharded layout
    sharding.place_params(net.collect_params(),
                          plan=ShardingPlan({}), mesh=mesh)


def test_scope_exit_restores_plain_path():
    mesh = parallel.make_mesh({"mp": 4})
    batches = _batches(2, 16, 8, 16)
    net, tr = _build(8, 16, seed=13)
    with sharding.plan_scope(_plan(), mesh):
        sharding.place_params(net.collect_params())
        _train(net, tr, batches, mesh=mesh)
    assert sharding.current_plan() is None
    assert tr._shard_token() is None
    # buffers are still mesh-committed; keep feeding mesh-placed
    # batches (the scope controls the EXECUTABLE layout, not where the
    # arrays live) — one more step must not break the fused path
    with sharding.plan_scope(_plan(), mesh):
        _train(net, tr, batches, mesh=mesh)
    assert not tr._fused_broken


def test_disabled_knob_makes_scope_inert(monkeypatch):
    monkeypatch.setenv("MXNET_SHARDING", "0")
    mesh = parallel.make_mesh({"mp": 4})
    net, tr = _build(8, 16, seed=15)
    with sharding.plan_scope(_plan(), mesh):
        assert sharding.current_plan() is None
        assert tr._shard_token() is None
        # place_params with explicit args still works (it is just a
        # device_put helper), but nothing reads the plan
        _train(net, tr, _batches(1, 16, 8, 16))
    assert not tr._fused_broken
