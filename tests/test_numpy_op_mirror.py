"""Mirror of the reference numpy-op checklist, one test per reference test
(reference: tests/python/unittest/test_numpy_op.py — 68 test fns). Each test
checks value parity against numpy on the same shapes the reference sweeps
(condensed), plus gradients where the reference uses check_numeric_gradient.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu import np, npx


def close(a, b, rtol=1e-5, atol=1e-5):
    onp.testing.assert_allclose(
        a.asnumpy() if hasattr(a, "asnumpy") else a,
        b.asnumpy() if hasattr(b, "asnumpy") else b, rtol=rtol, atol=atol)


def _rand(*shape):
    return onp.random.RandomState(0).uniform(-2, 2, shape).astype("f")


# ---- creation / ranges ---------------------------------------------------

def test_np_arange():
    close(np.arange(10), onp.arange(10, dtype="f"))
    close(np.arange(2, 10, 2), onp.arange(2, 10, 2, dtype="f"))
    close(np.arange(0.5, 5.5, 0.5), onp.arange(0.5, 5.5, 0.5, dtype="f"))
    a = np.arange(5, dtype="int32")
    assert a.dtype == onp.int32


def test_np_linspace():
    close(np.linspace(0, 10, 21), onp.linspace(0, 10, 21).astype("f"))
    v, step = np.linspace(0, 1, 5, retstep=True)
    assert abs(step - 0.25) < 1e-6
    close(np.linspace(0, 1, 5, endpoint=False),
          onp.linspace(0, 1, 5, endpoint=False).astype("f"))


def test_np_logspace():
    close(np.logspace(0, 3, 4), onp.logspace(0, 3, 4).astype("f"), rtol=1e-4)
    close(np.logspace(0, 2, 5, base=2.0),
          onp.logspace(0, 2, 5, base=2.0).astype("f"), rtol=1e-4)


def test_np_eye():
    close(np.eye(4), onp.eye(4, dtype="f"))
    close(np.eye(3, 5, k=1), onp.eye(3, 5, k=1, dtype="f"))
    close(np.eye(3, 5, k=-1), onp.eye(3, 5, k=-1, dtype="f"))


def test_np_indices():
    got = np.indices((3, 4))
    close(got, onp.indices((3, 4)))


def test_np_meshgrid():
    x, y = np.meshgrid(np.arange(3), np.arange(4))
    ex, ey = onp.meshgrid(onp.arange(3, dtype="f"), onp.arange(4, dtype="f"))
    close(x, ex)
    close(y, ey)
    xi, yi = np.meshgrid(np.arange(3), np.arange(4), indexing="ij")
    exi, eyi = onp.meshgrid(onp.arange(3, dtype="f"),
                            onp.arange(4, dtype="f"), indexing="ij")
    close(xi, exi)
    close(yi, eyi)


def test_np_windows():
    """reference: test_np_windows / src/operator/numpy/np_window_op.cc"""
    for name in ("hanning", "hamming", "blackman"):
        for M in (0, 1, 2, 5, 12):
            close(getattr(np, name)(M), getattr(onp, name)(M).astype("f"),
                  atol=1e-6)


# ---- shape manipulation --------------------------------------------------

def test_np_reshape():
    a = np.arange(24)
    close(a.reshape(2, 3, 4), onp.arange(24, dtype="f").reshape(2, 3, 4))
    close(np.reshape(a, (4, -1)), onp.arange(24, dtype="f").reshape(4, -1))


def test_np_flatten():
    a = np.array(_rand(3, 4))
    close(a.flatten(), _rand(3, 4).flatten())


def test_np_ravel():
    x = _rand(3, 4)
    close(np.ravel(np.array(x)), x.ravel())


def test_np_squeeze():
    x = _rand(1, 3, 1, 4)
    close(np.squeeze(np.array(x)), x.squeeze())
    close(np.squeeze(np.array(x), axis=0), x.squeeze(0))


def test_np_transpose():
    x = _rand(2, 3, 4)
    close(np.transpose(np.array(x)), x.T)
    close(np.transpose(np.array(x), (1, 0, 2)), x.transpose(1, 0, 2))


def test_np_swapaxes():
    x = _rand(2, 3, 4)
    close(np.swapaxes(np.array(x), 0, 2), x.swapaxes(0, 2))


def test_np_moveaxis():
    x = _rand(2, 3, 4)
    close(np.moveaxis(np.array(x), 0, -1), onp.moveaxis(x, 0, -1))
    close(np.moveaxis(np.array(x), [0, 1], [1, 0]),
          onp.moveaxis(x, [0, 1], [1, 0]))


def test_np_broadcast_to():
    x = _rand(1, 3)
    close(np.broadcast_to(np.array(x), (4, 3)), onp.broadcast_to(x, (4, 3)))


def test_np_broadcast_arrays():
    a, b = np.broadcast_arrays(np.array(_rand(1, 3)), np.array(_rand(4, 1)))
    ea, eb = onp.broadcast_arrays(_rand(1, 3), _rand(4, 1))
    close(a, ea)
    close(b, eb)


def test_np_concat():
    x, y = _rand(2, 3), _rand(4, 3)
    close(np.concatenate([np.array(x), np.array(y)], axis=0),
          onp.concatenate([x, y], axis=0))
    z = _rand(2, 3)
    close(np.concatenate([np.array(x), np.array(z)], axis=1),
          onp.concatenate([x, z], axis=1))


def test_np_stack():
    x, y = _rand(2, 3), _rand(2, 3)
    for ax in (0, 1, 2, -1):
        close(np.stack([np.array(x), np.array(y)], axis=ax),
              onp.stack([x, y], axis=ax))


def test_np_vstack():
    x, y = _rand(2, 3), _rand(1, 3)
    close(np.vstack([np.array(x), np.array(y)]), onp.vstack([x, y]))


def test_np_dstack():
    x, y = _rand(2, 3), _rand(2, 3)
    close(np.dstack([np.array(x), np.array(y)]), onp.dstack([x, y]))


def test_np_split():
    x = _rand(6, 4)
    for g, e in zip(np.split(np.array(x), 3), onp.split(x, 3)):
        close(g, e)
    for g, e in zip(np.split(np.array(x), [2, 5]), onp.split(x, [2, 5])):
        close(g, e)


def test_np_hsplit():
    x = _rand(4, 6)
    for g, e in zip(np.hsplit(np.array(x), 2), onp.hsplit(x, 2)):
        close(g, e)


def test_np_vsplit():
    x = _rand(6, 4)
    for g, e in zip(np.vsplit(np.array(x), 3), onp.vsplit(x, 3)):
        close(g, e)


def test_np_tile():
    x = _rand(2, 3)
    close(np.tile(np.array(x), 2), onp.tile(x, 2))
    close(np.tile(np.array(x), (2, 1)), onp.tile(x, (2, 1)))


def test_np_repeat():
    x = _rand(2, 3)
    close(np.repeat(np.array(x), 3), onp.repeat(x, 3))
    close(np.repeat(np.array(x), 2, axis=1), onp.repeat(x, 2, axis=1))


def test_np_roll():
    x = _rand(3, 4)
    close(np.roll(np.array(x), 2), onp.roll(x, 2))
    close(np.roll(np.array(x), 1, axis=0), onp.roll(x, 1, axis=0))


def test_np_rot90():
    x = _rand(3, 4)
    for k in range(4):
        close(np.rot90(np.array(x), k), onp.rot90(x, k))


def test_np_flip():
    x = _rand(3, 4)
    close(np.flip(np.array(x)), onp.flip(x))
    close(np.flip(np.array(x), 0), onp.flip(x, 0))


# ---- math / reductions ---------------------------------------------------

def test_np_sum():
    x = _rand(3, 4)
    close(np.sum(np.array(x)), x.sum())
    close(np.sum(np.array(x), axis=1, keepdims=True),
          x.sum(1, keepdims=True))
    # gradient
    a = np.array(x)
    a.attach_grad()
    with autograd.record():
        y = np.sum(a * a)
    y.backward()
    close(a.grad, 2 * x)


def test_np_prod():
    x = _rand(3, 4)
    close(np.prod(np.array(x)), x.prod(), rtol=1e-4)
    close(np.prod(np.array(x), axis=0), x.prod(0), rtol=1e-4)


def test_np_mean():
    x = _rand(3, 4)
    close(np.mean(np.array(x)), x.mean())
    close(np.mean(np.array(x), axis=1), x.mean(1))


def test_np_moment():
    x = _rand(3, 4)
    close(np.var(np.array(x)), x.var(), rtol=1e-4)
    close(np.std(np.array(x), axis=0), x.std(0), rtol=1e-4)
    close(np.var(np.array(x), axis=1, ddof=1), x.var(1, ddof=1), rtol=1e-4)


def test_np_max_min():
    x = _rand(3, 4)
    close(np.max(np.array(x)), x.max())
    close(np.min(np.array(x), axis=1), x.min(1))


def test_np_argmin_argmax():
    x = _rand(3, 4)
    close(np.argmax(np.array(x)), onp.argmax(x))
    close(np.argmin(np.array(x), axis=1), onp.argmin(x, 1))


def test_np_cumsum():
    x = _rand(3, 4)
    close(np.cumsum(np.array(x)), x.cumsum())
    close(np.cumsum(np.array(x), axis=1), x.cumsum(1))


def test_np_around():
    x = onp.array([0.4, 0.5, 1.5, -0.5, -1.7], "f")
    close(np.around(np.array(x)), onp.around(x))
    close(np.around(np.array(x * 10), decimals=-1), onp.around(x * 10, -1))


def test_np_clip():
    x = _rand(3, 4)
    close(np.clip(np.array(x), -1, 1), x.clip(-1, 1))
    close(np.clip(np.array(x), None, 0.5), x.clip(None, 0.5))


def test_np_diff():
    x = _rand(3, 6)
    close(np.diff(np.array(x)), onp.diff(x))
    close(np.diff(np.array(x), n=2, axis=1), onp.diff(x, 2, 1))


def test_np_unary_funcs():
    x = _rand(3, 4)
    pos = onp.abs(x) + 0.5
    for name in ("negative", "absolute", "sign", "rint", "ceil", "floor",
                 "trunc", "square", "exp", "expm1", "sin", "cos", "tan",
                 "sinh", "cosh", "tanh", "degrees", "radians"):
        close(getattr(np, name)(np.array(x)), getattr(onp, name)(x),
              rtol=1e-4)
    for name in ("sqrt", "cbrt", "log", "log2", "log10", "log1p",
                 "reciprocal"):
        close(getattr(np, name)(np.array(pos)), getattr(onp, name)(pos),
              rtol=1e-4)
    sym = x / 3.0
    for name in ("arcsin", "arccos", "arctan", "arcsinh", "arctanh"):
        close(getattr(np, name)(np.array(sym)), getattr(onp, name)(sym),
              rtol=1e-4, atol=1e-5)


def test_np_binary_funcs():
    x, y = _rand(3, 4), onp.abs(_rand(3, 4)) + 0.5
    for name in ("add", "subtract", "multiply", "divide", "maximum",
                 "minimum", "mod", "fmod", "copysign", "arctan2", "hypot",
                 "logaddexp", "heaviside", "fmax", "fmin"):
        close(getattr(np, name)(np.array(x), np.array(y)),
              getattr(onp, name)(x, y), rtol=1e-4, atol=1e-5)
    close(np.power(np.array(y), np.array(x)), onp.power(y, x), rtol=1e-3)
    # broadcasting
    close(np.add(np.array(x), np.array(y[0])), x + y[0])


def test_np_true_divide():
    a = np.array([4, 6], dtype="int32")
    b = np.array([2, 4], dtype="int32")
    r = np.true_divide(a, b)
    close(r, onp.array([2.0, 1.5]))
    assert r.dtype in (onp.float32, onp.float64)


# ---- linear algebra ------------------------------------------------------

def test_np_dot():
    a, b = _rand(3, 4), _rand(4, 5)
    close(np.dot(np.array(a), np.array(b)), a.dot(b), rtol=1e-4)
    v, w = _rand(4), _rand(4)
    close(np.dot(np.array(v), np.array(w)), v.dot(w), rtol=1e-4)
    close(np.dot(np.array(a), np.array(v[:4])), a.dot(v), rtol=1e-4)


def test_np_inner():
    a, b = _rand(3, 4), _rand(5, 4)
    close(np.inner(np.array(a), np.array(b)), onp.inner(a, b), rtol=1e-4)


def test_np_outer():
    a, b = _rand(3), _rand(4)
    close(np.outer(np.array(a), np.array(b)), onp.outer(a, b), rtol=1e-4)


def test_np_vdot():
    a, b = _rand(3, 4), _rand(3, 4)
    close(np.vdot(np.array(a), np.array(b)), onp.vdot(a, b), rtol=1e-4)


def test_np_tensordot():
    a, b = _rand(2, 3, 4), _rand(3, 4, 5)
    close(np.tensordot(np.array(a), np.array(b)),
          onp.tensordot(a, b), rtol=1e-4)
    c = _rand(4, 3, 2)
    close(np.tensordot(np.array(a), np.array(c), axes=([2, 1], [0, 1])),
          onp.tensordot(a, c, axes=([2, 1], [0, 1])), rtol=1e-4)


def test_np_einsum():
    a, b = _rand(3, 4), _rand(4, 5)
    close(np.einsum("ij,jk->ik", np.array(a), np.array(b)),
          onp.einsum("ij,jk->ik", a, b), rtol=1e-4)
    close(np.einsum("ij->i", np.array(a)), onp.einsum("ij->i", a), rtol=1e-4)
    c = _rand(3, 3)
    close(np.einsum("ii", np.array(c)), onp.einsum("ii", c), rtol=1e-4)


def test_np_trace():
    x = _rand(4, 4)
    close(np.trace(np.array(x)), onp.trace(x), rtol=1e-4)
    y = _rand(3, 4, 4)
    close(np.trace(np.array(y), axis1=1, axis2=2),
          onp.trace(y, axis1=1, axis2=2), rtol=1e-4)


def test_np_tril():
    x = _rand(4, 4)
    close(np.tril(np.array(x)), onp.tril(x))
    close(np.tril(np.array(x), k=1), onp.tril(x, 1))
    close(np.triu(np.array(x), k=-1), onp.triu(x, -1))


def test_np_linalg_norm():
    x = _rand(3, 4)
    close(np.linalg.norm(np.array(x)), onp.linalg.norm(x), rtol=1e-4)
    close(np.linalg.norm(np.array(x), axis=1),
          onp.linalg.norm(x, axis=1), rtol=1e-4)
    close(np.linalg.norm(np.array(x), ord=1, axis=0),
          onp.linalg.norm(x, ord=1, axis=0), rtol=1e-4)


def test_np_linalg_svd():
    x = _rand(4, 3)
    u, s, vt = np.linalg.svd(np.array(x), full_matrices=False)
    recon = u.asnumpy() @ onp.diag(s.asnumpy()) @ vt.asnumpy()
    onp.testing.assert_allclose(recon, x, rtol=1e-4, atol=1e-4)


# ---- indexing / selection ------------------------------------------------

def test_np_take():
    x = _rand(5, 4)
    idx = onp.array([0, 3, 1])
    close(np.take(np.array(x), np.array(idx, dtype="int32")),
          onp.take(x, idx))
    close(np.take(np.array(x), np.array(idx, dtype="int32"), axis=1),
          onp.take(x, idx, axis=1))


def test_np_nonzero():
    x = onp.array([[1, 0, 2], [0, 3, 0]], "f")
    g = np.nonzero(np.array(x))
    e = onp.nonzero(x)
    for gi, ei in zip(g, e):
        close(gi, ei)


def test_np_unique():
    x = onp.array([1, 3, 2, 3, 1, 7], "f")
    close(np.unique(np.array(x)), onp.unique(x))
    vals, counts = np.unique(np.array(x), return_counts=True)
    ev, ec = onp.unique(x, return_counts=True)
    close(vals, ev)
    close(counts, ec)


def test_np_histogram():
    x = _rand(100)
    h, edges = np.histogram(np.array(x), bins=10, range=(-2, 2))
    eh, ee = onp.histogram(x, bins=10, range=(-2, 2))
    close(h, eh)
    close(edges, ee, rtol=1e-5)


def test_npi_boolean_assign():
    """reference: test_npi_boolean_assign / np_boolean_mask_assign.cc"""
    x = _rand(3, 4)
    a = np.array(x)
    mask = a > 0.5
    a[mask] = 0.0
    e = x.copy()
    e[x > 0.5] = 0.0
    close(a, e)
    # tensor-valued assignment
    a2 = np.array(x)
    nsel = int((x > 0.5).sum())
    a2[a2 > 0.5] = np.zeros((nsel,))
    close(a2, e)


def test_np_share_memory():
    a = np.array(_rand(4))
    b = a
    assert np.shares_memory(a, b) or np.may_share_memory(a, b)
    c = np.array(_rand(4))
    assert not np.shares_memory(a, c)


# ---- random --------------------------------------------------------------

def test_np_rand():
    x = np.random.rand(500)
    v = x.asnumpy()
    assert v.shape == (500,) and (v >= 0).all() and (v < 1).all()


def test_np_randint():
    x = np.random.randint(0, 10, size=(1000,))
    v = x.asnumpy()
    assert ((v >= 0) & (v < 10)).all()
    assert len(onp.unique(v)) == 10


def test_np_random():
    u = np.random.uniform(-1, 1, size=(2000,)).asnumpy()
    assert -1 <= u.min() and u.max() < 1 and abs(u.mean()) < 0.1
    n = np.random.normal(3.0, 2.0, size=(4000,)).asnumpy()
    assert abs(n.mean() - 3.0) < 0.2 and abs(n.std() - 2.0) < 0.2
    g = np.random.geometric(0.5, size=(2000,)).asnumpy()
    assert 1.7 < g.mean() < 2.4
    nb = np.random.negative_binomial(5, 0.5, size=(2000,)).asnumpy()
    assert 4.0 < nb.mean() < 6.2
    f = np.random.f(10.0, 20.0, size=(3000,)).asnumpy()
    assert 0.9 < f.mean() < 1.35


def test_np_choice():
    x = np.random.choice(5, size=(1000,))
    v = x.asnumpy()
    assert set(onp.unique(v)).issubset(set(range(5)))
    y = np.random.choice(10, size=(5,), replace=False).asnumpy()
    assert len(onp.unique(y)) == 5


def test_random_seed():
    np.random.seed(42)
    a = np.random.uniform(size=(10,)).asnumpy()
    np.random.seed(42)
    b = np.random.uniform(size=(10,)).asnumpy()
    onp.testing.assert_array_equal(a, b)


# ---- npx extension ops ---------------------------------------------------

def test_npx_relu():
    x = _rand(3, 4)
    close(npx.relu(np.array(x)), onp.maximum(x, 0))


def test_npx_sigmoid():
    x = _rand(3, 4)
    close(npx.sigmoid(np.array(x)), 1 / (1 + onp.exp(-x)), rtol=1e-5)


def test_npx_batch_dot():
    a, b = _rand(2, 3, 4), _rand(2, 4, 5)
    close(npx.batch_dot(np.array(a), np.array(b)),
          onp.einsum("bij,bjk->bik", a, b), rtol=1e-4)
    close(npx.batch_dot(np.array(a), np.array(_rand(2, 5, 4)),
                        transpose_b=True),
          onp.einsum("bij,bkj->bik", a, _rand(2, 5, 4)), rtol=1e-4)


def test_npx_reshape():
    x = _rand(2, 3, 4)
    # npx.reshape supports -2 (copy remaining dims) semantics
    r = npx.reshape(np.array(x), (-2, -2, 4))
    assert r.shape == (2, 3, 4)
    r2 = npx.reshape(np.array(x), (6, -1))
    assert r2.shape == (6, 4)


def test_npx_slice():
    x = _rand(4, 5)
    close(npx.slice(np.array(x), begin=(1, 0), end=(3, 4)), x[1:3, 0:4])


def test_np_builtin_op_signature():
    """Ops accept out=/where= keywords like the reference's generated
    signatures (reference: test_np_builtin_op_signature)."""
    x = np.array(_rand(3))
    out = np.zeros((3,))
    r = np.add(x, x, out=out)
    assert r is out
    close(out, 2 * x.asnumpy())
    r2 = np.sin(x, out=out)
    assert r2 is out
