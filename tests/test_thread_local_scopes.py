"""Per-thread scope isolation (reference:
tests/python/unittest/test_thread_local.py — Context, AttrScope,
NameManager must not leak across threads)."""
import threading

import mxnet_tpu as mx
from mxnet_tpu import context, sym
from mxnet_tpu.attribute import AttrScope
from mxnet_tpu.name import NameManager, Prefix


def test_context_scope_is_thread_local():
    results = {}

    def worker():
        # the spawned thread sees the default, not the main thread's with
        results["inner"] = context.current_context().device_type

    with context.Context("cpu", 1):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
        results["outer"] = context.current_context()
    assert results["outer"].device_type == "cpu"
    assert results["outer"].device_id == 1
    assert results["inner"] in ("cpu", "tpu", "gpu")


def test_attr_scope_is_thread_local():
    seen = {}

    def worker():
        s = sym.Variable("b")
        seen["thread_attrs"] = s.attr("group")

    with AttrScope(group="4"):
        a = sym.Variable("a")
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    assert a.attr("group") == "4"
    assert seen["thread_attrs"] is None  # no leak into the worker


def test_name_manager_is_thread_local():
    out = {}

    def worker():
        with NameManager():
            s = sym.FullyConnected(sym.Variable("d"), num_hidden=1)
            out["thread_name"] = s.name

    with Prefix("main_"):
        s_main = sym.FullyConnected(sym.Variable("d"), num_hidden=1)
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    assert s_main.name.startswith("main_")
    assert not out["thread_name"].startswith("main_")
