"""The reference user workflow end to end across subsystems: Module.fit
training → save_checkpoint (symbol.json + arg:/aux: params) → reload
three independent ways (Module.load, C-API predictor, amalgamated
single-file bundle) — all four prediction paths must agree exactly
(reference: example/image-classification save/deploy flow +
c_predict_api + amalgamation)."""
import os
import subprocess
import sys

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import nd, sym, io
from mxnet_tpu.module import Module
from mxnet_tpu.test_utils import assert_almost_equal, with_seed


@with_seed(21)
def test_train_checkpoint_predict_amalgamate_agree(tmp_path):
    rs = onp.random.RandomState(0)
    X = rs.randn(192, 10).astype("f")
    y = (X[:, :5].sum(1) > X[:, 5:].sum(1)).astype("f")

    # 1. train through the symbolic path (BN included: aux states must
    # survive every reload below)
    data = sym.Variable("data")
    net = sym.FullyConnected(data, name="wf_fc1", num_hidden=16)
    net = sym.BatchNorm(net, name="wf_bn", fix_gamma=False)
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, name="wf_fc2", num_hidden=2)
    out = sym.SoftmaxOutput(net, sym.Variable("softmax_label"),
                            name="softmax")
    mod = Module(out, context=mx.cpu())
    it = io.NDArrayIter(X, y, batch_size=64, shuffle=True)
    mod.fit(it, num_epoch=6, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9})
    acc = dict(mod.score(io.NDArrayIter(X, y, batch_size=64), "acc"))
    assert acc["accuracy"] > 0.85, acc

    # 2. checkpoint in the reference layout
    prefix = str(tmp_path / "wf")
    mod.save_checkpoint(prefix, 6)
    assert os.path.isfile(prefix + "-symbol.json")
    assert os.path.isfile(prefix + "-0006.params")

    xq = X[:8]
    mod_batch = io.DataBatch(data=[nd.array(xq)])
    mod.forward(mod_batch, is_train=False)
    want = mod.get_outputs()[0].asnumpy()

    # 3a. reload through Module.load
    mod2 = Module.load(prefix, 6, context=mx.cpu())
    mod2.bind(data_shapes=[("data", (8, 10))], for_training=False,
              label_shapes=None)
    mod2.init_params()  # applies the checkpoint params loaded above
    mod2.forward(mod_batch, is_train=False)
    assert_almost_equal(mod2.get_outputs()[0].asnumpy(), want, rtol=1e-5,
                        atol=1e-6)

    # 3b. reload through the C-predictor surface
    from mxnet_tpu.c_bridge import CPredictor

    with open(prefix + "-symbol.json") as f:
        sym_json = f.read()
    with open(prefix + "-0006.params", "rb") as f:
        params_bytes = f.read()
    pred = CPredictor(sym_json, params_bytes,
                      input_shapes={"data": (8, 10)})
    pred.set_input("data", onp.ascontiguousarray(xq).tobytes())
    pred.forward()
    got_c = onp.frombuffer(pred.output_bytes(0), "f").reshape(8, 2)
    assert_almost_equal(got_c, want, rtol=1e-5, atol=1e-6)

    # 3c. reload through the amalgamated single-file bundle, run where
    # mxnet_tpu is NOT importable
    from mxnet_tpu.tools.amalgamate import amalgamate

    loaded = nd.load(prefix + "-0006.params")
    src = amalgamate(sym_json, {k: v.asnumpy() for k, v in loaded.items()})
    (tmp_path / "wf_bundle.py").write_text(src)
    drive = tmp_path / "drive.py"
    drive.write_text(
        "import sys, numpy as np\n"
        "import wf_bundle\n"
        "x = np.load(sys.argv[1])\n"
        "np.save(sys.argv[2], wf_bundle.predict(x))\n"
        "assert 'mxnet_tpu' not in sys.modules\n")
    onp.save(tmp_path / "xq.npy", xq)
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, str(drive), str(tmp_path / "xq.npy"),
         str(tmp_path / "out.npy")],
        cwd=tmp_path, env=env, capture_output=True, text=True,
        timeout=240)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    got_bundle = onp.load(tmp_path / "out.npy")
    assert_almost_equal(got_bundle, want, rtol=1e-5, atol=1e-6)
