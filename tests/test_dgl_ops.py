"""DGL graph-sampling op tests (reference: src/operator/contrib/
dgl_graph.cc + tests/python/unittest/test_dgl_graph.py)."""
import numpy as onp

from mxnet_tpu import nd
from mxnet_tpu.ndarray import sparse as sp
from mxnet_tpu.ndarray.contrib import (
    edge_id, dgl_adjacency, dgl_subgraph, dgl_graph_compact,
    dgl_csr_neighbor_uniform_sample, dgl_csr_neighbor_non_uniform_sample)


def _toy_graph():
    """5-vertex ring + chords; values are edge ids 1..nnz (the layout
    the reference samplers expect)."""
    dense = onp.array([
        [0, 1, 0, 0, 1],
        [1, 0, 1, 0, 0],
        [0, 1, 0, 1, 0],
        [0, 0, 1, 0, 1],
        [1, 0, 0, 1, 0]], onp.float32)
    indptr = [0]
    indices = []
    data = []
    eid = 1
    for r in range(5):
        for c in range(5):
            if dense[r, c]:
                indices.append(c)
                data.append(eid)
                eid += 1
        indptr.append(len(indices))
    return sp.CSRNDArray(onp.asarray(data, "f"),
                         onp.asarray(indices, onp.int64),
                         onp.asarray(indptr, onp.int64), (5, 5))


def test_edge_id():
    g = _toy_graph()
    out = edge_id(g, nd.array([0, 0, 2]), nd.array([1, 2, 3])).asnumpy()
    assert out[0] == 1     # edge 0->1 is the first stored edge
    assert out[1] == -1    # 0->2 absent
    assert out[2] > 0      # 2->3 exists


def test_dgl_adjacency():
    g = _toy_graph()
    adj = dgl_adjacency(g)
    assert adj.stype == "csr"
    onp.testing.assert_array_equal(adj.data.asnumpy(),
                                   onp.ones(g.nnz, "f"))
    onp.testing.assert_array_equal(adj.indices.asnumpy(),
                                   g.indices.asnumpy())


def test_dgl_subgraph_induced():
    g = _toy_graph()
    (sub,) = dgl_subgraph(g, nd.array([0, 1, 4]))
    d = sub.todense().asnumpy()
    # induced on {0,1,4}: edges 0-1, 0-4, 1-0, 4-0 survive; 4-3, 1-2 drop
    expect = onp.array([[0, 1, 1],
                        [1, 0, 0],
                        [1, 0, 0]], "f")
    onp.testing.assert_array_equal((d > 0).astype("f"), expect)


def test_dgl_subgraph_mapping_edge_ids():
    g = _toy_graph()
    sub, mapping = dgl_subgraph(g, nd.array([0, 1]),
                                return_mapping=True)
    md = mapping.todense().asnumpy()
    # value = parent edge id + 1; edge 0->1 has parent edge index 0
    assert md[0, 1] == 1.0
    assert md[1, 0] >= 1.0


def test_uniform_sample_layout():
    g = _toy_graph()
    verts, subg = dgl_csr_neighbor_uniform_sample(
        g, nd.array([0]), num_hops=1, num_neighbor=2,
        max_num_vertices=10, seed=0)
    v = verts.asnumpy().astype(int)
    count = v[-1]
    assert 1 <= count <= 9
    ids = v[:count]
    assert ids[0] == 0  # seeds come first
    assert (v[count:-1] == -1).all()  # padding
    assert subg.shape == (count, count)


def test_uniform_sample_respects_max_vertices():
    g = _toy_graph()
    verts, subg = dgl_csr_neighbor_uniform_sample(
        g, nd.array([0, 1, 2, 3, 4]), num_hops=3, num_neighbor=5,
        max_num_vertices=4, seed=0)
    v = verts.asnumpy().astype(int)
    assert v[-1] <= 3
    assert subg.shape[0] == v[-1]


def test_non_uniform_sample_probability_zero_excluded():
    g = _toy_graph()
    # probability 0 for all but vertices 0,1 -> sampled neighbors of 0
    # can only be 1 (its other neighbor, 4, has p=0)
    prob = nd.array([1.0, 1.0, 0.0, 0.0, 0.0])
    verts, subg = dgl_csr_neighbor_non_uniform_sample(
        g, prob, nd.array([0]), num_hops=1, num_neighbor=2,
        max_num_vertices=10, seed=0)
    v = verts.asnumpy().astype(int)
    ids = set(v[:v[-1]].tolist())
    assert ids <= {0, 1}


def test_graph_compact():
    g = _toy_graph()
    verts, subg = dgl_csr_neighbor_uniform_sample(
        g, nd.array([0]), num_hops=1, num_neighbor=2,
        max_num_vertices=10, seed=0)
    count = int(verts.asnumpy()[-1])
    (compact,) = dgl_graph_compact(subg, verts, graph_sizes=[count])
    assert compact.shape == (count, count)
