"""Module API, second suite (reference:
tests/python/unittest/test_module.py, 23 fns — lifecycle guards,
set/get params, predict, checkpoint epochs, reshape, fit with eval)."""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym, io
from mxnet_tpu.module import Module
from mxnet_tpu.test_utils import assert_almost_equal, with_seed


def _mlp(prefix="m2"):
    data = sym.Variable("data")
    fc1 = sym.FullyConnected(data, name=f"{prefix}_fc1", num_hidden=8)
    act = sym.Activation(fc1, act_type="relu")
    fc2 = sym.FullyConnected(act, name=f"{prefix}_fc2", num_hidden=2)
    return sym.SoftmaxOutput(fc2, sym.Variable("softmax_label"),
                             name="softmax")


def _data(n=64, seed=0):
    rs = onp.random.RandomState(seed)
    X = rs.randn(n, 6).astype("f")
    y = (X.sum(1) > 0).astype("f")
    return X, y


def _fit_module(prefix="m2", epochs=3, seed=0):
    X, y = _data(seed=seed)
    mod = Module(_mlp(prefix), context=mx.cpu())
    it = io.NDArrayIter(X, y, batch_size=32)
    # Xavier + a healthy lr: fit's default Uniform(0.01) init plus the
    # reference's rescale_grad=1/batch makes convergence glacial
    mod.fit(it, num_epoch=epochs, optimizer="sgd",
            initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9})
    return mod, X, y


def test_lifecycle_guards():
    mod = Module(_mlp("lg"), context=mx.cpu())
    with pytest.raises(AssertionError):
        mod.forward(io.DataBatch(data=[nd.zeros((2, 6))]))
    mod.bind(data_shapes=[("data", (2, 6))],
             label_shapes=[("softmax_label", (2,))])
    with pytest.raises(AssertionError):  # params not initialized yet
        mod.forward(io.DataBatch(data=[nd.zeros((2, 6))]))
    mod.init_params()
    mod.forward(io.DataBatch(data=[nd.zeros((2, 6))]), is_train=False)
    assert mod.get_outputs()[0].shape == (2, 2)


def test_get_set_params_roundtrip():
    mod, X, _ = _fit_module("gs")
    args, auxs = mod.get_params()
    assert args and all(hasattr(v, "asnumpy") for v in args.values())
    mod2 = Module(_mlp("gs"), context=mx.cpu())
    mod2.bind(data_shapes=[("data", (32, 6))],
              label_shapes=[("softmax_label", (32,))])
    mod2.set_params(args, auxs)
    b = io.DataBatch(data=[nd.array(X[:32])])
    mod.forward(b, is_train=False)
    mod2.forward(b, is_train=False)
    assert_almost_equal(mod2.get_outputs()[0].asnumpy(),
                        mod.get_outputs()[0].asnumpy(), rtol=1e-6)


def test_set_params_rejects_missing():
    mod = Module(_mlp("sm"), context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 6))],
             label_shapes=[("softmax_label", (4,))])
    with pytest.raises(RuntimeError, match="not presented"):
        mod.set_params({}, {}, allow_missing=False)


@with_seed(4)
def test_predict_returns_concatenated():
    mod, X, y = _fit_module("pr")
    out = mod.predict(io.NDArrayIter(X, y, batch_size=16))
    assert out.shape == (64, 2)
    probs = out.asnumpy()
    assert onp.allclose(probs.sum(axis=1), 1.0, atol=1e-4)


@with_seed(4)
def test_score_accuracy_reasonable():
    mod, X, y = _fit_module("sc", epochs=10)
    acc = dict(mod.score(io.NDArrayIter(X, y, batch_size=32), "acc"))
    assert acc["accuracy"] > 0.8


def test_checkpoint_epoch_naming(tmp_path):
    mod, _, _ = _fit_module("ck")
    prefix = str(tmp_path / "model")
    mod.save_checkpoint(prefix, 3)
    mod.save_checkpoint(prefix, 12)
    assert os.path.isfile(prefix + "-0003.params")
    assert os.path.isfile(prefix + "-0012.params")
    assert os.path.isfile(prefix + "-symbol.json")
    m2 = Module.load(prefix, 12, context=mx.cpu())
    m2.bind(data_shapes=[("data", (4, 6))], for_training=False)
    m2.init_params()
    # the round trip must restore the TRAINED params, not re-init
    want_args, _ = mod.get_params()
    got_args, _ = m2.get_params()
    for k, v in want_args.items():
        assert_almost_equal(got_args[k].asnumpy(), v.asnumpy(),
                            rtol=1e-6, atol=1e-7)


def test_executor_reshape_through_module():
    mod, X, _ = _fit_module("rs")
    # different batch size at inference: forward re-specializes
    b = io.DataBatch(data=[nd.array(X[:10])])
    mod.forward(b, is_train=False)
    assert mod.get_outputs()[0].shape == (10, 2)
    b = io.DataBatch(data=[nd.array(X[:32])])
    mod.forward(b, is_train=False)
    assert mod.get_outputs()[0].shape == (32, 2)


def test_fit_with_eval_data_and_callbacks():
    X, y = _data(seed=7)
    Xe, ye = _data(n=32, seed=8)
    seen = {"epochs": 0, "batches": 0}

    def epoch_cb(epoch, sym_, arg, aux):
        seen["epochs"] += 1

    def batch_cb(param):
        seen["batches"] += 1

    mod = Module(_mlp("cb"), context=mx.cpu())
    mod.fit(io.NDArrayIter(X, y, batch_size=32),
            eval_data=io.NDArrayIter(Xe, ye, batch_size=32),
            num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05},
            epoch_end_callback=epoch_cb, batch_end_callback=batch_cb)
    assert seen["epochs"] == 2
    assert seen["batches"] == 4  # 2 batches/epoch x 2 epochs


def test_output_and_data_names():
    mod = Module(_mlp("nm"), context=mx.cpu())
    assert mod.data_names == ["data"]
    assert mod.label_names == ["softmax_label"]
    assert mod.output_names == ["softmax_output"]


def test_inference_only_module_no_labels():
    data = sym.Variable("data")
    out = sym.FullyConnected(data, name="io_fc", num_hidden=3)
    mod = Module(out, label_names=[], context=mx.cpu())
    mod.bind(data_shapes=[("data", (5, 4))], for_training=False)
    mod.init_params()
    mod.forward(io.DataBatch(data=[nd.ones((5, 4))]), is_train=False)
    assert mod.get_outputs()[0].shape == (5, 3)


def test_init_optimizer_guard_and_force():
    mod, X, y = _fit_module("op")
    opt_before = mod._optimizer
    # re-init WITHOUT force: guarded no-op — same optimizer object
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.9})
    assert mod._optimizer is opt_before
    # WITH force: a fresh optimizer carrying the new hyperparams
    mod.init_optimizer(optimizer="sgd", force_init=True,
                       optimizer_params={"learning_rate": 0.9})
    assert mod._optimizer is not opt_before
    assert mod._optimizer.lr == 0.9
    b = io.NDArrayIter(X, y, batch_size=32)
    for batch in b:
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
        break


@with_seed(11)
def test_bucketing_module_multiple_buckets():
    from mxnet_tpu.module import BucketingModule

    def gen(bucket_key):
        # param shapes must be bucket-INDEPENDENT (like variable-length
        # RNN unrolls): reduce over the bucket-sized axis before the FC
        data = sym.Variable("data")
        pooled = sym.mean(data, axis=1, keepdims=True)
        fc = sym.FullyConnected(pooled, name="bk_fc", num_hidden=2)
        out = sym.SoftmaxOutput(fc, sym.Variable("softmax_label"),
                                name="softmax")
        return out, ("data",), ("softmax_label",)

    bm = BucketingModule(gen, default_bucket_key=8, context=mx.cpu())
    bm.bind(data_shapes=[("data", (4, 8))],
            label_shapes=[("softmax_label", (4,))])
    bm.init_params()
    bm.init_optimizer(optimizer="sgd",
                      optimizer_params={"learning_rate": 0.1})
    rs = onp.random.RandomState(0)
    for key, width in ((8, 8), (4, 4), (8, 8)):
        batch = io.DataBatch(
            data=[nd.array(rs.rand(4, width).astype("f"))],
            label=[nd.array(rs.randint(0, 2, 4).astype("f"))],
            bucket_key=key,
            provide_data=[io.DataDesc("data", (4, width))],
            provide_label=[io.DataDesc("softmax_label", (4,))])
        bm.forward(batch, is_train=True)
        bm.backward()
        bm.update()
    assert bm.get_outputs()[0].shape == (4, 2)
