"""Autotune subsystem (round 24): decision registry, TuningRecord
lifecycle (memory/disk/remote tiers), trial overrides, the
consult-before-heuristic hooks in the fusion cost model and quantize
lowering, salt coexistence with pre-autotune fingerprints, and the
two-process fleet-sharing acceptance path."""
import json
import os

import pytest

from mxnet_tpu import autotune
from mxnet_tpu.autotune import records, registry
from mxnet_tpu.base import MXNetError

DEC = "unit.synthetic"


def _declare():
    return autotune.declare_decision(
        DEC, candidates=(1, 2, 3), default=2, key_doc="(backend,)")


@pytest.fixture
def tuned(tmp_path, monkeypatch):
    """Isolated record dir + clean counters; mode = consult."""
    monkeypatch.setenv("MXNET_AUTOTUNE_DIR", str(tmp_path / "atr"))
    monkeypatch.setenv("MXNET_AUTOTUNE", "consult")
    autotune.reset_autotune_state()
    _declare()
    yield autotune
    autotune.reset_autotune_state()


# ---------------------------------------------------------------------------
# registry

def test_declare_returns_default_and_is_idempotent(tuned):
    assert _declare() == 2  # same declaration: fine, returns default
    point = autotune.get_point(DEC)
    assert point.candidates == (1, 2, 3) and point.default == 2


def test_conflicting_redeclaration_raises(tuned):
    with pytest.raises(MXNetError, match="already declared"):
        autotune.declare_decision(DEC, candidates=(1, 2), default=1)


def test_builtin_decision_points_cataloged(tuned):
    names = autotune.decision_points()
    assert list(names) == sorted(names)
    for expect in ("fusion.min_cluster", "fusion.attn_compute_bound_seq",
                   "fusion.elementwise_bandwidth_log2",
                   "quantize.lowering"):
        assert expect in names


def test_unknown_point_raises(tuned):
    with pytest.raises(MXNetError, match="unknown decision"):
        autotune.get_point("no.such.decision")


# ---------------------------------------------------------------------------
# mode knob

def test_mode_values(tuned, monkeypatch):
    assert autotune.mode() == "consult"
    for raw, want in (("0", "0"), ("off", "0"), ("false", "0"),
                      ("tune", "tune"), ("CONSULT", "consult")):
        monkeypatch.setenv("MXNET_AUTOTUNE", raw)
        assert autotune.mode() == want
    monkeypatch.setenv("MXNET_AUTOTUNE", "bogus")
    with pytest.raises(MXNetError, match="MXNET_AUTOTUNE"):
        autotune.mode()


def test_mode_off_short_circuits_lookup(tuned, monkeypatch):
    records.store_record(DEC, ("cpu",), 3)
    monkeypatch.setenv("MXNET_AUTOTUNE", "0")
    assert autotune.lookup(DEC, ("cpu",)) is None
    c = autotune.counters()
    assert c["lookups"] == 1 and c["hits"] == 0
    # and the salt provider contributes nothing when off
    assert autotune.autotune_salt() == ()


def test_tune_requires_tune_mode(tuned):
    with pytest.raises(MXNetError, match="MXNET_AUTOTUNE=tune"):
        autotune.tune(DEC, ("cpu",), lambda choice: (lambda: 1.0))


# ---------------------------------------------------------------------------
# record lifecycle: memory / disk tiers

def test_store_then_consult_and_disk_roundtrip_bitwise(tuned):
    rec = records.store_record(DEC, ("cpu",), 3,
                               extra={"speedup": 1.25, "won": True})
    fp = records.record_fingerprint(DEC, ("cpu",))
    path = os.path.join(records.records_dir(), fp + ".atr")
    with open(path, "rb") as f:
        blob1 = f.read()
    assert json.loads(blob1) == rec  # what's on disk IS the record
    # storing the same record again is byte-identical (sorted keys,
    # fixed indent — the file format is canonical)
    records.store_record(DEC, ("cpu",), 3,
                         extra={"speedup": 1.25, "won": True})
    with open(path, "rb") as f:
        assert f.read() == blob1
    assert autotune.lookup(DEC, ("cpu",)) == 3
    assert autotune.counters()["hits"] == 1


def test_records_survive_restart(tuned):
    records.store_record(DEC, ("cpu",), 1)
    # "restart": drop every in-memory tier, keep the disk files
    records.reset_record_state()
    assert records.consult(DEC, ("cpu",)) == 1
    assert autotune.counters()["record_load"] == 1


def test_store_rejects_choice_outside_candidates(tuned):
    with pytest.raises(MXNetError, match="outside the declared"):
        records.store_record(DEC, ("cpu",), 99)


def test_unfingerprintable_key_is_heuristic_only(tuned):
    key = (object(),)  # repr carries a memory address: not stable
    assert records.record_fingerprint(DEC, key) is None
    assert records.store_record(DEC, key, 1) is None
    assert records.consult(DEC, key) is None


# ---------------------------------------------------------------------------
# corrupt / drifted records: miss + removal, never a crash

def _plant(tuned, blob):
    fp = records.record_fingerprint(DEC, ("cpu",))
    d = records.records_dir()
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, fp + ".atr")
    with open(path, "wb") as f:
        f.write(blob)
    return path


def test_corrupt_record_is_miss_and_removed(tuned):
    path = _plant(tuned, b"{not json")
    assert records.consult(DEC, ("cpu",)) is None
    assert not os.path.exists(path), "corrupt file must be removed"
    assert autotune.counters()["record_corrupt"] == 1


def test_version_drifted_record_is_miss_and_removed(tuned):
    stale = {"version": 0, "decision": DEC, "key": "('cpu',)",
             "choice": 3}
    path = _plant(tuned, json.dumps(stale).encode())
    assert records.consult(DEC, ("cpu",)) is None
    assert not os.path.exists(path)
    assert autotune.counters()["record_corrupt"] == 1


def test_out_of_candidates_record_is_miss_and_removed(tuned):
    bad = {"version": records.RECORD_VERSION, "decision": DEC,
           "key": "('cpu',)", "choice": 99}
    path = _plant(tuned, json.dumps(bad).encode())
    assert records.consult(DEC, ("cpu",)) is None
    assert not os.path.exists(path)


def test_corrupt_record_never_breaks_decide(tuned):
    """A consult inside the fusion cost model degrades to the heuristic
    when the stored record is garbage — the decision still returns."""
    from mxnet_tpu.kernels import cost_model

    fp = records.record_fingerprint("fusion.min_cluster", ("cpu",))
    d = records.records_dir()
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, fp + ".atr"), "wb") as f:
        f.write(b"\x00garbage\xff")
    dec = cost_model.decide("elementwise", 3, out_shape=(8, 8),
                            backend="cpu")
    assert dec.fuse  # heuristic default (min_cluster=2) applied
    assert autotune.counters()["record_corrupt"] == 1


# ---------------------------------------------------------------------------
# trial overrides

def test_trial_overrides_and_shadows_stored_record(tuned):
    records.store_record(DEC, ("cpu",), 1)
    with records.trial(DEC, ("cpu",), 3):
        assert records.trial_active()
        assert autotune.lookup(DEC, ("cpu",)) == 3
        # the salt carries the trial, marked distinctly from a record
        entries = records.active_entries()
        assert any(c == "trial:3" for _, _, c in entries), entries
    assert autotune.lookup(DEC, ("cpu",)) == 1
    assert not records.trial_active()


def test_nested_trial_same_key_raises(tuned):
    with records.trial(DEC, ("cpu",), 1):
        with pytest.raises(MXNetError, match="nested trial"):
            with records.trial(DEC, ("cpu",), 2):
                pass
    assert not records.trial_active()  # cleanly unwound


# ---------------------------------------------------------------------------
# consult hooks in the shipped policies

def test_decide_consults_min_cluster_record(tuned):
    from mxnet_tpu.kernels import cost_model

    assert cost_model.decide("elementwise", 3, out_shape=(8, 8),
                             backend="cpu").fuse
    with records.trial("fusion.min_cluster", ("cpu",), 4):
        dec = cost_model.decide("elementwise", 3, out_shape=(8, 8),
                                backend="cpu")
    assert not dec.fuse and dec.reason == "too_small"


def test_decide_consults_attention_bound_by_feat_bucket(tuned):
    from mxnet_tpu.kernels import cost_model

    kw = dict(out_shape=(4, 64, 48), backend="cpu",
              score_shape=(4, 64, 64))
    # default bound 64: seq 64 is compute-bound -> unfused
    assert cost_model.decide("attention", 5, **kw).reason == \
        "compute_bound_attention"
    # a record for THIS feat bucket (48 -> 64) flips it
    with records.trial("fusion.attn_compute_bound_seq",
                       ("cpu", 64), 4096):
        assert cost_model.decide("attention", 5, **kw).fuse
    # a record for a DIFFERENT bucket does not
    with records.trial("fusion.attn_compute_bound_seq",
                       ("cpu", 128), 4096):
        assert not cost_model.decide("attention", 5, **kw).fuse


def test_decide_consults_elementwise_bandwidth_cap(tuned):
    from mxnet_tpu.kernels import cost_model

    big = (2048, 4096)  # 2**23 elements: above the default 2**22 cap
    assert cost_model.decide("elementwise", 7, out_shape=big,
                             backend="cpu").reason == "bandwidth_bound"
    with records.trial("fusion.elementwise_bandwidth_log2",
                       ("cpu",), 24):
        assert cost_model.decide("elementwise", 7, out_shape=big,
                                 backend="cpu").fuse


def test_quantize_lowering_consults_record(tuned, monkeypatch):
    from mxnet_tpu.ndarray import ops_quant

    monkeypatch.delenv("MXNET_QUANTIZE_LOWERING", raising=False)
    heuristic = ops_quant.lowering()  # dequant on cpu
    assert heuristic == "dequant"
    with records.trial("quantize.lowering", ("cpu",), "native"):
        assert ops_quant.lowering() == "native"
    # an explicit env choice always beats the record
    monkeypatch.setenv("MXNET_QUANTIZE_LOWERING", "dequant")
    with records.trial("quantize.lowering", ("cpu",), "native"):
        assert ops_quant.lowering() == "dequant"


# ---------------------------------------------------------------------------
# salt coexistence: record-absent fingerprints stay byte-identical

def test_autotune_salt_declared_but_inactive_keeps_fingerprint(tuned):
    from mxnet_tpu import artifact

    key = ("unit", "coexist")
    bare = artifact.CompiledArtifact("unit_autotune", key).fingerprint
    declared = artifact.CompiledArtifact(
        "unit_autotune", key, salts=("autotune",)).fingerprint
    # no active record: adding the salt to the declaration must NOT
    # move the fingerprint (warm pre-autotune caches stay warm)
    assert declared == bare

    records.store_record(DEC, ("cpu",), 3)
    tuned_fp = artifact.CompiledArtifact(
        "unit_autotune", key, salts=("autotune",)).fingerprint
    assert tuned_fp != bare  # a live record separates the executables
    undeclared = artifact.CompiledArtifact(
        "unit_autotune", key).fingerprint
    assert undeclared == bare  # undeclared artifacts unaffected


def test_salt_content_and_graph_opt_tag_form(tuned):
    assert autotune.autotune_salt() == ()
    records.store_record(DEC, ("cpu",), 3)
    salt = autotune.autotune_salt()
    assert salt[0] == "autotune" and salt[1] == records.RECORD_VERSION
    assert (DEC, "('cpu',)", "3") in salt[2:]
    # dropping the directory empties the salt again (scan authority)
    for fn in os.listdir(records.records_dir()):
        os.remove(os.path.join(records.records_dir(), fn))
    records.reset_record_state()
    assert autotune.autotune_salt() == ()


# ---------------------------------------------------------------------------
# tuner: sweep, no-win pin, budget, fault seam

def _fake_measure(costs):
    """make_measure returning constant synthetic 'timings': choice ->
    seconds per window (None = the heuristic default workload)."""
    def factory(choice):
        cost = costs[choice]
        return lambda: cost
    return factory


def test_tune_persists_winner_and_consults_back(tuned, monkeypatch):
    monkeypatch.setenv("MXNET_AUTOTUNE", "tune")
    rec = autotune.tune(DEC, ("cpu",),
                        _fake_measure({None: 1.0, 1: 1.0, 2: 1.0,
                                       3: 0.5}),
                        pairs=2)
    assert rec["choice"] == 3 and rec["won"] is True
    assert rec["speedup"] == pytest.approx(2.0)
    monkeypatch.setenv("MXNET_AUTOTUNE", "consult")
    assert autotune.lookup(DEC, ("cpu",)) == 3
    c = autotune.counters()
    assert c["measurements"] == 3 and c["wins"] == 1


def test_tune_no_win_pins_default_identity(tuned, monkeypatch):
    monkeypatch.setenv("MXNET_AUTOTUNE", "tune")
    rec = autotune.tune(DEC, ("cpu",),
                        _fake_measure({None: 1.0, 1: 1.01, 2: 1.0,
                                       3: 1.005}),
                        pairs=2)
    # nothing beat the default by min_speedup: the DEFAULT is pinned
    # with identity speedup so consults hit without changing behavior
    assert rec["choice"] == 2 and rec["won"] is False
    assert rec["speedup"] == 1.0
    assert autotune.counters()["wins"] == 0
    assert records.consult(DEC, ("cpu",)) == 2


def test_tune_budget_stops_between_candidates(tuned, monkeypatch):
    import time as _time

    monkeypatch.setenv("MXNET_AUTOTUNE", "tune")

    def factory(choice):
        def window():
            _time.sleep(0.02)
            return 1.0
        return window

    rec = autotune.tune(DEC, ("cpu",), factory, pairs=1, budget_ms=1)
    # the first candidate always completes; the budget stops the rest
    assert rec["budget_stopped"] is True
    assert len(rec["measured"]) == 1


def test_tune_fault_seam_skips_candidate(tuned, monkeypatch):
    from mxnet_tpu.resilience import faults

    monkeypatch.setenv("MXNET_AUTOTUNE", "tune")
    with faults.inject("autotune_measure", at=1):
        rec = autotune.tune(DEC, ("cpu",),
                            _fake_measure({None: 1.0, 1: 1.0, 2: 1.0,
                                           3: 0.5}),
                            pairs=2)
    # candidate 1 was skipped by the injected fault; the sweep degraded
    # to the remaining candidates instead of crashing
    assert rec["skipped"] == [1]
    assert [m["choice"] for m in rec["measured"]] == [2, 3]
    assert rec["choice"] == 3
    assert autotune.counters()["measure_failures"] == 1


def test_tune_all_candidates_failing_raises(tuned, monkeypatch):
    from mxnet_tpu.resilience import faults

    monkeypatch.setenv("MXNET_AUTOTUNE", "tune")
    with faults.inject("autotune_measure", every=1, times=3):
        with pytest.raises(MXNetError, match="measured no candidate"):
            autotune.tune(DEC, ("cpu",),
                          _fake_measure({None: 1.0, 1: 1.0, 2: 1.0,
                                         3: 1.0}))


# ---------------------------------------------------------------------------
# fleet sharing: one replica tunes, the fleet consults with zero
# measurements (the round-20 remote artifact tier verbatim)

_CHILD = """
import json, os
from mxnet_tpu import autotune
from mxnet_tpu.autotune import records
autotune.declare_decision(
    "unit.synthetic", candidates=(1, 2, 3), default=2,
    key_doc="(backend,)")
"""


def test_fleet_record_sharing_zero_measurements(
        forced_device_subprocess, tmp_path):
    """Acceptance: replica A tunes and publishes; replica B (fresh dir,
    same remote) consults A's record having measured NOTHING, and the
    record is written through to B's disk for its next restart."""
    remote = {"MXNET_ARTIFACT_REMOTE": "file://" + str(tmp_path / "fleet")}
    a = forced_device_subprocess(_CHILD + """
rec = autotune.tune(
    "unit.synthetic", ("cpu",),
    lambda choice: (lambda: {None: 1.0, 1: 1.0, 2: 1.0, 3: 0.5}[choice]),
    pairs=2)
print(json.dumps({"choice": rec["choice"], "won": rec["won"],
                  "counters": autotune.counters()}))
""", env=dict(remote, MXNET_AUTOTUNE="tune",
              MXNET_AUTOTUNE_DIR=str(tmp_path / "atr_a")))
    assert a["choice"] == 3 and a["won"] is True
    assert a["counters"]["measurements"] == 3

    b_dir = str(tmp_path / "atr_b")
    b = forced_device_subprocess(_CHILD + """
choice = autotune.lookup("unit.synthetic", ("cpu",))
on_disk = sorted(os.listdir(records.records_dir()))
print(json.dumps({"choice": choice, "counters": autotune.counters(),
                  "disk": on_disk}))
""", env=dict(remote, MXNET_AUTOTUNE="consult",
              MXNET_AUTOTUNE_DIR=b_dir))
    assert b["choice"] == 3, "B must consult A's tuned record"
    assert b["counters"]["measurements"] == 0, \
        "the fleet consumes records WITHOUT measuring"
    assert b["counters"]["hits"] == 1
    assert len(b["disk"]) == 1, "remote hit must write through to disk"

    # restart of B: the write-through serves from disk, no remote
    b2 = forced_device_subprocess(_CHILD + """
choice = autotune.lookup("unit.synthetic", ("cpu",))
from mxnet_tpu.artifact import remote
print(json.dumps({"choice": choice,
                  "remote_hits": remote.STATS.snapshot().get(
                      "remote_hits", 0)}))
""", env=dict(remote, MXNET_AUTOTUNE="consult",
              MXNET_AUTOTUNE_DIR=b_dir))
    assert b2["choice"] == 3
    assert b2["remote_hits"] == 0, "disk tier must serve the restart"
