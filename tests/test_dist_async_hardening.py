"""dist_async staleness/ordering guarantees (reference:
tests/nightly/dist_async_kvstore.py; kvstore_dist_server.h async push
handling — VERDICT r4 item 10).

Covered: read-your-writes (pull flushes this worker's pending pushes),
per-key ordering of async applies, exit-flush durability, and the
2-process path where concurrent pushes from both workers must all land
exactly once (no lost or double-applied updates across rounds).
"""
import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import nd
from _dist_harness import run_launched_workers


def test_async_pull_sees_own_pushes_in_order():
    kv = mx.kv.create("dist_async")
    applied = []

    def updater(key, recv, stored):
        applied.append(float(recv.asnumpy()[0]))
        stored._data = (stored + recv).data

    kv.set_updater(updater)
    kv.init("w", nd.zeros((4,)))
    for i in range(1, 9):
        kv.push("w", nd.ones((4,)) * i)
    out = nd.zeros((4,))
    kv.pull("w", out=out)
    # read-your-writes: every push applied before the pull returned
    assert applied == [float(i) for i in range(1, 9)], applied
    onp.testing.assert_allclose(out.asnumpy(), onp.full((4,), 36.0))


def test_async_interleaved_keys_keep_per_key_order():
    kv = mx.kv.create("dist_async")
    seen = {"a": [], "b": []}

    def updater(key, recv, stored):
        name = "a" if key == 0 else "b"
        seen[name].append(float(recv.asnumpy()[0]))
        stored._data = (stored + recv).data

    kv.set_updater(updater)
    kv.init("0", nd.zeros((2,)))
    kv.init("1", nd.zeros((2,)))
    for i in range(1, 6):
        kv.push("0", nd.ones((2,)) * i)
        kv.push("1", nd.ones((2,)) * (10 * i))
    o0, o1 = nd.zeros((2,)), nd.zeros((2,))
    kv.pull("0", out=o0)
    kv.pull("1", out=o1)
    assert seen["a"] == [1.0, 2.0, 3.0, 4.0, 5.0]
    assert seen["b"] == [10.0, 20.0, 30.0, 40.0, 50.0]
    assert float(o0.asnumpy()[0]) == 15.0
    assert float(o1.asnumpy()[0]) == 150.0


TWO_PROC_BODY = r"""
import numpy as onp
import mxnet_tpu as mx
from mxnet_tpu import nd

kv = mx.kv.create("dist_async")
rank, size = kv.rank, kv.num_workers
assert size == 2

kv.init("w", nd.zeros((4,)))
ROUNDS = 6
for r in range(1, ROUNDS + 1):
    # each worker pushes a rank-distinct value; dist push all-reduces so
    # every round lands (rank0 + rank1) exactly once on both replicas
    kv.push("w", nd.ones((4,)) * (r * (10 ** rank)))
    # read-your-writes after every round: the pulled value must already
    # include this worker's own push for round r
    out = nd.zeros((4,))
    kv.pull("w", out=out)
    v = float(out.asnumpy()[0])
    own = sum(q * (10 ** rank) for q in range(1, r + 1))
    assert v >= own - 1e-4, (r, v, own)

# eventual consistency: BOTH workers' rounds land exactly once.
# Async means the other worker's tail pushes may still be in flight —
# poll (the reference's dist_async nightly does the same)
import time as _t

expect = sum(range(1, ROUNDS + 1)) * 11.0
deadline = _t.monotonic() + 60
final = None
while _t.monotonic() < deadline:
    out = nd.zeros((4,))
    kv.pull("w", out=out)
    final = float(out.asnumpy()[0])
    if abs(final - expect) < 1e-3:
        break
    _t.sleep(0.05)
ok = abs(final - expect) < 1e-3
with open(os.path.join({outdir!r}, "r" + str(rank) + ".txt"), "w") as f:
    f.write("OK" if ok else "BAD final=%r expect=%r" % (final, expect))
"""


def test_two_process_async_no_lost_updates(tmp_path):
    run_launched_workers(tmp_path, TWO_PROC_BODY, n=2)
    for rank in (0, 1):
        p = tmp_path / f"r{rank}.txt"
        assert p.is_file(), f"worker {rank} produced no result"
        assert p.read_text() == "OK", p.read_text()


ASYNC_STALENESS_BODY = r"""
import time as _t
import numpy as onp
import mxnet_tpu as mx
from mxnet_tpu import nd

kv = mx.kv.create("dist_async")
rank = kv.rank
kv.init("w", nd.zeros((2,)))

if rank == 0:
    # rank 0 pushes once and pulls IMMEDIATELY — true async means it
    # must NOT block on rank 1 (which is sleeping): the elapsed time
    # proves no synchronous all-reduce happened
    t0 = _t.monotonic()
    kv.push("w", nd.ones((2,)))
    out = nd.zeros((2,))
    kv.pull("w", out=out)
    elapsed = _t.monotonic() - t0
    v = float(out.asnumpy()[0])
    # read-your-writes held AND we did not wait for the sleeper
    ok = v >= 1.0 - 1e-6 and elapsed < 5.0
    res = "OK" if ok else "BAD v=%r elapsed=%r" % (v, elapsed)
else:
    _t.sleep(8.0)   # long enough that a sync push would stall rank 0
    kv.push("w", nd.ones((2,)) * 2)
    out = nd.zeros((2,))
    kv.pull("w", out=out)
    v = float(out.asnumpy()[0])
    # rank 1 sees its own push plus (eventually) rank 0's
    res = "OK" if v >= 2.0 - 1e-6 else "BAD v=%r" % v
with open(os.path.join({outdir!r}, "r" + str(rank) + ".txt"), "w") as f:
    f.write(res)
# rank 0 doubles as the server: workers rendezvous before teardown so
# it keeps serving until every peer is done (the reference's ps-lite
# Finalize is likewise collective)
kv.barrier()
"""


def test_two_process_async_is_actually_async(tmp_path):
    """A pushing worker must not block on a sleeping peer — the property
    async mode exists for (reference kvstore_dist_server.h async)."""
    run_launched_workers(tmp_path, ASYNC_STALENESS_BODY, n=2)
    for rank in (0, 1):
        p = tmp_path / f"r{rank}.txt"
        assert p.is_file(), f"worker {rank} produced no result"
        assert p.read_text() == "OK", p.read_text()


THREE_PROC_BODY = r"""
import mxnet_tpu as mx
from mxnet_tpu import nd

kv = mx.kv.create("dist_async")
rank, size = kv.rank, kv.num_workers
assert size == 3
kv.init("w", nd.zeros((2,)))
# staggered pushes from three workers stress the consecutive-seq
# applier (seq gaps appear whenever increments interleave with blob
# writes); every push must land exactly once
import time as _t
for r in range(1, 5):
    kv.push("w", nd.ones((2,)) * (r * (10 ** rank)))
    _t.sleep(0.01 * rank)
out = nd.zeros((2,))
expect = sum(range(1, 5)) * (1 + 10 + 100)  # 10*111 = 1110
deadline = _t.monotonic() + 60
final = None
while _t.monotonic() < deadline:
    kv.pull("w", out=out)
    final = float(out.asnumpy()[0])
    if abs(final - expect) < 1e-3:
        break
    _t.sleep(0.05)
with open(os.path.join({outdir!r}, "r" + str(rank) + ".txt"), "w") as f:
    f.write("OK" if abs(final - expect) < 1e-3 else
            "BAD final=%r expect=%r" % (final, expect))
kv.barrier()
"""


def test_three_process_async_interleave(tmp_path):
    """Three workers' interleaved pushes all land exactly once through
    the consecutive-seq applier (gap tolerance exercised)."""
    run_launched_workers(tmp_path, THREE_PROC_BODY, n=3)
    for rank in (0, 1, 2):
        p = tmp_path / f"r{rank}.txt"
        assert p.is_file(), f"worker {rank} produced no result"
        assert p.read_text() == "OK", p.read_text()
