"""Small end-to-end training convergence tests (reference:
tests/python/train/{test_mlp,test_conv,test_dtype}.py — real convergence
assertions on tiny data, the layer of the reference test pyramid between
op unit tests and nightly full-model runs)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.test_utils import with_seed


def _two_moons(n=256, seed=0):
    """Separable 2-class blobs."""
    rs = onp.random.RandomState(seed)
    X = rs.randn(n, 8).astype("f")
    y = (X[:, :4].sum(1) > X[:, 4:].sum(1)).astype("f")
    return X, y


def _train(net, X, y, steps=40, lr=0.1, loss_fn=None):
    loss_fn = loss_fn or gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": lr, "momentum": 0.9})
    first = last = None
    for _ in range(steps):
        with autograd.record():
            loss = loss_fn(net(nd.array(X)), nd.array(y)).mean()
        loss.backward()
        trainer.step(1)
        last = float(loss.asscalar())
        first = first if first is not None else last
    return first, last


@with_seed(1)
def test_mlp_converges():
    """Reference: tests/python/train/test_mlp.py."""
    X, y = _two_moons()
    mx.random.seed(1)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(2))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    first, last = _train(net, X, y)
    assert last < first * 0.3, (first, last)
    # accuracy on the training set should be near-perfect
    pred = net(nd.array(X)).asnumpy().argmax(1)
    assert (pred == y).mean() > 0.95


@with_seed(2)
def test_conv_converges():
    """Reference: tests/python/train/test_conv.py (LeNet-ish on tiny
    synthetic images)."""
    rs = onp.random.RandomState(2)
    X = rs.rand(128, 1, 12, 12).astype("f")
    # class = which quadrant carries the bright blob
    y = rs.randint(0, 2, 128).astype("f")
    X[y == 1, :, :6, :6] += 2.0
    X[y == 0, :, 6:, 6:] += 2.0
    mx.random.seed(2)
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, kernel_size=3, activation="relu"),
            nn.MaxPool2D(2),
            nn.Flatten(),
            nn.Dense(2))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    first, last = _train(net, X, y, steps=30, lr=0.05)
    assert last < first * 0.3, (first, last)


@with_seed(3)
def test_bf16_training_converges():
    """Reference: tests/python/train/test_dtype.py (fp16 training) —
    recast for TPU: bf16 compute on fp32 masters via SPMDTrainer."""
    import jax

    from mxnet_tpu import parallel

    X, y = _two_moons(seed=3)
    mx.random.seed(3)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(2))
    net.initialize(mx.init.Xavier())
    mesh = parallel.make_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = parallel.SPMDTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), optimizer="sgd",
        optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
        mesh=mesh, compute_dtype="bfloat16")
    first = last = None
    for _ in range(40):
        loss = trainer.step(nd.array(X), nd.array(y))
        last = float(loss.asscalar())
        first = first if first is not None else last
    assert last < first * 0.5, (first, last)
    # master weights stay fp32 even though compute ran bf16
    for _, p in net.collect_params().items():
        assert str(trainer._param_vals[0].dtype) == "float32"
        break


@with_seed(4)
def test_module_fit_converges():
    """The symbolic path end to end: Module.fit over NDArrayIter
    (reference: base_module.fit driving executor forward/backward)."""
    from mxnet_tpu import sym, io
    from mxnet_tpu.module import Module

    X, y = _two_moons(seed=4)
    data = sym.Variable("data")
    fc1 = sym.FullyConnected(data, name="fc1", num_hidden=16)
    act = sym.Activation(fc1, act_type="relu")
    fc2 = sym.FullyConnected(act, name="fc2", num_hidden=2)
    out = sym.SoftmaxOutput(fc2, sym.Variable("softmax_label"),
                            name="softmax")
    mod = Module(out, context=mx.cpu())
    train_iter = io.NDArrayIter(X, y, batch_size=64, shuffle=True)
    mod.fit(train_iter, num_epoch=8,
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            eval_metric="acc")
    score = mod.score(io.NDArrayIter(X, y, batch_size=64), "acc")
    acc = dict(score)["accuracy"]
    assert acc > 0.9, acc
