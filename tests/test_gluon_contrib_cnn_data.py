"""gluon.contrib.cnn.DeformableConvolution, contrib.data
(IntervalSampler, WikiText), and the sym.contrib/sym.image namespaces
(reference: gluon/contrib/{cnn,data}, python/mxnet/symbol/contrib.py).
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.test_utils import assert_almost_equal


def _np(x):
    return onp.asarray(x.asnumpy())


def test_deformable_convolution_zero_offset_equals_conv():
    from mxnet_tpu.gluon.contrib.cnn import DeformableConvolution
    from mxnet_tpu.gluon.nn import Conv2D

    rng = onp.random.RandomState(0)
    x = nd.array(rng.rand(2, 3, 8, 8).astype("f"))
    d = DeformableConvolution(4, kernel_size=3, padding=1, use_bias=False)
    d.initialize(mx.init.Xavier())
    with autograd.pause():
        out = d(x)
    assert out.shape == (2, 4, 8, 8)
    # offset conv is zero-initialized -> exactly a regular convolution
    c = Conv2D(4, 3, 1, 1, use_bias=False, in_channels=3)
    c.initialize()
    c.weight.set_data(d.weight.data())
    with autograd.pause():
        want = c(x)
    assert_almost_equal(_np(out), _np(want), rtol=1e-4, atol=1e-5)


def test_deformable_convolution_trains():
    from mxnet_tpu.gluon.contrib.cnn import DeformableConvolution
    from mxnet_tpu import gluon

    rng = onp.random.RandomState(1)
    net = DeformableConvolution(2, kernel_size=3, padding=1,
                                activation="relu")
    net.initialize(mx.init.Xavier())
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1})
    x = nd.array(rng.rand(1, 2, 6, 6).astype("f"))
    with autograd.record():
        loss = nd.sum(net(x) ** 2)
        loss.backward()
    tr.step(1)
    # offsets receive gradient (the deformable path is differentiable
    # through the bilinear sampling)
    assert net.offset_weight.grad() is not None
    assert onp.isfinite(_np(net.offset_weight.grad())).all()


def test_interval_sampler_matches_reference_doc():
    from mxnet_tpu.gluon.contrib.data import IntervalSampler

    assert list(IntervalSampler(13, interval=3)) == \
        [0, 3, 6, 9, 12, 1, 4, 7, 10, 2, 5, 8, 11]
    assert list(IntervalSampler(13, interval=3, rollover=False)) == \
        [0, 3, 6, 9, 12]
    assert len(IntervalSampler(13, 3)) == 13
    with pytest.raises(ValueError):
        IntervalSampler(2, 5)


def test_wikitext_local_file(tmp_path):
    from mxnet_tpu.gluon.contrib.data.text import WikiText2

    corpus = (" = Heading = \n\n the cat sat on the mat \n"
              " the dog sat too \n")
    (tmp_path / "wiki.train.tokens").write_text(corpus)
    ds = WikiText2(root=str(tmp_path), segment="train", seq_len=5)
    assert len(ds) >= 1
    d, l = ds[0]
    assert d.shape == (5,) and l.shape == (5,)
    # label stream is the data stream shifted by one
    assert _np(d)[1:].tolist() == _np(l)[:-1].tolist()
    # eos terminates every non-empty line
    eos = ds.vocabulary.token_to_idx["<eos>"]
    flat = _np(ds._data).ravel().tolist()
    assert eos in flat
    # missing file raises with the expected path named
    with pytest.raises(FileNotFoundError, match="wiki.valid.tokens"):
        WikiText2(root=str(tmp_path), segment="validation")


def test_sym_contrib_and_image_namespaces():
    from mxnet_tpu import sym

    x = sym.Variable("x")
    node = sym.contrib.quadratic(x, a=1.0, b=1.0, c=1.0)
    ex = node.bind(args={"x": nd.array(onp.array([2.0], "f"))})
    out = ex.forward()[0]
    assert float(_np(out)[0]) == 7.0
    img = sym.Variable("img")
    flip = sym.image.flip_left_right(img)
    x_img = nd.array(onp.arange(6, dtype="f").reshape(1, 3, 2, 1))
    got = flip.bind(args={"img": x_img}).forward()[0]
    assert_almost_equal(_np(got), _np(x_img)[:, :, ::-1], rtol=0, atol=0)
    assert hasattr(sym.contrib, "ROIAlign")  # CamelCase aliases ride along
