"""Test harness config.

Runs the suite on a virtual 8-device CPU mesh (like the reference's
multi-process single-host distributed tests, SURVEY §4) so sharding paths
are exercised without TPU hardware. The platform forcing lives in
``_cpu_platform.force_cpu_platform`` (shared with bench.py and
__graft_entry__.py) — it must run before any backend initializes.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _cpu_platform import force_cpu_platform  # noqa: E402

force_cpu_platform(num_devices=8)

import numpy as onp  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running benchmark/smoke runs (tier-1 excludes them "
        "via -m 'not slow')")


@pytest.fixture(autouse=True)
def _seed():
    import mxnet_tpu as mx

    mx.random.seed(0)
    onp.random.seed(0)
    yield
