"""Test harness config.

Runs the suite on a virtual 8-device CPU mesh (like the reference's
multi-process single-host distributed tests, SURVEY §4) so sharding paths
are exercised without TPU hardware. Must set XLA flags before jax import.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"  # axon env presets this to the TPU tunnel
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

# The axon sitecustomize registers a TPU-tunnel PJRT plugin at interpreter
# start and sets the jax_platforms CONFIG to "axon,cpu" (config beats the
# env var). Tests must run on the virtual CPU mesh — and the tunnel admits
# one process at a time, so a test run would otherwise contend with the
# bench/driver for the single chip. Force the config back to cpu before
# any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as onp  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    import mxnet_tpu as mx

    mx.random.seed(0)
    onp.random.seed(0)
    yield
