"""Test harness config.

Runs the suite on a virtual 8-device CPU mesh (like the reference's
multi-process single-host distributed tests, SURVEY §4) so sharding paths
are exercised without TPU hardware. The platform forcing lives in
``_cpu_platform.force_cpu_platform`` (shared with bench.py and
__graft_entry__.py) — it must run before any backend initializes.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _cpu_platform import force_cpu_platform  # noqa: E402

force_cpu_platform(num_devices=8)

# Lock-discipline witness ON for the whole suite (before any mxnet_tpu
# import constructs a lock): every test doubles as a lock-order test,
# and the autouse gate below fails the test that produced a violation.
os.environ.setdefault("MXNET_LOCK_CHECK", "warn")

import numpy as onp  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running benchmark/smoke runs (tier-1 excludes them "
        "via -m 'not slow')")


# --------------------------------------------------------------------------
# Environment-gated expected failures.
#
# This container pins jax/jaxlib 0.4.37, whose CPU backend rejects
# cross-process collectives outright ("Multiprocess computations aren't
# implemented on the CPU backend") — the multi-process launch tests
# exercise exactly that path, so they cannot pass here regardless of
# framework correctness. (jax.shard_map itself is shimmed via
# mxnet_tpu.parallel._compat, which restores the single-process mesh
# tests; only the true multi-PROCESS runs stay blocked.) The xfail is
# version-gated: on a jax >= 0.5 container these run — and must pass —
# again.
_MULTIPROCESS_CPU_XFAIL = {
    "test_dist_async_hardening.py",
    "test_dist_moe_pipeline.py",
    "test_dist_multiprocess.py",
    "test_dist_ring_ulysses.py",
    "test_dist_sharded_ckpt.py",
}


def _jax_cpu_lacks_multiprocess_collectives():
    import jax

    try:
        major, minor = (int(x) for x in jax.__version__.split(".")[:2])
    except ValueError:
        return False
    return (major, minor) < (0, 5)


def pytest_collection_modifyitems(config, items):
    if not _jax_cpu_lacks_multiprocess_collectives():
        return
    import jax

    reason = (f"jaxlib {jax.__version__} CPU backend does not implement "
              "multi-process collectives (needs jax >= 0.5); the "
              "framework path is exercised single-process by "
              "test_multidevice/test_moe/test_pipeline instead")
    mark = pytest.mark.xfail(reason=reason, strict=False)
    for item in items:
        # only the tests that actually launch multiple processes — the
        # same files also hold single-process tests that must keep
        # counting as plain passes
        if item.fspath.basename in _MULTIPROCESS_CPU_XFAIL and \
                "process" in item.name:
            item.add_marker(mark)


@pytest.fixture
def forced_device_subprocess():
    """Run a python snippet in a subprocess with a FORCED virtual
    device count (1 by default — this session's 8-device forcing is
    process-wide and cannot be undone in-process). The snippet must
    print a single JSON document on its last stdout line; the helper
    returns it parsed. Used by the resharding-on-load tests to restore
    a mesh-sharded checkpoint into a genuinely single-device process."""
    import json
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def run(snippet, num_devices=1, env=None, timeout=600):
        code = (f"import sys; sys.path.insert(0, {root!r})\n"
                "from _cpu_platform import force_cpu_platform\n"
                f"force_cpu_platform(num_devices={num_devices})\n"
                + snippet)
        full_env = dict(os.environ, JAX_PLATFORMS="cpu")
        full_env.update(env or {})
        out = subprocess.run([sys.executable, "-c", code], cwd=root,
                             env=full_env, capture_output=True,
                             text=True, timeout=timeout)
        assert out.returncode == 0, \
            f"forced-device child failed:\n{out.stderr[-4000:]}"
        return json.loads(out.stdout.strip().splitlines()[-1])

    return run


@pytest.fixture(autouse=True)
def _seed():
    import mxnet_tpu as mx

    mx.random.seed(0)
    onp.random.seed(0)
    yield


@pytest.fixture(autouse=True)
def _lock_check_gate():
    """Fail THE TEST that produced a lock-order violation (out-of-rank
    acquire, order-graph cycle, self-deadlock) under the suite-wide
    MXNET_LOCK_CHECK=warn. Witness tests that provoke violations on
    purpose wrap them in locks.capture_violations(), which removes
    them from the global record before this gate reads it."""
    from mxnet_tpu.utils import locks

    before = len(locks.violations())
    yield
    new = locks.violations()[before:]
    assert not new, (
        "lock_check violations during this test (see "
        "docs/CONCURRENCY.md):\n" +
        "\n".join(f"  [{v['kind']}] {v['message']} "
                  f"(thread={v['thread']})" for v in new))


@pytest.fixture(scope="session", autouse=True)
def _hermetic_compile_cache(tmp_path_factory):
    """Point the persistent compile cache at a per-session tmpdir so
    tier-1 runs are hermetic: no executables leak in from (or out to)
    $MXNET_HOME/compile_cache across runs, and the suite never depends
    on what a previous run happened to compile. Tests that need their
    own isolation monkeypatch MXNET_COMPILE_CACHE_DIR on top."""
    d = tmp_path_factory.mktemp("compile_cache")
    prev = os.environ.get("MXNET_COMPILE_CACHE_DIR")
    os.environ["MXNET_COMPILE_CACHE_DIR"] = str(d)
    yield str(d)
    if prev is None:
        os.environ.pop("MXNET_COMPILE_CACHE_DIR", None)
    else:
        os.environ["MXNET_COMPILE_CACHE_DIR"] = prev
