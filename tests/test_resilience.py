"""Fault-tolerant training & serving (mxnet_tpu/resilience/): the
crash-consistent CheckpointManager, AutoResume supervisor, deterministic
fault-injection harness, shared retry/backoff policy, and the serving
circuit breaker — plus their wiring into trainer / pipeline / kvstore /
serving / compile-cache / engine seams.

The headline guarantees get the hard tests: a subprocess SIGKILLed
mid-epoch restarts through AutoResume to BITWISE-identical final
parameters and loss trace vs an uninterrupted run, and a corrupt or
truncated checkpoint is skipped with a warning while the previous good
one loads.
"""
import json
import os
import pickle
import subprocess
import sys
import threading
import time

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu import resilience
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.parameter import Parameter
from mxnet_tpu.resilience import (AutoResume, CheckpointManager,
                                  CircuitBreaker, InjectedFault,
                                  ResumeExhausted, RetryExhausted,
                                  RetryPolicy, faults)
from mxnet_tpu.resilience.breaker import CircuitOpen


@pytest.fixture(autouse=True)
def _clean_faults():
    """Every test starts and ends disarmed with fresh counters — an
    armed plan leaking across tests would fire in unrelated seams."""
    faults.disarm()
    resilience.reset_resilience_counters()
    yield
    faults.disarm()
    resilience.reset_resilience_counters()


@pytest.fixture(scope="module", autouse=True)
def _collect_cycles():
    """One gc pass after the module: break trainer<->manager<->
    supervisor reference cycles so this file's AMP trainers leave the
    fused-step registry (its weak set backs the process-wide
    ``skipped_steps`` profiler counter) before later test FILES read
    it. Module-scoped on purpose — a full collect per test costs
    seconds across the file for no extra isolation."""
    import gc

    yield
    gc.collect()


def _make_params(n, shape=(4, 4)):
    params = []
    for i in range(n):
        p = Parameter(f"res_p{i}", shape=shape, dtype="float32")
        p.initialize()
        p.set_data(nd.array(onp.full(shape, float(i + 1), "f")))
        params.append(p)
    return params


def _backward_over(params, scale=2.0):
    with autograd.record():
        loss = sum(((p.data() * scale).sum() for p in params),
                   nd.array(0.0))
    loss.backward()


def _dropout_net(seed=3, dim=8, out=4):
    mx.random.seed(seed)
    net = nn.Sequential()
    net.add(nn.Dense(16, activation="relu"))
    net.add(nn.Dropout(0.5))
    net.add(nn.Dense(out))
    net.initialize()
    net(nd.zeros((1, dim)))
    return net


def _param_bytes(net):
    return [p.data().asnumpy().tobytes()
            for p in net.collect_params().values()]


def _traces_equal(a, b):
    """Elementwise-identical loss traces; NaN == NaN (the poisoned AMP
    batch produces a NaN loss on BOTH sides by design)."""
    return len(a) == len(b) and onp.array_equal(
        onp.asarray(a, "float64"), onp.asarray(b, "float64"),
        equal_nan=True)


# ---------------------------------------------------------------------------
# fault-injection harness


def test_fault_plan_parse_and_at_trigger():
    faults.arm("engine_push:at=2")
    mx.engine.push(lambda: None)  # call 1: no fire
    with pytest.raises(InjectedFault):
        mx.engine.push(lambda: None)  # call 2: fires once
    mx.engine.push(lambda: None)  # at= fires exactly once
    counts = faults.fire_counts()
    assert counts == {"engine_push": 1}
    assert resilience.resilience_counters()["fault_fires"] == 1


def test_fault_every_and_times():
    faults.arm({"engine_push": dict(every=2, times=2)})
    fired = 0
    for _ in range(8):
        try:
            mx.engine.push(lambda: None)
        except InjectedFault:
            fired += 1
    assert fired == 2  # every 2nd call, capped at times=2


def test_fault_prob_is_seeded_deterministic():
    def fires(seed):
        faults.arm({"engine_push": dict(prob=0.5, times=100)}, seed=seed)
        out = []
        for i in range(20):
            try:
                mx.engine.push(lambda: None)
                out.append(0)
            except InjectedFault:
                out.append(1)
        faults.disarm()
        return out

    a, b, c = fires(7), fires(7), fires(8)
    assert a == b          # same seed: identical firing sequence
    assert a != c          # different seed: different sequence
    assert 0 < sum(a) < 20


def test_fault_unknown_point_and_bad_clause_raise():
    with pytest.raises(MXNetError):
        faults.arm("not_a_point:at=1")
    with pytest.raises(MXNetError):
        faults.arm("engine_push:bogus=1")
    with pytest.raises(MXNetError):
        faults.arm({"engine_push": {}})  # no trigger


def test_fault_exc_mapping_and_inject_scoping():
    faults.arm("engine_push:at=1")  # outer plan
    with faults.inject("engine_push", at=1, exc=OSError):
        with pytest.raises(OSError):
            mx.engine.push(lambda: None)
    # the context restored the OUTER plan (call count untouched)
    with pytest.raises(InjectedFault):
        mx.engine.push(lambda: None)


def test_fault_clause_seed_does_not_leak_across_clauses():
    """A clause-level seed= binds to ITS clause only — the clauses
    after it keep the plan-level default (order-independent plans)."""
    p1 = faults.parse_plan(
        "engine_push:prob=0.5:seed=7;kvstore_push:prob=0.5", seed=0)
    p2 = faults.parse_plan("kvstore_push:prob=0.5", seed=0)
    a = [p1["kvstore_push"]._rng.random() for _ in range(5)]
    b = [p2["kvstore_push"]._rng.random() for _ in range(5)]
    assert a == b


def test_injected_fault_is_oserror_and_mxneterror():
    assert issubclass(InjectedFault, OSError)
    assert issubclass(InjectedFault, MXNetError)


# ---------------------------------------------------------------------------
# retry policy


def test_retry_succeeds_after_transient_failures():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("transient")
        return "ok"

    pol = RetryPolicy(max_attempts=5, base_ms=0.01, jitter=0.0,
                      name="test")
    assert pol.run(flaky) == "ok"
    assert len(calls) == 3
    c = resilience.resilience_counters()
    assert c["retry_attempts"] == 2
    assert c["retry_giveups"] == 0


def test_retry_exhausted_is_terminal_and_chains():
    def dead():
        raise ConnectionError("down")

    pol = RetryPolicy(max_attempts=3, base_ms=0.01, jitter=0.0)
    with pytest.raises(RetryExhausted) as ei:
        pol.run(dead)
    assert ei.value.attempts == 3
    assert isinstance(ei.value.__cause__, ConnectionError)
    assert isinstance(ei.value, MXNetError)  # clear terminal error
    assert resilience.resilience_counters()["retry_giveups"] == 1


def test_retry_non_transient_propagates_immediately():
    calls = []

    def typo():
        calls.append(1)
        raise ValueError("not transient")

    pol = RetryPolicy(max_attempts=5, base_ms=0.01,
                      retry_on=(ConnectionError,))
    with pytest.raises(ValueError):
        pol.run(typo)
    assert len(calls) == 1


def test_retry_backoff_deterministic_with_seed():
    p1 = RetryPolicy(base_ms=100, max_ms=1000, jitter=0.5, seed=1)
    p2 = RetryPolicy(base_ms=100, max_ms=1000, jitter=0.5, seed=1)
    d1 = [p1.delay_ms(a) for a in range(1, 5)]
    d2 = [p2.delay_ms(a) for a in range(1, 5)]
    assert d1 == d2
    assert all(50 <= d1[0] <= 100 for _ in [0])  # jitter in [0.5, 1]x
    # exponential growth under the cap
    nojit = RetryPolicy(base_ms=100, max_ms=1000, jitter=0.0)
    assert [nojit.delay_ms(a) for a in range(1, 6)] == \
        [100, 200, 400, 800, 1000]


def test_retry_single_attempt_when_resilience_off(monkeypatch):
    monkeypatch.setenv("MXNET_RESILIENCE", "0")
    calls = []

    def flaky():
        calls.append(1)
        raise ConnectionError("x")

    pol = RetryPolicy(max_attempts=5, base_ms=0.01)
    with pytest.raises(RetryExhausted):
        pol.run(flaky)
    assert len(calls) == 1  # fail-fast: no retries


def test_kvstore_ps_push_retries_transient_sends(monkeypatch):
    """Satellite: AsyncParamServer.push routes its coordinator-KV
    sends through the shared policy — bounded attempts, then a clear
    terminal error — instead of failing the first push."""
    from mxnet_tpu import kvstore_ps

    class FakeClient:
        def __init__(self, fail_first):
            self.fail = fail_first
            self.seqs = {}
            self.blobs = {}
            self.set_calls = 0

        def key_value_increment(self, key, n):
            self.seqs[key] = self.seqs.get(key, 0) + n
            return self.seqs[key]

        def key_value_set_bytes(self, key, blob):
            self.set_calls += 1
            if self.fail > 0:
                self.fail -= 1
                raise ConnectionError("van dropped the message")
            self.blobs[key] = blob

    fake = FakeClient(fail_first=2)
    monkeypatch.setattr(kvstore_ps, "_client", lambda: fake)
    ps = kvstore_ps.AsyncParamServer(rank=1, get_updater=lambda: None)
    ps._retry = RetryPolicy(max_attempts=4, base_ms=0.01, jitter=0.0,
                            name="test kvstore_ps")
    try:
        ps.push("w", onp.ones(3, "f"))
        assert fake.set_calls == 3  # two transient failures retried
        assert len(fake.blobs) == 1
        # permanent failure: bounded attempts then RetryExhausted
        fake.fail = 10 ** 9
        with pytest.raises(RetryExhausted):
            ps.push("w", onp.ones(3, "f"))
    finally:
        ps._last_seq.clear()  # keep the atexit flush a no-op
        ps.close()


# ---------------------------------------------------------------------------
# circuit breaker


def test_breaker_trips_opens_and_half_open_recovers():
    clk = [0.0]
    br = CircuitBreaker(threshold=3, cooldown_ms=1000, name="t",
                        clock=lambda: clk[0])
    for _ in range(2):
        br.record_failure()
    assert br.state == "closed" and br.allow()
    br.record_failure()  # 3rd consecutive: trip
    assert br.state == "open"
    assert not br.allow()
    with pytest.raises(CircuitOpen):
        br.check()
    clk[0] = 1.5  # past the cooldown: one half-open probe
    assert br.state == "half-open"
    assert br.allow()       # the probe
    assert not br.allow()   # only ONE probe
    br.record_success()     # probe succeeded: closed again
    assert br.state == "closed" and br.allow()
    c = resilience.resilience_counters()
    assert c["breaker_trips"] == 1
    assert c["breaker_resets"] == 1
    assert c["breaker_fast_fails"] >= 1


def test_breaker_failed_probe_reopens():
    clk = [0.0]
    br = CircuitBreaker(threshold=1, cooldown_ms=1000,
                        clock=lambda: clk[0])
    br.record_failure()
    assert br.state == "open"
    clk[0] = 1.1
    assert br.allow()
    br.record_failure()  # probe failed: cooldown restarts
    assert br.state == "open"
    assert not br.allow()


def test_breaker_never_trips_when_resilience_off(monkeypatch):
    monkeypatch.setenv("MXNET_RESILIENCE", "0")
    br = CircuitBreaker(threshold=1, cooldown_ms=60000)
    for _ in range(5):
        br.record_failure()
    assert br.allow()


# ---------------------------------------------------------------------------
# seams


def test_device_put_fault_propagates_from_feed_worker():
    def gen():
        for i in range(5):
            yield onp.full((2, 2), float(i), "f")

    from mxnet_tpu.pipeline import DeviceFeed

    feed = DeviceFeed(gen(), depth=2)
    faults.arm("device_put:at=3")
    got, err = [], None
    try:
        for b in feed:
            got.append(b)
    except InjectedFault as e:
        err = e
    assert err is not None  # worker fault reached the consumer
    assert len(got) <= 3
    assert faults.fire_counts()["device_put"] == 1
    feed.close()


def test_grad_bucket_dispatch_fault_fires_mid_backward():
    from mxnet_tpu.pipeline import AsyncGradReducer

    params = _make_params(3)
    red = AsyncGradReducer(params, bucket_bytes=1,
                           reduce_fn=lambda f: f).attach()
    try:
        faults.arm("grad_bucket_dispatch:at=1")
        with pytest.raises(InjectedFault):
            _backward_over(params)
        faults.disarm()
        red.abandon()  # the recovery path: drop the partial round
        _backward_over(params)  # clean round still works
        red.flush([p.grad() for p in params])
    finally:
        red.detach()


def test_kvstore_push_pull_fault_points():
    kv = mx.kvstore.create("local")
    kv.init("w", nd.zeros((4,)))
    with faults.inject("kvstore_push", at=1):
        with pytest.raises(InjectedFault):
            kv.push("w", nd.ones((4,)))
    kv.push("w", nd.ones((4,)))  # disarmed: works
    out = nd.zeros((4,))
    with faults.inject("kvstore_pull", at=1):
        with pytest.raises(InjectedFault):
            kv.pull("w", out=out)
    kv.pull("w", out=out)
    onp.testing.assert_array_equal(out.asnumpy(), onp.ones(4, "f"))


def test_compile_cache_io_fault_degrades_to_miss():
    from mxnet_tpu.utils import compile_cache as cc

    import jax.numpy as jnp

    jf = cc.counting_jit(lambda x: x * 2.0, label="resil_test")
    fp = cc.fingerprint("resil_test", ("k", 1))
    compiled = cc.aot_compile(jf, jnp.zeros((2,)))
    assert cc.disk_store(fp, compiled)
    before = cc.compile_cache_stats()
    with faults.inject("compile_cache_io", every=1):
        assert cc.disk_load(fp) is None       # load fault -> a miss
        assert not cc.disk_store(fp, compiled)  # store fault -> skipped
    after = cc.compile_cache_stats()
    assert after["disk_misses"] >= before["disk_misses"] + 1
    # a transient injected failure must NOT destroy the valid entry:
    # once the drill ends, the warm start it was testing still works
    assert cc.disk_load(fp) is not None
    # the step path stays alive: a fresh load_or_compile still serves
    with faults.inject("compile_cache_io", every=1):
        fn, _, from_disk = cc.load_or_compile(fp, jf, (jnp.zeros((2,)),))
        assert not from_disk
        onp.testing.assert_array_equal(
            onp.asarray(fn(jnp.ones(2))), onp.full(2, 2.0, "f"))


# ---------------------------------------------------------------------------
# checkpoint manager


def _trainer_setup(scaler=False, seed=5):
    net = _dropout_net(seed=seed)
    tr = mx.gluon.Trainer(net.collect_params(), "sgd",
                          {"learning_rate": 0.05, "momentum": 0.9})
    if scaler:
        from mxnet_tpu.contrib.amp.loss_scaler import LossScaler

        tr._amp_loss_scaler = LossScaler(init_scale=2.0 ** 8,
                                         scale_window=3)
    return net, tr


def _train_steps(net, tr, n, seed=0, batch=4, poison_at=None):
    rs = onp.random.RandomState(seed)
    for s in range(n):
        x = rs.rand(batch, 8).astype("f")
        y = rs.rand(batch, 4).astype("f")
        if s == poison_at:
            x = onp.full_like(x, onp.inf)
        with autograd.record():
            loss = ((net(nd.array(x)) - nd.array(y)) ** 2).mean()
        loss.backward()
        tr.step(batch)


@pytest.mark.parametrize("async_mode", [False, True])
def test_checkpoint_roundtrip_bitwise(tmp_path, async_mode):
    """Params, optimizer state, update counters, PRNG position and a
    subsequent training trajectory all restore bitwise."""
    net, tr = _trainer_setup()
    _train_steps(net, tr, 3, seed=1)
    mgr = CheckpointManager(str(tmp_path), trainer=tr,
                            async_mode=async_mode)
    mgr.save(3, cursor={"epoch": 0, "step_in_epoch": 3})
    mgr.wait()
    snap_params = _param_bytes(net)
    # continue training from the snapshot twice; both continuations
    # must be identical (momentum + dropout masks + counters restored)
    _train_steps(net, tr, 3, seed=2)
    after_a = _param_bytes(net)
    meta = mgr.restore()
    assert meta["step"] == 3
    assert meta["cursor"]["step_in_epoch"] == 3
    assert _param_bytes(net) == snap_params
    assert tr._optimizer.num_update == 3
    _train_steps(net, tr, 3, seed=2)
    assert _param_bytes(net) == after_a
    assert resilience.resilience_counters()["ckpt_restores"] == 1


def test_checkpoint_amp_scaler_roundtrip(tmp_path):
    """The AMP loss scale + grow-window position + skip counters
    survive the round trip, through a real overflow episode."""
    net, tr = _trainer_setup(scaler=True)
    _train_steps(net, tr, 4, seed=3, poison_at=1)  # one skipped step
    scale_before = tr._amp_loss_scaler.loss_scale
    num_update = tr._optimizer.num_update
    mgr = CheckpointManager(str(tmp_path), trainer=tr, async_mode=False)
    mgr.save(4)
    _train_steps(net, tr, 2, seed=4)
    mgr.restore()
    assert tr._amp_loss_scaler.loss_scale == scale_before
    assert tr._optimizer.num_update == num_update
    assert scale_before == 2.0 ** 7  # the episode really halved it


def test_checkpoint_prng_stream_roundtrip(tmp_path):
    mx.random.seed(9)
    mx.nd.random_uniform(shape=(2,))  # advance the stream
    mgr = CheckpointManager(str(tmp_path), async_mode=False)
    mgr.save(1)
    expect = mx.nd.random_uniform(shape=(4,)).asnumpy()
    mx.nd.random_uniform(shape=(4,))  # drift further
    mgr.restore()
    onp.testing.assert_array_equal(
        mx.nd.random_uniform(shape=(4,)).asnumpy(), expect)


def test_checkpoint_kvstore_roundtrip(tmp_path):
    kv = mx.kvstore.create("local")
    kv.init("w", nd.array(onp.arange(4, dtype="f")))
    mgr = CheckpointManager(str(tmp_path), kvstore=kv, async_mode=False)
    mgr.save(1)
    kv.push("w", nd.ones((4,)))
    mgr.restore()
    out = nd.zeros((4,))
    kv.pull("w", out=out)
    onp.testing.assert_array_equal(out.asnumpy(),
                                   onp.arange(4, dtype="f"))


def test_checkpoint_atomic_no_tmp_left_and_manifest_hashes(tmp_path):
    net, tr = _trainer_setup()
    mgr = CheckpointManager(str(tmp_path), trainer=tr, async_mode=True)
    mgr.save(1)
    mgr.wait()
    names = sorted(os.listdir(tmp_path))
    assert names == ["ckpt-000000000001"]  # no .tmp- residue
    with open(tmp_path / "ckpt-000000000001" / "manifest.json") as f:
        manifest = json.load(f)
    assert manifest["files"]["state.pkl"]["sha256"]
    assert mgr.validate(1)


def test_corrupt_checkpoint_skipped_with_warning(tmp_path, caplog):
    """Satellite: a truncated/corrupted checkpoint is skipped with a
    warning and the previous good one loads."""
    net, tr = _trainer_setup()
    mgr = CheckpointManager(str(tmp_path), trainer=tr, async_mode=False)
    _train_steps(net, tr, 1, seed=1)
    mgr.save(1)
    good = _param_bytes(net)
    _train_steps(net, tr, 1, seed=2)
    mgr.save(2)
    # truncate the newest payload (a torn write that somehow renamed,
    # or bit rot): hash validation must reject it
    payload = tmp_path / "ckpt-000000000002" / "state.pkl"
    payload.write_bytes(payload.read_bytes()[:32])
    import logging

    with caplog.at_level(logging.WARNING,
                         logger="mxnet_tpu.resilience.checkpoint"):
        assert mgr.latest_valid() == 1
        meta = mgr.restore()
    assert meta["step"] == 1
    assert _param_bytes(net) == good
    assert any("corrupt" in r.message for r in caplog.records)
    assert resilience.resilience_counters()["ckpt_corrupt_skipped"] >= 1


def test_checkpoint_version_salt_invalidates(tmp_path, monkeypatch):
    mgr = CheckpointManager(str(tmp_path), async_mode=False)
    mgr.save(1)
    assert mgr.validate(1)
    from mxnet_tpu.resilience import checkpoint as ckpt_mod

    monkeypatch.setattr(ckpt_mod, "_salt",
                        lambda: ["other-version"])
    assert not mgr.validate(1)  # a different build must not load it


def test_checkpoint_retention_keeps_last_n(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep=2, async_mode=False)
    for s in range(1, 5):
        mgr.save(s)
    assert mgr.list_steps() == [3, 4]
    assert resilience.resilience_counters()["ckpt_pruned"] == 2


def test_checkpoint_write_fault_surfaces_async(tmp_path):
    mgr = CheckpointManager(str(tmp_path), async_mode=True)
    faults.arm("checkpoint_write:at=1")
    mgr.save(1)  # writer thread hits the fault
    with pytest.raises(MXNetError):
        mgr.wait()
    faults.disarm()
    mgr.save(2)
    mgr.wait()
    assert mgr.latest_valid() == 2


def test_checkpoint_async_overlaps_slow_write(tmp_path, monkeypatch):
    """The async writer really runs off-thread: a save returns while
    the (artificially slowed) write is still in flight."""
    from mxnet_tpu.resilience import checkpoint as ckpt_mod

    real_write = CheckpointManager._write
    gate = threading.Event()

    def slow_write(self, snap):
        gate.wait(5)
        real_write(self, snap)

    monkeypatch.setattr(CheckpointManager, "_write", slow_write)
    mgr = CheckpointManager(str(tmp_path), async_mode=True)
    t0 = time.perf_counter()
    mgr.save(1)
    assert time.perf_counter() - t0 < 1.0  # did not wait for the write
    assert mgr.latest_valid() is None      # still in flight
    gate.set()
    mgr.wait()
    assert mgr.latest_valid() == 1


# ---------------------------------------------------------------------------
# Trainer <-> async-grad-sync speculation (satellite)


def test_save_load_states_abandon_inflight_speculation(tmp_path,
                                                       monkeypatch):
    """Satellite: a save/load_states round trip with speculative
    grad reductions in flight must abandon them — and the next step
    must still produce the exact no-round-trip values."""
    monkeypatch.setenv("MXNET_ASYNC_GRAD_SYNC", "1")

    def run(round_trip):
        mx.random.seed(21)
        params = _make_params(3)
        tr = mx.gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                              kvstore="dist_sync")
        _backward_over(params)
        tr.step(1)  # wires the reducer + its grad-ready hook
        _backward_over(params, scale=3.0)  # speculation in flight
        if round_trip:
            red = tr._grad_reducer
            assert red is not None
            fname = str(tmp_path / "rt.states")
            tr.save_states(fname)
            # capture boundary: nothing speculative may survive it
            assert red._pending == {} and red._spec == {}
            tr.load_states(fname)
        tr.step(1)
        return [p.data().asnumpy().tobytes() for p in params]

    assert run(True) == run(False)


# ---------------------------------------------------------------------------
# AutoResume


def _resume_job(tmp_path, fault_at=None, epochs=2, steps=5,
                max_restarts=3, scaler=False, poison_at=None):
    net, tr = _trainer_setup(scaler=scaler, seed=11)
    faults.register_fault_point("test_step_fault",
                                "test-injected step failure")

    def data_factory(epoch):
        rs = onp.random.RandomState(500 + epoch)
        for s in range(steps):
            x = rs.rand(4, 8).astype("f")
            y = rs.rand(4, 4).astype("f")
            if (epoch, s) == poison_at:
                x = onp.full_like(x, onp.inf)
            yield x, y

    def step_fn(batch):
        faults.maybe_fail("test_step_fault")
        x, y = nd.array(batch[0]), nd.array(batch[1])
        with autograd.record():
            loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        tr.step(4)
        return float(loss.asnumpy())

    mgr = CheckpointManager(str(tmp_path), trainer=tr, async_mode=True)
    sup = AutoResume(mgr, data_factory, step_fn, epochs=epochs,
                     ckpt_every=3, max_restarts=max_restarts)
    if fault_at is not None:
        faults.arm({"test_step_fault": fault_at})
    try:
        trace = sup.run()
    finally:
        faults.disarm()
    return trace, _param_bytes(net), sup


def test_autoresume_bitwise_parity_after_fault(tmp_path):
    t_clean, p_clean, _ = _resume_job(tmp_path / "clean")
    t_fault, p_fault, sup = _resume_job(tmp_path / "fault",
                                        fault_at=dict(at=7))
    assert sup.restarts == 1
    assert p_fault == p_clean          # bitwise params
    assert t_fault == t_clean          # identical loss trace
    c = resilience.resilience_counters()
    assert c["resume_faults_caught"] == 1
    assert c["resume_restarts"] == 1


def test_autoresume_through_amp_skip_episode(tmp_path):
    """Crash AFTER an AMP overflow-skip: the restored scale/skip
    state reproduces the uninterrupted trajectory exactly."""
    kw = dict(scaler=True, poison_at=(0, 2))
    t_clean, p_clean, sup0 = _resume_job(tmp_path / "clean", **kw)
    t_fault, p_fault, sup = _resume_job(tmp_path / "fault",
                                        fault_at=dict(at=6), **kw)
    assert sup.restarts == 1
    assert p_fault == p_clean
    assert _traces_equal(t_fault, t_clean)


def test_autoresume_exhausts_restart_budget(tmp_path):
    with pytest.raises(ResumeExhausted) as ei:
        _resume_job(tmp_path, fault_at=dict(every=1, times=1000),
                    max_restarts=2)
    assert ei.value.restarts == 3
    assert isinstance(ei.value.__cause__, InjectedFault)


def test_autoresume_propagates_when_resilience_off(tmp_path,
                                                   monkeypatch):
    monkeypatch.setenv("MXNET_RESILIENCE", "0")
    with pytest.raises(InjectedFault):
        _resume_job(tmp_path, fault_at=dict(at=2))


def test_autoresume_survives_device_put_fault_in_feed(tmp_path):
    """End-to-end over a real seam: the fault fires inside DeviceFeed's
    worker (H2D staging), propagates to the loop, and AutoResume
    restores + resumes to the clean-run result."""
    from mxnet_tpu.pipeline import DeviceFeed

    def job(ckpt_dir, plan):
        net, tr = _trainer_setup(seed=13)

        def data_factory(epoch):
            rs = onp.random.RandomState(900 + epoch)
            src = ((rs.rand(4, 8).astype("f"),
                    rs.rand(4, 4).astype("f")) for _ in range(4))
            return DeviceFeed(src, depth=2)

        def step_fn(batch):
            x, y = batch
            with autograd.record():
                loss = ((net(x) - y) ** 2).mean()
            loss.backward()
            tr.step(4)
            return float(loss.asnumpy())

        mgr = CheckpointManager(str(ckpt_dir), trainer=tr,
                                async_mode=True)
        sup = AutoResume(mgr, data_factory, step_fn, epochs=1,
                         ckpt_every=2)
        if plan:
            faults.arm(plan)
        try:
            trace = sup.run()
        finally:
            faults.disarm()
        return trace, _param_bytes(net), sup.restarts

    t_clean, p_clean, _ = job(tmp_path / "clean", None)
    t_fault, p_fault, restarts = job(tmp_path / "fault",
                                     "device_put:at=6")
    assert restarts == 1
    assert p_fault == p_clean and t_fault == t_clean


# ---------------------------------------------------------------------------
# DeviceFeed cursor


def test_device_feed_position_and_skip():
    from mxnet_tpu.pipeline import DeviceFeed

    feed = DeviceFeed((onp.full((2,), float(i), "f") for i in range(6)),
                      depth=2)
    assert feed.position == 0
    it = iter(feed)
    next(it), next(it)
    assert feed.position == 2
    feed.close()
    # skip repositions a fresh one-shot source before iteration
    feed2 = DeviceFeed((onp.full((2,), float(i), "f")
                        for i in range(6)), depth=0)
    feed2.skip(4)
    assert feed2.position == 4  # the cursor stays ABSOLUTE in the epoch
    vals = [float(b.asnumpy()[0]) for b in feed2]
    assert vals == [4.0, 5.0]
    assert feed2.position == 6  # skip base + delivered
    # a re-iterable source would silently rewind: refuse it
    with pytest.raises(RuntimeError):
        DeviceFeed([onp.zeros(2, "f")] * 3, depth=0).skip(1)


# ---------------------------------------------------------------------------
# serving degradation


def _mlp(seed=0):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    with autograd.pause(train_mode=False):
        net(nd.zeros((1, 8)))
    return net


def test_serving_bucket_demotes_to_jit_path_and_recovers():
    from mxnet_tpu import serving

    net = _mlp()
    sess = serving.InferenceSession(net, input_shapes=[(1, 8)],
                                    buckets=[4])
    x = onp.random.RandomState(0).rand(4, 8).astype("f")
    with autograd.pause(train_mode=False):
        ref = net(nd.array(x)).asnumpy()
    faults.arm({"serving_execute": dict(every=1, times=2)})
    for _ in range(2):
        with pytest.raises(InjectedFault):
            sess.predict(x)
    faults.disarm()
    assert sess.degraded == [4]  # demoted off the AOT executable
    out = sess.predict(x).asnumpy()  # jit path serves, bitwise-equal
    onp.testing.assert_array_equal(out, ref)
    assert sess.breaker_states()[4] == "closed"  # success reset it
    assert resilience.resilience_counters()["breaker_demotions"] == 1


def test_serving_breaker_opens_and_fails_fast(monkeypatch):
    from mxnet_tpu import serving

    monkeypatch.setenv("MXNET_BREAKER_THRESHOLD", "3")
    monkeypatch.setenv("MXNET_BREAKER_COOLDOWN_MS", "60000")
    sess = serving.InferenceSession(_mlp(), input_shapes=[(1, 8)],
                                    buckets=[4])
    x = onp.zeros((4, 8), "f")
    faults.arm({"serving_execute": dict(every=1, times=3)})
    for _ in range(3):
        with pytest.raises(InjectedFault):
            sess.predict(x)
    faults.disarm()
    with pytest.raises(CircuitOpen):
        sess.predict(x)  # open circuit: fail fast, no execution
    assert sess.breaker_states()[4] == "open"
    c = resilience.resilience_counters()
    assert c["breaker_trips"] == 1
    assert c["breaker_fast_fails"] >= 1


def test_serving_batcher_isolates_injected_batch_failure():
    """An injected execution fault fails that batch's requests and
    later requests succeed — the batcher/worker survives."""
    from mxnet_tpu import serving

    net = _mlp()
    sess = serving.InferenceSession(net, input_shapes=[(1, 8)],
                                    buckets=[4])
    batcher = serving.DynamicBatcher(sess, max_batch_size=4,
                                     max_latency_ms=1.0)
    try:
        x = onp.random.RandomState(1).rand(2, 8).astype("f")
        with faults.inject("serving_execute", at=1):
            with pytest.raises(InjectedFault):
                batcher.predict(x)
        out = batcher.predict(x)
        with autograd.pause(train_mode=False):
            ref = net(nd.array(x)).asnumpy()
        onp.testing.assert_array_equal(onp.asarray(out), ref)
    finally:
        batcher.close()


# ---------------------------------------------------------------------------
# observability surfaces


def test_profiler_runtime_and_dump_surfaces(tmp_path):
    from mxnet_tpu import profiler, runtime

    c = profiler.resilience_counters()
    assert "ckpt_saves" in c and "retry_attempts" in c \
        and "breaker_trips" in c and "fault_fires" in c
    feats = runtime.Features()
    assert "RESILIENCE" in feats
    assert feats.is_enabled("RESILIENCE")
    fname = str(tmp_path / "prof.json")
    profiler.set_config(filename=fname)
    try:
        out = profiler.dump()
        with open(out) as f:
            events = json.load(f)["traceEvents"]
        assert any(e["name"].startswith("resilience/") for e in events)
    finally:
        profiler.set_config(filename="profile.json")


def test_resilience_feature_off(monkeypatch):
    monkeypatch.setenv("MXNET_RESILIENCE", "0")
    from mxnet_tpu import runtime

    assert not runtime.Features().is_enabled("RESILIENCE")
    assert resilience.resilience_counters()["enabled"] is False


# ---------------------------------------------------------------------------
# the hard one: SIGKILL mid-epoch, restart, bitwise parity


def _run_child(env_extra, check=True):
    env = dict(os.environ)
    env.pop("MXNET_FAULT_PLAN", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra)
    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__),
                      "_resilience_child.py")],
        capture_output=True, text=True, env=env, timeout=300)
    if check and proc.returncode != 0:
        raise AssertionError(
            f"child failed rc={proc.returncode}\nstdout:{proc.stdout}"
            f"\nstderr:{proc.stderr}")
    return proc


def test_sigkill_mid_epoch_resumes_bitwise(tmp_path):
    """Satellite: SIGKILL a training subprocess mid-epoch (a hard
    crash — no atexit, the async checkpoint writer dies wherever it
    was), restart the same command, and AutoResume reaches final
    params and a loss trace BITWISE-identical to a never-killed run."""
    cache = str(tmp_path / "compile_cache")
    base = {"MXNET_COMPILE_CACHE_DIR": cache}
    # uninterrupted reference
    ref_out = str(tmp_path / "ref.npz")
    _run_child({**base, "RESIL_CKPT_DIR": str(tmp_path / "ck_ref"),
                "RESIL_OUT": ref_out})
    # killed mid-epoch-2 (global step 8 of 12; last checkpoint at 6)
    kill_dir = str(tmp_path / "ck_kill")
    proc = _run_child({**base, "RESIL_CKPT_DIR": kill_dir,
                       "RESIL_KILL_AT": "8"}, check=False)
    assert proc.returncode == -9, proc.stderr  # really SIGKILLed
    assert os.listdir(kill_dir)  # checkpoints survived the crash
    # restart: restores the newest valid checkpoint and finishes
    res_out = str(tmp_path / "resumed.npz")
    proc = _run_child({**base, "RESIL_CKPT_DIR": kill_dir,
                       "RESIL_OUT": res_out})
    assert "done" in proc.stdout
    ref = onp.load(ref_out)
    res = onp.load(res_out)
    assert sorted(ref.files) == sorted(res.files)
    for k in ref.files:
        assert ref[k].tobytes() == res[k].tobytes(), \
            f"{k} diverged after kill+resume"
