"""Deeper numeric oracles for detection/contrib ops.

Reference cases: tests/python/unittest/test_contrib_operator.py
(multibox/box_nms edge cases) — the round-2 VERDICT flagged these
families as riding on smoke tests; this suite pins the arithmetic.
"""
import numpy as onp
import pytest

from mxnet_tpu import nd, autograd


def _iou(a, b):
    x1 = max(a[0], b[0])
    y1 = max(a[1], b[1])
    x2 = min(a[2], b[2])
    y2 = min(a[3], b[3])
    inter = max(0.0, x2 - x1) * max(0.0, y2 - y1)
    ua = (a[2] - a[0]) * (a[3] - a[1]) + (b[2] - b[0]) * (b[3] - b[1]) \
        - inter
    return inter / ua if ua > 0 else 0.0


def test_box_iou_oracle_grid():
    rng = onp.random.RandomState(0)
    a = onp.sort(rng.rand(5, 2, 2), axis=-1).reshape(5, 4).astype("f")
    b = onp.sort(rng.rand(7, 2, 2), axis=-1).reshape(7, 4).astype("f")
    a = a[:, [0, 2, 1, 3]]
    b = b[:, [0, 2, 1, 3]]
    got = nd.contrib.box_iou(nd.array(a), nd.array(b),
                             format="corner").asnumpy()
    for i in range(5):
        for j in range(7):
            onp.testing.assert_allclose(got[i, j], _iou(a[i], b[j]),
                                        rtol=1e-5, atol=1e-6)


def test_box_nms_suppression_order():
    # three boxes: #1 overlaps #0 heavily (suppressed), #2 is disjoint
    dets = onp.array([[0, 0.9, 0.0, 0.0, 1.0, 1.0],
                      [0, 0.8, 0.05, 0.05, 1.05, 1.05],
                      [0, 0.7, 2.0, 2.0, 3.0, 3.0]], "f")[None]
    out = nd.contrib.box_nms(nd.array(dets), overlap_thresh=0.5,
                             coord_start=2, score_index=1,
                             id_index=0).asnumpy()[0]
    kept = out[out[:, 0] >= 0]
    scores = sorted(kept[:, 1].tolist(), reverse=True)
    assert scores == [pytest.approx(0.9), pytest.approx(0.7)]


def test_multibox_prior_counts_and_centers():
    x = nd.zeros((1, 3, 4, 4))
    anchors = nd.contrib.MultiBoxPrior(
        x, sizes=[0.5, 0.25], ratios=[1.0, 2.0]).asnumpy()[0]
    # per cell: sizes + ratios - 1 anchors (reference convention)
    assert anchors.shape == (4 * 4 * 3, 4)
    # first cell's first anchor centers on pixel center (0.5/4)
    cx = (anchors[0, 0] + anchors[0, 2]) / 2
    cy = (anchors[0, 1] + anchors[0, 3]) / 2
    onp.testing.assert_allclose([cx, cy], [0.125, 0.125], atol=1e-6)


def test_multibox_target_encodes_offsets():
    # one anchor exactly on the gt box -> offsets ~ 0, class set
    anchors = onp.array([[0.1, 0.1, 0.4, 0.4],
                         [0.6, 0.6, 0.9, 0.9]], "f")[None]
    label = onp.array([[[0, 0.1, 0.1, 0.4, 0.4]]], "f")  # cls 0 box
    cls_preds = onp.zeros((1, 2, 2), "f")
    t_loc, t_mask, t_cls = nd.contrib.MultiBoxTarget(
        nd.array(anchors), nd.array(label), nd.array(cls_preds),
        overlap_threshold=0.5, negative_mining_ratio=-1)
    t_cls = t_cls.asnumpy()[0]
    t_loc = t_loc.asnumpy()[0]
    assert t_cls[0] == 1  # anchor 0 assigned to class 0 (+1 background)
    assert t_cls[1] == 0  # anchor 1 background
    onp.testing.assert_allclose(t_loc[:4], onp.zeros(4), atol=1e-5)


def test_multibox_detection_decodes_offsets():
    anchors = onp.array([[0.25, 0.25, 0.75, 0.75]], "f")[None]
    cls_prob = onp.array([[[0.1], [0.9]]], "f")  # bg, cls0
    loc_pred = onp.zeros((1, 4), "f")  # zero offsets -> anchor itself
    out = nd.contrib.MultiBoxDetection(
        nd.array(cls_prob), nd.array(loc_pred), nd.array(anchors),
        threshold=0.5).asnumpy()[0]
    det = out[out[:, 0] >= 0][0]
    assert det[0] == 0  # class id
    onp.testing.assert_allclose(det[1], 0.9, rtol=1e-5)
    onp.testing.assert_allclose(det[2:], [0.25, 0.25, 0.75, 0.75],
                                atol=1e-5)


def test_roi_align_matches_bilinear_oracle():
    # 1x1 output over an axis-aligned roi equals the bilinear sample at
    # the roi's sampled points' mean
    x = onp.arange(16, dtype="f").reshape(1, 1, 4, 4)
    rois = onp.array([[0, 1.0, 1.0, 2.0, 2.0]], "f")
    out = nd.contrib.ROIAlign(nd.array(x), nd.array(rois),
                              pooled_size=(1, 1), spatial_scale=1.0,
                              sample_ratio=1).asnumpy()
    # sample point at roi center (1.5, 1.5): bilinear of 5,6,9,10 = 7.5
    onp.testing.assert_allclose(out[0, 0, 0, 0], 7.5, rtol=1e-5)


def test_roi_align_gradient_flows_to_covered_pixels():
    x = nd.array(onp.random.RandomState(0).rand(1, 1, 6, 6).astype("f"))
    rois = nd.array(onp.array([[0, 0.0, 0.0, 3.0, 3.0]], "f"))
    x.attach_grad()
    with autograd.record():
        out = nd.contrib.ROIAlign(x, rois, pooled_size=(2, 2),
                                  spatial_scale=1.0, sample_ratio=1)
        loss = nd.sum(out)
    loss.backward()
    g = x.grad.asnumpy()[0, 0]
    assert g[:4, :4].sum() > 0   # covered region gets gradient
    assert abs(g[5:, 5:]).sum() < 1e-6  # far corner untouched


def test_smooth_l1_piecewise():
    x = onp.array([-2.0, -0.5, 0.0, 0.5, 2.0], "f")
    out = nd.smooth_l1(nd.array(x), scalar=1.0).asnumpy()
    expect = onp.where(onp.abs(x) < 1.0, 0.5 * x * x,
                       onp.abs(x) - 0.5)
    onp.testing.assert_allclose(out, expect, rtol=1e-6)


def test_bipartite_matching_greedy_order():
    score = onp.array([[0.9, 0.8], [0.85, 0.1]], "f")[None]
    rows, cols = nd.contrib.bipartite_matching(
        nd.array(score), threshold=0.0)
    rows = rows.asnumpy()[0].astype(int)
    # greedy: (0,0)=0.9 first, then (1,?) only col 1 left -> 0.1
    assert rows[0] == 0 and rows[1] == 1
