"""tools/graft_lint inside tier-1: the framework must lint clean, and
every lint check must still fire on the seeded violation fixture."""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import graft_lint  # noqa: E402

FIXTURE = os.path.join(REPO, "tests", "fixtures", "lint_violation.py")
PIPE_FIXTURE = os.path.join(REPO, "tests", "fixtures",
                            "pipeline_sync_violation.py")
EXC_FIXTURE = os.path.join(REPO, "tests", "fixtures",
                           "lint_bare_except.py")
CLOCK_FIXTURE = os.path.join(REPO, "tests", "fixtures",
                             "lint_wallclock_deadline.py")
MUT_FIXTURE = os.path.join(REPO, "tests", "fixtures",
                           "lint_graph_mutation.py")
SHARD_FIXTURE = os.path.join(REPO, "tests", "fixtures",
                             "lint_raw_sharding.py")
PALLAS_FIXTURE = os.path.join(REPO, "tests", "fixtures",
                              "lint_raw_pallas.py")
CTR_FIXTURE = os.path.join(REPO, "tests", "fixtures",
                           "lint_raw_counter.py")
SALT_FIXTURE = os.path.join(REPO, "tests", "fixtures",
                            "lint_salt_assembly.py")
LOCK_FIXTURE = os.path.join(REPO, "tests", "fixtures",
                            "lint_raw_lock.py")
GUARD_FIXTURE = os.path.join(REPO, "tests", "fixtures",
                             "lint_guarded_by.py")
POLICY_FIXTURE = os.path.join(REPO, "tests", "fixtures",
                              "lint_policy_literal.py")


def test_shipped_tree_lints_clean():
    """The acceptance gate: mxnet_tpu/ carries zero violations —
    env-read discipline, jit-body safety, op docstring coverage, and
    the registry/dtype-table consistency checks."""
    findings = graft_lint.lint_paths(
        [os.path.join(REPO, "mxnet_tpu")], repo_root=REPO, registry=True)
    assert findings == [], "\n".join(str(f) for f in findings)


def test_fixture_triggers_every_check():
    findings = graft_lint.lint_paths([FIXTURE], repo_root=REPO,
                                     registry=False)
    codes = {f.code for f in findings}
    assert {"L101", "L102", "L201", "L202", "L301",
            "jit-nocache"} <= codes, codes
    # the three distinct host-sync species are each caught
    msgs = "\n".join(f.message for f in findings)
    assert "host clock" in msgs
    assert "numpy RNG" in msgs
    assert "print()" in msgs


def test_step_sync_fixture_triggers_each_species():
    """L401: every blocking-host-sync species in the seeded step-loop
    fixture is flagged, and the allow(L401) epoch-end site is not."""
    findings = graft_lint.lint_paths([PIPE_FIXTURE], repo_root=REPO,
                                     registry=False)
    l401 = [f for f in findings if f.code == "L401"]
    msgs = "\n".join(f.message for f in l401)
    for species in (".asnumpy()", ".item()", ".wait_to_read()",
                    ".block_until_ready()", "onp.asarray"):
        assert species in msgs, msgs
    assert len(l401) == 5, l401
    # the pragma'd whitelisted_epoch_end sync is suppressed
    assert all(f.line < 32 for f in l401), l401


def test_bare_except_fixture_triggers_l501():
    """L501: the seeded fixture's bare except and pass-only broad
    handlers are flagged; the narrow/handled/pragma'd sites are not."""
    findings = graft_lint.lint_paths([EXC_FIXTURE], repo_root=REPO,
                                     registry=False)
    l501 = [f for f in findings if f.code == "L501"]
    assert len(l501) == 3, l501  # bare + Exception-pass + tuple-Base
    msgs = "\n".join(f.message for f in l501)
    assert "bare 'except:'" in msgs
    assert "silently swallows" in msgs
    # each finding anchors to an actual except line of the fixture
    src = open(EXC_FIXTURE).read().splitlines()
    for f in l501:
        assert src[f.line - 1].lstrip().startswith("except"), \
            (f.line, src[f.line - 1])
    assert {f.code for f in findings} == {"L501"}, findings


def test_wallclock_fixture_triggers_l602():
    """L602: every wall-clock species in the seeded deadline fixture
    is flagged — dotted time.time(), the aliased `from time import
    time` form — and the monotonic and allow(L602) sites are not."""
    findings = graft_lint.lint_paths([CLOCK_FIXTURE], repo_root=REPO,
                                     registry=False)
    l602 = [f for f in findings if f.code == "L602"]
    assert len(l602) == 3, findings  # deadline + queue exit + alias
    src = open(CLOCK_FIXTURE).read().splitlines()
    for f in l602:
        line = src[f.line - 1]
        assert "time.time()" in line or "now()" in line, (f.line, line)
    # the good_monotonic and pragma'd sites stay clean
    assert all(f.line < 30 for f in l602), l602
    assert {f.code for f in findings} == {"L602"}, findings


def test_wallclock_scope_is_serving_plus_marker(tmp_path):
    """The L602 discipline binds mxnet_tpu/serving/ automatically and
    other files only via the scope(serving-deadline) marker."""
    src = "import time\n\ndef stamp():\n    return time.time()\n"
    free = tmp_path / "stamp_frag.py"
    free.write_text(src)
    assert graft_lint.lint_paths([str(free)], repo_root=REPO,
                                 registry=False) == []
    scoped = tmp_path / "mxnet_tpu" / "serving" / "frag.py"
    scoped.parent.mkdir(parents=True)
    scoped.write_text(src)
    codes = [fi.code for fi in graft_lint.lint_paths(
        [str(scoped)], repo_root=REPO, registry=False)]
    assert codes == ["L602"]


def test_graph_mutation_fixture_triggers_l601():
    """L601: every graph-node-mutation species in the seeded fixture is
    flagged — field assignment, .append() on _inputs, subscripted attr
    write, .update() on kwargs — while reads, self-receiver fields and
    the allow(L601) site are not."""
    findings = graft_lint.lint_paths([MUT_FIXTURE], repo_root=REPO,
                                     registry=False)
    l601 = [f for f in findings if f.code == "L601"]
    assert len(l601) == 4, findings
    src = open(MUT_FIXTURE).read().splitlines()
    for f in l601:
        assert "node._" in src[f.line - 1], (f.line, src[f.line - 1])
    # everything below bad_rewire (reads, OwnFields, pragma) is clean
    assert all(f.line < 24 for f in l601), l601
    assert {f.code for f in findings} == {"L601"}, findings


def test_graph_mutation_scope_binds_package_not_passes(tmp_path):
    """L601 binds mxnet_tpu/ automatically but exempts the pass
    manager (analysis/) and the Symbol constructors (symbol/); outside
    the package it is opt-in via scope(symbol-graph)."""
    src = "def rewire(node, y):\n    node._inputs.append(y)\n"
    free = tmp_path / "rewire_frag.py"
    free.write_text(src)
    assert graft_lint.lint_paths([str(free)], repo_root=REPO,
                                 registry=False) == []
    pkg = tmp_path / "mxnet_tpu" / "contrib" / "frag.py"
    pkg.parent.mkdir(parents=True)
    pkg.write_text(src)
    codes = [fi.code for fi in graft_lint.lint_paths(
        [str(pkg)], repo_root=REPO, registry=False)]
    assert codes == ["L601"]
    passes = tmp_path / "mxnet_tpu" / "analysis" / "frag.py"
    passes.parent.mkdir(parents=True)
    passes.write_text(src)
    assert graft_lint.lint_paths([str(passes)], repo_root=REPO,
                                 registry=False) == []


def test_raw_sharding_fixture_triggers_l701():
    """L701: every construction form in the seeded fixture is flagged
    — direct NamedSharding + aliased PartitionSpec on one line, the
    fully-dotted and module-aliased forms — while the pragma'd site,
    attribute reads and same-named classes on other modules are not."""
    findings = graft_lint.lint_paths([SHARD_FIXTURE], repo_root=REPO,
                                     registry=False)
    l701 = [f for f in findings if f.code == "L701"]
    assert len(l701) == 4, findings
    msgs = "\n".join(f.message for f in l701)
    assert "NamedSharding" in msgs and "PartitionSpec" in msgs
    src = open(SHARD_FIXTURE).read().splitlines()
    for f in l701:
        assert "Sharding" in src[f.line - 1] or \
            "PartitionSpec" in src[f.line - 1], (f.line, src[f.line - 1])
    # the allow(L701) site and the non-construction sites stay clean
    assert all(f.line < 25 for f in l701), l701
    assert {f.code for f in findings} == {"L701"}, findings


def test_raw_sharding_scope_exempts_subsystem(tmp_path):
    """L701 binds mxnet_tpu/ automatically but exempts the sharding
    subsystem and parallel/ (which own the primitives); outside the
    package it is opt-in via scope(sharding-plan)."""
    src = ("from jax.sharding import NamedSharding, PartitionSpec\n"
           "def place(mesh):\n"
           "    return NamedSharding(mesh, PartitionSpec('dp'))\n")
    free = tmp_path / "place_frag.py"
    free.write_text(src)
    assert graft_lint.lint_paths([str(free)], repo_root=REPO,
                                 registry=False) == []
    pkg = tmp_path / "mxnet_tpu" / "serving" / "frag.py"
    pkg.parent.mkdir(parents=True)
    pkg.write_text(src)
    codes = [fi.code for fi in graft_lint.lint_paths(
        [str(pkg)], repo_root=REPO, registry=False)]
    assert codes == ["L701", "L701"], codes
    for exempt in ("sharding", "parallel"):
        own = tmp_path / "mxnet_tpu" / exempt / "frag.py"
        own.parent.mkdir(parents=True)
        own.write_text(src)
        assert graft_lint.lint_paths([str(own)], repo_root=REPO,
                                     registry=False) == [], exempt


def test_raw_pallas_fixture_triggers_l801():
    """L801: every Pallas import form in the seeded fixture is flagged
    — module import, dotted tpu submodule, from-experimental, and
    from-pallas — while the pragma'd site and sibling experimental
    imports stay clean."""
    findings = graft_lint.lint_paths([PALLAS_FIXTURE], repo_root=REPO,
                                     registry=False)
    l801 = [f for f in findings if f.code == "L801"]
    assert len(l801) == 4, findings
    src = open(PALLAS_FIXTURE).read().splitlines()
    for f in l801:
        assert "pallas" in src[f.line - 1], (f.line, src[f.line - 1])
    # the allow(L801) site and the non-pallas imports stay clean
    assert all(f.line < 15 for f in l801), l801
    assert {f.code for f in findings} == {"L801"}, findings


def test_raw_counter_fixture_triggers_l901():
    """L901: every raw-counter-mutation species in the seeded fixture
    is flagged — subscript write, augmented bump, .update(), .clear()
    — while reads, the registry-bound form and the allow(L901)
    bootstrap site are not."""
    findings = graft_lint.lint_paths([CTR_FIXTURE], repo_root=REPO,
                                     registry=False)
    l901 = [f for f in findings if f.code == "L901"]
    assert len(l901) == 4, findings
    msgs = "\n".join(f.message for f in l901)
    assert "counter_family" in msgs
    src = open(CTR_FIXTURE).read().splitlines()
    for f in l901:
        line = src[f.line - 1]
        assert "_COUNTERS" in line or "_STATS" in line, (f.line, line)
    # good_read and the pragma'd bootstrap site stay clean
    assert all(f.line < 43 for f in l901), l901
    assert {f.code for f in findings} == {"L901"}, findings


def test_raw_counter_scope_exempts_telemetry_package(tmp_path):
    """L901 binds mxnet_tpu/ automatically but exempts
    mxnet_tpu/telemetry/ (which owns the CounterFamily primitive);
    outside the package it is opt-in via scope(counter-registry), and
    a counter_family(...) binding is never flagged."""
    src = ('_COUNTERS = {"hits": 0}\n'
           "def bump():\n"
           '    _COUNTERS["hits"] += 1\n')
    free = tmp_path / "ctr_frag.py"
    free.write_text(src)
    assert graft_lint.lint_paths([str(free)], repo_root=REPO,
                                 registry=False) == []
    pkg = tmp_path / "mxnet_tpu" / "utils" / "frag.py"
    pkg.parent.mkdir(parents=True)
    pkg.write_text(src)
    codes = [fi.code for fi in graft_lint.lint_paths(
        [str(pkg)], repo_root=REPO, registry=False)]
    assert codes == ["L901"], codes
    own = tmp_path / "mxnet_tpu" / "telemetry" / "frag.py"
    own.parent.mkdir(parents=True)
    own.write_text(src)
    assert graft_lint.lint_paths([str(own)], repo_root=REPO,
                                 registry=False) == []
    blessed = tmp_path / "mxnet_tpu" / "utils" / "frag2.py"
    blessed.write_text(
        "from ..telemetry import metrics as _telemetry\n"
        '_COUNTERS = _telemetry.counter_family("frag")\n'
        "def bump():\n"
        '    _COUNTERS.add("hits")\n')
    assert graft_lint.lint_paths([str(blessed)], repo_root=REPO,
                                 registry=False) == []


def test_raw_pallas_scope_exempts_kernels_package(tmp_path):
    """L801 binds mxnet_tpu/ automatically but exempts
    mxnet_tpu/kernels/ (which owns the Pallas code); outside the
    package it is opt-in via scope(pallas-kernels)."""
    src = ("from jax.experimental import pallas as pl\n"
           "def kern(x_ref, o_ref):\n"
           "    o_ref[...] = x_ref[...]\n")
    free = tmp_path / "kern_frag.py"
    free.write_text(src)
    assert graft_lint.lint_paths([str(free)], repo_root=REPO,
                                 registry=False) == []
    pkg = tmp_path / "mxnet_tpu" / "ndarray" / "frag.py"
    pkg.parent.mkdir(parents=True)
    pkg.write_text(src)
    codes = [fi.code for fi in graft_lint.lint_paths(
        [str(pkg)], repo_root=REPO, registry=False)]
    assert codes == ["L801"], codes
    own = tmp_path / "mxnet_tpu" / "kernels" / "frag.py"
    own.parent.mkdir(parents=True)
    own.write_text(src)
    assert graft_lint.lint_paths([str(own)], repo_root=REPO,
                                 registry=False) == []


def test_salt_assembly_fixture_triggers_l1001():
    """L1001: every ad-hoc salt/fingerprint-assembly species in the
    seeded fixture is flagged — method-form fingerprint_salt, bare
    provider-function call, raw compile_cache.fingerprint via the
    module alias and via the from-import alias — while the sanctioned
    CompiledArtifact(salts=...) site and the allow(L1001) legacy site
    are not."""
    findings = graft_lint.lint_paths([SALT_FIXTURE], repo_root=REPO,
                                     registry=False)
    l1001 = [f for f in findings if f.code == "L1001"]
    assert len(l1001) == 4, findings
    msgs = "\n".join(f.message for f in l1001)
    assert "register_salt_provider" in msgs
    assert "CompiledArtifact(salts=...)" in msgs
    assert {f.code for f in findings} == {"L1001"}, findings


def test_salt_scope_exempts_artifact_and_providers(tmp_path):
    """L1001 binds mxnet_tpu/ automatically but exempts the artifact
    package (which owns fingerprint composition) and any file that
    DEFINES a salt provider; outside the package it is opt-in via
    scope(salt-providers)."""
    src = ("def consume(plan, mesh):\n"
           "    return plan.fingerprint_salt(mesh)\n")
    free = tmp_path / "salt_frag.py"
    free.write_text(src)
    assert graft_lint.lint_paths([str(free)], repo_root=REPO,
                                 registry=False) == []
    pkg = tmp_path / "mxnet_tpu" / "gluon" / "frag.py"
    pkg.parent.mkdir(parents=True)
    pkg.write_text(src)
    codes = [f.code for f in graft_lint.lint_paths(
        [str(pkg)], repo_root=REPO, registry=False)]
    assert codes == ["L1001"], codes
    own = tmp_path / "mxnet_tpu" / "artifact" / "frag.py"
    own.parent.mkdir(parents=True)
    own.write_text(src)
    assert graft_lint.lint_paths([str(own)], repo_root=REPO,
                                 registry=False) == []
    prov = tmp_path / "mxnet_tpu" / "gluon" / "prov.py"
    prov.write_text(
        src + "\n\ndef fingerprint_salt(x):\n    return (x,)\n")
    assert graft_lint.lint_paths([str(prov)], repo_root=REPO,
                                 registry=False) == []


def test_raw_lock_fixture_triggers_l1101_and_l1103():
    """L1101: every raw-construction species in the seeded fixture is
    flagged — module-attr Lock, from-imported RLock/Condition, aliased
    module, in-function construction — while the RankedLock factory
    and the allow(L1101) harness site are not. L1103: every blocking
    species inside the ``with <ranked-lock>`` body fires — host sync,
    sleep, file IO, HTTP, retry machinery — while the same calls
    outside the lock and the allow(L1103) site stay clean."""
    findings = graft_lint.lint_paths([LOCK_FIXTURE], repo_root=REPO,
                                     registry=False)
    l1101 = [f for f in findings if f.code == "L1101"]
    l1103 = [f for f in findings if f.code == "L1103"]
    assert len(l1101) == 6, findings
    assert len(l1103) == 6, findings
    msgs = "\n".join(f.message for f in l1101)
    assert "RankedLock" in msgs and "RankedCondition" in msgs
    blocked = "\n".join(f.message for f in l1103)
    for species in ("host sync", "sleep", "file IO", "HTTP",
                    "RetryPolicy", "retry loop"):
        assert species in blocked, (species, blocked)
    # every L1103 lands inside bad_blocking_under_lock, none in the
    # outside-the-lock twin or the pragma'd site
    src = open(LOCK_FIXTURE).read().splitlines()
    bad = next(i for i, ln in enumerate(src, 1)
               if "def bad_blocking_under_lock" in ln)
    good = next(i for i, ln in enumerate(src, 1)
                if "def good_blocking_outside_lock" in ln)
    assert all(bad < f.line < good for f in l1103), l1103
    assert {f.code for f in findings} == {"L1101", "L1103"}, findings


def test_guarded_by_fixture_triggers_l1102():
    """L1102: unlocked access to a ``# guards:`` attribute fires for
    both the module-global and the instance-attr form, while every
    sanctioned holding idiom in the fixture — with-block, shared-lock
    condition, acquire/release, getattr alias, *_locked helper,
    __init__, allow(L1102) — stays clean."""
    findings = graft_lint.lint_paths([GUARD_FIXTURE], repo_root=REPO,
                                     registry=False)
    assert {f.code for f in findings} == {"L1102"}, findings
    assert len(findings) == 3, findings
    src = open(GUARD_FIXTURE).read().splitlines()
    flagged = {src[f.line - 1].strip().split("#")[0].strip()
               for f in findings}
    assert flagged == {"return _REGISTRY.get(name)",
                       "return self._slots.get(sid)",
                       "self._closed = True"}, flagged


def test_policy_literal_fixture_triggers_l1201():
    """L1201: every policy-literal species in the seeded fixture is
    flagged — bare module constant, literal shift, unary minus,
    literal product, and both inline-comparison forms — while the
    ``declare_decision`` result, the lowercase binding, the structural
    small constants (len >= 2, != 0, % 8 == 0), the lookup-backed
    named threshold, and both allow(L1201) sites stay clean."""
    findings = graft_lint.lint_paths([POLICY_FIXTURE], repo_root=REPO,
                                     registry=False)
    assert {f.code for f in findings} == {"L1201"}, findings
    l1201 = [f for f in findings if f.code == "L1201"]
    assert len(l1201) == 6, l1201
    msgs = "\n".join(f.message for f in l1201)
    for constant in ("_BAD_THRESHOLD", "BAD_BYTES_CAP",
                     "_BAD_NEGATIVE", "_BAD_PRODUCT"):
        assert constant in msgs, (constant, msgs)
    # the literal-shift comparator is reported by VALUE (1 << 22)
    assert "4194304" in msgs, msgs
    # every inline finding lands inside bad_inline_compare, none in
    # the structural twin or the pragma'd site
    src = open(POLICY_FIXTURE).read().splitlines()
    bad = next(i for i, ln in enumerate(src, 1)
               if "def bad_inline_compare" in ln)
    good = next(i for i, ln in enumerate(src, 1)
                if "def good_structural_compares" in ln)
    inline = [f for f in l1201 if "inline comparison" in f.message]
    assert len(inline) == 2 and \
        all(bad < f.line < good for f in inline), inline


def test_policy_literal_scope_binds_cost_model_only(tmp_path):
    """The decision-point discipline binds the fusion cost-model pair
    automatically and is opt-in elsewhere: the same bare threshold in
    a free-standing file (or any other mxnet_tpu file) is not
    flagged."""
    src = "_THRESHOLD = 64\n"
    free = tmp_path / "policy_frag.py"
    free.write_text(src)
    assert graft_lint.lint_paths([str(free)], repo_root=REPO,
                                 registry=False) == []
    scoped = tmp_path / "policy_scoped.py"
    scoped.write_text("# graft-lint: scope(policy-literal)\n" + src)
    got = graft_lint.lint_paths([str(scoped)], repo_root=REPO,
                                registry=False)
    assert [f.code for f in got] == ["L1201"], got


def test_ranked_lock_scope_exempts_locks_module(tmp_path):
    """The lock discipline binds mxnet_tpu/ automatically but exempts
    utils/locks.py (which owns the primitive and the witness's raw
    internals); outside the package it is opt-in via
    scope(ranked-locks)."""
    src = ("import threading\n"
           "_L = threading.Lock()\n")
    free = tmp_path / "lock_frag.py"
    free.write_text(src)
    assert graft_lint.lint_paths([str(free)], repo_root=REPO,
                                 registry=False) == []
    pkg = tmp_path / "mxnet_tpu" / "serving" / "frag.py"
    pkg.parent.mkdir(parents=True)
    pkg.write_text(src)
    codes = [f.code for f in graft_lint.lint_paths(
        [str(pkg)], repo_root=REPO, registry=False)]
    assert codes == ["L1101"], codes
    own = tmp_path / "mxnet_tpu" / "utils" / "locks.py"
    own.parent.mkdir(parents=True)
    own.write_text(src)
    assert graft_lint.lint_paths([str(own)], repo_root=REPO,
                                 registry=False) == []


def test_l501_swallowed_variants(tmp_path):
    """Edge shapes: ellipsis-only body is swallowed; a logging body is
    not; bare except is flagged even with a real body."""
    p = tmp_path / "mod.py"
    p.write_text(
        "import logging\n"
        "def a():\n"
        "    try:\n"
        "        pass\n"
        "    except BaseException:\n"
        "        ...\n"
        "def b():\n"
        "    try:\n"
        "        pass\n"
        "    except Exception:\n"
        "        logging.warning('seen')\n"
        "def c(xs):\n"
        "    try:\n"
        "        pass\n"
        "    except:\n"
        "        xs.append(1)\n")
    findings = graft_lint.lint_paths([str(p)], repo_root=REPO,
                                     registry=False)
    lines = sorted(f.line for f in findings if f.code == "L501")
    assert lines == [5, 15], findings  # a() ellipsis + c() bare


def test_step_sync_scope_is_opt_in_outside_pipeline(tmp_path):
    """The L401 discipline binds pipeline/trainer modules automatically
    and other files only via the scope(step-loop) marker — a metric
    helper elsewhere may sync freely."""
    src = "def poll(x):\n    return x.asnumpy()\n"
    free = tmp_path / "metrics_frag.py"
    free.write_text(src)
    assert graft_lint.lint_paths([str(free)], repo_root=REPO,
                                 registry=False) == []
    scoped = tmp_path / "loop_frag.py"
    scoped.write_text("# graft-lint: scope(step-loop)\n" + src)
    codes = [fi.code for fi in graft_lint.lint_paths(
        [str(scoped)], repo_root=REPO, registry=False)]
    assert codes == ["L401"]


def test_cli_exit_codes():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    ok = subprocess.run(
        [sys.executable, "-m", "tools.graft_lint", "--no-registry",
         "mxnet_tpu"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=180)
    assert ok.returncode == 0, ok.stdout[-2000:]
    bad = subprocess.run(
        [sys.executable, "-m", "tools.graft_lint", "--no-registry",
         FIXTURE],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=180)
    assert bad.returncode == 1
    assert "L201" in bad.stdout


def test_pragma_suppression(tmp_path):
    src = (
        "import os\n"
        "a = os.environ.get('MXNET_EAGER_JIT')"
        "  # graft-lint: allow(L101)\n"
        "b = os.environ.get('MXNET_EAGER_JIT')\n")
    f = tmp_path / "frag.py"
    f.write_text(src)
    findings = graft_lint.lint_paths([str(f)], repo_root=REPO,
                                     registry=False)
    assert [fi.code for fi in findings] == ["L101"]
    assert findings[0].line == 3


def test_knob_registry_parsed_from_env_module():
    knobs = graft_lint.load_registered_knobs(REPO)
    assert knobs and "MXNET_GRAPH_VERIFY" in knobs
    assert "MXNET_EAGER_JIT" in knobs


def test_jit_scope_detection_covers_all_fronts():
    """fused_step executable bodies and optimizer fused kernels are
    jit scopes; non-op register decorators (optimizer classes) are not."""
    import ast

    path = os.path.join(REPO, "mxnet_tpu", "gluon", "fused_step.py")
    tree = ast.parse(open(path).read(), path)
    labels = {l for _, l in graft_lint.collect_jit_scopes(path, tree)}
    assert any("step" in l for l in labels), labels

    path = os.path.join(REPO, "mxnet_tpu", "optimizer", "optimizer.py")
    tree = ast.parse(open(path).read(), path)
    labels = {l for _, l in graft_lint.collect_jit_scopes(path, tree)}
    assert any("fused kernel" in l for l in labels), labels
    assert not any(l.startswith("op '") for l in labels), labels


def test_registry_checks_catch_fake_gap(monkeypatch):
    """R301/R302 actually look at the live registry: a synthetic
    docless op and a dangling dtype-table entry are both reported."""
    from mxnet_tpu.ndarray import registry as reg
    from mxnet_tpu.symbol import infer as inf

    def undocumented(data):
        return data

    undocumented.__doc__ = None
    monkeypatch.setitem(reg._OPS, "zz_lint_probe",
                        reg.OpDef("zz_lint_probe", undocumented))
    monkeypatch.setitem(inf._FIXED_OUT_DTYPE, "zz_not_registered",
                        None)
    findings = []
    graft_lint.registry_checks(findings)
    codes = {(f.code, "zz" in f.message) for f in findings}
    assert ("R301", True) in codes
    assert ("R302", True) in codes


@pytest.mark.parametrize("snippet,code", [
    ("import time\nfrom .registry import register\n"
     "@register()\ndef op_x(d):\n    '''doc'''\n"
     "    return d * time.time()\n", "L201"),
    ("import jax\nfrom .registry import register\n"
     "@register('y')\ndef op_y(d):\n    '''doc'''\n"
     "    return jax.random.uniform(jax.random.PRNGKey(0), d.shape)\n",
     "L202"),
])
def test_jit_checks_on_snippets(tmp_path, snippet, code):
    f = tmp_path / "ops_frag.py"
    f.write_text(snippet)
    findings = graft_lint.lint_paths([str(f)], repo_root=REPO,
                                     registry=False)
    assert code in {fi.code for fi in findings}, findings
