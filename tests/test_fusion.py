"""Round-17 fusion clustering: cost-model goldens, per-pattern rewrite
goldens, bitwise parity across the eager / hybridized / serving paths,
the MXNET_FUSION kill switch and MXNET_FUSION_PATTERNS selection,
post-verify rejection falling back to the 1:1 lowering, interpret-mode
Pallas kernel parity, and the fused serving pad/slice."""
import numpy as onp
import pytest

import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import autograd, kernels, serving
from mxnet_tpu.analysis import graph_opt
from mxnet_tpu.analysis.graph_opt import _Graph, optimize_symbol
from mxnet_tpu.gluon import SymbolBlock
from mxnet_tpu.kernels import cost_model
from mxnet_tpu.ndarray import registry

nd = mx.nd
sym = mx.sym


@pytest.fixture(autouse=True)
def _armed(monkeypatch):
    monkeypatch.setenv("MXNET_GRAPH_OPT", "2")
    monkeypatch.delenv("MXNET_FUSION", raising=False)
    monkeypatch.delenv("MXNET_FUSION_PATTERNS", raising=False)
    monkeypatch.delenv("MXNET_FUSION_COST_MODEL", raising=False)
    kernels.reset_counters()
    graph_opt.reset_counters()
    yield
    kernels.reset_counters()
    graph_opt.reset_counters()


def _ops(s):
    return sorted(n._op for n in _Graph(s).nodes if n._op is not None)


def _chain(x=None):
    x = x if x is not None else sym.var("x")
    return sym.sqrt(sym.broadcast_add(sym.exp(x), sym.square(x)))


def _norm_act():
    d, g, b = sym.var("data"), sym.var("gamma"), sym.var("beta")
    return sym.leaky_relu(sym.layer_norm(d, g, b), act_type="gelu")


def _attention(scale_op="mul"):
    q, k, v = sym.var("q"), sym.var("k"), sym.var("v")
    s = sym.batch_dot(q, k, transpose_b=True)
    if scale_op == "mul":
        s = sym.broadcast_mul_scalar(s, scalar=0.125)
    elif scale_op == "div":
        s = sym.broadcast_div_scalar(s, scalar=8.0)
    return sym.batch_dot(sym.softmax(s), v)


def _feed(**shapes):
    rs = onp.random.RandomState(7)
    return {k: rs.randn(*v).astype("float32") for k, v in shapes.items()}


def _eval(s, feed):
    return s.eval_with({k: nd.array(v)
                        for k, v in feed.items()}).asnumpy()


# ---------------------------------------------------------------------------
# cost model goldens

def test_cost_model_goldens():
    d = cost_model.decide("elementwise", 1)
    assert (d.fuse, d.reason) == (False, "too_small")
    d = cost_model.decide("elementwise", 3)
    assert (d.fuse, d.impl) == (True, "lax")
    d = cost_model.decide("elementwise", 3, out_shape=(1 << 23,))
    assert (d.fuse, d.reason) == (False, "bandwidth_bound")
    # pallas only on TPU, only at tile-aligned shapes
    d = cost_model.decide("norm_act", 2, out_shape=(256, 512),
                          backend="tpu")
    assert (d.fuse, d.impl) == (True, "pallas")
    d = cost_model.decide("norm_act", 2, out_shape=(256, 100),
                          backend="tpu")
    assert (d.fuse, d.impl) == (True, "lax")
    d = cost_model.decide("norm_act", 2, out_shape=(256, 512),
                          backend="cpu")
    assert (d.fuse, d.impl) == (True, "lax")
    # elementwise has no TPU kernel: lax even on TPU
    d = cost_model.decide("elementwise", 4, out_shape=(256, 512),
                          backend="tpu")
    assert (d.fuse, d.impl) == (True, "lax")
    d = cost_model.decide("attention", 3, mode="never")
    assert (d.fuse, d.reason) == (False, "cost_model_never")
    d = cost_model.decide("attention", 1, mode="always")
    assert d.fuse
    # BENCH_FUSION_r17: lax attention at seq>=64 is compute-bound
    # (0.92x) — reject; below the floor or on either short axis, fuse
    d = cost_model.decide("attention", 3, score_shape=(2, 64, 64))
    assert (d.fuse, d.reason) == (False, "compute_bound_attention")
    d = cost_model.decide("attention", 3, score_shape=(2, 63, 64))
    assert d.fuse
    d = cost_model.decide("attention", 3, score_shape=(2, 6, 6))
    assert d.fuse
    # the Pallas TPU kernel stays profitable at long sequence lengths
    d = cost_model.decide("attention", 3, out_shape=(256, 512),
                          backend="tpu", score_shape=(2, 128, 128))
    assert (d.fuse, d.impl) == (True, "pallas")


def test_cost_model_never_keeps_lowering(monkeypatch):
    monkeypatch.setenv("MXNET_FUSION_COST_MODEL", "never")
    out = _chain()
    opt, st = optimize_symbol(out, shapes={"x": (4, 5)}, subject="never")
    assert "_fused_elementwise" not in _ops(opt)
    assert kernels.counters()["fallback_cost_model_never"] >= 1


# ---------------------------------------------------------------------------
# per-pattern goldens + bitwise parity (lax replay)

def test_elementwise_chain_golden_and_bitwise():
    out = _chain()
    opt, st = optimize_symbol(out, shapes={"x": (4, 5)}, subject="ew")
    assert _ops(opt) == ["_fused_elementwise"]
    c = kernels.counters()
    assert c["clusters_elementwise"] == 1
    assert c["nodes_absorbed"] == 3
    assert c["impl_lax"] == 1
    feed = _feed(x=(4, 5))
    assert (_eval(out, feed) == _eval(opt, feed)).all()


def test_norm_act_golden_and_bitwise():
    out = _norm_act()
    opt, _ = optimize_symbol(
        out, shapes={"data": (8, 16), "gamma": (16,), "beta": (16,)},
        subject="na")
    assert _ops(opt) == ["_fused_norm_act"]
    assert kernels.counters()["clusters_norm_act"] == 1
    feed = _feed(data=(8, 16), gamma=(16,), beta=(16,))
    assert (_eval(out, feed) == _eval(opt, feed)).all()


@pytest.mark.parametrize("scale_op", ["mul", "div", "none"])
def test_attention_golden_and_bitwise(scale_op):
    out = _attention(scale_op)
    shapes = {k: (2, 6, 8) for k in ("q", "k", "v")}
    opt, _ = optimize_symbol(out, shapes=shapes, subject="att")
    assert _ops(opt) == ["_fused_attention"]
    assert kernels.counters()["clusters_attention"] == 1
    feed = _feed(q=(2, 6, 8), k=(2, 6, 8), v=(2, 6, 8))
    assert (_eval(out, feed) == _eval(opt, feed)).all()


def test_attention_compute_bound_seq_not_fused():
    """seq>=64 lax attention is compute-bound (BENCH_FUSION_r17 showed
    the fused replay at 0.92x): the shape-aware cost model must keep
    the 1:1 lowering and count the fallback."""
    out = _attention("mul")
    shapes = {k: (2, 64, 8) for k in ("q", "k", "v")}
    opt, _ = optimize_symbol(out, shapes=shapes, subject="att_cb")
    assert "_fused_attention" not in _ops(opt)
    c = kernels.counters()
    assert c["fallback_compute_bound_attention"] == 1
    assert c.get("clusters_attention", 0) == 0
    feed = _feed(q=(2, 64, 8), k=(2, 64, 8), v=(2, 64, 8))
    assert (_eval(out, feed) == _eval(opt, feed)).all()


def test_multi_consumer_interior_stays_external():
    # exp feeds two consumers: it must NOT be absorbed; the root
    # cluster fuses around it and reads it as an external input
    x = sym.var("x")
    e = sym.exp(x)
    out = sym.sqrt(e) + e
    opt, _ = optimize_symbol(out, shapes={"x": (4, 4)}, subject="mc")
    assert _ops(opt) == ["_fused_elementwise", "exp"]
    feed = _feed(x=(4, 4))
    assert (_eval(out, feed) == _eval(opt, feed)).all()


def test_batch_norm_act_rejected_as_effectful():
    d = sym.var("data")
    g, b = sym.var("gamma"), sym.var("beta")
    mm, mv = sym.var("moving_mean"), sym.var("moving_var")
    out = sym.activation(sym.batch_norm(d, g, b, mm, mv),
                         act_type="relu")
    opt, _ = optimize_symbol(
        out, shapes={"data": (4, 3), "gamma": (3,), "beta": (3,),
                     "moving_mean": (3,), "moving_var": (3,)},
        subject="bn")
    assert "batch_norm" in _ops(opt)
    assert "_fused_norm_act" not in _ops(opt)
    assert kernels.counters()["fallback_effectful"] >= 1


# ---------------------------------------------------------------------------
# knobs

def test_kill_switch_disables_all_patterns(monkeypatch):
    monkeypatch.setenv("MXNET_FUSION", "0")
    out = _chain()
    opt, _ = optimize_symbol(out, shapes={"x": (4, 5)}, subject="off")
    assert "_fused_elementwise" not in _ops(opt)
    assert kernels.counters()["pass_skipped_disabled"] >= 1
    from mxnet_tpu import runtime
    assert runtime._detect()["FUSION"] is False
    monkeypatch.setenv("MXNET_FUSION", "1")
    assert runtime._detect()["FUSION"] is True


def test_patterns_knob_selects_subset(monkeypatch):
    monkeypatch.setenv("MXNET_FUSION_PATTERNS", "norm_act")
    ew, _ = optimize_symbol(_chain(), shapes={"x": (4, 5)},
                            subject="ew-off")
    assert "_fused_elementwise" not in _ops(ew)
    na, _ = optimize_symbol(
        _norm_act(),
        shapes={"data": (8, 16), "gamma": (16,), "beta": (16,)},
        subject="na-on")
    assert "_fused_norm_act" in _ops(na)


def test_fusion_salt_tracks_knobs(monkeypatch):
    armed = graph_opt.fingerprint_salt()
    assert any("fusion" in str(part) for part in armed)
    monkeypatch.setenv("MXNET_FUSION", "0")
    assert kernels.fusion_salt() == ("fusion", 0)
    assert graph_opt.fingerprint_salt() != armed
    monkeypatch.setenv("MXNET_FUSION", "1")
    monkeypatch.setenv("MXNET_FUSION_PATTERNS", "elementwise")
    assert kernels.fusion_salt() != armed[-1]


# ---------------------------------------------------------------------------
# post-verify rejection: a bad fused kernel must not ship

def test_post_verify_rejection_serves_original(monkeypatch):
    good = registry.get_op("_fused_elementwise")

    def bad(*data, program=()):
        """Deliberately unshapeable fused body (test double)."""
        raise ValueError("broken fused kernel")

    monkeypatch.setitem(
        registry._OPS, "_fused_elementwise",
        registry.OpDef("_fused_elementwise", bad, good.differentiable,
                       bad.__doc__, good.namespaces))
    out = _chain()
    opt, st = optimize_symbol(out, shapes={"x": (4, 5)}, subject="bad")
    assert st["rejected"] is True
    assert opt is out  # the original graph is served
    c = kernels.counters()
    assert c["fallback_post_verify"] == 1
    assert graph_opt.counters()["graphs_rejected"] == 1
    feed = _feed(x=(4, 5))
    onp.testing.assert_allclose(_eval(out, feed),
                                onp.sqrt(onp.exp(feed["x"])
                                         + feed["x"] ** 2), rtol=1e-6)


# ---------------------------------------------------------------------------
# interpret-mode Pallas parity (documented-ulp, off-TPU)

def test_norm_act_interpret_matches_lax():
    rs = onp.random.RandomState(3)
    d = jnp.asarray(rs.randn(16, 32).astype("float32"))
    g = jnp.asarray(rs.randn(32).astype("float32"))
    b = jnp.asarray(rs.randn(32).astype("float32"))
    fn = registry.get_op("_fused_norm_act").fn
    kw = dict(norm_kw=(), act_op="leaky_relu",
              act_kw=(("act_type", "gelu"),))
    ref = fn(d, g, b, impl="lax", **kw)
    pal = fn(d, g, b, impl="interpret", **kw)
    assert float(jnp.abs(ref - pal).max()) < 1e-5


def test_attention_interpret_matches_lax():
    rs = onp.random.RandomState(4)
    q, k, v = (jnp.asarray(rs.randn(2, 16, 8).astype("float32"))
               for _ in range(3))
    fn = registry.get_op("_fused_attention").fn
    ref = fn(q, k, v, scale_op="mul", scale=0.125, impl="lax")
    pal = fn(q, k, v, scale_op="mul", scale=0.125, impl="interpret")
    assert float(jnp.abs(ref - pal).max()) < 1e-5


# ---------------------------------------------------------------------------
# hybridized + serving paths

def _chain_block():
    x = sym.var("data")
    blk = SymbolBlock(_chain(x), [x])
    with autograd.pause(train_mode=False):
        blk(nd.zeros((1, 8)))
    return blk


def test_symbolblock_forward_parity(monkeypatch):
    xv = onp.random.RandomState(11).randn(4, 8).astype("float32")
    monkeypatch.setenv("MXNET_FUSION", "0")
    blk = _chain_block()
    with autograd.pause(train_mode=False):
        ref = blk(nd.array(xv)).asnumpy()
    monkeypatch.setenv("MXNET_FUSION", "1")
    with autograd.pause(train_mode=False):
        fused = blk(nd.array(xv)).asnumpy()
    assert (ref == fused).all()
    # the optimized-graph cache re-keyed on the fusion salt
    assert "_fused_elementwise" in [
        n._op for n in blk._optimized_outputs()._walk()]


def test_serving_parity_and_fused_pad_slice():
    blk = _chain_block()
    xv = onp.random.RandomState(12).randn(3, 8).astype("float32")
    with autograd.pause(train_mode=False):
        ref = blk(nd.array(xv)).asnumpy()
    sess = serving.InferenceSession(blk, input_shapes=[(1, 8)],
                                    buckets=[1, 2, 4])
    out = sess.predict(nd.array(xv)).asnumpy()
    onp.testing.assert_array_equal(ref, out)
    c = kernels.counters()
    # batch 3 rides the 4-bucket: one fused pad, one fused slice
    assert c["serving_pad_fused"] >= 1
    assert c["serving_slice_fused"] >= 1


def test_serving_fused_pad_slice_off_is_bitwise_same(monkeypatch):
    blk = _chain_block()
    xv = onp.random.RandomState(13).randn(3, 8).astype("float32")
    sess = serving.InferenceSession(blk, input_shapes=[(1, 8)],
                                    buckets=[1, 2, 4])
    fused = sess.predict(nd.array(xv)).asnumpy()
    monkeypatch.setenv("MXNET_FUSION", "0")
    blk2 = _chain_block()
    sess2 = serving.InferenceSession(blk2, input_shapes=[(1, 8)],
                                     buckets=[1, 2, 4])
    plain = sess2.predict(nd.array(xv)).asnumpy()
    onp.testing.assert_array_equal(fused, plain)


# ---------------------------------------------------------------------------
# observability

def test_profiler_and_prometheus_surface():
    optimize_symbol(_chain(), shapes={"x": (4, 5)}, subject="obs")
    from mxnet_tpu import profiler
    fc = profiler.fusion_counters()
    assert fc["clusters_elementwise"] >= 1
    text = serving.prometheus_text()
    assert "mxnet_fusion_clusters_elementwise_total" in text
