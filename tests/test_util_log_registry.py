"""mx.util / mx.log / mx.registry / mx.kvstore_server parity
(reference: python/mxnet/{util,log,registry,kvstore_server}.py)."""
import logging
import os
import subprocess
import sys

import numpy as onp
import pytest

import mxnet_tpu as mx


def test_util_np_shape_scope_and_decorator():
    from mxnet_tpu import util

    prev = util.is_np_shape()
    with util.np_shape(True):
        assert util.is_np_shape()
        with util.np_shape(False):
            assert not util.is_np_shape()
        assert util.is_np_shape()
    assert util.is_np_shape() == prev

    @util.np_shape(True)
    def inner():
        return util.is_np_shape()

    assert inner() is True
    assert util.is_np_shape() == prev


def test_util_np_array_and_set_np():
    from mxnet_tpu import util

    util.set_np(shape=True, array=True)
    assert util.is_np_array() and util.is_np_shape()
    util.reset_np()
    assert not util.is_np_array()
    old = util.set_np_shape(True)
    assert util.is_np_shape()
    util.set_np_shape(old)


def test_util_misc_helpers(tmp_path):
    from mxnet_tpu import util

    d = tmp_path / "a" / "b"
    util.makedirs(str(d))
    assert d.is_dir()
    util.makedirs(str(d))  # idempotent

    @util.set_module("mxnet_tpu.fake")
    def f():
        pass

    assert f.__module__ == "mxnet_tpu.fake"

    class NoDoc:
        pass

    del_attr = util.wraps_safely(NoDoc)  # missing __doc__ etc. tolerated

    @del_attr
    def g():
        pass

    assert util.get_gpu_count() >= 0


def test_log_get_logger_format_and_idempotence(tmp_path):
    from mxnet_tpu import log

    f = tmp_path / "x.log"
    lg = log.get_logger("mxtest_file", filename=str(f), level=log.INFO)
    lg2 = log.get_logger("mxtest_file")
    assert lg is lg2 and len(lg.handlers) == 1  # no duplicate handlers
    lg.info("hello %s", "world")
    for h in lg.handlers:
        h.flush()
    text = f.read_text()
    assert "hello world" in text and text[0] == "I"  # level letter prefix
    assert log.getLogger("mxtest_file") is lg


def test_registry_register_alias_create():
    from mxnet_tpu import registry

    class Base:
        def __init__(self, x=1):
            self.x = x

    register = registry.get_register_func(Base, "thing")
    alias = registry.get_alias_func(Base, "thing")
    create = registry.get_create_func(Base, "thing")

    @register
    class Foo(Base):
        pass

    @alias("bar", "baz")
    class Bar(Base):
        pass

    assert isinstance(create("foo"), Foo)
    assert isinstance(create("bar", x=3), Bar)
    assert create("baz").x == 1
    assert set(registry.get_registry(Base)) >= {"foo", "bar", "baz"}
    # instance passthrough
    inst = Foo(7)
    assert create(inst) is inst
    # json config forms
    assert create('["foo", {"x": 9}]').x == 9
    assert isinstance(create('{"thing": "bar"}'), Bar)
    with pytest.raises(AssertionError):
        create("unregistered_name")
    # duplicate registration warns
    with pytest.warns(UserWarning):
        register(Bar, "foo")


def test_kvstore_server_role_exits():
    # reference _init_kvstore_server_module: non-worker roles never run
    # the user script
    code = ("import mxnet_tpu\n"
            "print('SHOULD_NOT_REACH')\n")
    env = dict(os.environ, DMLC_ROLE="server", JAX_PLATFORMS="cpu",
               PALLAS_AXON_POOL_IPS="")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=240)
    assert out.returncode == 0
    assert "SHOULD_NOT_REACH" not in out.stdout
    assert "no" in out.stderr.lower() or "exiting" in out.stderr.lower()


def test_kvstore_server_shim_api():
    from mxnet_tpu.kvstore_server import KVStoreServer

    srv = KVStoreServer(kvstore=None)
    srv.run()  # no-op, must not raise
    srv._controller()(0, b"", None)


def test_device_memory_info_surfaces():
    # reference: mx.context.gpu_memory_info / Storage device accounting.
    # On the CPU test backend PJRT may expose no stats — the lenient
    # Storage probe still returns well-formed values, while the strict
    # context API raises on a nonexistent accelerator id (like the
    # reference's cudaMemGetInfo path).
    import jax

    from mxnet_tpu.storage import device_memory_info

    free, total, stats = device_memory_info()
    assert isinstance(stats, dict)
    assert isinstance(free, int) and isinstance(total, int)
    assert free >= 0 and total >= 0
    n_acc = len([d for d in jax.devices() if d.platform != "cpu"])
    if n_acc:
        f2, t2 = mx.context.gpu_memory_info(0)
        assert 0 <= f2 <= max(t2, 1)
    else:
        with pytest.raises(ValueError):
            mx.context.gpu_memory_info(0)


def test_gluon_shape_is_known():
    # reference: gluon/utils.py shape_is_known under both semantics
    from mxnet_tpu.gluon.utils import shape_is_known
    from mxnet_tpu.util import np_shape

    assert shape_is_known((2, 3))
    assert not shape_is_known((2, 0))
    assert not shape_is_known(None)
    assert not shape_is_known(())
    with np_shape(True):
        assert shape_is_known(())
        assert shape_is_known((2, 0))  # zero-size is legal np shape
        assert not shape_is_known((2, -1))
    # invalid negative dims raise like the reference, never "known"
    with pytest.raises(AssertionError):
        shape_is_known((2, -1))  # classic semantics: -1 is invalid
