"""ModelServer HTTP end-to-end (slow: real sockets, excluded from
tier-1 via ``-m 'not slow'``; the socketless batcher+session smoke
coverage lives in test_serving.py)."""
import io
import json
import threading
import urllib.error
import urllib.request

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, serving
from mxnet_tpu.gluon import nn

nd = mx.nd

pytestmark = pytest.mark.slow


@pytest.fixture()
def served():
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    with autograd.pause(train_mode=False):
        net(nd.zeros((1, 8)))
    sess = serving.InferenceSession(net, input_shapes=[(1, 8)],
                                    buckets=[1, 4, 8])
    server = serving.ModelServer(sess, port=0).start()
    serving.reset_serving_counters()
    yield net, server, f"http://127.0.0.1:{server.port}"
    server.stop()


def _post(url, body, ctype="application/json"):
    req = urllib.request.Request(url, data=body,
                                 headers={"Content-Type": ctype})
    return urllib.request.urlopen(req, timeout=30)


def test_http_predict_json(served):
    net, _, url = served
    x = onp.random.RandomState(1).rand(3, 8).astype("float32")
    resp = json.load(_post(url + "/predict",
                           json.dumps({"data": x.tolist()}).encode()))
    with autograd.pause(train_mode=False):
        ref = net(nd.array(x)).asnumpy()
    assert resp["shapes"] == [[3, 4]]
    assert onp.array_equal(
        onp.array(resp["outputs"][0], dtype="float32"), ref)


def test_http_predict_npy_roundtrip(served):
    net, _, url = served
    x = onp.random.RandomState(2).rand(2, 8).astype("float32")
    buf = io.BytesIO()
    onp.save(buf, x)
    resp = _post(url + "/predict", buf.getvalue(),
                 ctype="application/x-npy")
    assert resp.headers["Content-Type"] == "application/x-npy"
    out = onp.load(io.BytesIO(resp.read()))
    with autograd.pause(train_mode=False):
        ref = net(nd.array(x)).asnumpy()
    assert onp.array_equal(out, ref)


def test_http_concurrent_clients_each_get_their_rows(served):
    net, _, url = served
    results = {}

    def client(i):
        x = onp.random.RandomState(10 + i).rand(1, 8).astype("float32")
        resp = json.load(_post(
            url + "/predict", json.dumps({"data": x.tolist()}).encode()))
        results[i] = (x, onp.array(resp["outputs"][0], dtype="float32"))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(results) == 8
    for x, out in results.values():
        with autograd.pause(train_mode=False):
            assert onp.array_equal(out, net(nd.array(x)).asnumpy())


def test_http_healthz_and_metrics(served):
    _, server, url = served
    h = json.load(urllib.request.urlopen(url + "/healthz", timeout=30))
    assert h["status"] == "ok"
    assert h["warm"] is True
    assert h["buckets"] == [1, 4, 8]
    x = onp.ones((1, 8), dtype="float32")
    _post(url + "/predict", json.dumps({"data": x.tolist()}).encode())
    text = urllib.request.urlopen(url + "/metrics",
                                  timeout=30).read().decode()
    assert "mxnet_serving_responses_total 1" in text
    assert "mxnet_serving_request_latency_seconds_bucket" in text


def test_http_healthz_reflects_degraded_bucket(served):
    """A bucket demoted to the jit path by repeated failures shows up
    in /healthz as status "degraded" (still 200 — it serves, slower),
    and an open circuit maps predict to 503."""
    from mxnet_tpu.resilience import faults

    net, server, url = served
    x = onp.ones((4, 8), dtype="float32")
    faults.arm({"serving_execute": dict(every=1, times=2)})
    try:
        for _ in range(2):
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(url + "/predict",
                      json.dumps({"data": x.tolist()}).encode())
            assert ei.value.code == 500  # injected execution failure
    finally:
        faults.disarm()
    h = json.load(urllib.request.urlopen(url + "/healthz", timeout=30))
    assert h["status"] == "degraded"
    assert h["degraded_buckets"] == [4]
    # the demoted bucket still serves (jit path), bitwise-correct
    resp = json.load(_post(url + "/predict",
                           json.dumps({"data": x.tolist()}).encode()))
    with autograd.pause(train_mode=False):
        ref = net(nd.array(x)).asnumpy()
    assert onp.array_equal(
        onp.array(resp["outputs"][0], dtype="float32"), ref)


def test_http_error_mapping(served):
    _, _, url = served
    # malformed JSON -> 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(url + "/predict", b"not json")
    assert e.value.code == 400
    # wrong row shape -> 400, with the validation message
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(url + "/predict",
              json.dumps({"data": [[1.0, 2.0]]}).encode())
    assert e.value.code == 400
    assert "row shape" in json.load(e.value)["error"]
    # unknown route -> 404
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(url + "/nope", timeout=30)
    assert e.value.code == 404


def test_http_graceful_stop_is_idempotent(served):
    _, server, url = served
    x = onp.ones((2, 8), dtype="float32")
    _post(url + "/predict", json.dumps({"data": x.tolist()}).encode())
    server.stop()
    server.stop()  # idempotent
    with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
        urllib.request.urlopen(url + "/healthz", timeout=3)


# ---------------------------------------------------------------------------
# round 13: repository mode + SLO surface


@pytest.fixture()
def repo_served():
    nets = {}
    repo = serving.ModelRepository(max_latency_ms=2.0)
    for i, name in enumerate(("alpha", "beta")):
        mx.random.seed(20 + i)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
        net.initialize()
        with autograd.pause(train_mode=False):
            net(nd.zeros((1, 8)))
        repo.deploy(name, serving.InferenceSession(
            net, input_shapes=[(1, 8)], buckets=[1, 4]))
        nets[name] = net
    server = serving.ModelServer(repository=repo, port=0).start()
    serving.reset_serving_counters()
    yield nets, server, f"http://127.0.0.1:{server.port}"
    server.stop()


def _eager(net, x):
    with autograd.pause(train_mode=False):
        return net(nd.array(x)).asnumpy()


def test_http_repository_routing(repo_served):
    nets, _, url = repo_served
    x = onp.random.RandomState(3).rand(2, 8).astype("float32")
    # bare /predict routes to the default (first-deployed) model
    resp = json.load(_post(url + "/predict",
                           json.dumps({"data": x.tolist()}).encode()))
    assert onp.array_equal(
        onp.array(resp["outputs"][0], dtype="float32"),
        _eager(nets["alpha"], x))
    # /models/<name>/predict targets a specific model
    resp = json.load(_post(url + "/models/beta/predict",
                           json.dumps({"data": x.tolist()}).encode()))
    assert onp.array_equal(
        onp.array(resp["outputs"][0], dtype="float32"),
        _eager(nets["beta"], x))
    # the listing names both, default first
    doc = json.load(urllib.request.urlopen(url + "/models", timeout=30))
    assert doc["default"] == "alpha"
    assert sorted(doc["models"]) == ["alpha", "beta"]
    assert doc["models"]["beta"]["state"] == "serving"
    # unknown model -> 404
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(url + "/models/ghost/predict",
              json.dumps({"data": x.tolist()}).encode())
    assert e.value.code == 404


def test_http_slo_class_header_and_shed_maps_to_503(repo_served):
    from mxnet_tpu.resilience import faults

    _, _, url = repo_served
    x = onp.random.RandomState(4).rand(1, 8).astype("float32")
    body = json.dumps({"data": x.tolist()}).encode()

    def post_cls(cls):
        req = urllib.request.Request(
            url + "/predict", data=body,
            headers={"Content-Type": "application/json",
                     "X-SLO-Class": cls})
        return urllib.request.urlopen(req, timeout=30)

    # unknown class -> 400 at the boundary, not silent best_effort
    with pytest.raises(urllib.error.HTTPError) as e:
        post_cls("vip")
    assert e.value.code == 400
    assert "unknown SLO class" in json.load(e.value)["error"]
    # a forced admission shed -> fast 503 carrying Retry-After;
    # the protected class still gets through
    with faults.inject("serving_admission", every=1):
        with pytest.raises(urllib.error.HTTPError) as e:
            post_cls("best_effort")
        assert e.value.code == 503
        assert float(e.value.headers["Retry-After"]) > 0
        assert "shed" in json.load(e.value)["error"]
        resp = json.load(post_cls("critical"))
        assert resp["shapes"] == [[1, 4]]


def test_http_healthz_slo_surface(served, repo_served):
    # single-session mode: per-class depths + the slo headroom block
    _, _, url = served
    h = json.load(urllib.request.urlopen(url + "/healthz", timeout=30))
    assert set(h["queue_depths"]) == set(serving.SLO_CLASSES)
    assert h["queue_depth"] == 0
    assert h["slo"]["enabled"] is True
    assert 0.0 <= h["slo"]["headroom"] <= 1.0
    assert h["slo"]["shedding"] == []
    # repository mode: same block, plus per-model lifecycle states
    _, _, rurl = repo_served
    h = json.load(urllib.request.urlopen(rurl + "/healthz", timeout=30))
    assert h["status"] == "ok"
    assert set(h["queue_depths"]) == set(serving.SLO_CLASSES)
    assert h["slo"] is not None
    assert h["models"]["alpha"]["active_version"] == 1
