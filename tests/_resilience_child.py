"""Child process of tests/test_resilience.py's kill-and-resume test.

Runs a small deterministic training job (dropout exercises the global
PRNG stream; momentum SGD exercises optimizer state) under AutoResume
with async checkpoints. Driven by env vars:

  RESIL_CKPT_DIR   checkpoint directory (required)
  RESIL_OUT        .npz written on COMPLETION: final params + loss trace
  RESIL_KILL_AT    SIGKILL self when the next global step == this
                   (simulating a hard mid-epoch crash: no atexit, no
                   flush, whatever the writer was doing is torn)

A killed run writes nothing; re-running the same command restores the
newest valid checkpoint and finishes. The parent compares the resumed
run's output bitwise against an uninterrupted run.
"""
import os
import signal
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from _cpu_platform import force_cpu_platform  # noqa: E402

force_cpu_platform(num_devices=1)

import numpy as onp  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, nd  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402
from mxnet_tpu.resilience import AutoResume, CheckpointManager  # noqa: E402

EPOCHS = 2
STEPS_PER_EPOCH = 6
BATCH, DIM, OUT = 4, 8, 4


def build():
    mx.random.seed(42)
    net = nn.Sequential()
    net.add(nn.Dense(16, activation="relu"))
    net.add(nn.Dropout(0.5))  # draws from the global stream per step
    net.add(nn.Dense(OUT))
    net.initialize()
    net(nd.zeros((1, DIM)))
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.05, "momentum": 0.9})
    return net, trainer


def data_factory(epoch):
    rs = onp.random.RandomState(1000 + epoch)
    for _ in range(STEPS_PER_EPOCH):
        yield (rs.rand(BATCH, DIM).astype("f"),
               rs.rand(BATCH, OUT).astype("f"))


def main():
    ckpt_dir = os.environ["RESIL_CKPT_DIR"]
    out = os.environ.get("RESIL_OUT")
    kill_at = int(os.environ.get("RESIL_KILL_AT", "0"))
    net, trainer = build()
    counter = {"g": 0}

    def step_fn(batch):
        if kill_at and counter["g"] + 1 == kill_at:
            os.kill(os.getpid(), signal.SIGKILL)  # a REAL hard crash
        counter["g"] += 1
        x, y = nd.array(batch[0]), nd.array(batch[1])
        with autograd.record():
            loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        trainer.step(BATCH)
        return float(loss.asnumpy())

    manager = CheckpointManager(ckpt_dir, trainer=trainer,
                                async_mode=True, keep=3)
    sup = AutoResume(manager, data_factory, step_fn, epochs=EPOCHS,
                     ckpt_every=3)
    trace = sup.run()
    if out:
        params = {name: p.data().asnumpy()
                  for name, p in net.collect_params().items()}
        onp.savez(out, trace=onp.asarray(trace, dtype="float64"),
                  **params)
    print(f"done steps={counter['g']} trace_len={len(trace)}")


if __name__ == "__main__":
    main()
