"""mxnet_tpu.artifact — the round-20 CompiledArtifact layer.

Covers: declarative salt providers (registration, ordering, lazy
built-ins), CompiledArtifact fingerprint compatibility (salt-free kinds
keep their pre-artifact-layer fingerprints) and tiered resolve
(compile -> disk -> remote), the remote cache tier over both backends
(file:// shared dir and the reference HTTP server) with its
retry/breaker degradation, deployment bundles (export/import, stale
salt, repository wrapper), and the two-process acceptance paths: a
bundle-warm replica and a remote-warm replica each serve their first
response with zero traces, zero XLA compiles, bitwise-equal outputs.
"""
import hashlib
import json
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import artifact, autograd, serving
from mxnet_tpu.artifact import remote as art_remote
from mxnet_tpu.artifact import salts as art_salts
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.utils import compile_cache as cc

nd = mx.nd


@pytest.fixture(autouse=True)
def _fresh_artifact_state():
    artifact.reset_artifact_counters()
    artifact.reset_remote_state()
    artifact.reset_protected_fingerprints()
    cc.reset_compile_cache_counters()
    yield
    artifact.reset_artifact_counters()
    artifact.reset_remote_state()
    artifact.reset_protected_fingerprints()


def _mlp(seed=3, out_dim=4):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(out_dim))
    net.initialize()
    with autograd.pause(train_mode=False):
        net(nd.zeros((1, 8)))
    return net


# ---------------------------------------------------------------------------
# salt providers

def test_register_salt_provider_rejects_duplicates_and_noncallables():
    name = "unit_test_salt_a"
    art_salts.register_salt_provider(name, lambda ctx: ("a", 1))
    assert name in artifact.salt_providers()
    with pytest.raises(MXNetError, match="already registered"):
        art_salts.register_salt_provider(name, lambda ctx: ("b",))
    art_salts.register_salt_provider(name, lambda ctx: ("b", 2),
                                     replace=True)
    assert art_salts.resolve_salts((name,)) == (("b", 2),)
    with pytest.raises(MXNetError, match="not callable"):
        art_salts.register_salt_provider("unit_test_salt_bad", 7)


def test_resolve_salts_order_and_context():
    art_salts.register_salt_provider(
        "unit_test_salt_x", lambda ctx: ("x", ctx.get("n", 0)),
        replace=True)
    art_salts.register_salt_provider(
        "unit_test_salt_y", lambda ctx: (), replace=True)
    got = art_salts.resolve_salts(
        ("unit_test_salt_y", "unit_test_salt_x"), {"n": 9})
    assert got == ((), ("x", 9))


def test_unknown_salt_provider_raises():
    with pytest.raises(MXNetError, match="unknown salt provider"):
        art_salts.resolve_salts(("no_such_provider",))


def test_builtin_providers_resolve():
    # the built-ins live with their subsystems and register at import;
    # resolving them must work regardless of import order (lazy import)
    got = art_salts.resolve_salts(
        ("graph_opt", "sharding", "quantize"),
        {"optimizable": False, "shard": None, "graph_signature": None})
    assert got == (("graph_opt", 0), ("sharding", 0), ())


# ---------------------------------------------------------------------------
# CompiledArtifact fingerprints

def test_salt_free_fingerprint_matches_raw_compile_cache():
    """Kinds that declare no salts ('dispatch', 'fused_step') must keep
    their pre-artifact-layer fingerprints, so disk entries written by
    earlier rounds stay valid."""
    key = ("unit", 1, (2, 3))
    art = artifact.CompiledArtifact("dispatch_compat", key)
    assert art.fingerprint == cc.fingerprint("dispatch_compat", key)


def test_none_key_is_memory_only():
    art = artifact.CompiledArtifact("serving", None)
    assert art.fingerprint is None
    assert art.load() is None


def test_declared_salts_fold_into_fingerprint():
    art_salts.register_salt_provider(
        "unit_test_salt_lvl", lambda ctx: ("lvl", ctx["lvl"]),
        replace=True)

    def fp(lvl):
        return artifact.CompiledArtifact(
            "unit_salted", ("k",), salts=("unit_test_salt_lvl",),
            salt_ctx={"lvl": lvl}).fingerprint

    assert fp(0) == fp(0)  # deterministic
    assert fp(0) != fp(1)  # provider output differentiates artifacts
    assert fp(0) != artifact.CompiledArtifact(
        "unit_salted", ("k",)).fingerprint


def test_artifact_resolve_compile_then_disk(monkeypatch, tmp_path):
    import jax
    import jax.numpy as jnp

    monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR", str(tmp_path / "cc"))

    def f(x):
        return jnp.sin(x) + 1.0

    jfn = cc.counting_jit(f, label="artifact_unit")
    aval = jax.ShapeDtypeStruct((4,), jnp.float32)
    art = artifact.CompiledArtifact("unit_resolve", ("k1",),
                                    code_of=(f,))
    fn, meta, source = art.resolve(jfn, (aval,), meta={"n": 7})
    assert source == "compile"
    x = jnp.arange(4.0)
    cold = onp.asarray(fn(x))

    art2 = artifact.CompiledArtifact("unit_resolve", ("k1",),
                                     code_of=(f,))
    fn2, meta2, source2 = art2.resolve(jfn, (aval,))
    assert source2 == "disk"
    assert meta2 == {"n": 7}  # envelope meta rides to warm processes
    assert onp.array_equal(onp.asarray(fn2(x)), cold)


# ---------------------------------------------------------------------------
# remote tier: file:// backend

def test_remote_file_tier_fleet_roundtrip(monkeypatch, tmp_path):
    import jax
    import jax.numpy as jnp

    monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR", str(tmp_path / "l1"))
    monkeypatch.setenv("MXNET_ARTIFACT_REMOTE",
                       "file://" + str(tmp_path / "shared"))

    def f(x):
        return x * 2.0 + 1.0

    jfn = cc.counting_jit(f, label="artifact_remote_unit")
    aval = jax.ShapeDtypeStruct((3,), jnp.float32)

    def make():
        return artifact.CompiledArtifact("unit_remote", ("k",),
                                         code_of=(f,))

    fn, _, source = make().resolve(jfn, (aval,))
    assert source == "compile"
    assert artifact.artifact_stats()["remote_publishes"] == 1

    # a "fresh replica": empty local cache, same shared remote
    monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR", str(tmp_path / "l2"))
    x = jnp.arange(3.0)
    fn2, _, source2 = make().resolve(jfn, (aval,))
    assert source2 == "remote"
    st = artifact.artifact_stats()
    assert st["remote_hits"] == 1 and st["fetch_bytes"] > 0
    assert onp.array_equal(onp.asarray(fn2(x)), onp.asarray(fn(x)))

    # the fetched blob was adopted locally: next resolve is a disk hit
    _, _, source3 = make().resolve(jfn, (aval,))
    assert source3 == "disk"


def test_remote_publish_disabled_by_knob(monkeypatch, tmp_path):
    monkeypatch.setenv("MXNET_ARTIFACT_REMOTE",
                       "file://" + str(tmp_path / "shared"))
    monkeypatch.setenv("MXNET_ARTIFACT_REMOTE_PUBLISH", "0")
    assert not art_remote.publish("aa", b"blob")
    assert artifact.artifact_stats()["remote_publishes"] == 0
    assert not os.path.exists(str(tmp_path / "shared" / "aa.mxc"))


def test_remote_file_gc_prunes_oldest(monkeypatch, tmp_path):
    """A size-bounded file:// store sheds oldest-used entries down to
    80% of MXNET_ARTIFACT_REMOTE_MAX_MB on publish — same contract as
    the local tier's _maybe_prune; the fresh publish survives."""
    shared = str(tmp_path / "shared")
    monkeypatch.setenv("MXNET_ARTIFACT_REMOTE", "file://" + shared)
    monkeypatch.setenv("MXNET_ARTIFACT_REMOTE_MAX_MB", "1")
    monkeypatch.setattr(art_remote, "_GC_EVERY", 1)
    os.makedirs(shared)
    # an overgrown fleet store: ~1.5 MB of stale artifacts, distinct
    # mtimes so eviction order is deterministic
    for i in range(12):
        p = os.path.join(shared, f"stale{i:02d}.mxc")
        with open(p, "wb") as f:
            f.write(b"x" * (128 * 1024))
        os.utime(p, (1000 + i, 1000 + i))
    assert art_remote.publish("freshfp", b"y" * 1024)
    files = [f for f in os.listdir(shared) if f.endswith(".mxc")]
    total = sum(os.path.getsize(os.path.join(shared, f)) for f in files)
    assert total <= 0.8 * 1024 * 1024, (total, files)
    assert "freshfp.mxc" in files          # never the entry just pushed
    assert "stale00.mxc" not in files      # oldest went first
    assert "stale11.mxc" in files          # newest stale survived
    st = artifact.artifact_stats()
    assert st["gc_runs"] == 1 and st["gc_evicted"] >= 6
    assert st["gc_bytes"] >= 6 * 128 * 1024


def test_remote_file_gc_survives_concurrent_pruner(monkeypatch,
                                                   tmp_path):
    """Two replicas publishing into one shared dir GC concurrently —
    entries the other pruner already removed vanish between
    scandir/stat and stat/remove. The sweep tolerates every per-entry
    race and still bounds what remains."""
    import contextlib

    monkeypatch.setenv("MXNET_ARTIFACT_REMOTE_MAX_MB", "1")
    monkeypatch.setattr(art_remote, "_GC_EVERY", 1)
    d = str(tmp_path)
    for i in range(12):
        p = os.path.join(d, f"stale{i:02d}.mxc")
        with open(p, "wb") as f:
            f.write(b"x" * (256 * 1024))
        os.utime(p, (1000 + i, 1000 + i))

    real_scandir = os.scandir
    vanish = {"stale00.mxc": "pre-stat", "stale01.mxc": "pre-stat",
              "stale02.mxc": "pre-remove"}

    class _RacyEntry:
        def __init__(self, e, race):
            self._e, self._race = e, race
            self.name, self.path = e.name, e.path

        def stat(self):
            if self._race == "pre-stat":
                os.remove(self.path)
                raise FileNotFoundError(self.path)
            st = self._e.stat()
            if self._race == "pre-remove":
                os.remove(self.path)
            return st

    @contextlib.contextmanager
    def racy_scandir(path):
        with real_scandir(path) as it:
            yield (_RacyEntry(e, vanish.get(e.name)) for e in it)

    monkeypatch.setattr(art_remote.os, "scandir", racy_scandir)
    art_remote._maybe_gc_file(d)  # must not raise
    monkeypatch.setattr(art_remote.os, "scandir", real_scandir)
    st = artifact.artifact_stats()
    assert st["gc_runs"] == 1 and st["gc_evicted"] > 0
    left = [f for f in os.listdir(d) if f.endswith(".mxc")]
    total = sum(os.path.getsize(os.path.join(d, f)) for f in left)
    assert total <= 1024 * 1024, (total, left)


def test_remote_file_gc_age_bound_and_bundle_protection(monkeypatch,
                                                        tmp_path):
    """Round 23: entries older than MXNET_ARTIFACT_GC_MAX_AGE_S are
    reclaimed even while the store is under its byte cap — only age
    can collect a dead fingerprint nobody re-publishes — and
    fingerprints named by a live bundle manifest (here via the
    MXNET_ARTIFACT_GC_PROTECT knob) survive the sweep."""
    import pickle
    import time

    shared = str(tmp_path / "shared")
    monkeypatch.setenv("MXNET_ARTIFACT_REMOTE", "file://" + shared)
    monkeypatch.setenv("MXNET_ARTIFACT_GC_MAX_AGE_S", "3600")
    monkeypatch.setattr(art_remote, "_GC_EVERY", 1)
    os.makedirs(shared)
    now = time.time()
    for name, age in (("old0", 7200.0), ("old1", 7200.0),
                      ("fresh0", 10.0)):
        p = os.path.join(shared, name + ".mxc")
        with open(p, "wb") as f:
            f.write(b"x" * 64)
        os.utime(p, (now - age, now - age))
    # a live bundle manifest pins old1 (the knob path is deliberately
    # salt-agnostic: a shared mount serves replicas of every salt)
    bp = str(tmp_path / "pin.bundle")
    with open(bp, "wb") as f:
        pickle.dump({"format": artifact.BUNDLE_FORMAT, "salt": "any",
                     "manifest": {}, "entries": {"old1": b""}}, f)
    monkeypatch.setenv("MXNET_ARTIFACT_GC_PROTECT", bp)
    assert art_remote.publish("freshfp", b"z" * 16)
    left = {f[:-4] for f in os.listdir(shared) if f.endswith(".mxc")}
    assert left == {"old1", "fresh0", "freshfp"}
    st = artifact.artifact_stats()
    assert st["gc_runs"] == 1
    assert st["gc_evicted"] == 1 and st["gc_age_evicted"] == 1
    assert st["gc_protected"] == 1


# ---------------------------------------------------------------------------
# remote tier: HTTP backend + resilience

def test_remote_http_fetch_publish_and_miss(monkeypatch):
    with artifact.ArtifactCacheServer() as srv:
        monkeypatch.setenv("MXNET_ARTIFACT_REMOTE", srv.url)
        assert art_remote.fetch("deadbeef") is None  # 404: clean miss
        assert artifact.artifact_stats()["remote_misses"] == 1
        assert art_remote.publish("deadbeef", b"envelope-bytes")
        assert srv.store["deadbeef"] == b"envelope-bytes"
        assert art_remote.fetch("deadbeef") == b"envelope-bytes"
        st = artifact.artifact_stats()
        assert st["remote_hits"] == 1
        assert st["publish_bytes"] == len(b"envelope-bytes")


def test_artifact_server_evicts_least_recently_fetched(monkeypatch):
    """The reference server is byte-bounded: a PUT over the cap evicts
    the least-recently-ACCESSED blob (a GET refreshes recency), never
    the blob just written; an evicted fingerprint is a clean 404."""
    with artifact.ArtifactCacheServer(max_bytes=300) as srv:
        monkeypatch.setenv("MXNET_ARTIFACT_REMOTE", srv.url)
        assert art_remote.publish("aa", b"a" * 100)
        assert art_remote.publish("bb", b"b" * 100)
        assert art_remote.fetch("aa") == b"a" * 100   # aa is now warm
        assert art_remote.publish("cc", b"c" * 100)   # exactly at cap
        assert srv.gc_evicted == 0
        assert art_remote.publish("dd", b"d" * 100)   # over: bb coldest
        assert set(srv.store) == {"aa", "cc", "dd"}
        assert srv.gc_evicted == 1 and srv.store_bytes == 300
        st = artifact.artifact_stats()
        assert st["gc_runs"] == 1 and st["gc_evicted"] == 1
        assert st["gc_bytes"] == 100
        assert art_remote.fetch("bb") is None  # evicted = clean miss


def test_artifact_server_age_eviction_skips_live_bundle(monkeypatch,
                                                        tmp_path):
    """The reference server mirrors the file:// pruner's round-23
    rules: a PUT drops entries untouched for max_age_s whatever the
    byte total, but a fingerprint a live (imported) bundle references
    is pinned."""
    import pickle

    # importing a salt-matching bundle registers its fingerprints as
    # protected in-process
    bp = str(tmp_path / "pin.bundle")
    with open(bp, "wb") as f:
        pickle.dump({"format": artifact.BUNDLE_FORMAT,
                     "salt": cc._salt(), "manifest": {},
                     "entries": {"bb": b"pinned-blob"}}, f)
    assert artifact.import_bundle(bp)["stale"] is False
    assert "bb" in artifact.protected_fingerprints()

    clock = [0.0]
    with artifact.ArtifactCacheServer(max_bytes=0, max_age_s=100,
                                      clock=lambda: clock[0]) as srv:
        monkeypatch.setenv("MXNET_ARTIFACT_REMOTE", srv.url)
        assert art_remote.publish("aa", b"a" * 10)
        assert art_remote.publish("bb", b"b" * 10)
        clock[0] = 200.0  # both aa and bb are now past the age bound
        assert art_remote.publish("cc", b"c" * 10)
        assert set(srv.store) == {"bb", "cc"}
        st = artifact.artifact_stats()
        assert st["gc_age_evicted"] == 1 and srv.gc_evicted == 1
        assert st["gc_protected"] == 1
        assert art_remote.fetch("aa") is None


def test_remote_http_flaky_host_retries(monkeypatch):
    with artifact.ArtifactCacheServer() as srv:
        monkeypatch.setenv("MXNET_ARTIFACT_REMOTE", srv.url)
        srv.store["aa"] = b"blob"
        srv.fail_requests = 1  # first attempt 503s, the retry lands
        assert art_remote.fetch("aa") == b"blob"
        assert srv.requests == 2
        assert artifact.artifact_stats()["remote_errors"] == 0


def test_remote_breaker_opens_and_degrades(monkeypatch):
    monkeypatch.setenv("MXNET_ARTIFACT_REMOTE_RETRIES", "1")
    with artifact.ArtifactCacheServer() as srv:
        monkeypatch.setenv("MXNET_ARTIFACT_REMOTE", srv.url)
        srv.fail_requests = 10 ** 6  # host is down for good
        for _ in range(5):  # MXNET_BREAKER_THRESHOLD default
            assert art_remote.fetch("aa") is None  # degrade, not raise
        st = artifact.artifact_stats()
        assert st["remote_errors"] == 5
        assert art_remote.breaker_state() == "open"
        served = srv.requests
        assert art_remote.fetch("aa") is None  # skipped, no round-trip
        assert srv.requests == served
        assert artifact.artifact_stats()["remote_skipped"] >= 1
    # repointing the knob must not inherit the dead host's streak
    monkeypatch.setenv("MXNET_ARTIFACT_REMOTE", "file:///nowhere")
    assert art_remote.breaker_state() == "closed"


def test_no_remote_configured_is_free(monkeypatch):
    monkeypatch.delenv("MXNET_ARTIFACT_REMOTE", raising=False)
    assert art_remote.fetch("aa") is None
    assert not art_remote.publish("aa", b"x")
    st = artifact.artifact_stats()
    assert st["remote_misses"] == 0 and st["remote_errors"] == 0


# ---------------------------------------------------------------------------
# deployment bundles

def _seed_cache_entries(d, entries):
    os.makedirs(d, exist_ok=True)
    for name, blob in entries.items():
        with open(os.path.join(d, name + ".mxc"), "wb") as f:
            f.write(blob)


def test_bundle_export_import_roundtrip(monkeypatch, tmp_path):
    monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR", str(tmp_path / "c1"))
    _seed_cache_entries(cc.cache_dir(), {"aa": b"A", "bb": b"BB"})
    path = str(tmp_path / "m.bundle")
    report = artifact.export_bundle(
        path, ["bb", "aa", "aa", None, "gone"],
        manifest={"model": "m", "version": 1})
    assert report["entries"] == 2  # deduped, None dropped
    assert report["missing"] == ["gone"]
    assert report["bytes"] == os.path.getsize(path)

    monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR", str(tmp_path / "c2"))
    res = artifact.import_bundle(path)
    assert res == {"written": 2, "skipped": 0, "stale": False,
                   "manifest": {"model": "m", "version": 1}}
    for name, blob in (("aa", b"A"), ("bb", b"BB")):
        with open(os.path.join(cc.cache_dir(), name + ".mxc"),
                  "rb") as f:
            assert f.read() == blob
    st = artifact.artifact_stats()
    assert st["bundle_exports"] == 1 and st["bundle_imports"] == 1
    assert st["bundle_entries_written"] == 2


def test_bundle_stale_salt_skips_everything(monkeypatch, tmp_path):
    monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR", str(tmp_path / "c1"))
    _seed_cache_entries(cc.cache_dir(), {"aa": b"A"})
    path = str(tmp_path / "m.bundle")
    artifact.export_bundle(path, ["aa"])
    monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR", str(tmp_path / "c2"))
    # the importer runs a different jax/backend/format generation
    monkeypatch.setattr(cc, "_salt", lambda: ("other-generation",))
    res = artifact.import_bundle(path)
    assert res["stale"] and res["written"] == 0 and res["skipped"] == 1
    assert not os.path.exists(os.path.join(cc.cache_dir(), "aa.mxc"))


def test_import_bundle_rejects_non_bundles(tmp_path):
    junk = tmp_path / "junk.bundle"
    junk.write_bytes(b"not a pickle")
    with pytest.raises(MXNetError, match="cannot read bundle"):
        artifact.import_bundle(str(junk))
    import pickle

    notb = tmp_path / "notb.bundle"
    notb.write_bytes(pickle.dumps({"something": "else"}))
    with pytest.raises(MXNetError, match="not a format"):
        artifact.import_bundle(str(notb))
    with pytest.raises(MXNetError):
        artifact.import_bundle(str(tmp_path / "absent.bundle"))


def test_repository_export_bundle(monkeypatch, tmp_path):
    monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR", str(tmp_path / "cc"))
    net = _mlp(seed=21)
    sess = serving.InferenceSession(net, input_shapes=[(1, 8)],
                                    buckets=[1, 2])
    with serving.ModelRepository() as repo:
        repo.deploy("m", sess)
        with pytest.raises(MXNetError, match="unknown model"):
            repo.export_bundle("ghost", str(tmp_path / "g.bundle"))
        with pytest.raises(MXNetError, match="no version"):
            repo.export_bundle("m", str(tmp_path / "g.bundle"),
                               version=9)
        report = repo.export_bundle("m", str(tmp_path / "m.bundle"))
    assert report["model"] == "m" and report["version"] == 1
    assert report["entries"] == 2 and report["missing"] == []
    # the bundle really carries both bucket executables
    res = artifact.import_bundle(str(tmp_path / "m.bundle"))
    assert res["manifest"] == {"model": "m", "version": 1,
                               "buckets": [1, 2]}


# ---------------------------------------------------------------------------
# telemetry surface

def test_artifact_family_renders_in_prometheus(monkeypatch, tmp_path):
    from mxnet_tpu import telemetry

    monkeypatch.setenv("MXNET_ARTIFACT_REMOTE",
                       "file://" + str(tmp_path / "empty"))
    assert art_remote.fetch("aa" * 8) is None  # one clean remote miss
    text = telemetry.prometheus_text()
    assert "mxnet_artifact_remote_misses 1" in text
    assert "mxnet_artifact_remote_hits 0" in text
    # satellite: the new compile-cache prune counters render too
    assert "mxnet_compile_cache_disk_evicted" in text
    assert "mxnet_compile_cache_prunes" in text


# ---------------------------------------------------------------------------
# two-process acceptance: bundle-warm and remote-warm replicas

_CHILD_COMMON = """
import hashlib, json, os
import numpy as onp
import mxnet_tpu as mx
from mxnet_tpu import artifact, autograd, serving
from mxnet_tpu.gluon import nn
from mxnet_tpu.utils import compile_cache as cc

nd = mx.nd
mx.random.seed(3)
net = nn.HybridSequential()
net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
net.initialize()
with autograd.pause(train_mode=False):
    net(nd.zeros((1, 8)))
sess = serving.InferenceSession(net, input_shapes=[(1, 8)],
                                buckets=[1, 4], warm=False)
# measure the SERVING path only: model construction above dispatches
# one-shot eager ops whose executables never persist (the dispatch
# tier stores on first in-process hit), and those are not what a
# bundle/remote-warm replica is promising about
cc.reset_compile_cache_counters()
warm = sess.warmup()
# a DEVICE-array request exercises the fused pad + slice helpers
# (host inputs pad in numpy before upload)
x = nd.array(onp.random.RandomState(5).rand(3, 8).astype("float32"))
out = sess.predict(x).asnumpy()
report = {
    "warm": warm,
    "retraces": cc.compile_cache_stats()["retraces"],
    "digest": hashlib.sha256(out.tobytes()).hexdigest(),
    "fps": sess.artifact_fingerprints(),
    "artifact": artifact.artifact_stats(),
}
"""


def test_bundle_warm_replica_zero_compiles(forced_device_subprocess,
                                           tmp_path):
    """Acceptance: process A warms + exports a bundle; process B — a
    fresh replica with an EMPTY local cache — imports it and serves its
    first response with zero traces, zero XLA compiles, bitwise-equal
    outputs."""
    bundle = str(tmp_path / "model.bundle")
    a = forced_device_subprocess(
        _CHILD_COMMON + f"""
from mxnet_tpu.kernels import serving_fused as sf
report["export"] = artifact.export_bundle(
    {bundle!r},
    sess.artifact_fingerprints() + sf.fusion_artifact_fingerprints(),
    manifest={{"model": "m", "version": 1}})
report["export"].pop("path")
print(json.dumps(report))
""", env={"MXNET_COMPILE_CACHE_DIR": str(tmp_path / "cache_a")})
    assert a["warm"] == {"disk_hits": 0, "compiles": 2}
    # 2 bucket executables + the fused pad and slice helpers the
    # served request resolved
    assert a["export"]["entries"] == 4 and not a["export"]["missing"]

    b = forced_device_subprocess(
        f"""
import mxnet_tpu
from mxnet_tpu import artifact
imported = artifact.import_bundle({bundle!r})
""" + _CHILD_COMMON + """
report["imported"] = imported
print(json.dumps(report))
""", env={"MXNET_COMPILE_CACHE_DIR": str(tmp_path / "cache_b")})
    assert b["imported"] == {"written": 4, "skipped": 0, "stale": False,
                             "manifest": {"model": "m", "version": 1}}
    assert b["warm"] == {"disk_hits": 2, "compiles": 0}
    assert b["retraces"] == 0, "bundle-warm replica must never trace"
    assert b["digest"] == a["digest"], "outputs must be bitwise equal"
    assert b["fps"] == a["fps"]


def test_remote_warm_replica_zero_compiles(forced_device_subprocess,
                                           tmp_path):
    """Acceptance: replica A compiles and PUBLISHES to the fleet cache;
    replica B (empty local cache, same remote) warms entirely from the
    remote tier — zero compiles, zero retraces, bitwise-equal
    outputs."""
    remote_env = {"MXNET_ARTIFACT_REMOTE":
                  "file://" + str(tmp_path / "fleet")}
    a = forced_device_subprocess(
        _CHILD_COMMON + "print(json.dumps(report))",
        env=dict(remote_env,
                 MXNET_COMPILE_CACHE_DIR=str(tmp_path / "cache_a")))
    assert a["warm"] == {"disk_hits": 0, "compiles": 2}
    # 2 bucket executables + fused pad/slice, all pushed to the fleet
    assert a["artifact"]["remote_publishes"] == 4

    b = forced_device_subprocess(
        _CHILD_COMMON + "print(json.dumps(report))",
        env=dict(remote_env,
                 MXNET_COMPILE_CACHE_DIR=str(tmp_path / "cache_b")))
    assert b["warm"] == {"disk_hits": 2, "compiles": 0}
    assert b["retraces"] == 0, "remote-warm replica must never trace"
    assert b["artifact"]["remote_hits"] == 4
    assert b["artifact"]["fetch_bytes"] > 0
    assert b["digest"] == a["digest"], "outputs must be bitwise equal"
