"""Async training pipeline (mxnet_tpu/pipeline/): DeviceFeed prefetch,
dispatch-as-ready gradient all-reduce, async kvstore pushes, counters.

Exception/shutdown paths get explicit coverage: a prefetch worker that
raises mid-epoch must propagate to the training loop without deadlock,
close()/reset() must drain a blocked worker, and the pipeline must keep
working (inline) after engine.close() — the round-10 batcher contract.
"""
import threading
import time

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu import pipeline as pl
from mxnet_tpu.gluon.parameter import Parameter
from mxnet_tpu.io import NDArrayIter
from mxnet_tpu.pipeline import AsyncGradReducer, DeviceFeed


def _arrays(n=8, d=4):
    X = onp.arange(n * d, dtype="f").reshape(n, d)
    Y = onp.arange(n, dtype="f")
    return X, Y


# ---------------------------------------------------------------------------
# DeviceFeed


def test_device_feed_preserves_order_and_content():
    X, Y = _arrays()
    it = NDArrayIter(nd.array(X), nd.array(Y), batch_size=4)
    feed = DeviceFeed(it, depth=2)
    batches = list(feed)
    assert len(batches) == 2
    onp.testing.assert_array_equal(batches[0].data[0].asnumpy(), X[:4])
    onp.testing.assert_array_equal(batches[1].data[0].asnumpy(), X[4:])
    onp.testing.assert_array_equal(batches[0].label[0].asnumpy(), Y[:4])
    feed.reset()
    again = [b.data[0].asnumpy() for b in feed]
    assert len(again) == 2
    onp.testing.assert_array_equal(again[0], X[:4])


def test_device_feed_stages_generator_tuples_onto_device():
    def gen():
        for i in range(3):
            yield (onp.full((2, 2), float(i), "f"),
                   onp.full((2,), float(i), "f"))

    feed = DeviceFeed(gen(), depth=2)
    out = list(feed)
    assert len(out) == 3
    for i, (x, y) in enumerate(out):
        assert isinstance(x, nd.NDArray) and isinstance(y, nd.NDArray)
        onp.testing.assert_array_equal(x.asnumpy(),
                                       onp.full((2, 2), float(i), "f"))


def test_device_feed_depth_bounds_staging():
    """At most ``depth`` batches are staged (queued) plus one mid-stage
    in the worker — prefetch must not balloon into buffering the whole
    epoch."""
    produced = []

    def gen():
        for i in range(16):
            produced.append(i)
            yield onp.full((2,), float(i), "f")

    feed = DeviceFeed(gen(), depth=2)
    first = next(feed)  # starts the worker
    time.sleep(0.3)  # give an unbounded worker time to run away
    # consumed 1; queue holds <= 2; worker holds <= 1 mid-stage
    assert len(produced) <= 1 + 2 + 1, produced
    onp.testing.assert_array_equal(first.asnumpy(), [0.0, 0.0])
    feed.close()


def test_device_feed_depth_zero_is_synchronous_passthrough():
    """MXNET_DEVICE_PREFETCH=0: no thread, same values bit-for-bit."""
    X, Y = _arrays()
    it = NDArrayIter(nd.array(X), nd.array(Y), batch_size=4)
    feed = DeviceFeed(it, depth=0)
    n0 = threading.active_count()
    batches = list(feed)
    assert threading.active_count() == n0  # no worker spawned
    assert len(batches) == 2
    assert batches[0].data[0].asnumpy().tobytes() == X[:4].tobytes()


def test_device_feed_depth_from_env(monkeypatch):
    monkeypatch.setenv("MXNET_DEVICE_PREFETCH", "5")
    assert pl.prefetch_depth() == 5
    feed = DeviceFeed([onp.zeros((1,), "f")])
    assert feed._depth == 5
    monkeypatch.setenv("MXNET_DEVICE_PREFETCH", "0")
    assert pl.prefetch_depth() == 0
    assert not pl.pipeline_enabled()
    monkeypatch.delenv("MXNET_DEVICE_PREFETCH")
    assert pl.pipeline_enabled()
    feed.close()


def test_device_feed_worker_exception_propagates_without_deadlock():
    """A source that raises mid-epoch surfaces the ORIGINAL exception in
    the consumer at next(); the worker thread exits; the feed can be
    re-armed afterwards."""

    def gen():
        yield onp.ones((2,), "f")
        yield onp.ones((2,), "f") * 2
        raise ValueError("decode exploded")

    feed = DeviceFeed(gen(), depth=2)
    got = []
    with pytest.raises(ValueError, match="decode exploded"):
        for b in feed:
            got.append(b)
    assert len(got) == 2
    with pytest.raises(StopIteration):
        next(feed)  # failed pass is over, not wedged
    assert pl.pipeline_counters()["feed_errors"] >= 1
    feed.close()


def test_device_feed_close_unblocks_full_queue():
    """close() mid-epoch drains a worker blocked on the bounded queue —
    no deadlock, idempotent, and usable as a context manager."""

    def endless():
        i = 0
        while True:
            yield onp.full((2,), float(i), "f")
            i += 1

    with DeviceFeed(endless(), depth=1) as feed:
        next(feed)
        time.sleep(0.1)  # let the worker wedge itself against the cap
    feed.close()  # second close is a no-op
    # a fresh pass works after close
    assert float(next(iter(feed)).asnumpy()[0]) >= 0.0
    feed.close()


def test_device_feed_survives_engine_close():
    """engine.close() mid-epoch must not wedge the pipeline: DataLoader
    collection ops run inline post-close and the feed drains cleanly
    (the round-10 batcher drain contract)."""
    from mxnet_tpu import engine as _engine
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    try:
        eng = _engine.Engine()
    except RuntimeError:
        pytest.skip("native engine library unavailable")
    orig = _engine._engine
    _engine._engine = eng
    try:
        X = onp.arange(12 * 2, dtype="f").reshape(12, 2)
        loader = DataLoader(ArrayDataset(nd.array(X)), batch_size=4,
                            num_workers=1)
        feed = DeviceFeed(loader, depth=2)
        it = iter(feed)
        got = [next(it).asnumpy()]
        eng.close()  # mid-epoch shutdown
        got.extend(b.asnumpy() for b in it)
        assert len(got) == 3
        onp.testing.assert_array_equal(onp.concatenate(got), X)
        feed.close()
    finally:
        _engine._engine = orig


def test_device_feed_counters_hits_and_stalls():
    pl.reset_pipeline_counters()

    def slow():
        for i in range(3):
            time.sleep(0.05)
            yield onp.full((2,), float(i), "f")

    list(DeviceFeed(slow(), depth=2))
    c = pl.pipeline_counters()
    assert c["prefetch_batches"] == 3
    assert c["prefetch_stalls"] >= 1  # source slower than consumer
    assert c["prefetch_stall_s"] > 0
    assert c["engine_idle_s"] == c["prefetch_stall_s"]

    def fast():
        for i in range(4):
            yield onp.full((2,), float(i), "f")

    pl.reset_pipeline_counters()
    feed = DeviceFeed(fast(), depth=4)
    next(feed)
    time.sleep(0.2)  # worker stages everything ahead
    for b in feed:
        pass
    c = pl.pipeline_counters()
    assert c["prefetch_hits"] >= 3  # the rest were already staged
    assert 0.0 <= c["overlap_ratio"] <= 1.0


# ---------------------------------------------------------------------------
# dispatch-as-ready gradient all-reduce


def _make_params(n, shape=(4, 4), dtype="float32"):
    params = []
    for i in range(n):
        p = Parameter(f"gs_p{i}", shape=shape, dtype=dtype)
        p.initialize()
        p.set_data(nd.array(onp.full(shape, float(i + 1), dtype)))
        params.append(p)
    return params


def _backward_over(params, scale=2.0):
    with autograd.record():
        loss = sum(((p.data() * scale).sum() for p in params),
                   nd.array(0.0))
    loss.backward()


def test_grad_ready_hook_fires_in_order_and_unregisters():
    params = _make_params(3)
    seen = []
    remove = autograd.register_grad_ready_hook(
        lambda arr: seen.append(id(arr)))
    try:
        _backward_over(params)
        assert set(seen) >= {id(p._ndarray) for p in params}
    finally:
        remove()
    seen.clear()
    _backward_over(params)
    assert seen == []  # unregistered
    remove()  # idempotent


def test_reducer_dispatches_buckets_during_backward():
    pl.reset_pipeline_counters()
    params = _make_params(6)
    calls = []

    def fake_reduce(flat):
        calls.append(int(flat.size))
        return flat * 2.0

    itemsize = 4 * 4 * 4
    red = AsyncGradReducer(params, bucket_bytes=2 * itemsize,
                           reduce_fn=fake_reduce).attach()
    try:
        _backward_over(params)
        assert len(calls) == 3  # 6 params / 2-param buckets, mid-backward
        grads = [p.grad() for p in params]
        assert red.flush(grads) == 0  # everything was already reduced
        for g in grads:  # d(2p)/dp = 2, then the fake reduce doubles
            onp.testing.assert_array_equal(g.asnumpy(),
                                           onp.full((4, 4), 4.0, "f"))
        c = pl.pipeline_counters()
        assert c["grad_buckets"] == 3
        assert c["grad_async_grads"] == 6
        assert c["grad_flush_grads"] == 0
    finally:
        red.detach()


def test_reducer_flush_covers_partial_buckets_and_missing_grads():
    params = _make_params(3)
    calls = []

    def fake_reduce(flat):
        calls.append(int(flat.size))
        return flat + 1.0

    # cap bigger than the whole group: nothing dispatches mid-backward
    red = AsyncGradReducer(params, bucket_bytes=1 << 30,
                           reduce_fn=fake_reduce).attach()
    try:
        _backward_over(params)
        assert calls == []
        grads = [p.grad() for p in params]
        red.flush(grads)
        assert len(calls) >= 1  # partial bucket dispatched at flush
        for g in grads:
            onp.testing.assert_array_equal(g.asnumpy(),
                                           onp.full((4, 4), 3.0, "f"))
    finally:
        red.detach()


def test_reducer_respeculates_on_double_backward():
    """Gradient accumulation (a second backward before step) re-signals
    the hook — the reducer re-speculates over the ACCUMULATED buffer,
    so flush binds reduce(final value), never a half-reduced one."""
    params = _make_params(2)
    red = AsyncGradReducer(params, bucket_bytes=1,  # dispatch per grad
                           reduce_fn=lambda f: f * 10.0).attach()
    try:
        _backward_over(params, scale=1.0)   # speculative reduce of 1.0
        _backward_over(params, scale=3.0)   # overwrite; hook re-fires
        grads = [p.grad() for p in params]
        red.flush(grads)
        for g in grads:  # reduce(3.0), NOT reduce(1.0) or raw 3.0
            onp.testing.assert_array_equal(g.asnumpy(),
                                           onp.full((4, 4), 30.0, "f"))
    finally:
        red.detach()


def test_reducer_discards_stale_speculation_on_manual_grad_edit():
    """A grad modified AFTER its speculative dispatch (hand-rolled
    clipping, custom hooks) invalidates the speculation: flush must
    detect the buffer changed and re-reduce the current value."""
    pl.reset_pipeline_counters()
    params = _make_params(2)
    red = AsyncGradReducer(params, bucket_bytes=1,
                           reduce_fn=lambda f: f * 10.0).attach()
    try:
        _backward_over(params, scale=1.0)   # speculative reduce of 1.0
        grads = [p.grad() for p in params]
        for g in grads:  # post-backward manual edit (no hook fires)
            g._data = g.data * 5.0
        red.flush(grads)
        for g in grads:  # reduce(5.0) = 50, NOT stale reduce(1.0) = 10
            onp.testing.assert_array_equal(g.asnumpy(),
                                           onp.full((4, 4), 50.0, "f"))
        assert pl.pipeline_counters()["grad_stale_discards"] >= 2
    finally:
        red.detach()


def test_reducer_knob_off_is_noop_per_round(monkeypatch):
    params = _make_params(2)
    calls = []
    red = AsyncGradReducer(params, bucket_bytes=1,
                           reduce_fn=lambda f: calls.append(1) or f)
    red.attach()
    try:
        monkeypatch.setenv("MXNET_ASYNC_GRAD_SYNC", "0")
        _backward_over(params)
        assert calls == []  # hook no-ops for the whole round
    finally:
        red.detach()


def test_reducer_abandon_rearms_after_knob_flip(monkeypatch):
    """Knob flipped off between backward and step(): the trainer
    abandons the round (speculation discarded, per-round knob read
    re-armed) so later backwards stop dispatching collectives — the
    knob is a true fallback switch at any point in the round."""
    params = _make_params(2)
    calls = []
    red = AsyncGradReducer(params, bucket_bytes=1,
                           reduce_fn=lambda f: calls.append(1) or f)
    red.attach()
    try:
        monkeypatch.setenv("MXNET_ASYNC_GRAD_SYNC", "1")
        _backward_over(params)
        assert calls and red._spec  # speculative dispatch happened
        monkeypatch.setenv("MXNET_ASYNC_GRAD_SYNC", "0")
        red.abandon()  # what Trainer._async_reducer does when off
        assert red._spec == {} and red._pending == {}
        calls.clear()
        _backward_over(params)  # knob re-read: hook must no-op now
        assert calls == []
    finally:
        red.detach()


def test_trainer_abandons_reducer_when_knob_flips_off(monkeypatch):
    """End-to-end version of the nastiest toggle: knob ON during
    backward, OFF by step() time. The trainer must abandon the round
    (not leave the hook armed dispatching collectives forever) and the
    params must match an always-off run."""
    pl.reset_pipeline_counters()
    mx.random.seed(13)
    params = _make_params(3)
    trainer = mx.gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                               kvstore="dist_sync")
    monkeypatch.setenv("MXNET_ASYNC_GRAD_SYNC", "1")
    _backward_over(params, scale=1.0)
    trainer.step(1)  # round 0: reducer created + hook armed
    _backward_over(params, scale=2.0)  # round 1: hook speculates...
    monkeypatch.setenv("MXNET_ASYNC_GRAD_SYNC", "0")  # ...flip mid-round
    trainer.step(1)
    red = trainer._grad_reducer
    assert red is not None and red._spec == {} and red._pending == {}
    buckets_after_flip = pl.pipeline_counters()["grad_buckets"]
    for step in range(2, 4):  # knob stays off: hook must stay quiet
        _backward_over(params, scale=float(step + 1))
        trainer.step(1)
    assert pl.pipeline_counters()["grad_buckets"] == buckets_after_flip

    def run_off():
        mx.random.seed(13)
        ps = _make_params(3)
        tr = mx.gluon.Trainer(ps, "sgd", {"learning_rate": 0.1},
                              kvstore="dist_sync")
        for step in range(4):
            _backward_over(ps, scale=float(step + 1))
            tr.step(1)
        return [p.data().asnumpy().tobytes() for p in ps]

    assert [p.data().asnumpy().tobytes() for p in params] == run_off()


def test_trainer_distributed_async_grad_sync_parity(monkeypatch):
    """Single-process 'dist' trainer: the async path must produce the
    exact grads/params the coalesced-at-step path does, and wire the
    reducer in only when the knob is on."""

    def run(async_on):
        monkeypatch.setenv("MXNET_ASYNC_GRAD_SYNC",
                           "1" if async_on else "0")
        mx.random.seed(11)
        params = _make_params(4)
        trainer = mx.gluon.Trainer(params, "sgd",
                                   {"learning_rate": 0.1},
                                   kvstore="dist_sync")
        for step in range(3):
            _backward_over(params, scale=float(step + 1))
            trainer.step(1)
        return ([p.data().asnumpy().tobytes() for p in params],
                trainer._grad_reducer)

    sync_params, r0 = run(False)
    async_params, r1 = run(True)
    assert sync_params == async_params
    assert r0 is None and r1 is not None
    assert r1._unhook is not None


# ---------------------------------------------------------------------------
# async kvstore


def test_kvstore_async_push_overlaps_and_flushes(monkeypatch):
    monkeypatch.setenv("MXNET_KVSTORE_ASYNC", "1")
    pl.reset_pipeline_counters()
    kv = mx.kvstore.create("local")
    kv.init("w", nd.zeros((4,)))
    gate = threading.Event()
    applied = []

    def updater(key, grad, stored):
        gate.wait(5)
        applied.append(key)
        stored._data = (stored + grad).data

    kv.set_updater(updater)
    t0 = time.perf_counter()
    kv.push("w", nd.ones((4,)))  # must NOT block on the slow updater
    assert time.perf_counter() - t0 < 1.0
    assert applied == []  # still gated: push really was asynchronous
    gate.set()
    out = nd.zeros((4,))
    kv.pull("w", out=out)  # read-your-writes: flushes the pending push
    assert applied == ["w"]
    onp.testing.assert_array_equal(out.asnumpy(), onp.ones(4, "f"))
    assert pl.pipeline_counters()["kvstore_async_pushes"] >= 1


def test_kvstore_async_error_propagates_at_pull(monkeypatch):
    monkeypatch.setenv("MXNET_KVSTORE_ASYNC", "1")
    kv = mx.kvstore.create("local")
    kv.init("w", nd.zeros((2,)))

    def bad_updater(key, grad, stored):
        raise RuntimeError("updater exploded")

    kv.set_updater(bad_updater)
    kv.push("w", nd.ones((2,)))
    with pytest.raises(mx.MXNetError, match="updater exploded"):
        kv.pull("w", out=nd.zeros((2,)))


def test_kvstore_async_off_by_default():
    kv = mx.kvstore.create("local")
    assert kv._async_mode is False


# ---------------------------------------------------------------------------
# DataLoader prefetch/timeout satellite


def test_dataloader_prefetch_env_default_and_override(monkeypatch):
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    ds = ArrayDataset(nd.array(onp.arange(8, dtype="f")))
    assert DataLoader(ds, batch_size=2, num_workers=2)._prefetch == 4
    monkeypatch.setenv("MXNET_DATALOADER_PREFETCH", "7")
    assert DataLoader(ds, batch_size=2, num_workers=2)._prefetch == 7
    # an explicit constructor value always wins over the env knob
    assert DataLoader(ds, batch_size=2, num_workers=2,
                      prefetch=3)._prefetch == 3


def test_dataloader_prefetch_depth_semantics():
    """Any depth yields the same batches in the same order — depth is a
    pipeline knob, never a semantics knob — and the pipelined iterator
    clamps depth >= 1 so prefetch=0 with workers cannot deadlock."""
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    X = onp.arange(10 * 3, dtype="f").reshape(10, 3)
    ds = ArrayDataset(nd.array(X))
    ref = [b.asnumpy().tobytes()
           for b in DataLoader(ds, batch_size=2, num_workers=0)]
    for depth in (0, 1, 4):
        got = [b.asnumpy().tobytes()
               for b in DataLoader(ds, batch_size=2, num_workers=2,
                                   prefetch=depth)]
        assert got == ref, depth


def test_dataloader_timeout_raises_instead_of_hanging():
    from mxnet_tpu.gluon.data import DataLoader

    class Glacial:
        def __len__(self):
            return 4

        def __getitem__(self, i):
            time.sleep(2)
            return nd.zeros((2,))

    loader = DataLoader(Glacial(), batch_size=2, num_workers=1,
                        timeout=0.2)
    with pytest.raises(RuntimeError, match="timeout"):
        next(iter(loader))


def test_dataloader_timeout_disabled_with_nonpositive():
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    ds = ArrayDataset(nd.array(onp.arange(4, dtype="f")))
    assert DataLoader(ds, batch_size=2, num_workers=1,
                      timeout=0)._timeout is None
    assert DataLoader(ds, batch_size=2, num_workers=1,
                      timeout=None)._timeout is None
    assert DataLoader(ds, batch_size=2, num_workers=1,
                      timeout=60)._timeout == 60.0


# ---------------------------------------------------------------------------
# observability wiring


def test_profiler_and_runtime_surfaces(monkeypatch, tmp_path):
    import json

    from mxnet_tpu import profiler, runtime

    pl.reset_pipeline_counters()
    list(DeviceFeed([onp.zeros((2,), "f")] * 3, depth=2))
    c = profiler.pipeline_counters()
    assert c["prefetch_batches"] == 3
    assert {"prefetch_hits", "prefetch_stalls", "engine_idle_s",
            "overlap_ratio", "grad_buckets",
            "kvstore_async_pushes"} <= set(c)
    profiler.set_config(filename=str(tmp_path / "prof.json"))
    try:
        fname = profiler.dump()
        with open(fname) as f:
            names = {e["name"] for e in json.load(f)["traceEvents"]}
        assert "pipeline/prefetch_batches" in names
        assert "pipeline/overlap_ratio" in names
    finally:
        profiler.set_config(filename="profile.json")

    feats = runtime.Features()
    assert feats.is_enabled("PIPELINE")
    monkeypatch.setenv("MXNET_DEVICE_PREFETCH", "0")
    assert not runtime.Features().is_enabled("PIPELINE")
