"""Two-OS-process sequence parallelism: ring attention and Ulysses
all-to-all attention across a process-spanning 2-device mesh, checked for
exact equivalence against full (unsharded) attention on the same global
tensors; plus MoE loss equivalence sharded-vs-local (VERDICT r4 item 6 —
multi-process runs of the NEW parallelism with loss-equivalence asserts).
"""
from _dist_harness import run_launched_workers

BODY = r"""
import numpy as onp
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import mxnet_tpu
from mxnet_tpu.parallel.ring_attention import ring_attention
from mxnet_tpu.parallel.ulysses import ulysses_attention
from mxnet_tpu.parallel.moe import moe_ffn

rank = jax.process_index()
devs = jax.devices()
assert len(devs) == 2, devs

rng = onp.random.RandomState(0)
B, H, S, D = 2, 4, 16, 8
q = jnp.asarray(rng.randn(B, H, S, D).astype("f"))
k = jnp.asarray(rng.randn(B, H, S, D).astype("f"))
v = jnp.asarray(rng.randn(B, H, S, D).astype("f"))

# reference: full attention on the replicated tensors
sm = 1.0 / onp.sqrt(D)
logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * sm
ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(logits, -1), v)
ref_np = onp.asarray(ref)

mesh = Mesh(onp.array(devs), ("sp",))
ring_out = ring_attention(q, k, v, mesh=mesh, axis_name="sp")
ring_vals = [onp.asarray(s.data) for s in ring_out.addressable_shards]
# each process holds its S/2 sequence shard of the result
lo = rank * (S // 2)
ring_ok = all(
    onp.allclose(vv, ref_np[:, :, lo:lo + S // 2, :], rtol=2e-4,
                 atol=2e-5) for vv in ring_vals)

uly_out = ulysses_attention(q, k, v, mesh=mesh, axis_name="sp")
uly_vals = [onp.asarray(s.data) for s in uly_out.addressable_shards]
uly_ok = all(
    onp.allclose(vv, ref_np[:, :, lo:lo + S // 2, :], rtol=2e-4,
                 atol=2e-5) for vv in uly_vals)

# MoE loss equivalence: sharded (ep crossing the process boundary) vs
# the single-shard fallback on the same global batch, ample capacity
E, Dm, Hm = 4, 8, 16
params = (jnp.asarray(rng.randn(Dm, E).astype("f") * 0.5),
          jnp.asarray(rng.randn(E, Dm, Hm).astype("f") * 0.2),
          jnp.zeros((E, Hm), jnp.float32),
          jnp.asarray(rng.randn(E, Hm, Dm).astype("f") * 0.2),
          jnp.zeros((E, Dm), jnp.float32))
x = jnp.asarray(rng.randn(8, 4, Dm).astype("f"))
out_sh, aux_sh = moe_ffn(x, *params, mesh=mesh, axis_name="ep",
                         batch_axes=("ep",), capacity_factor=8.0)
loss_sh = float(jnp.mean(out_sh ** 2) + 0.01 * aux_sh)
out_lo, aux_lo = moe_ffn(x, *params, mesh=None, capacity_factor=8.0)
loss_lo = float(jnp.mean(out_lo ** 2) + 0.01 * aux_lo)
moe_ok = abs(loss_sh - loss_lo) < 5e-5 * max(1.0, abs(loss_lo))

with open(os.path.join({outdir!r}, "r" + str(rank) + ".txt"), "w") as f:
    f.write("OK" if (ring_ok and uly_ok and moe_ok) else
            "BAD ring=%s uly=%s moe=%s (%r vs %r)" %
            (ring_ok, uly_ok, moe_ok, loss_sh, loss_lo))
"""


def test_two_process_ring_ulysses_moe_equivalence(tmp_path):
    run_launched_workers(tmp_path, BODY, n=2)
    for rank in (0, 1):
        p = tmp_path / f"r{rank}.txt"
        assert p.is_file(), f"worker {rank} produced no result"
        assert p.read_text() == "OK", p.read_text()
