"""dist_async kvstore, AMP graph-conversion pass, diagnose/parse_log tools.

Reference: kvstore_dist_server.h async push; amp.py convert_symbol →
low_precision_pass.cc; tools/diagnose.py; parse_log.
"""
import time

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, kvstore, sym
from mxnet_tpu.base import MXNetError
from mxnet_tpu.contrib import amp


# ---------------------------------------------------------------- async ---

def test_dist_async_applies_eventually():
    kv = kvstore.create("dist_async")
    kv.init("w", nd.zeros((4,)))
    for _ in range(5):
        kv.push("w", nd.ones((4,)))
    out = nd.zeros((4,))
    kv.pull("w", out=out)  # flushes pending pushes
    onp.testing.assert_allclose(out.asnumpy(), onp.full((4,), 5.0))


def test_dist_async_matches_sync_result():
    def run(kind):
        kv = kvstore.create(kind)
        kv.init("3", nd.ones((2, 3)))
        for step in range(4):
            kv.push("3", nd.array(onp.full((2, 3), step + 1.0, "f")))
        kv.barrier()
        out = nd.zeros((2, 3))
        kv.pull("3", out=out)
        return out.asnumpy()

    onp.testing.assert_allclose(run("dist_async"), run("dist_sync"))


def test_dist_async_updater_and_error_propagation():
    kv = kvstore.create("dist_async")
    kv.init("w", nd.zeros((3,)))
    seen = []

    def updater(key, grad, weight):
        if len(seen) == 1:
            raise RuntimeError("boom at second update")
        seen.append(key)
        weight._data = (weight - 0.1 * grad).data

    kv.set_updater(updater)
    kv.push("w", nd.ones((3,)))
    kv.push("w", nd.ones((3,)))
    with pytest.raises(MXNetError, match="boom"):
        for _ in range(100):
            kv.barrier()
            time.sleep(0.01)


def test_dist_async_nonblocking_push():
    """push must return before a slow updater finishes applying."""
    kv = kvstore.create("dist_async")
    kv.init("w", nd.zeros((2,)))
    applied = []

    def slow_updater(key, grad, weight):
        time.sleep(0.3)
        applied.append(key)

    kv.set_updater(slow_updater)
    t0 = time.perf_counter()
    kv.push("w", nd.ones((2,)))
    elapsed = time.perf_counter() - t0
    assert elapsed < 0.25, f"push blocked for {elapsed:.3f}s"
    kv.barrier()
    assert applied


# ------------------------------------------------------------- amp pass ---

def _mlp():
    x = sym.Variable("data")
    fc = sym.FullyConnected(x, name="fc", num_hidden=8,
                            weight=sym.Variable("fc_weight"),
                            bias=sym.Variable("fc_bias"))
    act = sym.Activation(fc, act_type="relu")
    return sym.softmax(act)


def test_convert_symbol_inserts_casts():
    converted = amp.convert_symbol(_mlp(), target_dtype="bfloat16")
    ops = [s._op for s in converted._walk() if s._op]
    assert "amp_cast" in ops
    # fully_connected is a TARGET op: its data/weight/bias all get casts;
    # softmax is FP32-listed: its input gets a cast back up
    assert ops.count("amp_cast") >= 4


def test_convert_symbol_runs_and_matches_fp32():
    s = _mlp()
    rng = onp.random.RandomState(0)
    args = {"data": nd.array(rng.rand(4, 6).astype("f")),
            "fc_weight": nd.array(rng.rand(8, 6).astype("f") * 0.1),
            "fc_bias": nd.array(rng.rand(8).astype("f") * 0.1)}
    base = s.bind(args=dict(args)).forward(is_train=False)[0].asnumpy()
    conv = amp.convert_symbol(s, target_dtype="bfloat16")
    got = conv.bind(args=dict(args)).forward(is_train=False)[0].asnumpy()
    assert got.dtype == onp.float32  # softmax forced back to fp32
    onp.testing.assert_allclose(got, base, rtol=2e-2, atol=2e-2)


def test_convert_symbol_excluded_names():
    conv = amp.convert_symbol(_mlp(), target_dtype="bfloat16",
                              excluded_sym_names=["fc"])
    # fc excluded -> only softmax's fp32 cast remains
    casts = [s for s in conv._walk() if s._op == "amp_cast"]
    assert all("softmax" in (c._name or "") or
               "relu" in (c._name or "") or
               "activation" in (c._name or "").lower()
               for c in casts)


def test_convert_model_symbolic_triple():
    s = _mlp()
    arg = {"fc_weight": nd.ones((8, 6))}
    aux = {}
    s2, arg2, aux2 = amp.convert_model(s, arg, aux,
                                       target_dtype="bfloat16")
    assert "amp_cast" in [n._op for n in s2._walk()]
    assert set(arg2) == {"fc_weight"}


def test_amp_multicast_widest():
    a = nd.array(onp.ones((2, 2), "float32"))
    b = nd.array(onp.ones((2, 2)), dtype="bfloat16")
    oa, ob = nd.amp_multicast(a, b, num_outputs=2)
    assert str(oa.dtype) == "float32" and str(ob.dtype) == "float32"


def test_amp_cast_leaves_ints():
    x = nd.array(onp.arange(4, dtype="int32"))
    y = nd.amp_cast(x, dtype="bfloat16")
    assert str(y.dtype) == "int32"


# ---------------------------------------------------------------- tools ---

def test_parse_log(tmp_path):
    from mxnet_tpu.tools import parse_log

    log = tmp_path / "train.log"
    log.write_text(
        "INFO Epoch[0] Batch [20] Speed: 1000.00 samples/sec "
        "accuracy=0.50\n"
        "INFO Epoch[0] Batch [40] Speed: 1200.00 samples/sec "
        "accuracy=0.60\n"
        "INFO Epoch[0] Train-accuracy=0.61\n"
        "INFO Epoch[0] Time cost=33.3\n"
        "INFO Epoch[0] Validation-accuracy=0.55\n"
        "INFO Epoch[1] Batch [20] Speed: 1100.00 samples/sec "
        "accuracy=0.70\n"
        "INFO Epoch[1] Validation-accuracy=0.65\n")
    parsed = parse_log.parse(log.read_text().splitlines())
    assert parsed[0]["valid"]["accuracy"] == 0.55
    assert parsed[0]["train"]["accuracy"] == 0.61
    assert parsed[0]["time"] == 33.3
    assert parsed[0]["speed"] == [1000.0, 1200.0]
    assert parsed[1]["valid"]["accuracy"] == 0.65
    table = parse_log.rows(parsed)
    assert table[0][0] == "epoch" and len(table) == 3


def test_diagnose_runs(capsys):
    from mxnet_tpu.tools import diagnose

    diagnose.check_python()
    diagnose.check_deps()
    diagnose.check_mxnet()
    diagnose.check_environment()
    out = capsys.readouterr().out
    assert "Python Info" in out
    assert "MXNet-TPU Info" in out
    assert "Native libs" in out


def test_convert_symbol_multi_output_views_stay_one_node():
    """Re-converting a graph whose amp_multicast outputs feed one op must
    keep ONE converted multicast node (unique names; views share it)."""
    a, b = sym.Variable("a"), sym.Variable("b")
    s = sym.elemwise_add(a, b)  # widest-list op -> amp_multicast inserted
    c1 = amp.convert_symbol(s)
    c2 = amp.convert_symbol(c1)  # multicast outputs consumed as views
    nodes = {}
    for n in c2._walk():
        if n._op == "amp_multicast":
            nodes.setdefault(n._name, set()).add(
                (id(n._inputs), id(n._kwargs)))
    for name, idents in nodes.items():
        assert len(idents) == 1, f"{name} split into {len(idents)} nodes"
    # still evaluates correctly
    out = c2.bind(args={"a": nd.ones((2,)), "b": nd.ones((2,))}).forward()
    onp.testing.assert_allclose(out[0].asnumpy(), [2.0, 2.0])
