"""docs/ENV_VARS.md is generated from the env.py knob registry; keep
the committed file in lockstep with the code (regenerate with
``python -m mxnet_tpu.env > docs/ENV_VARS.md``)."""
import os

from mxnet_tpu import env

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_env_vars_md_matches_registry():
    path = os.path.join(_REPO, "docs", "ENV_VARS.md")
    with open(path) as f:
        committed = f.read()
    assert committed == env.markdown_table(), (
        "docs/ENV_VARS.md is stale — regenerate with "
        "`python -m mxnet_tpu.env > docs/ENV_VARS.md`")


def test_fused_step_knobs_registered():
    for name in ("MXNET_FUSED_STEP", "MXNET_FUSED_STEP_CACHE_SIZE",
                 "MXNET_FUSED_STEP_DONATE"):
        assert name in env.KNOBS
        assert env.KNOBS[name][0] == "wired"


def test_markdown_table_covers_all_knobs():
    table = env.markdown_table()
    for name in env.KNOBS:
        assert f"`{name}`" in table


def test_readme_links_env_vars():
    with open(os.path.join(_REPO, "README.md")) as f:
        assert "docs/ENV_VARS.md" in f.read()
