"""Multi-PROCESS sharded checkpointing: two OS processes rendezvous via
jax.distributed (tools/launch.py local mode), form one global 2-device
mesh, write a sharded checkpoint where each process stores only its
shards, and restore it — the multi-host half of SURVEY §5.4's
checkpoint/resume story (single-process cross-topology restore is
covered by tests/test_sharded_checkpoint.py)."""
import pytest

pytest.importorskip("orbax.checkpoint")

from _dist_harness import run_launched_workers

BODY = r"""
import numpy as onp
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental import multihost_utils

import mxnet_tpu  # joins the cluster; registers ops
from mxnet_tpu import parallel

rank = jax.process_index()
devs = jax.devices()
assert len(devs) == 2, devs
mesh = Mesh(onp.array(devs), ("dp",))
sh = NamedSharding(mesh, P("dp"))
# a GLOBAL sharded array: each process materializes only its half
arr = jax.jit(lambda: jnp.arange(16.0).reshape(8, 2),
              out_shardings=sh)()
ck = os.path.join({outdir!r}, "ck")
parallel.save_sharded(ck, {{"w": arr}})
multihost_utils.sync_global_devices("ckpt_written")
back = parallel.load_sharded(ck, shardings={{"w": sh}})
w = back["w"]
# every process checks ITS addressable shards against the truth
ok = True
for s in w.addressable_shards:
    want = onp.arange(16.0).reshape(8, 2)[s.index]
    ok = ok and onp.allclose(onp.asarray(s.data), want)
with open(os.path.join({outdir!r}, "r" + str(rank) + ".txt"), "w") as f:
    f.write("OK" if ok else "BAD")
"""


def test_two_process_sharded_checkpoint(tmp_path):
    run_launched_workers(tmp_path, BODY, n=2)
    for rank in (0, 1):
        p = tmp_path / f"r{rank}.txt"
        assert p.is_file(), f"worker {rank} produced no result"
        assert p.read_text() == "OK"
