"""mx.np / mx.npx tests (reference analog: tests/python/unittest/
test_numpy_op.py, test_numpy_ndarray.py — 71+ test fns)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np, npx


def assert_close(a, b, rtol=1e-5, atol=1e-6):
    onp.testing.assert_allclose(
        a.asnumpy() if hasattr(a, "asnumpy") else a,
        b.asnumpy() if hasattr(b, "asnumpy") else b, rtol=rtol, atol=atol)


def test_array_creation():
    a = np.array([[1, 2], [3, 4]])
    assert isinstance(a, np.ndarray)
    assert a.shape == (2, 2)
    assert a.dtype == onp.float32
    z = np.zeros((3, 4))
    assert z.shape == (3, 4) and float(z.sum()) == 0
    o = np.ones((2,), dtype="int32")
    assert o.dtype == onp.int32
    f = np.full((2, 2), 7.0)
    assert float(f[0, 0]) == 7.0
    e = np.eye(3)
    assert float(e.trace() if hasattr(e, 'trace') else np.trace(e)) == 3.0
    r = np.arange(5)
    assert r.shape == (5,) and r.dtype == onp.float32
    ls = np.linspace(0, 1, 11)
    assert ls.shape == (11,)
    assert abs(float(ls[5]) - 0.5) < 1e-6


def test_zero_dim_scalar():
    a = np.array(3.5)
    assert a.shape == ()
    assert abs(float(a) - 3.5) < 1e-6
    b = a + 1
    assert b.shape == ()


def test_elementwise_and_broadcast():
    a = np.array([[1.0, 2.0], [3.0, 4.0]])
    b = np.array([10.0, 20.0])
    c = a + b
    assert isinstance(c, np.ndarray)
    assert_close(c, onp.array([[11, 22], [13, 24]], dtype=onp.float32))
    assert_close(np.add(a, b), c)
    assert_close(np.exp(a), onp.exp(a.asnumpy()))
    assert_close(np.sqrt(a), onp.sqrt(a.asnumpy()))
    assert_close(np.maximum(a, 2.5), onp.maximum(a.asnumpy(), 2.5))
    assert_close(a ** 2, a.asnumpy() ** 2)


def test_true_divide_int():
    a = np.array([1, 2, 3], dtype="int32")
    r = a / 2
    assert r.dtype.kind == "f"
    assert_close(r, onp.array([0.5, 1.0, 1.5], dtype=onp.float32))
    fd = a // 2
    assert_close(fd, onp.array([0, 1, 1]))


def test_comparisons_bool():
    a = np.array([1.0, 2.0, 3.0])
    m = a > 1.5
    assert m.dtype == onp.bool_
    assert m.asnumpy().tolist() == [False, True, True]


def test_boolean_mask_indexing():
    a = np.array([1.0, 2.0, 3.0, 4.0])
    m = a > 2.0
    sel = a[m]
    assert sel.shape == (2,)
    assert_close(sel, onp.array([3.0, 4.0], dtype=onp.float32))
    a[a < 2.5] = 0.0
    assert_close(a, onp.array([0, 0, 3, 4], dtype=onp.float32))


def test_reductions():
    x = onp.random.RandomState(0).rand(3, 4).astype(onp.float32)
    a = np.array(x)
    assert_close(np.sum(a, axis=1), x.sum(1), rtol=1e-4)
    assert_close(np.mean(a), x.mean(), rtol=1e-5)
    assert_close(np.std(a, axis=0), x.std(0), rtol=1e-4)
    assert_close(np.var(a, ddof=1), x.var(ddof=1), rtol=1e-4)
    assert_close(a.std(), x.std(), rtol=1e-4)
    assert int(np.argmax(a)) == int(x.argmax())
    assert_close(np.cumsum(a, axis=1), x.cumsum(1), rtol=1e-4)
    assert bool(np.all(a >= 0))
    assert_close(np.median(a), onp.median(x), rtol=1e-5)


def test_shape_manipulation():
    x = onp.arange(24, dtype=onp.float32).reshape(2, 3, 4)
    a = np.array(x)
    assert np.transpose(a).shape == (4, 3, 2)
    assert a.T.shape == (4, 3, 2)
    assert np.moveaxis(a, 0, -1).shape == (3, 4, 2)
    assert np.reshape(a, (6, 4)).shape == (6, 4)
    assert a.reshape(4, 6).shape == (4, 6)
    assert np.squeeze(np.expand_dims(a, 0), 0).shape == x.shape
    st = np.stack([a, a], axis=1)
    assert st.shape == (2, 2, 3, 4)
    cc = np.concatenate([a, a], axis=2)
    assert cc.shape == (2, 3, 8)
    parts = np.split(a, 3, axis=1)
    assert len(parts) == 3 and parts[0].shape == (2, 1, 4)
    assert np.flip(a, 0).shape == x.shape
    assert_close(np.flip(a, 0), onp.flip(x, 0))
    assert np.tile(a, (1, 2, 1)).shape == (2, 6, 4)
    assert np.repeat(a, 2, axis=0).shape == (4, 3, 4)
    assert np.roll(a, 1, axis=2).shape == x.shape
    assert np.pad(a, ((0, 0), (1, 1), (0, 0))).shape == (2, 5, 4)


def test_linalg_family():
    rs = onp.random.RandomState(1)
    m = rs.rand(4, 4).astype(onp.float32)
    spd = m @ m.T + 4 * onp.eye(4, dtype=onp.float32)
    a = np.array(spd)
    assert_close(np.linalg.inv(a), onp.linalg.inv(spd), rtol=1e-2, atol=1e-3)
    assert abs(float(np.linalg.det(a)) - onp.linalg.det(spd)) / \
        abs(onp.linalg.det(spd)) < 1e-3
    L = np.linalg.cholesky(a)
    assert_close(np.matmul(L, L.T if hasattr(L, 'T') else L),
                 spd, rtol=1e-3, atol=1e-3)
    w, v = np.linalg.eigh(a)
    assert w.shape == (4,)
    q, r = np.linalg.qr(a)
    assert_close(np.matmul(q, r), spd, rtol=1e-3, atol=1e-3)
    b = np.array(rs.rand(4).astype(onp.float32))
    x = np.linalg.solve(a, b)
    assert_close(np.matmul(a, x), b, rtol=1e-2, atol=1e-3)
    assert_close(np.linalg.norm(a), onp.linalg.norm(spd), rtol=1e-4)
    u, s, vt = np.linalg.svd(np.array(m), full_matrices=False,
                             compute_uv=True)
    assert s.shape == (4,)


def test_einsum_tensordot():
    rs = onp.random.RandomState(2)
    x = rs.rand(3, 4).astype(onp.float32)
    y = rs.rand(4, 5).astype(onp.float32)
    a, b = np.array(x), np.array(y)
    assert_close(np.einsum("ij,jk->ik", a, b), x @ y, rtol=1e-4)
    assert_close(np.tensordot(a, b, axes=1), x @ y, rtol=1e-4)
    assert_close(np.dot(a, b), x @ y, rtol=1e-4)
    assert_close(np.matmul(a, b), x @ y, rtol=1e-4)


def test_dynamic_shape_ops():
    a = np.array([0.0, 1.0, 0.0, 2.0])
    (idx,) = np.nonzero(a)
    assert idx.asnumpy().tolist() == [1, 3]
    u = np.unique(np.array([3, 1, 2, 3, 1]))
    assert u.asnumpy().tolist() == [1, 2, 3]
    vals, counts = np.unique(np.array([1, 1, 2]), return_counts=True)
    assert counts.asnumpy().tolist() == [2, 1]


def test_where_sort_takealong():
    a = np.array([3.0, 1.0, 2.0])
    assert_close(np.sort(a), onp.array([1, 2, 3], dtype=onp.float32))
    idx = np.argsort(a)
    assert idx.asnumpy().tolist() == [1, 2, 0]
    w = np.where(a > 1.5, a, np.zeros_like(a))
    assert_close(w, onp.array([3, 0, 2], dtype=onp.float32))
    t = np.take(a, np.array([0, 2], dtype="int32"))
    assert_close(t, onp.array([3, 2], dtype=onp.float32))


def test_np_autograd():
    from mxnet_tpu import autograd

    a = np.array([1.0, 2.0, 3.0])
    a.attach_grad()
    with autograd.record():
        y = np.sum(a * a)
    y.backward()
    assert_close(a.grad, onp.array([2.0, 4.0, 6.0]), rtol=1e-5)
    assert isinstance(a.grad, mx.NDArray)


def test_np_random():
    np.random.seed(0)
    u = np.random.uniform(0, 1, size=(1000,))
    assert u.shape == (1000,)
    assert 0.4 < float(np.mean(u)) < 0.6
    n = np.random.normal(5.0, 0.1, size=(500,))
    assert 4.9 < float(np.mean(n)) < 5.1
    r = np.random.randint(0, 10, size=(100,))
    arr = r.asnumpy()
    assert arr.min() >= 0 and arr.max() < 10
    c = np.random.choice(5, size=(20,))
    assert c.shape == (20,)
    p = np.random.permutation(10)
    assert sorted(p.asnumpy().tolist()) == list(range(10))
    g = np.random.gamma(2.0, 1.0, size=(100,))
    assert float(np.mean(g)) > 0


def test_npx_mode_and_ops():
    npx.set_np()
    try:
        assert npx.is_np_array()
        x = np.array([[1.0, -1.0], [2.0, -2.0]])
        r = npx.relu(x)
        assert isinstance(r, np.ndarray)
        assert_close(r, onp.array([[1, 0], [2, 0]], dtype=onp.float32))
        s = npx.softmax(x, axis=-1)
        assert_close(np.sum(s, axis=-1), onp.ones(2), rtol=1e-5)
        oh = npx.one_hot(np.array([0, 1], dtype="int32"), 3)
        assert oh.shape == (2, 3)
    finally:
        npx.reset_np()
    assert not npx.is_np_array()


def test_npx_bernoulli():
    np.random.seed(0)
    b = npx.random.bernoulli(prob=0.5, size=(200,))
    m = float(np.mean(b))
    assert 0.3 < m < 0.7


def test_mixed_nd_np():
    a = np.array([1.0, 2.0])
    nd_view = a.as_nd_ndarray()
    assert type(nd_view) is mx.NDArray
    back = mx.nd.array([1.0]).data
    assert np.asarray(np.array(back)).shape == (1,)


def test_np_in_jit():
    import jax

    @jax.jit
    def f(x):
        a = np.ndarray(x)
        return np.sum(a * 2).data

    out = f(onp.ones(4, onp.float32))
    assert float(out) == 8.0


def test_grad_flows_through_multi_output_and_views():
    """Regression: taped path for split/as_nd_ndarray/bool-mask getitem."""
    from mxnet_tpu import autograd

    x = np.array([1.0, 2.0, 3.0, 4.0])
    x.attach_grad()
    with autograd.record():
        a, b = np.split(x, 2)
        y = np.sum(a) + np.sum(b)
    y.backward()
    assert_close(x.grad, onp.ones(4))

    x2 = mx.nd.array([1.0, 2.0])
    x2.attach_grad()
    with autograd.record():
        y2 = np.multiply(x2.as_np_ndarray(), x2.as_np_ndarray()).sum()
    y2.backward()
    assert_close(x2.grad, onp.array([2.0, 4.0]))

    x3 = np.array([1.0, -2.0, 3.0])
    x3.attach_grad()
    with autograd.record():
        z = x3[x3 > 0].sum()
    z.backward()
    assert_close(x3.grad, onp.array([1.0, 0.0, 1.0]))


def test_bool_mask_setitem_compacted():
    a = np.array([1.0, -2.0, 3.0])
    a[a > 0] = np.array([10.0, 30.0])
    assert_close(a, onp.array([10.0, -2.0, 30.0]))


def test_random_param_broadcast():
    u = np.random.uniform(np.array([0.0, 10.0]), np.array([1.0, 11.0]))
    assert u.shape == (2,)
    v = u.asnumpy()
    assert 0 <= v[0] <= 1 and 10 <= v[1] <= 11
    r = np.random.randint(np.array([0, 100], dtype="int32"),
                          np.array([10, 110], dtype="int32"))
    rv = r.asnumpy()
    assert 0 <= rv[0] < 10 and 100 <= rv[1] < 110


def test_review_regressions_round2():
    import jax as _jax
    from mxnet_tpu import autograd

    # NDArray params to shifted/scaled samplers stay raw jax arrays
    r = np.random.laplace(scale=np.array([1.0, 2.0]))
    assert isinstance(r._data, _jax.Array) and r.shape == (2,)
    r2 = np.random.rayleigh(scale=np.array([1.0, 1.0]))
    assert r2.shape == (2,)

    # bool-mask setitem is rejected under record
    a = np.array([1.0, -2.0, 3.0])
    a.attach_grad()
    with pytest.raises(mx.MXNetError):
        with autograd.record():
            a[a > 0] = 0.0

    # comparisons with None follow numpy semantics
    eqn = np.array([1.0, 2.0]) == None  # noqa: E711
    assert eqn.asnumpy().tolist() == [False, False]
    nen = np.array([1.0, 2.0]) != None  # noqa: E711
    assert nen.asnumpy().tolist() == [True, True]

    # mixed nd/np ops yield np.ndarray in either operand order
    nd_a, np_b = mx.nd.array([1.0]), np.array([2.0])
    assert isinstance(nd_a + np_b, np.ndarray)
    assert isinstance(np_b + nd_a, np.ndarray)
