"""Detection contrib ops (reference:
tests/python/unittest/test_contrib_operator.py multibox/box_nms cases)."""
import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def test_box_iou():
    a = nd.array([[0, 0, 2, 2], [1, 1, 3, 3]])
    b = nd.array([[0, 0, 2, 2], [2, 2, 4, 4]])
    iou = nd.contrib.box_iou(a, b).asnumpy()
    assert onp.allclose(iou, [[1.0, 0.0], [1 / 7, 1 / 7]], atol=1e-6)


def test_box_nms():
    d = nd.array([[[0, 0.9, 0, 0, 2, 2], [0, 0.8, 0.1, 0.1, 2, 2],
                   [1, 0.7, 0, 0, 2, 2], [0, 0.6, 5, 5, 6, 6]]])
    out = nd.contrib.box_nms(d, overlap_thresh=0.5, coord_start=2,
                             score_index=1, id_index=0).asnumpy()[0]
    assert out[0][1] == 0.9
    assert (out[1] == -1).all()  # same class, high overlap → suppressed
    assert out[2][0] == 1  # different class survives
    assert out[3][1] == 0.6  # disjoint box survives
    # force_suppress kills cross-class overlaps too
    out = nd.contrib.box_nms(d, overlap_thresh=0.5, coord_start=2,
                             score_index=1, id_index=0,
                             force_suppress=True).asnumpy()[0]
    assert (out[2] == -1).all()


def test_multibox_prior():
    x = nd.zeros((1, 3, 2, 2))
    anchors = nd.contrib.MultiBoxPrior(x, sizes=[0.5, 0.25], ratios=[1, 2])
    assert anchors.shape == (1, 12, 4)
    a = anchors.asnumpy()[0]
    # first anchor: size .5 centered (0.25, 0.25)
    assert onp.allclose(a[0], [0, 0, 0.5, 0.5], atol=1e-6)
    assert onp.allclose(a[1], [0.125, 0.125, 0.375, 0.375], atol=1e-6)
    # ratio-2 anchor is wider than tall
    w, h = a[2][2] - a[2][0], a[2][3] - a[2][1]
    assert w > h
    clipped = nd.contrib.MultiBoxPrior(x, sizes=[0.9], clip=True).asnumpy()
    assert clipped.min() >= 0 and clipped.max() <= 1


def test_multibox_target():
    anc = nd.array([[[0.1, 0.1, 0.4, 0.4], [0.5, 0.5, 0.9, 0.9],
                     [0.0, 0.0, 0.2, 0.2]]])
    lab = nd.array([[[0, 0.1, 0.1, 0.42, 0.42], [-1, -1, -1, -1, -1]]])
    cp = nd.zeros((1, 3, 3))
    bt, bm, ct = nd.contrib.MultiBoxTarget(anc, lab, cp)
    assert onp.allclose(ct.asnumpy(), [[1.0, 0.0, 0.0]])
    assert onp.allclose(bm.asnumpy()[0][:4], 1.0)
    assert onp.allclose(bm.asnumpy()[0][4:], 0.0)
    assert onp.isfinite(bt.asnumpy()).all()


def test_multibox_target_negative_mining():
    anc = nd.array([[[0.1, 0.1, 0.4, 0.4], [0.5, 0.5, 0.9, 0.9],
                     [0.0, 0.0, 0.2, 0.2], [0.6, 0.6, 0.8, 0.8]]])
    lab = nd.array([[[0, 0.1, 0.1, 0.42, 0.42]]])
    cp = nd.array(onp.random.RandomState(0).rand(1, 3, 4).astype("f"))
    bt, bm, ct = nd.contrib.MultiBoxTarget(
        anc, lab, cp, negative_mining_ratio=1.0, negative_mining_thresh=0.0)
    c = ct.asnumpy()[0]
    assert c[0] == 1.0
    # with ratio 1.0 and 1 positive, at most 1 negative stays 0, rest -1
    assert (c == -1).sum() >= 1


def test_multibox_detection():
    anc = nd.array([[[0.1, 0.1, 0.4, 0.4], [0.5, 0.5, 0.9, 0.9]]])
    cls_prob = nd.array([[[0.2, 0.8], [0.7, 0.1], [0.1, 0.1]]])
    loc = nd.zeros((1, 8))
    det = nd.contrib.MultiBoxDetection(cls_prob, loc, anc,
                                       threshold=0.05).asnumpy()[0]
    # anchor0: class1 prob .7 → id 0; anchor1: bg .8 dominates, best
    # non-bg .1 still > threshold
    assert det[0][0] == 0 and abs(det[0][1] - 0.7) < 1e-6
    assert onp.allclose(det[0][2:], [0.1, 0.1, 0.4, 0.4], atol=1e-5)


def test_roi_align_values_and_grad():
    data = nd.array(onp.arange(16, dtype="f").reshape(1, 1, 4, 4))
    rois = nd.array([[0, 0, 0, 3, 3]])
    data.attach_grad()
    with autograd.record():
        out = nd.contrib.roi_align(data, rois, pooled_size=(2, 2),
                                   spatial_scale=1.0)
    assert onp.allclose(out.asnumpy().reshape(2, 2),
                        [[3.75, 5.25], [9.75, 11.25]])
    out.backward()
    g = data.grad.asnumpy()
    assert abs(g.sum() - 4.0) < 1e-5  # 4 bins of averaged weights


def test_bipartite_matching():
    s = nd.array([[[0.9, 0.1], [0.8, 0.7]]])
    rm, cm = nd.contrib.bipartite_matching(s, threshold=0.05)
    assert onp.allclose(rm.asnumpy(), [[0, 1]])
    assert onp.allclose(cm.asnumpy(), [[0, 1]])
    # threshold excludes weak matches
    rm, cm = nd.contrib.bipartite_matching(s, threshold=0.75)
    assert onp.allclose(rm.asnumpy(), [[0, -1]])
