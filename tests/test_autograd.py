"""Autograd tape (reference suite: tests/python/unittest/test_autograd.py)."""
import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def test_simple_grad():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [2, 4, 6])


def test_chain_rule():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = nd.exp(x)
        z = y * y
    z.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(),
                                2 * onp.exp(4.0, dtype="f"), rtol=1e-5)


def test_head_grad():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = 3 * x
    y.backward(nd.array([2.0, 4.0]))
    onp.testing.assert_allclose(x.grad.asnumpy(), [6, 12])


def test_grad_req_add():
    x = nd.array([1.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = 2 * x
        y.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [6])


def test_grad_req_null():
    x = nd.array([1.0])
    x.attach_grad(grad_req="null")
    with autograd.record():
        y = 2 * x
    y.backward()  # should not crash


def test_detach_stops_grad():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        z = nd.stop_gradient(y) * x
    z.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [6.0])


def test_is_recording_training():
    assert not autograd.is_recording()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
    with autograd.record(train_mode=False):
        assert not autograd.is_training()
    with autograd.train_mode():
        assert autograd.is_training()
    with autograd.pause():
        assert not autograd.is_recording()


def test_multi_output_op_grad():
    x = nd.array(onp.arange(6).reshape(2, 3).astype("f"))
    x.attach_grad()
    with autograd.record():
        a, b, c = nd.split(x, 3, axis=1)
        loss = (a * 1 + b * 2 + c * 3).sum()
    loss.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(),
                                [[1, 2, 3], [1, 2, 3]])


def test_mark_variables_api():
    x = nd.array([1.0, 1.0])
    g = nd.zeros((2,))
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = (x * 4).sum()
    autograd.backward([y])
    onp.testing.assert_allclose(g.asnumpy(), [4, 4])


def test_grad_function():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x * x
    g = autograd.grad(y, x)
    onp.testing.assert_allclose(g.asnumpy(), [12.0])


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = nd.sigmoid(x)
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            (y,) = self.saved_tensors
            return dy * y * (1 - y)

    f = Sigmoid()
    x = nd.array([0.0])
    x.attach_grad()
    with autograd.record():
        y = f(x)
    y.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [0.25], rtol=1e-5)


def test_retain_graph():
    x = nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        y = x * 5
    y.backward(retain_graph=True)
    onp.testing.assert_allclose(x.grad.asnumpy(), [5])
    y.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [5])


def test_dropout_respects_mode():
    x = nd.ones((100,))
    with autograd.record(train_mode=False):
        y = nd.dropout(x, p=0.5)
    onp.testing.assert_allclose(y.asnumpy(), x.asnumpy())
    with autograd.record(train_mode=True):
        y = nd.dropout(x, p=0.5)
    assert (y.asnumpy() == 0).any()
