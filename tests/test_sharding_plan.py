"""Rule-based ShardingPlan: matching, fallback, env grammar, analysis.

The suite runs on the conftest's forced 8-device host platform, so
real meshes (and real NamedShardings) are available everywhere.
"""
import pytest

import mxnet_tpu as mx  # noqa: F401  (backend init order)
from mxnet_tpu import parallel, sharding
from mxnet_tpu.sharding import ShardingPlan
from mxnet_tpu.sharding.plan import parse_rules, plan_from_env


@pytest.fixture
def mesh():
    return parallel.make_mesh({"dp": 2, "mp": 4})


def _spec(plan, name, shape, mesh):
    return tuple(plan.spec_for(name, shape, mesh))


def test_first_match_wins(mesh):
    plan = ShardingPlan([
        (r"dense0_weight", ("mp", None)),
        (r"weight$", ("dp", None)),
    ])
    assert _spec(plan, "dense0_weight", (8, 4), mesh) == ("mp", None)
    assert _spec(plan, "dense1_weight", (8, 4), mesh) == ("dp", None)


def test_unmatched_replicates_by_default(mesh):
    plan = ShardingPlan({r"weight$": ("mp", None)})
    assert _spec(plan, "dense0_bias", (8,), mesh) == ()


def test_unmatched_error_policy(mesh):
    plan = ShardingPlan({r"weight$": ("mp", None)}, unmatched="error")
    with pytest.raises(ValueError, match="no sharding rule matches"):
        plan.spec_for("dense0_bias", (8,), mesh)
    with pytest.raises(ValueError, match="unmatched"):
        ShardingPlan({}, unmatched="bogus")


def test_scalars_replicate_under_fallback(mesh):
    plan = ShardingPlan({r".*": ("mp",)})
    assert _spec(plan, "loss_scale", (), mesh) == ()
    assert _spec(plan, "one", (1,), mesh) == ()


def test_divisibility_fallback_per_dim(mesh):
    sharding.reset_sharding_counters()
    plan = ShardingPlan({r"w": ("mp", "dp")})
    # dim0 = 6 is not divisible by mp=4 -> that dim replicates; dim1
    # stays sharded over dp
    assert _spec(plan, "w", (6, 4), mesh) == (None, "dp")
    assert sharding.sharding_counters()["divisibility_fallbacks"] == 1


def test_unknown_axis_falls_back(mesh):
    plan = ShardingPlan({r"w": ("tp", None)})
    assert _spec(plan, "w", (8, 4), mesh) == (None, None)


def test_fallback_false_applies_verbatim(mesh):
    plan = ShardingPlan({r"w": ("mp", None)}, fallback=False)
    # 6 % 4 != 0, but verbatim mode hands the spec through untouched
    assert _spec(plan, "w", (6, 4), mesh) == ("mp", None)


def test_spec_longer_than_rank_truncates(mesh):
    plan = ShardingPlan({r"b": ("mp", "dp", None)})
    assert _spec(plan, "b", (8,), mesh) == ("mp",)


def test_parse_rules_grammar():
    rules = parse_rules(
        ".*dense.*weight = mp , * ; bias$ = * ; emb = dp+mp, *")
    assert rules == [
        (".*dense.*weight", ("mp", None)),
        ("bias$", (None,)),
        ("emb", (("dp", "mp"), None)),
    ]
    with pytest.raises(ValueError, match="bad sharding rule"):
        parse_rules("no-equals-here")


def test_plan_from_env(monkeypatch, mesh):
    monkeypatch.delenv("MXNET_SHARDING_RULES", raising=False)
    assert plan_from_env() is None
    monkeypatch.setenv("MXNET_SHARDING_RULES", r"weight$=mp,*")
    monkeypatch.setenv("MXNET_SHARDING_UNMATCHED", "error")
    plan = plan_from_env()
    assert _spec(plan, "d0_weight", (8, 4), mesh) == ("mp", None)
    assert plan.unmatched == "error"


def test_fingerprint_salt_varies_with_mesh_and_rules(mesh):
    p1 = ShardingPlan({r"weight$": ("mp", None)})
    p2 = ShardingPlan({r"weight$": ("dp", None)})
    small = parallel.make_mesh({"mp": 4})
    assert p1.fingerprint_salt(mesh) != p2.fingerprint_salt(mesh)
    assert p1.fingerprint_salt(mesh) != p1.fingerprint_salt(small)
    # process-stable: same inputs, same (cached) tuple
    assert p1.fingerprint_salt(mesh) is p1.fingerprint_salt(mesh)


def test_plan_scope_and_kill_switch(monkeypatch, mesh):
    plan = ShardingPlan({})
    assert sharding.current_plan() is None
    with sharding.plan_scope(plan, mesh) as (p, m):
        assert (p, m) == (plan, mesh)
        assert sharding.current_plan() == (plan, mesh)
        monkeypatch.setenv("MXNET_SHARDING", "0")
        assert sharding.current_plan() is None  # one knob kills it all
        monkeypatch.delenv("MXNET_SHARDING")
    assert sharding.current_plan() is None


def test_plan_scope_needs_a_mesh():
    with pytest.raises(ValueError, match="needs a mesh"):
        sharding.plan_scope(ShardingPlan({}))


def test_shardings_and_named_sharding(mesh):
    import jax

    plan = ShardingPlan({r"weight$": ("mp", None)})
    sh = plan.shardings({"d0_weight": (8, 4), "d0_bias": (8,)},
                        mesh=mesh)
    assert isinstance(sh["d0_weight"], jax.sharding.NamedSharding)
    assert tuple(sh["d0_weight"].spec) == ("mp", None)
    assert tuple(sh["d0_bias"].spec) == ()
    rep = sharding.replicated(mesh)
    assert rep.is_fully_replicated


def test_spmd_shard_params_shim(mesh):
    """The legacy parallel.spmd entry point rides the plan matcher but
    keeps verbatim specs + unmatched-replicate semantics."""

    class _P:
        def __init__(self, shape):
            self.shape = shape

    out = parallel.shard_params(
        {"d0_weight": _P((8, 4)), "d0_bias": _P((8,))},
        mesh, rules={r"weight$": ("mp", None)})
    assert tuple(out["d0_weight"].spec) == ("mp", None)
    assert out["d0_bias"].is_fully_replicated


def test_verify_plan_gv_diagnostics(mesh):
    from mxnet_tpu.analysis import verify_plan

    plan = ShardingPlan([
        (r"weight$", ("tp", None)),   # axis the mesh doesn't have
        (r"typo_never_matches", ("mp",)),
    ])
    report = verify_plan(plan, {"d0_weight": (8, 4), "d0_bias": (8,)},
                         mesh)
    codes = report.codes()
    assert "GV501" in codes  # bad axis
    assert "GV503" in codes  # dead rule
    clean = verify_plan(ShardingPlan({r"weight$": ("mp", None)}),
                        {"d0_weight": (8, 4)}, mesh)
    assert not clean


def test_counters_roundtrip(mesh):
    sharding.reset_sharding_counters()
    plan = ShardingPlan({r"weight$": ("mp", None)})
    plan.spec_for("d0_weight", (8, 4), mesh)
    plan.spec_for("d0_bias", (8,), mesh)
    c = sharding.sharding_counters()
    assert c["plans_built"] == 1
    assert c["rules_matched"] == 1
    assert c["rules_unmatched"] == 1
    assert c["enabled"] is True
    from mxnet_tpu import profiler

    assert profiler.sharding_counters() == c


def test_runtime_feature_flag():
    from mxnet_tpu import runtime

    feats = runtime.Features()
    assert feats.is_enabled("SHARDING")
