"""Compiled CSV/LibSVM parser tests (native/textio.cc behind CSVIter /
LibSVMIter; reference: src/io/iter_csv.cc, src/io/iter_libsvm.cc)."""
import numpy as onp
import pytest

from mxnet_tpu._native import textlib
from mxnet_tpu.io import CSVIter
from mxnet_tpu.io.image_record import LibSVMIter
from mxnet_tpu.base import MXNetError


def test_native_parser_loaded():
    assert textlib is not None, "libtextio.so failed to build/load"


def test_csviter_matches_numpy(tmp_path):
    rng = onp.random.RandomState(0)
    data = rng.randn(256, 6).astype("f")
    labels = rng.randint(0, 3, (256, 1)).astype("f")
    dpath, lpath = tmp_path / "d.csv", tmp_path / "l.csv"
    onp.savetxt(dpath, data, delimiter=",", fmt="%.6g")
    onp.savetxt(lpath, labels, delimiter=",", fmt="%.6g")
    it = CSVIter(data_csv=str(dpath), data_shape=(6,),
                 label_csv=str(lpath), label_shape=(1,), batch_size=64,
                 round_batch=False)
    got_d, got_l = [], []
    for batch in it:
        got_d.append(batch.data[0].asnumpy())
        got_l.append(batch.label[0].asnumpy())
    got_d = onp.concatenate(got_d)
    got_l = onp.concatenate(got_l)
    onp.testing.assert_allclose(got_d, data, rtol=1e-4, atol=1e-5)
    onp.testing.assert_allclose(got_l.reshape(-1, 1), labels, rtol=1e-5)


def test_csv_blank_lines_and_spaces(tmp_path):
    p = tmp_path / "x.csv"
    p.write_text("1.0, 2.0 ,3.0\n\n4.5,5.5,6.5\n   \n7,8,9\n")
    it = CSVIter(data_csv=str(p), data_shape=(3,), batch_size=3,
                 round_batch=False)
    batch = next(iter(it))
    onp.testing.assert_allclose(
        batch.data[0].asnumpy(),
        [[1.0, 2.0, 3.0], [4.5, 5.5, 6.5], [7.0, 8.0, 9.0]])


def test_csv_ragged_raises(tmp_path):
    p = tmp_path / "bad.csv"
    p.write_text("1,2,3\n4,5\n")
    with pytest.raises((MXNetError, ValueError)):
        CSVIter(data_csv=str(p), data_shape=(3,), batch_size=1)


def test_csv_malformed_raises(tmp_path):
    p = tmp_path / "bad.csv"
    p.write_text("1,abc,3\n")
    with pytest.raises((MXNetError, ValueError)):
        CSVIter(data_csv=str(p), data_shape=(3,), batch_size=1)


def test_libsvm_inline_labels(tmp_path):
    p = tmp_path / "t.libsvm"
    p.write_text("1 0:1.5 3:2.5\n"
                 "0 1:-1.0\n"
                 "\n"
                 "2 0:0.5 2:4.0 3:-2.0\n")
    it = LibSVMIter(data_libsvm=str(p), data_shape=(4,), batch_size=3,
                    round_batch=False)
    batch = next(iter(it))
    dense = batch.data[0].asnumpy() if not hasattr(
        batch.data[0], "todense") else batch.data[0].todense().asnumpy()
    expect = onp.array([[1.5, 0, 0, 2.5],
                        [0, -1.0, 0, 0],
                        [0.5, 0, 4.0, -2.0]], "f")
    onp.testing.assert_allclose(dense, expect)
    onp.testing.assert_allclose(batch.label[0].asnumpy(), [1, 0, 2])


def test_libsvm_separate_label_file(tmp_path):
    d = tmp_path / "d.libsvm"
    l = tmp_path / "l.libsvm"
    d.write_text("0:1.0\n1:2.0\n")
    l.write_text("5\n7\n")
    it = LibSVMIter(data_libsvm=str(d), data_shape=(2,),
                    label_libsvm=str(l), batch_size=2, round_batch=False)
    batch = next(iter(it))
    onp.testing.assert_allclose(batch.label[0].asnumpy(), [5, 7])


def test_libsvm_malformed_raises(tmp_path):
    p = tmp_path / "bad.libsvm"
    p.write_text("1 0:1.5 nonsense\n")
    with pytest.raises((MXNetError, ValueError)):
        LibSVMIter(data_libsvm=str(p), data_shape=(4,), batch_size=1)


def test_native_csv_large_parallel(tmp_path):
    """Big enough to span several parser threads; order must hold."""
    n = 20000
    data = onp.arange(n * 3, dtype=onp.float32).reshape(n, 3)
    p = tmp_path / "big.csv"
    onp.savetxt(p, data, delimiter=",", fmt="%.1f")
    from mxnet_tpu.io.io import _parse_csv

    out = _parse_csv(str(p))
    assert out.shape == (n, 3)
    onp.testing.assert_allclose(out, data)


def test_csv_comments_like_loadtxt(tmp_path):
    p = tmp_path / "c.csv"
    p.write_text("# header comment\n1,2,3\n4,5,6 # trailing\n")
    from mxnet_tpu.io.io import _parse_csv

    out = _parse_csv(str(p))
    onp.testing.assert_allclose(out, [[1, 2, 3], [4, 5, 6]])


def test_csv_directory_raises_not_aborts(tmp_path):
    with pytest.raises((MXNetError, ValueError, OSError)):
        CSVIter(data_csv=str(tmp_path), data_shape=(3,), batch_size=1)
