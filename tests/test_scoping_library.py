"""NameManager/Prefix, AttrScope, and mx.library dynamic op libs
(reference: python/mxnet/name.py, attribute.py, library.py +
tests/python/unittest/test_symbol.py name/attr cases)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.base import MXNetError


def test_auto_names_per_hint_counter():
    a = sym.Variable("data")
    fc1 = sym.FullyConnected(a, num_hidden=4)
    act = sym.Activation(fc1, act_type="relu")
    fc2 = sym.FullyConnected(act, num_hidden=4)
    # per-hint counters like the reference (NOT one global counter)
    assert fc1.name.startswith("fully_connected")
    assert act.name.startswith("activation")
    int(fc2.name[len("fully_connected"):])  # numeric suffix
    assert fc1.name != fc2.name


def test_prefix_scopes_names():
    with mx.name.Prefix("block1_"):
        v = sym.Variable(None)
        fc = sym.FullyConnected(sym.Variable("data"), name="fc",
                                num_hidden=2)
    assert v.name.startswith("block1_var")
    # explicit op names are prefixed too (reference Prefix.get) — this is
    # what namespaces checkpoints
    assert fc.name == "block1_fc"
    # explicit VARIABLE names are used verbatim (reference var())
    assert "data" in fc.list_arguments()
    # auto-created params inherit the scoped node name
    assert "block1_fc_weight" in fc.list_arguments()
    # outside the scope the prefix is gone
    assert sym.Variable(None).name.startswith("var")


def test_name_manager_nesting_restores():
    outer = mx.name.NameManager()
    with outer:
        n1 = sym.Variable(None).name
        with mx.name.Prefix("in_"):
            n2 = sym.Variable(None).name
        n3 = sym.Variable(None).name
    assert n2.startswith("in_")
    assert not n3.startswith("in_")
    assert n1 != n3  # same manager, counter advanced


def test_attr_scope_stamps_nodes():
    with mx.AttrScope(ctx_group="dev1", __lr_mult__="2.0"):
        v = sym.Variable("w")
        fc = sym.FullyConnected(sym.Variable("data"), weight=v,
                                num_hidden=2)
    assert v.attr("ctx_group") == "dev1"
    assert fc.attr("__lr_mult__") == "2.0"
    # nested scopes merge, inner wins
    with mx.AttrScope(a="1", b="1"):
        with mx.AttrScope(b="2"):
            s = sym.Variable("x")
    assert s.attr("a") == "1" and s.attr("b") == "2"
    # outside any scope: no stamps
    assert sym.Variable("y").attr("ctx_group") is None


def test_attr_scope_rejects_non_string():
    with pytest.raises(ValueError, match="strings"):
        mx.AttrScope(lr_mult=2.0)


def test_attr_scope_survives_json_roundtrip():
    with mx.AttrScope(ctx_group="dev7"):
        fc = sym.FullyConnected(sym.Variable("data"), num_hidden=2)
    loaded = sym.load_json(fc.tojson())
    # find the fc node in the loaded graph
    node = loaded if loaded.name == fc.name else None
    assert node is not None, f"fc node lost: {loaded.name}"
    assert node.attr("ctx_group") == "dev7"


OPLIB = '''
import jax.numpy as jnp


def register_ops(registry):
    @registry.register("scaled_shift", namespaces=("nd", "sym"))
    def scaled_shift(x, scale=2.0, shift=0.0):
        """y = x * scale + shift (test op library)."""
        return x * scale + shift
'''


def test_library_load_registers_ops(tmp_path):
    p = tmp_path / "myops.py"
    p.write_text(OPLIB)
    mod = mx.library.load(str(p))
    assert mod is mx.library.load(str(p))  # idempotent
    assert str(p) in mx.library.loaded_libraries()
    x = nd.array(onp.arange(4, dtype="f"))
    y = nd.scaled_shift(x, scale=3.0, shift=1.0)
    onp.testing.assert_allclose(y.asnumpy(), onp.arange(4) * 3 + 1)
    # symbol namespace picked the op up too
    s = sym.scaled_shift(sym.Variable("data"), scale=2.0)
    out = s.eval_with({"data": x})
    onp.testing.assert_allclose(out.asnumpy(), onp.arange(4) * 2)


def test_library_load_errors():
    with pytest.raises(MXNetError, match="not found"):
        mx.library.load("/nonexistent/lib.so")


def test_library_requires_hook(tmp_path):
    p = tmp_path / "empty.py"
    p.write_text("x = 1\n")
    with pytest.raises(MXNetError, match="register_ops"):
        mx.library.load(str(p))
