"""Launcher, bandwidth tool, contrib.text, tensorboard writer, legacy
mx.rnn cells + BucketSentenceIter, env-knob registry.

Reference coverage model: tests/python/unittest/test_contrib_text.py,
test_rnn.py, plus tracker smoke tests under tools/.
"""
import collections
import os
import struct
import subprocess
import sys

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, rnn, env
import mxnet_tpu.symbol as sym

rs = onp.random.RandomState(4)


# ------------------------------------------------------------- launcher ---

def test_launch_local_spawns_workers(tmp_path):
    from mxnet_tpu.tools import launch

    out = tmp_path / "out"
    script = tmp_path / "w.py"
    script.write_text(
        "import os\n"
        f"open(r'{out}' + os.environ['MXNET_PROCESS_ID'], 'w')"
        ".write(os.environ['MXNET_NUM_PROCESSES'] + ' ' +"
        "os.environ['MXNET_COORDINATOR'])\n")
    rc = launch.main(["-n", "3", "--launcher", "local",
                      "--env", "FOO:bar",
                      sys.executable, str(script)])
    assert rc == 0
    for rank in range(3):
        text = (tmp_path / f"out{rank}").read_text()
        assert text.startswith("3 127.0.0.1:")


def test_launch_init_noop_without_env(monkeypatch):
    from mxnet_tpu.tools import launch

    monkeypatch.delenv("MXNET_COORDINATOR", raising=False)
    assert launch.init() is False


def test_bandwidth_tool_runs():
    from mxnet_tpu.tools import bandwidth

    res = bandwidth.measure(4096, iters=2, warmup=1)
    assert res["num_devices"] >= 1
    assert res["collective_gbps"] > 0
    assert res["kvstore_gbps"] > 0


# ----------------------------------------------------------- contrib.text ---

def test_vocabulary():
    from mxnet_tpu.contrib import text

    counter = text.utils.count_tokens_from_str(
        "a b b c c c\nd d d d", to_lower=False)
    assert counter == collections.Counter(
        {"d": 4, "c": 3, "b": 2, "a": 1})
    v = text.Vocabulary(counter, min_freq=2,
                        reserved_tokens=["<pad>"])
    assert v.idx_to_token[:2] == ["<unk>", "<pad>"]
    assert v.to_indices("d") == 2  # most frequent first
    assert v.to_indices(["c", "zzz"]) == [3, 0]  # unknown -> 0
    assert v.to_tokens(2) == "d"
    assert len(v) == 5  # unk, pad, d, c, b


def test_custom_embedding(tmp_path):
    from mxnet_tpu.contrib.text import embedding

    f = tmp_path / "emb.txt"
    f.write_text("hello 1.0 2.0 3.0\nworld 4.0 5.0 6.0\n")
    emb = embedding.CustomEmbedding(str(f))
    assert emb.vec_len == 3
    vec = emb.get_vecs_by_tokens("world")
    onp.testing.assert_allclose(vec.asnumpy(), [4, 5, 6])
    vecs = emb.get_vecs_by_tokens(["hello", "nope"])
    onp.testing.assert_allclose(vecs.asnumpy()[0], [1, 2, 3])
    onp.testing.assert_allclose(vecs.asnumpy()[1], [0, 0, 0])
    emb.update_token_vectors("hello", nd.array([[9.0, 9.0, 9.0]]))
    onp.testing.assert_allclose(
        emb.get_vecs_by_tokens("hello").asnumpy(), [9, 9, 9])
    # registry
    assert "customembedding" in embedding.get_pretrained_file_names()


# ------------------------------------------------------------ tensorboard ---

def test_tensorboard_event_file(tmp_path):
    from mxnet_tpu.contrib.tensorboard import SummaryWriter

    w = SummaryWriter(str(tmp_path))
    w.add_scalar("loss", 0.5, global_step=1)
    w.add_scalar("loss", 0.25, global_step=2)
    w.close()
    files = [f for f in os.listdir(tmp_path) if "tfevents" in f]
    assert len(files) == 1
    # verify tfrecord framing: length + masked crc of length header
    from mxnet_tpu.contrib.tensorboard import _masked_crc

    with open(tmp_path / files[0], "rb") as f:
        blob = f.read()
    off = 0
    events = 0
    while off < len(blob):
        (ln,) = struct.unpack_from("<Q", blob, off)
        (crc,) = struct.unpack_from("<I", blob, off + 8)
        assert crc == _masked_crc(blob[off:off + 8])
        payload = blob[off + 12:off + 12 + ln]
        (pcrc,) = struct.unpack_from("<I", blob, off + 12 + ln)
        assert pcrc == _masked_crc(payload)
        off += 12 + ln + 4
        events += 1
    assert events == 3  # file-version event + 2 scalars
    assert b"loss" in blob


# ------------------------------------------------------------- legacy rnn ---

def _run_unrolled(cell, T=4, N=2, C=3, H=5):
    outputs, states = cell.unroll(T, sym.Variable("data"),
                                  merge_outputs=True)
    feed = {"data": nd.array(rs.randn(N, T, C).astype("f"))}
    args = outputs.list_arguments()
    shapes = {"data": (N, T, C)}
    for name in args:
        if name == "data":
            continue
        if "i2h_weight" in name:
            feed[name] = nd.array(rs.randn(
                H * _gates(cell), C).astype("f") * 0.1)
        elif "h2h_weight" in name:
            feed[name] = nd.array(rs.randn(
                H * _gates(cell), H).astype("f") * 0.1)
        elif "bias" in name:
            feed[name] = nd.zeros((H * _gates(cell),))
        elif "begin_state" in name:
            feed[name] = nd.zeros((N, H))
    ex = outputs.bind(mx.cpu(), feed)
    (out,) = ex.forward()
    return out


def _gates(cell):
    from mxnet_tpu.rnn import LSTMCell, GRUCell

    if isinstance(cell, LSTMCell):
        return 4
    if isinstance(cell, GRUCell):
        return 3
    return 1


@pytest.mark.parametrize("ctor", [rnn.RNNCell, rnn.LSTMCell,
                                  rnn.GRUCell])
def test_legacy_cell_unroll_shapes(ctor):
    out = _run_unrolled(ctor(5))
    assert out.shape == (2, 4, 5)
    assert onp.isfinite(out.asnumpy()).all()


def test_legacy_lstm_matches_gluon():
    """The symbolic LSTMCell unroll and the gluon LSTM agree given the
    same weights."""
    from mxnet_tpu.gluon import rnn as grnn

    T, N, C, H = 3, 2, 4, 5
    x = rs.randn(N, T, C).astype("f")
    iW = rs.randn(4 * H, C).astype("f") * 0.2
    hW = rs.randn(4 * H, H).astype("f") * 0.2
    iB = rs.randn(4 * H).astype("f") * 0.1
    hB = rs.randn(4 * H).astype("f") * 0.1

    cell = rnn.LSTMCell(H, prefix="l_")
    outputs, _ = cell.unroll(T, sym.Variable("data"),
                             merge_outputs=True)
    ex = outputs.bind(mx.cpu(), {
        "data": nd.array(x), "l_i2h_weight": nd.array(iW),
        "l_h2h_weight": nd.array(hW), "l_i2h_bias": nd.array(iB),
        "l_h2h_bias": nd.array(hB),
        "l_begin_state_0": nd.zeros((N, H)),
        "l_begin_state_1": nd.zeros((N, H))})
    (out_sym,) = ex.forward()

    layer = grnn.LSTM(H, layout="NTC", input_size=C)
    layer.initialize()
    params = {p.name: p for p in layer.collect_params().values()}
    for name, p in params.items():
        if "i2h_weight" in name:
            p.set_data(nd.array(iW))
        elif "h2h_weight" in name:
            p.set_data(nd.array(hW))
        elif "i2h_bias" in name:
            p.set_data(nd.array(iB))
        elif "h2h_bias" in name:
            p.set_data(nd.array(hB))
    out_gluon = layer(nd.array(x))
    onp.testing.assert_allclose(out_sym.asnumpy(), out_gluon.asnumpy(),
                                rtol=2e-3, atol=1e-4)


def test_sequential_and_fused_cells():
    stack = rnn.SequentialRNNCell()
    stack.add(rnn.LSTMCell(4, prefix="a_"))
    stack.add(rnn.DropoutCell(0.0))
    stack.add(rnn.GRUCell(4, prefix="b_"))
    outputs, states = stack.unroll(3, sym.Variable("data"),
                                   merge_outputs=True)
    assert len(states) == 3  # lstm h,c + gru h
    fused = rnn.FusedRNNCell(4, num_layers=2, mode="lstm")
    unf = fused.unfuse()
    assert len(unf._cells) == 2


def test_encode_sentences_and_bucket_iter():
    sentences = [["a", "b", "c"], ["a", "c"], ["b", "c", "a", "d"],
                 ["a", "b"], ["c", "a"], ["d", "c", "a"]]
    coded, vocab = rnn.encode_sentences(sentences, invalid_label=0,
                                        start_label=1)
    assert all(all(i >= 1 for i in s) for s in coded)
    it = rnn.BucketSentenceIter(coded, batch_size=2, buckets=[2, 3, 4],
                                invalid_label=0)
    seen = 0
    for batch in it:
        T = batch.bucket_key
        assert batch.data[0].shape == (2, T)
        assert batch.label[0].shape == (2, T)
        d = batch.data[0].asnumpy()
        lab = batch.label[0].asnumpy()
        # label is data shifted left
        onp.testing.assert_allclose(lab[:, :-1], d[:, 1:])
        seen += 1
    # bucket 2 holds 3 sentences (1 batch), bucket 3 holds 2 (1 batch),
    # bucket 4 holds 1 (< batch_size, dropped)
    assert seen == 2


# ------------------------------------------------------------- env knobs ---

def test_env_registry():
    assert "MXNET_ENGINE_TYPE" in env.KNOBS
    table = env.describe()
    assert "MXNET_KVSTORE_BIGARRAY_BOUND" in table
    assert env.get_int("MXNET_NOT_SET_XYZ", 7) == 7


def test_env_check_warns_on_unknown(monkeypatch, caplog):
    monkeypatch.setenv("MXNET_TOTALLY_BOGUS_KNOB", "1")
    unknown = env.check()
    assert "MXNET_TOTALLY_BOGUS_KNOB" in unknown


def test_env_kvstore_gc(monkeypatch):
    from mxnet_tpu import kvstore

    monkeypatch.setenv("MXNET_KVSTORE_GC_TYPE", "2bit")
    monkeypatch.setenv("MXNET_KVSTORE_GC_THRESHOLD", "0.25")
    kv = kvstore.create("device")
    assert kv._compression is not None
    assert kv._compression.threshold == 0.25


def test_mxnet_seed_subprocess(tmp_path):
    script = tmp_path / "s.py"
    script.write_text(
        "import os\n"
        "os.environ.pop('PALLAS_AXON_POOL_IPS', None)\n"
        "import sys; sys.path.insert(0, r'%s')\n"
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu import nd\n"
        "print(float(mx.nd.random.uniform(shape=(1,)).asnumpy()[0]))\n"
        % os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env_base = dict(os.environ, MXNET_SEED="42", JAX_PLATFORMS="cpu")
    env_base.pop("PALLAS_AXON_POOL_IPS", None)
    r1 = subprocess.run([sys.executable, str(script)], env=env_base,
                        capture_output=True, text=True, timeout=300)
    r2 = subprocess.run([sys.executable, str(script)], env=env_base,
                        capture_output=True, text=True, timeout=300)
    assert r1.returncode == 0, r1.stderr[-500:]
    assert r1.stdout.strip() == r2.stdout.strip()
