"""Optimizer tail: LARS, LBSGD, DCASGD, SGLD, multi-precision, SVRG.

Reference coverage model: tests/python/unittest/test_optimizer.py
(per-optimizer update-math checks) +
tests/python/unittest/test_contrib_svrg_module.py.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon, optimizer as opt
from mxnet_tpu.gluon import nn

rs = onp.random.RandomState(2)


def _one_step(name, params, w0, g0, steps=1):
    o = opt.create(name, **params)
    w = nd.array(w0.copy())
    state = o.create_state(0, w)
    for _ in range(steps):
        o.update(0, w, nd.array(g0), state)
    return w.asnumpy()


def test_lars_update_math():
    w0 = rs.rand(6).astype("f") + 0.5
    g0 = rs.rand(6).astype("f")
    lr, eta, wd = 0.1, 0.01, 0.001
    out = _one_step("lars", {"learning_rate": lr, "eta": eta, "wd": wd,
                             "momentum": 0.0}, w0, g0)
    wn = onp.linalg.norm(w0)
    gn = onp.linalg.norm(g0)
    lr_l = lr * eta * wn / (gn + wd * wn)
    expect = w0 - lr_l * (g0 + wd * w0)
    onp.testing.assert_allclose(out, expect, rtol=1e-5)


def test_lars_skips_bias_and_bn_params():
    o = opt.create("lars", learning_rate=0.1, eta=0.01,
                   param_idx2name={0: "fc_bias", 1: "bn_gamma",
                                   2: "fc_weight"})
    assert not o._is_scaled(0)
    assert not o._is_scaled(1)
    assert o._is_scaled(2)


def test_lbsgd_warmup_schedule():
    o = opt.create("lbsgd", learning_rate=0.1, momentum=0.0,
                   batch_scale=8, warmup_epochs=2, updates_per_epoch=10,
                   warmup_strategy="linear")
    m0 = o._warmup_mult()
    for _ in range(9):
        o._update_count(0)
    m_mid = o._warmup_mult()  # halfway through the 20-update warmup
    for _ in range(30):
        o._update_count(0)
    m_end = o._warmup_mult()
    assert m0 < m_mid < m_end == 8.0


def test_dcasgd_update_math():
    w0 = rs.rand(5).astype("f")
    g0 = rs.rand(5).astype("f")
    lr, lamda = 0.05, 0.04
    out = _one_step("dcasgd", {"learning_rate": lr, "lamda": lamda,
                               "momentum": 0.0, "wd": 0.0}, w0, g0)
    # first step: previous == current weight, so compensation is zero
    onp.testing.assert_allclose(out, w0 - lr * g0, rtol=1e-5)
    # two steps with constant grad: second step compensates
    o = opt.create("dcasgd", learning_rate=lr, lamda=lamda, momentum=0.0)
    w = nd.array(w0.copy())
    st = o.create_state(0, w)
    o.update(0, w, nd.array(g0), st)
    w1 = w.asnumpy().copy()
    o.update(0, w, nd.array(g0), st)
    expect2 = w1 - lr * (g0 + lamda * g0 * g0 * (w1 - w0))
    onp.testing.assert_allclose(w.asnumpy(), expect2, rtol=1e-5)


def test_sgld_moves_and_is_stochastic():
    mx.random.seed(0)
    w0 = onp.zeros(1000, "f")
    g0 = onp.zeros(1000, "f")
    out = _one_step("sgld", {"learning_rate": 0.01}, w0, g0)
    # pure noise step: mean ~ 0, std ~ sqrt(lr)
    assert abs(out.mean()) < 0.02
    assert abs(out.std() - 0.1) < 0.02


def test_multi_precision_master_weights():
    o = opt.create("sgd", learning_rate=0.1, momentum=0.9,
                   multi_precision=True)
    w = nd.array(rs.rand(4).astype("float16"), dtype="float16")
    st = o.create_state_multi_precision(0, w)
    master, base = st
    assert str(master.data.dtype) == "float32"
    g = nd.array(rs.rand(4).astype("float16"), dtype="float16")
    w_before = w.asnumpy().copy()
    o.update_multi_precision(0, w, g, st)
    assert str(w.data.dtype) == "float16"
    assert not onp.allclose(w.asnumpy(), w_before)
    # master kept full precision
    assert str(master.data.dtype) == "float32"


def test_svrg_module_runs_and_learns():
    import mxnet_tpu.symbol as sym
    from mxnet_tpu.contrib.svrg_optimization import SVRGModule
    from mxnet_tpu.io import NDArrayIter

    r = onp.random.RandomState(0)
    X = r.randn(128, 10).astype("f")
    yv = (X.sum(1) > 0).astype("f")
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=16, name="fc1",
                             weight=sym.Variable("fc1_weight"),
                             bias=sym.Variable("fc1_bias"))
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=2, name="fc2",
                             weight=sym.Variable("fc2_weight"),
                             bias=sym.Variable("fc2_bias"))
    net = sym.SoftmaxOutput(net, sym.Variable("softmax_label"),
                            name="softmax")
    it = NDArrayIter(X, yv, batch_size=32, shuffle=False,
                     label_name="softmax_label")
    mod = SVRGModule(net, update_freq=2)
    mod.fit(it, num_epoch=4, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5})
    it.reset()
    import mxnet_tpu.metric as metric

    m = metric.create("acc")
    mod_score = mod.score(it, m) if hasattr(mod, "score") else None
    # direct predict accuracy
    it.reset()
    correct = total = 0
    for batch in it:
        mod.forward(batch, is_train=False)
        pred = mod.get_outputs()[0].asnumpy().argmax(1)
        lab = batch.label[0].asnumpy()
        correct += (pred == lab).sum()
        total += len(lab)
    assert correct / total > 0.8


def test_svrg_variance_reduction_changes_grads():
    import mxnet_tpu.symbol as sym
    from mxnet_tpu.contrib.svrg_optimization import SVRGModule
    from mxnet_tpu.io import NDArrayIter

    r = onp.random.RandomState(1)
    X = r.randn(64, 8).astype("f")
    yv = r.randint(0, 2, 64).astype("f")
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=2, name="fc",
                             weight=sym.Variable("fc_weight"),
                             bias=sym.Variable("fc_bias"))
    net = sym.SoftmaxOutput(net, sym.Variable("softmax_label"),
                            name="softmax")
    it = NDArrayIter(X, yv, batch_size=16, label_name="softmax_label")
    mod = SVRGModule(net, update_freq=1)
    mod.bind([d for d in it.provide_data],
             [d for d in it.provide_label])
    mod.init_params()
    mod.update_full_grads(it)
    assert mod._param_dict and all(
        onp.isfinite(v.asnumpy()).all()
        for v in mod._param_dict.values())
    it.reset()
    batch = next(iter(it))
    # plain gradient
    mod.forward(batch, is_train=True)
    mod.backward()
    plain = {n: mod._exec.grad_dict[n].asnumpy().copy()
             for n in mod._param_names() if n in mod._exec.grad_dict}
    # svrg-corrected gradient from the same batch
    mod.forward_backward(batch)
    changed = any(
        not onp.allclose(plain[n],
                         mod._exec.grad_dict[n].asnumpy())
        for n in plain)
    # snapshot == current params and full-grad != batch-grad => corrected
    assert changed


def test_group_adagrad_row_wise_history():
    """Reference: optimizer/contrib.py GroupAdaGrad — one history cell
    per ROW; dense and row_sparse paths agree on touched rows."""
    import numpy as onp

    from mxnet_tpu import nd
    from mxnet_tpu import optimizer as opt

    o = opt.create("groupadagrad", learning_rate=0.1, eps=1e-5)
    rng = onp.random.RandomState(0)
    w = rng.randn(4, 3).astype("f")
    g = rng.randn(4, 3).astype("f")
    wn = nd.array(w.copy())
    state = o.create_state(0, wn)
    assert state.shape == (4, 1)
    o.update(0, wn, nd.array(g.copy()), state)
    hist = onp.mean(g ** 2, axis=1, keepdims=True)
    want = w - 0.1 * g / onp.sqrt(hist + 1e-5)
    onp.testing.assert_allclose(wn.asnumpy(), want, rtol=1e-5)
    onp.testing.assert_allclose(state.asnumpy(), hist, rtol=1e-5)
    # second update accumulates
    o.update(0, wn, nd.array(g.copy()), state)
    onp.testing.assert_allclose(state.asnumpy(), 2 * hist, rtol=1e-5)
    # wd is rejected like the reference
    bad = opt.create("groupadagrad", learning_rate=0.1, wd=0.1)
    import pytest

    with pytest.raises(AssertionError, match="not supported"):
        bad.update(0, nd.array(w.copy()), nd.array(g.copy()),
                   bad.create_state(0, nd.array(w.copy())))


def test_group_adagrad_sparse_rows_only():
    import numpy as onp

    from mxnet_tpu import nd
    from mxnet_tpu import optimizer as opt
    from mxnet_tpu.ndarray import sparse as sp

    o = opt.create("groupadagrad", learning_rate=0.5)
    w0 = onp.ones((5, 2), "f")
    wn = nd.array(w0.copy())
    state = o.create_state(0, wn)
    vals = onp.array([[1.0, 1.0], [2.0, 2.0]], "f")
    g = sp.row_sparse_array((vals, onp.array([1, 3])), shape=(5, 2))
    o.update(0, wn, g, state)
    got = wn.asnumpy()
    st = state.asnumpy()
    # untouched rows unchanged, histories zero
    for r in (0, 2, 4):
        onp.testing.assert_allclose(got[r], w0[r])
        assert st[r, 0] == 0.0
    # touched rows follow the dense formula
    for r, v in ((1, 1.0), (3, 2.0)):
        h = v * v
        onp.testing.assert_allclose(st[r, 0], h, rtol=1e-6)
        onp.testing.assert_allclose(
            got[r], w0[r] - 0.5 * v / onp.sqrt(h + 1e-5), rtol=1e-5)


def test_mp_update_ops_master_copy_semantics():
    """r5 op tail: mp_* optimizer ops keep an fp32 master alongside a
    low-precision weight (reference optimizer_op.cc MP_SGD etc.)."""
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import nd

    w32 = nd.array(onp.ones(4, "f"))
    w = w32.astype("float16")
    g = nd.array(onp.full(4, 0.5, "f")).astype("float16")
    nw, nw32 = nd.mp_sgd_update(w, g, w32, lr=0.1)
    onp.testing.assert_allclose(nw32.asnumpy(), 0.95 * onp.ones(4),
                                rtol=1e-6)
    assert str(nw.dtype) == "float16"
    mom = nd.zeros(4)
    nw, nmom, nw32 = nd.mp_sgd_mom_update(w, g, mom, w32, lr=0.1,
                                          momentum=0.9)
    assert str(nw.dtype) == "float16" and nw32.dtype == onp.float32

    # mp_adamw: rescale_grad is a TENSOR (loss-scale)
    mean, var = nd.zeros(4), nd.zeros(4)
    scale = nd.array([1.0])
    ws, nmean, nvar, nw32 = nd.mp_adamw_update(
        w, g, mean, var, w32, scale, lr=0.01)
    assert str(ws.dtype) == "float16"
    assert float(nvar.asnumpy()[0]) > 0

    # multi_all_finite over mixed tensors
    good = nd.array(onp.ones(3, "f"))
    bad = nd.array(onp.array([1.0, onp.inf, 0.0], "f"))
    assert float(nd.multi_all_finite(good, good).asnumpy()[0]) == 1.0
    assert float(nd.multi_all_finite(good, bad).asnumpy()[0]) == 0.0


def test_multi_adamw_and_preloaded_mp_sgd():
    import numpy as onp

    from mxnet_tpu import nd

    w1, w2 = nd.array(onp.ones(3, "f")), nd.array(onp.ones(2, "f") * 2)
    g1, g2 = nd.array(onp.full(3, 0.1, "f")), nd.array(onp.full(2, 0.2, "f"))
    m1, m2 = nd.zeros(3), nd.zeros(2)
    v1, v2 = nd.zeros(3), nd.zeros(2)
    scale = nd.array([1.0])
    outs = nd.multi_adamw_update(w1, g1, m1, v1, w2, g2, m2, v2, scale,
                                 lrs=(0.01, 0.01), wds=(0.0, 0.0),
                                 etas=(1.0, 1.0), num_weights=2)
    assert len(outs) == 6
    assert outs[0].shape == (3,) and outs[1].shape == (2,)
    assert float(outs[0].asnumpy()[0]) < 1.0  # moved toward smaller

    # preloaded: lrs/wds ride as tensors
    w32a, w32b = nd.array(onp.ones(3, "f")), nd.array(onp.ones(2, "f"))
    wa, wb = w32a.astype("float16"), w32b.astype("float16")
    ga, gb = nd.array(onp.full(3, 0.5, "f")), nd.array(onp.full(2, 0.5, "f"))
    lrs, wds = nd.array([0.1, 0.2]), nd.array([0.0, 0.0])
    outs = nd.preloaded_multi_mp_sgd_update(
        wa, ga, w32a, wb, gb, w32b, lrs, wds, num_weights=2)
    assert len(outs) == 4
    onp.testing.assert_allclose(outs[2].asnumpy(), 0.95 * onp.ones(3),
                                rtol=1e-6)
    onp.testing.assert_allclose(outs[3].asnumpy(), 0.9 * onp.ones(2),
                                rtol=1e-6)


def test_r5_utility_ops():
    import numpy as onp

    from mxnet_tpu import nd

    # slice_assign / scalar
    a = nd.array(onp.zeros((3, 4), "f"))
    r = nd.slice_assign(a, nd.array(onp.ones((2, 2), "f")),
                        begin=(0, 1), end=(2, 3))
    assert r.asnumpy()[0, 1] == 1.0 and r.asnumpy()[2, 3] == 0.0
    r2 = nd.slice_assign_scalar(a, begin=(1,), end=(2,), scalar=7.0)
    assert r2.asnumpy()[1, 0] == 7.0
    # scatter_set_nd
    base = nd.array(onp.zeros((3, 3), "f"))
    idx = nd.array(onp.array([[0, 2], [1, 0]], "f"))
    vals = nd.array(onp.array([5.0, 6.0], "f"))
    out = nd.scatter_set_nd(base, vals, idx)
    assert out.asnumpy()[0, 1] == 5.0 and out.asnumpy()[2, 0] == 6.0
    # arange_like
    x = nd.array(onp.zeros((2, 3), "f"))
    al = nd.arange_like(x)
    assert al.shape == (2, 3) and float(al.asnumpy()[1, 2]) == 5.0
    assert nd.arange_like(x, axis=1).shape == (3,)
    # unravel_index alias
    flat = nd.array(onp.array([5.0]))
    ur = nd.unravel_index(flat, shape=(2, 3))
    assert ur.asnumpy().ravel().tolist() == [1.0, 2.0]
    # cast_storage exported on nd
    dense = nd.array(onp.array([[1.0, 0.0], [0.0, 2.0]], "f"))
    csr = nd.cast_storage(dense, "csr")
    assert csr.stype == "csr"
    back = nd.cast_storage(csr, "default")
    onp.testing.assert_allclose(back.asnumpy(), dense.asnumpy())
    # calibrate_entropy op form
    h = onp.histogram(onp.abs(onp.random.RandomState(0).randn(4000)),
                      bins=512)
    mn, mx_ = nd.calibrate_entropy(nd.array(h[0].astype("f")),
                                   nd.array(h[1].astype("f")))
    assert float(mx_.asnumpy()) > 0 and float(mn.asnumpy()) < 0
