"""The dependency engine is load-bearing for the IO paths: PrefetchingIter
fetches, ImageRecordIter decodes, and nd.save writes are engine ops
(reference: src/io/iter_prefetcher.h:142, iter_image_recordio_2.cc,
MXNDArraySave engine deps). These tests pin (a) correctness through both
engines and (b) that NaiveEngine observably serializes the path."""
import os
import threading

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, engine, io, recordio
from mxnet_tpu.base import MXNetError


@pytest.fixture
def naive_engine(monkeypatch):
    """Swap the process engine singleton for a NaiveEngine."""
    monkeypatch.setattr(engine, "_engine", engine.NaiveEngine())
    yield engine.get()
    # monkeypatch restores the previous singleton


@pytest.fixture
def threaded_engine(monkeypatch):
    try:
        eng = engine.Engine(nthreads=2)
    except RuntimeError:
        pytest.skip("native engine unavailable")
    monkeypatch.setattr(engine, "_engine", eng)
    yield eng
    eng.wait_all()


def _epoch(it):
    out = []
    for batch in it:
        out.append(onp.array(batch.data[0].asnumpy()))
    return out


def test_prefetching_iter_matches_underlying(threaded_engine):
    base = onp.arange(48, dtype="f").reshape(12, 4)
    want = [base[i:i + 4] for i in range(0, 12, 4)]
    it = io.PrefetchingIter(io.NDArrayIter(base, batch_size=4))
    got = _epoch(it)
    assert len(got) == len(want)
    for g, w in zip(got, want):
        onp.testing.assert_allclose(g, w)
    it.reset()  # second epoch identical
    got2 = _epoch(it)
    for g, w in zip(got2, want):
        onp.testing.assert_allclose(g, w)


def test_prefetching_iter_fetches_ride_worker_threads(threaded_engine):
    """Under the threaded engine, the fetch ops run on engine workers —
    the main thread never calls the sub-iterator."""
    seen = set()
    base = io.NDArrayIter(onp.zeros((8, 2), "f"), batch_size=2)
    orig = base.next

    def spy():
        seen.add(threading.get_ident())
        return orig()

    base.next = spy
    it = io.PrefetchingIter(base)
    _epoch(it)
    assert seen and threading.get_ident() not in seen


def test_prefetching_iter_naive_engine_serializes(naive_engine):
    """NaiveEngine runs each fetch inline at push, on the caller thread —
    the observable serialization of the IO path."""
    seen = []
    base = io.NDArrayIter(onp.arange(16, dtype="f").reshape(8, 2),
                          batch_size=2)
    orig = base.next

    def spy():
        seen.append(threading.get_ident())
        return orig()

    base.next = spy
    it = io.PrefetchingIter(base)
    batches = _epoch(it)
    assert len(batches) == 4
    assert set(seen) == {threading.get_ident()}


def _write_rec(path, n=6):
    from PIL import Image
    from io import BytesIO

    w = recordio.MXIndexedRecordIO(str(path) + ".idx", str(path), "w")
    rng = onp.random.RandomState(0)
    for i in range(n):
        img = Image.fromarray(rng.randint(0, 255, (40, 40, 3), "uint8"))
        buf = BytesIO()
        img.save(buf, format="JPEG")
        packed = recordio.pack(
            recordio.IRHeader(0, float(i % 3), i, 0), buf.getvalue())
        w.write_idx(i, packed)
    w.close()


@pytest.mark.parametrize("engine_fixture", ["naive", "threaded"])
def test_image_record_iter_through_both_engines(engine_fixture, tmp_path,
                                                monkeypatch, request):
    if engine_fixture == "naive":
        monkeypatch.setattr(engine, "_engine", engine.NaiveEngine())
    else:
        try:
            monkeypatch.setattr(engine, "_engine", engine.Engine(nthreads=2))
        except RuntimeError:
            pytest.skip("native engine unavailable")
    rec = tmp_path / "imgs.rec"
    _write_rec(rec)
    it = io.ImageRecordIter(str(rec), data_shape=(3, 32, 32), batch_size=2,
                            path_imgidx=str(rec) + ".idx")
    seen_labels = []
    nb = 0
    for batch in it:
        assert batch.data[0].shape == (2, 3, 32, 32)
        seen_labels.extend(batch.label[0].asnumpy().tolist())
        nb += 1
    assert nb == 3
    assert sorted(seen_labels) == [0.0, 0.0, 1.0, 1.0, 2.0, 2.0]
    it.reset()
    assert sum(1 for _ in it) == 3  # clean second epoch


def test_nd_save_is_an_engine_op(naive_engine, tmp_path):
    p = str(tmp_path / "x.params")
    d = {"w": nd.array(onp.arange(6, dtype="f"))}
    nd.save(p, d)
    loaded = nd.load(p)
    onp.testing.assert_allclose(loaded["w"].asnumpy(), onp.arange(6))
    # write failure surfaces at the save() call via engine poison
    with pytest.raises((OSError, MXNetError)):
        nd.save(str(tmp_path / "no" / "dir" / "x.params"), d)


def test_image_record_iter_recovers_after_corrupt_record(tmp_path,
                                                         monkeypatch):
    """A poisoned decode var must not wedge the iterator: reset() gets
    fresh vars and later epochs decode cleanly."""
    try:
        monkeypatch.setattr(engine, "_engine", engine.Engine(nthreads=2))
    except RuntimeError:
        pytest.skip("native engine unavailable")
    rec = tmp_path / "imgs.rec"
    _write_rec(rec, n=4)
    it = io.ImageRecordIter(str(rec), data_shape=(3, 32, 32), batch_size=2,
                            path_imgidx=str(rec) + ".idx")
    assert sum(1 for _ in it) == 2  # construction epoch consumed
    # now force the NEXT epoch's first decode op to blow up
    orig = it._decode
    calls = {"n": 0}

    def boom(blobs, H, W, crops):
        calls["n"] += 1
        if calls["n"] == 1:
            raise ValueError("corrupt record")
        return orig(blobs, H, W, crops)

    it._decode = boom
    it.reset()
    with pytest.raises(ValueError, match="corrupt record"):
        for _ in it:
            pass
    it.reset()  # recovery: fresh vars, clean epoch
    assert sum(1 for _ in it) == 2


def test_engine_keepalives_bounded_by_waits(threaded_engine):
    """wait_for_var prunes the waited ops' keepalives — a steady-state
    pipeline does not need wait_all barriers to stay bounded."""
    eng = threaded_engine
    start = eng.num_live_callbacks()
    for _ in range(50):
        v = eng.new_variable()
        eng.push(lambda: None, mutable_vars=(v,))
        eng.wait_for_var(v)
    assert eng.num_live_callbacks() <= start + 1


def test_dataloader_collection_is_engine_scheduled(naive_engine):
    """gluon DataLoader result collection runs through the engine: with
    NaiveEngine each batch is collected inline at push on the caller
    thread, and batches come out in order."""
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    X = onp.arange(24, dtype="f").reshape(12, 2)
    ds = ArrayDataset(X, onp.arange(12, dtype="f"))
    dl = DataLoader(ds, batch_size=4, num_workers=2, thread_pool=True)
    got = [b[0].asnumpy() for b in dl]
    assert len(got) == 3
    onp.testing.assert_allclose(onp.concatenate(got), X)
    # second epoch clean (fresh vars per __iter__)
    got2 = [b[0].asnumpy() for b in dl]
    assert len(got2) == 3
