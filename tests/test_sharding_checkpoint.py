"""Sharded checkpoints: per-shard files, resharding-on-load, faults.

Save runs on a 1x4 ``mp`` mesh; restores land on a 2x2 mesh (same
process) and on a genuinely single-device process (the conftest's
``forced_device_subprocess`` helper) — parameters AND optimizer
counters must come back bitwise either way.
"""
import json
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd, parallel, sharding
from mxnet_tpu.gluon import nn
from mxnet_tpu.resilience import CheckpointManager, faults
from mxnet_tpu.sharding import ShardingPlan

DIM, OUT, BATCH, STEPS = 16, 8, 4, 3


def _build(seed=51):
    mx.random.seed(seed)
    net = nn.HybridSequential(prefix="net_")
    net.add(nn.Dense(OUT, prefix="d0_"))
    net.initialize()
    net(nd.zeros((1, DIM)))
    trainer = mx.gluon.Trainer(net.collect_params(), "adam",
                               {"learning_rate": 0.02})
    return net, trainer


def _train(net, trainer, mesh, steps=STEPS, seed=57):
    rs = onp.random.RandomState(seed)
    for _ in range(steps):
        x = parallel.replicate(
            nd.array(rs.rand(BATCH, DIM).astype("f")), mesh)
        y = parallel.replicate(
            nd.array(rs.rand(BATCH, OUT).astype("f")), mesh)
        with autograd.record():
            loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        trainer.step(BATCH)


def _params(net):
    return {p.name: p.data().asnumpy()
            for p in net.collect_params().values()}


def _plan():
    return ShardingPlan({r"weight$": ("mp", None)})


def _save_sharded(tmp_path, seed=51):
    """Train + save under the 1x4 plan; returns (params, num_update)."""
    mesh = parallel.make_mesh({"mp": 4})
    with sharding.plan_scope(_plan(), mesh):
        net, trainer = _build(seed)
        sharding.place_params(net.collect_params())
        _train(net, trainer, mesh)
        mgr = CheckpointManager(str(tmp_path), trainer=trainer,
                                async_mode=False)
        mgr.save(STEPS)
    return _params(net), trainer._optimizer.num_update


def test_sharded_save_writes_shard_files_and_manifest(tmp_path):
    sharding.reset_sharding_counters()
    _save_sharded(tmp_path)
    c = sharding.sharding_counters()
    assert c["ckpt_sharded_saves"] == 1
    assert c["ckpt_shard_files"] == 4
    step_dir = os.path.join(str(tmp_path), f"ckpt-{STEPS:012d}")
    names = sorted(os.listdir(step_dir))
    shards = [n for n in names if n.startswith("shard-")]
    assert len(shards) == 4
    with open(os.path.join(step_dir, "manifest.json")) as f:
        manifest = json.load(f)
    meta = manifest["sharding"]
    assert meta["mesh"]["axes"] == ["mp"]
    assert meta["mesh"]["shape"] == [4]
    assert meta["shard_files"] == shards
    assert any(e["spec"] for e in meta["entries"])
    # every shard file is hash-pinned like the payload
    for n in shards:
        assert n in manifest["files"]


def test_restore_onto_different_mesh_shape(tmp_path):
    ref, ref_updates = _save_sharded(tmp_path)
    sharding.reset_sharding_counters()
    mesh22 = parallel.make_mesh({"dp": 2, "mp": 2})
    with sharding.plan_scope(_plan(), mesh22):
        net2, trainer2 = _build(seed=61)
        sharding.place_params(net2.collect_params())
        CheckpointManager(str(tmp_path), trainer=trainer2,
                          async_mode=False).restore()
        got = _params(net2)
        assert {k: v.tobytes() for k, v in got.items()} == \
            {k: v.tobytes() for k, v in ref.items()}
        assert trainer2._optimizer.num_update == ref_updates
        # restored buffers landed on the NEW mesh at the plan layout
        w = net2.collect_params()["d0_weight"]
        assert tuple(w.data().data.sharding.spec)[0] == "mp"
        # and the restored state is live: one more step on 2x2
        _train(net2, trainer2, mesh22, steps=1)
        assert not trainer2._fused_broken
    c = sharding.sharding_counters()
    assert c["ckpt_sharded_restores"] == 1
    assert c["ckpt_reshards"] == 1


def test_restore_into_single_device_process(tmp_path,
                                            forced_device_subprocess):
    """A plan-sharded checkpoint restores into a 1-device process with
    no plan at all — reassembly is mesh-agnostic."""
    ref, ref_updates = _save_sharded(tmp_path)
    snippet = f"""
import json
import numpy as onp
import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon import nn
from mxnet_tpu.resilience import CheckpointManager

mx.random.seed(99)
net = nn.HybridSequential(prefix="net_")
net.add(nn.Dense({OUT}, prefix="d0_"))
net.initialize()
net(nd.zeros((1, {DIM})))
trainer = mx.gluon.Trainer(net.collect_params(), "adam",
                           {{"learning_rate": 0.02}})
CheckpointManager({str(tmp_path)!r}, trainer=trainer,
                  async_mode=False).restore()
import jax
assert jax.device_count() == 1
print(json.dumps({{
    "num_update": trainer._optimizer.num_update,
    "params": {{p.name: p.data().asnumpy().tolist()
               for p in net.collect_params().values()}},
}}))
"""
    out = forced_device_subprocess(snippet, num_devices=1)
    assert out["num_update"] == ref_updates
    for name, vals in out["params"].items():
        got = onp.asarray(vals, dtype="f")
        assert got.tobytes() == ref[name].tobytes()


def test_unsharded_checkpoints_unchanged(tmp_path):
    """No plan -> no shard files, manifest has no sharding section."""
    net, trainer = _build(seed=63)
    rs = onp.random.RandomState(3)
    x = nd.array(rs.rand(BATCH, DIM).astype("f"))
    y = nd.array(rs.rand(BATCH, OUT).astype("f"))
    with autograd.record():
        loss = ((net(x) - y) ** 2).mean()
    loss.backward()
    trainer.step(BATCH)
    CheckpointManager(str(tmp_path), trainer=trainer,
                      async_mode=False).save(1)
    step_dir = os.path.join(str(tmp_path), "ckpt-" + "0" * 11 + "1")
    names = os.listdir(step_dir)
    assert not [n for n in names if n.startswith("shard-")]
    with open(os.path.join(step_dir, "manifest.json")) as f:
        manifest = json.load(f)
    assert "sharding" not in manifest


def test_shard_write_fault_keeps_checkpoint_invisible(tmp_path):
    """A crash mid shard-file write must leave no visible ckpt dir —
    the atomic tmpdir+rename protocol covers the new files too."""
    assert "checkpoint_shard_write" in faults.FAULT_POINTS
    mesh = parallel.make_mesh({"mp": 4})
    with sharding.plan_scope(_plan(), mesh):
        net, trainer = _build(seed=67)
        sharding.place_params(net.collect_params())
        _train(net, trainer, mesh, steps=1)
        mgr = CheckpointManager(str(tmp_path), trainer=trainer,
                                async_mode=False)
        with faults.inject("checkpoint_shard_write", at=2):
            with pytest.raises(Exception):
                mgr.save(1)
    assert mgr.latest_valid() is None
    leftovers = [n for n in os.listdir(str(tmp_path))
                 if not n.startswith(".")]
    assert leftovers == []
