"""nd.image namespace + contrib batch-3 ops (quadratic/allclose/STE/
box coding/rroi_align/reshape_like/softmax params).

Reference models: src/operator/image/image_random.cc tests
(tests/python/unittest/test_gluon_data_vision.py) and
tests/python/unittest/test_operator.py (quadratic_function,
allclose_function, support_vector_machine_*).
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.test_utils import assert_almost_equal, with_seed


def _np(x):
    return onp.asarray(x.asnumpy() if hasattr(x, "asnumpy") else x)


# ------------------------------------------------------------- nd.image ---

def test_to_tensor_and_normalize():
    rng = onp.random.RandomState(0)
    img = rng.randint(0, 255, (5, 7, 3)).astype("uint8")
    t = nd.image.to_tensor(nd.array(img))
    assert t.shape == (3, 5, 7) and t.dtype == onp.float32
    assert_almost_equal(_np(t), img.transpose(2, 0, 1) / 255.0,
                        rtol=1e-6, atol=1e-6)
    norm = nd.image.normalize(t, mean=(0.1, 0.2, 0.3), std=(0.5, 0.6, 0.7))
    want = (img.transpose(2, 0, 1) / 255.0
            - onp.array([0.1, 0.2, 0.3]).reshape(3, 1, 1)) \
        / onp.array([0.5, 0.6, 0.7]).reshape(3, 1, 1)
    assert_almost_equal(_np(norm), want, rtol=1e-5, atol=1e-6)
    # batched NHWC -> NCHW
    b = nd.image.to_tensor(nd.array(rng.randint(0, 255, (2, 5, 7, 3))
                                    .astype("uint8")))
    assert b.shape == (2, 3, 5, 7)


def test_flips_are_involutions():
    rng = onp.random.RandomState(1)
    x = nd.array(rng.rand(4, 6, 3).astype("f"))
    lr = nd.image.flip_left_right(x)
    assert_almost_equal(_np(lr), _np(x)[:, ::-1], rtol=0, atol=0)
    assert_almost_equal(_np(nd.image.flip_left_right(lr)), _np(x),
                        rtol=0, atol=0)
    tb = nd.image.flip_top_bottom(x)
    assert_almost_equal(_np(tb), _np(x)[::-1], rtol=0, atol=0)


@with_seed(7)
def test_random_image_ops_reproducible_and_bounded():
    rng = onp.random.RandomState(2)
    x = nd.array(rng.rand(8, 8, 3).astype("f"))
    mx.random.seed(11)
    a = _np(nd.image.random_brightness(x, 0.5, 1.5))
    mx.random.seed(11)
    b = _np(nd.image.random_brightness(x, 0.5, 1.5))
    assert_almost_equal(a, b, rtol=0, atol=0)
    # brightness is a pure scale: ratio constant across pixels
    ratio = a / _np(x)
    assert onp.allclose(ratio, ratio.flat[0], rtol=1e-5)
    assert 0.5 - 1e-5 <= ratio.flat[0] <= 1.5 + 1e-5
    # random flip either flips or not
    mx.random.seed(3)
    f = _np(nd.image.random_flip_left_right(x))
    assert (onp.allclose(f, _np(x)) or onp.allclose(f, _np(x)[:, ::-1]))


def test_hue_and_lighting_identity_at_zero():
    rng = onp.random.RandomState(3)
    x = nd.array(rng.rand(4, 4, 3).astype("f"))
    out = nd.image.random_hue(x, 0.0, 0.0)  # alpha=0 -> identity rotation
    # the truncated 3-decimal tyiq/ityiq pair (same constants as the
    # reference) is only approximately inverse — ~1.5% residual
    assert_almost_equal(_np(out), _np(x), rtol=0.03, atol=0.02)
    lit = nd.image.adjust_lighting(x, alpha=(0.0, 0.0, 0.0))
    assert_almost_equal(_np(lit), _np(x), rtol=0, atol=0)


def test_saturation_and_contrast_grayscale_blend():
    rng = onp.random.RandomState(4)
    x = _np(nd.array(rng.rand(5, 5, 3).astype("f")))
    # alpha=0 saturation -> per-pixel BT.601 luma in every channel
    out = _np(nd.image.random_saturation(nd.array(x), 0.0, 0.0))
    gray = (x * onp.array([0.299, 0.587, 0.114])).sum(-1, keepdims=True)
    assert_almost_equal(out, onp.broadcast_to(gray, x.shape),
                        rtol=1e-5, atol=1e-6)
    # alpha=0 contrast -> image-mean luma everywhere
    outc = _np(nd.image.random_contrast(nd.array(x), 0.0, 0.0))
    assert_almost_equal(outc, onp.full_like(x, gray.mean()),
                        rtol=1e-5, atol=1e-6)


def test_image_crop_and_resize():
    x = onp.arange(2 * 6 * 8 * 3, dtype="f").reshape(2, 6, 8, 3)
    c = nd.image.crop(nd.array(x), x=2, y=1, width=4, height=3)
    assert_almost_equal(_np(c), x[:, 1:4, 2:6], rtol=0, atol=0)
    r = nd.image.resize(nd.array(x), size=(4, 3))  # (w, h)
    assert r.shape == (2, 3, 4, 3)
    rk = nd.image.resize(nd.array(x), size=4, keep_ratio=True)
    assert rk.shape == (2, 4, 5, 3)  # shorter side 6 -> 4, 8 -> 5


# ------------------------------------------------------------- contrib3 ---

def test_quadratic_value_and_gradient():
    x = nd.array(onp.array([1.0, -2.0, 0.5], "f"))
    x.attach_grad()
    with autograd.record():
        y = nd.contrib.quadratic(x, a=2.0, b=-3.0, c=1.0)
        y.backward(nd.ones_like(y))
    assert_almost_equal(_np(y), 2 * _np(x) ** 2 - 3 * _np(x) + 1,
                        rtol=1e-6, atol=1e-7)
    assert_almost_equal(_np(x.grad), 4 * _np(x) - 3, rtol=1e-6, atol=1e-7)


def test_allclose_op():
    a = nd.array(onp.array([1.0, 2.0], "f"))
    b = nd.array(onp.array([1.0, 2.0 + 1e-7], "f"))
    assert _np(nd.contrib.allclose(a, b))[0] == 1.0
    c = nd.array(onp.array([1.0, 2.5], "f"))
    assert _np(nd.contrib.allclose(a, c))[0] == 0.0


def test_div_sqrt_dim():
    x = nd.array(onp.ones((2, 16), "f"))
    assert_almost_equal(_np(nd.contrib.div_sqrt_dim(x)),
                        onp.ones((2, 16)) / 4.0, rtol=1e-6, atol=1e-7)


def test_ste_ops_identity_gradient():
    v = nd.array(onp.array([0.4, -1.2, 2.6], "f"))
    v.attach_grad()
    with autograd.record():
        o = nd.contrib.round_ste(v)
        o.backward(nd.array(onp.array([3.0, 5.0, 7.0], "f")))
    assert_almost_equal(_np(o), onp.round(_np(v)), rtol=0, atol=0)
    assert_almost_equal(_np(v.grad), [3.0, 5.0, 7.0], rtol=0, atol=0)
    s = nd.array(onp.array([-0.3, 0.8], "f"))
    s.attach_grad()
    with autograd.record():
        o2 = nd.contrib.sign_ste(s)
        o2.backward(nd.ones_like(o2))
    assert_almost_equal(_np(o2), [-1.0, 1.0], rtol=0, atol=0)
    assert_almost_equal(_np(s.grad), [1.0, 1.0], rtol=0, atol=0)


def test_gradient_multiplier_reversal():
    v = nd.array(onp.array([2.0], "f"))
    v.attach_grad()
    with autograd.record():
        o = nd.contrib.gradientmultiplier(v, scalar=-1.0)  # GRL
        o.backward(nd.array(onp.array([4.0], "f")))
    assert_almost_equal(_np(o), [2.0], rtol=0, atol=0)
    assert_almost_equal(_np(v.grad), [-4.0], rtol=0, atol=0)


def test_reset_arrays():
    a = nd.array(onp.ones((2, 3), "f"))
    b = nd.array(onp.ones((4,), "f"))
    oa, ob = nd.contrib.reset_arrays(a, b, num_arrays=2)
    assert _np(oa).sum() == 0 and _np(ob).sum() == 0
    assert oa.shape == a.shape and ob.shape == b.shape
    # reference contract: call sites discard the return and expect the
    # INPUTS zeroed (contrib/reset_arrays.cc mutates in place)
    assert _np(a).sum() == 0 and _np(b).sum() == 0


def test_box_encode_decode_roundtrip():
    anchors = onp.array([[[0.0, 0.0, 2.0, 2.0],
                          [1.0, 1.0, 3.0, 4.0]]], "f")
    refs = onp.array([[[0.5, 0.5, 2.5, 2.5],
                       [1.0, 2.0, 3.0, 3.0]]], "f")
    samples = onp.array([[1.0, -1.0]], "f")  # second anchor negative
    matches = onp.array([[0, 1]], "f")
    t, m = nd.contrib.box_encode(
        nd.array(samples), nd.array(matches), nd.array(anchors),
        nd.array(refs), nd.array(onp.zeros(4, "f")),
        nd.array(onp.array([0.1, 0.1, 0.2, 0.2], "f")))
    # masked-out anchor encodes to zeros with zero mask
    assert_almost_equal(_np(m)[0, 1], onp.zeros(4), rtol=0, atol=0)
    assert_almost_equal(_np(t)[0, 1], onp.zeros(4), rtol=0, atol=0)
    # hand-computed target for the positive anchor
    want0 = onp.array([(1.5 - 1.0) / 2.0 / 0.1, (1.5 - 1.0) / 2.0 / 0.1,
                       onp.log(2.0 / 2.0) / 0.2, onp.log(2.0 / 2.0) / 0.2])
    assert_almost_equal(_np(t)[0, 0], want0, rtol=1e-5, atol=1e-5)
    # decode(encode(x)) == x for the positive anchor (stds folded in)
    dec = nd.contrib.box_decode(
        t * nd.array(onp.array([0.1, 0.1, 0.2, 0.2], "f")),
        nd.array(anchors), std0=1, std1=1, std2=1, std3=1)
    assert_almost_equal(_np(dec)[0, 0], refs[0, 0], rtol=1e-5, atol=1e-5)


def test_box_decode_center_format_and_clip():
    anchors = onp.array([[[1.0, 1.0, 2.0, 2.0]]], "f")  # cx,cy,w,h
    data = onp.array([[[0.0, 0.0, 10.0, 10.0]]], "f")  # huge dw/dh
    out = nd.contrib.box_decode(nd.array(data), nd.array(anchors),
                                format="center", clip=1.0)
    # dw clipped to 1.0 -> half-width = e * 1
    e = onp.exp(1.0)
    assert_almost_equal(_np(out)[0, 0],
                        [1 - e, 1 - e, 1 + e, 1 + e], rtol=1e-5, atol=1e-5)


def test_rroi_align_axis_aligned_matches_mean():
    data = onp.arange(64, dtype="f").reshape(1, 1, 8, 8)
    # 4x4 box centered at (4,4), no rotation, 2x2 bins
    rois = onp.array([[0, 4.0, 4.0, 4.0, 4.0, 0.0]], "f")
    out = nd.contrib.rroi_align(nd.array(data), nd.array(rois),
                                pooled_size=(2, 2), spatial_scale=1.0,
                                sampling_ratio=2)
    # each 2x2 output bin averages a 2x2-sample grid inside [2,6)x[2,6)
    got = _np(out)[0, 0]
    assert got.shape == (2, 2)
    assert got[0, 0] < got[0, 1] and got[0, 0] < got[1, 0]
    # 90-degree rotation of a symmetric box permutes bins
    rois90 = onp.array([[0, 4.0, 4.0, 4.0, 4.0, 90.0]], "f")
    out90 = _np(nd.contrib.rroi_align(nd.array(data), nd.array(rois90),
                                      pooled_size=(2, 2),
                                      spatial_scale=1.0,
                                      sampling_ratio=2))[0, 0]
    # rotating the sampling grid by 90deg maps (ph,pw) bins onto each
    # other: the multiset of bin values is preserved on this symmetric
    # center box
    assert_almost_equal(onp.sort(out90.ravel()), onp.sort(got.ravel()),
                        rtol=1e-4, atol=1e-4)


def test_rroi_align_out_of_bounds_zero():
    data = onp.ones((1, 1, 4, 4), "f")
    rois = onp.array([[0, 40.0, 40.0, 2.0, 2.0, 0.0]], "f")  # far outside
    out = nd.contrib.rroi_align(nd.array(data), nd.array(rois),
                                pooled_size=(1, 1))
    assert _np(out).sum() == 0.0


def test_hawkesll_reference_values():
    # oracle values from the reference's own unit test
    # (tests/python/unittest/test_contrib_hawkesll.py)
    N, T, K = 4, 4, 3
    mu = nd.array(onp.tile(onp.array([1.5, 2.0, 3.0], "f"), (N, 1)))
    alpha = nd.array(onp.array([0.2, 0.3, 0.4], "f"))
    beta = nd.array(onp.array([1.0, 2.0, 3.0], "f"))
    lags = nd.array(onp.array([[6, 7, 8, 9], [1, 2, 3, 4],
                               [3, 4, 5, 6], [8, 9, 10, 11]], "f"))
    marks = nd.array(onp.zeros((N, T), "i4"))
    ll, st = nd.contrib.hawkesll(
        mu, alpha, beta, nd.zeros((N, K)), lags, marks,
        nd.array(onp.array([1, 2, 3, 4], "f")),
        nd.array(onp.full(N, 100.0, "f")))
    assert_almost_equal(
        _np(ll), [-649.79453489, -649.57118596, -649.38025115,
                  -649.17811484], rtol=1e-5, atol=1e-2)
    assert st.shape == (N, K)


def test_hawkesll_multivariate_and_gradient():
    N, K = 2, 3
    mu = nd.array(onp.tile(onp.array([1.5, 2.0, 3.0], "f"), (N, 1)))
    alpha = nd.array(onp.array([0.2, 0.3, 0.4], "f"))
    beta = nd.array(onp.array([2.0, 2.0, 2.0], "f"))
    lags = nd.array(onp.array([[6, 7, 8, 9, 3, 2, 5, 1, 7],
                               [1, 2, 3, 4, 2, 1, 2, 1, 4]], "f"))
    marks = nd.array(onp.array([[0, 1, 2, 1, 0, 2, 1, 0, 2],
                                [1, 2, 0, 0, 0, 2, 2, 1, 0]], "i4"))
    vl = nd.array(onp.array([7, 9], "f"))
    mt = nd.array(onp.full(N, 100.0, "f"))
    ll, _ = nd.contrib.hawkesll(mu, alpha, beta, nd.zeros((N, K)), lags,
                                marks, vl, mt)
    assert_almost_equal(_np(ll), [-647.01240372, -646.28617272],
                        rtol=1e-5, atol=1e-2)
    # gradient wrt mu: finite-difference check on the summed ll
    mu.attach_grad()
    with autograd.record():
        ll, _ = nd.contrib.hawkesll(mu, alpha, beta, nd.zeros((N, K)),
                                    lags, marks, vl, mt)
        s = nd.sum(ll)
        s.backward()
    g = _np(mu.grad)
    eps = 1e-2
    mu_np = _np(mu)
    for (i, k) in [(0, 0), (1, 2)]:
        up, dn = mu_np.copy(), mu_np.copy()
        up[i, k] += eps
        dn[i, k] -= eps
        lu, _ = nd.contrib.hawkesll(nd.array(up), alpha, beta,
                                    nd.zeros((N, K)), lags, marks, vl, mt)
        ld, _ = nd.contrib.hawkesll(nd.array(dn), alpha, beta,
                                    nd.zeros((N, K)), lags, marks, vl, mt)
        fd = (_np(nd.sum(lu)) - _np(nd.sum(ld))) / (2 * eps)
        assert abs(g[i, k] - fd) < 0.05 * max(1.0, abs(fd)), (i, k, g[i, k], fd)


# ------------------------------------------------- reshape_like/softmax ---

def test_reshape_like_full_and_ranges():
    lhs = nd.array(onp.arange(24, dtype="f").reshape(2, 3, 4))
    rhs = nd.array(onp.ones((6, 4), "f"))
    assert nd.reshape_like(lhs, rhs).shape == (6, 4)
    # partial: reshape lhs axes [0,2) like rhs axes [0,1)
    rhs2 = nd.array(onp.ones((6, 2, 2), "f"))
    out = nd.reshape_like(lhs, rhs2, lhs_begin=0, lhs_end=2,
                          rhs_begin=0, rhs_end=1)
    assert out.shape == (6, 4)
    # negative indices
    out2 = nd.reshape_like(lhs, rhs2, lhs_begin=-3, lhs_end=-1,
                           rhs_begin=0, rhs_end=1)
    assert out2.shape == (6, 4)


def test_softmax_use_length_and_dtype():
    x = onp.ones((2, 4), "f")
    out = _np(nd.softmax(nd.array(x), length=nd.array(
        onp.array([1, 3], "f")), use_length=True))
    assert_almost_equal(out[0], [1, 0, 0, 0], rtol=1e-6, atol=1e-6)
    assert_almost_equal(out[1], [1 / 3, 1 / 3, 1 / 3, 0],
                        rtol=1e-5, atol=1e-6)
    h = nd.array(x).astype("float16")
    assert nd.softmax(h, dtype="float32").dtype == onp.float32
    assert nd.log_softmax(h, dtype="float32").dtype == onp.float32
    assert nd.softmax(h).dtype == onp.float16
    # length without use_length must be loud, not silently unmasked
    # (reference softmax.cc CHECKs use_length)
    with pytest.raises(ValueError):
        nd.softmax(nd.array(x), length=nd.array(onp.array([1, 3], "f")))
