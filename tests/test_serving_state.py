"""Stateful serving (round 16): SessionStateStore, continuous-batching
decode through DynamicBatcher, and the lifecycle around them.

Covers: step() bitwise-correctness across occupancy buckets vs the
hybridized reference block, mixed-length join/leave streams, session
affinity, TTL + LRU eviction under a tiny byte budget, the
``session_state_evict`` fault seam (blast radius: exactly one client),
close()-drain running in-flight streams to their step boundary and
checkpointing the states, canary promote migrating live sessions, the
decode counter family, and slot-headroom admission for new streams."""
import time

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, serving
from mxnet_tpu.gluon import HybridBlock, nn, rnn
from mxnet_tpu.resilience import faults
from mxnet_tpu.resilience.checkpoint import CheckpointManager
from mxnet_tpu.serving import SessionEvicted, SessionStateStore

nd = mx.nd

N_IN, HID, N_OUT = 4, 6, 3


class _DecodeStep(HybridBlock):
    """GRU cell + projection head, the flat ``(x, h) -> (out, h')``
    state-threading contract a stateful session compiles."""

    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.cell = rnn.GRUCell(HID, input_size=N_IN)
            self.head = nn.Dense(N_OUT)

    def hybrid_forward(self, F, x, h):
        out, states = self.cell(x, [h])
        return self.head(out), states[0]


def _gru(seed=16):
    mx.random.seed(seed)
    net = _DecodeStep()
    net.initialize()
    with autograd.pause(train_mode=False):
        net(nd.zeros((1, N_IN)), nd.zeros((1, HID)))
    return net


def _session(net, **kw):
    kw.setdefault("buckets", [1, 2, 4])
    return serving.InferenceSession(net, input_shapes=[(1, N_IN)],
                                    state_shapes=[(HID,)], **kw)


def _unroll(net, xs, h0=None):
    """Offline reference chain over the HYBRIDIZED block — the bitwise
    ground truth the served step must match exactly."""
    net.hybridize()
    h = nd.array(h0) if h0 is not None else nd.zeros((1, HID))
    out = None
    with autograd.pause(train_mode=False):
        for x in xs:
            out, h = net(nd.array(x), h)
    return out.asnumpy(), h.asnumpy()


def _x(seed, rows=1):
    return onp.random.RandomState(seed).rand(rows, N_IN).astype("float32")


@pytest.fixture(autouse=True)
def _fresh_counters():
    serving.reset_serving_counters()
    yield
    serving.reset_serving_counters()
    faults.disarm()


# ---------------------------------------------------------------------------
# InferenceSession.step()

def test_step_bitwise_vs_hybridized_block_across_buckets():
    net = _gru()
    sess = _session(net)
    net.hybridize()
    rng = onp.random.RandomState(0)
    try:
        for occ in (1, 2, 3, 4):  # 3 pads to bucket 4: must stay
            x = rng.rand(occ, N_IN).astype("float32")  # row-bitwise
            h = rng.rand(occ, HID).astype("float32")
            out, news = sess.step(nd.array(x), states=[nd.array(h)])
            with autograd.pause(train_mode=False):
                ref_o, ref_h = net(nd.array(x), nd.array(h))
            assert onp.array_equal(out.asnumpy(), ref_o.asnumpy()), \
                f"output not bitwise at occupancy {occ}"
            assert onp.array_equal(news[0].asnumpy(), ref_h.asnumpy()), \
                f"new state not bitwise at occupancy {occ}"
        assert serving.serving_stats()["decode_steps"] == 4
    finally:
        sess.close()


def test_step_and_predict_guardrails():
    net = _gru()
    sess = _session(net, buckets=[1, 2])
    try:
        with pytest.raises(mx.MXNetError, match="stateless"):
            sess.predict(_x(0))
        with pytest.raises(ValueError, match="occupancy"):
            sess.step(nd.zeros((3, N_IN)),
                      states=[nd.zeros((3, HID))])
    finally:
        sess.close()
    # a stateless session over the same block has no step()
    sess0 = serving.InferenceSession(
        net, input_shapes=[(1, N_IN), (1, HID)], buckets=[1])
    with pytest.raises(mx.MXNetError, match="stateful"):
        sess0.step(nd.zeros((1, N_IN)), states=[nd.zeros((1, HID))])


# ---------------------------------------------------------------------------
# SessionStateStore policies

def test_store_lru_eviction_under_byte_budget_and_affinity():
    # 4 fp32 scalars = 16 bytes/session; a 32-byte budget caps the
    # pool at 2 slots regardless of max_sessions
    store = SessionStateStore([(4,)], max_sessions=8, byte_budget=32,
                              ttl_s=0)
    assert store.num_slots == 2
    assert store.stats()["bytes_per_session"] == 16
    store.open("a")
    store.open("b")
    store.open("c")  # pool full: LRU ("a") reclaimed
    assert sorted(store.live_sessions()) == ["b", "c"]
    with pytest.raises(SessionEvicted, match="LRU"):
        store.acquire("a")
    with pytest.raises(mx.MXNetError, match="unknown"):
        store.acquire("ghost")
    assert serving.serving_stats()["evictions"] == 1
    # affinity: an in-flight slot is never double-acquired, and
    # eviction pressure reclaims around it
    rec = store.acquire("b")
    with pytest.raises(mx.MXNetError, match="affinity"):
        store.acquire("b")
    store.open("d")  # reclaims LRU "c", never in-flight "b"
    assert store.has("b") and store.has("d") and not store.has("c")
    store.release(rec)
    # an explicit re-open clears the tombstone: the client restarts
    store.open("c")
    rec2 = store.acquire("c")
    store.release(rec2)
    store.close()


def test_store_ttl_expiry_is_lazy_and_clean():
    store = SessionStateStore([(4,)], max_sessions=2, ttl_s=0.05)
    store.open("s", init_states=[onp.ones(4, "float32")])
    assert onp.array_equal(store.read("s")[0], onp.ones(4, "float32"))
    time.sleep(0.08)
    with pytest.raises(SessionEvicted, match="expired"):
        store.acquire("s")
    assert not store.has("s")
    store.close()


def test_store_state_shape_validation():
    store = SessionStateStore([(4,)], max_sessions=2)
    with pytest.raises(mx.MXNetError, match="row shape"):
        store.open("s", init_states=[onp.zeros((5,), "float32")])
    with pytest.raises(mx.MXNetError, match="state tensor"):
        store.open("s", init_states=[onp.zeros((4,), "float32")] * 2)
    store.close()
    with pytest.raises(mx.MXNetError, match="at least one"):
        SessionStateStore([])


# ---------------------------------------------------------------------------
# continuous batching through DynamicBatcher

def test_continuous_batching_mixed_length_streams_bitwise():
    net = _gru()
    sess = _session(net)
    bat = serving.DynamicBatcher(sess, max_batch_size=4,
                                 max_latency_ms=2.0, admission=False)
    rng = onp.random.RandomState(1)
    lengths = {"s0": 2, "s1": 5, "s2": 3}
    xs = {sid: [rng.rand(1, N_IN).astype("float32")
                for _ in range(n)] for sid, n in lengths.items()}
    try:
        # open-loop: each stream submits ALL its steps up front — the
        # per-session FIFO keeps order, streams join/leave the
        # executing batch at step boundaries
        futs = {sid: [bat.submit(x, session_id=sid, block=True)
                      for x in seq] for sid, seq in xs.items()}
        for sid, fs in futs.items():
            final = onp.asarray(fs[-1].result(timeout=60))
            ref_o, ref_h = _unroll(net, xs[sid])
            assert onp.array_equal(final, ref_o), \
                f"stream {sid} final output not bitwise vs unroll"
            # the server-side slot holds exactly the chain's state
            assert onp.array_equal(sess.state_store.read(sid)[0],
                                   ref_h[0])
        st = serving.serving_stats()
        assert st["decode_steps"] >= max(lengths.values())
        assert st["decode_steps"] <= sum(lengths.values())
        assert st["slot_occupancy"] == 3  # streams stay resident
    finally:
        bat.close()
        sess.close()


def test_fault_seam_evicts_exactly_one_client():
    """The ``session_state_evict`` chaos drill: one injected fire maps
    to SessionEvicted on every remaining step of exactly ONE stream —
    the other stream finishes bitwise-correct, and the evicted stream
    never silently restarts from zero state."""
    net = _gru()
    sess = _session(net)
    bat = serving.DynamicBatcher(sess, max_batch_size=2,
                                 max_latency_ms=1.0, admission=False)
    rng = onp.random.RandomState(2)
    xa = [rng.rand(1, N_IN).astype("float32") for _ in range(3)]
    xb = [rng.rand(1, N_IN).astype("float32") for _ in range(3)]
    try:
        # step 1 for both streams opens their slots cleanly
        bat.predict(xa[0], session_id="a")
        bat.predict(xb[0], session_id="b")
        with faults.inject("session_state_evict", at=1):
            fa = [bat.submit(x, session_id="a", block=True)
                  for x in xa[1:]]
            fb = [bat.submit(x, session_id="b", block=True)
                  for x in xb[1:]]
            # "a" re-joins first, so the armed acquire hits it: every
            # remaining step of that one stream fails retryably
            for f in fa:
                with pytest.raises(SessionEvicted, match="re-open"):
                    f.result(timeout=60)
            final_b = onp.asarray(fb[-1].result(timeout=60))
        assert faults.fire_counts()["session_state_evict"] == 1
        ref_b, _ = _unroll(net, xb)
        assert onp.array_equal(final_b, ref_b), \
            "the surviving stream must be untouched"
        assert not sess.state_store.has("a")
        assert serving.serving_stats()["evictions"] == 1
        # the client's explicit re-open clears the tombstone and the
        # stream restarts cleanly from step 0
        sess.state_store.open("a")
        out = onp.asarray(bat.predict(xa[0], session_id="a"))
        ref_a1, _ = _unroll(net, xa[:1])
        assert onp.array_equal(out, ref_a1)
    finally:
        bat.close()
        sess.close()


def test_close_drains_streams_to_boundary_and_checkpoints(tmp_path):
    """close() must EXECUTE every accepted step (streams advance to
    their boundary, nothing drops) and checkpoint the session states;
    a fresh process restores them and the streams resume bitwise."""
    net = _gru()
    sess = _session(net)
    mgr = CheckpointManager(str(tmp_path),
                            session_state=sess.state_store,
                            async_mode=False)
    bat = serving.DynamicBatcher(sess, max_batch_size=2,
                                 max_latency_ms=20.0, admission=False,
                                 state_checkpoint=mgr)
    rng = onp.random.RandomState(3)
    xs = {sid: [rng.rand(1, N_IN).astype("float32") for _ in range(3)]
          for sid in ("u", "v")}
    futs = [bat.submit(x, session_id=sid, block=True)
            for sid, seq in xs.items() for x in seq]
    bat.close()  # in-flight sequences run to their step boundary
    for f in futs:
        assert f.done(), "close() must drain accepted steps"
        f.result(timeout=0)
    refs = {sid: _unroll(net, seq) for sid, seq in xs.items()}
    for sid in xs:
        assert onp.array_equal(sess.state_store.read(sid)[0],
                               refs[sid][1][0])
    sess.close()

    # --- next process: restore and resume ---------------------------
    serving.reset_serving_counters()
    sess2 = _session(net)
    mgr2 = CheckpointManager(str(tmp_path),
                             session_state=sess2.state_store,
                             async_mode=False)
    mgr2.restore()
    assert sorted(sess2.state_store.live_sessions()) == ["u", "v"]
    assert serving.serving_stats()["resumed_sessions"] == 2
    bat2 = serving.DynamicBatcher(sess2, max_batch_size=2,
                                  max_latency_ms=2.0, admission=False)
    try:
        x_next = rng.rand(1, N_IN).astype("float32")
        out = onp.asarray(bat2.predict(x_next, session_id="u"))
        ref_o, _ = _unroll(net, xs["u"] + [x_next])
        assert onp.array_equal(out, ref_o), \
            "resumed stream must continue bitwise from the checkpoint"
    finally:
        bat2.close()
        sess2.close()


# ---------------------------------------------------------------------------
# canary promote migrates live sessions

def test_canary_promote_migrates_live_sessions():
    net = _gru()
    repo = serving.ModelRepository(max_latency_ms=2.0)
    rng = onp.random.RandomState(4)
    xs = {sid: [rng.rand(1, N_IN).astype("float32") for _ in range(2)]
          for sid in ("u1", "u2")}
    try:
        repo.deploy("m", _session(net))
        for sid, seq in xs.items():
            for x in seq:
                repo.submit("m", x, session_id=sid).result(timeout=60)
        v2 = _session(net)
        assert repo.deploy("m", v2) == 2
        assert repo.model_states()["m"]["state"] == "canary"
        serving.reset_serving_counters()
        repo.promote("m")
        st = repo.model_states()["m"]
        assert st["active_version"] == 2
        # both live streams crossed into the new version's store...
        assert sorted(v2.state_store.live_sessions()) == ["u1", "u2"]
        assert serving.serving_stats()["resumed_sessions"] == 2
        assert st["session_state"]["sessions"] == 2
        # ...and continue stepping bitwise — zero dropped sessions
        for sid, seq in xs.items():
            x_next = rng.rand(1, N_IN).astype("float32")
            out = repo.submit(
                "m", x_next, session_id=sid).result(timeout=60)
            ref_o, _ = _unroll(net, seq + [x_next])
            assert onp.array_equal(onp.asarray(out), ref_o), sid
    finally:
        repo.close()


# ---------------------------------------------------------------------------
# observability + admission

def test_decode_counters_in_stats_profiler_and_prometheus():
    from mxnet_tpu import profiler

    net = _gru()
    sess = _session(net)
    try:
        sess.step(nd.zeros((1, N_IN)), states=[nd.zeros((1, HID))])
        sess.state_store.open("live")
        st = serving.serving_stats()
        assert st["decode_steps"] == 1
        assert st["slot_occupancy"] == 1
        assert "evictions" in st and "resumed_sessions" in st
        assert profiler.serving_counters()["decode_steps"] == 1
        text = serving.prometheus_text()
        assert "mxnet_serving_decode_steps_total 1" in text
        assert "mxnet_serving_slot_occupancy 1" in text
        assert "mxnet_serving_evictions_total" in text
    finally:
        sess.close()


def test_admission_sheds_new_streams_when_pool_is_full(monkeypatch):
    """Slot headroom folds into admission ONLY for steps that must
    allocate a state slot: sheddable classes stop claiming slots
    before the pool evicts live streams; held slots and the protected
    class are untouched."""
    from mxnet_tpu.serving.admission import ShedLoad

    monkeypatch.setenv("MXNET_SERVING_SLO_MS", "60000")  # keep the
    # latency term idle so the slot term is what decides
    net = _gru()
    store = SessionStateStore([(HID,)], max_sessions=2, ttl_s=0)
    sess = serving.InferenceSession(
        net, input_shapes=[(1, N_IN)], state_shapes=[(HID,)],
        state_store=store, buckets=[1, 2])
    bat = serving.DynamicBatcher(sess, max_batch_size=2,
                                 max_latency_ms=1.0, admission=True)
    x = _x(7)
    try:
        bat.predict(x, session_id="a")
        bat.predict(x, session_id="b")  # pool now full
        assert bat.admission.snapshot()["slot_headroom"] == 0.0
        with pytest.raises(ShedLoad):
            bat.submit(x, session_id="c", slo_class="best_effort")
        assert serving.serving_stats()["shed"] == 1
        # live streams keep stepping: their slot is already held
        bat.predict(x, session_id="a")
        # the protected class still allocates (evicting LRU "b")
        bat.predict(x, session_id="crit", slo_class="critical")
        assert store.has("crit")
    finally:
        bat.close()
        sess.close()
