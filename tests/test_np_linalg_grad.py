"""Gradient coverage for the mx.np.linalg delegates.

Round-2 VERDICT: "their gradient behavior is untested" — these pin that
the np.linalg surface participates in the autograd tape with correct
cotangents (numeric-difference oracles).
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu import np as mnp


def _numeric_grad(f, x, eps=1e-4):
    g = onp.zeros_like(x)
    it = onp.nditer(x, flags=["multi_index"])
    while not it.finished:
        i = it.multi_index
        xp = x.copy()
        xp[i] += eps
        xm = x.copy()
        xm[i] -= eps
        g[i] = (f(xp) - f(xm)) / (2 * eps)
        it.iternext()
    return g


def _spd(n, seed):
    rng = onp.random.RandomState(seed)
    a = rng.rand(n, n).astype("f")
    return (a @ a.T + n * onp.eye(n, dtype="f"))


def _check(fn_np, fn_mx, x, rtol=1e-2, atol=1e-3):
    xa = mnp.array(x)
    xa.attach_grad()
    with autograd.record():
        out = fn_mx(xa)
        loss = out.sum() if hasattr(out, "sum") else out
    loss.backward()
    num = _numeric_grad(lambda v: float(onp.sum(fn_np(v))),
                        x.astype("float64")).astype("f")
    onp.testing.assert_allclose(xa.grad.asnumpy(), num, rtol=rtol,
                                atol=atol)


def test_det_grad():
    _check(onp.linalg.det, mnp.linalg.det, _spd(3, 0))


def test_slogdet_grad():
    def np_logdet(v):
        return onp.linalg.slogdet(v)[1]

    def mx_logdet(a):
        sign, logdet = mnp.linalg.slogdet(a)
        return logdet

    _check(np_logdet, mx_logdet, _spd(3, 1))


def test_inv_grad():
    _check(lambda v: onp.linalg.inv(v), lambda a: mnp.linalg.inv(a),
           _spd(3, 2))


def test_cholesky_grad():
    # symmetrize in BOTH paths: numpy/jax agree on the value but use
    # different conventions for the cotangent of the (redundant) upper
    # triangle; routing through (v+v.T)/2 pins a single convention
    _check(lambda v: onp.linalg.cholesky((v + v.T) / 2),
           lambda a: mnp.linalg.cholesky((a + a.transpose()) / 2),
           _spd(3, 3))


def test_solve_grad_wrt_matrix():
    b = onp.array([1.0, 2.0, 3.0], "f")

    _check(lambda v: onp.linalg.solve(v, b.astype(v.dtype)),
           lambda a: mnp.linalg.solve(a, mnp.array(b)), _spd(3, 4))


def test_norm_grad():
    rng = onp.random.RandomState(5)
    x = rng.rand(4, 3).astype("f") + 0.1
    _check(lambda v: onp.linalg.norm(v), lambda a: mnp.linalg.norm(a), x)


def test_eigh_eigenvalue_grad():
    def np_f(v):
        return onp.linalg.eigvalsh((v + v.T) / 2)

    def mx_f(a):
        sym_a = (a + a.transpose()) / 2
        w = mnp.linalg.eigvalsh(sym_a)
        return w

    rng = onp.random.RandomState(6)
    # distinct eigenvalues: symmetric diag-dominant random
    x = rng.rand(3, 3).astype("f") + onp.diag([3.0, 6.0, 9.0]).astype("f")
    _check(np_f, mx_f, x, rtol=2e-2, atol=2e-3)


def test_svd_singular_values_grad():
    def np_f(v):
        return onp.linalg.svd(v, compute_uv=False)

    def mx_f(a):
        u, s, vt = mnp.linalg.svd(a)
        return s

    rng = onp.random.RandomState(7)
    x = rng.rand(4, 3).astype("f") + onp.eye(4, 3, dtype="f") * [3, 2, 1]
    _check(np_f, mx_f, x, rtol=2e-2, atol=2e-3)


def test_pinv_value_and_grad_shape():
    rng = onp.random.RandomState(8)
    x = rng.rand(4, 3).astype("f")
    a = mnp.array(x)
    a.attach_grad()
    with autograd.record():
        p = mnp.linalg.pinv(a)
        loss = p.sum()
    loss.backward()
    onp.testing.assert_allclose(p.asnumpy(), onp.linalg.pinv(x),
                                rtol=1e-4, atol=1e-5)
    assert a.grad.shape == x.shape
    assert float(abs(a.grad.asnumpy()).sum()) > 0


def test_qr_backward_pytree():
    """Regression: QRResult namedtuple output must not break backward
    (normalized centrally in registry.apply_pure)."""
    rng = onp.random.RandomState(9)
    a = mnp.array(rng.rand(4, 3).astype("f") + onp.eye(4, 3, dtype="f"))
    a.attach_grad()
    with autograd.record():
        q, r = mnp.linalg.qr(a)
        loss = r.sum()
    loss.backward()
    assert a.grad.shape == (4, 3)
    assert float(abs(a.grad.asnumpy()).sum()) > 0
