"""FleetRouter (round 23): consistent-hash routing, replica
lifecycle, live-session drain, fleet canary, and the router HTTP
surface.

Most tests run the router against FAKE replica HTTP servers (stdlib,
in-process) so routing/affinity/drain/ejection logic is exercised in
milliseconds; one tier-1 smoke spawns two REAL replica subprocesses
(bundle-warm via the shared disk cache) and routes through the full
stack. The N-replica drain/join/canary e2e lives in the slow-marked
fleet_bench smoke (tests/test_bench_smoke.py)."""
import json
import pickle
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from mxnet_tpu import serving
from mxnet_tpu.serving import FleetRouter, fleet_counters
from mxnet_tpu.serving.fleet import _HashRing, _hash64
from mxnet_tpu.telemetry import metrics as tmetrics

_ROUTERS = []


@pytest.fixture(autouse=True)
def _fresh():
    serving.reset_fleet_counters()
    yield
    while _ROUTERS:  # close admission probes even on assert failure
        _ROUTERS.pop().stop()
    serving.reset_fleet_counters()


def _router(**kw):
    fr = FleetRouter(port=0, **kw)
    _ROUTERS.append(fr)
    return fr


# ---------------------------------------------------------------------------
# fake replica: the replica HTTP contract, no jax involved

class _FakeReplica:
    """Answers /healthz, /predict, and the /admin state endpoints the
    way a ModelServer replica does; records restores."""

    def __init__(self, name, outputs=None, depth=0, capacity=8,
                 export=None):
        self.name = name
        self.outputs = outputs if outputs is not None else [[1.0, 2.0]]
        self.depth = depth
        self.capacity = capacity
        self.export = export  # None -> 409 (stateless replica)
        self.restored = []
        self.predicts = 0
        fake = self

        class _H(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _send(self, code, body,
                      ctype="application/json"):
                if isinstance(body, (dict, list)):
                    body = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._send(200, {
                        "warm": True, "queue_depth": fake.depth,
                        "queue_capacity": fake.capacity})
                elif self.path == "/admin/export_state":
                    if fake.export is None:
                        self._send(409, {"error": "stateless"})
                    else:
                        self._send(200, pickle.dumps(fake.export),
                                   ctype="application/octet-stream")
                else:
                    self._send(404, {"error": "no route"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                if self.path == "/admin/restore_state":
                    payload = pickle.loads(body)
                    fake.restored.append(payload)
                    self._send(200, {"restored":
                                     len(payload["sessions"])})
                else:
                    fake.predicts += 1
                    self._send(200, {
                        "outputs": fake.outputs, "replica": fake.name,
                        "sid": self.headers.get("X-Session-Id")})

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), _H)
        self._srv.daemon_threads = True
        self.port = self._srv.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True)
        self._thread.start()

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()
        self._thread.join()


@pytest.fixture()
def fakes():
    reps = []
    yield lambda *a, **kw: reps.append(_FakeReplica(*a, **kw)) or \
        reps[-1]
    for r in reps:
        r.stop()


def _routed(fr, sid=None, slo="standard", path="/predict"):
    status, _, _, body = fr.forward_request(
        path, b'{"data": [[1.0]]}', slo, sid,
        {"Content-Type": "application/json",
         "X-Session-Id": sid or ""})
    return status, json.loads(body)


# ---------------------------------------------------------------------------
# consistent-hash ring

def test_hash_ring_distribution_and_minimal_remap():
    ring = _HashRing(vnodes=64)
    assert ring.lookup("anything") is None  # empty ring
    for n in ("a", "b", "c"):
        ring.add(n)
    assert len(ring) == 3 and "b" in ring
    keys = [f"sess-{i}" for i in range(300)]
    owners = {k: ring.lookup(k) for k in keys}
    assert set(owners.values()) == {"a", "b", "c"}, \
        "64 vnodes must spread keys over every replica"
    ring.remove("b")
    for k in keys:
        if owners[k] == "b":
            assert ring.lookup(k) in ("a", "c")
        else:  # the consistent-hash property: survivors keep keys
            assert ring.lookup(k) == owners[k]
    ring.add("b")  # re-join lands the same arcs: pins come back
    assert all(ring.lookup(k) == owners[k] for k in keys)


def test_hash_ring_stable_across_instances():
    """sha-based points: a restarted router re-derives the SAME
    placement (hash() would re-shard every process)."""
    r1, r2 = _HashRing(8), _HashRing(8)
    for n in ("x", "y"):
        r1.add(n)
        r2.add(n)
    assert _hash64("x#0") == _hash64("x#0")
    assert all(r1.lookup(f"k{i}") == r2.lookup(f"k{i}")
               for i in range(64))


# ---------------------------------------------------------------------------
# telemetry: labeled exposition lines

def test_labeled_lines_escaping_and_types():
    rows = [({"replica": 'a"b\\c\nd'}, 1),
            ({"replica": "ok"}, True),       # bool -> int
            ({"replica": "skip"}, "nan?")]   # non-numeric dropped
    lines = tmetrics.labeled_lines("fleet_replica_up", rows, "help")
    text = "\n".join(lines)
    assert '# TYPE mxnet_fleet_replica_up gauge' in text
    assert 'mxnet_fleet_replica_up{replica="a\\"b\\\\c\\nd"} 1' in text
    assert 'mxnet_fleet_replica_up{replica="ok"} 1' in text
    assert "skip" not in text
    assert tmetrics.labeled_lines("empty", []) == []


# ---------------------------------------------------------------------------
# membership + gossip

def test_membership_gossip_and_healthz(fakes):
    a = fakes("a", depth=2, capacity=8)
    b = fakes("b", depth=3, capacity=8)
    fr = _router()
    fr.add_replica("a", a.url)
    fr.add_replica("b", b.url)
    with pytest.raises(ValueError, match="already in fleet"):
        fr.add_replica("a", a.url)
    fr.probe_once()
    assert fr._gossip_depth() == 5
    assert fr._gossip_capacity() == 16
    doc = fr.healthz()
    assert doc["status"] == "ok" and doc["warm"]
    assert doc["queue_depth"] == 5
    assert doc["queue_capacity"] == 16
    assert doc["replicas"]["b"]["state"] == "serving"
    assert doc["replicas"]["b"]["breaker"] == "closed"
    assert fleet_counters()["joins"] == 2
    assert fr.remove("b").name == "b"
    assert "b" not in fr._ring and fr.remove("b") is None


def test_add_replica_unreachable_never_joins():
    fr = _router()
    with pytest.raises(TimeoutError, match="did not warm"):
        fr.add_replica("ghost", "http://127.0.0.1:9",
                       timeout_s=0.3)
    assert fr.replicas() == {}  # a failed join leaves no record


# ---------------------------------------------------------------------------
# stateful affinity + drain migration

def test_stateful_affinity_pins_and_drain_migrates(fakes):
    payload = {"format": 1, "state_shapes": [[6]],
               "state_dtypes": ["float32"], "sessions": {}}
    a = fakes("a", export=payload)
    b = fakes("b", export={**payload, "sessions": {}})
    fr = _router()
    fr.add_replica("a", a.url)
    fr.add_replica("b", b.url)
    sids = [f"s{i}" for i in range(8)]
    homes = {}
    for sid in sids:
        status, doc = _routed(fr, sid=sid)
        assert status == 200
        homes[sid] = doc["replica"]
        for _ in range(3):  # affinity: every step lands on the pin
            assert _routed(fr, sid=sid)[1]["replica"] == homes[sid]
    assert set(homes.values()) == {"a", "b"}
    # drain a: its pinned sessions migrate to b, dense-row form
    a_sids = [s for s in sids if homes[s] == "a"]
    a.export = {**payload,
                "sessions": {s: {"steps": 4, "states": [[0.0] * 6]}
                             for s in a_sids}}
    moved = fr.drain("a")
    assert moved == len(a_sids)
    assert [sorted(p["sessions"]) for p in b.restored] == \
        [sorted(a_sids)]
    assert sorted(fr.replicas()) == ["b"]
    for sid in sids:  # every stream (moved or not) now steps on b
        assert _routed(fr, sid=sid)[1]["replica"] == "b"
    c = fleet_counters()
    assert c["drains"] == 1
    assert c["drained_sessions"] == len(a_sids)
    assert c["affinity_moves"] >= len(a_sids)
    assert c["transport_errors"] == 0


def test_drain_without_peer_restores_the_replica(fakes):
    payload = {"format": 1, "state_shapes": [[2]],
               "state_dtypes": ["float32"],
               "sessions": {"u": {"steps": 1, "states": [[0.0, 0.0]]}}}
    a = fakes("a", export=payload)
    fr = _router()
    fr.add_replica("a", a.url)
    assert _routed(fr, sid="u")[0] == 200
    with pytest.raises(RuntimeError, match="no serving peer"):
        fr.drain("a")
    # failed drain is a no-op: state never left the replica
    assert fr.replicas()["a"]["state"] == "serving"
    assert "a" in fr._ring
    assert _routed(fr, sid="u")[1]["replica"] == "a"
    with pytest.raises(KeyError):
        fr.drain("nope")


def test_stateful_requests_park_through_a_drain(fakes):
    a = fakes("a")
    b = fakes("b")
    fr = _router(drain_timeout_ms=5000.0)
    fr.add_replica("a", a.url)
    fr.add_replica("b", b.url)
    sid = next(s for s in (f"s{i}" for i in range(64))
               if _routed(fr, sid=s)[1]["replica"] == "a")
    rep = fr._replicas["a"]
    with fr._lock:  # freeze mid-drain without timing games
        rep.state = "draining"
        ev = fr._drain_events["a"] = threading.Event()
    out = {}

    def _step():
        out["reply"] = _routed(fr, sid=sid)

    t = threading.Thread(target=_step)
    t.start()
    deadline = time.monotonic() + 5.0
    while fleet_counters()["blocked_on_drain"] < 1:
        assert time.monotonic() < deadline, "request never parked"
        time.sleep(0.01)
    assert "reply" not in out  # parked, not failed
    with fr._lock:  # migration lands the pin on b, drain completes
        fr._sessions[sid] = "b"
        rep.state = "left"
        fr._replicas.pop("a")
        fr._drain_events.pop("a")
    ev.set()
    t.join(timeout=5)
    assert out["reply"][0] == 200
    assert out["reply"][1]["replica"] == "b"
    assert fleet_counters()["drain_timeouts"] == 0


def test_parked_request_times_out_503(fakes):
    a = fakes("a")
    fr = _router(drain_timeout_ms=100.0)
    fr.add_replica("a", a.url)
    sid = "stuck"
    assert _routed(fr, sid=sid)[0] == 200
    with fr._lock:
        fr._replicas["a"].state = "draining"
        fr._drain_events["a"] = threading.Event()  # never set
    status, doc = _routed(fr, sid=sid)
    assert status == 503 and "draining" in doc["error"]
    assert fleet_counters()["drain_timeouts"] == 1


# ---------------------------------------------------------------------------
# stateless routing: least-loaded, retry, ejection, recovery

def test_stateless_least_loaded_and_transport_retry(fakes):
    a = fakes("a", depth=5)
    b = fakes("b", depth=0)
    fr = _router(retries=2)
    fr.add_replica("a", a.url)
    fr.add_replica("b", b.url)
    fr.probe_once()
    assert _routed(fr)[1]["replica"] == "b"  # least gossiped depth
    b.stop()  # transport failure -> bounded cross-replica retry
    status, doc = _routed(fr)
    assert status == 200 and doc["replica"] == "a"
    c = fleet_counters()
    assert c["retries"] == 1 and c["transport_errors"] == 1
    a.stop()  # both down: excluded-then-empty pool answers 503
    status, doc = _routed(fr)
    assert status == 503
    assert "unreachable" in doc["error"] or "no serving" in doc["error"]


def test_probe_ejection_and_recovery(fakes):
    a = fakes("a")
    b = fakes("b")
    fr = _router()
    fr.add_replica("a", a.url)
    fr.add_replica("b", b.url)
    a.stop()
    for _ in range(5):  # breaker threshold (default 5)
        fr.probe_once()
    snap = fr.replicas()["a"]
    assert snap["state"] == "ejected"
    assert "a" not in fr._ring and "b" in fr._ring
    assert fleet_counters()["ejections"] == 1
    assert fr.healthz()["status"] == "degraded"
    for _ in range(4):  # ejected replica takes no traffic
        assert _routed(fr)[1]["replica"] == "b"
    # the process comes back: the next successful probe rejoins it
    revived = _FakeReplica("a")
    try:
        with fr._lock:  # re-point the record (same name, new port)
            fr._replicas["a"].url = revived.url
        fr.probe_once()
        assert fr.replicas()["a"]["state"] == "serving"
        assert "a" in fr._ring
        assert fleet_counters()["recoveries"] == 1
    finally:
        revived.stop()


def test_fleet_admission_sheds_standard_not_critical(fakes):
    a = fakes("a", depth=8, capacity=8)  # gossiped queue full
    fr = _router()
    fr.add_replica("a", a.url)
    fr.probe_once()
    from mxnet_tpu.serving import ShedLoad

    with pytest.raises(ShedLoad):
        fr.forward_request("/predict", b"{}", "standard", None, {})
    assert _routed(fr, slo="critical")[0] == 200  # never shed


# ---------------------------------------------------------------------------
# fleet canary: shadow gate, rollback, client never sees it

def test_canary_shadow_mismatch_rolls_back(fakes):
    inc = fakes("inc", outputs=[[1.0, 1.0]])
    bad = fakes("bad", outputs=[[100.0, -3.0]])
    fr = _router(canary_fraction=1.0, canary_threshold=1,
                 shadow_tol=0.1)
    fr.add_replica("inc", inc.url)
    fr.add_replica("bad", bad.url, canary=True)
    for _ in range(6):
        status, doc = _routed(fr)
        assert status == 200
        assert doc["replica"] == "inc", \
            "client answers must come from the incumbent"
    assert not fr.canary_active
    c = fleet_counters()
    assert c["shadow_checks"] >= 1
    assert c["shadow_mismatches"] >= 1
    assert c["canary_rollbacks"] == 1
    assert c["canary_requests"] == 1, \
        "rollback must stop shadow traffic immediately"


def test_canary_agreement_serves_and_critical_skips_it(fakes):
    inc = fakes("inc", outputs=[[1.0, 2.0]])
    good = fakes("good", outputs=[[1.0, 2.0]])
    fr = _router(canary_fraction=1.0, canary_threshold=1,
                 shadow_tol=0.1)
    fr.add_replica("inc", inc.url)
    fr.add_replica("good", good.url, canary=True)
    assert _routed(fr)[1]["replica"] == "good", \
        "an agreeing canary's reply is the promoted answer"
    assert fr.canary_active
    before = fleet_counters()["canary_requests"]
    assert _routed(fr, slo="critical")[1]["replica"] == "inc"
    assert fleet_counters()["canary_requests"] == before, \
        "critical traffic never routes through the canary pair"


# ---------------------------------------------------------------------------
# the router's own HTTP surface + prometheus exposition

def test_router_http_surface_and_metrics(fakes):
    a = fakes("a")
    fr = _router().start()
    fr.add_replica("a", a.url)
    base = fr.address
    with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
        doc = json.loads(r.read())
    assert r.status == 200 and doc["role"] == "router"
    assert doc["replicas"]["a"]["state"] == "serving"
    req = urllib.request.Request(
        base + "/predict", data=b'{"data": [[1.0]]}',
        headers={"Content-Type": "application/json",
                 "X-Request-Id": "trace-42"})
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.headers["X-Request-Id"] == "trace-42", \
            "trace ids must propagate router -> client"
        assert json.loads(r.read())["replica"] == "a"
    assert a.predicts == 1
    with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
        text = r.read().decode()
    assert "mxnet_fleet_requests 1" in text
    assert 'mxnet_fleet_replica_up{replica="a"} 1' in text
    assert 'mxnet_fleet_replica_state{canary="false",replica="a",' \
        'state="serving"} 1' in text
    assert text.count("# TYPE mxnet_fleet gauge") == 1, \
        "the exposition block must replace the flat gauge pass"
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(base + "/nope", timeout=10)
    assert ei.value.code == 404
    bad = urllib.request.Request(
        base + "/predict", data=b'{"slo_class": "warp-speed"}',
        headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(bad, timeout=10)
    assert ei.value.code == 400


# ---------------------------------------------------------------------------
# tier-1 smoke: two REAL replica subprocesses behind the router

def test_two_real_replicas_smoke(tmp_path):
    from mxnet_tpu.benchmark.fleet_bench import DENSE
    from mxnet_tpu.serving import spawn_replica

    env = {"MXNET_FLEET_BENCH_HIDDEN": "16",
           "MXNET_FLEET_BENCH_ROWS": "4",
           "MXNET_COMPILE_CACHE_DIR": str(tmp_path / "cache"),
           "MXNET_COMPILE_CACHE": "1"}
    r0 = spawn_replica(DENSE, env=env)
    r1 = spawn_replica(DENSE, env=env)
    fr = _router()
    fr.start()
    fr.add_replica("r0", r0.url, process=r0)
    fr.add_replica("r1", r1.url, process=r1)
    try:
        # the second replica warmed from the first's disk cache
        assert r1.ready["warm"]["compiles"] == 0
        assert r1.ready["warm"]["disk_hits"] > 0
        body = json.dumps(
            {"data": [[0.1] * 16 for _ in range(4)]}).encode()
        for _ in range(4):
            req = urllib.request.Request(
                fr.address + "/predict", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as resp:
                doc = json.loads(resp.read())
            assert len(doc["outputs"][0]) == 4  # one (4, 8) tensor
        assert fleet_counters()["routed"] == 4
        assert fr.healthz()["status"] == "ok"
        # graceful leave: stateless replicas drain with zero sessions
        assert fr.drain("r0") == 0
        assert sorted(fr.replicas()) == ["r1"]
        req = urllib.request.Request(
            fr.address + "/predict", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            assert resp.status == 200
    finally:
        fr.stop(stop_replicas=True)
        r0.stop()
