"""AMP tests (reference: tests/python/gpu/test_contrib_amp.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.contrib import amp


@pytest.fixture(autouse=True)
def _amp_off():
    yield
    amp.disable()


def test_amp_cast_policy():
    amp.init("bfloat16")
    x = nd.array(onp.random.rand(4, 8).astype("f"))
    w = nd.array(onp.random.rand(16, 8).astype("f"))
    out = nd.fully_connected(x, w, num_hidden=16, no_bias=True)
    assert str(out.dtype) == "bfloat16"  # target-dtype op
    s = nd.softmax(nd.array(onp.random.rand(2, 3).astype("f"))
                   .astype("bfloat16"))
    assert str(s.dtype) == "float32"  # fp32 op upcasts
    m = nd.elemwise_add(nd.array([1.]).astype("bfloat16"), nd.array([2.]))
    assert str(m.dtype) == "float32"  # widest-type op
    amp.disable()
    out = nd.fully_connected(x, w, num_hidden=16, no_bias=True)
    assert str(out.dtype) == "float32"


def test_amp_grads_flow_through_casts():
    amp.init("bfloat16")
    x = nd.array(onp.random.rand(4, 8).astype("f"))
    w = nd.array(onp.random.rand(16, 8).astype("f"))
    w.attach_grad()
    with autograd.record():
        out = nd.fully_connected(x, w, num_hidden=16, no_bias=True)
        loss = nd.sum(out)
    loss.backward()
    g = w.grad
    assert str(g.dtype) == "float32"  # grads land in the param dtype
    assert float(nd.sum(nd.abs(g)).asnumpy()) > 0


def test_amp_training_with_loss_scaler():
    amp.init("bfloat16")
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(2))
    net.initialize(mx.init.Xavier())
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    amp.init_trainer(tr)
    lf = gluon.loss.SoftmaxCrossEntropyLoss()
    rs = onp.random.RandomState(0)
    X = rs.randn(32, 8).astype("f")
    y = (X.sum(1) > 0).astype("f")
    first = None
    for _ in range(20):
        with autograd.record():
            l = lf(net(nd.array(X)), nd.array(y)).mean()
            with amp.scale_loss(l, tr) as sl:
                sl.backward()
        tr.step(1)
        first = first if first is not None else float(l.asscalar())
    assert float(l.asscalar()) < first * 0.8


def test_amp_overflow_skips_step():
    amp.init("bfloat16")
    net = nn.Dense(2)
    net.initialize()
    net(nd.ones((1, 3)))
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    amp.init_trainer(tr)
    p = list(net.collect_params().values())[0]
    with autograd.record():
        l = net(nd.ones((1, 3))).sum()
        l.backward()
    p.grad()[:] = float("inf")
    w0 = p.data().asnumpy().copy()
    s0 = tr._amp_loss_scaler.loss_scale
    tr.step(1)
    assert onp.allclose(p.data().asnumpy(), w0)
    assert tr._amp_loss_scaler.loss_scale == s0 / 2


def test_convert_model_keeps_norms_fp32():
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.BatchNorm(), nn.Dense(2))
    net.initialize()
    net(nd.ones((2, 3)))
    amp.convert_model(net, "bfloat16")
    params = net.collect_params()
    dtypes = {name: str(p.dtype) for name, p in params.items()}
    assert any(v == "bfloat16" for k, v in dtypes.items() if "dense" in k)
    assert all(v == "float32" for k, v in dtypes.items()
               if "batchnorm" in k or "gamma" in k or "beta" in k)


def test_amp_conditional_fp32_ops():
    """CONDITIONAL_FP32_OPS (reference symbol.py:504): softrelu/elu/selu
    run fp32 under AMP (their exp/expm1 overflow in 16-bit); other attr
    values keep the target dtype."""
    import numpy as onp

    from mxnet_tpu import nd
    from mxnet_tpu.contrib import amp

    amp.init("bfloat16")
    try:
        x = nd.array(onp.random.rand(4, 8).astype("f")).astype("bfloat16")
        assert nd.Activation(x, act_type="softrelu").dtype == onp.float32
        assert str(nd.Activation(x, act_type="relu").dtype) == "bfloat16"
        assert nd.LeakyReLU(x, act_type="elu").dtype == onp.float32
        assert nd.LeakyReLU(x, act_type="selu").dtype == onp.float32
        assert str(nd.LeakyReLU(x, act_type="leaky").dtype) == "bfloat16"
    finally:
        amp.disable()


def test_amp_convert_symbol_conditional():
    import json

    import mxnet_tpu.symbol as S
    from mxnet_tpu.contrib import amp

    a = S.Variable("data")
    net = S.Activation(S.FullyConnected(a, name="fc", num_hidden=4),
                       name="sr", act_type="softrelu")
    cs = amp.convert_symbol(net, target_dtype="bfloat16")
    nodes = json.loads(cs.tojson())["nodes"]
    f32_casts = [n for n in nodes if n["op"] == "amp_cast"
                 and "float32" in str(n.get("attrs", {}))]
    assert f32_casts, "softrelu input not cast to fp32"
