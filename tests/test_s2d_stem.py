"""Space-to-depth stem equivalence: the MLPerf-style TPU stem
(`stem_s2d=True`) must be bit-equivalent to the plain 7x7/2 conv —
same parameters, same outputs, same gradients.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.gluon.model_zoo.vision.resnet import _S2DStemConv
from mxnet_tpu.gluon.nn import Conv2D
from mxnet_tpu.gluon.model_zoo import vision
from mxnet_tpu.test_utils import assert_almost_equal


def _np(x):
    return onp.asarray(x.asnumpy())


@pytest.mark.parametrize("layout,hw", [("NCHW", (32, 32)),
                                       ("NHWC", (32, 32)),
                                       ("NCHW", (33, 35)),  # odd sizes
                                       ("NHWC", (33, 35))])
def test_s2d_stem_matches_plain_conv(layout, hw):
    rng = onp.random.RandomState(0)
    h, w = hw
    shape = (2, 3, h, w) if layout == "NCHW" else (2, h, w, 3)
    x = nd.array(rng.randn(*shape).astype("f"))

    plain = Conv2D(8, 7, 2, 3, use_bias=False, layout=layout)
    plain.initialize(mx.init.Xavier())
    with autograd.pause():
        want = plain(x)
    s2d = _S2DStemConv(8, use_bias=False, layout=layout)
    s2d.initialize()
    with autograd.pause():
        s2d(x)  # finish deferred init
    # identical parameter shape -> copy the plain weights over
    s2d.weight.set_data(plain.weight.data())
    with autograd.pause():
        got = s2d(x)
    assert got.shape == want.shape
    assert_almost_equal(_np(got), _np(want), rtol=1e-4, atol=1e-4)


def test_s2d_stem_gradients_match():
    rng = onp.random.RandomState(1)
    x_np = rng.randn(2, 3, 16, 16).astype("f")

    plain = Conv2D(4, 7, 2, 3, use_bias=False, layout="NCHW")
    plain.initialize(mx.init.Xavier())
    x1 = nd.array(x_np)
    x1.attach_grad()
    with autograd.record():
        o1 = plain(x1)
        o1.backward(nd.ones_like(o1))
    s2d = _S2DStemConv(4, use_bias=False, layout="NCHW")
    s2d.initialize()
    with autograd.pause():
        s2d(nd.array(x_np))
    s2d.weight.set_data(plain.weight.data())
    x2 = nd.array(x_np)
    x2.attach_grad()
    with autograd.record():
        o2 = s2d(x2)
        o2.backward(nd.ones_like(o2))
    assert_almost_equal(_np(x2.grad), _np(x1.grad), rtol=1e-4, atol=1e-4)
    assert_almost_equal(_np(s2d.weight.grad()), _np(plain.weight.grad()),
                        rtol=1e-4, atol=1e-4)


def test_s2d_stem_exports_via_sym_trace(tmp_path):
    # F=sym has no static shapes; the stem must fall back to the plain
    # 7x7/2 form so export/SymbolBlock keep working
    mx.random.seed(1)
    net = vision.resnet18_v1(classes=4, stem_s2d=True)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    x = nd.array(onp.random.RandomState(3).rand(1, 3, 32, 32).astype("f"))
    with autograd.pause():
        y = net(x)
    net.export(str(tmp_path / "m"))
    from mxnet_tpu.gluon import SymbolBlock

    sb = SymbolBlock.imports(str(tmp_path / "m-symbol.json"), ["data"],
                             str(tmp_path / "m-0000.params"))
    with autograd.pause():
        y2 = sb(x)
    assert_almost_equal(_np(y2), _np(y), rtol=1e-3, atol=1e-3)


def test_resnet_stem_s2d_checkpoint_compatible(tmp_path):
    # a checkpoint written by the plain model loads into the s2d model
    # and produces the same logits (same param names and shapes)
    mx.random.seed(0)
    a = vision.resnet18_v1(classes=10)
    a.initialize(mx.init.Xavier())
    x = nd.array(onp.random.RandomState(2).rand(1, 3, 32, 32).astype("f"))
    with autograd.pause():
        ya = a(x)
    f = str(tmp_path / "w.params")
    a.save_parameters(f)
    b = vision.resnet18_v1(classes=10, stem_s2d=True)
    b.load_parameters(f)
    with autograd.pause():
        yb = b(x)
    assert_almost_equal(_np(yb), _np(ya), rtol=1e-3, atol=1e-3)
