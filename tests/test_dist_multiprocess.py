"""True multi-PROCESS distributed kvstore test on one host — the
reference's nightly dist_sync_kvstore.py pattern: N OS processes
launched via tools/launch.py (local mode) rendezvous through
jax.distributed and assert exact aggregated values after concurrent
push/pull (SURVEY §4: 'multi-process tests on one host with a
mocked/loopback mesh')."""
import numpy as onp

from _dist_harness import run_launched_workers

BODY = r"""
import numpy as onp
import mxnet_tpu as mx
from mxnet_tpu import nd, kv

store = kv.create("dist_sync")
rank, n = store.rank, store.num_workers
assert n == 2, n
store.init(3, nd.zeros((4,)))
# each worker pushes rank+1; dist_sync sums across workers -> 3
store.push(3, nd.array(onp.full(4, float(rank + 1), "f")))
out = nd.zeros((4,))
store.pull(3, out=out)
store.barrier()
with open(os.path.join({outdir!r}, "r" + str(rank) + ".txt"), "w") as f:
    f.write(",".join(str(float(v)) for v in out.asnumpy()))
"""


def test_dist_sync_two_processes(tmp_path):
    run_launched_workers(tmp_path, BODY, n=2, timeout=240)
    for rank in (0, 1):
        p = tmp_path / f"r{rank}.txt"
        assert p.is_file(), f"worker {rank} produced no result"
        vals = [float(v) for v in p.read_text().split(",")]
        # both workers converge on the same aggregated value 1+2=3
        onp.testing.assert_allclose(vals, [3.0] * 4)
