"""True multi-PROCESS distributed kvstore test on one host — the
reference's nightly dist_sync_kvstore.py pattern: N OS processes
launched via tools/launch.py (local mode) rendezvous through
jax.distributed and assert exact aggregated values after concurrent
push/pull (SURVEY §4: 'multi-process tests on one host with a
mocked/loopback mesh')."""
import os
import subprocess
import sys

import numpy as onp
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import os, sys
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, {repo!r})
from mxnet_tpu.tools import launch
assert launch.init(), "launcher env missing"
import numpy as onp
import mxnet_tpu as mx
from mxnet_tpu import nd, kv

store = kv.create("dist_sync")
rank, n = store.rank, store.num_workers
assert n == 2, n
store.init(3, nd.zeros((4,)))
# each worker pushes rank+1; dist_sync sums across workers -> 3
store.push(3, nd.array(onp.full(4, float(rank + 1), "f")))
out = nd.zeros((4,))
store.pull(3, out=out)
store.barrier()
with open(os.path.join({outdir!r}, "r" + str(rank) + ".txt"), "w") as f:
    f.write(",".join(str(float(v)) for v in out.asnumpy()))
"""


def test_dist_sync_two_processes(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER.format(repo=REPO, outdir=str(tmp_path)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    proc = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.tools.launch", "-n", "2",
         "--launcher", "local", sys.executable, str(worker)],
        env=env, capture_output=True, text=True, timeout=240, cwd=REPO)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    for rank in (0, 1):
        p = tmp_path / f"r{rank}.txt"
        assert p.is_file(), f"worker {rank} produced no result"
        vals = [float(v) for v in p.read_text().split(",")]
        # both workers converge on the same aggregated value 1+2=3
        onp.testing.assert_allclose(vals, [3.0] * 4)
