"""Gluon blocks / training (reference suite:
tests/python/unittest/test_gluon.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon
from mxnet_tpu.gluon import nn


def test_dense_forward():
    layer = nn.Dense(4, in_units=3)
    layer.initialize()
    x = nd.ones((2, 3))
    out = layer(x)
    assert out.shape == (2, 4)


def test_dense_deferred_init():
    layer = nn.Dense(4)
    layer.initialize()
    out = layer(nd.ones((2, 7)))
    assert out.shape == (2, 4)
    assert layer.weight.shape == (4, 7)


def test_sequential_mlp():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(8), nn.Dense(2))
    net.initialize()
    out = net(nd.ones((5, 10)))
    assert out.shape == (5, 2)


def test_collect_params_names():
    net = nn.HybridSequential(prefix="net_")
    with net.name_scope():
        net.add(nn.Dense(4, in_units=2))
    params = net.collect_params()
    names = list(params.keys())
    assert any("weight" in n for n in names)
    assert any("bias" in n for n in names)
    assert all(n.startswith("net_") for n in names)


def test_param_save_load(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(3, in_units=2))
    net.initialize()
    f = str(tmp_path / "p.params")
    net.save_parameters(f)
    net2 = nn.HybridSequential()
    net2.add(nn.Dense(3, in_units=2))
    net2.load_parameters(f)
    x = nd.ones((1, 2))
    onp.testing.assert_allclose(net(x).asnumpy(), net2(x).asnumpy(),
                                rtol=1e-6)


def test_conv2d():
    layer = nn.Conv2D(8, kernel_size=3, padding=1)
    layer.initialize()
    out = layer(nd.ones((2, 3, 16, 16)))
    assert out.shape == (2, 8, 16, 16)
    assert layer.weight.shape == (8, 3, 3, 3)


def test_conv_stride_groups():
    layer = nn.Conv2D(8, kernel_size=3, strides=2, padding=1, groups=2,
                      in_channels=4)
    layer.initialize()
    out = layer(nd.ones((1, 4, 8, 8)))
    assert out.shape == (1, 8, 4, 4)


def test_conv2d_transpose():
    layer = nn.Conv2DTranspose(4, kernel_size=2, strides=2)
    layer.initialize()
    out = layer(nd.ones((1, 3, 8, 8)))
    assert out.shape == (1, 4, 16, 16)


def test_pooling_layers():
    x = nd.ones((1, 2, 8, 8))
    assert nn.MaxPool2D(2)(x).shape == (1, 2, 4, 4)
    assert nn.AvgPool2D(2)(x).shape == (1, 2, 4, 4)
    assert nn.GlobalAvgPool2D()(x).shape == (1, 2, 1, 1)
    assert nn.GlobalMaxPool2D()(x).shape == (1, 2, 1, 1)


def test_batchnorm_train_updates_stats():
    layer = nn.BatchNorm(in_channels=3)
    layer.initialize()
    x = nd.array(onp.random.rand(4, 3, 2, 2).astype("f") * 10)
    with autograd.record():
        layer(x)
    rm = layer.running_mean.data().asnumpy()
    assert (onp.abs(rm) > 0).any()  # moved off init
    # inference path uses running stats
    out = layer(nd.zeros((2, 3, 2, 2)))
    assert out.shape == (2, 3, 2, 2)


def test_layernorm():
    layer = nn.LayerNorm(in_channels=5)
    layer.initialize()
    out = layer(nd.array(onp.random.rand(2, 5).astype("f")))
    onp.testing.assert_allclose(out.asnumpy().mean(axis=-1), [0, 0],
                                atol=1e-5)


def test_embedding():
    layer = nn.Embedding(10, 4)
    layer.initialize()
    out = layer(nd.array([1, 2, 5], dtype="int32"))
    assert out.shape == (3, 4)


def test_dropout_layer():
    layer = nn.Dropout(0.5)
    x = nd.ones((10, 10))
    assert (layer(x).asnumpy() == 1).all()  # not training
    with autograd.record():
        y = layer(x)
    assert (y.asnumpy() == 0).any()


def test_activations():
    x = nd.array([-1.0, 0.0, 1.0])
    assert (nn.LeakyReLU(0.1)(x).asnumpy()[0] + 0.1) < 1e-6
    assert nn.ELU()(x).shape == (3,)
    assert nn.SELU()(x).shape == (3,)
    assert nn.Swish()(x).shape == (3,)
    assert nn.GELU()(x).shape == (3,)
    prelu = nn.PReLU()
    prelu.initialize()
    assert prelu(x).shape == (3,)


def test_hybridize_matches_eager():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    x = nd.array(onp.random.rand(3, 8).astype("f"))
    eager = net(x).asnumpy()
    net.hybridize()
    compiled = net(x).asnumpy()
    onp.testing.assert_allclose(eager, compiled, rtol=1e-5, atol=1e-6)
    # second call hits the jit cache
    onp.testing.assert_allclose(net(x).asnumpy(), eager, rtol=1e-5,
                                atol=1e-6)


def test_hybridize_grad_matches_eager():
    def run(hybrid):
        mx.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(8, activation="relu", in_units=4), nn.Dense(2,
                                                                     in_units=8))
        net.initialize(mx.init.Xavier())
        if hybrid:
            net.hybridize()
        x = nd.array(onp.arange(8).reshape(2, 4).astype("f"))
        with autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        return {k: p.grad().asnumpy()
                for k, p in net._collect_params_with_prefix().items()}

    g1, g2 = run(False), run(True)
    assert g1.keys() == g2.keys()
    for k in g1:
        onp.testing.assert_allclose(g1[k], g2[k], rtol=1e-4, atol=1e-5)


def test_hybridized_batchnorm_updates_stats():
    layer = nn.BatchNorm(in_channels=3)
    layer.initialize()
    layer.hybridize()
    x = nd.array(onp.random.rand(4, 3, 2, 2).astype("f") * 5 + 3)
    with autograd.record():
        layer(x)
    rm = layer.running_mean.data().asnumpy()
    assert (onp.abs(rm) > 0.01).any()


def test_trainer_sgd_step():
    net = nn.Dense(1, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    x = nd.array([[1.0, 2.0]])
    w_before = net.weight.data().asnumpy().copy()
    with autograd.record():
        loss = (net(x)).sum()
    loss.backward()
    trainer.step(1)
    w_after = net.weight.data().asnumpy()
    onp.testing.assert_allclose(w_after, w_before - 0.1 * x.asnumpy(),
                                rtol=1e-5)


def test_training_reduces_loss():
    mx.random.seed(42)
    onp.random.seed(42)
    w_true = onp.array([[2.0], [-3.0]], dtype="f")
    X = onp.random.rand(64, 2).astype("f")
    y = X @ w_true + 0.5

    net = nn.Dense(1, in_units=2)
    net.initialize()
    l2 = gluon.loss.L2Loss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    first = None
    for _ in range(50):
        with autograd.record():
            loss = l2(net(nd.array(X)), nd.array(y))
            total = loss.mean()
        total.backward()
        trainer.step(X.shape[0] / 64.0)
        if first is None:
            first = total.asscalar()
    assert total.asscalar() < first * 0.1


def test_losses():
    pred = nd.array(onp.random.rand(4, 5).astype("f"))
    label = nd.array([1, 2, 3, 0])
    l = gluon.loss.SoftmaxCrossEntropyLoss()(pred, label)
    assert l.shape == (4,)
    dense_label = nd.one_hot(label, 5)
    l2 = gluon.loss.SoftmaxCrossEntropyLoss(sparse_label=False)(pred,
                                                                dense_label)
    onp.testing.assert_allclose(l.asnumpy(), l2.asnumpy(), rtol=1e-5)
    assert gluon.loss.L1Loss()(pred, nd.zeros((4, 5))).shape == (4,)
    assert gluon.loss.L2Loss()(pred, nd.zeros((4, 5))).shape == (4,)
    assert gluon.loss.SigmoidBCELoss()(pred, nd.zeros((4, 5))).shape == (4,)
    assert gluon.loss.HuberLoss()(pred, nd.zeros((4, 5))).shape == (4,)
    assert gluon.loss.HingeLoss()(pred, nd.ones((4, 5))).shape == (4,)
    assert gluon.loss.KLDivLoss(from_logits=False)(
        pred, nd.softmax(pred)).shape == (4,)


def test_block_repr_and_name():
    d = nn.Dense(2)
    assert d.prefix.startswith("dense")
    assert "Dense" in repr(d)


def test_cast():
    net = nn.Dense(2, in_units=2)
    net.initialize()
    net.cast("bfloat16")
    out = net(nd.ones((1, 2)).astype("bfloat16"))
    assert "bfloat16" in str(out.data.dtype)


def test_ctc_loss_has_gradient():
    pred = nd.array(onp.random.rand(8, 2, 5).astype("f"))  # (T, N, C)
    pred.attach_grad()
    label = nd.array([[1, 2, 3], [2, 3, 4]])
    ctc = gluon.loss.CTCLoss(layout="TNC")
    with autograd.record():
        loss = ctc(pred, label)
    assert loss.shape == (2,)
    loss.backward()
    assert (onp.abs(pred.grad.asnumpy()) > 0).any(), "CTC grad must flow"


def test_inplace_raises_under_record():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        try:
            y += x
            raised = False
        except mx.MXNetError:
            raised = True
    assert raised


def test_out_kwarg_keeps_gradient():
    x = nd.array([1.0, -2.0, 3.0])
    w = nd.array([2.0, 2.0, 2.0])
    x.attach_grad()
    y = nd.zeros((3,))
    with autograd.record():
        nd.relu(x, out=y)
        z = (y * w).sum()
    z.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [2, 0, 2])
