"""Benchmark harness smoke tests + tools/bench_compare.py.

The ``slow``-marked tests run dispatch_bench and train_step_bench in
``--smoke`` mode so the benchmark entry points can't rot (excluded from
tier-1 via ``-m 'not slow'``); the bench_compare tests are fast unit
tests over synthetic documents."""
import json
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))

import bench_compare  # noqa: E402


@pytest.mark.slow
def test_dispatch_bench_smoke(tmp_path):
    from mxnet_tpu.benchmark import dispatch_bench

    out = str(tmp_path / "dispatch.json")
    doc = dispatch_bench.run(smoke=True, out_path=out)
    assert doc["smoke"] is True
    assert set(doc["results"]) == {"nograd", "recorded"}
    assert os.path.exists(out)


@pytest.mark.slow
def test_train_step_bench_smoke(tmp_path):
    from mxnet_tpu.benchmark import train_step_bench

    out = str(tmp_path / "step.json")
    doc = train_step_bench.run(smoke=True, out_path=out)
    assert doc["smoke"] is True
    assert doc["bitwise_equal"]
    assert doc["loss_scale_equal"]
    assert doc["skip_step_exercised"]
    assert doc["results"]["fused_ms_per_step"] > 0
    with open(out) as f:
        assert json.load(f)["benchmark"] == "fused_train_step"


@pytest.mark.slow
def test_compile_cache_bench_smoke(tmp_path):
    from mxnet_tpu.benchmark import compile_cache_bench

    out = str(tmp_path / "compile.json")
    doc = compile_cache_bench.run(smoke=True, out_path=out)
    assert doc["smoke"] is True
    assert doc["warm_start_bitwise_equal"]
    assert doc["bucketing_bitwise_equal"]
    assert doc["results"]["warm_speedup"] > 1.0
    assert doc["results"]["retraces_bucketed"] < \
        doc["results"]["retraces_unbucketed"]
    with open(out) as f:
        assert json.load(f)["benchmark"] == "compile_cache"


@pytest.mark.slow
def test_serving_bench_smoke(tmp_path):
    from mxnet_tpu.benchmark import serving_bench

    out = str(tmp_path / "serve.json")
    doc = serving_bench.run(smoke=True, out_path=out)
    assert doc["smoke"] is True
    assert doc["dynamic_bitwise_equal"]
    assert doc["warm_start_bitwise_equal"]
    assert doc["warm_start_zero_compiles"], \
        "warm restart must serve its first request with zero compiles"
    assert doc["results"]["warm_retraces"] == 0
    assert doc["results"]["batching_speedup"] > 1.0
    assert doc["results"]["latency_p99_ms"] > 0
    with open(out) as f:
        assert json.load(f)["benchmark"] == "serving"


@pytest.mark.slow
def test_pipeline_bench_smoke(tmp_path):
    from mxnet_tpu.benchmark import pipeline_bench

    out = str(tmp_path / "pipeline.json")
    doc = pipeline_bench.run(smoke=True, out_path=out)
    assert doc["smoke"] is True
    assert doc["bitwise_equal"]
    assert doc["fallback_bitwise_equal"]
    assert doc["loss_trace_equal"]
    assert doc["loss_scale_trace_equal"]
    assert doc["results"]["pipelined_epoch_s"] > 0
    assert doc["counters"]["prefetch_hits"] > 0, \
        "the prefetcher never got ahead of the step loop"
    with open(out) as f:
        assert json.load(f)["benchmark"] == "pipeline_epoch"


@pytest.mark.slow
def test_overload_bench_smoke(tmp_path):
    from mxnet_tpu.benchmark import overload_bench

    out = str(tmp_path / "overload.json")
    doc = overload_bench.run(smoke=True, out_path=out)
    assert doc["smoke"] is True
    assert doc["criteria"]["offered_2x"], doc["results"]
    assert doc["criteria"]["best_effort_shed"], doc["overload"]
    assert doc["criteria"]["critical_never_shed"]
    assert doc["criteria"]["sheds_fast"]
    assert doc["criteria"]["zero_critical_failures"]
    assert doc["results"]["goodput_rps"] > 0
    assert doc["results"]["shed_rate"] > 0
    # the committed full run asserts <= 1.5x; smoke phases are short
    # (noisy quantiles), so only gate against gross protection loss
    assert doc["results"]["critical_p99_ratio"] < 2.5, doc["results"]
    with open(out) as f:
        assert json.load(f)["benchmark"] == "overload"


@pytest.mark.slow
def test_resilience_bench_smoke(tmp_path):
    from mxnet_tpu.benchmark import resilience_bench

    out = str(tmp_path / "resil.json")
    doc = resilience_bench.run(smoke=True, out_path=out)
    assert doc["smoke"] is True
    assert doc["recovery"]["bitwise_equal"]
    assert doc["recovery"]["loss_trace_equal"]
    assert doc["recovery"]["amp_bitwise_equal"]
    assert doc["recovery"]["amp_scale_trace_equal"]
    assert doc["recovery"]["amp_skip_exercised"]
    assert doc["recovery"]["restarts"] == 1
    assert doc["recovery"]["fault_fires"].get("bench_step") == 2
    assert doc["overhead"]["nockpt_epoch_s"] > 0
    with open(out) as f:
        assert json.load(f)["benchmark"] == "resilience"


@pytest.mark.slow
def test_graphopt_bench_smoke(tmp_path):
    from mxnet_tpu.benchmark import graphopt_bench

    out = str(tmp_path / "graphopt.json")
    doc = graphopt_bench.run(smoke=True, out_path=out)
    assert doc["smoke"] is True
    assert doc["bind_bitwise_equal"]
    assert doc["eager_bitwise_equal"]
    r = doc["results"]
    assert r["graph_nodes_after"] < r["graph_nodes_before"]
    assert r["bind_nodes_opt2"] < r["bind_nodes_opt0"]
    assert not r["rejected"]
    # every shipped pass fired on the redundant benchmark graph —
    # except fusion, which the legacy run pins off (MXNET_FUSION=0)
    # to keep the r14 ledger comparable; --fusion measures it
    assert set(r["rewrites_per_pass"]) == \
        {"fold", "cse", "transpose_elision", "fusion", "dce"}
    assert r["rewrites_per_pass"]["fusion"] == 0
    assert all(v > 0 for k, v in r["rewrites_per_pass"].items()
               if k != "fusion")
    with open(out) as f:
        assert json.load(f)["benchmark"] == "graph_opt"


@pytest.mark.slow
def test_fusion_bench_smoke(tmp_path):
    from mxnet_tpu.benchmark import graphopt_bench

    out = str(tmp_path / "fusion.json")
    doc = graphopt_bench.run_fusion(smoke=True, out_path=out)
    assert doc["smoke"] is True
    # parity contract (bitwise or documented ulp) holds at any scale;
    # the >=1.1x two-pattern speedup gate is only enforced on the
    # committed full run (BENCH_FUSION_r17.json)
    assert set(doc["patterns"]) == \
        {"elementwise", "norm_act", "attention", "serving"}
    for row in doc["patterns"].values():
        assert row["bitwise_equal"] or row["max_abs_err"] <= 1e-6
        assert row["fused_ms"] > 0 and row["unfused_ms"] > 0
    for zoo_row in doc["zoo"].values():
        assert zoo_row["clusters_total"] >= 1
        assert 0.0 < zoo_row["hit_rate"] <= 1.0
    with open(out) as f:
        assert json.load(f)["benchmark"] == "fusion"


@pytest.mark.slow
def test_bundle_bench_smoke(tmp_path):
    from mxnet_tpu.benchmark import bundle_bench

    out = str(tmp_path / "bundle.json")
    doc = bundle_bench.run(smoke=True, out_path=out)
    assert doc["smoke"] is True
    assert doc["bitwise_equal"]
    # the tentpole promise holds at any scale: a bundle- or
    # remote-warm replica's first response pays zero traces and zero
    # XLA compiles (latency gates only on the committed full run)
    assert doc["results"]["bundle_warm_retraces"] == 0
    assert doc["results"]["remote_warm_retraces"] == 0
    assert doc["warm_counters"]["bundle_warm"]["compiles"] == 0
    assert doc["warm_counters"]["remote_warm"]["compiles"] == 0
    assert doc["bundle_entries"] >= 2
    assert doc["remote_hits"] >= 2
    with open(out) as f:
        assert json.load(f)["benchmark"] == "bundle"


@pytest.mark.slow
def test_sharding_bench_smoke(tmp_path):
    from mxnet_tpu.benchmark import sharding_bench

    out = str(tmp_path / "shard.json")
    doc = sharding_bench.run(smoke=True, out_path=out)
    assert doc["smoke"] is True
    assert doc["config"]["devices"] >= 4  # conftest forces 8
    # correctness gates must hold even at smoke sizes; the efficiency
    # and speedup gates are timing-dependent and only enforced on the
    # committed full run (BENCH_SHARD_r15.json)
    assert doc["gates"]["scaling_parity_ulp"]
    assert doc["gates"]["zero1_state_1_over_n"]
    assert doc["gates"]["zero1_parity_ulp"]
    assert doc["gates"]["serving_bitwise"]
    assert doc["gates"]["ckpt_reshape_bitwise"]
    assert doc["gates"]["ckpt_resharded_on_load"]
    assert doc["checkpoint_reshape"]["shard_files"] == 4
    assert doc["checkpoint_reshape"]["post_restore_step_ok"]
    assert doc["fused_scaling"]["update_ms_sharded"] > 0
    with open(out) as f:
        assert json.load(f)["benchmark"] == "sharding_r15"


@pytest.mark.slow
def test_decode_bench_smoke(tmp_path):
    from mxnet_tpu.benchmark import decode_bench

    out = str(tmp_path / "decode.json")
    doc = decode_bench.run(smoke=True, out_path=out)
    assert doc["smoke"] is True
    # the bitwise contracts must hold at any scale; the >= 3x decode
    # gate is a seq-64 property only enforced on the committed full
    # run (BENCH_DECODE_r16.json)
    assert doc["incremental"]["bitwise_incremental_vs_prefix"]
    assert doc["incremental"]["bitwise_vs_offline_unroll"]
    assert doc["continuous_batching"]["bitwise_vs_offline_unroll"]
    assert doc["continuous_batching"]["bitwise_continuous_vs_flush"]
    assert doc["results"]["decode_speedup"] > 1.0
    assert doc["results"]["continuous_vs_flush_speedup"] > 1.0
    assert doc["results"]["decode_steps"] > 0
    with open(out) as f:
        assert json.load(f)["benchmark"] == "decode"


@pytest.mark.slow
def test_paged_decode_bench_smoke(tmp_path):
    from mxnet_tpu.benchmark import decode_bench

    out = str(tmp_path / "paged.json")
    doc = decode_bench.run_paged(smoke=True, out_path=out)
    assert doc["smoke"] is True
    # the structural contracts hold at any scale: paged packs >= 3x
    # the sessions of row-slot at one byte budget (int8 more still),
    # and the batcher-served streams are bitwise against the
    # explicit-state unroll. The 0.9x throughput and step-flatness
    # gates are timing properties only enforced on the committed full
    # run (BENCH_PAGED_r21.json)
    assert doc["capacity"]["max_sessions_x"] >= 3.0
    assert doc["capacity"]["int8_sessions_x"] > \
        doc["capacity"]["max_sessions_x"]
    assert doc["throughput"]["bitwise_vs_offline_unroll"]
    assert doc["results"]["paged_tokens_per_s"] > 0
    assert doc["results"]["step_flat_ratio"] > 0
    with open(out) as f:
        assert json.load(f)["benchmark"] == "paged_decode"


@pytest.mark.slow
def test_telemetry_bench_smoke(tmp_path):
    from mxnet_tpu.benchmark import telemetry_bench

    out = str(tmp_path / "telem.json")
    trace = str(tmp_path / "telem.trace.json")
    doc = telemetry_bench.run(smoke=True, out_path=out,
                              trace_path=trace)
    assert doc["smoke"] is True
    # the <2%/<3% overhead gates are timing properties of the full
    # loop lengths and only enforced on the committed run
    # (BENCH_TELEM_r18.json); the structural contracts hold at any
    # scale: one trace id stitches the request lifecycle across >= 2
    # lanes, and the pipelined slice records prefetch + fused-step
    # spans
    tr = doc["trace"]
    assert tr["request_lifecycle_complete"], tr
    assert tr["request_lanes"] >= 2
    assert tr["prefetch_spans"] > 0 and tr["fused_step_spans"] > 0
    with open(trace) as f:
        trace_doc = json.load(f)  # the Perfetto acceptance bar
    assert any(e["ph"] == "X" for e in trace_doc["traceEvents"])
    with open(out) as f:
        assert json.load(f)["benchmark"] == "telemetry"


def test_bench_compare_telemetry_metrics():
    """BENCH_TELEM_r18.json names: the tracer overhead percentages are
    lower-is-better, the drain rps higher-is-better, per-step ms
    lower-is-better; pair counts untracked."""
    base = {"results": {"fused_step_overhead_pct": 0.8,
                        "serving_overhead_pct": 2.5,
                        "serving_rps_telem1": 3280.0,
                        "fused_step_ms_telem1": 3.11},
            "serving": {"pairs": 12}}
    worse = {"results": {"fused_step_overhead_pct": 6.0,
                         "serving_overhead_pct": 9.0,
                         "serving_rps_telem1": 1500.0,
                         "fused_step_ms_telem1": 3.11},
             "serving": {"pairs": 12}}
    rows = {r[0]: r for r in bench_compare.compare(base, worse)}
    assert bench_compare._direction(
        "results.serving_overhead_pct") == "lower"
    assert rows["results.fused_step_overhead_pct"][4]  # span got hot
    assert rows["results.serving_overhead_pct"][4]
    assert rows["results.serving_rps_telem1"][4]       # drain halved
    assert not rows["results.fused_step_ms_telem1"][4]
    assert "serving.pairs" not in rows     # not a perf direction
    assert not any(r[4] for r in bench_compare.compare(base, base))


def test_bench_compare_decode_metrics():
    """BENCH_DECODE_r16.json names: tokens/s throughputs and the two
    speedup ratios are higher-is-better, step counts untracked."""
    base = {"results": {"decode_speedup": 30.0,
                        "incremental_tokens_per_s": 2500.0,
                        "continuous_tokens_per_s": 2200.0,
                        "continuous_vs_flush_speedup": 40.0,
                        "decode_steps": 2336}}
    worse = {"results": {"decode_speedup": 4.0,
                         "incremental_tokens_per_s": 900.0,
                         "continuous_tokens_per_s": 2200.0,
                         "continuous_vs_flush_speedup": 40.0,
                         "decode_steps": 2336}}
    rows = {r[0]: r for r in bench_compare.compare(base, worse)}
    assert bench_compare._direction(
        "results.incremental_tokens_per_s") == "higher"
    assert bench_compare._direction(
        "results.decode_speedup") == "higher"
    assert rows["results.decode_speedup"][4]  # prefix re-execution back
    assert rows["results.incremental_tokens_per_s"][4]
    assert not rows["results.continuous_tokens_per_s"][4]
    assert "results.decode_steps" not in rows  # not a perf direction
    assert not any(r[4] for r in bench_compare.compare(base, base))


def test_bench_compare_paged_metrics():
    """BENCH_PAGED_r21.json names: session capacities/ratios and
    tokens/s are higher-is-better, step_flat_ratio lower-is-better,
    the byte budget untracked (a config fact, not a speed)."""
    base = {"capacity": {"byte_budget": 8388608,
                         "paged_max_sessions": 255,
                         "max_sessions_x": 8.0},
            "results": {"paged_tokens_per_s": 900.0,
                        "paged_vs_rowslot_throughput_x": 0.95,
                        "step_flat_ratio": 1.02}}
    worse = {"capacity": {"byte_budget": 8388608,
                          "paged_max_sessions": 40,
                          "max_sessions_x": 1.2},
             "results": {"paged_tokens_per_s": 300.0,
                         "paged_vs_rowslot_throughput_x": 0.5,
                         "step_flat_ratio": 3.0}}
    rows = {r[0]: r for r in bench_compare.compare(base, worse)}
    assert bench_compare._direction(
        "capacity.paged_max_sessions") == "higher"
    assert bench_compare._direction(
        "results.step_flat_ratio") == "lower"
    assert rows["capacity.paged_max_sessions"][4]  # packing collapsed
    assert rows["capacity.max_sessions_x"][4]
    assert rows["results.paged_tokens_per_s"][4]
    assert rows["results.paged_vs_rowslot_throughput_x"][4]
    assert rows["results.step_flat_ratio"][4]  # O(prefix) crept back
    assert "capacity.byte_budget" not in rows  # not a perf direction
    assert not any(r[4] for r in bench_compare.compare(base, base))


@pytest.mark.slow
def test_lockcheck_bench_smoke(tmp_path):
    from mxnet_tpu.benchmark import lockcheck_bench

    doc = lockcheck_bench.run(smoke=True)
    assert doc["smoke"] is True
    micro = doc["uncontended_acquire"]
    # structural contracts at any scale: the level-0 factory handed
    # back a raw primitive (asserted inside the bench) and both sides
    # timed something real. The <1% passthrough gate is only enforced
    # on the committed full run (BENCH_LOCKCHECK_r22.json) — smoke
    # pair counts are noise-dominated.
    assert micro["raw_acquire_us"] > 0
    assert micro["level0_acquire_us"] > 0
    # an armed acquire costs more than a raw one, by construction
    assert micro["checked_acquire_us"] > micro["level0_acquire_us"]
    drain = doc["serving_drain"]
    assert drain["level0_drain_ms"] > 0 and drain["warn_drain_ms"] > 0


def test_bench_compare_lockcheck_metrics():
    """BENCH_LOCKCHECK_r22.json names: the passthrough/warn overhead
    percentages are lower-is-better (the 'overhead' tag), per-acquire
    and drain times lower-is-better; pair counts untracked."""
    base = {"uncontended_acquire": {"passthrough_overhead_pct": 0.4,
                                    "checked_acquire_us": 1.5,
                                    "pairs": 40},
            "serving_drain": {"serving_warn_overhead_pct": 30.0,
                              "level0_drain_ms": 24.0}}
    worse = {"uncontended_acquire": {"passthrough_overhead_pct": 5.0,
                                     "checked_acquire_us": 9.0,
                                     "pairs": 40},
             "serving_drain": {"serving_warn_overhead_pct": 80.0,
                               "level0_drain_ms": 60.0}}
    rows = {r[0]: r for r in bench_compare.compare(base, worse)}
    assert bench_compare._direction(
        "uncontended_acquire.passthrough_overhead_pct") == "lower"
    assert rows["uncontended_acquire.passthrough_overhead_pct"][4]
    assert rows["uncontended_acquire.checked_acquire_us"][4]
    assert rows["serving_drain.serving_warn_overhead_pct"][4]
    assert rows["serving_drain.level0_drain_ms"][4]
    assert "uncontended_acquire.pairs" not in rows
    assert not any(r[4] for r in bench_compare.compare(base, base))


def test_bench_compare_sharding_metrics():
    """BENCH_SHARD_r15.json names: efficiency and the plan-vs-replicated
    speedup are higher-is-better, update/step ms lower-is-better, the
    state-bytes ratio untracked (it is a layout fact, not a speed)."""
    base = {"fused_scaling": {"efficiency": 0.93, "update_ms_sharded":
                              21.0, "plan_vs_replicated_speedup": 4.8},
            "zero1": {"state_ratio": 0.25}}
    worse = {"fused_scaling": {"efficiency": 0.5, "update_ms_sharded":
                               40.0, "plan_vs_replicated_speedup": 1.1},
             "zero1": {"state_ratio": 0.25}}
    rows = {r[0]: r for r in bench_compare.compare(base, worse)}
    assert rows["fused_scaling.efficiency"][4]       # scaling collapsed
    assert rows["fused_scaling.update_ms_sharded"][4]
    assert rows["fused_scaling.plan_vs_replicated_speedup"][4]
    assert "zero1.state_ratio" not in rows           # not a direction
    assert not any(r[4] for r in bench_compare.compare(base, base))


def test_bench_compare_graphopt_metrics():
    """BENCH_GRAPHOPT_r14.json names: node counts and trace+compile ms
    are lower-is-better, the speedups higher-is-better, rewrite counts
    untracked."""
    base = {"results": {"graph_nodes_after": 29,
                        "trace_compile_ms_opt2": 38.0,
                        "exec_speedup": 3.7, "compile_speedup": 1.35,
                        "rewrites": 103}}
    worse = {"results": {"graph_nodes_after": 90,
                         "trace_compile_ms_opt2": 60.0,
                         "exec_speedup": 1.0, "compile_speedup": 1.35,
                         "rewrites": 103}}
    rows = {r[0]: r for r in bench_compare.compare(base, worse)}
    assert rows["results.graph_nodes_after"][4]      # rewrites stopped
    assert rows["results.trace_compile_ms_opt2"][4]  # +58%: REGRESSED
    assert rows["results.exec_speedup"][4]
    assert not rows["results.compile_speedup"][4]
    assert "results.rewrites" not in rows            # not a direction
    assert not any(r[4] for r in bench_compare.compare(base, base))


def test_bench_compare_fusion_metrics():
    """BENCH_FUSION_r17.json names: fused/unfused ms lower-is-better,
    speedup and the zoo cluster hit_rate higher-is-better; cluster
    counters and max_abs_err untracked."""
    base = {"patterns": {"elementwise": {
                "unfused_ms": 0.37, "fused_ms": 0.12, "speedup": 3.2,
                "max_abs_err": 0.0}},
            "zoo": {"resnet18_v1": {"hit_rate": 0.18,
                                    "clusters_total": 8}}}
    worse = {"patterns": {"elementwise": {
                "unfused_ms": 0.37, "fused_ms": 0.30, "speedup": 1.2,
                "max_abs_err": 0.0}},
            "zoo": {"resnet18_v1": {"hit_rate": 0.05,
                                    "clusters_total": 2}}}
    rows = {r[0]: r for r in bench_compare.compare(base, worse)}
    assert rows["patterns.elementwise.fused_ms"][4]   # 2.5x: REGRESSED
    assert rows["patterns.elementwise.speedup"][4]
    assert rows["zoo.resnet18_v1.hit_rate"][4]        # matchers quiet
    assert bench_compare._direction(
        "zoo.resnet18_v1.hit_rate") == "higher"
    assert "zoo.resnet18_v1.clusters_total" not in rows
    assert "patterns.elementwise.max_abs_err" not in rows
    assert not any(r[4] for r in bench_compare.compare(base, base))


def test_bench_compare_resilience_overhead_metrics():
    """BENCH_RESIL_r12.json names: checkpoint overhead percentages and
    epoch seconds are lower-is-better; counters untracked."""
    base = {"overhead": {"async_overhead_pct": 2.0,
                         "async_ckpt_epoch_s": 0.51,
                         "saves_per_epoch": 8}}
    worse = {"overhead": {"async_overhead_pct": 9.0,
                          "async_ckpt_epoch_s": 0.80,
                          "saves_per_epoch": 8}}
    rows = {r[0]: r for r in bench_compare.compare(base, worse)}
    assert rows["overhead.async_overhead_pct"][4]   # 2% -> 9%: REGRESSED
    assert rows["overhead.async_ckpt_epoch_s"][4]
    assert "overhead.saves_per_epoch" not in rows   # not a direction
    assert not any(r[4] for r in bench_compare.compare(base, base))


def test_bench_compare_pipeline_epoch_metrics():
    """BENCH_PIPELINE_r11.json names: epoch/idle seconds are
    lower-is-better, steps_per_s and overlap_ratio higher-is-better,
    the depth knob untracked."""
    base = {"results": {"pipelined_epoch_s": 0.43, "sync_engine_idle_s":
                        0.36, "pipelined_steps_per_s": 140.0,
                        "overlap_ratio": 0.86, "prefetch_depth": 2}}
    worse = {"results": {"pipelined_epoch_s": 0.65, "sync_engine_idle_s":
                         0.36, "pipelined_steps_per_s": 90.0,
                         "overlap_ratio": 0.4, "prefetch_depth": 2}}
    rows = {r[0]: r for r in bench_compare.compare(base, worse)}
    assert rows["results.pipelined_epoch_s"][4]      # +51%: REGRESSED
    assert rows["results.pipelined_steps_per_s"][4]  # throughput drop
    assert rows["results.overlap_ratio"][4]          # overlap collapsed
    assert not rows["results.sync_engine_idle_s"][4]
    assert "results.prefetch_depth" not in rows      # not a perf direction
    assert not any(r[4] for r in bench_compare.compare(base, base))


def test_bench_compare_serving_latency_metrics():
    """p50/p99 quantiles are lower-is-better whatever suffix they
    carry; *_rps counts as throughput (BENCH_SERVE_r10.json names)."""
    base = {"results": {"latency_p50_ms": 10.0, "latency_p99_ms": 25.0,
                        "dynamic_rps": 18000.0, "batches": 32}}
    worse = {"results": {"latency_p50_ms": 10.0, "latency_p99_ms": 40.0,
                         "dynamic_rps": 9000.0, "batches": 32}}
    rows = {r[0]: r for r in bench_compare.compare(base, worse)}
    assert rows["results.latency_p99_ms"][4]  # +60% p99: REGRESSED
    assert not rows["results.latency_p50_ms"][4]
    assert rows["results.dynamic_rps"][4]     # rps halved: REGRESSED
    assert "results.batches" not in rows      # not a perf direction
    same = bench_compare.compare(base, base)
    assert not any(r[4] for r in same)


def test_bench_compare_retrace_metrics_gated():
    """The regression gate understands the BENCH_COMPILE_r09.json
    metric names: retrace counts are lower-is-better, the speedups
    higher-is-better, pad_ratio untracked."""
    base = {"results": {"retraces_bucketed": 20, "warm_speedup": 16.0,
                        "bucketing_speedup": 6.4, "pad_ratio": 0.43,
                        "cold_first_step_ms": 1500.0}}
    worse = {"results": {"retraces_bucketed": 30, "warm_speedup": 10.0,
                         "bucketing_speedup": 6.4, "pad_ratio": 0.9,
                         "cold_first_step_ms": 1500.0}}
    rows = {r[0]: r for r in bench_compare.compare(base, worse)}
    assert rows["results.retraces_bucketed"][4]  # +50% retraces: REGRESSED
    assert rows["results.warm_speedup"][4]       # speedup drop: REGRESSED
    assert not rows["results.bucketing_speedup"][4]
    assert "results.pad_ratio" not in rows       # not a perf direction
    same = {r[0]: r for r in bench_compare.compare(base, base)}
    assert not any(r[4] for r in same.values())


def test_bench_compare_overload_metrics():
    """BENCH_OVERLOAD_r13.json names: shed_rate and the p99s are
    lower-is-better, goodput_rps higher-is-better, counts untracked."""
    base = {"results": {"shed_rate": 0.70, "goodput_rps": 120.0,
                        "overload_critical_p99_ms": 40.0,
                        "shed_decision_p99_us": 400.0,
                        "overload_x": 2.2}}
    worse = {"results": {"shed_rate": 0.95, "goodput_rps": 40.0,
                         "overload_critical_p99_ms": 90.0,
                         "shed_decision_p99_us": 400.0,
                         "overload_x": 2.2}}
    rows = {r[0]: r for r in bench_compare.compare(base, worse)}
    assert rows["results.shed_rate"][4]        # +36% shed: REGRESSED
    assert rows["results.goodput_rps"][4]      # goodput collapsed
    assert rows["results.overload_critical_p99_ms"][4]
    assert not rows["results.shed_decision_p99_us"][4]
    assert "results.overload_x" not in rows    # not a perf direction
    assert not any(r[4] for r in bench_compare.compare(base, base))


def _doc(ms, speedup):
    return {"results": {"fused_ms_per_step": ms, "speedup": speedup},
            "steps": 50, "counters": {"hits": 1}}


def test_bench_compare_directions():
    rows = bench_compare.compare(_doc(1.0, 4.0), _doc(1.1, 3.9))
    by_path = {r[0]: r for r in rows}
    # 10% slower latency / 2.5% lower speedup: both worse, neither > 20%
    assert by_path["results.fused_ms_per_step"][3] == pytest.approx(0.1)
    assert not any(r[4] for r in rows)
    # counters/steps are not perf metrics
    assert "steps" not in by_path and "counters.hits" not in by_path


def test_bench_compare_flags_regression():
    rows = bench_compare.compare(_doc(1.0, 4.0), _doc(1.5, 4.0))
    assert any(r[4] for r in rows)  # 50% latency regression
    rows = bench_compare.compare(_doc(1.0, 4.0), _doc(1.0, 2.0))
    assert any(r[4] for r in rows)  # speedup halved


@pytest.mark.slow
def test_quant_bench_smoke(tmp_path):
    from mxnet_tpu.benchmark import quant_bench

    out = str(tmp_path / "quant.json")
    doc = quant_bench.run(smoke=True, out_path=out)
    assert doc["smoke"] is True
    assert doc["lowering"] in ("native", "dequant")
    assert doc["weights"]["reduction_x"] > 2.0
    assert doc["results"][0]["accuracy_delta"] < 0.1
    assert doc["quantize_counters"]["graphs_quantized"] >= 1
    with open(out) as f:
        assert json.load(f)["benchmark"] == "quantized_serving"


def test_bench_compare_quant_metrics():
    """BENCH_QUANT_r19.json names: bytes_moved and accuracy_delta are
    lower-is-better, the int8 speedup/rps higher-is-better, the weight
    reduction ratio untracked (a layout fact, not a speed)."""
    base = {"weights": {"int8_bytes_moved": 11759880,
                        "reduction_x": 3.98},
            "results": [{"speedup": 1.34, "int8_rps": 10.4,
                         "accuracy_delta": 0.03}]}
    worse = {"weights": {"int8_bytes_moved": 46796448,
                         "reduction_x": 3.98},
             "results": [{"speedup": 0.9, "int8_rps": 6.1,
                          "accuracy_delta": 0.21}]}
    rows = {r[0]: r for r in bench_compare.compare(base, worse)}
    assert bench_compare._direction(
        "weights.int8_bytes_moved") == "lower"
    assert bench_compare._direction(
        "results[0].accuracy_delta") == "lower"
    assert rows["weights.int8_bytes_moved"][4]   # weights grew back
    assert rows["results[0].accuracy_delta"][4]  # int8 went numerically bad
    assert rows["results[0].speedup"][4]
    assert rows["results[0].int8_rps"][4]
    assert "weights.reduction_x" not in rows     # not a perf direction
    assert not any(r[4] for r in bench_compare.compare(base, base))


@pytest.mark.slow
def test_fleet_bench_smoke(tmp_path):
    from mxnet_tpu.benchmark import fleet_bench

    out = str(tmp_path / "fleet.json")
    doc = fleet_bench.run(smoke=True, out_path=out)
    assert doc["smoke"] is True
    r = doc["results"]
    # correctness gates hold at any scale: zero dropped requests and
    # zero corrupted sessions through a live drain (bitwise vs the
    # offline unroll), the joining replica warm at zero compiles, the
    # canary rolled back with zero client-visible failures
    assert r["drain_dropped_requests"] == 0
    assert r["drain_corrupted_sessions"] == 0
    assert r["drain_migrated_sessions"] >= 1
    assert r["replicas_after_drain"] == ["b", "c"]
    assert r["join_compiles_must_be_zero"] == 0
    assert r["join_retraces_must_be_zero"] == 0
    assert r["join_disk_hits"] > 0
    assert r["canary_failures_must_be_zero"] == 0
    assert r["canary_wrong_answers_must_be_zero"] == 0
    assert r["canary_rolled_back"]
    assert r["canary_shadow_mismatches"] >= 1
    # the 2.5x aggregate-throughput floor is a compute fan-out claim —
    # only a host with cores to spare can express it; a core-bound box
    # still must not collapse behind the router
    assert r["single_replica_rps"] > 0
    if r["scale_floor_applies"]:
        assert r["fleet_scale_speedup"] >= doc["scale_floor_x"], r
    else:
        assert r["fleet_scale_speedup"] > 0.5, r
    with open(out) as f:
        assert json.load(f)["benchmark"] == "fleet"


def test_bench_compare_fleet_metrics():
    """BENCH_FLEET_r23.json names: rps/speedup leaves directional,
    dropped/corrupted/_must_be_zero leaves gated EXACTLY (nonzero
    candidate regresses even against a zero baseline), cpu_count
    untracked."""
    base = {"results": {"single_replica_rps": 40.0,
                        "fleet3_aggregate_rps": 110.0,
                        "fleet_scale_speedup": 2.75,
                        "drain_dropped_requests": 0,
                        "drain_corrupted_sessions": 0,
                        "join_compiles_must_be_zero": 0,
                        "canary_failures_must_be_zero": 0,
                        "cpu_count": 8}}
    worse = {"results": {"single_replica_rps": 40.0,
                         "fleet3_aggregate_rps": 50.0,
                         "fleet_scale_speedup": 1.2,
                         "drain_dropped_requests": 3,
                         "drain_corrupted_sessions": 1,
                         "join_compiles_must_be_zero": 2,
                         "canary_failures_must_be_zero": 0,
                         "cpu_count": 8}}
    rows = {r[0]: r for r in bench_compare.compare(base, worse)}
    assert bench_compare._direction(
        "results.fleet3_aggregate_rps") == "higher"
    assert bench_compare._exact_zero("results.drain_dropped_requests")
    assert bench_compare._exact_zero("results.join_compiles_must_be_zero")
    assert not bench_compare._exact_zero("results.fleet_scale_speedup")
    assert rows["results.fleet3_aggregate_rps"][4]  # fan-out collapsed
    assert rows["results.fleet_scale_speedup"][4]
    # exact gates: ANY nonzero regresses, zero baseline notwithstanding
    assert rows["results.drain_dropped_requests"][4]
    assert rows["results.drain_corrupted_sessions"][4]
    assert rows["results.join_compiles_must_be_zero"][4]
    assert not rows["results.canary_failures_must_be_zero"][4]
    assert "results.cpu_count" not in rows  # a host fact, not a speed
    assert not any(r[4] for r in bench_compare.compare(base, base))


@pytest.mark.slow
def test_autotune_bench_smoke(tmp_path):
    from mxnet_tpu.benchmark import autotune_bench

    out = str(tmp_path / "autotune.json")
    doc = autotune_bench.run(smoke=True, out_path=out)
    assert doc["smoke"] is True
    # structural contracts at any scale: both families swept, each
    # sweep persisted a record that consults back to the stored choice
    # (asserted inside the bench), and the tuner actually measured.
    # The >=1.0 / >1.05 tuned_vs_default gates are timing properties
    # only enforced on the committed full run (BENCH_AUTOTUNE_r24.json)
    # — smoke shapes have no bandwidth cliff to find.
    assert set(doc["families"]) == {"elementwise_bandwidth",
                                    "attn_compute_bound"}
    for row in doc["families"].values():
        assert row["sweep"], row  # at least one candidate measured
        assert row["tuned_vs_default"] > 0
        point_candidates = [m["choice"] for m in row["sweep"]]
        assert row["choice"] in point_candidates \
            or row["choice"] == row["default_choice"]
    assert doc["counters"]["measurements"] >= 2
    assert doc["counters"]["record_store"] == 2
    with open(out) as f:
        assert json.load(f)["benchmark"] == "autotune"


def test_bench_compare_autotune_metrics():
    """BENCH_AUTOTUNE_r24.json names: tuned_vs_default is
    higher-is-better (below 1.0 means a persisted record made the
    workload SLOWER than the heuristic), tune_ms lower-is-better,
    choices/counters untracked (a config fact, not a speed)."""
    base = {"families": {"elementwise_bandwidth": {
                "choice": 24, "tuned_vs_default": 4.9,
                "tune_ms": 21540.0},
            "attn_compute_bound": {
                "choice": 64, "tuned_vs_default": 1.0,
                "tune_ms": 830.0}},
            "counters": {"measurements": 8}}
    worse = {"families": {"elementwise_bandwidth": {
                "choice": 24, "tuned_vs_default": 0.8,
                "tune_ms": 60000.0},
            "attn_compute_bound": {
                "choice": 64, "tuned_vs_default": 1.0,
                "tune_ms": 830.0}},
             "counters": {"measurements": 8}}
    rows = {r[0]: r for r in bench_compare.compare(base, worse)}
    assert bench_compare._direction(
        "families.elementwise_bandwidth.tuned_vs_default") == "higher"
    assert bench_compare._direction(
        "families.elementwise_bandwidth.tune_ms") == "lower"
    # a record that used to win 4.9x now LOSES to the default: REGRESSED
    assert rows["families.elementwise_bandwidth.tuned_vs_default"][4]
    assert rows["families.elementwise_bandwidth.tune_ms"][4]
    assert not rows["families.attn_compute_bound.tuned_vs_default"][4]
    assert "families.elementwise_bandwidth.choice" not in rows
    assert "counters.measurements" not in rows
    assert not any(r[4] for r in bench_compare.compare(base, base))


def test_bench_compare_cli_exit_codes(tmp_path):
    base, new_ok, new_bad = (str(tmp_path / n) for n in
                             ("base.json", "ok.json", "bad.json"))
    with open(base, "w") as f:
        json.dump(_doc(1.0, 4.0), f)
    with open(new_ok, "w") as f:
        json.dump(_doc(1.05, 4.1), f)
    with open(new_bad, "w") as f:
        json.dump(_doc(2.0, 1.5), f)
    script = os.path.join(_REPO, "tools", "bench_compare.py")
    ok = subprocess.run([sys.executable, script, base, new_ok],
                        capture_output=True, text=True)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    bad = subprocess.run([sys.executable, script, base, new_bad],
                         capture_output=True, text=True)
    assert bad.returncode == 1
    assert "REGRESSED" in bad.stdout
