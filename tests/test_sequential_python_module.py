"""SequentialModule + PythonModule/PythonLossModule (reference:
python/mxnet/module/{sequential_module,python_module}.py +
tests/python/unittest/test_module.py test_module_layout chains)."""
import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import nd, sym, io
from mxnet_tpu.module import (Module, PythonLossModule, SequentialModule)
from mxnet_tpu.test_utils import with_seed


def _data(n=128, seed=0):
    rs = onp.random.RandomState(seed)
    X = rs.randn(n, 6).astype("f")
    y = (X.sum(1) > 0).astype("f")
    return X, y


def _features_module():
    data = sym.Variable("data")
    fc = sym.FullyConnected(data, name="feat_fc", num_hidden=16)
    act = sym.Activation(fc, name="feat_act", act_type="relu")
    return Module(act, label_names=[], context=mx.cpu())


@with_seed(11)
def test_sequential_module_trains():
    X, y = _data()
    head_in = sym.Variable("data")
    out = sym.SoftmaxOutput(
        sym.FullyConnected(head_in, name="head_fc", num_hidden=2),
        sym.Variable("softmax_label"), name="softmax")
    seq = SequentialModule()
    seq.add(_features_module(), auto_wiring=True) \
       .add(Module(out, context=mx.cpu()), take_labels=True,
            auto_wiring=True)
    seq.bind(data_shapes=[("data", (32, 6))],
             label_shapes=[("softmax_label", (32,))])
    seq.init_params(mx.init.Xavier())
    seq.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05,
                                         "momentum": 0.9})
    metric = mx.metric.Accuracy()
    it = io.NDArrayIter(X, y, batch_size=32, shuffle=True)
    for epoch in range(6):
        it.reset()
        metric.reset()
        for batch in it:
            seq.forward(batch, is_train=True)
            seq.backward()
            seq.update()
            seq.update_metric(metric, batch.label)
    assert metric.get()[1] > 0.9, metric.get()


@with_seed(12)
def test_python_loss_module_chain():
    """PythonLossModule as chain head: python-computed softmax-CE grad
    flows back into the symbolic features module."""
    X, y = _data(seed=5)
    scores_in = sym.Variable("data")
    scores = sym.FullyConnected(scores_in, name="sc_fc", num_hidden=2)
    seq = SequentialModule()
    seq.add(Module(scores, label_names=[], context=mx.cpu()),
            auto_wiring=True) \
       .add(PythonLossModule(data_names=("data",),
                             label_names=("softmax_label",)),
            take_labels=True, auto_wiring=True)
    seq.bind(data_shapes=[("data", (64, 6))],
             label_shapes=[("softmax_label", (64,))])
    seq.init_params(mx.init.Xavier())
    seq.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05})
    it = io.NDArrayIter(X, y, batch_size=64)
    def nll():
        it.reset()
        tot = n = 0
        for batch in it:
            seq.forward(batch, is_train=False)
            s = seq.get_outputs()[0].asnumpy()
            e = onp.exp(s - s.max(1, keepdims=True))
            p = e / e.sum(1, keepdims=True)
            lab = batch.label[0].asnumpy().astype(int)
            tot += -onp.log(p[onp.arange(len(lab)), lab] + 1e-9).sum()
            n += len(lab)
        return tot / n

    first = nll()
    for _ in range(40):
        it.reset()
        for batch in it:
            seq.forward(batch, is_train=True)
            seq.backward()
            seq.update()
    assert nll() < first * 0.6, (first, nll())
