"""Round-19 int8 serving: quantized SymbolBlocks behind
InferenceSession, int8/fp32 AOT fingerprint coexistence, and the
canary-gated rollout with the MXNET_QUANTIZE_SHADOW accuracy gate —
the ISSUE acceptance scenario: an int8 canary that answers fast but
WRONG (injected accuracy regression) rolls back automatically with
zero client-visible failures, and a clean int8 canary auto-promotes."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, serving
from mxnet_tpu.contrib.quantization import quantize_net_graph
from mxnet_tpu.gluon import nn
from mxnet_tpu.serving.repository import _rel_deviation

nd = mx.nd


def _mlp(seed=0):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    with autograd.pause(train_mode=False):
        net(nd.zeros((1, 8)))
    return net


def _quantized(net):
    calib = [nd.array(onp.random.RandomState(i).rand(4, 8)
                      .astype("float32")) for i in range(3)]
    return quantize_net_graph(net, calib_data=calib, calib_mode="naive")


def _session(block, **kw):
    return serving.InferenceSession(block, input_shapes=[(1, 8)],
                                    buckets=[1, 2, 4], **kw)


def _x(seed, rows=1):
    return onp.random.RandomState(seed).rand(rows, 8).astype("float32")


def _ref(net, x):
    with autograd.pause(train_mode=False):
        return net(nd.array(x)).asnumpy()


@pytest.fixture(autouse=True)
def _fresh_counters():
    serving.reset_serving_counters()
    yield
    serving.reset_serving_counters()


def _wait_state(repo, name, state, timeout_s=10.0):
    import time

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        st = repo.model_states()[name]
        if st["state"] == state:
            return st
        time.sleep(0.01)
    raise AssertionError(
        f"model {name} never reached {state!r}: "
        f"{repo.model_states()[name]}")


class _Corrupt:
    """An int8 rollout gone numerically wrong: executes fine (no
    exceptions, no latency), answers garbage — invisible to the
    failure and latency canary checks, only the shadow gate sees it."""

    def __init__(self, inner, scale=8.0):
        self._inner = inner
        self._scale = scale

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def predict(self, *arrs):
        out = self._inner.predict(*arrs)
        if isinstance(out, (list, tuple)):
            return type(out)(o * self._scale for o in out)
        return out * self._scale


# ---------------------------------------------------------------------------
# quantized graphs behind InferenceSession

def test_session_serves_quantized_graph_accurately():
    net = _mlp(3)
    qb = _quantized(net)
    with serving.ModelRepository(max_latency_ms=1.0, admission=False) as repo:
        repo.deploy("q", _session(qb))
        for i in range(3):
            out = repo.submit("q", _x(i)).result(timeout=30)
            dev = _rel_deviation(out, _ref(net, _x(i)))
            assert dev < 0.1, dev


def test_int8_fp32_fingerprints_coexist(monkeypatch):
    """The AOT disk keys for the fp32 and int8 versions of the SAME
    model must never collide, int8 keys are salted per lowering mode,
    and the fp32 key ignores the quantize knob entirely."""
    monkeypatch.delenv("MXNET_QUANTIZE_LOWERING", raising=False)
    net = _mlp(4)
    qb = _quantized(net)
    fs, qs = _session(net), _session(qb)
    try:
        fp32_fp = fs._fingerprint(2, 0)
        int8_fp = qs._fingerprint(2, 0)
        assert fp32_fp is not None and int8_fp is not None
        assert fp32_fp != int8_fp
        # the lowering knob re-keys int8 artifacts ...
        monkeypatch.setenv("MXNET_QUANTIZE_LOWERING", "native")
        int8_native = qs._fingerprint(2, 0)
        monkeypatch.setenv("MXNET_QUANTIZE_LOWERING", "dequant")
        int8_dequant = qs._fingerprint(2, 0)
        assert int8_native != int8_dequant
        # ... and leaves every fp32 key byte-stable
        assert fs._fingerprint(2, 0) == fp32_fp
        # different buckets stay distinct within each family
        assert qs._fingerprint(4, 0) != qs._fingerprint(2, 0)
    finally:
        for s in (fs, qs):
            close = getattr(s, "close", None)
            if close:
                close()


# ---------------------------------------------------------------------------
# canary-gated int8 rollout

def test_int8_canary_clean_run_auto_promotes(monkeypatch):
    """A good int8 canary under the shadow accuracy gate: every canary
    request is diffed against the incumbent, int8 deviation stays
    within MXNET_QUANTIZE_SHADOW_TOL, and the version auto-promotes."""
    monkeypatch.setenv("MXNET_QUANTIZE_SHADOW", "1.0")
    monkeypatch.setenv("MXNET_QUANTIZE_SHADOW_TOL", "0.1")
    net = _mlp(5)
    qb = _quantized(net)
    repo = serving.ModelRepository(canary_min_requests=6,
                                   canary_fraction=1.0,
                                   max_latency_ms=1.0, admission=False)
    try:
        repo.deploy("m", _session(net))
        assert repo.deploy("m", _session(qb)) == 2
        for i in range(6):
            out = repo.submit("m", _x(10 + i),
                              slo_class="standard").result(timeout=30)
            dev = _rel_deviation(out, _ref(net, _x(10 + i)))
            assert dev < 0.1, dev  # the client got a usable answer
        st = _wait_state(repo, "m", "serving")
        assert st["active_version"] == 2
        stats = serving.serving_stats()
        assert stats["canary_promotions"] == 1
        assert stats["canary_shadow_checks"] >= 1
        assert stats.get("canary_shadow_mismatches", 0) == 0
        assert stats["canary_rollbacks"] == 0
    finally:
        repo.close()


def test_int8_canary_accuracy_regression_rolls_back(monkeypatch):
    """The ISSUE acceptance scenario: an int8 canary with an injected
    accuracy regression executes without errors and at normal latency —
    only the shadow diff catches it. The breaker trips, the rollout
    rolls back, and no client request ever failed."""
    monkeypatch.setenv("MXNET_QUANTIZE_SHADOW", "1.0")
    monkeypatch.setenv("MXNET_QUANTIZE_SHADOW_TOL", "0.1")
    net = _mlp(6)
    qb = _quantized(net)
    repo = serving.ModelRepository(canary_threshold=3,
                                   canary_fraction=1.0,
                                   canary_min_requests=1000,
                                   max_latency_ms=1.0, admission=False)
    try:
        repo.deploy("m", _session(net))
        repo.deploy("m", _Corrupt(_session(qb)))
        futs = [repo.submit("m", _x(30 + i), slo_class="standard")
                for i in range(6)]
        for f in futs:
            f.result(timeout=30)  # no client-visible failure, ever
        st = _wait_state(repo, "m", "rolled_back")
        assert st["active_version"] == 1
        assert "shadow accuracy deviation" in st["last_transition"]
        stats = serving.serving_stats()
        assert stats["canary_rollbacks"] == 1
        assert stats["canary_shadow_mismatches"] >= 3
        assert stats["canary_failures"] == 0  # it never ERRORED
        # post-rollback traffic is the fp32 incumbent, bitwise
        out = repo.submit("m", _x(99)).result(timeout=30)
        assert onp.array_equal(out, _ref(net, _x(99)))
    finally:
        repo.close()


def test_shadow_disabled_by_default():
    """Without MXNET_QUANTIZE_SHADOW the gate costs nothing: no
    duplicate incumbent runs, no shadow counters."""
    net = _mlp(7)
    with serving.ModelRepository(canary_fraction=1.0,
                                 canary_min_requests=1000,
                                 max_latency_ms=1.0, admission=False) as repo:
        repo.deploy("m", _session(net))
        repo.deploy("m", _Corrupt(_session(_mlp(7))))
        for i in range(4):
            repo.submit("m", _x(i),
                        slo_class="standard").result(timeout=30)
        stats = serving.serving_stats()
        assert stats.get("canary_shadow_checks", 0) == 0
        assert repo.model_states()["m"]["state"] == "canary"
