"""Symbol + Executor + Module (reference suites:
tests/python/unittest/test_symbol.py, test_executor.py, test_module.py)."""
import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.io import NDArrayIter
from mxnet_tpu.module import Module


def _mlp_symbol(hidden=16, classes=4):
    data = sym.Variable("data")
    fc1 = sym.FullyConnected(data, name="fc1", num_hidden=hidden,
                             weight=sym.Variable("fc1_weight"),
                             bias=sym.Variable("fc1_bias"))
    act = sym.Activation(fc1, act_type="relu")
    fc2 = sym.FullyConnected(act, name="fc2", num_hidden=classes,
                             weight=sym.Variable("fc2_weight"),
                             bias=sym.Variable("fc2_bias"))
    label = sym.Variable("softmax_label")
    return sym.SoftmaxOutput(fc2, label, name="softmax")


def test_symbol_compose_and_arguments():
    s = _mlp_symbol()
    args = s.list_arguments()
    assert "data" in args and "fc1_weight" in args and \
        "softmax_label" in args


def test_symbol_eval():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = 2 * a + b
    out = c.eval(a=nd.array([1.0, 2.0]), b=nd.array([10.0, 20.0]))
    onp.testing.assert_allclose(out[0].asnumpy(), [12, 24])


def test_symbol_infer_shape():
    s = _mlp_symbol(hidden=16, classes=4)
    arg_shapes, out_shapes, _ = s.infer_shape(
        data=(8, 10), fc1_weight=(16, 10), fc1_bias=(16,),
        fc2_weight=(4, 16), fc2_bias=(4,), softmax_label=(8,))
    assert out_shapes == [(8, 4)]


def test_symbol_json_roundtrip(tmp_path):
    s = _mlp_symbol()
    f = str(tmp_path / "net-symbol.json")
    s.save(f)
    s2 = sym.load(f)
    assert set(s2.list_arguments()) == set(s.list_arguments())
    # same numeric behavior
    feed = {n: nd.array(onp.random.rand(*shape).astype("f"))
            for n, shape in [("data", (2, 10)), ("fc1_weight", (16, 10)),
                             ("fc1_bias", (16,)), ("fc2_weight", (4, 16)),
                             ("fc2_bias", (4,)), ("softmax_label", (2,))]}
    o1 = s.eval_with(dict(feed))
    o2 = s2.eval_with(dict(feed))
    onp.testing.assert_allclose(o1.asnumpy(), o2.asnumpy(), rtol=1e-5)


def test_executor_simple_bind_forward_backward():
    s = _mlp_symbol()
    exe = s.simple_bind(data=(8, 10), fc1_weight=(16, 10), fc1_bias=(16,),
                        fc2_weight=(4, 16), fc2_bias=(4,),
                        softmax_label=(8,))
    for name, arr in exe.arg_dict.items():
        if name.endswith("weight"):
            arr._data = nd.array(
                onp.random.rand(*arr.shape).astype("f") * 0.1).data
    exe.arg_dict["data"]._data = nd.array(
        onp.random.rand(8, 10).astype("f")).data
    exe.arg_dict["softmax_label"]._data = nd.array(
        onp.random.randint(0, 4, 8).astype("f")).data
    outs = exe.forward(is_train=True)
    assert outs[0].shape == (8, 4)
    onp.testing.assert_allclose(outs[0].asnumpy().sum(axis=1),
                                onp.ones(8), rtol=1e-5)
    exe.backward()
    g = exe.grad_dict["fc1_weight"].asnumpy()
    assert (onp.abs(g) > 0).any()


def test_module_fit_mlp():
    onp.random.seed(0)
    centroids = onp.random.randn(4, 10).astype("f") * 2
    y = onp.random.randint(0, 4, 128).astype("f")
    X = centroids[y.astype(int)] + \
        0.3 * onp.random.randn(128, 10).astype("f")
    train_iter = NDArrayIter(X, y, batch_size=32, shuffle=True,
                             label_name="softmax_label")

    mod = Module(_mlp_symbol(hidden=32, classes=4))
    mod.fit(train_iter, num_epoch=12,
            optimizer_params={"learning_rate": 0.5})
    score = mod.score(train_iter, "acc")
    assert score[0][1] > 0.8, f"accuracy {score}"


def test_module_predict_and_checkpoint(tmp_path):
    onp.random.seed(1)
    X = onp.random.rand(64, 10).astype("f")
    y = onp.random.randint(0, 4, 64).astype("f")
    it = NDArrayIter(X, y, batch_size=16)
    mod = Module(_mlp_symbol(hidden=8, classes=4))
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    pred = mod.predict(it)
    assert pred.shape == (64, 4)
    prefix = str(tmp_path / "ck")
    mod.init_optimizer()
    mod.save_checkpoint(prefix, 3)
    symbol, arg_params, aux_params = mx.model.load_checkpoint(prefix, 3)
    assert "fc1_weight" in arg_params
    # reload into a fresh module and check predictions match
    mod2 = Module(symbol)
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod2.init_params(arg_params=arg_params, aux_params=aux_params)
    pred2 = mod2.predict(it)
    onp.testing.assert_allclose(pred.asnumpy(), pred2.asnumpy(), rtol=1e-5)


def test_auto_created_param_variables():
    """Omitted weight/bias become variables named {node}_{arg}
    (reference: NNVM composition fills missing inputs)."""
    data = sym.Variable("data")
    fc = sym.FullyConnected(data, name="fc1", num_hidden=8)
    args = fc.list_arguments()
    assert args == ["data", "fc1_weight", "fc1_bias"]
    # no_bias drops the bias input entirely
    fc2 = sym.FullyConnected(data, name="fc2", num_hidden=8, no_bias=True)
    assert fc2.list_arguments() == ["data", "fc2_weight"]
    # shapes infer from data like the reference
    exe = fc.simple_bind(data=(4, 6))
    assert dict(zip(exe.arg_names,
                    [a.shape for a in exe.arg_arrays]))["fc1_weight"] \
        == (8, 6)
    out = exe.forward()[0]
    assert out.shape == (4, 8)


def test_auto_created_batchnorm_params():
    data = sym.Variable("data")
    conv = sym.Convolution(data, name="c0", kernel=(3, 3), num_filter=4,
                           pad=(1, 1))
    bn = sym.BatchNorm(conv, name="bn0")
    # running stats are AUXILIARY states, not optimizer-visible arguments
    # (reference: BN's FMutateInputs; Module must never train them)
    assert bn.list_arguments() == ["data", "c0_weight", "c0_bias",
                                   "bn0_gamma", "bn0_beta"]
    assert bn.list_auxiliary_states() == ["bn0_moving_mean",
                                          "bn0_moving_var"]
    exe = bn.simple_bind(data=(2, 3, 8, 8))
    assert exe.aux_dict["bn0_moving_var"].shape == (4,)
    # moving_var initializes to ONES (rsqrt(0) would be inf)
    onp.testing.assert_array_equal(
        exe.aux_dict["bn0_moving_var"].asnumpy(), onp.ones(4, "f"))
    out = exe.forward(is_train=False)[0]
    assert out.shape == (2, 4, 8, 8)


def test_auto_created_deconv_respects_no_bias_default():
    data = sym.Variable("data")
    d = sym.Deconvolution(data, name="d0", kernel=(2, 2), num_filter=4)
    # deconvolution defaults no_bias=True: no phantom bias argument
    assert d.list_arguments() == ["data", "d0_weight"]


def test_bn_aux_states_update_and_drive_inference():
    """Training forwards fold batch statistics into moving_mean/var;
    inference normalizes WITH them (reference: BN FMutateInputs +
    is_train gating in batch_norm.cc)."""
    data = sym.Variable("data")
    bn = sym.BatchNorm(data, name="bn0", momentum=0.5)
    exe = bn.simple_bind(data=(64, 3))
    rng = onp.random.RandomState(0)
    x = (rng.rand(64, 3).astype("f") * 4.0 + 10.0)  # mean ~12, var ~1.3
    exe.arg_dict["data"][:] = nd.array(x)
    m0 = exe.aux_dict["bn0_moving_mean"].asnumpy().copy()
    for _ in range(8):
        exe.forward(is_train=True)
    m1 = exe.aux_dict["bn0_moving_mean"].asnumpy()
    v1 = exe.aux_dict["bn0_moving_var"].asnumpy()
    assert not onp.allclose(m0, m1), "moving_mean never updated"
    # after several steps the moving stats approach the batch stats
    onp.testing.assert_allclose(m1, x.mean(0), rtol=0.1)
    onp.testing.assert_allclose(v1, x.var(0), rtol=0.3, atol=0.2)
    # inference normalizes with the moving stats, not the batch's
    out = exe.forward(is_train=False)[0].asnumpy()
    expect = (x - m1) / onp.sqrt(v1 + 1e-3)
    onp.testing.assert_allclose(out, expect, rtol=1e-2, atol=1e-2)


def test_bn_eager_follows_autograd_mode():
    from mxnet_tpu import autograd

    rng = onp.random.RandomState(1)
    x = nd.array(rng.rand(8, 4).astype("f") + 3.0)
    g, b = nd.ones(4), nd.zeros(4)
    mm, mv = nd.zeros(4), nd.ones(4)
    # outside record: moving stats (mean 0, var 1) -> out ~ x
    out_inf = nd.batch_norm(x, g, b, mm, mv, eps=1e-5,
                            fix_gamma=False).asnumpy()
    onp.testing.assert_allclose(out_inf, x.asnumpy(), rtol=1e-4,
                                atol=1e-4)
    # under record(train_mode=True): batch stats -> zero mean
    with autograd.record():
        out_tr = nd.batch_norm(x, g, b, mm, mv, eps=1e-5,
                               fix_gamma=False).asnumpy()
    assert abs(out_tr.mean()) < 1e-5


def test_bn_use_global_stats_never_updates_aux():
    """Frozen BN (use_global_stats=True) must keep its running stats
    untouched by training forwards (reference batch_norm.cc)."""
    data = sym.Variable("data")
    bn = sym.BatchNorm(data, name="bn0", use_global_stats=True)
    exe = bn.simple_bind(data=(16, 3))
    exe.arg_dict["data"][:] = nd.array(
        onp.random.RandomState(0).rand(16, 3).astype("f") * 5 + 7)
    before = exe.aux_dict["bn0_moving_mean"].asnumpy().copy()
    for _ in range(3):
        exe.forward(is_train=True)
    onp.testing.assert_array_equal(
        exe.aux_dict["bn0_moving_mean"].asnumpy(), before)


def test_legacy_opname_json_interop():
    """Reference-era JSON graphs carrying legacy / underscore-prefixed
    nnvm op names (BatchNorm_v1, _slice_assign_scalar, ...) load and
    evaluate (r5 alias table; SURVEY §7 checkpoint-interop)."""
    import json

    import numpy as onp

    from mxnet_tpu import nd
    import mxnet_tpu.symbol as S

    js = json.dumps({
        "nodes": [
            {"op": "null", "name": "data", "inputs": []},
            {"op": "null", "name": "bn_gamma", "inputs": []},
            {"op": "null", "name": "bn_beta", "inputs": []},
            {"op": "null", "name": "bn_moving_mean", "inputs": []},
            {"op": "null", "name": "bn_moving_var", "inputs": []},
            {"op": "BatchNorm_v1", "name": "bn",
             "attrs": {"fix_gamma": "True"},
             "inputs": [[0, 0, 0], [1, 0, 0], [2, 0, 0], [3, 0, 1],
                        [4, 0, 1]]},
            {"op": "_slice_assign_scalar", "name": "sa",
             "attrs": {"begin": "(0,)", "end": "(1,)", "scalar": "9.0"},
             "inputs": [[5, 0, 0]]},
        ],
        "arg_nodes": [0, 1, 2, 3, 4],
        "node_row_ptr": list(range(8)),
        "heads": [[6, 0, 0]],
        "attrs": {"mxnet_version": ["int", 10500]},
    })
    symb = S.load_json(js)
    feed = {"data": nd.array(onp.ones((2, 3), "f")),
            "bn_gamma": nd.array(onp.ones(3, "f")),
            "bn_beta": nd.array(onp.zeros(3, "f")),
            "bn_moving_mean": nd.array(onp.zeros(3, "f")),
            "bn_moving_var": nd.array(onp.ones(3, "f"))}
    out = symb.eval_with(feed)
    assert out.asnumpy()[0, 0] == 9.0
