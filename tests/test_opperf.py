"""Per-op perf harness (reference: benchmark/opperf/ — here a smoke of
the measurement contract, not a perf assertion: timings exist, flops
columns appear where defined, subsets and unknown ops behave)."""
import numpy as onp
import pytest

from mxnet_tpu import nd
from mxnet_tpu.benchmark import run_performance_test, run_op_suite


def test_run_performance_test_contract():
    r = run_performance_test(lambda a, b: nd.dot(a, b),
                             inputs=[(64, 64), (64, 64)],
                             flops=2 * 64 ** 3, runs=2, warmup=1)
    assert r["fwd_ms"] > 0 and r["fwd_bwd_ms"] > 0
    assert r["fwd_gflops"] > 0
    assert r["inputs"] == [[64, 64], [64, 64]]


def test_run_performance_test_bf16_and_no_backward():
    r = run_performance_test(lambda a: nd.exp(a), inputs=[(32, 32)],
                             dtype="bfloat16", run_backward=False,
                             runs=2, warmup=1)
    assert r["dtype"] == "bfloat16"
    assert "fwd_bwd_ms" not in r


def test_suite_subset_and_unknown():
    out = run_op_suite(["dot", "softmax"], runs=2, warmup=1)
    assert [r["op"] for r in out] == ["dot", "softmax"]
    with pytest.raises(ValueError, match="unknown suite ops"):
        run_op_suite(["definitely_not_an_op"])
