"""Golden tests for the static-analysis subsystem (mxnet_tpu.analysis).

One seeded defect per diagnostic code, each caught under
MXNET_GRAPH_VERIFY=error through the real integration point where
possible (bind, dispatch cache, shard_params) — the acceptance contract
of the analysis ISSUE: shape mismatch (GV101), dtype mismatch (GV102),
use-after-donate (GV201), double donation (GV202), PRNG key reuse
(GV301), dead node (GV401), duplicate name (GV403), sharding mismatch
(GV501) / mesh mismatch (GV502)."""
import logging

import numpy as onp
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import analysis, autograd, gluon, nd, sym
from mxnet_tpu import random as mxrandom
from mxnet_tpu.analysis import GraphVerifyError


@pytest.fixture
def verify_error(monkeypatch):
    monkeypatch.setenv("MXNET_GRAPH_VERIFY", "error")


@pytest.fixture
def verify_warn(monkeypatch):
    monkeypatch.setenv("MXNET_GRAPH_VERIFY", "warn")


# ------------------------------------------------------------- GV101 ------

def test_shape_mismatch_caught_on_bind(verify_error):
    """Declared parameter shape contradicting the consuming layer's
    requirement fails AT BIND with a diagnostic naming the parameter."""
    data = sym.var("data")
    w = sym.var("w_bad", shape=(10, 5))  # fc wants (8, 5)
    net = sym.fully_connected(data, weight=w, num_hidden=8, name="fc")
    with pytest.raises(GraphVerifyError) as ei:
        net.simple_bind(data=(4, 5))
    assert "GV101" in ei.value.report.codes()
    assert any("w_bad" in (d.node or "") for d in ei.value.report)


def test_shape_mismatch_bound_vs_declared(verify_error):
    """A bound array disagreeing with the Variable(shape=...) declaration
    is caught before any compilation."""
    data = sym.var("data", shape=(2, 3))
    net = sym.relu(data, name="r")
    rep = analysis.verify_symbol(net, shapes={"data": (4, 3)})
    assert "GV101" in rep.codes()
    with pytest.raises(GraphVerifyError):
        rep.disposition()


def test_clean_graph_has_no_diagnostics(verify_error):
    data = sym.var("data")
    net = sym.fully_connected(data, num_hidden=8, name="fc_ok")
    ex = net.simple_bind(data=(4, 5))  # must NOT raise
    assert ex.forward()[0].shape == (4, 8)


def test_shape_inference_failure_is_gv101(verify_error):
    data = sym.var("data")
    net = sym.split(data, num_outputs=3, name="sp3")  # axis 1 size 4: 4 % 3 != 0
    rep = analysis.verify_symbol(net[0], shapes={"data": (6, 4)})
    assert "GV101" in rep.codes()


# ------------------------------------------------------------- GV102 ------

def test_dtype_mismatch_declared_vs_bound(verify_error):
    data = sym.var("data", dtype="int32")
    net = sym.relu(data, name="r2")
    rep = analysis.verify_symbol(net, shapes={"data": (2, 2)},
                                 dtypes={"data": onp.float32})
    assert "GV102" in rep.codes()
    with pytest.raises(GraphVerifyError):
        rep.disposition()


# ------------------------------------------------------------- GV201 ------

def test_use_after_donate_dispatch_guard(verify_error, monkeypatch):
    """MXNET_EAGER_JIT_DONATE + a tape node still holding the out=
    buffer: the dispatch cache's donation guard raises instead of
    letting XLA delete a buffer backward will read."""
    monkeypatch.setenv("MXNET_EAGER_JIT_DONATE", "1")
    a = nd.ones((4,))
    a.attach_grad()
    with autograd.record():
        b = a * a  # tape node holds a's buffer as a saved primal
    with pytest.raises(GraphVerifyError) as ei:
        nd.broadcast_add_scalar(a, scalar=1.0, out=a)
    assert "GV201" in ei.value.report.codes()
    # the tape is intact: backward still works
    b.backward()
    onp.testing.assert_allclose(a.grad.asnumpy(), 2 * onp.ones(4))


def test_use_after_donate_in_trace(verify_error):
    """Trace front end: a snapshot alias read after an in-place op
    rebound/donated the buffer."""
    with analysis.record_trace("uad") as tr:
        a = nd.ones((4,))
        snap = nd.NDArray(a.data)  # aliases a's buffer
        nd.broadcast_add_scalar(a, scalar=1.0, out=a)
        z = snap + 1  # reads the donated buffer
        tr.mark_outputs([z])
    rep = analysis.verify_trace(tr, passes=("donation",))
    assert "GV201" in rep.codes()
    with pytest.raises(GraphVerifyError):
        rep.disposition()


def test_donation_guard_allows_clean_inplace(verify_error, monkeypatch):
    monkeypatch.setenv("MXNET_EAGER_JIT_DONATE", "1")
    a = nd.ones((4,))
    for _ in range(3):  # no live aliases: donation is safe, no raise
        nd.broadcast_add_scalar(a, scalar=1.0, out=a)
    onp.testing.assert_allclose(a.asnumpy(), 4 * onp.ones(4))


# ------------------------------------------------------------- GV202 ------

def test_double_donation_synthetic_trace(verify_error):
    tr = analysis.GraphTrace("dd")
    tr.add("fused_axpy", inputs=(1, 2), outputs=(3,), donated=(1, 1))
    rep = analysis.verify_trace(tr, passes=("donation",))
    assert "GV202" in rep.codes()


def test_fused_step_param_donation_guard(verify_error, monkeypatch):
    """MXNET_FUSED_STEP_DONATE + a live tape referencing the parameters:
    the fused step refuses to donate them out from under backward."""
    monkeypatch.setenv("MXNET_FUSED_STEP_DONATE", "1")
    net = gluon.nn.Dense(3)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    x = nd.ones((2, 4))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(2)  # tape cleared by backward: fine
    with autograd.record():
        loss2 = net(x).sum()
    # backward NOT called: tape still holds the parameter buffers
    with pytest.raises(GraphVerifyError) as ei:
        trainer.step(2)
    assert "GV201" in ei.value.report.codes()


# ------------------------------------------------------------- GV301 ------

def test_prng_key_reuse_detected(verify_error):
    k = jax.random.PRNGKey(7)
    with analysis.record_trace("keys") as tr:
        with mxrandom.key_replayer([k, k]):
            x1 = nd.random_uniform(shape=(3,))
            x2 = nd.random_normal(shape=(3,))
        tr.mark_outputs([x1, x2])
    rep = analysis.verify_trace(tr, passes=("key_reuse",))
    assert "GV301" in rep.codes()
    with pytest.raises(GraphVerifyError):
        rep.disposition()


def test_distinct_keys_are_clean(verify_error):
    with analysis.record_trace("keys2") as tr:
        x1 = nd.random_uniform(shape=(3,))
        x2 = nd.random_uniform(shape=(3,))
        tr.mark_outputs([x1, x2])
    assert analysis.verify_trace(tr, passes=("key_reuse",)).codes() == []


def test_verify_does_not_shift_prng_stream(monkeypatch):
    """Arming MXNET_GRAPH_VERIFY must not change the keys a seeded run
    draws: the hybridize verification forward is throwaway, so the
    global stream is restored after it."""
    def seeded_draws(mode):
        monkeypatch.setenv("MXNET_GRAPH_VERIFY", mode)
        mx.random.seed(1234)
        net = gluon.nn.Sequential()
        net.add(gluon.nn.Dense(4), gluon.nn.Dropout(0.5))
        net.initialize()
        net.hybridize()
        net(nd.ones((2, 3)))  # triggers (possibly verified) cache build
        return nd.random_uniform(shape=(5,)).asnumpy()

    off = seeded_draws("0")
    on = seeded_draws("warn")
    onp.testing.assert_array_equal(off, on)


def test_verify_does_not_double_update_batchnorm_stats(monkeypatch):
    """The throwaway verification forward must not mutate model state:
    BatchNorm running stats after the first training step are identical
    with verification on and off."""
    def first_step_stats(mode):
        monkeypatch.setenv("MXNET_GRAPH_VERIFY", mode)
        mx.random.seed(5)
        # explicit (identical) prefixes: the auto-name counters advance
        # per process, so the two runs would otherwise disagree on
        # parameter names
        net = gluon.nn.Sequential(prefix="bnv_")
        net.add(gluon.nn.Dense(4, prefix="bnv_d_"),
                gluon.nn.BatchNorm(prefix="bnv_b_"))
        net.initialize()
        net.hybridize()
        x = nd.array(onp.random.RandomState(0).randn(8, 3).astype("f"))
        with autograd.record():
            net(x).sum().backward()
        stats = {name: p.data().asnumpy()
                 for name, p in net.collect_params().items()
                 if "running" in name or "moving" in name}
        assert stats, "no BN stats found"
        return stats

    off = first_step_stats("0")
    on = first_step_stats("warn")
    for name in off:
        onp.testing.assert_array_equal(off[name], on[name])


def test_out_without_input_alias_is_not_donation(verify_error):
    """out= to a fresh destination is a write, not a donation: a live
    alias of the destination's OLD buffer must not trip GV201."""
    with analysis.record_trace("w") as tr:
        a, b = nd.ones((4,)), nd.ones((4,))
        c = nd.zeros((4,))
        view = nd.NDArray(c.data)  # alias of c's pre-write buffer
        nd.broadcast_add(a, b, out=c)  # c is NOT an input: no donation
        z = view + 1
        tr.mark_outputs([z, c])
    assert analysis.verify_trace(tr, passes=("donation",)).codes() == []


def test_hybridize_verify_runs_clean_with_dropout(verify_error):
    """verify-on-hybridize records a forward through a stochastic block;
    a correctly key-split dropout emits nothing and behavior is intact."""
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(4), gluon.nn.Dropout(0.5))
    net.initialize()
    net.hybridize()
    y = net(nd.ones((2, 3)))
    assert y.shape == (2, 4)


# ------------------------------------------------------------- GV401 ------

def test_dead_outputs_detected(verify_error):
    data = sym.var("data")
    parts = sym.split(data, num_outputs=3, name="sp")
    net = sym.relu(parts[0], name="keep")
    rep = analysis.verify_symbol(net, shapes={"data": (6, 6)})
    assert "GV401" in rep.codes()
    (diag,) = rep.by_code("GV401")
    assert "[1, 2]" in diag.message
    with pytest.raises(GraphVerifyError):
        rep.disposition()  # error mode raises on warnings too


def test_consumed_outputs_are_live(verify_error):
    data = sym.var("data")
    parts = sym.split(data, num_outputs=2, name="sp2")
    net = parts[0] + parts[1]
    rep = analysis.verify_symbol(net, shapes={"data": (6, 6)})
    assert rep.by_code("GV401") == []


# ------------------------------------------------------------- GV403 ------

def test_duplicate_node_names(verify_error):
    a = sym.var("x")
    n1 = sym.relu(a, name="same")
    n2 = sym.sigmoid(n1, name="same")
    rep = analysis.verify_symbol(n2, shapes={"x": (2, 2)})
    assert "GV403" in rep.codes()


# ------------------------------------------------------- GV501 / GV502 ----

def test_sharding_mismatch_through_shard_params(verify_error):
    from mxnet_tpu import parallel

    mesh = parallel.make_mesh({"dp": jax.device_count()})
    params = {"w": nd.ones((5, 4))}  # 5 % 8 != 0
    with pytest.raises(GraphVerifyError) as ei:
        parallel.shard_params(params, mesh, rules={"w": ("dp", None)})
    assert "GV501" in ei.value.report.codes()


def test_sharding_unknown_axis(verify_error):
    from jax.sharding import PartitionSpec as P

    from mxnet_tpu import parallel

    mesh = parallel.make_mesh({"dp": jax.device_count()})
    rep = analysis.verify_shardings({"w": (16, 4)}, {"w": P("tp")},
                                    mesh=mesh)
    assert "GV501" in rep.codes()


def test_mesh_mismatch(verify_error):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mxnet_tpu import parallel

    devs = jax.devices()
    m1 = parallel.make_mesh({"dp": len(devs)})
    m2 = parallel.make_mesh({"mp": 2}, devices=devs[:2])
    rep = analysis.verify_shardings(
        {"a": (16, 4), "b": (16, 4)},
        {"a": NamedSharding(m1, P("dp")), "b": NamedSharding(m2, P("mp"))})
    assert "GV502" in rep.codes()


def test_valid_shardings_clean(verify_error):
    from mxnet_tpu import parallel

    mesh = parallel.make_mesh({"dp": jax.device_count()})
    params = {"w": nd.ones((16, 4)), "b": nd.ones((4,))}
    sh = parallel.shard_params(params, mesh, rules={"^w$": ("dp", None)})
    assert set(sh) == {"w", "b"}


# --------------------------------------------------------- modes/surface --

def test_warn_mode_logs_instead_of_raising(verify_warn, caplog):
    data = sym.var("data", shape=(2, 3))
    net = sym.relu(data, name="rw")
    with caplog.at_level(logging.WARNING):
        rep = analysis.verify_symbol(net, shapes={"data": (4, 3)})
        rep.disposition()  # must NOT raise
    assert any("GV101" in r.message for r in caplog.records)


def test_off_mode_skips_verification(monkeypatch):
    monkeypatch.setenv("MXNET_GRAPH_VERIFY", "0")
    data = sym.var("data")
    w = sym.var("w_off", shape=(10, 5))
    net = sym.fully_connected(data, weight=w, num_hidden=8, name="fc_off")
    # bind must not verify (shape conflict would raise under =error)...
    # but the conflicting declared shape DOES break real compilation, so
    # only assert the verifier stayed out of the way at bind time
    before = analysis.counters()["graphs_checked"]
    try:
        net.simple_bind(data=(4, 5))
    except Exception:
        pass
    assert analysis.counters()["graphs_checked"] == before


def test_counters_and_profiler_surface(verify_error):
    from mxnet_tpu import profiler

    before = analysis.counters()["graphs_checked"]
    data = sym.var("data")
    net = sym.relu(data, name="cnt")
    analysis.verify_symbol(net, shapes={"data": (2, 2)}).disposition()
    after = profiler.graph_verify_counters()
    assert after["graphs_checked"] == before + 1


def test_runtime_feature_flag(monkeypatch):
    from mxnet_tpu import runtime

    monkeypatch.setenv("MXNET_GRAPH_VERIFY", "warn")
    assert runtime.Features().is_enabled("GRAPH_VERIFY")
    monkeypatch.setenv("MXNET_GRAPH_VERIFY", "0")
    assert not runtime.Features().is_enabled("GRAPH_VERIFY")


def test_eval_shape_cross_check_runs_clean(verify_error):
    """Full-information graphs run the eval_shape desync pass; on a
    healthy registry it must agree with symbol/infer.py everywhere."""
    data = sym.var("data")
    h = sym.fully_connected(data, num_hidden=8, name="l1")
    h = sym.Activation(h, act_type="relu", name="a1")
    out = sym.fully_connected(h, num_hidden=3, name="l2")
    rep = analysis.verify_symbol(out, shapes={"data": (4, 6)})
    assert rep.by_code("GV103") == []


def test_report_structure():
    rep = analysis.DiagnosticReport("s")
    d = rep.emit("GV101", "msg", node="n", hint="h")
    assert d.severity == analysis.SEV_ERROR
    assert rep.errors and not rep.warnings
    assert "GV101" in repr(d) and "hint" in repr(d)
    with pytest.raises(ValueError):
        rep.emit("GV999", "nope")
