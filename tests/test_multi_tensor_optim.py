"""Multi-tensor fused optimizer ops + aggregated Updater path.

Reference: src/operator/optimizer_op.cc MultiSGD(Mom)Update /
MultiMPSGD(Mom)Update, src/operator/contrib/preloaded_multi_sgd.cc,
contrib/multi_lars.cc, and python/mxnet/optimizer/optimizer.py
_update_impl(aggregate=True) + create_state_multi_precision.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import optimizer as opt

RTOL, ATOL = 1e-5, 1e-6


def _rand(shape, dtype="float32", seed=0):
    rng = onp.random.RandomState(seed)
    return (rng.rand(*shape).astype("float32") - 0.5).astype(dtype)


def test_multi_sgd_update_matches_single():
    ws = [_rand((4, 3), seed=i) for i in range(3)]
    gs = [_rand((4, 3), seed=10 + i) for i in range(3)]
    lrs, wds = [0.1, 0.2, 0.05], [0.01, 0.0, 0.1]
    ins = [nd.array(x) for pair in zip(ws, gs) for x in pair]
    outs = nd.multi_sgd_update(*ins, lrs=lrs, wds=wds, num_weights=3,
                               rescale_grad=0.5, clip_gradient=0.2)
    for i in range(3):
        single = nd.sgd_update(nd.array(ws[i]), nd.array(gs[i]), lrs[i],
                               wd=wds[i], rescale_grad=0.5,
                               clip_gradient=0.2)
        onp.testing.assert_allclose(outs[i].asnumpy(), single.asnumpy(),
                                    rtol=RTOL, atol=ATOL)


def test_multi_sgd_mom_update_matches_single():
    n = 3
    ws = [_rand((5,), seed=i) for i in range(n)]
    gs = [_rand((5,), seed=10 + i) for i in range(n)]
    ms = [_rand((5,), seed=20 + i) for i in range(n)]
    lrs, wds = [0.1] * n, [0.01] * n
    ins = [nd.array(x) for tri in zip(ws, gs, ms) for x in tri]
    outs = nd.multi_sgd_mom_update(*ins, lrs=lrs, wds=wds, momentum=0.9,
                                   num_weights=n)
    for i in range(n):
        w2, m2 = nd.sgd_mom_update(nd.array(ws[i]), nd.array(gs[i]),
                                   nd.array(ms[i]), lrs[i], momentum=0.9,
                                   wd=wds[i])
        onp.testing.assert_allclose(outs[i].asnumpy(), w2.asnumpy(),
                                    rtol=RTOL, atol=ATOL)
        onp.testing.assert_allclose(outs[n + i].asnumpy(), m2.asnumpy(),
                                    rtol=RTOL, atol=ATOL)


def test_multi_mp_sgd_mom_update_fp32_master():
    """Half weights advance through an fp32 master: after many tiny steps
    the master must accumulate what bf16 weights alone would drop."""
    n = 2
    w32 = [onp.ones((8,), "float32") for _ in range(n)]
    wh = [nd.array(w.astype("float32"), dtype="bfloat16") for w in w32]
    masters = [nd.array(w) for w in w32]
    moms = [nd.zeros((8,)) for _ in range(n)]
    g = onp.full((8,), 1e-3, "float32")
    for _ in range(10):
        ins = [x for j in range(n)
               for x in (wh[j], nd.array(g, dtype="bfloat16"), moms[j],
                         masters[j])]
        out = nd.multi_mp_sgd_mom_update(*ins, lrs=[0.01] * n,
                                         wds=[0.0] * n, momentum=0.0,
                                         num_weights=n)
        for j in range(n):
            wh[j], moms[j], masters[j] = out[j], out[n + j], out[2 * n + j]
    # 10 steps of -0.01*1e-3 = -1e-4 total; bf16 can't represent
    # 1 - 1e-5 per-step (eps≈7.8e-3) but the fp32 master can
    expect = 1.0 - 1e-4
    onp.testing.assert_allclose(masters[0].asnumpy(),
                                onp.full((8,), expect), rtol=1e-6)
    assert str(wh[0].dtype) == "bfloat16"


def test_preloaded_multi_sgd_update():
    n = 2
    ws = [_rand((3, 3), seed=i) for i in range(n)]
    gs = [_rand((3, 3), seed=5 + i) for i in range(n)]
    lrs = onp.array([0.1, 0.3], "float32")
    wds = onp.array([0.0, 0.02], "float32")
    ins = [nd.array(x) for pair in zip(ws, gs) for x in pair]
    outs = nd.preloaded_multi_sgd_update(
        *ins, nd.array(lrs), nd.array(wds), num_weights=n)
    for i in range(n):
        single = nd.sgd_update(nd.array(ws[i]), nd.array(gs[i]),
                               float(lrs[i]), wd=float(wds[i]))
        onp.testing.assert_allclose(outs[i].asnumpy(), single.asnumpy(),
                                    rtol=RTOL, atol=ATOL)


def test_preloaded_multi_sgd_mom_update():
    n = 2
    ws = [_rand((4,), seed=i) for i in range(n)]
    gs = [_rand((4,), seed=5 + i) for i in range(n)]
    ms = [_rand((4,), seed=9 + i) for i in range(n)]
    lrs = onp.array([0.1, 0.3], "float32")
    wds = onp.array([0.01, 0.0], "float32")
    ins = [nd.array(x) for tri in zip(ws, gs, ms) for x in tri]
    outs = nd.preloaded_multi_sgd_mom_update(
        *ins, nd.array(lrs), nd.array(wds), momentum=0.85, num_weights=n)
    for i in range(n):
        w2, m2 = nd.sgd_mom_update(nd.array(ws[i]), nd.array(gs[i]),
                                   nd.array(ms[i]), float(lrs[i]),
                                   momentum=0.85, wd=float(wds[i]))
        onp.testing.assert_allclose(outs[i].asnumpy(), w2.asnumpy(),
                                    rtol=RTOL, atol=ATOL)
        onp.testing.assert_allclose(outs[n + i].asnumpy(), m2.asnumpy(),
                                    rtol=RTOL, atol=ATOL)


def test_multi_lars_rates():
    lrs = nd.array(onp.array([0.1, 0.1, 0.1], "float32"))
    wsq = nd.array(onp.array([4.0, 0.0, 1.0], "float32"))
    gsq = nd.array(onp.array([1.0, 1.0, 0.0], "float32"))
    wds = nd.array(onp.array([0.0, 0.0, 0.0], "float32"))
    out = nd.multi_lars(lrs, wsq, gsq, wds, eta=0.1, eps=0.0).asnumpy()
    # layer 0: 0.1 * eta*||w||/||g|| = 0.1 * 0.1*2/1 = 0.02
    onp.testing.assert_allclose(out[0], 0.02, rtol=1e-5)
    # zero-norm weight or grad → keep base lr
    onp.testing.assert_allclose(out[1], 0.1, rtol=1e-5)
    onp.testing.assert_allclose(out[2], 0.1, rtol=1e-5)


def _run_updater(aggregate, n=6, steps=3, dtype="float32",
                 multi_precision=False):
    mx.random.seed(0)
    sgd = opt.SGD(learning_rate=0.1, momentum=0.9, wd=0.01,
                  multi_precision=multi_precision)
    if not aggregate:
        sgd.aggregate_num = 0
    upd = opt.get_updater(sgd)
    ws = [nd.array(_rand((7,), dtype=dtype, seed=i)) for i in range(n)]
    for step in range(steps):
        gs = [nd.array(_rand((7,), dtype=dtype, seed=100 + step * n + i))
              for i in range(n)]
        if aggregate:
            upd(list(range(n)), gs, ws)
        else:
            for i in range(n):
                upd(i, gs[i], ws[i])
    return [w.asnumpy().astype("float32") for w in ws]


def test_updater_aggregate_matches_sequential():
    agg = _run_updater(True)
    seq = _run_updater(False)
    for a, s in zip(agg, seq):
        onp.testing.assert_allclose(a, s, rtol=1e-5, atol=1e-6)


def test_updater_aggregate_multi_precision_bf16():
    agg = _run_updater(True, dtype="bfloat16", multi_precision=True)
    seq = _run_updater(False, dtype="bfloat16", multi_precision=True)
    for a, s in zip(agg, seq):
        onp.testing.assert_allclose(a, s, rtol=1e-2, atol=1e-3)


def test_updater_num_update_counting():
    sgd = opt.SGD(learning_rate=0.1)
    upd = opt.get_updater(sgd)
    ws = [nd.array(_rand((3,), seed=i)) for i in range(5)]
    gs = [nd.array(_rand((3,), seed=10 + i)) for i in range(5)]
    upd(list(range(5)), gs, ws)
    assert sgd.num_update == 1
    upd(list(range(5)), gs, ws)
    assert sgd.num_update == 2


def test_create_state_multi_precision_bf16_master():
    sgd = opt.SGD(learning_rate=0.1, momentum=0.9, multi_precision=True)
    w = nd.array(_rand((4,), dtype="bfloat16"))
    st = sgd.create_state_multi_precision(0, w)
    master, mom = st
    assert str(master.dtype) == "float32"
    assert str(mom.dtype) == "float32"
