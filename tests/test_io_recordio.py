"""RecordIO + image pipeline tests.

Reference coverage model: tests/python/unittest/test_recordio.py +
test_io.py (ImageRecordIter cases).
"""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio as rio

MAGIC = bytes.fromhex("0a23d7ce")  # little-endian 0xced7230a


@pytest.fixture(scope="module")
def img_pack(tmp_path_factory):
    from PIL import Image
    from mxnet_tpu.tools import im2rec as i2r

    tmp = tmp_path_factory.mktemp("rec")
    root = tmp / "imgs"
    for ci, cls in enumerate(["a", "b"]):
        (root / cls).mkdir(parents=True)
        for i in range(5):
            arr = onp.full((40 + 8 * i, 48, 3), 30 + 90 * ci,
                           dtype=onp.uint8)
            Image.fromarray(arr).save(root / cls / f"{i}.jpg")
    prefix = str(tmp / "ds")
    i2r.make_list(str(root), prefix, shuffle=False)
    n = i2r.im2rec(prefix + ".lst", str(root), prefix)
    assert n == 10
    return prefix


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "t.rec")
    payloads = [b"hello", b"x" * 37, b"A" + MAGIC + b"B", MAGIC * 3, b"",
                MAGIC + b"tail", b"head" + MAGIC]
    w = rio.MXRecordIO(path, "w")
    for p in payloads:
        w.write(p)
    w.close()
    r = rio.MXRecordIO(path, "r")
    for p in payloads:
        assert r.read() == p
    assert r.read() is None
    r.close()


def test_native_reader_parity(tmp_path):
    from mxnet_tpu import _native
    if _native.lib is None:
        pytest.skip("native lib unavailable")
    import ctypes

    path = str(tmp_path / "t.rec")
    payloads = [b"abc", MAGIC + b"x" + MAGIC, b"z" * 101]
    w = rio.MXRecordIO(path, "w")
    for p in payloads:
        w.write(p)
    w.close()
    h = _native.lib.rio_open(path.encode())
    out = ctypes.POINTER(ctypes.c_ubyte)()
    for p in payloads:
        n = _native.lib.rio_next(h, ctypes.byref(out))
        got = bytes(bytearray(out[:n])) if n > 0 else b""
        assert got == p
    assert _native.lib.rio_next(h, ctypes.byref(out)) == -1
    _native.lib.rio_close(h)


def test_indexed_recordio(tmp_path):
    path = str(tmp_path / "t.rec")
    idx = str(tmp_path / "t.idx")
    w = rio.MXIndexedRecordIO(idx, path, "w")
    for i in range(7):
        w.write_idx(i, b"rec%d" % i)
    w.close()
    r = rio.MXIndexedRecordIO(idx, path, "r")
    assert r.keys == list(range(7))
    assert r.read_idx(5) == b"rec5"
    assert r.read_idx(0) == b"rec0"
    r.close()


def test_pack_unpack_labels():
    h = rio.IRHeader(0, 3.5, 7, 0)
    blob = rio.pack(h, b"payload")
    h2, s = rio.unpack(blob)
    assert h2.label == 3.5 and h2.id == 7 and s == b"payload"
    # multi-label
    h = rio.IRHeader(0, [1.0, 2.0, 3.0], 9, 0)
    h2, s = rio.unpack(rio.pack(h, b"xy"))
    assert h2.flag == 3
    assert onp.allclose(h2.label, [1.0, 2.0, 3.0])
    assert s == b"xy"


def test_image_record_iter(img_pack):
    it = mx.io.ImageRecordIter(
        path_imgrec=img_pack + ".rec", path_imgidx=img_pack + ".idx",
        data_shape=(3, 32, 32), batch_size=4, shuffle=False)
    batches = list(it)
    assert sum(4 - b.pad for b in batches) == 10
    first = batches[0]
    assert first.data[0].shape == (4, 3, 32, 32)
    # class 'a' images are constant 30 (jpeg-lossy): first records
    v = first.data[0].asnumpy()[0].mean()
    assert abs(v - 30) < 3, v
    assert onp.allclose(first.label[0].asnumpy(), 0)
    # reset + iterate again works
    it.reset()
    assert sum(1 for _ in it) == len(batches)


def test_image_record_iter_augment(img_pack):
    it = mx.io.ImageRecordIter(
        path_imgrec=img_pack + ".rec", data_shape=(3, 24, 24), batch_size=2,
        shuffle=True, rand_crop=True, rand_mirror=True, resize=30,
        mean_r=127.0, mean_g=127.0, mean_b=127.0, std_r=64.0, std_g=64.0,
        std_b=64.0, seed=3)
    b = next(iter(it))
    x = b.data[0].asnumpy()
    assert x.shape == (2, 3, 24, 24)
    assert x.min() >= -2.1 and x.max() <= 2.1


def test_image_det_record_iter(img_pack):
    it = mx.io.ImageDetRecordIter(
        path_imgrec=img_pack + ".rec", data_shape=(3, 24, 24), batch_size=5,
        label_pad_width=8)
    b = next(iter(it))
    lab = b.label[0].asnumpy()
    assert lab.shape == (5, 8)
    assert (lab[:, 1:] == -1).all()  # single scalar label, rest padded


def test_libsvm_iter(tmp_path):
    svm = str(tmp_path / "d.libsvm")
    with open(svm, "w") as f:
        f.write("1 0:1.5 3:2.0\n0 1:1.0\n1 2:3.0 4:1.0\n")
    it = mx.io.LibSVMIter(data_libsvm=svm, data_shape=(5,), batch_size=2)
    b = next(iter(it))
    dense = b.data[0].asnumpy()
    assert onp.allclose(dense, [[1.5, 0, 0, 2.0, 0], [0, 1.0, 0, 0, 0]])
    assert onp.allclose(b.label[0].asnumpy(), [1, 0])


def test_native_python_decode_parity(img_pack):
    from mxnet_tpu import _native
    from mxnet_tpu.io.image_record import _decode_batch_python
    if _native.lib is None:
        pytest.skip("native lib unavailable")
    r = rio.MXIndexedRecordIO(img_pack + ".idx", img_pack + ".rec", "r")
    _, blob = rio.unpack(r.read_idx(r.keys[0]))
    r.close()
    it = mx.io.ImageRecordIter(
        path_imgrec=img_pack + ".rec", data_shape=(3, 32, 32), batch_size=1,
        resize=36)
    native = it._decode([blob], 32, 32, onp.full((1, 3), -1, onp.int32))
    native[0, :, :, :]  # shape check
    py = _decode_batch_python([blob], 32, 32, 36, [(-1, -1, 0)])
    # uniform-color images: decode paths must agree almost exactly
    assert abs(native.astype(int) - py.astype(int)).max() <= 2
