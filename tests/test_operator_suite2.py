"""Op-spec suite, part 2: indexing, NN core, legacy ops, random
sampling — numpy oracles + gradient checks.

Reference coverage model: tests/python/unittest/test_operator.py
(test_take/test_pick/test_one_hot/test_order/test_convolution_*/
test_pooling_*/test_softmax/test_sequence_*, test_random.py).
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.test_utils import (assert_almost_equal,
                                  check_numeric_gradient)

rs = onp.random.RandomState(13)


def _x(shape=(3, 4), lo=-2.0, hi=2.0):
    return (rs.rand(*shape) * (hi - lo) + lo).astype("f")


# -------------------------------------------------------------- indexing ---

def test_op_take_modes():
    x = _x((5, 3))
    idx = onp.array([0, 4, 2], "f")
    assert_almost_equal(nd.take(nd.array(x), nd.array(idx)).asnumpy(),
                        x[[0, 4, 2]], rtol=1e-6)
    big = onp.array([0, 7, -1], "f")
    out = nd.take(nd.array(x), nd.array(big), mode="clip")
    assert_almost_equal(out.asnumpy(), x[[0, 4, 0]], rtol=1e-6)
    wrap = nd.take(nd.array(x), nd.array(big), mode="wrap")
    assert_almost_equal(wrap.asnumpy(), x[[0, 2, 4]], rtol=1e-6)


def test_op_take_axis1_and_grad():
    x = _x((4, 6))
    idx = onp.array([1, 3], "f")
    out = nd.take(nd.array(x), nd.array(idx), axis=1)
    assert_almost_equal(out.asnumpy(), x[:, [1, 3]], rtol=1e-6)
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.sum(nd.take(a, nd.array(idx), axis=1))
    y.backward()
    expect = onp.zeros_like(x)
    expect[:, [1, 3]] = 1
    assert_almost_equal(a.grad.asnumpy(), expect, rtol=1e-6)


def test_op_pick():
    x = _x((3, 5))
    idx = onp.array([0, 2, 4], "f")
    out = nd.pick(nd.array(x), nd.array(idx), axis=1)
    assert_almost_equal(out.asnumpy(), x[onp.arange(3), [0, 2, 4]],
                        rtol=1e-6)
    outk = nd.pick(nd.array(x), nd.array(idx), axis=1, keepdims=True)
    assert outk.shape == (3, 1)


def test_op_gather_scatter_nd():
    x = _x((3, 4))
    indices = onp.array([[0, 2], [1, 3]], "f")  # 2 points (row, col)
    out = nd.gather_nd(nd.array(x), nd.array(indices))
    assert_almost_equal(out.asnumpy(), x[[0, 2], [1, 3]], rtol=1e-6)
    scat = nd.scatter_nd(out, nd.array(indices), shape=(3, 4))
    expect = onp.zeros((3, 4), "f")
    expect[0, 1] = x[0, 1]
    expect[2, 3] = x[2, 3]
    assert_almost_equal(scat.asnumpy(), expect, rtol=1e-6)


def test_op_one_hot():
    idx = onp.array([0, 2, 1], "f")
    out = nd.one_hot(nd.array(idx), depth=4)
    expect = onp.eye(4, dtype="f")[[0, 2, 1]]
    assert_almost_equal(out.asnumpy(), expect, rtol=1e-6)
    out2 = nd.one_hot(nd.array(idx), depth=4, on_value=2.0,
                      off_value=-1.0)
    assert_almost_equal(out2.asnumpy(), expect * 3 - 1, rtol=1e-6)


def test_op_topk_ret_types():
    x = _x((2, 6))
    v = nd.topk(nd.array(x), k=2, ret_typ="value")
    expect_v = -onp.sort(-x, axis=1)[:, :2]
    assert_almost_equal(v.asnumpy(), expect_v, rtol=1e-5)
    i = nd.topk(nd.array(x), k=2)
    expect_i = onp.argsort(-x, axis=1)[:, :2]
    assert_almost_equal(i.asnumpy(), expect_i.astype("f"), rtol=1e-6)
    both = nd.topk(nd.array(x), k=2, ret_typ="both")
    assert len(both) == 2
    asc = nd.topk(nd.array(x), k=1, is_ascend=True, ret_typ="value")
    assert_almost_equal(asc.asnumpy(), x.min(1, keepdims=True),
                        rtol=1e-5)


def test_op_sort_argsort():
    x = _x((3, 5))
    assert_almost_equal(nd.sort(nd.array(x), axis=1).asnumpy(),
                        onp.sort(x, 1), rtol=1e-6)
    assert_almost_equal(
        nd.sort(nd.array(x), axis=1, is_ascend=False).asnumpy(),
        -onp.sort(-x, 1), rtol=1e-6)
    assert_almost_equal(nd.argsort(nd.array(x), axis=1).asnumpy(),
                        onp.argsort(x, 1).astype("f"), rtol=1e-6)


def test_op_boolean_mask():
    x = _x((4, 3))
    m = onp.array([1, 0, 1, 0], "f")
    out = nd.contrib.boolean_mask(nd.array(x), nd.array(m))
    assert_almost_equal(out.asnumpy(), x[[0, 2]], rtol=1e-6)


def test_op_ravel_unravel():
    shape = (3, 4)
    flat = onp.array([0, 5, 11], "f")
    un = nd.unravel(nd.array(flat), shape=shape)
    expect = onp.stack(onp.unravel_index(flat.astype(int), shape))
    assert_almost_equal(un.asnumpy(), expect.astype("f"), rtol=1e-6)
    back = nd.ravel_multi_index(un, shape=shape)
    assert_almost_equal(back.asnumpy(), flat, rtol=1e-6)


def test_op_histogram():
    x = _x((50,), lo=0, hi=10)
    cnt, edges = nd.histogram(nd.array(x), bins=5, range=(0, 10))
    ec, ee = onp.histogram(x, bins=5, range=(0, 10))
    assert_almost_equal(cnt.asnumpy(), ec.astype("f"), rtol=1e-6)
    assert_almost_equal(edges.asnumpy(), ee.astype("f"), rtol=1e-5)


def test_op_index_array_copy():
    x = _x((2, 3))
    ia = nd.contrib.index_array(nd.array(x))
    assert ia.shape == (2, 3, 2)
    assert ia.asnumpy()[1, 2].tolist() == [1, 2]
    old = nd.array(_x((4, 3)))
    new = nd.array(_x((2, 3)))
    out = nd.contrib.index_copy(old, nd.array(onp.array([0, 2], "f")),
                                new)
    assert_almost_equal(out.asnumpy()[[0, 2]], new.asnumpy(), rtol=1e-6)
    assert_almost_equal(out.asnumpy()[1], old.asnumpy()[1], rtol=1e-6)


# --------------------------------------------------------------- NN core ---

def _naive_conv2d(x, w, stride, pad):
    B, C, H, W = x.shape
    F, _, kh, kw = w.shape
    sh, sw = stride
    ph, pw = pad
    xp = onp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
    Ho = (H + 2 * ph - kh) // sh + 1
    Wo = (W + 2 * pw - kw) // sw + 1
    out = onp.zeros((B, F, Ho, Wo), "f")
    for b in range(B):
        for f in range(F):
            for i in range(Ho):
                for j in range(Wo):
                    patch = xp[b, :, i * sh:i * sh + kh,
                               j * sw:j * sw + kw]
                    out[b, f, i, j] = (patch * w[f]).sum()
    return out


def test_op_convolution_vs_naive():
    x = _x((2, 3, 7, 7))
    w = _x((4, 3, 3, 3))
    out = nd.convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                         stride=(2, 2), pad=(1, 1), num_filter=4,
                         no_bias=True)
    assert_almost_equal(out.asnumpy(),
                        _naive_conv2d(x, w, (2, 2), (1, 1)),
                        rtol=1e-3, atol=1e-4)


def test_op_convolution_groups_and_bias():
    x = _x((1, 4, 5, 5))
    w = _x((4, 2, 3, 3))
    b = _x((4,))
    out = nd.convolution(nd.array(x), nd.array(w), nd.array(b),
                         kernel=(3, 3), pad=(1, 1), num_filter=4,
                         num_group=2)
    # group conv == two independent half convs
    o1 = _naive_conv2d(x[:, :2], w[:2], (1, 1), (1, 1))
    o2 = _naive_conv2d(x[:, 2:], w[2:], (1, 1), (1, 1))
    expect = onp.concatenate([o1, o2], 1) + b.reshape(1, -1, 1, 1)
    assert_almost_equal(out.asnumpy(), expect, rtol=1e-3, atol=1e-4)


def test_op_convolution_gradients():
    x = _x((1, 2, 5, 5))
    w = _x((2, 2, 3, 3))
    check_numeric_gradient(
        lambda a, b: nd.convolution(a, b, kernel=(3, 3), pad=(1, 1),
                                    num_filter=2, no_bias=True),
        [x, w], rtol=3e-2, atol=1e-3)


def test_op_deconvolution_shape_inverse():
    x = _x((1, 3, 4, 4))
    w = _x((3, 5, 3, 3))
    out = nd.deconvolution(nd.array(x), nd.array(w), kernel=(3, 3),
                           stride=(2, 2), pad=(1, 1), num_filter=5)
    assert out.shape == (1, 5, 7, 7)


def test_op_pooling_max_avg():
    x = _x((1, 2, 4, 4))
    mx_out = nd.pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                        pool_type="max")
    expect = x.reshape(1, 2, 2, 2, 2, 2).max(axis=(3, 5))
    assert_almost_equal(mx_out.asnumpy(), expect, rtol=1e-5)
    avg = nd.pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                     pool_type="avg")
    expecta = x.reshape(1, 2, 2, 2, 2, 2).mean(axis=(3, 5))
    assert_almost_equal(avg.asnumpy(), expecta, rtol=1e-5)


def test_op_pooling_global_and_full_convention():
    x = _x((2, 3, 5, 5))
    g = nd.pooling(nd.array(x), pool_type="avg", global_pool=True)
    assert_almost_equal(g.asnumpy().reshape(2, 3),
                        x.mean(axis=(2, 3)), rtol=1e-5)
    full = nd.pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                      pool_type="max", pooling_convention="full")
    assert full.shape == (2, 3, 3, 3)


def test_op_avg_pool_count_include_pad():
    x = onp.ones((1, 1, 2, 2), "f")
    incl = nd.pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                      pad=(1, 1), pool_type="avg",
                      count_include_pad=True)
    excl = nd.pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                      pad=(1, 1), pool_type="avg",
                      count_include_pad=False)
    assert incl.asnumpy()[0, 0, 0, 0] == pytest.approx(0.25)
    assert excl.asnumpy()[0, 0, 0, 0] == pytest.approx(1.0)


def test_op_fully_connected_flatten():
    x = _x((2, 3, 4))
    w = _x((5, 12))
    b = _x((5,))
    out = nd.fully_connected(nd.array(x), nd.array(w), nd.array(b),
                             num_hidden=5)
    expect = x.reshape(2, 12) @ w.T + b
    assert_almost_equal(out.asnumpy(), expect, rtol=1e-4)
    nf = nd.fully_connected(nd.array(x), nd.array(_x((5, 4))),
                            nd.array(b), num_hidden=5, flatten=False)
    assert nf.shape == (2, 3, 5)


def test_op_softmax_properties():
    x = _x((3, 5))
    out = nd.softmax(nd.array(x), axis=1)
    e = onp.exp(x - x.max(1, keepdims=True))
    assert_almost_equal(out.asnumpy(), e / e.sum(1, keepdims=True),
                        rtol=1e-5)
    ls = nd.log_softmax(nd.array(x), axis=1)
    assert_almost_equal(ls.asnumpy(), onp.log(e / e.sum(1,
                                                        keepdims=True)),
                        rtol=1e-4, atol=1e-5)
    sm = nd.softmin(nd.array(x), axis=1)
    en = onp.exp(-(x - x.min(1, keepdims=True)))
    assert_almost_equal(sm.asnumpy(), en / en.sum(1, keepdims=True),
                        rtol=1e-4)


def test_op_softmax_gradient():
    x = _x((2, 4))
    w = nd.array(_x((2, 4)))  # fixed weights — the fn must be pure
    check_numeric_gradient(
        lambda a: nd.sum(nd.softmax(a, axis=1) * w),
        [x], rtol=3e-2, atol=1e-3)


def test_op_dropout_train_inference():
    x = onp.ones((200, 10), "f")
    with autograd.record(train_mode=True):
        out = nd.dropout(nd.array(x), p=0.5)
    kept = out.asnumpy()
    frac = (kept > 0).mean()
    assert 0.35 < frac < 0.65
    assert_almost_equal(kept[kept > 0], onp.full((kept > 0).sum(), 2.0),
                        rtol=1e-5)  # inverted scaling
    out_inf = nd.dropout(nd.array(x), p=0.5)
    assert_almost_equal(out_inf.asnumpy(), x, rtol=1e-6)


def test_op_embedding_and_grad():
    w = _x((10, 4))
    idx = onp.array([1, 3, 1], "f")
    out = nd.embedding(nd.array(idx), nd.array(w), input_dim=10,
                       output_dim=4)
    assert_almost_equal(out.asnumpy(), w[[1, 3, 1]], rtol=1e-6)
    wv = nd.array(w)
    wv.attach_grad()
    with autograd.record():
        y = nd.sum(nd.embedding(nd.array(idx), wv, input_dim=10,
                                output_dim=4))
    y.backward()
    expect = onp.zeros_like(w)
    expect[1] = 2  # index 1 used twice
    expect[3] = 1
    assert_almost_equal(wv.grad.asnumpy(), expect, rtol=1e-6)


def test_op_layer_norm_vs_numpy():
    x = _x((4, 6))
    g, b = _x((6,)), _x((6,))
    out = nd.layer_norm(nd.array(x), nd.array(g), nd.array(b), axis=-1,
                        eps=1e-5)
    mu = x.mean(1, keepdims=True)
    var = x.var(1, keepdims=True)
    expect = (x - mu) / onp.sqrt(var + 1e-5) * g + b
    assert_almost_equal(out.asnumpy(), expect, rtol=1e-4, atol=1e-5)


def test_op_instance_group_norm():
    x = _x((2, 4, 3, 3))
    g, b = _x((4,)), _x((4,))
    out = nd.instance_norm(nd.array(x), nd.array(g), nd.array(b),
                           eps=1e-5)
    mu = x.mean(axis=(2, 3), keepdims=True)
    var = x.var(axis=(2, 3), keepdims=True)
    expect = (x - mu) / onp.sqrt(var + 1e-5) * g.reshape(1, -1, 1, 1) \
        + b.reshape(1, -1, 1, 1)
    assert_almost_equal(out.asnumpy(), expect, rtol=1e-3, atol=1e-4)
    # group_norm: per-GROUP gamma/beta (reference group_norm-inl.h:163)
    gg, gb = _x((2,)), _x((2,))
    gn = nd.group_norm(nd.array(x), nd.array(gg), nd.array(gb),
                       num_groups=2)
    xg = x.reshape(2, 2, 2, 3, 3)
    mu = xg.mean(axis=(2, 3, 4), keepdims=True)
    var = xg.var(axis=(2, 3, 4), keepdims=True)
    expect_g = ((xg - mu) / onp.sqrt(var + 1e-5)
                * gg.reshape(1, 2, 1, 1, 1)
                + gb.reshape(1, 2, 1, 1, 1)).reshape(x.shape)
    assert_almost_equal(gn.asnumpy(), expect_g, rtol=1e-3, atol=1e-4)


def test_op_batch_norm_inference_stats():
    x = _x((3, 4, 2, 2))
    mean = _x((4,))
    var = onp.abs(_x((4,))) + 0.5
    out = nd.batch_norm(nd.array(x), nd.ones(4), nd.zeros(4),
                        nd.array(mean), nd.array(var),
                        use_global_stats=True, use_batch_stats=False,
                        eps=1e-3, fix_gamma=False)
    expect = (x - mean.reshape(1, -1, 1, 1)) / onp.sqrt(
        var.reshape(1, -1, 1, 1) + 1e-3)
    assert_almost_equal(out.asnumpy(), expect, rtol=1e-3, atol=1e-4)


def test_op_lrn():
    x = _x((1, 6, 3, 3), lo=0.1, hi=1.0)
    out = nd.lrn(nd.array(x), nsize=3, alpha=1e-3, beta=0.75, knorm=2.0)
    # oracle: across-channel normalization
    sq = onp.zeros_like(x)
    for c in range(6):
        lo, hi = max(0, c - 1), min(6, c + 2)
        sq[:, c] = (x[:, lo:hi] ** 2).sum(1)
    expect = x / (2.0 + 1e-3 / 3 * sq) ** 0.75
    assert_almost_equal(out.asnumpy(), expect, rtol=1e-3, atol=1e-4)


def test_op_l2_normalization():
    x = _x((2, 3, 4))
    out = nd.l2_normalization(nd.array(x), mode="instance")
    norm = onp.sqrt((x.reshape(2, -1) ** 2).sum(1) + 1e-10)
    assert_almost_equal(out.asnumpy(),
                        x / norm.reshape(2, 1, 1), rtol=1e-4)
    ch = nd.l2_normalization(nd.array(x), mode="channel")
    nc = onp.sqrt((x ** 2).sum(1, keepdims=True) + 1e-10)
    assert_almost_equal(ch.asnumpy(), x / nc, rtol=1e-4)


def test_op_sequence_family():
    x = _x((4, 2, 3))  # (T, N, C)
    lens = onp.array([2, 3], "f")
    m = nd.sequence_mask(nd.array(x), nd.array(lens),
                         use_sequence_length=True, value=-1.0)
    mn = m.asnumpy()
    assert (mn[2:, 0] == -1).all() and (mn[3:, 1] == -1).all()
    assert_almost_equal(mn[:2, 0], x[:2, 0], rtol=1e-6)
    last = nd.sequence_last(nd.array(x), nd.array(lens),
                            use_sequence_length=True)
    assert_almost_equal(last.asnumpy(),
                        onp.stack([x[1, 0], x[2, 1]]), rtol=1e-6)
    rev = nd.sequence_reverse(nd.array(x), nd.array(lens),
                              use_sequence_length=True)
    assert_almost_equal(rev.asnumpy()[0, 0], x[1, 0], rtol=1e-6)
    assert_almost_equal(rev.asnumpy()[0, 1], x[2, 1], rtol=1e-6)


def test_op_leaky_relu_variants():
    x = _x()
    leaky = nd.leaky_relu(nd.array(x), act_type="leaky", slope=0.1)
    assert_almost_equal(leaky.asnumpy(),
                        onp.where(x > 0, x, 0.1 * x), rtol=1e-5)
    elu = nd.leaky_relu(nd.array(x), act_type="elu", slope=1.0)
    assert_almost_equal(elu.asnumpy(),
                        onp.where(x > 0, x, onp.expm1(x)), rtol=1e-4,
                        atol=1e-5)
    g = _x((x.shape[-1],), lo=0.1, hi=0.3)
    pr = nd.leaky_relu(nd.array(x), nd.array(g), act_type="prelu")
    assert_almost_equal(pr.asnumpy(), onp.where(x > 0, x, g * x),
                        rtol=1e-5)


def test_op_upsampling_nearest():
    x = _x((1, 2, 3, 3))
    out = nd.upsampling(nd.array(x), scale=2, sample_type="nearest")
    assert out.shape == (1, 2, 6, 6)
    assert_almost_equal(out.asnumpy()[0, 0, ::2, ::2], x[0, 0],
                        rtol=1e-6)


def test_op_softmax_cross_entropy():
    x = _x((3, 5))
    lab = onp.array([0, 2, 4], "f")
    out = nd.softmax_cross_entropy(nd.array(x), nd.array(lab))
    e = onp.exp(x - x.max(1, keepdims=True))
    p = e / e.sum(1, keepdims=True)
    expect = -onp.log(p[onp.arange(3), lab.astype(int)]).sum()
    assert_almost_equal(out.asnumpy().reshape(()), expect, rtol=1e-4)


# ------------------------------------------------------------ legacy ops ---

def test_op_smooth_l1_piecewise():
    x = onp.array([-2.0, -0.3, 0.0, 0.3, 2.0], "f")
    out = nd.smooth_l1(nd.array(x), scalar=1.0)
    expect = onp.where(onp.abs(x) < 1, 0.5 * x * x, onp.abs(x) - 0.5)
    assert_almost_equal(out.asnumpy(), expect, rtol=1e-5)


def test_op_moments():
    x = _x((3, 4))
    mean, var = nd.moments(nd.array(x), axes=(1,))
    assert_almost_equal(mean.asnumpy(), x.mean(1), rtol=1e-5)
    assert_almost_equal(var.asnumpy(), x.var(1), rtol=1e-4)


def test_op_regression_outputs_backward():
    x = _x((4, 3))
    lab = _x((4, 3))
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        out = nd.linear_regression_output(a, nd.array(lab))
    out.backward()
    # forward is identity; backward is (pred - label) * grad_scale /
    # num_output with num_output = per-sample feature count (reference
    # regression_output-inl.h:201)
    assert_almost_equal(out.asnumpy(), x, rtol=1e-6)
    assert_almost_equal(a.grad.asnumpy(), (x - lab) / 3, rtol=1e-4)


def test_op_roi_pooling():
    x = onp.arange(16, dtype="f").reshape(1, 1, 4, 4)
    rois = onp.array([[0, 0, 0, 3, 3]], "f")
    out = nd.roi_pooling(nd.array(x), nd.array(rois),
                         pooled_size=(2, 2), spatial_scale=1.0)
    assert_almost_equal(out.asnumpy().reshape(2, 2),
                        [[5, 7], [13, 15]], rtol=1e-5)


def test_op_grid_generator_bilinear_sampler_identity():
    x = _x((1, 2, 4, 4))
    # identity affine transform
    theta = onp.array([[1, 0, 0, 0, 1, 0]], "f")
    grid = nd.grid_generator(nd.array(theta), transform_type="affine",
                             target_shape=(4, 4))
    out = nd.bilinear_sampler(nd.array(x), grid)
    assert_almost_equal(out.asnumpy(), x, rtol=1e-4, atol=1e-4)


def test_op_spatial_transformer_identity():
    x = _x((1, 2, 4, 4))
    theta = onp.array([[1, 0, 0, 0, 1, 0]], "f")
    out = nd.spatial_transformer(nd.array(x), nd.array(theta),
                                 target_shape=(4, 4),
                                 transform_type="affine",
                                 sampler_type="bilinear")
    assert_almost_equal(out.asnumpy(), x, rtol=1e-4, atol=1e-4)


def test_op_correlation_self():
    x = _x((1, 2, 5, 5))
    out = nd.correlation(nd.array(x), nd.array(x), kernel_size=1,
                         max_displacement=0, stride1=1, stride2=1)
    expect = (x * x).mean(1, keepdims=True)
    assert_almost_equal(out.asnumpy(), expect, rtol=1e-4)


def test_op_crop():
    x = _x((1, 2, 6, 6))
    out = nd.crop(nd.array(x), offset=(1, 2), h_w=(3, 3))
    assert_almost_equal(out.asnumpy(), x[:, :, 1:4, 2:5], rtol=1e-6)


def test_op_make_loss_identity_grad():
    x = _x((3,))
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.make_loss(a * 2)
    y.backward()
    assert_almost_equal(a.grad.asnumpy(), onp.full(3, 2.0), rtol=1e-5)


# ---------------------------------------------------------------- random ---

def test_op_random_uniform_range():
    mx.random.seed(0)
    x = nd.random.uniform(low=2.0, high=5.0, shape=(2000,))
    v = x.asnumpy()
    assert v.min() >= 2.0 and v.max() <= 5.0
    assert abs(v.mean() - 3.5) < 0.1


def test_op_random_normal_moments():
    mx.random.seed(0)
    x = nd.random.normal(loc=1.0, scale=2.0, shape=(4000,))
    v = x.asnumpy()
    assert abs(v.mean() - 1.0) < 0.15
    assert abs(v.std() - 2.0) < 0.15


def test_op_random_poisson_gamma_exponential():
    mx.random.seed(0)
    p = nd.random.poisson(lam=4.0, shape=(3000,)).asnumpy()
    assert abs(p.mean() - 4.0) < 0.25
    g = nd.random.gamma(alpha=2.0, beta=3.0, shape=(3000,)).asnumpy()
    assert abs(g.mean() - 6.0) < 0.5
    e = nd.random.exponential(scale=2.0, shape=(3000,)).asnumpy()
    assert abs(e.mean() - 2.0) < 0.25


def test_op_random_randint_multinomial():
    mx.random.seed(0)
    r = nd.random.randint(low=0, high=5, shape=(2000,)).asnumpy()
    assert r.min() >= 0 and r.max() <= 4
    probs = nd.array(onp.array([[0.0, 0.0, 1.0]], "f"))
    m = nd.sample_multinomial(probs, shape=(10,))
    assert (m.asnumpy() == 2).all()


def test_op_random_seed_reproducible():
    mx.random.seed(123)
    a = nd.random.uniform(shape=(5,)).asnumpy()
    mx.random.seed(123)
    b = nd.random.uniform(shape=(5,)).asnumpy()
    assert_almost_equal(a, b, rtol=1e-7)
    c = nd.random.uniform(shape=(5,)).asnumpy()
    assert not onp.allclose(a, c)


def test_op_shuffle_is_permutation():
    x = onp.arange(20, dtype="f")
    out = nd.shuffle(nd.array(x)).asnumpy()
    assert sorted(out.tolist()) == x.tolist()


def test_op_gather_nd_grad_scatters():
    data = nd.array(onp.arange(12, dtype="f").reshape(3, 4))
    data.attach_grad()
    idx = nd.array(onp.array([[0, 2], [1, 3]], "f"))  # rows, cols pairs
    with autograd.record():
        out = nd.gather_nd(data, idx)
        loss = nd.sum(out * nd.array([2.0, 3.0]))
    loss.backward()
    g = data.grad.asnumpy()
    expect = onp.zeros((3, 4), "f")
    expect[0, 1] = 2.0
    expect[2, 3] = 3.0
    onp.testing.assert_allclose(g, expect)


def test_op_take_along_axis_grad():
    data = nd.array(onp.arange(6, dtype="f").reshape(2, 3))
    data.attach_grad()
    idx = nd.array(onp.array([[2], [0]], "f"))
    with autograd.record():
        out = nd.take_along_axis(data, idx, axis=1)
        loss = nd.sum(out)
    loss.backward()
    expect = onp.zeros((2, 3), "f")
    expect[0, 2] = 1.0
    expect[1, 0] = 1.0
    onp.testing.assert_allclose(data.grad.asnumpy(), expect)


def test_op_topk_value_grad_routes_to_argmax_slots():
    data = nd.array(onp.array([[1.0, 5.0, 3.0], [4.0, 2.0, 6.0]], "f"))
    data.attach_grad()
    with autograd.record():
        vals = nd.topk(data, k=1, ret_typ="value")
        loss = nd.sum(vals)
    loss.backward()
    expect = onp.array([[0, 1, 0], [0, 0, 1]], "f")
    onp.testing.assert_allclose(data.grad.asnumpy(), expect)
