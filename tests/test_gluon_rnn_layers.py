"""gluon.rnn layer-level behavior (reference:
tests/python/unittest/test_gluon_rnn.py — LSTM/GRU/RNN layers: shapes,
states, bidirectional, layouts, layer-vs-cell equivalence, hybridize).
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.gluon import rnn
from mxnet_tpu.test_utils import assert_almost_equal


def _x(T=5, N=3, C=4, seed=0, layout="TNC"):
    rng = onp.random.RandomState(seed)
    shape = (T, N, C) if layout == "TNC" else (N, T, C)
    return nd.array(rng.rand(*shape).astype("f"))


@pytest.mark.parametrize("ctor,nstate", [(rnn.RNN, 1), (rnn.GRU, 1),
                                         (rnn.LSTM, 2)])
def test_layer_output_and_state_shapes(ctor, nstate):
    layer = ctor(hidden_size=6, num_layers=2)
    layer.initialize(mx.init.Xavier())
    x = _x()
    out = layer(x)
    assert out.shape == (5, 3, 6)
    begin = layer.begin_state(batch_size=3)
    assert len(begin) == nstate
    out2, states = layer(x, begin)
    assert out2.shape == (5, 3, 6)
    assert len(states) == nstate
    for s in states:
        assert s.shape == (2, 3, 6)  # (layers, N, H)


def test_bidirectional_doubles_features():
    layer = rnn.LSTM(hidden_size=5, num_layers=1, bidirectional=True)
    layer.initialize(mx.init.Xavier())
    out = layer(_x())
    assert out.shape == (5, 3, 10)
    begin = layer.begin_state(batch_size=3)
    _, states = layer(_x(), begin)
    for s in states:
        assert s.shape == (2, 3, 5)  # (layers*dirs, N, H)


def test_ntc_layout_matches_tnc():
    a = rnn.GRU(hidden_size=4, layout="TNC")
    a.initialize(mx.init.Xavier())
    b = rnn.GRU(hidden_size=4, layout="NTC")
    b.initialize(mx.init.Xavier())
    x_tnc = _x(seed=3)
    out_a = a(x_tnc).asnumpy()  # materializes a's params
    # identical parameters, different layout
    x_ntc = nd.transpose(x_tnc, axes=(1, 0, 2))
    b(x_ntc)  # finish deferred init
    for n, p in b.collect_params().items():
        key = n.split("_", 1)[-1] if "_" in n else n
        src = [q for n2, q in a.collect_params().items()
               if n2.endswith(key)]
        p.set_data(src[0].data())
    out_b = b(x_ntc).asnumpy()
    assert_almost_equal(out_b.transpose(1, 0, 2), out_a,
                        rtol=1e-5, atol=1e-6)


def test_layer_matches_cell_unroll():
    mx.random.seed(1)
    layer = rnn.LSTM(hidden_size=4, num_layers=1)
    layer.initialize(mx.init.Xavier())
    x = _x(seed=4)
    out = layer(x).asnumpy()
    # unroll the equivalent cell with the LAYER's own parameters
    cell = rnn.LSTMCell(4, input_size=4)
    cell.initialize()
    for name, p in cell.collect_params().items():
        suffix = "_".join(name.split("_")[-2:])  # e.g. i2h_weight
        src = [q for n2, q in layer.collect_params().items()
               if n2.endswith(suffix)]
        assert src, (name, list(layer.collect_params()))
        p.set_data(src[0].data())
    outputs, _ = cell.unroll(5, x, layout="TNC", merge_outputs=True)
    assert_almost_equal(outputs.asnumpy(), out, rtol=1e-4, atol=1e-5)


def test_layer_trains_and_hybridizes():
    layer = rnn.GRU(hidden_size=8, num_layers=2, dropout=0.1)
    layer.initialize(mx.init.Xavier())
    from mxnet_tpu import gluon

    tr = gluon.Trainer(layer.collect_params(), "adam",
                       {"learning_rate": 1e-2})
    x = _x(seed=5)
    tgt = nd.ones((5, 3, 8)) * 0.1
    first = None
    for _ in range(8):
        with autograd.record():
            out = layer(x)
            loss = nd.mean((out - tgt) ** 2)
        loss.backward()
        tr.step(3)
        first = first or float(loss.asscalar())
    assert float(loss.asscalar()) < first


def test_unequal_length_masking_with_sequence_mask():
    # variable-length batches: mask padded steps like the reference's
    # use_sequence_length flows
    layer = rnn.RNN(hidden_size=3)
    layer.initialize(mx.init.Xavier())
    x = _x(T=6, seed=6)
    out = layer(x)
    lens = nd.array(onp.array([6.0, 3.0, 1.0], "f"))
    masked = nd.sequence_mask(out, sequence_length=lens,
                              use_sequence_length=True)
    m = masked.asnumpy()
    assert (m[3:, 1] == 0).all() and (m[1:, 2] == 0).all()
    assert (m[:, 0] != 0).any()
