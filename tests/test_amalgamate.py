"""Single-file deploy bundles (reference: amalgamation/ — here the
bundle is generated jax source with embedded weights; the test runs it
in a subprocess with mxnet_tpu NOT importable, proving the deploy-site
dependency set is jax+numpy only)."""
import os
import subprocess
import sys

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.tools.amalgamate import amalgamate


def _export_convnet():
    data = sym.Variable("data")
    c1 = sym.Convolution(data, name="c1", kernel=(3, 3), num_filter=4,
                         pad=(1, 1))
    bn = sym.BatchNorm(c1, name="bn1", fix_gamma=False)
    act = sym.Activation(bn, act_type="relu")
    pool = sym.Pooling(act, kernel=(2, 2), stride=(2, 2), pool_type="max")
    fc = sym.FullyConnected(sym.Flatten(pool), name="fc", num_hidden=3)
    out = sym.softmax(fc)
    rng = onp.random.RandomState(0)
    params = {
        "c1_weight": rng.randn(4, 1, 3, 3).astype("f") * 0.2,
        "c1_bias": rng.randn(4).astype("f") * 0.1,
        "bn1_gamma": rng.rand(4).astype("f") + 0.5,
        "bn1_beta": rng.randn(4).astype("f") * 0.1,
        "bn1_moving_mean": rng.randn(4).astype("f") * 0.1,
        "bn1_moving_var": rng.rand(4).astype("f") + 0.5,
        "fc_weight": rng.randn(3, 4 * 4 * 4).astype("f") * 0.1,
        "fc_bias": rng.randn(3).astype("f") * 0.1,
    }
    return out, params


def test_amalgamated_bundle_matches_framework(tmp_path):
    out, params = _export_convnet()
    x = onp.random.RandomState(1).rand(2, 1, 8, 8).astype("f")
    # framework reference output (inference semantics)
    args = {"data": nd.array(x)}
    args.update({k: nd.array(v) for k, v in params.items()
                 if "moving" not in k})
    aux = {k: nd.array(v) for k, v in params.items() if "moving" in k}
    ex = out.bind(args=args, aux_states=aux)
    want = ex.forward(is_train=False)[0].asnumpy()

    src = amalgamate(out.tojson(), params)
    bundle = tmp_path / "predict_model.py"
    bundle.write_text(src)
    driver = tmp_path / "drive.py"
    driver.write_text(
        "import sys, numpy as np\n"
        "import predict_model as m\n"
        "x = np.load(sys.argv[1])\n"
        "np.save(sys.argv[2], m.predict(x))\n"
        "assert 'mxnet_tpu' not in sys.modules, 'deploy leaked mxnet_tpu'\n")
    xin = tmp_path / "x.npy"
    onp.save(xin, x)
    yout = tmp_path / "y.npy"
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, str(driver), str(xin), str(yout)],
        cwd=tmp_path, env=env, capture_output=True, text=True,
        timeout=240)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    got = onp.load(yout)
    onp.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_amalgamate_rejects_out_of_set_ops():
    import pytest

    data = sym.Variable("data")
    out = sym.LRN(data, nsize=3)
    with pytest.raises(ValueError, match="deploy op set"):
        amalgamate(out.tojson(), {})
