"""Pipeline parallelism (parallel/pipeline.py): pipelined == sequential,
microbatch counts, gradients through the scan+ppermute program, training.
Runs on the 8-device virtual CPU mesh from conftest.
"""
import numpy as onp
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu import parallel
from mxnet_tpu.parallel.pipeline import pipeline_apply


def _stage_fn(params, x):
    w, b = params
    return jnp.tanh(x @ w + b)


def _params(rng, p=4, d=8):
    return (jnp.asarray(rng.randn(p, d, d).astype("f") * 0.4),
            jnp.asarray(rng.randn(p, d).astype("f") * 0.1))


def _sequential(params, x):
    w, b = params
    act = x
    for i in range(w.shape[0]):
        act = _stage_fn((w[i], b[i]), act)
    return act


@pytest.mark.parametrize("n_micro", [4, 8])
def test_pipeline_matches_sequential(n_micro):
    rng = onp.random.RandomState(0)
    params = _params(rng, p=4)
    x = jnp.asarray(rng.randn(16, 8).astype("f"))
    want = _sequential(params, x)
    mesh = parallel.make_mesh({"pp": 4}, devices=jax.devices()[:4])
    got = pipeline_apply(_stage_fn, params, x, mesh=mesh,
                         n_microbatches=n_micro)
    onp.testing.assert_allclose(onp.asarray(got), onp.asarray(want),
                                rtol=2e-5, atol=2e-6)


def test_pipeline_single_shard_fallback():
    rng = onp.random.RandomState(1)
    params = _params(rng, p=3)
    x = jnp.asarray(rng.randn(6, 8).astype("f"))
    got = pipeline_apply(_stage_fn, params, x, mesh=None)
    onp.testing.assert_allclose(onp.asarray(got),
                                onp.asarray(_sequential(params, x)),
                                rtol=1e-6)


def test_pipeline_gradients_match_sequential():
    rng = onp.random.RandomState(2)
    params = _params(rng, p=4)
    x = jnp.asarray(rng.randn(8, 8).astype("f"))
    mesh = parallel.make_mesh({"pp": 4}, devices=jax.devices()[:4])

    def loss_pp(ps):
        return jnp.sum(pipeline_apply(_stage_fn, ps, x, mesh=mesh) ** 2)

    def loss_seq(ps):
        return jnp.sum(_sequential(ps, x) ** 2)

    g_pp = jax.grad(loss_pp)(params)
    g_seq = jax.grad(loss_seq)(params)
    for a, b in zip(g_pp, g_seq):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=5e-4, atol=5e-5)


def test_pipeline_trains_under_jit():
    rng = onp.random.RandomState(3)
    params = _params(rng, p=4)
    mesh = parallel.make_mesh({"pp": 4}, devices=jax.devices()[:4])
    x = jnp.asarray(rng.randn(8, 8).astype("f"))
    y = jnp.tanh(x * 0.5)

    @jax.jit
    def step(ps):
        def loss_fn(p):
            out = pipeline_apply(_stage_fn, p, x, mesh=mesh)
            return jnp.mean((out - y) ** 2)

        l, g = jax.value_and_grad(loss_fn)(ps)
        return tuple(w - 0.2 * gi for w, gi in zip(ps, g)), l

    first = None
    for _ in range(20):
        params, l = step(params)
        first = first or float(l)
    assert float(l) < first * 0.8, (first, float(l))


def test_pipeline_composes_with_dp_mesh():
    # pp pipeline on a ('dp','pp') mesh: x replicated over pp, params
    # over pp only — the pipeline runs within each dp row
    rng = onp.random.RandomState(4)
    params = _params(rng, p=4)
    x = jnp.asarray(rng.randn(8, 8).astype("f"))
    mesh = parallel.make_mesh({"dp": 2, "pp": 4})
    got = pipeline_apply(_stage_fn, params, x, mesh=mesh)
    onp.testing.assert_allclose(onp.asarray(got),
                                onp.asarray(_sequential(params, x)),
                                rtol=2e-5, atol=2e-6)
