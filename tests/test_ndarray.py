"""NDArray basics (reference suite: tests/python/unittest/test_ndarray.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_create_and_asnumpy():
    a = nd.array([[1, 2], [3, 4]])
    assert a.shape == (2, 2)
    assert a.dtype == onp.float32
    onp.testing.assert_allclose(a.asnumpy(), [[1, 2], [3, 4]])


def test_zeros_ones_full_arange():
    assert nd.zeros((2, 3)).asnumpy().sum() == 0
    assert nd.ones((2, 3)).asnumpy().sum() == 6
    onp.testing.assert_allclose(nd.full((2,), 7).asnumpy(), [7, 7])
    onp.testing.assert_allclose(nd.arange(0, 6, 2).asnumpy(), [0, 2, 4])


def test_arithmetic():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([4.0, 5.0, 6.0])
    onp.testing.assert_allclose((a + b).asnumpy(), [5, 7, 9])
    onp.testing.assert_allclose((a - b).asnumpy(), [-3, -3, -3])
    onp.testing.assert_allclose((a * b).asnumpy(), [4, 10, 18])
    onp.testing.assert_allclose((b / a).asnumpy(), [4, 2.5, 2])
    onp.testing.assert_allclose((a ** 2).asnumpy(), [1, 4, 9])
    onp.testing.assert_allclose((2 + a).asnumpy(), [3, 4, 5])
    onp.testing.assert_allclose((2 - a).asnumpy(), [1, 0, -1])
    onp.testing.assert_allclose((-a).asnumpy(), [-1, -2, -3])


def test_inplace_ops():
    a = nd.array([1.0, 2.0])
    a += 1
    onp.testing.assert_allclose(a.asnumpy(), [2, 3])
    a *= 2
    onp.testing.assert_allclose(a.asnumpy(), [4, 6])


def test_comparisons_return_numeric():
    a = nd.array([1.0, 2.0, 3.0])
    out = (a > 1.5).asnumpy()
    assert out.dtype == onp.float32
    onp.testing.assert_allclose(out, [0, 1, 1])


def test_indexing():
    a = nd.array(onp.arange(12).reshape(3, 4))
    onp.testing.assert_allclose(a[1].asnumpy(), [4, 5, 6, 7])
    onp.testing.assert_allclose(a[0:2, 1].asnumpy(), [1, 5])
    idx = nd.array([0, 2], dtype="int32")
    onp.testing.assert_allclose(a[idx].asnumpy(), [[0, 1, 2, 3],
                                                   [8, 9, 10, 11]])


def test_setitem():
    a = nd.zeros((3, 3))
    a[1] = 5
    assert a.asnumpy()[1].sum() == 15
    a[0, 0] = 2
    assert a.asnumpy()[0, 0] == 2


def test_reshape_special_codes():
    a = nd.zeros((2, 3, 4))
    assert a.reshape((0, -1)).shape == (2, 12)
    assert a.reshape((-1,)).shape == (24,)
    assert a.reshape((0, 0, 4)).shape == (2, 3, 4)
    assert a.reshape((-3, 0)).shape == (6, 4)
    assert a.reshape((0, -4, 1, 3, 0)).shape == (2, 1, 3, 4)
    assert a.reshape((0, -2)).shape == (2, 3, 4)


def test_reductions():
    a = nd.array(onp.arange(6).reshape(2, 3).astype("float32"))
    assert a.sum().asscalar() == 15
    onp.testing.assert_allclose(nd.sum(a, axis=0).asnumpy(), [3, 5, 7])
    onp.testing.assert_allclose(nd.sum(a, axis=1, keepdims=True).asnumpy(),
                                [[3], [12]])
    onp.testing.assert_allclose(
        nd.sum(a, axis=0, exclude=True).asnumpy(), [3, 12])
    onp.testing.assert_allclose(nd.mean(a).asnumpy(), 2.5)
    assert nd.max(a).asscalar() == 5
    assert nd.argmax(a, axis=1).asnumpy().tolist() == [2, 2]
    onp.testing.assert_allclose(nd.norm(a).asscalar(),
                                onp.sqrt((onp.arange(6) ** 2).sum()),
                                rtol=1e-5)


def test_dot():
    a = nd.array(onp.random.rand(3, 4))
    b = nd.array(onp.random.rand(4, 5))
    onp.testing.assert_allclose(nd.dot(a, b).asnumpy(),
                                a.asnumpy() @ b.asnumpy(), rtol=1e-5)
    onp.testing.assert_allclose(
        nd.dot(a, b.T, transpose_b=True).asnumpy()[0, 0],
        nd.dot(a, b).asnumpy()[0, 0], rtol=1e-5)


def test_batch_dot():
    a = nd.array(onp.random.rand(2, 3, 4))
    b = nd.array(onp.random.rand(2, 4, 5))
    out = nd.batch_dot(a, b)
    assert out.shape == (2, 3, 5)
    onp.testing.assert_allclose(out.asnumpy(),
                                a.asnumpy() @ b.asnumpy(), rtol=1e-5)


def test_shape_ops():
    a = nd.array(onp.arange(6).reshape(2, 3))
    assert nd.transpose(a).shape == (3, 2)
    assert nd.expand_dims(a, axis=0).shape == (1, 2, 3)
    assert nd.flip(a, axis=1).asnumpy()[0, 0] == 2
    b = nd.concat(a, a, dim=0)
    assert b.shape == (4, 3)
    c = nd.stack(a, a, axis=0)
    assert c.shape == (2, 2, 3)
    parts = nd.split(a, 3, axis=1)
    assert len(parts) == 3 and parts[0].shape == (2, 1)
    parts = nd.split(a, 3, axis=1, squeeze_axis=True)
    assert parts[0].shape == (2,)
    assert nd.tile(a, (2, 2)).shape == (4, 6)
    assert nd.repeat(a, 2, axis=0).shape == (4, 3)


def test_slice_ops():
    a = nd.array(onp.arange(24).reshape(2, 3, 4))
    s = nd.slice(a, begin=(0, 1, 0), end=(2, 3, 2))
    assert s.shape == (2, 2, 2)
    s2 = nd.slice_axis(a, axis=2, begin=1, end=3)
    assert s2.shape == (2, 3, 2)
    s3 = nd.slice_like(a, nd.zeros((1, 2, 2)))
    assert s3.shape == (1, 2, 2)


def test_take_pick_onehot():
    a = nd.array(onp.arange(12).reshape(3, 4).astype("f"))
    idx = nd.array([0, 2], dtype="int32")
    assert nd.take(a, idx).shape == (2, 4)
    p = nd.pick(a, nd.array([1, 0, 3]), axis=1)
    onp.testing.assert_allclose(p.asnumpy(), [1, 4, 11])
    oh = nd.one_hot(nd.array([0, 2]), 3)
    onp.testing.assert_allclose(oh.asnumpy(), [[1, 0, 0], [0, 0, 1]])


def test_topk_sort():
    a = nd.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]])
    v = nd.topk(a, k=2, ret_typ="value")
    onp.testing.assert_allclose(v.asnumpy(), [[3, 2], [5, 4]])
    i = nd.topk(a, k=1)
    onp.testing.assert_allclose(i.asnumpy(), [[0], [1]])
    s = nd.sort(a, axis=1)
    onp.testing.assert_allclose(s.asnumpy(), [[1, 2, 3], [0, 4, 5]])
    ars = nd.argsort(a, axis=1)
    onp.testing.assert_allclose(ars.asnumpy(), [[1, 2, 0], [0, 2, 1]])


def test_cast_astype():
    a = nd.array([1.5, 2.5])
    b = a.astype("int32")
    assert b.dtype == onp.int32
    assert b.asnumpy().tolist() == [1, 2]


def test_where_clip():
    a = nd.array([-1.0, 0.5, 2.0])
    onp.testing.assert_allclose(nd.clip(a, 0, 1).asnumpy(), [0, 0.5, 1])
    w = nd.where(a > 0, a, nd.zeros_like(a))
    onp.testing.assert_allclose(w.asnumpy(), [0, 0.5, 2])


def test_save_load_roundtrip(tmp_path):
    f = str(tmp_path / "arrs")
    d = {"w": nd.array([1.0, 2.0]), "b": nd.array([[3.0]])}
    nd.save(f, d)
    loaded = nd.load(f)
    assert set(loaded) == {"w", "b"}
    onp.testing.assert_allclose(loaded["w"].asnumpy(), [1, 2])
    lst = [nd.array([1.0]), nd.array([2.0, 3.0])]
    nd.save(f, lst)
    loaded = nd.load(f)
    assert len(loaded) == 2
    onp.testing.assert_allclose(loaded[1].asnumpy(), [2, 3])


def test_gather_scatter():
    data = nd.array(onp.arange(9).reshape(3, 3).astype("f"))
    indices = nd.array([[0, 2], [1, 0]], dtype="int32")
    # indices[0] = axis-0 coords, indices[1] = axis-1 coords (mxnet layout)
    g = nd.gather_nd(data, indices)
    onp.testing.assert_allclose(g.asnumpy(), [1, 6])
    s = nd.scatter_nd(nd.array([1.0, 2.0]), indices, shape=(3, 3))
    assert s.asnumpy()[0, 1] == 1 and s.asnumpy()[2, 0] == 2


def test_broadcast_ops():
    a = nd.array(onp.ones((2, 1, 3)))
    assert nd.broadcast_to(a, (2, 4, 3)).shape == (2, 4, 3)
    assert nd.broadcast_axis(a, axis=1, size=5).shape == (2, 5, 3)
    b = nd.array(onp.ones((1, 3)))
    assert nd.broadcast_add(a, b).shape == (2, 1, 3)
    assert nd.broadcast_maximum(a, b).shape == (2, 1, 3)


def test_context():
    a = nd.array([1.0], ctx=mx.cpu())
    assert a.context.device_type in ("cpu", "tpu")
    b = a.as_in_context(mx.cpu(0))
    assert b.shape == a.shape


def test_wait_and_scalar():
    a = nd.array([3.14])
    a.wait_to_read()
    assert abs(a.asscalar() - 3.14) < 1e-6
    assert abs(float(a) - 3.14) < 1e-6
    nd.waitall()
