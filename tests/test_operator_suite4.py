"""Operator spec suite 4: ops with no direct coverage in suites 1-3.

Oracles: torch (CPU) for ctc_loss, numpy replications of the reference
update-rule formulas (src/operator/optimizer_op-inl.h) for the optimizer
ops, closed-form/numpy for the rest. Modeled on the reference's
tests/python/unittest/test_operator.py.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.test_utils import assert_almost_equal, with_seed


def _np(x):
    return onp.asarray(x.asnumpy() if hasattr(x, "asnumpy") else x)


# ------------------------------------------------------------------ ctc ---

def _torch_ctc(acts, labels, in_lens, lab_lens, blank):
    import torch
    import torch.nn.functional as F

    lp = F.log_softmax(torch.tensor(acts), dim=-1)
    return F.ctc_loss(
        lp, torch.tensor(labels), torch.tensor(in_lens),
        torch.tensor(lab_lens), blank=blank, reduction="none",
        zero_infinity=False).numpy()


@with_seed(0)
def test_ctc_loss_matches_torch_blank_first():
    T, N, C, L = 10, 4, 6, 3
    rng = onp.random.RandomState(0)
    acts = rng.randn(T, N, C).astype("f")
    # blank_label='first': classes are 1..C-1, padding value 0
    labels = rng.randint(1, C, (N, L)).astype("f")
    out = nd.ctc_loss(nd.array(acts), nd.array(labels))
    want = _torch_ctc(acts, labels.astype("i8"), [T] * N, [L] * N, blank=0)
    assert_almost_equal(_np(out), want, rtol=1e-4, atol=1e-4)


@with_seed(1)
def test_ctc_loss_matches_torch_blank_last():
    T, N, C, L = 8, 3, 5, 3
    rng = onp.random.RandomState(1)
    acts = rng.randn(T, N, C).astype("f")
    # blank_label='last': classes are 0..C-2, padding value -1
    labels = rng.randint(0, C - 1, (N, L)).astype("f")
    labels[1, 2] = -1  # row 1 has only 2 labels
    out = nd.ctc_loss(nd.array(acts), nd.array(labels), blank_label="last")
    want = _torch_ctc(acts, labels.astype("i8"), [T] * N, [L, 2, L],
                      blank=C - 1)
    assert_almost_equal(_np(out), want, rtol=1e-4, atol=1e-4)


@with_seed(4)
def test_ctc_loss_empty_target_and_bad_blank():
    T, N, C, L = 7, 2, 5, 3
    rng = onp.random.RandomState(4)
    acts = rng.randn(T, N, C).astype("f")
    labels = rng.randint(0, C - 1, (N, L)).astype("f")
    labels[0, :] = -1  # row 0: empty target -> loss is -sum_t log p_blank
    labels[1, 1] = -1  # row 1: MID-sequence pad -> packed to [l0, l2]
    out = nd.ctc_loss(nd.array(acts), nd.array(labels), blank_label="last")
    packed_row1 = labels[1][labels[1] >= 0].astype("i8")
    want = _torch_ctc(
        acts, onp.stack([onp.zeros(L, "i8"),
                         onp.pad(packed_row1, (0, L - len(packed_row1)))]),
        [T] * N, [0, len(packed_row1)], blank=C - 1)
    assert_almost_equal(_np(out), want, rtol=1e-4, atol=1e-4)
    with pytest.raises(ValueError):
        nd.ctc_loss(nd.array(acts), nd.array(labels), blank_label="middle")


@with_seed(2)
def test_ctc_loss_variable_lengths():
    T, N, C, L = 12, 3, 7, 4
    rng = onp.random.RandomState(2)
    acts = rng.randn(T, N, C).astype("f")
    labels = rng.randint(1, C, (N, L)).astype("f")
    dlen = onp.array([12, 9, 7], "f")
    llen = onp.array([4, 2, 3], "f")
    out = nd.ctc_loss(nd.array(acts), nd.array(labels),
                      data_lengths=nd.array(dlen),
                      label_lengths=nd.array(llen),
                      use_data_lengths=True, use_label_lengths=True)
    want = _torch_ctc(acts, labels.astype("i8"), dlen.astype("i8"),
                      llen.astype("i8"), blank=0)
    assert_almost_equal(_np(out), want, rtol=1e-4, atol=1e-4)


@with_seed(3)
def test_ctc_loss_gradient_matches_torch():
    import torch
    import torch.nn.functional as F

    T, N, C, L = 6, 2, 5, 2
    rng = onp.random.RandomState(3)
    acts = rng.randn(T, N, C).astype("f")
    labels = rng.randint(1, C, (N, L)).astype("f")
    x = nd.array(acts)
    x.attach_grad()
    with autograd.record():
        loss = nd.ctc_loss(x, nd.array(labels))
        loss.backward(nd.ones_like(loss))
    t = torch.tensor(acts, requires_grad=True)
    tl = F.ctc_loss(F.log_softmax(t, dim=-1), torch.tensor(
        labels.astype("i8")), [T] * N, [L] * N, blank=0, reduction="sum")
    tl.backward()
    assert_almost_equal(_np(x.grad), t.grad.numpy(), rtol=1e-3, atol=1e-4)


# -------------------------------------------------------------- resizing ---

def test_bilinear_resize2d_identity_and_upscale():
    rng = onp.random.RandomState(0)
    x = rng.rand(2, 3, 5, 7).astype("f")
    same = nd.bilinear_resize2d(nd.array(x), height=5, width=7)
    assert_almost_equal(_np(same), x, rtol=1e-6, atol=1e-6)
    up = nd.bilinear_resize2d(nd.array(x), height=10, width=14)
    assert up.shape == (2, 3, 10, 14)
    # corners are exact under align_corners=True
    got = _np(up)
    assert_almost_equal(got[..., 0, 0], x[..., 0, 0], rtol=1e-5, atol=1e-6)
    assert_almost_equal(got[..., -1, -1], x[..., -1, -1],
                        rtol=1e-5, atol=1e-6)


def test_bilinear_resize2d_scale_mode():
    x = nd.array(onp.arange(24, dtype="f").reshape(1, 1, 4, 6))
    out = nd.bilinear_resize2d(x, scale_height=2.0, scale_width=0.5,
                               mode="scale")
    assert out.shape == (1, 1, 8, 3)


def test_adaptive_avg_pooling2d_global_and_even():
    rng = onp.random.RandomState(1)
    x = rng.rand(2, 4, 6, 6).astype("f")
    g = nd.contrib.adaptive_avg_pooling2d(nd.array(x), output_size=1)
    assert_almost_equal(_np(g)[..., 0, 0], x.mean((2, 3)),
                        rtol=1e-5, atol=1e-6)
    h = nd.contrib.adaptive_avg_pooling2d(nd.array(x), output_size=3)
    want = x.reshape(2, 4, 3, 2, 3, 2).mean((3, 5))
    assert_almost_equal(_np(h), want, rtol=1e-5, atol=1e-6)


# ------------------------------------------------------- optimizer rules ---

def _opt_data(shape=(7, 3), seed=0, n_extra=0):
    rng = onp.random.RandomState(seed)
    return [rng.randn(*shape).astype("f") for _ in range(2 + n_extra)]


def test_rmsprop_update_formula():
    w, g, n = _opt_data(n_extra=1)
    n = onp.square(n)
    lr, gamma1, eps, wd = 0.02, 0.9, 1e-8, 0.01
    w2, n2 = nd.rmsprop_update(nd.array(w), nd.array(g), nd.array(n), lr,
                               gamma1=gamma1, epsilon=eps, wd=wd)
    ge = g + wd * w
    n_want = (1 - gamma1) * ge ** 2 + gamma1 * n
    w_want = w - lr * ge / onp.sqrt(n_want + eps)
    assert_almost_equal(_np(n2), n_want, rtol=1e-5, atol=1e-6)
    assert_almost_equal(_np(w2), w_want, rtol=1e-5, atol=1e-6)


def test_rmspropalex_update_formula():
    w, g, n, gbar, delta = _opt_data(n_extra=3)
    n = onp.square(n)
    # a consistent EMA state keeps n - gbar^2 >= 0 (as in real trajectories)
    gbar = onp.zeros_like(gbar)
    delta = onp.zeros_like(delta)
    lr, g1, g2, eps = 0.01, 0.95, 0.9, 1e-8
    outs = nd.rmspropalex_update(
        nd.array(w), nd.array(g), nd.array(n), nd.array(gbar),
        nd.array(delta), lr, gamma1=g1, gamma2=g2, epsilon=eps)
    n_want = (1 - g1) * g ** 2 + g1 * n
    g_want = (1 - g1) * g + g1 * gbar
    d_want = g2 * delta - lr * g / onp.sqrt(n_want - g_want ** 2 + eps)
    assert_almost_equal(_np(outs[1]), n_want, rtol=1e-5, atol=1e-6)
    assert_almost_equal(_np(outs[2]), g_want, rtol=1e-5, atol=1e-6)
    assert_almost_equal(_np(outs[3]), d_want, rtol=1e-4, atol=1e-5)
    assert_almost_equal(_np(outs[0]), w + d_want, rtol=1e-4, atol=1e-5)


def test_ftrl_update_formula():
    w, g, z, n = _opt_data(n_extra=2)
    n = onp.square(n)
    lr, l1, beta, wd = 0.1, 0.05, 1.0, 0.01
    w2, z2, n2 = nd.ftrl_update(nd.array(w), nd.array(g), nd.array(z),
                                nd.array(n), lr, lamda1=l1, beta=beta, wd=wd)
    n_want = n + g ** 2
    z_want = z + g - (onp.sqrt(n_want) - onp.sqrt(n)) / lr * w
    w_want = onp.where(
        onp.abs(z_want) <= l1, 0.0,
        -(z_want - onp.sign(z_want) * l1)
        / ((beta + onp.sqrt(n_want)) / lr + wd))
    assert_almost_equal(_np(z2), z_want, rtol=1e-5, atol=1e-6)
    assert_almost_equal(_np(n2), n_want, rtol=1e-5, atol=1e-6)
    assert_almost_equal(_np(w2), w_want, rtol=1e-5, atol=1e-6)
    # sparsifying property: small |z| coordinates land exactly at zero
    assert (onp.abs(_np(w2))[onp.abs(z_want) <= l1] == 0).all()


def test_ftml_update_formula():
    w, g, d, v, z = _opt_data(n_extra=3)
    v = onp.square(v)
    lr, b1, b2, eps, t = 0.05, 0.6, 0.999, 1e-8, 3
    outs = nd.ftml_update(nd.array(w), nd.array(g), nd.array(d),
                          nd.array(v), nd.array(z), lr, beta1=b1, beta2=b2,
                          epsilon=eps, t=t)
    v_want = b2 * v + (1 - b2) * g ** 2
    d_want = (1 - b1 ** t) / lr * (onp.sqrt(v_want / (1 - b2 ** t)) + eps)
    sigma = d_want - b1 * d
    z_want = b1 * z + (1 - b1) * g - sigma * w
    assert_almost_equal(_np(outs[1]), d_want, rtol=1e-5, atol=1e-6)
    assert_almost_equal(_np(outs[2]), v_want, rtol=1e-5, atol=1e-6)
    assert_almost_equal(_np(outs[3]), z_want, rtol=1e-5, atol=1e-6)
    assert_almost_equal(_np(outs[0]), -z_want / d_want, rtol=1e-5, atol=1e-6)


def test_nag_mom_update_formula():
    w, g, m = _opt_data(n_extra=1)
    lr, mom, wd = 0.1, 0.9, 0.01
    w2, m2 = nd.nag_mom_update(nd.array(w), nd.array(g), nd.array(m), lr,
                               momentum=mom, wd=wd)
    ge = g + wd * w
    m_want = mom * m + ge
    w_want = w - lr * (ge + mom * m_want)
    assert_almost_equal(_np(m2), m_want, rtol=1e-5, atol=1e-6)
    assert_almost_equal(_np(w2), w_want, rtol=1e-5, atol=1e-6)


def test_signsgd_signum_formulas():
    w, g, m = _opt_data(n_extra=1)
    lr = 0.01
    w2 = nd.signsgd_update(nd.array(w), nd.array(g), lr)
    assert_almost_equal(_np(w2), w - lr * onp.sign(g), rtol=1e-6, atol=1e-7)
    mom, wd_lh = 0.9, 0.1
    w3, m3 = nd.signum_update(nd.array(w), nd.array(g), nd.array(m), lr,
                              momentum=mom, wd_lh=wd_lh)
    m_want = mom * m - (1 - mom) * g
    assert_almost_equal(_np(m3), m_want, rtol=1e-5, atol=1e-6)
    assert_almost_equal(_np(w3), (1 - lr * wd_lh) * w + lr * onp.sign(m_want),
                        rtol=1e-5, atol=1e-6)


def test_update_ops_clip_and_rescale():
    w, g = _opt_data()
    w2 = nd.sgd_update(nd.array(w), nd.array(g), 1.0, rescale_grad=0.5,
                       clip_gradient=0.1)
    want = w - onp.clip(0.5 * g, -0.1, 0.1)
    assert_almost_equal(_np(w2), want, rtol=1e-6, atol=1e-7)


# --------------------------------------------------- amp / grad plumbing ---

def test_multi_sum_sq_and_all_finite():
    rng = onp.random.RandomState(2)
    arrs = [rng.randn(4, 5).astype("f"), rng.randn(7).astype("f")]
    outs = nd.multi_sum_sq(*[nd.array(a) for a in arrs])
    for o, a in zip(outs, arrs):
        assert_almost_equal(_np(o), [(a ** 2).sum()], rtol=1e-5, atol=1e-6)
    ok = nd.all_finite(*[nd.array(a) for a in arrs])
    assert _np(ok)[0] == 1.0
    arrs[1][3] = onp.inf
    bad = nd.all_finite(*[nd.array(a) for a in arrs])
    assert _np(bad)[0] == 0.0
    nan = nd.all_finite(nd.array(onp.array([onp.nan], "f")))
    assert _np(nan)[0] == 0.0


def test_amp_multicast_widest_type():
    a = nd.array(onp.ones((2, 2), "f")).astype("float16")
    b = nd.array(onp.ones((2, 2), "f"))
    outs = nd.amp_multicast(a, b, num_outputs=2)
    assert all(o.dtype == onp.float32 for o in outs)


# ------------------------------------------------------------- indexing ---

def test_batch_take_rows():
    x = onp.arange(12, dtype="f").reshape(4, 3)
    idx = onp.array([2, 0, 1, 2], "f")
    out = nd.batch_take(nd.array(x), nd.array(idx))
    assert_almost_equal(_np(out), x[onp.arange(4), idx.astype(int)],
                        rtol=0, atol=0)


def test_index_copy_semantics():
    old = nd.zeros((5, 3))
    new = nd.array(onp.arange(6, dtype="f").reshape(2, 3))
    out = nd.contrib.index_copy(old, nd.array(onp.array([1, 3], "f")), new)
    want = onp.zeros((5, 3), "f")
    want[[1, 3]] = _np(new)
    assert_almost_equal(_np(out), want, rtol=0, atol=0)


def test_split_v2_sections_indices_squeeze():
    x = onp.arange(24, dtype="f").reshape(6, 4)
    parts = nd.split_v2(nd.array(x), 3)
    assert len(parts) == 3 and parts[0].shape == (2, 4)
    assert_almost_equal(_np(parts[1]), x[2:4], rtol=0, atol=0)
    uneven = nd.split_v2(nd.array(x), (1, 4), axis=0)
    assert [p.shape[0] for p in uneven] == [1, 3, 2]
    assert_almost_equal(_np(uneven[1]), x[1:4], rtol=0, atol=0)
    sq = nd.split_v2(nd.array(x), 6, axis=0, squeeze_axis=True)
    assert sq[0].shape == (4,)


def test_mean_all_scalar():
    rng = onp.random.RandomState(3)
    x = rng.rand(3, 4, 5).astype("f")
    out = nd.mean_all(nd.array(x))
    assert out.shape == ()
    assert_almost_equal(_np(out), x.mean(), rtol=1e-6, atol=1e-7)


# ----------------------------------------------------------- svm_output ---

def test_svm_output_forward_identity_and_hinge_grad():
    # Reference svm_output-inl.h L1_SVM/L2_SVM: one-vs-rest hinge — the
    # true-class logit is pushed above +margin, every other logit below
    # -margin, each element independently.
    rng = onp.random.RandomState(4)
    x = rng.randn(4, 5).astype("f")
    y = onp.array([0, 2, 4, 1], "f")
    margin, reg = 0.7, 1.3
    for use_linear in (True, False):
        xv = nd.array(x)
        xv.attach_grad()
        with autograd.record():
            out = nd.svm_output(xv, nd.array(y), margin=margin,
                                regularization_coefficient=reg,
                                use_linear=use_linear)
            out.backward(nd.ones_like(out))
        assert_almost_equal(_np(out), x, rtol=1e-6, atol=1e-7)
        onehot = onp.eye(5, dtype="f")[y.astype(int)]
        signed = onp.where(onehot > 0, x, -x)
        sgn = onp.where(onehot > 0, -1.0, 1.0)
        if use_linear:
            want = onp.where(margin - signed > 0, sgn, 0.0) * reg
        else:
            want = onp.where(margin - signed > 0,
                             2.0 * (margin - signed) * sgn, 0.0) * reg
        assert_almost_equal(_np(xv.grad), want, rtol=1e-5, atol=1e-6)


@with_seed(5)
def test_sample_ops_per_row_params():
    mu = nd.array(onp.array([[0.0], [10.0]], "f").reshape(2))
    sig = nd.array(onp.array([1.0, 2.0], "f"))
    s = nd.sample_normal(mu=mu, sigma=sig, shape=(4000,))
    assert s.shape == (2, 4000)
    m = _np(s).mean(1)
    assert abs(m[0]) < 0.2 and abs(m[1] - 10) < 0.4
    u = nd.sample_uniform(low=nd.array(onp.array([0.0, 5.0], "f")),
                          high=nd.array(onp.array([1.0, 6.0], "f")),
                          shape=(1000,))
    un = _np(u)
    assert un[0].min() >= 0 and un[0].max() <= 1
    assert un[1].min() >= 5 and un[1].max() <= 6
