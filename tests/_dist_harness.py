"""Shared harness for multi-OS-process launcher tests: run N workers
through tools/launch.py (local mode, jax.distributed rendezvous) on a
FREE coordinator port, with the env scrubbed so each process owns one
CPU device. Worker bodies write per-rank result files the caller
asserts on."""
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PREAMBLE = r"""
import os, sys
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, {repo!r})
from mxnet_tpu.tools import launch
assert launch.init(), "launcher env missing"
"""


def free_port():
    """An OS-assigned free TCP port (avoids rendezvous collisions with
    concurrently running launcher tests or orphans of timed-out ones)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_launched_workers(tmp_path, body, n=2, timeout=360):
    """Write `_PREAMBLE + body` as the worker script (formatted with
    repo=REPO, outdir=str(tmp_path)) and run it under
    ``launch.py -n N --launcher local`` on a free port. Returns the
    CompletedProcess; asserts rc==0 with captured output on failure."""
    worker = tmp_path / "worker.py"
    worker.write_text((_PREAMBLE + body).format(repo=REPO,
                                                outdir=str(tmp_path)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)  # one CPU device per process
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    proc = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.tools.launch", "-n", str(n),
         "--launcher", "local", "--port", str(free_port()),
         sys.executable, str(worker)],
        env=env, capture_output=True, text=True, timeout=timeout,
        cwd=REPO)
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-2000:])
    return proc
