"""Multi-PROCESS expert/pipeline parallelism: two OS processes form a
global 2-device mesh and run one jitted MoE training step (the
all-to-all dispatch/combine crossing the process boundary) and one
pipelined forward (ppermute handoff across processes) — the multi-host
face of parallel/moe.py and parallel/pipeline.py."""
import pytest

from _dist_harness import run_launched_workers

BODY = r"""
import numpy as onp
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import mxnet_tpu
from mxnet_tpu.parallel.moe import moe_ffn
from mxnet_tpu.parallel.pipeline import pipeline_apply

rank = jax.process_index()
devs = jax.devices()
assert len(devs) == 2, devs
mesh = Mesh(onp.array(devs), ("ep",))
rng = onp.random.RandomState(0)
E, D, H = 4, 8, 16
params = (jnp.asarray(rng.randn(D, E).astype("f") * 0.5),
          jnp.asarray(rng.randn(E, D, H).astype("f") * 0.2),
          jnp.zeros((E, H), jnp.float32),
          jnp.asarray(rng.randn(E, H, D).astype("f") * 0.2),
          jnp.zeros((E, D), jnp.float32))
x = jnp.asarray(rng.randn(8, 4, D).astype("f"))
y = jnp.asarray(rng.randn(8, 4, D).astype("f"))

@jax.jit
def step(ps, xv, yv):
    def loss_fn(p):
        out, aux = moe_ffn(xv, *p, mesh=mesh, axis_name="ep",
                           batch_axes=("ep",), capacity_factor=4.0)
        return jnp.mean((xv + out - yv) ** 2) + 0.01 * aux

    l, g = jax.value_and_grad(loss_fn)(ps)
    return tuple(w - 0.1 * gi for w, gi in zip(ps, g)), l

params, l1 = step(params, x, y)
params, l2 = step(params, x, y)
moe_ok = bool(jnp.isfinite(l1)) and float(l2) < float(l1)

# pipeline over the same 2 processes ('pp' axis)
mesh_pp = Mesh(onp.array(devs), ("pp",))
sp = (jnp.asarray(rng.randn(2, D, D).astype("f") * 0.3),
      jnp.asarray(rng.randn(2, D).astype("f") * 0.1))
xp = jnp.asarray(rng.randn(8, D).astype("f"))

def stage(p, act):
    w, b = p
    return jnp.tanh(act @ w + b)

got = pipeline_apply(stage, sp, xp, mesh=mesh_pp, n_microbatches=4)
act = onp.asarray(xp)
for i in range(2):
    act = onp.tanh(act @ onp.asarray(sp[0][i]) + onp.asarray(sp[1][i]))
# the pipelined output is replicated (out_specs=P()): each process's
# addressable copy must equal the full sequential stack
vals = [onp.asarray(s.data) for s in got.addressable_shards]
pp_ok = bool(vals) and all(
    v.shape == act.shape and onp.allclose(v, act, rtol=2e-4, atol=2e-5)
    for v in vals)

with open(os.path.join({outdir!r}, "r" + str(rank) + ".txt"), "w") as f:
    f.write("OK" if (moe_ok and pp_ok) else
            "BAD moe=%s pp=%s" % (moe_ok, pp_ok))
"""


def test_two_process_moe_and_pipeline(tmp_path):
    run_launched_workers(tmp_path, BODY, n=2)
    for rank in (0, 1):
        p = tmp_path / f"r{rank}.txt"
        assert p.is_file(), f"worker {rank} produced no result"
        assert p.read_text() == "OK", p.read_text()
