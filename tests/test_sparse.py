"""Sparse NDArray tests (reference analog: tests/python/unittest/
test_sparse_ndarray.py, test_sparse_operator.py — 35+ test fns)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ndarray import sparse


def dense_rand(shape, density=0.3, seed=0):
    rs = onp.random.RandomState(seed)
    arr = rs.rand(*shape).astype(onp.float32)
    mask = rs.rand(*shape) < density
    return arr * mask


def test_csr_roundtrip():
    d = dense_rand((6, 8))
    a = nd.array(d)
    csr = sparse.cast_storage(a, "csr")
    assert csr.stype == "csr"
    assert csr.shape == (6, 8)
    assert csr.nnz == int((d != 0).sum())
    onp.testing.assert_allclose(csr.asnumpy(), d, rtol=1e-6)
    back = csr.tostype("default")
    assert back.stype == "default"
    onp.testing.assert_allclose(back.asnumpy(), d, rtol=1e-6)


def test_csr_matrix_from_triplet():
    data = [1.0, 2.0, 3.0]
    indices = [0, 2, 1]
    indptr = [0, 2, 2, 3]
    csr = sparse.csr_matrix((data, indices, indptr), shape=(3, 4))
    expect = onp.zeros((3, 4), onp.float32)
    expect[0, 0], expect[0, 2], expect[2, 1] = 1, 2, 3
    onp.testing.assert_allclose(csr.asnumpy(), expect)
    # aux accessors mirror reference API
    assert csr.indices.asnumpy().tolist() == indices
    assert csr.indptr.asnumpy().tolist() == indptr
    assert csr.data.asnumpy().tolist() == data


def test_row_sparse_roundtrip():
    d = onp.zeros((8, 3), onp.float32)
    d[2] = [1, 2, 3]
    d[5] = [4, 5, 6]
    rsp = sparse.cast_storage(nd.array(d), "row_sparse")
    assert rsp.stype == "row_sparse"
    assert rsp.nnz == 2
    assert rsp.indices.asnumpy().tolist() == [2, 5]
    onp.testing.assert_allclose(rsp.asnumpy(), d)


def test_row_sparse_array_ctor():
    rsp = sparse.row_sparse_array(
        ([[1.0, 2.0], [3.0, 4.0]], [1, 3]), shape=(5, 2))
    expect = onp.zeros((5, 2), onp.float32)
    expect[1] = [1, 2]
    expect[3] = [3, 4]
    onp.testing.assert_allclose(rsp.asnumpy(), expect)


def test_sparse_zeros():
    csr = sparse.zeros("csr", (3, 4))
    assert csr.nnz == 0 and csr.shape == (3, 4)
    onp.testing.assert_allclose(csr.asnumpy(), onp.zeros((3, 4)))
    rsp = sparse.zeros("row_sparse", (3, 4))
    assert rsp.nnz == 0
    onp.testing.assert_allclose(rsp.asnumpy(), onp.zeros((3, 4)))


def test_csr_dot_dense():
    d = dense_rand((5, 7), seed=1)
    w = onp.random.RandomState(2).rand(7, 4).astype(onp.float32)
    csr = sparse.cast_storage(nd.array(d), "csr")
    out = sparse.dot(csr, nd.array(w))
    onp.testing.assert_allclose(out.asnumpy(), d @ w, rtol=1e-5)
    # transpose_a: csr.T @ dense
    w2 = onp.random.RandomState(3).rand(5, 4).astype(onp.float32)
    out_t = sparse.dot(csr, nd.array(w2), transpose_a=True)
    onp.testing.assert_allclose(out_t.asnumpy(), d.T @ w2, rtol=1e-5)


def test_retain():
    rsp = sparse.row_sparse_array(
        ([[1.0, 1.0], [2.0, 2.0], [3.0, 3.0]], [0, 2, 4]), shape=(6, 2))
    kept = sparse.retain(rsp, nd.array([2, 3, 4]))
    assert kept.indices.asnumpy().tolist() == [2, 3, 4]
    expect = onp.zeros((6, 2), onp.float32)
    expect[2] = 2
    expect[4] = 3
    onp.testing.assert_allclose(kept.asnumpy(), expect)


def test_elemwise_add_stypes():
    a = sparse.row_sparse_array(([[1.0, 1.0]], [1]), shape=(3, 2))
    b = sparse.row_sparse_array(([[2.0, 2.0]], [1]), shape=(3, 2))
    s = sparse.elemwise_add(a, b)
    assert s.stype == "row_sparse"
    expect = onp.zeros((3, 2), onp.float32)
    expect[1] = 3
    onp.testing.assert_allclose(s.asnumpy(), expect)
    dense = nd.ones((3, 2))
    mixed = sparse.elemwise_add(a, dense)
    assert mixed.stype == "default"
    onp.testing.assert_allclose(mixed.asnumpy(), expect / 3 + 1)


def test_sparse_sgd_lazy_update():
    w0 = onp.ones((6, 3), onp.float32)
    weight = nd.array(w0)
    grad = sparse.row_sparse_array(
        (onp.full((2, 3), 0.5, onp.float32), [1, 4]), shape=(6, 3))
    opt = mx.optimizer.SGD(learning_rate=0.1, lazy_update=True)
    opt.update(0, weight, grad, opt.create_state(0, weight))
    out = weight.asnumpy()
    expect = w0.copy()
    expect[[1, 4]] -= 0.1 * 0.5
    onp.testing.assert_allclose(out, expect, rtol=1e-6)


def test_sparse_adam_lazy_update():
    w0 = onp.ones((5, 2), onp.float32)
    weight = nd.array(w0)
    grad = sparse.row_sparse_array(
        (onp.full((1, 2), 1.0, onp.float32), [3]), shape=(5, 2))
    opt = mx.optimizer.Adam(learning_rate=0.01)
    state = opt.create_state(0, weight)
    opt.update(0, weight, grad, state)
    out = weight.asnumpy()
    # untouched rows unchanged
    onp.testing.assert_allclose(out[[0, 1, 2, 4]], w0[[0, 1, 2, 4]])
    assert (out[3] < 1.0).all()


def test_kvstore_sparse_push():
    kv = mx.kv.create("local")
    kv.init("w", nd.zeros((4, 2)))
    g1 = sparse.row_sparse_array(([[1.0, 1.0]], [0]), shape=(4, 2))
    g2 = sparse.row_sparse_array(([[2.0, 2.0]], [3]), shape=(4, 2))
    kv.push("w", [g1, g2])
    out = nd.zeros((4, 2))
    kv.pull("w", out=out)
    expect = onp.zeros((4, 2), onp.float32)
    expect[0] = 1
    expect[3] = 2
    onp.testing.assert_allclose(out.asnumpy(), expect)


def test_row_sparse_pull():
    kv = mx.kv.create("local")
    w = onp.arange(12, dtype=onp.float32).reshape(6, 2)
    kv.init("e", nd.array(w))
    out = nd.zeros((3, 2))
    kv.row_sparse_pull("e", out=out, row_ids=nd.array([1, 3, 5]))
    onp.testing.assert_allclose(out.asnumpy(), w[[1, 3, 5]])


def test_sparse_dot_in_jit():
    """csr dot with static nnz compiles under jit (TPU path)."""
    import jax
    import jax.numpy as jnp

    d = dense_rand((4, 6), seed=5)
    csr = sparse.cast_storage(nd.array(d), "csr")
    w = onp.random.RandomState(6).rand(6, 3).astype(onp.float32)

    @jax.jit
    def f(vals, idx, indptr, wj):
        c = sparse.CSRNDArray(vals, idx, indptr, (4, 6))
        return sparse.dot(c, mx.NDArray(wj)).data

    out = f(csr.data.data, csr.indices.data, csr.indptr.data,
            jnp.asarray(w))
    onp.testing.assert_allclose(onp.asarray(out), d @ w, rtol=1e-5)


def test_unsupported_ops_raise():
    csr = sparse.zeros("csr", (2, 2))
    with pytest.raises(mx.MXNetError):
        csr[0, 1]
    with pytest.raises(mx.MXNetError):
        csr[0] = 1.0


def test_review_regressions():
    import jax.numpy as jnp
    from mxnet_tpu import np as mnp

    # rsp+rsp with overlapping rows merges duplicates
    a = sparse.row_sparse_array(([[1.0, 1.0]], [2]), shape=(4, 2))
    b = sparse.row_sparse_array(([[2.0, 2.0]], [2]), shape=(4, 2))
    s = sparse.elemwise_add(a, b)
    assert s.indices.asnumpy().tolist() == [2]
    onp.testing.assert_allclose(s.asnumpy()[2], [3.0, 3.0])
    # ...and the lazy SGD update after kvstore aggregation is exact
    kv = mx.kv.create("local")
    w = nd.ones((4, 2))
    opt = mx.optimizer.SGD(learning_rate=1.0)
    kv.init("w2", w)
    kv.set_optimizer(opt)
    kv.push("w2", [a, b])
    out = nd.zeros((4, 2))
    kv.pull("w2", out=out)
    onp.testing.assert_allclose(out.asnumpy()[2], [-2.0, -2.0])

    # sparse copy preserves format/shape
    c = a.copy()
    assert c.stype == "row_sparse" and c.shape == (4, 2)

    # dot transpose_b
    d = dense_rand((2, 3), seed=7)
    csr = sparse.cast_storage(nd.array(d), "csr")
    w2 = onp.random.RandomState(8).rand(4, 3).astype(onp.float32)
    out_b = sparse.dot(csr, nd.array(w2), transpose_b=True)
    onp.testing.assert_allclose(out_b.asnumpy(), d @ w2.T, rtol=1e-5)

    # retain on empty rsp returns zeros
    empty = sparse.zeros("row_sparse", (4, 2))
    r = sparse.retain(empty, nd.array([0, 1]))
    onp.testing.assert_allclose(r.asnumpy(), onp.zeros((4, 2)))

    # np.random array params broadcast for gamma/beta/poisson/chisquare
    g = mnp.random.gamma(mnp.array([1.0, 2.0]))
    assert g.shape == (2,)
    bt = mnp.random.beta(mnp.array([1.0, 2.0]), mnp.array([2.0, 3.0]))
    assert bt.shape == (2,)
    ch = mnp.random.chisquare(mnp.array([1.0, 2.0, 3.0]))
    assert ch.shape == (3,)
