"""The north-star configuration as a test: .rec -> native JPEG decode ->
ImageRecordIter augment -> SPMDTrainer compiled step (reference:
example/image-classification/train_imagenet.py)."""
import os
import subprocess
import sys

import pytest


def test_train_imagenet_rec_example_runs():
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    out = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "examples",
                      "train_imagenet_rec.py"),
         "--images", "64", "--batch", "8", "--image-size", "32",
         "--depth", "18", "--steps", "3", "--threads", "2"],
        env=env, capture_output=True, text=True, timeout=540)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "pipeline" in out.stdout and "img/s" in out.stdout, out.stdout


def test_train_gan_toy_example_converges():
    """Adversarial two-Trainer pattern (reference example/gluon/dcgan):
    the generator must move its mass from the origin toward the ring."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    out = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "examples",
                      "train_gan_toy.py"), "--steps", "150"],
        env=env, capture_output=True, text=True, timeout=540)
    assert out.returncode == 0, out.stderr[-2000:]
    import re

    m = re.search(r"mean radius ([0-9.]+)", out.stdout)
    assert m, out.stdout
    assert 0.8 < float(m.group(1)) < 3.5, out.stdout


def test_device_prefetch_iter_overlap(tmp_path):
    """DevicePrefetchIter stages batches to the device off-thread and
    preserves order/content; reset restarts the stream."""
    import numpy as onp

    from mxnet_tpu import io as mxio, nd

    X = onp.arange(8 * 4, dtype="f").reshape(8, 4)
    Y = onp.arange(8, dtype="f")
    base = mxio.NDArrayIter(nd.array(X), nd.array(Y), batch_size=4)
    pf = mxio.DevicePrefetchIter(base)
    b1 = next(pf)
    b2 = next(pf)
    onp.testing.assert_allclose(b1.data[0].asnumpy(), X[:4])
    onp.testing.assert_allclose(b2.data[0].asnumpy(), X[4:])
    try:
        next(pf)
        assert False, "expected StopIteration"
    except StopIteration:
        pass
    pf.reset()
    again = [b.data[0].asnumpy() for b in pf]
    assert len(again) == 2
    onp.testing.assert_allclose(again[0], X[:4])


@pytest.mark.slow  # same example as the _runs test above, +overlap JSON
def test_train_imagenet_rec_overlap_report(tmp_path):
    import json
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "examples",
                                      "train_imagenet_rec.py"),
         "--images", "96", "--batch", "16", "--image-size", "32",
         "--depth", "18", "--steps", "3", "--overlap-report"],
        env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-1500:]
    line = [l for l in r.stdout.splitlines()
            if l.startswith("{") and "data_fed" in l]
    assert line, r.stdout
    payload = json.loads(line[-1])
    assert payload["extra"]["overlap_efficiency_pct"] > 30


def test_recommender_mf_example_converges():
    """examples/train_recommender_mf.py: two-Embedding dot-product MF
    (reference example/recommenders) converges on synthetic ratings."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run(
        [sys.executable,
         os.path.join(repo, "examples", "train_recommender_mf.py"),
         "--epochs", "10", "--ratings", "2000"],
        env=env, capture_output=True, text=True, timeout=500)
    assert r.returncode == 0, (r.stdout[-500:], r.stderr[-500:])
    assert "->" in r.stdout
