"""The north-star configuration as a test: .rec -> native JPEG decode ->
ImageRecordIter augment -> SPMDTrainer compiled step (reference:
example/image-classification/train_imagenet.py)."""
import os
import subprocess
import sys


def test_train_imagenet_rec_example_runs():
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    out = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "examples",
                      "train_imagenet_rec.py"),
         "--images", "64", "--batch", "8", "--image-size", "32",
         "--depth", "18", "--steps", "3", "--threads", "2"],
        env=env, capture_output=True, text=True, timeout=540)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "pipeline" in out.stdout and "img/s" in out.stdout, out.stdout
