"""The north-star configuration as a test: .rec -> native JPEG decode ->
ImageRecordIter augment -> SPMDTrainer compiled step (reference:
example/image-classification/train_imagenet.py)."""
import os
import subprocess
import sys


def test_train_imagenet_rec_example_runs():
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    out = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "examples",
                      "train_imagenet_rec.py"),
         "--images", "64", "--batch", "8", "--image-size", "32",
         "--depth", "18", "--steps", "3", "--threads", "2"],
        env=env, capture_output=True, text=True, timeout=540)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "pipeline" in out.stdout and "img/s" in out.stdout, out.stdout


def test_train_gan_toy_example_converges():
    """Adversarial two-Trainer pattern (reference example/gluon/dcgan):
    the generator must move its mass from the origin toward the ring."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    out = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "examples",
                      "train_gan_toy.py"), "--steps", "150"],
        env=env, capture_output=True, text=True, timeout=540)
    assert out.returncode == 0, out.stderr[-2000:]
    import re

    m = re.search(r"mean radius ([0-9.]+)", out.stdout)
    assert m, out.stdout
    assert 0.8 < float(m.group(1)) < 3.5, out.stdout
