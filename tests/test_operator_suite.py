"""Op-spec suite, part 1: unary math, binary/broadcast, reductions,
shape manipulation — value checks against numpy oracles + numeric
gradients for the differentiable families.

Reference coverage model: tests/python/unittest/test_operator.py
(test_unary_math_operators, test_binary_op, test_reduce,
test_reshape/test_transpose/...).
"""
import math

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.test_utils import (assert_almost_equal,
                                  check_numeric_gradient)

rs = onp.random.RandomState(77)


def _x(shape=(3, 4), lo=-2.0, hi=2.0):
    return (rs.rand(*shape) * (hi - lo) + lo).astype("f")


# ------------------------------------------------------------ unary math ---

def _unary_case(opname, np_fn, lo=-2.0, hi=2.0, grad=True, rtol=1e-4):
    x = _x(lo=lo, hi=hi)
    out = getattr(nd, opname)(nd.array(x))
    assert_almost_equal(out.asnumpy(), np_fn(x).astype("f"), rtol=rtol,
                        atol=1e-5)
    if grad:
        check_numeric_gradient(lambda a: getattr(nd, opname)(a), [x],
                               rtol=2e-2, atol=1e-3)


def test_op_exp():
    _unary_case("exp", onp.exp)


def test_op_log():
    _unary_case("log", onp.log, lo=0.1, hi=4.0)


def test_op_log2_log10_log1p_expm1():
    for name, fn, lo in [("log2", onp.log2, 0.1), ("log10", onp.log10,
                                                   0.1),
                         ("log1p", onp.log1p, -0.5),
                         ("expm1", onp.expm1, -1.0)]:
        x = _x(lo=lo, hi=3.0)
        assert_almost_equal(getattr(nd, name)(nd.array(x)).asnumpy(),
                            fn(x).astype("f"), rtol=1e-4, atol=1e-5)


def test_op_sqrt_rsqrt_cbrt_rcbrt():
    x = _x(lo=0.2, hi=4.0)
    assert_almost_equal(nd.sqrt(nd.array(x)).asnumpy(), onp.sqrt(x),
                        rtol=1e-5)
    assert_almost_equal(nd.rsqrt(nd.array(x)).asnumpy(),
                        1 / onp.sqrt(x), rtol=1e-5)
    assert_almost_equal(nd.cbrt(nd.array(x)).asnumpy(), onp.cbrt(x),
                        rtol=1e-5)
    assert_almost_equal(nd.rcbrt(nd.array(x)).asnumpy(),
                        1 / onp.cbrt(x), rtol=1e-5)


def test_op_square_reciprocal():
    _unary_case("square", onp.square)
    _unary_case("reciprocal", lambda v: 1.0 / v, lo=0.5, hi=3.0)


def test_op_abs_sign_negative():
    x = _x()
    assert_almost_equal(nd.abs(nd.array(x)).asnumpy(), onp.abs(x),
                        rtol=1e-6)
    assert_almost_equal(nd.sign(nd.array(x)).asnumpy(), onp.sign(x),
                        rtol=1e-6)
    assert_almost_equal(nd.negative(nd.array(x)).asnumpy(), -x,
                        rtol=1e-6)


def test_op_rounding_family():
    x = _x(lo=-3.0, hi=3.0)
    assert_almost_equal(nd.floor(nd.array(x)).asnumpy(), onp.floor(x),
                        rtol=1e-6)
    assert_almost_equal(nd.ceil(nd.array(x)).asnumpy(), onp.ceil(x),
                        rtol=1e-6)
    assert_almost_equal(nd.trunc(nd.array(x)).asnumpy(), onp.trunc(x),
                        rtol=1e-6)
    assert_almost_equal(nd.rint(nd.array(x)).asnumpy(), onp.rint(x),
                        rtol=1e-6)
    assert_almost_equal(nd.fix(nd.array(x)).asnumpy(), onp.fix(x),
                        rtol=1e-6)


def test_op_trig():
    x = _x(lo=-1.2, hi=1.2)
    for name, fn in [("sin", onp.sin), ("cos", onp.cos),
                     ("tan", onp.tan)]:
        assert_almost_equal(getattr(nd, name)(nd.array(x)).asnumpy(),
                            fn(x).astype("f"), rtol=1e-4, atol=1e-5)
    check_numeric_gradient(lambda a: nd.sin(a), [x], rtol=2e-2,
                           atol=1e-3)


def test_op_hyperbolic():
    x = _x(lo=-1.5, hi=1.5)
    for name, fn in [("sinh", onp.sinh), ("cosh", onp.cosh),
                     ("tanh", onp.tanh)]:
        assert_almost_equal(getattr(nd, name)(nd.array(x)).asnumpy(),
                            fn(x).astype("f"), rtol=1e-4, atol=1e-5)


def test_op_degrees_radians():
    x = _x(lo=-180, hi=180)
    assert_almost_equal(nd.degrees(nd.array(x)).asnumpy(),
                        onp.degrees(x).astype("f"), rtol=1e-5)
    assert_almost_equal(nd.radians(nd.array(x)).asnumpy(),
                        onp.radians(x).astype("f"), rtol=1e-5)


def test_op_erf_erfinv():
    x = _x(lo=-1.5, hi=1.5)
    expect = onp.array([[math.erf(v) for v in row] for row in x], "f")
    assert_almost_equal(nd.erf(nd.array(x)).asnumpy(), expect,
                        rtol=1e-4, atol=1e-5)
    y = _x(lo=-0.9, hi=0.9)
    inv = nd.erfinv(nd.array(y))
    back = onp.array([[math.erf(v) for v in row]
                      for row in inv.asnumpy()], "f")
    assert_almost_equal(back, y, rtol=1e-3, atol=1e-4)


def test_op_gamma_gammaln():
    x = _x(lo=0.5, hi=4.0)
    expect = onp.array([[math.gamma(v) for v in row] for row in x], "f")
    assert_almost_equal(nd.gamma(nd.array(x)).asnumpy(), expect,
                        rtol=1e-3)
    expectln = onp.array([[math.lgamma(v) for v in row] for row in x],
                         "f")
    assert_almost_equal(nd.gammaln(nd.array(x)).asnumpy(), expectln,
                        rtol=1e-3, atol=1e-4)


def test_op_sigmoid_softsign_hard_sigmoid():
    x = _x()
    assert_almost_equal(nd.sigmoid(nd.array(x)).asnumpy(),
                        1 / (1 + onp.exp(-x)), rtol=1e-4)
    assert_almost_equal(nd.softsign(nd.array(x)).asnumpy(),
                        x / (1 + onp.abs(x)), rtol=1e-4)
    hs = nd.hard_sigmoid(nd.array(x))
    assert_almost_equal(hs.asnumpy(),
                        onp.clip(0.2 * x + 0.5, 0, 1), rtol=1e-4)


def test_op_relu_grad_at_kink():
    x = onp.array([[-1.0, 0.5, 2.0, -0.25]], "f")
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.relu(a)
    y.backward()
    assert_almost_equal(a.grad.asnumpy(), (x > 0).astype("f"),
                        rtol=1e-6)


def test_op_clip_gradient_masks():
    x = onp.array([[-2.0, 0.0, 0.5, 3.0]], "f")
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.clip(a, -1.0, 1.0)
    y.backward()
    assert_almost_equal(y.asnumpy(), onp.clip(x, -1, 1), rtol=1e-6)
    assert_almost_equal(a.grad.asnumpy(),
                        ((x > -1) & (x < 1)).astype("f"), rtol=1e-6)


# --------------------------------------------------------- binary family ---

def test_op_elemwise_binary():
    a, b = _x(), _x(lo=0.5, hi=2.0)
    assert_almost_equal(nd.elemwise_add(nd.array(a),
                                        nd.array(b)).asnumpy(), a + b,
                        rtol=1e-5)
    assert_almost_equal(nd.elemwise_sub(nd.array(a),
                                        nd.array(b)).asnumpy(), a - b,
                        rtol=1e-5)
    assert_almost_equal(nd.elemwise_mul(nd.array(a),
                                        nd.array(b)).asnumpy(), a * b,
                        rtol=1e-5)
    assert_almost_equal(nd.elemwise_div(nd.array(a),
                                        nd.array(b)).asnumpy(), a / b,
                        rtol=1e-5)


def test_op_broadcast_binary_shapes():
    a = _x((2, 1, 4))
    b = _x((1, 3, 1))
    for name, fn in [("broadcast_add", onp.add),
                     ("broadcast_sub", onp.subtract),
                     ("broadcast_mul", onp.multiply),
                     ("broadcast_maximum", onp.maximum),
                     ("broadcast_minimum", onp.minimum)]:
        out = getattr(nd, name)(nd.array(a), nd.array(b))
        assert out.shape == (2, 3, 4)
        assert_almost_equal(out.asnumpy(), fn(a, b).astype("f"),
                            rtol=1e-5)


def test_op_broadcast_power_mod_hypot():
    a = _x(lo=0.5, hi=2.0)
    b = _x(lo=0.5, hi=2.0)
    assert_almost_equal(
        nd.broadcast_power(nd.array(a), nd.array(b)).asnumpy(),
        onp.power(a, b).astype("f"), rtol=1e-4)
    assert_almost_equal(
        nd.broadcast_mod(nd.array(a), nd.array(b)).asnumpy(),
        onp.mod(a, b).astype("f"), rtol=1e-4, atol=1e-5)
    assert_almost_equal(
        nd.broadcast_hypot(nd.array(a), nd.array(b)).asnumpy(),
        onp.hypot(a, b).astype("f"), rtol=1e-4)


def test_op_comparison_family():
    a = _x()
    b = _x()
    for name, fn in [("broadcast_equal", onp.equal),
                     ("broadcast_not_equal", onp.not_equal),
                     ("broadcast_greater", onp.greater),
                     ("broadcast_greater_equal", onp.greater_equal),
                     ("broadcast_lesser", onp.less),
                     ("broadcast_lesser_equal", onp.less_equal)]:
        out = getattr(nd, name)(nd.array(a), nd.array(b))
        assert_almost_equal(out.asnumpy(), fn(a, b).astype("f"),
                            rtol=1e-6)


def test_op_logical_family():
    a = (rs.rand(3, 4) > 0.5).astype("f")
    b = (rs.rand(3, 4) > 0.5).astype("f")
    assert_almost_equal(
        nd.broadcast_logical_and(nd.array(a), nd.array(b)).asnumpy(),
        onp.logical_and(a, b).astype("f"), rtol=1e-6)
    assert_almost_equal(
        nd.broadcast_logical_or(nd.array(a), nd.array(b)).asnumpy(),
        onp.logical_or(a, b).astype("f"), rtol=1e-6)
    assert_almost_equal(
        nd.broadcast_logical_xor(nd.array(a), nd.array(b)).asnumpy(),
        onp.logical_xor(a, b).astype("f"), rtol=1e-6)
    assert_almost_equal(nd.logical_not(nd.array(a)).asnumpy(),
                        onp.logical_not(a).astype("f"), rtol=1e-6)


def test_op_scalar_binops_reverse():
    a = _x(lo=0.5, hi=2.0)
    x = nd.array(a)
    assert_almost_equal((3.0 - x).asnumpy(), 3.0 - a, rtol=1e-5)
    assert_almost_equal((3.0 / x).asnumpy(), 3.0 / a, rtol=1e-5)
    assert_almost_equal((x ** 2.0).asnumpy(), a ** 2, rtol=1e-5)
    assert_almost_equal((2.0 ** x).asnumpy(), 2.0 ** a, rtol=1e-4)


def test_op_binary_gradients():
    a, b = _x(lo=0.5, hi=2.0), _x(lo=0.5, hi=2.0)
    check_numeric_gradient(
        lambda x, y: nd.broadcast_mul(x, y) + nd.broadcast_div(x, y),
        [a, b], rtol=2e-2, atol=1e-3)


def test_op_maximum_minimum_scalar():
    a = _x()
    assert_almost_equal(nd.maximum(nd.array(a), 0.5).asnumpy(),
                        onp.maximum(a, 0.5), rtol=1e-6)
    assert_almost_equal(nd.minimum(nd.array(a), 0.5).asnumpy(),
                        onp.minimum(a, 0.5), rtol=1e-6)


def test_op_where():
    cond = (rs.rand(3, 4) > 0.5).astype("f")
    a, b = _x(), _x()
    out = nd.where(nd.array(cond), nd.array(a), nd.array(b))
    assert_almost_equal(out.asnumpy(), onp.where(cond > 0, a, b),
                        rtol=1e-6)


def test_op_dot_transpose_flags():
    a = _x((3, 4))
    b = _x((4, 5))
    assert_almost_equal(nd.dot(nd.array(a), nd.array(b)).asnumpy(),
                        a @ b, rtol=1e-4)
    assert_almost_equal(
        nd.dot(nd.array(a.T), nd.array(b), transpose_a=True).asnumpy(),
        a @ b, rtol=1e-4)
    assert_almost_equal(
        nd.dot(nd.array(a), nd.array(b.T), transpose_b=True).asnumpy(),
        a @ b, rtol=1e-4)


# ------------------------------------------------------------ reductions ---

def test_op_sum_axis_exclude_keepdims():
    x = _x((2, 3, 4))
    assert_almost_equal(nd.sum(nd.array(x)).asnumpy(),
                        x.sum().astype("f"), rtol=1e-4)
    assert_almost_equal(nd.sum(nd.array(x), axis=1).asnumpy(),
                        x.sum(1), rtol=1e-4)
    assert_almost_equal(
        nd.sum(nd.array(x), axis=1, keepdims=True).asnumpy(),
        x.sum(1, keepdims=True), rtol=1e-4)
    assert_almost_equal(
        nd.sum(nd.array(x), axis=1, exclude=True).asnumpy(),
        x.sum(axis=(0, 2)), rtol=1e-4)


def test_op_mean_prod_max_min():
    x = _x((2, 3, 4), lo=0.5, hi=1.5)
    assert_almost_equal(nd.mean(nd.array(x), axis=2).asnumpy(),
                        x.mean(2), rtol=1e-4)
    assert_almost_equal(nd.prod(nd.array(x), axis=0).asnumpy(),
                        x.prod(0), rtol=1e-4)
    assert_almost_equal(nd.max(nd.array(x), axis=1).asnumpy(),
                        x.max(1), rtol=1e-5)
    assert_almost_equal(nd.min(nd.array(x), axis=1).asnumpy(),
                        x.min(1), rtol=1e-5)


def test_op_nansum_nanprod():
    x = _x((3, 4))
    x[0, 0] = onp.nan
    x[1, 2] = onp.nan
    assert_almost_equal(nd.nansum(nd.array(x), axis=0).asnumpy(),
                        onp.nansum(x, 0), rtol=1e-4)
    assert_almost_equal(nd.nanprod(nd.array(x), axis=0).asnumpy(),
                        onp.nanprod(x, 0), rtol=1e-4)


def test_op_norm_orders():
    x = _x((3, 4))
    assert_almost_equal(nd.norm(nd.array(x)).asnumpy(),
                        onp.linalg.norm(x).astype("f"), rtol=1e-4)
    assert_almost_equal(nd.norm(nd.array(x), ord=1, axis=1).asnumpy(),
                        onp.abs(x).sum(1), rtol=1e-4)
    assert_almost_equal(nd.norm(nd.array(x), ord=2, axis=0).asnumpy(),
                        onp.sqrt((x * x).sum(0)), rtol=1e-4)


def test_op_argmax_argmin():
    x = _x((3, 5))
    assert_almost_equal(nd.argmax(nd.array(x), axis=1).asnumpy(),
                        x.argmax(1).astype("f"), rtol=1e-6)
    assert_almost_equal(nd.argmin(nd.array(x), axis=0).asnumpy(),
                        x.argmin(0).astype("f"), rtol=1e-6)


def test_op_sum_gradient_broadcast_back():
    x = _x((2, 3))
    check_numeric_gradient(
        lambda a: nd.sum(a, axis=1, keepdims=True) * a, [x],
        rtol=2e-2, atol=1e-3)


# ------------------------------------------------------------ shape ops ---

def test_op_reshape_special_codes():
    x = _x((2, 3, 4))
    assert nd.reshape(nd.array(x), shape=(-1,)).shape == (24,)
    assert nd.reshape(nd.array(x), shape=(0, -1)).shape == (2, 12)
    assert nd.reshape(nd.array(x), shape=(4, 6)).shape == (4, 6)
    assert_almost_equal(
        nd.reshape(nd.array(x), shape=(4, 6)).asnumpy(),
        x.reshape(4, 6), rtol=1e-6)


def test_op_transpose_swapaxes():
    x = _x((2, 3, 4))
    assert_almost_equal(nd.transpose(nd.array(x)).asnumpy(),
                        x.T, rtol=1e-6)
    assert_almost_equal(
        nd.transpose(nd.array(x), axes=(1, 0, 2)).asnumpy(),
        x.transpose(1, 0, 2), rtol=1e-6)
    assert_almost_equal(nd.swapaxes(nd.array(x), 0, 2).asnumpy(),
                        x.swapaxes(0, 2), rtol=1e-6)


def test_op_flip_reverse():
    x = _x((2, 3))
    assert_almost_equal(nd.flip(nd.array(x), axis=1).asnumpy(),
                        x[:, ::-1], rtol=1e-6)
    assert_almost_equal(nd.reverse(nd.array(x), axis=0).asnumpy(),
                        x[::-1], rtol=1e-6)


def test_op_tile_repeat():
    x = _x((2, 3))
    assert_almost_equal(nd.tile(nd.array(x), reps=(2, 2)).asnumpy(),
                        onp.tile(x, (2, 2)), rtol=1e-6)
    assert_almost_equal(
        nd.repeat(nd.array(x), repeats=2, axis=1).asnumpy(),
        onp.repeat(x, 2, 1), rtol=1e-6)
    assert_almost_equal(nd.repeat(nd.array(x), repeats=2).asnumpy(),
                        onp.repeat(x, 2), rtol=1e-6)


def test_op_expand_squeeze():
    x = _x((2, 3))
    e = nd.expand_dims(nd.array(x), axis=1)
    assert e.shape == (2, 1, 3)
    s = nd.squeeze(e)
    assert s.shape == (2, 3)
    assert_almost_equal(s.asnumpy(), x, rtol=1e-6)


def test_op_stack_concat_split():
    a, b = _x((2, 3)), _x((2, 3))
    st = nd.stack(nd.array(a), nd.array(b), axis=1)
    assert st.shape == (2, 2, 3)
    cc = nd.concat(nd.array(a), nd.array(b), dim=0)
    assert cc.shape == (4, 3)
    parts = nd.split(nd.array(a), num_outputs=3, axis=1)
    assert len(parts) == 3 and parts[0].shape == (2, 1)
    sq = nd.split(nd.array(a), num_outputs=3, axis=1, squeeze_axis=True)
    assert sq[0].shape == (2,)


def test_op_slice_family():
    x = _x((4, 6))
    assert_almost_equal(
        nd.slice(nd.array(x), begin=(1, 2), end=(3, 5)).asnumpy(),
        x[1:3, 2:5], rtol=1e-6)
    assert_almost_equal(
        nd.slice_axis(nd.array(x), axis=1, begin=1, end=4).asnumpy(),
        x[:, 1:4], rtol=1e-6)
    y = _x((2, 3))
    out = nd.slice_like(nd.array(x), nd.array(y))
    assert out.shape == (2, 3)
    ch = nd.slice_channel(nd.array(x), num_outputs=2, axis=1)
    assert len(ch) == 2 and ch[0].shape == (4, 3)


def test_op_pad_constant_edge():
    x = _x((1, 2, 3, 3))
    out = nd.pad(nd.array(x), mode="constant",
                 pad_width=(0, 0, 0, 0, 1, 1, 2, 2), constant_value=7.0)
    assert out.shape == (1, 2, 5, 7)
    assert (out.asnumpy()[0, 0, 0] == 7).all()
    oute = nd.pad(nd.array(x), mode="edge",
                  pad_width=(0, 0, 0, 0, 1, 1, 1, 1))
    assert_almost_equal(oute.asnumpy()[0, 0, 0, 1:-1], x[0, 0, 0],
                        rtol=1e-6)


def test_op_depth_space_roundtrip():
    x = _x((1, 8, 2, 3))
    d2s = nd.depth_to_space(nd.array(x), block_size=2)
    assert d2s.shape == (1, 2, 4, 6)
    back = nd.space_to_depth(d2s, block_size=2)
    assert_almost_equal(back.asnumpy(), x, rtol=1e-6)


def test_op_broadcast_axis_to():
    x = _x((1, 3, 1))
    out = nd.broadcast_axis(nd.array(x), axis=(0, 2), size=(2, 4))
    assert out.shape == (2, 3, 4)
    out2 = nd.broadcast_to(nd.array(x), shape=(2, 3, 4))
    assert_almost_equal(out.asnumpy(), out2.asnumpy(), rtol=1e-6)


def test_op_diag_khatri_rao():
    x = _x((4, 4))
    assert_almost_equal(nd.diag(nd.array(x)).asnumpy(), onp.diag(x),
                        rtol=1e-6)
    v = _x((3,))
    assert_almost_equal(nd.diag(nd.array(v)).asnumpy(), onp.diag(v),
                        rtol=1e-6)
    a = _x((2, 3))
    b = _x((4, 3))
    kr = nd.khatri_rao(nd.array(a), nd.array(b))
    expect = onp.stack([onp.kron(a[:, i], b[:, i]).reshape(-1)
                        for i in range(3)], axis=1)
    assert kr.shape == (8, 3)
    assert_almost_equal(kr.asnumpy(), expect, rtol=1e-5)


def test_op_shape_size_arrays():
    x = _x((3, 5))
    assert list(nd.shape_array(nd.array(x)).asnumpy()) == [3, 5]
    assert int(nd.size_array(nd.array(x)).asnumpy().reshape(())) == 15


def test_op_zeros_ones_like():
    x = _x((2, 3))
    assert (nd.zeros_like(nd.array(x)).asnumpy() == 0).all()
    assert (nd.ones_like(nd.array(x)).asnumpy() == 1).all()


def test_op_cast_dtypes():
    x = _x((2, 3), lo=0, hi=10)
    for dt in ("float16", "int32", "uint8"):
        out = nd.cast(nd.array(x), dtype=dt)
        assert str(out.data.dtype) == dt
    assert_almost_equal(
        nd.cast(nd.array(x), dtype="int32").asnumpy(),
        x.astype("int32"), rtol=1e-6)


def test_op_stop_gradient_blocks():
    x = _x((2, 2))
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.sum(a * nd.stop_gradient(a))
    y.backward()
    # d/da [a * sg(a)] = sg(a), not 2a
    assert_almost_equal(a.grad.asnumpy(), x, rtol=1e-5)
