"""Multi-device (8-way virtual CPU mesh) tests for everything that
claims SPMD.

Reference model: tests/nightly/dist_sync_kvstore.py (exact-value asserts
across workers) + the multi-GPU tests in tests/python/gpu. The conftest
mesh plays the role of the reference's multi-process launcher.
"""
import os

import numpy as onp
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon, kvstore, parallel
from mxnet_tpu.gluon import nn

rs = onp.random.RandomState(3)

pytestmark = pytest.mark.skipif(jax.device_count() < 8,
                                reason="needs the 8-device test mesh")


# ------------------------------------------------------- collectives ---

def test_group_all_reduce_exact():
    vals = [rs.rand(16, 8).astype("f") for _ in range(8)]
    devs = jax.devices()[:8]
    nds = [nd.NDArray(jax.device_put(v, d)) for v, d in zip(vals, devs)]
    out = parallel.group_all_reduce(nds)
    expect = onp.sum(vals, axis=0)
    assert len(out) == 8
    for i, o in enumerate(out):
        onp.testing.assert_allclose(o.asnumpy(), expect, rtol=1e-6)
        assert list(o.data.devices())[0] == devs[i]


def test_group_all_reduce_rejects_same_device():
    a = nd.array(rs.rand(4).astype("f"))
    b = nd.array(rs.rand(4).astype("f"))
    with pytest.raises(mx.base.MXNetError):
        parallel.group_all_reduce([a, b])


def test_kvstore_device_push_collective():
    kv = kvstore.create("device")
    shape = (8, 4)
    kv.init("w", nd.zeros(shape))
    devs = jax.devices()[:8]
    grads = [rs.rand(*shape).astype("f") for _ in range(8)]
    kv.push("w", [nd.NDArray(jax.device_put(g, d))
                  for g, d in zip(grads, devs)])
    out = nd.zeros(shape)
    kv.pull("w", out=out)
    onp.testing.assert_allclose(out.asnumpy(), onp.sum(grads, 0),
                                rtol=1e-5)


def test_kvstore_multi_key_multi_device():
    kv = kvstore.create("device")
    keys = ["a", "b", "c"]
    shapes = [(4, 4), (16,), (2, 3, 4)]
    for k, s in zip(keys, shapes):
        kv.init(k, nd.zeros(s))
    devs = jax.devices()[:4]
    expects = {}
    for k, s in zip(keys, shapes):
        grads = [rs.rand(*s).astype("f") for _ in range(4)]
        expects[k] = onp.sum(grads, 0)
        kv.push(k, [nd.NDArray(jax.device_put(g, d))
                    for g, d in zip(grads, devs)])
    for k, s in zip(keys, shapes):
        out = nd.zeros(s)
        kv.pull(k, out=out)
        onp.testing.assert_allclose(out.asnumpy(), expects[k], rtol=1e-5)


def test_kvstore_bigarray_sharded_storage(monkeypatch):
    monkeypatch.setenv("MXNET_KVSTORE_BIGARRAY_BOUND", "100")
    kv = kvstore.create("dist_sync")
    big = nd.array(rs.rand(16, 32).astype("f"))  # 512 > 100
    kv.init("big", big)
    stored = kv._store["big"]
    assert len(stored.data.sharding.device_set) == 8
    out = nd.zeros((16, 32))
    kv.pull("big", out=out)
    onp.testing.assert_allclose(out.asnumpy(), big.asnumpy(), rtol=1e-6)
    # pull must not leak the kvshard layout into the caller's array
    assert len(out.data.sharding.device_set) == 1
    small = nd.array(rs.rand(3, 3).astype("f"))
    kv.init("small", small)
    assert len(kv._store["small"].data.sharding.device_set) == 1


def test_kvstore_bigarray_push_pull_cycle(monkeypatch):
    """push/updater/pull all keep working after init shards a big key
    (regression: sharded store value used to clash with single-device
    gradients)."""
    monkeypatch.setenv("MXNET_KVSTORE_BIGARRAY_BOUND", "100")
    kv = kvstore.create("dist_sync")
    big = rs.rand(16, 32).astype("f")
    kv.init("big", nd.array(big))
    g = rs.rand(16, 32).astype("f")
    kv.push("big", nd.array(g))
    out = nd.zeros((16, 32))
    kv.pull("big", out=out)
    onp.testing.assert_allclose(out.asnumpy(), big + g, rtol=1e-5)
    # the stored value stays row-sharded across the device group
    assert len(kv._store["big"].data.sharding.device_set) == 8
    # updater path on the sharded key
    kv2 = kvstore.create("dist_sync")
    kv2.init("w", nd.array(big))
    def upd(key, grad, weight):
        weight._data = (weight - 0.5 * grad).data

    kv2.set_updater(upd)
    kv2.push("w", nd.array(g))
    out2 = nd.zeros((16, 32))
    kv2.pull("w", out=out2)
    onp.testing.assert_allclose(out2.asnumpy(), big - 0.5 * g, rtol=1e-5)


def test_group_all_reduce_rejects_multi_device_value():
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = parallel.make_mesh({"dp": 8})
    sharded = nd.NDArray(jax.device_put(rs.rand(8, 4).astype("f"),
                                        NamedSharding(mesh, P("dp"))))
    single = nd.NDArray(jax.device_put(rs.rand(8, 4).astype("f"),
                                       jax.devices()[1]))
    with pytest.raises(mx.base.MXNetError, match="single-device"):
        parallel.group_all_reduce([sharded, single])


# ------------------------------------------------ gradient compression ---

def _ref_quantize(grad, residual, th):
    """Reference quantize_2bit semantics, scalar python oracle
    (gradient_compression-inl.h:64-79)."""
    out = onp.zeros_like(grad)
    r = residual.copy()
    for i in range(grad.size):
        r[i] += grad[i]
        if r[i] >= th:
            out[i] = th
            r[i] -= th
        elif r[i] <= -th:
            out[i] = -th
            r[i] += th
    return out, r


def test_2bit_quantize_matches_reference_semantics():
    from mxnet_tpu.gradient_compression import GradientCompression

    gc = GradientCompression("2bit", threshold=0.4)
    g = (rs.rand(37).astype("f") - 0.5) * 2
    res = onp.zeros(37, "f")
    packed, new_res = gc.quantize(jnp.asarray(g), jnp.asarray(res))
    assert packed.dtype == jnp.uint32 and packed.shape == (3,)
    deq = gc.dequantize(packed, 37)
    exp_out, exp_res = _ref_quantize(g, res, 0.4)
    onp.testing.assert_allclose(onp.asarray(deq), exp_out, rtol=1e-6)
    onp.testing.assert_allclose(onp.asarray(new_res), exp_res, rtol=1e-5)


def test_2bit_error_feedback_converges():
    """Residual accumulation means the summed dequantized gradients
    approach the summed true gradients over steps."""
    from mxnet_tpu.gradient_compression import GradientCompression

    gc = GradientCompression("2bit", threshold=0.05)
    g = (rs.rand(64).astype("f") - 0.5) * 0.2
    res = jnp.zeros(64)
    total = onp.zeros(64, "f")
    for _ in range(50):
        packed, res = gc.quantize(jnp.asarray(g), res)
        total += onp.asarray(gc.dequantize(packed, 64))
    onp.testing.assert_allclose(total / 50, g, atol=0.06)


def test_kvstore_compressed_push_exact():
    kv = kvstore.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.3})
    shape = (24,)
    kv.init("w", nd.zeros(shape))
    devs = jax.devices()[:4]
    grads = [(rs.rand(*shape).astype("f") - 0.5) for _ in range(4)]
    kv.push("w", [nd.NDArray(jax.device_put(g, d))
                  for g, d in zip(grads, devs)])
    expect = onp.zeros(shape, "f")
    for g in grads:
        q, _ = _ref_quantize(g, onp.zeros(shape, "f"), 0.3)
        expect += q
    out = nd.zeros(shape)
    kv.pull("w", out=out)
    onp.testing.assert_allclose(out.asnumpy(), expect, rtol=1e-5)
    # second push uses the per-source residuals
    kv.push("w", [nd.NDArray(jax.device_put(g, d))
                  for g, d in zip(grads, devs)])
    for g in grads:
        _, r = _ref_quantize(g, onp.zeros(shape, "f"), 0.3)
        q2, _ = _ref_quantize(g, r, 0.3)
        expect += q2
    kv.pull("w", out=out)
    onp.testing.assert_allclose(out.asnumpy(), expect, rtol=1e-5)


def test_compression_rejects_unknown_type():
    kv = kvstore.create("device")
    with pytest.raises(mx.base.MXNetError):
        kv.set_gradient_compression({"type": "1bit"})
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.set_gradient_compression({"type": "none"})
    assert kv._compression is None


# -------------------------------------------------------- SPMDTrainer ---

def _make_net(seed=0):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"), nn.BatchNorm(),
            nn.Dense(8))
    net.initialize(mx.init.Xavier())
    return net


def _train(mesh_axes, opt, params, steps=6, cdt=None, seed=0):
    net = _make_net(seed)
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    mesh = parallel.make_mesh(mesh_axes)
    rules = {r"dense1_weight": ("mp", None)} if "mp" in mesh_axes else None
    tr = parallel.SPMDTrainer(net, loss, optimizer=opt,
                              optimizer_params=params, mesh=mesh,
                              param_rules=rules, compute_dtype=cdt)
    r = onp.random.RandomState(11)
    X = nd.array(r.randn(64, 16).astype("f"))
    y = nd.array(r.randint(0, 8, 64).astype("f"))
    losses = [float(tr.step(X, y).asscalar()) for _ in range(steps)]
    return losses


@pytest.mark.parametrize("opt,params", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.01}),
    ("lamb", {"learning_rate": 0.05}),
])
def test_spmd_dp8_matches_single_device(opt, params):
    l8 = _train({"dp": 8}, opt, params)
    l1 = _train({"dp": 1}, opt, params)
    onp.testing.assert_allclose(l8, l1, rtol=2e-4, atol=2e-5)
    assert l8[-1] < l8[0]  # actually learning


def test_spmd_dp_x_mp_matches_single_device():
    lmp = _train({"dp": 4, "mp": 2}, "sgd", {"learning_rate": 0.1})
    l1 = _train({"dp": 1}, "sgd", {"learning_rate": 0.1})
    onp.testing.assert_allclose(lmp, l1, rtol=2e-4, atol=2e-5)


def test_spmd_bf16_on_mesh_learns():
    losses = _train({"dp": 8}, "adam", {"learning_rate": 0.01}, steps=10,
                    cdt="bfloat16")
    assert losses[-1] < losses[0] * 0.9


def test_spmd_adamw_weight_decay_on_mesh():
    l = _train({"dp": 8}, "adamw", {"learning_rate": 0.01, "wd": 0.01},
               steps=6)
    assert l[-1] < l[0]


def test_spmd_param_sync_back_to_gluon():
    net = _make_net()
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    mesh = parallel.make_mesh({"dp": 8})
    tr = parallel.SPMDTrainer(net, loss, optimizer="sgd",
                              optimizer_params={"learning_rate": 0.1},
                              mesh=mesh)
    r = onp.random.RandomState(1)
    X = nd.array(r.randn(32, 16).astype("f"))
    y = nd.array(r.randint(0, 8, 32).astype("f"))
    for _ in range(3):
        tr.step(X, y)
    tr.sync_params_to_gluon()
    out = net(X)  # eager forward with the synced params works
    assert out.shape == (32, 8)


# ----------------------------------------------- SyncBatchNorm / AMP ---

def test_sync_batch_norm_stats_match_global_batch():
    """pmean-reduced statistics == stats of the full (unsharded) batch."""
    from mxnet_tpu.parallel._compat import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mxnet_tpu.gluon.contrib import nn as contrib_nn

    sbn = contrib_nn.SyncBatchNorm(in_channels=4)
    sbn.initialize()
    X = rs.rand(16, 4, 3, 3).astype("f")

    mesh = parallel.make_mesh({"dp": 8})

    def step(x):
        with autograd.pause(train_mode=True):  # batch-stat mode
            out = sbn(nd.NDArray(x))
        return out.data

    sharded = jax.device_put(X, NamedSharding(mesh, P("dp")))
    with mesh:
        out = jax.jit(shard_map(step, mesh=mesh, in_specs=P("dp"),
                                out_specs=P("dp")))(
            sharded)
    # plain BN over the full batch gives the same normalized output
    # (use_batch_stats=True explicitly: outside autograd.record the op
    # now follows the reference and normalizes with the MOVING stats)
    bn_full = nd.batch_norm(
        nd.array(X), nd.ones(4), nd.zeros(4), nd.zeros(4), nd.ones(4),
        fix_gamma=False, eps=1e-5, use_batch_stats=True)
    onp.testing.assert_allclose(onp.asarray(out), bn_full.asnumpy(),
                                rtol=2e-3, atol=2e-3)


def test_amp_overflow_skip_under_dp():
    """LossScaler skips the update when ANY shard's gradient overflows —
    the all_finite check runs on gradients sharded over the dp mesh, so
    the reduction is distributed-safe."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mxnet_tpu.contrib.amp import LossScaler

    mesh = parallel.make_mesh({"dp": 8})

    class FakeParam:
        grad_req = "write"

        def __init__(self, g):
            self._g = nd.NDArray(
                jax.device_put(g, NamedSharding(mesh, P("dp"))))

        def grad(self):
            return self._g

    good = onp.ones((8, 4), "f")
    bad = good.copy()
    bad[5, 2] = onp.inf  # overflow on shard 5 only
    scaler = LossScaler(init_scale=2 ** 10)
    assert scaler.has_overflow([FakeParam(bad)])
    assert not scaler.has_overflow([FakeParam(good)])
    s0 = scaler.loss_scale
    scaler.update_scale(True)
    assert scaler.loss_scale == s0 / 2  # halved on overflow


def test_shard_batch_layout():
    mesh = parallel.make_mesh({"dp": 8})
    x = nd.array(rs.rand(16, 4).astype("f"))
    sx = parallel.shard_batch(x, mesh)
    assert len(sx.data.sharding.device_set) == 8
    onp.testing.assert_allclose(sx.asnumpy(), x.asnumpy(), rtol=1e-6)


def test_module_multi_context_data_parallel():
    """Module(context=[8 devices]) trains as ONE sharded computation:
    batch inputs split over 'dp', params replicated, gradients globally
    reduced by GSPMD — the Module-API analog of the reference's
    DataParallelExecutorGroup (executor_group.py:144)."""
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import sym, io
    from mxnet_tpu.module import Module

    ndev = min(8, jax.device_count())
    if ndev < 2:
        import pytest

        pytest.skip("needs multiple devices")
    rs = onp.random.RandomState(0)
    X = rs.randn(128, 6).astype("f")
    y = (X.sum(1) > 0).astype("f")

    def build(ctx):
        mx.random.seed(0)
        data = sym.Variable("data")
        fc1 = sym.FullyConnected(data, name="mc_fc1", num_hidden=16)
        out = sym.SoftmaxOutput(
            sym.FullyConnected(sym.Activation(fc1, act_type="relu"),
                               name="mc_fc2", num_hidden=2),
            sym.Variable("softmax_label"), name="softmax")
        m = Module(out, context=ctx)
        m.bind(data_shapes=[("data", (64, 6))],
               label_shapes=[("softmax_label", (64,))])
        m.init_params(mx.init.Uniform(0.1))
        m.init_optimizer(optimizer="sgd",
                         optimizer_params={"learning_rate": 0.05})
        return m

    def run_epochs(m, epochs=4):
        it = io.NDArrayIter(X, y, batch_size=64)
        for _ in range(epochs):
            it.reset()
            for batch in it:
                m.forward(batch, is_train=True)
                m.backward()
                m.update()
        return {k: v.asnumpy() for k, v in m.get_params()[0].items()}

    # identical graphs/params trained single- vs multi-context must agree
    single = run_epochs(build(mx.cpu(0)))
    multi_mod = build([mx.cpu(i) for i in range(ndev)])
    multi = run_epochs(multi_mod)
    assert single.keys() == multi.keys()
    for k in single:
        onp.testing.assert_allclose(multi[k], single[k], rtol=2e-4,
                                    atol=1e-5, err_msg=k)
    # and the bound computation really is sharded over dp
    m = multi_mod
    m.forward(io.DataBatch(data=[nd.array(X[:64])],
                           label=[nd.array(y[:64])]), is_train=False)
    assert m.get_outputs()[0].shape == (64, 2)
