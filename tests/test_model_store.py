"""model_store cache resolution + pretrained wiring.

Reference: the download/caching logic of
python/mxnet/gluon/model_zoo/model_store.py (checksummed cache, purge).
Network-free: only the cache/verify paths run; download raises the
documented no-egress error.
"""
import hashlib
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon.model_zoo import model_store, vision


def test_short_hash_known_and_unknown():
    assert len(model_store.short_hash("resnet50_v1")) == 8
    with pytest.raises(ValueError):
        model_store.short_hash("not_a_model")


def test_cache_hit_returns_verified_file(tmp_path):
    # build a fake cached weight whose sha1 we register temporarily
    payload = b"fake-params-bytes"
    sha = hashlib.sha1(payload).hexdigest()
    old = model_store._model_sha1.get("resnet18_v1")
    model_store._model_sha1["resnet18_v1"] = sha
    try:
        fname = tmp_path / f"resnet18_v1-{sha[:8]}.params"
        fname.write_bytes(payload)
        got = model_store.get_model_file("resnet18_v1",
                                         root=str(tmp_path))
        assert got == str(fname)
    finally:
        model_store._model_sha1["resnet18_v1"] = old


def test_download_raises_helpful_error_without_egress(tmp_path):
    with pytest.raises((RuntimeError, ValueError)) as ei:
        model_store.get_model_file("alexnet", root=str(tmp_path))
    assert "alexnet" in str(ei.value)


def test_pretrained_flag_routes_to_model_store(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_HOME", str(tmp_path))
    asked = []

    def fake_get(name, root=None):
        asked.append(name)
        raise RuntimeError("no egress in test")

    monkeypatch.setattr(model_store, "get_model_file", fake_get)
    for ctor, expect in [
            (lambda: vision.resnet50_v1(pretrained=True), "resnet50_v1"),
            (lambda: vision.mobilenet1_0(pretrained=True),
             "mobilenet1.0"),
            (lambda: vision.mobilenet_v2_0_5(pretrained=True),
             "mobilenetv2_0.5"),
            (lambda: vision.squeezenet1_1(pretrained=True),
             "squeezenet1.1"),
            (lambda: vision.vgg16_bn(pretrained=True), "vgg16_bn"),
            (lambda: vision.densenet169(pretrained=True), "densenet169"),
            (lambda: vision.inception_v3(pretrained=True),
             "inceptionv3"),
            (lambda: vision.alexnet(pretrained=True), "alexnet")]:
        with pytest.raises(RuntimeError):
            ctor()
        assert asked[-1] == expect


def test_purge(tmp_path):
    f = tmp_path / "x-12345678.params"
    f.write_bytes(b"1")
    model_store.purge(str(tmp_path))
    assert not f.exists()


def test_structure_checkpoint_roundtrip_zoo(tmp_path):
    """save_parameters/load_parameters (the zoo-file format) restores
    identical outputs."""
    net = vision.squeezenet1_1(classes=13)
    net.initialize(mx.init.Xavier())
    x = nd.array(onp.random.RandomState(0).rand(1, 3, 224, 224)
                 .astype("f"))
    ref = net(x)
    f = str(tmp_path / "w.params")
    net.save_parameters(f)
    net2 = vision.squeezenet1_1(classes=13)
    net2.load_parameters(f)
    onp.testing.assert_allclose(net2(x).asnumpy(), ref.asnumpy(),
                                rtol=1e-5)
