"""Third op-spec suite: under-covered operators against numpy oracles
(reference: tests/python/unittest/test_operator.py — growing toward its
253 per-op test functions; suites 1/2 cover the core families, this one
the long tail: special functions, sorting/top-k, scatter/gather, space
reshuffles, binary-extended, norms, cumulative/np ops)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import (assert_almost_equal, check_consistency,
                                  with_seed)

RS = onp.random.RandomState(42)


def _a(*shape):
    return RS.randn(*shape).astype("f")


# ---- special functions ----------------------------------------------------

def test_erf_erfinv_roundtrip():
    import scipy.special as sp

    x = onp.linspace(-2, 2, 21).astype("f")
    assert_almost_equal(nd.erf(nd.array(x)), sp.erf(x), rtol=1e-5,
                        atol=1e-6)
    y = onp.linspace(-0.9, 0.9, 9).astype("f")
    assert_almost_equal(nd.erf(nd.erfinv(nd.array(y))), y, rtol=1e-4,
                        atol=1e-5)


def test_gamma_gammaln():
    import scipy.special as sp

    x = onp.array([0.5, 1.0, 2.5, 4.0], "f")
    assert_almost_equal(nd.gamma(nd.array(x)), sp.gamma(x), rtol=1e-5)
    assert_almost_equal(nd.gammaln(nd.array(x)), sp.gammaln(x), rtol=1e-5,
                        atol=1e-6)


def test_digamma():
    import scipy.special as sp

    x = onp.array([0.5, 1.0, 3.0, 7.5], "f")
    assert_almost_equal(nd.digamma(nd.array(x)), sp.digamma(x), rtol=1e-5,
                        atol=1e-6)


def test_log1p_expm1_inverse():
    x = onp.array([1e-6, 0.1, 1.0, 5.0], "f")
    assert_almost_equal(nd.log1p(nd.array(x)), onp.log1p(x), rtol=1e-6)
    assert_almost_equal(nd.expm1(nd.array(x)), onp.expm1(x), rtol=1e-6)
    assert_almost_equal(nd.expm1(nd.log1p(nd.array(x))), x, rtol=1e-5)


def test_cbrt_rcbrt():
    x = onp.array([-8.0, -1.0, 1.0, 27.0], "f")
    assert_almost_equal(nd.cbrt(nd.array(x)), onp.cbrt(x), rtol=1e-6)
    xp = onp.array([1.0, 8.0, 27.0], "f")
    assert_almost_equal(nd.rcbrt(nd.array(xp)), 1.0 / onp.cbrt(xp),
                        rtol=1e-6)


def test_hypot_ldexp():
    a, b = _a(3, 4), _a(3, 4)
    assert_almost_equal(nd.hypot(nd.array(a), nd.array(b)),
                        onp.hypot(a, b), rtol=1e-6)
    e = RS.randint(-3, 4, (3, 4)).astype("f")
    assert_almost_equal(nd.ldexp(nd.array(a), nd.array(e)),
                        a * onp.exp2(e), rtol=1e-6)


def test_trunc_fix_rint_round():
    x = onp.array([-1.7, -0.5, 0.5, 1.5, 2.5], "f")
    assert_almost_equal(nd.trunc(nd.array(x)), onp.trunc(x))
    assert_almost_equal(nd.fix(nd.array(x)), onp.fix(x))
    assert_almost_equal(nd.rint(nd.array(x)), onp.rint(x))


def test_sign_reciprocal_square():
    x = onp.array([-2.0, -0.5, 0.5, 4.0], "f")
    assert_almost_equal(nd.sign(nd.array(x)), onp.sign(x))
    assert_almost_equal(nd.reciprocal(nd.array(x)), 1.0 / x, rtol=1e-6)
    assert_almost_equal(nd.square(nd.array(x)), x * x, rtol=1e-6)


def test_logical_binary_ops():
    a = onp.array([0.0, 1.0, 2.0, 0.0], "f")
    b = onp.array([0.0, 0.0, 3.0, 5.0], "f")
    assert_almost_equal(nd.logical_and(nd.array(a), nd.array(b)),
                        (a.astype(bool) & b.astype(bool)))
    assert_almost_equal(nd.logical_or(nd.array(a), nd.array(b)),
                        (a.astype(bool) | b.astype(bool)))
    assert_almost_equal(nd.logical_xor(nd.array(a), nd.array(b)),
                        (a.astype(bool) ^ b.astype(bool)))
    assert_almost_equal(nd.logical_not(nd.array(a)), ~a.astype(bool))


# ---- sorting / top-k ------------------------------------------------------

@with_seed(1)
def test_topk_value_and_indices():
    x = _a(4, 8)
    vals = nd.topk(nd.array(x), k=3, axis=1, ret_typ="value").asnumpy()
    want = -onp.sort(-x, axis=1)[:, :3]
    assert_almost_equal(vals, want)
    idx = nd.topk(nd.array(x), k=3, axis=1).asnumpy().astype(int)
    for r in range(4):
        assert_almost_equal(x[r, idx[r]], want[r])


@with_seed(2)
def test_sort_argsort_descending():
    x = _a(5, 6)
    assert_almost_equal(nd.sort(nd.array(x), axis=1, is_ascend=False),
                        -onp.sort(-x, axis=1))
    idx = nd.argsort(nd.array(x), axis=1).asnumpy().astype(int)
    for r in range(5):
        assert_almost_equal(x[r, idx[r]], onp.sort(x, axis=1)[r])


def test_pick_along_axis():
    x = _a(4, 5)
    idx = RS.randint(0, 5, (4,)).astype("f")
    got = nd.pick(nd.array(x), nd.array(idx), axis=1).asnumpy()
    assert_almost_equal(got, x[onp.arange(4), idx.astype(int)])


# ---- scatter / gather / indexing -----------------------------------------

def test_gather_nd_2d():
    x = _a(4, 5)
    ind = onp.array([[0, 1, 3], [2, 0, 4]], "f")  # (2, K): row/col ids
    got = nd.gather_nd(nd.array(x), nd.array(ind)).asnumpy()
    assert_almost_equal(got, x[[0, 1, 3], [2, 0, 4]])


def test_scatter_nd_roundtrip():
    data = onp.array([9.0, 8.0, 7.0], "f")
    ind = onp.array([[0, 1, 2], [2, 0, 1]], "f")
    got = nd.scatter_nd(nd.array(data), nd.array(ind),
                        shape=(3, 3)).asnumpy()
    want = onp.zeros((3, 3), "f")
    want[[0, 1, 2], [2, 0, 1]] = data
    assert_almost_equal(got, want)


def test_one_hot_depth_and_values():
    idx = onp.array([1.0, 0.0, 3.0], "f")
    got = nd.one_hot(nd.array(idx), depth=4, on_value=2.0,
                     off_value=-1.0).asnumpy()
    want = onp.full((3, 4), -1.0, "f")
    want[onp.arange(3), idx.astype(int)] = 2.0
    assert_almost_equal(got, want)


def test_diag_extract_and_build():
    x = _a(4, 4)
    assert_almost_equal(nd.diag(nd.array(x)), onp.diag(x))
    v = _a(3)
    assert_almost_equal(nd.diag(nd.array(v)), onp.diag(v))


def test_unravel_ravel_roundtrip():
    shape = (3, 7)
    flat = onp.array([0.0, 5.0, 13.0, 20.0], "f")
    unr = nd.unravel(nd.array(flat), shape=shape).asnumpy()
    assert_almost_equal(
        unr, onp.stack(onp.unravel_index(flat.astype(int), shape)))
    back = nd.ravel_multi_index(nd.array(unr), shape=shape).asnumpy()
    assert_almost_equal(back, flat)


def test_slice_like_trailing_axes():
    x = _a(6, 8)
    ref = _a(3, 4)
    got = nd.slice_like(nd.array(x), nd.array(ref)).asnumpy()
    assert_almost_equal(got, x[:3, :4])


def test_broadcast_like_axes():
    x = _a(1, 4)
    ref = _a(5, 4)
    assert_almost_equal(nd.broadcast_like(nd.array(x), nd.array(ref)),
                        onp.broadcast_to(x, (5, 4)))
    y = _a(2, 1)
    got = nd.broadcast_like(nd.array(y), nd.array(_a(9, 7)),
                            lhs_axes=(1,), rhs_axes=(1,)).asnumpy()
    assert_almost_equal(got, onp.broadcast_to(y, (2, 7)))


# ---- shape reshuffles -----------------------------------------------------

def test_depth_space_roundtrip():
    x = _a(2, 12, 4, 4)
    d2s = nd.depth_to_space(nd.array(x), block_size=2)
    assert d2s.shape == (2, 3, 8, 8)
    back = nd.space_to_depth(d2s, block_size=2)
    assert_almost_equal(back, x)


def test_repeat_expand_squeeze_flip():
    x = _a(2, 3)
    assert_almost_equal(nd.repeat(nd.array(x), repeats=2, axis=1),
                        onp.repeat(x, 2, axis=1))
    e = nd.expand_dims(nd.array(x), axis=0)
    assert e.shape == (1, 2, 3)
    assert nd.squeeze(e, axis=0).shape == (2, 3)
    assert_almost_equal(nd.flip(nd.array(x), axis=1), x[:, ::-1])


@with_seed(3)
def test_shuffle_is_permutation():
    x = onp.arange(24, dtype="f").reshape(6, 4)
    got = nd.shuffle(nd.array(x)).asnumpy()
    assert sorted(map(tuple, got)) == sorted(map(tuple, x))


# ---- norms / reductions ---------------------------------------------------

def test_l2_normalization_instance():
    x = _a(3, 5)
    got = nd.L2Normalization(nd.array(x), mode="instance").asnumpy()
    want = x / onp.sqrt((x * x).sum(axis=1, keepdims=True) + 1e-10)
    assert_almost_equal(got, want, rtol=1e-5)


def test_lrn_matches_formula():
    x = onp.abs(_a(1, 5, 3, 3)) + 0.1
    alpha, beta, knorm, size = 1e-4, 0.75, 2.0, 3
    got = nd.LRN(nd.array(x), alpha=alpha, beta=beta, knorm=knorm,
                 nsize=size).asnumpy()
    pad = size // 2
    sq = onp.pad(x * x, ((0, 0), (pad, pad), (0, 0), (0, 0)))
    acc = onp.zeros_like(x)
    for c in range(5):
        acc[:, c] = sq[:, c:c + size].sum(axis=1)
    # reference lrn.cc normalizes alpha by the window size
    want = x / (knorm + alpha / size * acc) ** beta
    assert_almost_equal(got, want, rtol=1e-4, atol=1e-6)


def test_instance_group_norm_zero_mean():
    x = _a(2, 4, 5)
    g = onp.ones(4, "f")
    b = onp.zeros(4, "f")
    out = nd.InstanceNorm(nd.array(x), nd.array(g), nd.array(b)).asnumpy()
    assert_almost_equal(out.mean(axis=2), onp.zeros((2, 4)), atol=1e-5)
    # GroupNorm gamma/beta are PER-GROUP (group_norm-inl.h:163)
    g2, b2 = onp.ones(2, "f"), onp.zeros(2, "f")
    out2 = nd.GroupNorm(nd.array(x), nd.array(g2), nd.array(b2),
                        num_groups=2).asnumpy()
    assert_almost_equal(out2.reshape(2, 2, -1).mean(axis=2),
                        onp.zeros((2, 2)), atol=1e-5)


def test_nansum_prod():
    x = onp.array([[1.0, onp.nan, 2.0], [3.0, 4.0, onp.nan]], "f")
    assert_almost_equal(nd.nansum(nd.array(x), axis=1),
                        onp.nansum(x, axis=1))
    y = _a(3, 4)
    assert_almost_equal(nd.prod(nd.array(y), axis=0), onp.prod(y, axis=0),
                        rtol=1e-5)


def test_smooth_l1_branches():
    x = onp.array([-2.0, -0.3, 0.0, 0.4, 3.0], "f")
    got = nd.smooth_l1(nd.array(x), scalar=1.0).asnumpy()
    want = onp.where(onp.abs(x) < 1.0, 0.5 * x * x, onp.abs(x) - 0.5)
    assert_almost_equal(got, want, rtol=1e-6)


# ---- mx.np long tail ------------------------------------------------------

def test_np_cumsum_cumprod():
    x = _a(3, 4)
    assert_almost_equal(mx.np.cumsum(mx.np.array(x), axis=1),
                        onp.cumsum(x, axis=1), rtol=1e-5)
    assert_almost_equal(mx.np.cumprod(mx.np.array(x), axis=0),
                        onp.cumprod(x, axis=0), rtol=1e-5)


def test_np_triu_tril_kron():
    x = _a(4, 4)
    assert_almost_equal(mx.np.triu(mx.np.array(x)), onp.triu(x))
    assert_almost_equal(mx.np.tril(mx.np.array(x)), onp.tril(x))
    a, b = _a(2, 2), _a(3, 3)
    assert_almost_equal(mx.np.kron(mx.np.array(a), mx.np.array(b)),
                        onp.kron(a, b), rtol=1e-5)


def test_np_arctan2_radians_degrees():
    a, b = _a(5), onp.abs(_a(5)) + 0.1
    assert_almost_equal(mx.np.arctan2(mx.np.array(a), mx.np.array(b)),
                        onp.arctan2(a, b), rtol=1e-5)
    d = onp.array([0.0, 90.0, 180.0], "f")
    assert_almost_equal(mx.np.radians(mx.np.array(d)), onp.radians(d),
                        rtol=1e-6)
    assert_almost_equal(mx.np.degrees(mx.np.radians(mx.np.array(d))), d,
                        rtol=1e-5)


# ---- gradients for the new ops -------------------------------------------

def test_hypot_gradient():
    from mxnet_tpu.test_utils import check_numeric_gradient

    check_numeric_gradient(lambda a, b: nd.hypot(a, b),
                           [onp.abs(_a(3, 3)) + 0.5,
                            onp.abs(_a(3, 3)) + 0.5])


def test_broadcast_like_gradient_sums():
    from mxnet_tpu import autograd

    x = nd.array(_a(1, 4))
    x.attach_grad()
    ref = nd.array(_a(5, 4))
    with autograd.record():
        out = nd.broadcast_like(x, ref)
        loss = nd.sum(out * out)
    loss.backward()
    want = 2 * 5 * x.asnumpy()  # each element replicated 5x
    assert_almost_equal(x.grad, want, rtol=1e-5)


def test_new_ops_jit_consistency():
    check_consistency(lambda a, b: nd.hypot(a, b), [_a(3, 3), _a(3, 3)])
    check_consistency(lambda a: nd.digamma(nd.abs(a) + 1.0), [_a(4)])
    check_consistency(lambda a, b: nd.ldexp(a, b),
                      [_a(3), onp.array([1.0, -1.0, 2.0], "f")])
