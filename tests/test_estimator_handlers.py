"""Estimator event handlers (reference:
tests/python/unittest/test_gluon_event_handler.py): checkpointing with
rotation + save-best, early stopping, validation cadence, logging, and
custom handler hooks.
"""
import glob
import logging
import os

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.gluon.contrib.estimator import Estimator
from mxnet_tpu.gluon.contrib.estimator.event_handler import (
    BatchEnd, CheckpointHandler, EarlyStoppingHandler, EpochEnd,
    LoggingHandler, TrainBegin, TrainEnd, ValidationHandler)


def _data(n=32, d=8, classes=2, seed=0):
    rng = onp.random.RandomState(seed)
    x = nd.array(rng.rand(n, d).astype("f"))
    y = nd.array(rng.randint(0, classes, n).astype("f"))
    return gluon.data.DataLoader(
        gluon.data.ArrayDataset(x, y), batch_size=8)


def _estimator(d=8, classes=2):
    net = gluon.nn.Dense(classes, in_units=d)
    net.initialize(mx.init.Xavier())
    return Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss())


def test_checkpoint_handler_rotation_and_best(tmp_path):
    est = _estimator()
    ck = CheckpointHandler(str(tmp_path), model_prefix="m",
                           monitor=est.train_metrics[-1],  # loss
                           save_best=True, max_checkpoints=2)
    est.fit(_data(), epochs=4, event_handlers=[ck])
    epochs = sorted(glob.glob(str(tmp_path / "m-epoch*.params")))
    assert len(epochs) == 2  # rotation keeps only the newest two
    assert epochs[-1].endswith("epoch4.params")
    assert os.path.isfile(str(tmp_path / "m-best.params"))
    # the checkpoint loads back into a fresh net
    net2 = gluon.nn.Dense(2, in_units=8)
    net2.load_parameters(str(tmp_path / "m-best.params"))


def test_early_stopping_stops_training():
    est = _estimator()

    class PlateauMetric:
        name = "val_acc"

        def get(self):
            return self.name, 0.5  # never improves after epoch 1

    stopper = EarlyStoppingHandler(PlateauMetric(), patience=1)
    epochs_seen = []

    class Counter(EpochEnd):
        def epoch_end(self, estimator, *args, **kwargs):
            epochs_seen.append(1)

    est.fit(_data(), epochs=10, event_handlers=[stopper, Counter()])
    assert stopper.stop_training
    # first epoch sets best, two non-improving epochs exhaust patience=1
    assert len(epochs_seen) < 10


def test_validation_handler_runs_eval():
    est = _estimator()
    from mxnet_tpu.metric import Accuracy

    val_metric = Accuracy(name="val_accuracy")
    vh = ValidationHandler(_data(seed=1), eval_fn=est.evaluate,
                           val_metrics=[val_metric], epoch_period=1)
    est.fit(_data(), epochs=2, event_handlers=[vh])
    name, value = val_metric.get()
    assert 0.0 <= value <= 1.0


def test_logging_handler_emits_records(caplog):
    est = _estimator()
    with caplog.at_level(logging.INFO):
        est.fit(_data(), epochs=1,
                event_handlers=[LoggingHandler()])
    text = " ".join(r.getMessage() for r in caplog.records)
    assert "poch" in text or "loss" in text.lower(), text


def test_custom_handler_hook_order():
    est = _estimator()
    calls = []

    class Tracker(TrainBegin, BatchEnd, EpochEnd, TrainEnd):
        def train_begin(self, estimator, *args, **kwargs):
            calls.append("train_begin")

        def batch_end(self, estimator, *args, **kwargs):
            calls.append("batch")

        def epoch_end(self, estimator, *args, **kwargs):
            calls.append("epoch_end")

        def train_end(self, estimator, *args, **kwargs):
            calls.append("train_end")

    est.fit(_data(), epochs=2, event_handlers=[Tracker()])
    assert calls[0] == "train_begin" and calls[-1] == "train_end"
    assert calls.count("epoch_end") == 2
    assert calls.count("batch") == 8  # 4 batches/epoch x 2
