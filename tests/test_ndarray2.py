"""NDArray semantics, second suite (reference:
tests/python/unittest/test_ndarray.py, 77 fns — indexing, in-place ops,
views, dtype/copy semantics, shape special codes, order ops)."""
import copy as pycopy
import pickle

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal, with_seed

RS = onp.random.RandomState(99)


def _arr(*shape):
    return RS.randn(*shape).astype("f")


def test_setitem_int_row():
    a = nd.array(_arr(4, 3))
    a[1] = 7.0
    assert (a.asnumpy()[1] == 7.0).all()


def test_setitem_slice():
    x = _arr(6, 2)
    a = nd.array(x)
    a[2:5] = 0.0
    x[2:5] = 0.0
    assert_almost_equal(a, x)


def test_setitem_array_value():
    a = nd.zeros((3, 4))
    v = _arr(4)
    a[0] = nd.array(v)
    assert_almost_equal(a.asnumpy()[0], v)


def test_setitem_fancy_index():
    x = _arr(5, 2)
    a = nd.array(x)
    idx = onp.array([0, 3])
    a[nd.array(idx.astype("f"))] = -1.0
    x[idx] = -1.0
    assert_almost_equal(a, x)


def test_getitem_ellipsis_and_none():
    x = _arr(2, 3, 4)
    a = nd.array(x)
    assert_almost_equal(a[..., 1], x[..., 1])
    assert a[1].shape == (3, 4)
    assert a[1:, 0].shape == (1, 4)


def test_getitem_negative_and_step():
    x = _arr(8)
    a = nd.array(x)
    assert_almost_equal(a[-3:], x[-3:])
    assert_almost_equal(a[::2], x[::2])
    assert_almost_equal(a[::-1], x[::-1])


def test_inplace_arith_updates_handle():
    a = nd.ones((3,))
    b = a
    a += 2.0
    # MXNet in-place semantics: the same handle observes the update
    assert (b.asnumpy() == 3.0).all()
    a *= 2.0
    assert (b.asnumpy() == 6.0).all()
    a -= 1.0
    a /= 5.0
    assert_almost_equal(b, onp.full(3, 1.0))


def test_broadcast_binary_matrix_vector():
    m, v = _arr(4, 5), _arr(5)
    assert_almost_equal(nd.array(m) + nd.array(v), m + v)
    assert_almost_equal(nd.array(m) * nd.array(v), m * v)
    assert_almost_equal(nd.array(m) / (nd.array(v) + 10.0), m / (v + 10))


def test_rsub_rdiv_rpow_scalar():
    x = onp.abs(_arr(3, 3)) + 0.5
    a = nd.array(x)
    assert_almost_equal(2.0 - a, 2.0 - x, rtol=1e-6)
    assert_almost_equal(2.0 / a, 2.0 / x, rtol=1e-6)
    assert_almost_equal(2.0 ** a, 2.0 ** x, rtol=1e-5)


def test_comparison_ops_return_01():
    a, b = _arr(4), _arr(4)
    got = (nd.array(a) > nd.array(b)).asnumpy()
    assert set(onp.unique(got)) <= {0.0, 1.0}
    assert_almost_equal(got, (a > b).astype("f"))
    assert_almost_equal((nd.array(a) <= nd.array(b)),
                        (a <= b).astype("f"))
    assert_almost_equal((nd.array(a) == nd.array(a)), onp.ones(4))


def test_neg_abs_round_trip():
    x = _arr(5)
    a = nd.array(x)
    assert_almost_equal(-a, -x)
    assert_almost_equal(nd.abs(-a), onp.abs(x), rtol=1e-6)


def test_astype_all_dtypes():
    x = onp.array([0.0, 1.6, -2.4, 3.0], "f")
    a = nd.array(x)
    for dt in ("float16", "int32", "uint8", "int8"):
        got = a.astype(dt)
        assert str(got.dtype) == dt or dt in str(got.dtype)
    # float64/int64 stay 32-bit wide with JAX x64 disabled (platform
    # limitation; 64-bit CHECKPOINT payloads stay exact via host arrays)
    assert a.astype("int32").asnumpy().tolist() == [0, 1, -2, 3]
    same = a.astype("float32", copy=False)
    assert same is a  # no-copy fast path


def test_copy_is_independent():
    a = nd.array(_arr(3))
    b = a.copy()
    a += 1.0
    assert not onp.allclose(a.asnumpy(), b.asnumpy())


def test_copyto_casts_dtype():
    a = nd.array(onp.array([1.9, -0.1], "f"))
    b = nd.zeros((2,), dtype="int32")
    a.copyto(b)
    assert str(b.dtype) == "int32"


def test_deepcopy_and_pickle():
    a = nd.array(_arr(2, 2))
    d = pycopy.deepcopy(a)
    assert_almost_equal(d, a.asnumpy())
    p = pickle.loads(pickle.dumps(a))
    assert_almost_equal(p, a.asnumpy())


def test_reshape_special_codes():
    a = nd.array(_arr(2, 3, 4))
    assert nd.reshape(a, (0, -1)).shape == (2, 12)
    assert nd.reshape(a, (-1,)).shape == (24,)
    assert nd.reshape(a, (-2,)).shape == (2, 3, 4)
    assert a.reshape((4, 6)).shape == (4, 6)


def test_expand_dims_squeeze_roundtrip():
    a = nd.array(_arr(3, 4))
    e = nd.expand_dims(a, axis=0)
    assert e.shape == (1, 3, 4)
    assert nd.squeeze(e).shape == (3, 4)


def test_scalar_conversions():
    a = nd.array(onp.array([2.5], "f"))
    assert float(a) == 2.5
    assert int(a) == 2
    assert a.asscalar() == onp.float32(2.5)
    assert bool(nd.array(onp.array([1.0], "f")))
    with pytest.raises(ValueError):
        bool(nd.array(_arr(3)))


def test_len_and_iter():
    a = nd.array(_arr(4, 2))
    assert len(a) == 4
    rows = [r for r in a]
    assert len(rows) == 4 and rows[0].shape == (2,)


def test_zeros_ones_full_like():
    a = nd.array(_arr(2, 3))
    assert (nd.zeros_like(a).asnumpy() == 0).all()
    assert (nd.ones_like(a).asnumpy() == 1).all()
    f = nd.full((2, 2), 7.5)
    assert (f.asnumpy() == 7.5).all()


def test_arange_variants():
    assert nd.arange(5).asnumpy().tolist() == [0, 1, 2, 3, 4]
    assert_almost_equal(nd.arange(1, 7, 2), onp.arange(1, 7, 2,
                                                       dtype="f"))


def test_concatenate_api():
    a, b = _arr(2, 3), _arr(4, 3)
    got = nd.concatenate([nd.array(a), nd.array(b)], axis=0)
    assert_almost_equal(got, onp.concatenate([a, b], axis=0))


def test_split_returns_views():
    x = _arr(6, 2)
    parts = nd.split(nd.array(x), num_outputs=3, axis=0)
    assert len(parts) == 3
    for i, p in enumerate(parts):
        assert_almost_equal(p, x[2 * i:2 * i + 2])


def test_clip_and_maximum_minimum_scalar():
    x = _arr(8)
    a = nd.array(x)
    assert_almost_equal(nd.clip(a, -0.3, 0.3),
                        onp.clip(x, -0.3, 0.3))
    assert_almost_equal(nd.maximum(a, nd.zeros_like(a)),
                        onp.maximum(x, 0))


def test_dot_transpose_flags():
    a, b = _arr(3, 4), _arr(3, 5)
    got = nd.dot(nd.array(a), nd.array(b), transpose_a=True)
    assert_almost_equal(got, a.T @ b, rtol=1e-5)


def test_norm_ord_axis():
    x = _arr(3, 4)
    assert_almost_equal(nd.norm(nd.array(x)),
                        onp.linalg.norm(x), rtol=1e-5)


def test_sum_mean_dtype_stability():
    x = _arr(4, 4)
    assert_almost_equal(nd.sum(nd.array(x)), x.sum(), rtol=1e-5)
    assert_almost_equal(nd.mean(nd.array(x), axis=1, exclude=False),
                        x.mean(axis=1), rtol=1e-5)


@with_seed(5)
def test_shuffle_axis0_only():
    x = onp.arange(20, dtype="f").reshape(5, 4)
    got = nd.shuffle(nd.array(x)).asnumpy()
    # rows permuted, rows themselves intact
    assert sorted(map(tuple, got)) == sorted(map(tuple, x))


def test_context_properties():
    a = nd.array(_arr(2))
    assert a.context.device_type in ("cpu", "tpu")
    b = a.as_in_context(a.context)
    assert b is a  # same-context fast path


def test_attach_grad_and_backward():
    from mxnet_tpu import autograd

    a = nd.array(_arr(3))
    a.attach_grad()
    with autograd.record():
        y = (a * a).sum()
    y.backward()
    assert_almost_equal(a.grad, 2 * a.asnumpy(), rtol=1e-5)


def test_save_load_list_and_dict(tmp_path):
    a, b = nd.array(_arr(2)), nd.array(_arr(3))
    p = str(tmp_path / "l.params")
    nd.save(p, [a, b])
    la = nd.load(p)
    assert isinstance(la, list)
    assert_almost_equal(la[0], a.asnumpy())
    nd.save(p, {"a": a, "b": b})
    ld = nd.load(p)
    assert_almost_equal(ld["b"], b.asnumpy())


def test_size_ndim_properties():
    a = nd.array(_arr(2, 3, 4))
    assert a.size == 24 and a.ndim == 3
    assert nd.array(onp.float32(5)).ndim == 0


def test_getitem_float_index_array():
    x = _arr(5, 2)
    a = nd.array(x)
    got = a[nd.array(onp.array([0.0, 3.0], "f"))]
    assert_almost_equal(got, x[[0, 3]])


def test_oob_int_raises_get_and_set():
    a = nd.array(_arr(3, 4))
    with pytest.raises(IndexError):
        a[3]
    with pytest.raises(IndexError):
        a[-4]
    with pytest.raises(IndexError):
        a[1, 4]
    with pytest.raises(IndexError):
        a[0, 0, 0, 0]
    with pytest.raises(IndexError):
        a[3] = 1.0
    # slices/arrays keep jax clipping semantics (no false positives)
    assert a[2:99].shape == (1, 4)


def test_fluent_method_surface_matches_reference():
    """Reference NDArray exposes data-first ops as methods (fluent
    autogen); the same spellings must work here."""
    import numpy as onp

    from mxnet_tpu import nd

    a = nd.array(onp.random.RandomState(0).rand(3, 4).astype("f"))
    assert a.sort().shape == (3, 4)
    assert a.topk(k=2).shape == (3, 2)
    assert a.argsort().shape == (3, 4)
    assert a.tile(reps=(2, 1)).shape == (6, 4)
    assert a.flip(axis=1).shape == (3, 4)
    assert a.pick(nd.array(onp.zeros(3, "f"))).shape == (3,)
    assert float(a.ones_like().asnumpy().sum()) == 12.0
    assert float(a.zeros_like().asnumpy().sum()) == 0.0
    assert a.argmax_channel().shape == (3,)
    assert a.broadcast_axes(axis=0, size=3).shape == (3, 4)
    assert a.nansum().shape == ()
    assert a.shape_array().asnumpy().tolist() == [3, 4]
    assert int(a.size_array().asnumpy()[0]) == 12
    parts = a.split_v2(2, axis=1)
    assert parts[0].shape == (3, 2)
    assert a.slice(begin=(0, 1), end=(2, 3)).shape == (2, 2)
    assert a.softmin().shape == (3, 4)
    assert a.repeat(repeats=2, axis=0).shape == (6, 4)
    assert a.to_dlpack_for_read() is not None
