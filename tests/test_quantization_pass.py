"""Symbol-graph int8 quantization pass (reference:
src/operator/quantization/quantize_graph_pass.cc, the quantized op files
quantized_{conv,fully_connected,pooling,concat,activation,elemwise_add,
batch_norm,flatten}.cc, and python/mxnet/contrib/quantization.py
quantize_model — VERDICT r4 item 5)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym as S
from mxnet_tpu.contrib.quantization import (quantize_model, quantize_net,
                                            quantize_symbol)


def _cnn_symbol():
    data = S.var("data")
    c1 = S.Convolution(data, name="conv1", kernel=(3, 3), num_filter=8,
                       pad=(1, 1))
    b1 = S.BatchNorm(c1, name="bn1", fix_gamma=False)
    a1 = S.Activation(b1, name="relu1", act_type="relu")
    c2 = S.Convolution(a1, name="conv2", kernel=(3, 3), num_filter=8,
                       pad=(1, 1))
    addn = S.elemwise_add(a1, c2, name="resadd")
    cat = S.Concat(addn, a1, name="cat1", dim=1)
    p1 = S.Pooling(cat, name="pool1", kernel=(2, 2), stride=(2, 2),
                   pool_type="max")
    f1 = S.Flatten(p1, name="flat1")
    return S.FullyConnected(f1, name="fc1", num_hidden=10)


def _init_params(symb, data_shape):
    onp.random.seed(0)
    args = symb.list_arguments()
    auxs = symb.list_auxiliary_states()
    arg_shapes, _, aux_shapes = symb.infer_shape(data=data_shape)
    arg_params = {n: nd.array(onp.random.randn(*shp).astype("f") * 0.2)
                  for n, shp in zip(args, arg_shapes) if n != "data"}
    aux_params = {n: nd.array(onp.zeros(shp, "f") if "mean" in n
                              else onp.ones(shp, "f"))
                  for n, shp in zip(auxs, aux_shapes)}
    return arg_params, aux_params


def _rel_err(a, b):
    return float(onp.abs(a - b).max() / (onp.abs(b).max() + 1e-9))


@pytest.fixture(scope="module")
def cnn():
    symb = _cnn_symbol()
    arg_params, aux_params = _init_params(symb, (4, 3, 16, 16))
    x = nd.array(onp.random.RandomState(7).randn(4, 3, 16, 16).astype("f"))
    fp32 = symb.eval_with({**arg_params, **aux_params,
                           "data": x}).asnumpy()
    calib = [nd.array(onp.random.RandomState(i).randn(4, 3, 16, 16)
                      .astype("f")) for i in range(3)] + [x]
    return symb, arg_params, aux_params, x, fp32, calib


def test_quantize_model_naive(cnn):
    symb, arg_params, aux_params, x, fp32, calib = cnn
    qsym, qarg, qaux = quantize_model(symb, arg_params, aux_params,
                                      calib_mode="naive", calib_data=calib)
    out = qsym.eval_with({**qarg, **qaux, "data": x}).asnumpy()
    assert _rel_err(out, fp32) < 0.1
    # offline weight quantization replaced the fp32 weights
    assert "conv1_weight_quantized" in qarg
    assert qarg["conv1_weight_quantized"].dtype == onp.int8
    assert "conv1_weight" not in qarg


def test_quantize_model_entropy_and_exclusions(cnn):
    symb, arg_params, aux_params, x, fp32, calib = cnn
    qsym, qarg, qaux = quantize_model(
        symb, arg_params, aux_params,
        excluded_sym_names=("conv1", "bn1"),
        calib_mode="entropy", calib_data=calib)
    out = qsym.eval_with({**qarg, **qaux, "data": x}).asnumpy()
    assert _rel_err(out, fp32) < 0.1
    # excluded layers keep fp32 weights; the rest quantize
    assert "conv1_weight" in qarg
    assert "conv1_weight_quantized" not in qarg
    assert "conv2_weight_quantized" in qarg


def test_quantize_model_excluded_op_names(cnn):
    symb, arg_params, aux_params, x, fp32, calib = cnn
    qsym, qarg, qaux = quantize_model(
        symb, arg_params, aux_params,
        excluded_op_names=("pooling", "elemwise_add"),
        calib_mode="naive", calib_data=calib)
    json = qsym.tojson()
    assert "_contrib_quantized_pooling" not in json
    assert "_contrib_quantized_elemwise_add" not in json
    assert "_contrib_quantized_conv" in json
    out = qsym.eval_with({**qarg, **qaux, "data": x}).asnumpy()
    assert _rel_err(out, fp32) < 0.1


def test_quantized_graph_structure(cnn):
    """Consecutive quantizable ops form one int8 region: no
    dequantize/quantize round trip between conv2 and the final fc."""
    symb, arg_params, aux_params, x, fp32, calib = cnn
    qsym, _ = quantize_symbol(symb)
    json = qsym.tojson()
    for op in ("_contrib_quantized_conv", "_contrib_quantized_batch_norm",
               "_contrib_quantized_act", "_contrib_quantized_pooling",
               "_contrib_quantized_concat", "_contrib_quantized_flatten",
               "_contrib_quantized_elemwise_add",
               "_contrib_quantized_fully_connected", "requantize",
               "dequantize"):
        assert op in json, f"{op} missing from quantized graph"
    # exactly ONE quantize node (at the data boundary): everything
    # downstream stays int8 until the single output dequantize
    import json as J

    nodes = J.loads(json)["nodes"]
    # tojson emits the REFERENCE names (_contrib_quantize_v2 et al.)
    n_quant = sum(1 for n in nodes
                  if n["op"] in ("quantize_v2", "_contrib_quantize_v2"))
    n_deq = sum(1 for n in nodes
                if n["op"] in ("dequantize", "_contrib_dequantize"))
    assert n_quant == 1, n_quant
    assert n_deq == 1, n_deq


def test_quantized_hlo_runs_int8(cnn, monkeypatch):
    """The lowered program provably computes in int8 on the MXU path:
    dot_general/convolution consume i8 operands and accumulate i32.
    Forces the native lowering — under MXNET_QUANTIZE_LOWERING=auto a
    CPU run takes the dequant path (fp32 accumulation), which is the
    fast path there but not what this test pins."""
    import re

    import jax

    monkeypatch.setenv("MXNET_QUANTIZE_LOWERING", "native")
    symb, arg_params, aux_params, x, fp32, calib = cnn
    qsym, qarg, qaux = quantize_model(symb, arg_params, aux_params,
                                      calib_mode="naive", calib_data=calib)
    names = [n for n in sorted(set(qsym.list_arguments())
                               | set(qsym.list_auxiliary_states()))
             if n != "data"]
    allp = {**qarg, **qaux}

    def run(feed_vals, xd):
        f = {n: nd.NDArray(v) for n, v in zip(names, feed_vals)}
        f["data"] = nd.NDArray(xd)
        return qsym.eval_with(f).data

    txt = jax.jit(run).lower([allp[n].data for n in names],
                             x.data).as_text()
    assert re.search(r"dot_general[^\n]*xi8[^\n]*xi32", txt), \
        "fc not int8->int32"
    assert re.search(r"convolution[^\n]*xi8[^\n]*xi32", txt) or \
        re.search(r"convolution(.|\n){0,400}?xi8", txt), "conv not int8"


def test_quantize_net_resnet18_mixed_exclusions():
    """VERDICT r4 done-criterion: quantize_net on resnet18 with mixed
    excluded layers matches fp32 within tolerance."""
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.resnet18_v1(pretrained=False)
    net.initialize(mx.init.Xavier())
    onp.random.seed(1)
    x = nd.array(onp.random.randn(2, 3, 64, 64).astype("f") * 0.5)
    fp32 = net(x).asnumpy()
    # exclude the stem conv + the classifier dense
    excl = []
    for blk in net.collect_params().keys():
        pass
    def find_names(b):
        from mxnet_tpu.gluon import nn
        out = []
        for c in b._children.values():
            if isinstance(c, (nn.Dense, nn.Conv2D)):
                out.append(c.name)
            out += find_names(c)
        return out
    names = find_names(net)
    excl = [names[0], names[-1]]
    calib = [x] + [nd.array(onp.random.randn(2, 3, 64, 64)
                            .astype("f") * 0.5) for _ in range(2)]
    qnet = quantize_net(net, calib_data=calib, calib_mode="naive",
                        exclude_layers=excl)
    qout = qnet(x).asnumpy()
    assert _rel_err(qout, fp32) < 0.15, _rel_err(qout, fp32)


def test_quantize_net_graph_mode():
    """Graph-mode gluon quantization: the traced block becomes a
    SymbolBlock whose conv→bn→relu→pool chain is ONE int8 region
    (reference quantize_net over quantize_graph_pass)."""
    import json as J

    from mxnet_tpu.contrib.quantization import quantize_net_graph
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1), nn.BatchNorm(),
            nn.Activation("relu"), nn.MaxPool2D(2), nn.Flatten(),
            nn.Dense(10))
    net.initialize(mx.init.Xavier())
    onp.random.seed(0)
    x = nd.array(onp.random.randn(2, 3, 16, 16).astype("f") * 0.5)
    fp32 = net(x).asnumpy()
    calib = [x] + [nd.array(onp.random.randn(2, 3, 16, 16)
                            .astype("f") * 0.5) for _ in range(2)]
    qb = quantize_net_graph(net, calib_data=calib, calib_mode="naive")
    qout = qb(x).asnumpy()
    assert _rel_err(qout, fp32) < 0.1
    nodes = J.loads(qb._outputs.tojson())["nodes"]
    ops = [n["op"] for n in nodes]
    for op in ("_contrib_quantized_conv", "_contrib_quantized_batch_norm",
               "_contrib_quantized_act", "_contrib_quantized_pooling",
               "_contrib_quantized_fully_connected"):
        assert op in ops, op
    # one quantize at the data boundary, one dequantize at the output
    assert sum(ops.count(o) for o in
               ("quantize_v2", "_contrib_quantize_v2")) == 1
    assert sum(ops.count(o) for o in
               ("dequantize", "_contrib_dequantize")) == 1
    # int8 weights made it into the block's parameters
    wq = [p for name, p in qb.collect_params().items()
          if name.endswith("_quantized")]
    assert wq and all(p.data().dtype == onp.int8 for p in wq)


def test_quantize_net_graph_resnet18_exclusions():
    from mxnet_tpu.contrib.quantization import quantize_net_graph
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.resnet18_v1(pretrained=False)
    net.initialize(mx.init.Xavier())
    onp.random.seed(1)
    x = nd.array(onp.random.randn(2, 3, 64, 64).astype("f") * 0.5)
    fp32 = net(x).asnumpy()
    calib = [x, nd.array(onp.random.randn(2, 3, 64, 64)
                         .astype("f") * 0.5)]
    # trace once to learn node names, exclude the stem conv + classifier
    from mxnet_tpu import sym as S

    traced = net(S.var("data"))
    convs = [s._name for s in traced._walk() if s._op == "convolution"]
    fcs = [s._name for s in traced._walk() if s._op == "fully_connected"]
    qb = quantize_net_graph(net, calib_data=calib, calib_mode="naive",
                            exclude_layers=(convs[0], fcs[-1]))
    qout = qb(x).asnumpy()
    assert _rel_err(qout, fp32) < 0.15, _rel_err(qout, fp32)


def test_quantize_net_graph_exclude_match_and_deferred_init():
    """reference quantize_net options: exclude_layers_match substring
    matching; deferred-init nets materialize from calib_data."""
    from mxnet_tpu.contrib.quantization import quantize_net_graph
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, 3, padding=1), nn.Activation("relu"),
            nn.Flatten(), nn.Dense(5))
    net.initialize(mx.init.Xavier())  # shapes deferred (no forward yet)
    x = nd.array(onp.random.RandomState(0).randn(2, 3, 8, 8).astype("f"))
    qb = quantize_net_graph(net, calib_data=[x], calib_mode="naive",
                            exclude_layers_match=("conv",))
    js = qb._outputs.tojson()
    assert "_contrib_quantized_conv" not in js
    assert "_contrib_quantized_fully_connected" in js
    assert qb(x).shape == (2, 5)


def test_elide_pair_removal_golden():
    """round-19 elision golden: ``quantize_v2(dequantize(triple))``
    collapses onto the producer triple, DCE collects the orphaned
    round trip, and the counter ticks."""
    import json as J

    from mxnet_tpu.analysis import quantize as qp
    from mxnet_tpu.analysis.graph_opt import optimize_symbol

    x = S.var("x")
    q = S.quantize_v2(x, out_type="int8", name="q0")
    d = S.dequantize(q[0], q[1], q[2], name="d0")
    q2 = S.quantize_v2(d, out_type="int8", name="q1")
    out = S.dequantize(q2[0], q2[1], q2[2], name="d1")
    qp.reset_counters()
    opt, st = optimize_symbol(out, level=1,
                              passes=("quantize_elide", "dce"),
                              subject="elide")
    assert not st.get("rejected")
    ops = [n["op"] for n in J.loads(opt.tojson())["nodes"]]
    assert sum(o in ("quantize_v2", "_contrib_quantize_v2")
               for o in ops) == 1, ops
    assert sum(o in ("dequantize", "_contrib_dequantize")
               for o in ops) == 1, ops
    assert qp.counters()["islands_elided"] == 1
    xs = nd.array(onp.random.RandomState(3).randn(4, 5).astype("f"))
    a = out.eval_with({"x": xs}).asnumpy()
    b = opt.eval_with({"x": xs}).asnumpy()
    assert _rel_err(b, a) < 0.02


def test_elide_negative_non_quantized_consumer():
    """Negative golden: when a plain fp32 op ALSO reads the quantize
    node, elision must NOT fire — re-pointing it at the producer triple
    could change the lattice it observes."""
    import json as J

    from mxnet_tpu.analysis import quantize as qp
    from mxnet_tpu.analysis.graph_opt import optimize_symbol

    x = S.var("x")
    q = S.quantize_v2(x, out_type="int8", name="q0")
    d = S.dequantize(q[0], q[1], q[2], name="d0")
    q2 = S.quantize_v2(d, out_type="int8", name="q1")
    d1 = S.dequantize(q2[0], q2[1], q2[2], name="d1")
    leak = S.elemwise_add(q2[0], q2[0], name="leak")
    out = S.Group([d1, leak])
    qp.reset_counters()
    opt, _ = optimize_symbol(out, level=1,
                             passes=("quantize_elide", "dce"),
                             subject="elide_neg")
    ops = [n["op"] for n in J.loads(opt.tojson())["nodes"]]
    assert sum(o in ("quantize_v2", "_contrib_quantize_v2")
               for o in ops) == 2, ops
    assert qp.counters()["islands_elided"] == 0


def test_quantize_mixed_fp32_int8_boundaries(cnn):
    """A non-quantizable op mid-graph (sigmoid — only relu quantizes)
    splits the int8 region in two: a dequantize/quantize pair brackets
    it, each island keeps its own boundary, and accuracy holds."""
    import json as J

    data = S.var("data")
    c1 = S.Convolution(data, name="conv1", kernel=(3, 3), num_filter=6,
                       pad=(1, 1))
    sg = S.Activation(c1, name="sig1", act_type="sigmoid")
    c2 = S.Convolution(sg, name="conv2", kernel=(3, 3), num_filter=6,
                       pad=(1, 1))
    fc = S.FullyConnected(S.Flatten(c2, name="fl"), name="fc1",
                          num_hidden=4)
    args = fc.list_arguments()
    shp, _, _ = fc.infer_shape(data=(2, 3, 12, 12))
    onp.random.seed(2)
    params = {n: nd.array(onp.random.randn(*s).astype("f") * 0.2)
              for n, s in zip(args, shp) if n != "data"}
    x = nd.array(onp.random.randn(2, 3, 12, 12).astype("f"))
    fp32 = fc.eval_with({**params, "data": x}).asnumpy()
    calib = [x, nd.array(onp.random.randn(2, 3, 12, 12).astype("f"))]
    qsym, qarg, _ = quantize_model(fc, params, {}, calib_mode="naive",
                                   calib_data=calib)
    ops = [n["op"] for n in J.loads(qsym.tojson())["nodes"]]
    assert "Activation" in ops  # sigmoid stayed fp32 (reference name)
    assert sum(o in ("quantize_v2", "_contrib_quantize_v2")
               for o in ops) == 2, ops
    assert sum(o in ("dequantize", "_contrib_dequantize")
               for o in ops) == 2, ops
    out = qsym.eval_with({**qarg, "data": x}).asnumpy()
    assert _rel_err(out, fp32) < 0.1


def test_post_verify_rejects_broken_quantize(cnn, monkeypatch):
    """The acceptance gate on the rejection net: a deliberately-broken
    int8 rewrite (quantized conv re-pointed at an unregistered op) trips
    post-verify (GV101) and the caller gets the ORIGINAL fp32 graph —
    bitwise, because it is the same object."""
    from mxnet_tpu.analysis import quantize as qp

    symb, arg_params, aux_params, x, fp32, calib = cnn
    monkeypatch.setitem(qp.QUANTIZED_OPS, "convolution",
                        "_contrib_quantized_bogus")
    qsym, offline = quantize_symbol(symb)
    assert offline == {}
    # the degraded result IS the original graph object — the strongest
    # bitwise statement there is (re-running the same executable twice
    # is not bitwise-stable on CPU XLA, so compare identity, not floats)
    assert qsym is symb
    out = qsym.eval_with({**arg_params, **aux_params,
                          "data": x}).asnumpy()
    assert onp.allclose(out, fp32, rtol=1e-5, atol=1e-5)


def test_quantized_batch_dot():
    """round-19: batch_dot quantizes with BOTH operands as activations
    (runtime minmax boundaries, no offline weights), accumulates int32
    through requantize, and matches fp32 within int8 tolerance."""
    import json as J

    a, b = S.var("a"), S.var("b")
    for kw in ({}, {"transpose_b": True}):
        out = S.batch_dot(a, b, **kw)
        qsym, offline = quantize_symbol(out)
        assert offline == {}
        ops = [n["op"] for n in J.loads(qsym.tojson())["nodes"]]
        assert "_contrib_quantized_batch_dot" in ops, ops
        assert "requantize" in ops or "_contrib_requantize" in ops, ops
        rs = onp.random.RandomState(5)
        av = nd.array(rs.randn(2, 4, 8).astype("f"))
        bv = nd.array(rs.randn(2, 4, 8).astype("f") if kw
                      else rs.randn(2, 8, 4).astype("f"))
        fp32 = out.eval_with({"a": av, "b": bv}).asnumpy()
        q = qsym.eval_with({"a": av, "b": bv}).asnumpy()
        assert _rel_err(q, fp32) < 0.1, _rel_err(q, fp32)


def test_profiler_quantize_counters_surface(cnn):
    from mxnet_tpu import profiler
    from mxnet_tpu.analysis import quantize as qp

    symb = cnn[0]
    qp.reset_counters()
    quantize_symbol(symb)
    c = profiler.quantize_counters()
    assert c["graphs_quantized"] == 1
    assert c["nodes_quantized"] > 0
    assert c["islands_elided"] > 0
    assert c == qp.counters()


def test_quantize_lowering_knob(monkeypatch):
    """MXNET_QUANTIZE_LOWERING: auto resolves per backend (dequant off
    TPU), explicit values pass through, junk raises."""
    import jax

    from mxnet_tpu.ndarray import ops_quant

    monkeypatch.delenv("MXNET_QUANTIZE_LOWERING", raising=False)
    expect = "native" if jax.default_backend() == "tpu" else "dequant"
    assert ops_quant.lowering() == expect
    for mode in ("native", "dequant"):
        monkeypatch.setenv("MXNET_QUANTIZE_LOWERING", mode)
        assert ops_quant.lowering() == mode
    monkeypatch.setenv("MXNET_QUANTIZE_LOWERING", "fast")
    with pytest.raises(ValueError):
        ops_quant.lowering()


def test_quantized_dtype_auto_uint8():
    """quantized_dtype='auto' (reference quantize_v2.cc auto mode):
    provably non-negative region boundaries (post-relu) take the uint8
    lattice; conv/fc consumers force int8 at their own boundary (XLA
    convs need matching operand dtypes) or hop uint8 chains onto the
    int8 lattice in-op."""
    import json as J

    def build(with_pool):
        data = S.var("data")
        c1 = S.Convolution(data, name="conv1", kernel=(3, 3),
                           num_filter=6, pad=(1, 1))
        r1 = S.Activation(c1, name="relu1", act_type="relu")
        mid = S.Pooling(r1, name="pool1", kernel=(2, 2), stride=(2, 2),
                        pool_type="max") if with_pool else r1
        c2 = S.Convolution(mid, name="conv2", kernel=(3, 3),
                           num_filter=6, pad=(1, 1))
        return S.FullyConnected(S.Flatten(c2, name="fl"), name="fc1",
                                num_hidden=4)

    onp.random.seed(0)
    for with_pool, expect_u8 in ((False, 0), (True, 1)):
        fc = build(with_pool)
        args = fc.list_arguments()
        shp, _, _ = fc.infer_shape(data=(2, 3, 12, 12))
        params = {n: nd.array(onp.random.randn(*s).astype("f") * 0.2)
                  for n, s in zip(args, shp) if n != "data"}
        x = nd.array(onp.random.randn(2, 3, 12, 12).astype("f"))
        fp32 = fc.eval_with({**params, "data": x}).asnumpy()
        calib = [x, nd.array(onp.random.randn(2, 3, 12, 12).astype("f"))]
        qsym, qarg, _ = quantize_model(
            fc, params, {}, calib_mode="naive", calib_data=calib,
            quantized_dtype="auto", excluded_sym_names=("conv1", "relu1"))
        nodes = J.loads(qsym.tojson())["nodes"]
        u8 = [n for n in nodes
              if n["op"] in ("quantize_v2", "_contrib_quantize_v2")
              and n.get("attrs", {}).get("out_type") == "uint8"]
        assert len(u8) == expect_u8, (with_pool, u8)
        out = qsym.eval_with({**qarg, "data": x}).asnumpy()
        assert _rel_err(out, fp32) < 0.1
