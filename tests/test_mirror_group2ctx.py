"""MXNET_BACKWARD_DO_MIRROR (remat) wiring + group2ctxs semantics
(reference: src/nnvm/gradient.cc:275 mirror pass; c_api_executor.cc:314
group2ctx placement)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym, autograd, gluon
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.module import Module


def _grads_of_hybrid_net(monkeypatch, mirror):
    if mirror:
        monkeypatch.setenv("MXNET_BACKWARD_DO_MIRROR", "1")
    else:
        monkeypatch.delenv("MXNET_BACKWARD_DO_MIRROR", raising=False)
    mx.random.seed(7)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="tanh"), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    x = nd.array(onp.random.RandomState(0).randn(8, 5).astype("f"))
    with autograd.record():
        out = net(x)
        loss = (out * out).mean()
    loss.backward()
    # positional, not by name: gluon's block counters are process-global
    # so the two nets get different auto prefixes
    return [p.grad().asnumpy()
            for _, p in sorted(net.collect_params().items())]


def test_mirror_gradients_match_baseline(monkeypatch):
    """Remat changes memory/compute, NEVER values."""
    base = _grads_of_hybrid_net(monkeypatch, mirror=False)
    mirrored = _grads_of_hybrid_net(monkeypatch, mirror=True)
    assert len(base) == len(mirrored) and base
    for b, m in zip(base, mirrored):
        onp.testing.assert_allclose(m, b, rtol=1e-5, atol=1e-6)


def test_mirror_inserts_remat_in_executor_backward(monkeypatch):
    """The backward jaxpr carries the remat primitive when the knob is
    set — the recompute-count proxy for 'activations are mirrored'."""
    monkeypatch.setenv("MXNET_BACKWARD_DO_MIRROR", "1")
    data = sym.Variable("data")
    fc = sym.FullyConnected(data, name="fc", num_hidden=8)
    out = sym.LinearRegressionOutput(sym.Activation(fc, act_type="tanh"),
                                     sym.Variable("label"))
    ex = out.simple_bind(data=(4, 3), label=(4, 8))
    ex._ensure_fwd()
    vals = [a.data for a in ex.arg_arrays + ex.aux_arrays]
    jaxpr = str(ex._grad_jit.trace(vals).jaxpr)
    assert "remat" in jaxpr
    # and without the knob there is no remat
    monkeypatch.delenv("MXNET_BACKWARD_DO_MIRROR")
    ex2 = out.simple_bind(data=(4, 3), label=(4, 8))
    ex2._ensure_fwd()
    assert "remat" not in str(ex2._grad_jit.trace(vals).jaxpr)


def test_group2ctxs_nontrivial_raises():
    data = sym.Variable("data")
    with mx.AttrScope(ctx_group="dev1"):
        fc1 = sym.FullyConnected(data, name="fc1", num_hidden=4)
    with mx.AttrScope(ctx_group="dev2"):
        out = sym.FullyConnected(fc1, name="fc2", num_hidden=2)
    with pytest.raises(MXNetError, match="group2ctxs"):
        Module(out, context=mx.cpu(),
               group2ctxs={"dev1": mx.cpu(0), "dev2": mx.cpu(1)})


def test_group2ctxs_trivial_mapping_accepted():
    data = sym.Variable("data")
    fc = sym.FullyConnected(data, name="fc", num_hidden=2)
    m = Module(fc, label_names=[], context=mx.cpu(0),
               group2ctxs={"dev1": mx.cpu(0)})
    m.bind(data_shapes=[("data", (2, 3))])
    m.init_params()
    m.forward(mx.io.DataBatch(data=[nd.ones((2, 3))]), is_train=False)
    assert m.get_outputs()[0].shape == (2, 2)
