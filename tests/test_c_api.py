"""Flat C ABI tests (native/c_api.cc over mxnet_tpu/c_bridge.py).

Reference surface: include/mxnet/c_api.h + c_predict_api.h; the reference
exercises these through its frontend bindings, here we drive them through
ctypes exactly as an external C consumer would (plus one genuinely
standalone compiled C program for the deploy story).
"""
import ctypes
import os
import shutil
import struct
import subprocess
import sys

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu._native import build_c_api

i64 = ctypes.c_int64


@pytest.fixture(scope="module")
def capi():
    so = build_c_api()
    if so is None:
        pytest.skip("no toolchain to build libmxnet_c.so")
    lib = ctypes.CDLL(so)
    vp, c_int, u32 = ctypes.c_void_p, ctypes.c_int, ctypes.c_uint32
    lib.MXGetLastError.restype = ctypes.c_char_p
    lib.MXGetVersion.argtypes = [ctypes.POINTER(c_int)]
    lib.MXNDArrayCreate.argtypes = [ctypes.POINTER(i64), c_int, c_int,
                                    ctypes.POINTER(vp)]
    lib.MXNDArrayFree.argtypes = [vp]
    lib.MXNDArrayGetShape.argtypes = [vp, ctypes.POINTER(c_int),
                                      ctypes.POINTER(i64)]
    lib.MXNDArrayGetDType.argtypes = [vp, ctypes.POINTER(c_int)]
    lib.MXNDArraySyncCopyFromCPU.argtypes = [vp, vp, ctypes.c_size_t]
    lib.MXNDArraySyncCopyToCPU.argtypes = [vp, vp, ctypes.c_size_t]
    lib.MXImperativeInvoke.argtypes = [
        ctypes.c_char_p, c_int, ctypes.POINTER(vp), ctypes.POINTER(c_int),
        ctypes.POINTER(ctypes.POINTER(vp)), c_int,
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_char_p)]
    lib.MXPredCreate.argtypes = [
        ctypes.c_char_p, vp, ctypes.c_size_t, c_int, c_int, u32,
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(u32),
        ctypes.POINTER(i64), ctypes.POINTER(vp)]
    lib.MXPredSetInput.argtypes = [vp, ctypes.c_char_p,
                                   ctypes.POINTER(ctypes.c_float), u32]
    lib.MXPredForward.argtypes = [vp]
    lib.MXPredGetOutputShape.argtypes = [vp, u32, ctypes.POINTER(c_int),
                                         ctypes.POINTER(i64)]
    lib.MXPredGetOutput.argtypes = [vp, u32,
                                    ctypes.POINTER(ctypes.c_float), u32]
    lib.MXPredFree.argtypes = [vp]
    return lib


def _err(lib):
    return lib.MXGetLastError().decode()


def test_version_and_error_empty(capi):
    v = ctypes.c_int()
    assert capi.MXGetVersion(ctypes.byref(v)) == 0
    assert v.value >= 10000


def test_ndarray_roundtrip(capi):
    shape = (i64 * 2)(3, 4)
    h = ctypes.c_void_p()
    assert capi.MXNDArrayCreate(shape, 2, 0, ctypes.byref(h)) == 0, _err(capi)
    ndim = ctypes.c_int()
    out_shape = (i64 * 8)()
    assert capi.MXNDArrayGetShape(h, ctypes.byref(ndim), out_shape) == 0
    assert ndim.value == 2 and tuple(out_shape[:2]) == (3, 4)
    dt = ctypes.c_int()
    assert capi.MXNDArrayGetDType(h, ctypes.byref(dt)) == 0
    assert dt.value == 0  # float32
    data = onp.arange(12, dtype="f").reshape(3, 4)
    assert capi.MXNDArraySyncCopyFromCPU(
        h, data.ctypes.data_as(ctypes.c_void_p), data.nbytes) == 0, _err(capi)
    back = onp.zeros_like(data)
    assert capi.MXNDArraySyncCopyToCPU(
        h, back.ctypes.data_as(ctypes.c_void_p), back.nbytes) == 0, _err(capi)
    onp.testing.assert_array_equal(back, data)
    assert capi.MXNDArrayFree(h) == 0


def test_imperative_invoke(capi):
    def make(vals):
        a = onp.asarray(vals, dtype="f")
        shape = (i64 * a.ndim)(*a.shape)
        h = ctypes.c_void_p()
        assert capi.MXNDArrayCreate(shape, a.ndim, 0, ctypes.byref(h)) == 0
        assert capi.MXNDArraySyncCopyFromCPU(
            h, a.ctypes.data_as(ctypes.c_void_p), a.nbytes) == 0
        return h, a

    ha, a = make([[1.0, 2.0], [3.0, 4.0]])
    hb, b = make([[10.0, 20.0], [30.0, 40.0]])
    ins = (ctypes.c_void_p * 2)(ha, hb)
    nout = ctypes.c_int()
    outs = ctypes.POINTER(ctypes.c_void_p)()
    assert capi.MXImperativeInvoke(
        b"broadcast_add", 2, ins, ctypes.byref(nout), ctypes.byref(outs),
        0, None, None) == 0, _err(capi)
    assert nout.value == 1
    res = onp.zeros((2, 2), dtype="f")
    assert capi.MXNDArraySyncCopyToCPU(
        outs[0], res.ctypes.data_as(ctypes.c_void_p), res.nbytes) == 0
    onp.testing.assert_allclose(res, a + b)
    assert capi.MXNDArrayWaitAll() == 0
    capi.MXNDArrayFree(ha)
    capi.MXNDArrayFree(hb)


def test_imperative_invoke_with_params(capi):
    a = onp.arange(6, dtype="f").reshape(2, 3)
    shape = (i64 * 2)(2, 3)
    h = ctypes.c_void_p()
    capi.MXNDArrayCreate(shape, 2, 0, ctypes.byref(h))
    capi.MXNDArraySyncCopyFromCPU(
        h, a.ctypes.data_as(ctypes.c_void_p), a.nbytes)
    ins = (ctypes.c_void_p * 1)(h)
    nout = ctypes.c_int()
    outs = ctypes.POINTER(ctypes.c_void_p)()
    keys = (ctypes.c_char_p * 1)(b"shape")
    vals = (ctypes.c_char_p * 1)(b"(3, 2)")
    assert capi.MXImperativeInvoke(
        b"reshape", 1, ins, ctypes.byref(nout), ctypes.byref(outs),
        1, keys, vals) == 0, _err(capi)
    ndim = ctypes.c_int()
    oshape = (i64 * 8)()
    capi.MXNDArrayGetShape(outs[0], ctypes.byref(ndim), oshape)
    assert tuple(oshape[:2]) == (3, 2)
    capi.MXNDArrayFree(h)


def test_invoke_unknown_op_sets_error(capi):
    nout = ctypes.c_int()
    outs = ctypes.POINTER(ctypes.c_void_p)()
    rc = capi.MXImperativeInvoke(
        b"definitely_not_an_op", 0, None, ctypes.byref(nout),
        ctypes.byref(outs), 0, None, None)
    assert rc == -1
    assert "definitely_not_an_op" in _err(capi)


@pytest.fixture(scope="module")
def exported_mlp(tmp_path_factory):
    """Export a small trained-ish MLP the way a deploy pipeline would:
    symbol json + reference-format params with arg:/aux: prefixes."""
    root = tmp_path_factory.mktemp("c_predict")
    from mxnet_tpu import sym

    x = sym.Variable("data")
    fc1 = sym.FullyConnected(x, name="fc1", num_hidden=16,
                             weight=sym.Variable("fc1_weight"),
                             bias=sym.Variable("fc1_bias"))
    act = sym.Activation(fc1, act_type="relu")
    fc2 = sym.FullyConnected(act, name="fc2", num_hidden=3,
                             weight=sym.Variable("fc2_weight"),
                             bias=sym.Variable("fc2_bias"))
    out = sym.softmax(fc2)
    rng = onp.random.RandomState(0)
    params = {
        "arg:fc1_weight": nd.array(rng.randn(16, 8).astype("f") * 0.1),
        "arg:fc1_bias": nd.array(rng.randn(16).astype("f") * 0.1),
        "arg:fc2_weight": nd.array(rng.randn(3, 16).astype("f") * 0.1),
        "arg:fc2_bias": nd.array(rng.randn(3).astype("f") * 0.1),
    }
    json_path = os.path.join(root, "mlp-symbol.json")
    params_path = os.path.join(root, "mlp-0000.params")
    with open(json_path, "w") as f:
        f.write(out.tojson())
    nd.save(params_path, params)
    xval = rng.rand(4, 8).astype("f")
    args = {"data": nd.array(xval)}
    args.update({k[4:]: v for k, v in params.items()})
    ex = out.bind(args=args)
    expect = ex.forward(is_train=False)[0].asnumpy()
    return json_path, params_path, xval, expect


def test_c_predict_api(capi, exported_mlp):
    json_path, params_path, xval, expect = exported_mlp
    with open(json_path) as f:
        sym_json = f.read().encode()
    with open(params_path, "rb") as f:
        param_bytes = f.read()
    keys = (ctypes.c_char_p * 1)(b"data")
    indptr = (ctypes.c_uint32 * 2)(0, 2)
    shp = (i64 * 2)(4, 8)
    h = ctypes.c_void_p()
    assert capi.MXPredCreate(
        sym_json, param_bytes, len(param_bytes), 1, 0, 1, keys, indptr,
        shp, ctypes.byref(h)) == 0, _err(capi)
    assert capi.MXPredSetInput(
        h, b"data", xval.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        xval.size) == 0, _err(capi)
    assert capi.MXPredForward(h) == 0, _err(capi)
    ndim = ctypes.c_int()
    oshape = (i64 * 8)()
    assert capi.MXPredGetOutputShape(
        h, 0, ctypes.byref(ndim), oshape) == 0, _err(capi)
    shape = tuple(oshape[:ndim.value])
    assert shape == expect.shape
    res = onp.zeros(shape, dtype="f")
    assert capi.MXPredGetOutput(
        h, 0, res.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        res.size) == 0, _err(capi)
    onp.testing.assert_allclose(res, expect, rtol=1e-5, atol=1e-6)
    assert capi.MXPredFree(h) == 0


C_PROGRAM = r"""
#include <stdio.h>
#include <stdint.h>
#include <string.h>
#include "mxnet_tpu/c_api.h"

int main(void) {
  int version = 0;
  if (MXGetVersion(&version) != 0 || version < 10000) return 1;
  int64_t shape[2] = {2, 3};
  NDArrayHandle h = NULL;
  if (MXNDArrayCreate(shape, 2, 0, &h) != 0) {
    fprintf(stderr, "create: %s\n", MXGetLastError());
    return 2;
  }
  float data[6] = {1, 2, 3, 4, 5, 6};
  if (MXNDArraySyncCopyFromCPU(h, data, sizeof(data)) != 0) return 3;
  NDArrayHandle ins[1] = {h};
  int nout = 0;
  NDArrayHandle* outs = NULL;
  const char* keys[1] = {"shape"};
  const char* vals[1] = {"(3, 2)"};
  if (MXImperativeInvoke("reshape", 1, ins, &nout, &outs, 1, keys, vals)
      != 0) {
    fprintf(stderr, "invoke: %s\n", MXGetLastError());
    return 4;
  }
  int ndim = 0;
  int64_t oshape[MX_MAX_DIM];
  if (MXNDArrayGetShape(outs[0], &ndim, oshape) != 0) return 5;
  if (ndim != 2 || oshape[0] != 3 || oshape[1] != 2) return 6;
  float back[6];
  if (MXNDArraySyncCopyToCPU(outs[0], back, sizeof(back)) != 0) return 7;
  if (memcmp(back, data, sizeof(back)) != 0) return 8;
  MXNDArrayFree(h);
  printf("C_OK\n");
  return 0;
}
"""


def test_standalone_c_program(capi, tmp_path):
    """The deploy story: a plain C program (no Python code) linking
    libmxnet_c drives the runtime end to end."""
    if shutil.which("gcc") is None:
        pytest.skip("no gcc")
    so = build_c_api()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    csrc = tmp_path / "main.c"
    csrc.write_text(C_PROGRAM)
    exe = tmp_path / "drive"
    subprocess.run(
        ["gcc", str(csrc), "-o", str(exe), f"-I{repo}/include",
         so, f"-Wl,-rpath,{os.path.dirname(so)}"],
        check=True, capture_output=True)
    env = dict(os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)  # keep the child off the tunnel
    proc = subprocess.run([str(exe)], env=env, capture_output=True,
                          text=True, timeout=240)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "C_OK" in proc.stdout


# ---------------------------------------------------------------------------
# Training surface: symbol compose + simple bind + forward/backward + kvstore
# (reference: c_api_symbolic.cc, c_api_executor.cc:189, MXKVStore*)
# ---------------------------------------------------------------------------

def _train_argtypes(lib):
    vp, c_int, u32 = ctypes.c_void_p, ctypes.c_int, ctypes.c_uint32
    cp = ctypes.c_char_p
    lib.MXSymbolCreateVariable.argtypes = [cp, ctypes.POINTER(vp)]
    lib.MXSymbolCreateAtomicSymbol.argtypes = [
        cp, u32, ctypes.POINTER(cp), ctypes.POINTER(cp), ctypes.POINTER(vp)]
    lib.MXSymbolCompose.argtypes = [vp, cp, u32, ctypes.POINTER(cp),
                                    ctypes.POINTER(vp)]
    lib.MXSymbolCreateFromJSON.argtypes = [cp, ctypes.POINTER(vp)]
    lib.MXSymbolSaveToJSON.argtypes = [vp, ctypes.POINTER(cp)]
    for f in (lib.MXSymbolListArguments, lib.MXSymbolListAuxiliaryStates,
              lib.MXSymbolListOutputs):
        f.argtypes = [vp, ctypes.POINTER(u32),
                      ctypes.POINTER(ctypes.POINTER(cp))]
    lib.MXSymbolFree.argtypes = [vp]
    lib.MXExecutorSimpleBind.argtypes = [
        vp, cp, u32, ctypes.POINTER(cp), ctypes.POINTER(u32),
        ctypes.POINTER(i64), ctypes.POINTER(vp)]
    lib.MXExecutorArgArray.argtypes = [vp, cp, cp, ctypes.POINTER(vp)]
    lib.MXExecutorForward.argtypes = [vp, ctypes.c_int]
    lib.MXExecutorOutputs.argtypes = [vp, ctypes.POINTER(c_int),
                                      ctypes.POINTER(ctypes.POINTER(vp))]
    lib.MXExecutorBackward.argtypes = [vp]
    lib.MXExecutorFree.argtypes = [vp]
    lib.MXKVStoreCreate.argtypes = [cp, ctypes.POINTER(vp)]
    lib.MXKVStoreSetOptimizer.argtypes = [vp, cp, u32, ctypes.POINTER(cp),
                                          ctypes.POINTER(cp)]
    for f in (lib.MXKVStoreInit,):
        f.argtypes = [vp, u32, ctypes.POINTER(c_int), ctypes.POINTER(vp)]
    for f in (lib.MXKVStorePush, lib.MXKVStorePull):
        f.argtypes = [vp, u32, ctypes.POINTER(c_int), ctypes.POINTER(vp),
                      ctypes.c_int]
    lib.MXKVStoreFree.argtypes = [vp]
    return lib


def test_symbol_compose_and_json_roundtrip(capi):
    lib = _train_argtypes(capi)
    vp, u32, cp = ctypes.c_void_p, ctypes.c_uint32, ctypes.c_char_p
    data = vp()
    assert lib.MXSymbolCreateVariable(b"data", ctypes.byref(data)) == 0
    fc = vp()
    keys = (cp * 1)(b"num_hidden")
    vals = (cp * 1)(b"4")
    assert lib.MXSymbolCreateAtomicSymbol(
        b"FullyConnected", 1, keys, vals, ctypes.byref(fc)) == 0, _err(capi)
    args = (vp * 1)(data)
    assert lib.MXSymbolCompose(fc, b"fc", 1, None, args) == 0, _err(capi)
    n = u32()
    names = ctypes.POINTER(cp)()
    assert lib.MXSymbolListArguments(fc, ctypes.byref(n),
                                     ctypes.byref(names)) == 0
    got = sorted(names[i].decode() for i in range(n.value))
    assert got == ["data", "fc_bias", "fc_weight"]
    js = cp()
    assert lib.MXSymbolSaveToJSON(fc, ctypes.byref(js)) == 0
    re = vp()
    assert lib.MXSymbolCreateFromJSON(js.value, ctypes.byref(re)) == 0
    assert lib.MXSymbolListOutputs(re, ctypes.byref(n),
                                   ctypes.byref(names)) == 0
    assert n.value == 1 and names[0].decode() == "fc_output"
    lib.MXSymbolFree(re)
    lib.MXSymbolFree(fc)
    lib.MXSymbolFree(data)


def test_c_training_loop_via_ctypes(capi):
    """The full training story through the flat ABI: compose an MLP,
    simple-bind, forward/backward, kvstore sgd updates — loss drops."""
    lib = _train_argtypes(capi)
    vp, u32, cp, c_int = (ctypes.c_void_p, ctypes.c_uint32, ctypes.c_char_p,
                          ctypes.c_int)
    data = vp(); label = vp()
    lib.MXSymbolCreateVariable(b"data", ctypes.byref(data))
    lib.MXSymbolCreateVariable(b"softmax_label", ctypes.byref(label))
    fc1 = vp()
    lib.MXSymbolCreateAtomicSymbol(b"FullyConnected", 1,
                                   (cp * 1)(b"num_hidden"), (cp * 1)(b"16"),
                                   ctypes.byref(fc1))
    assert lib.MXSymbolCompose(fc1, b"fc1", 1, None,
                               (vp * 1)(data)) == 0, _err(capi)
    act = vp()
    lib.MXSymbolCreateAtomicSymbol(b"Activation", 1, (cp * 1)(b"act_type"),
                                   (cp * 1)(b"relu"), ctypes.byref(act))
    assert lib.MXSymbolCompose(act, b"act", 1, None,
                               (vp * 1)(fc1)) == 0, _err(capi)
    fc2 = vp()
    lib.MXSymbolCreateAtomicSymbol(b"FullyConnected", 1,
                                   (cp * 1)(b"num_hidden"), (cp * 1)(b"2"),
                                   ctypes.byref(fc2))
    assert lib.MXSymbolCompose(fc2, b"fc2", 1, None,
                               (vp * 1)(act)) == 0, _err(capi)
    sm = vp()
    lib.MXSymbolCreateAtomicSymbol(b"SoftmaxOutput", 0, None, None,
                                   ctypes.byref(sm))
    assert lib.MXSymbolCompose(sm, b"softmax", 2, None,
                               (vp * 2)(fc2, label)) == 0, _err(capi)

    B, D = 64, 8
    ikeys = (cp * 2)(b"data", b"softmax_label")
    indptr = (u32 * 3)(0, 2, 3)
    shp = (i64 * 3)(B, D, B)
    ex = vp()
    assert lib.MXExecutorSimpleBind(sm, b"write", 2, ikeys, indptr, shp,
                                    ctypes.byref(ex)) == 0, _err(capi)

    rng = onp.random.RandomState(0)
    X = rng.randn(B, D).astype("f")
    y = (X[:, 0] > 0).astype("f")

    def arr(kind, name):
        h = vp()
        assert lib.MXExecutorArgArray(ex, kind.encode(), name.encode(),
                                      ctypes.byref(h)) == 0, _err(capi)
        return h

    def put(h, a):
        a = onp.ascontiguousarray(a)
        assert capi.MXNDArraySyncCopyFromCPU(
            h, a.ctypes.data_as(vp), a.nbytes) == 0, _err(capi)

    wnames = ["fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"]
    weights = [arr("arg", n) for n in wnames]
    grads = [arr("grad", n) for n in wnames]
    put(arr("arg", "data"), X)
    put(arr("arg", "softmax_label"), y)
    for h, shape in zip(weights, [(16, D), (16,), (2, 16), (2,)]):
        put(h, (rng.randn(*shape) * 0.1).astype("f"))

    kv = vp()
    assert lib.MXKVStoreCreate(b"local", ctypes.byref(kv)) == 0
    assert lib.MXKVStoreSetOptimizer(
        kv, b"sgd", 1, (cp * 1)(b"learning_rate"),
        (cp * 1)(b"0.01")) == 0, _err(capi)
    kkeys = (c_int * 4)(0, 1, 2, 3)
    assert lib.MXKVStoreInit(kv, 4, kkeys, (vp * 4)(*weights)) == 0, \
        _err(capi)

    def step():
        assert lib.MXExecutorForward(ex, 1) == 0, _err(capi)
        nout = c_int()
        outs = ctypes.POINTER(vp)()
        assert lib.MXExecutorOutputs(ex, ctypes.byref(nout),
                                     ctypes.byref(outs)) == 0
        probs = onp.zeros((B, 2), "f")
        assert capi.MXNDArraySyncCopyToCPU(
            outs[0], probs.ctypes.data_as(vp), probs.nbytes) == 0
        loss = -onp.log(probs[onp.arange(B), y.astype(int)] + 1e-9).mean()
        assert lib.MXExecutorBackward(ex) == 0, _err(capi)
        assert lib.MXKVStorePush(kv, 4, kkeys, (vp * 4)(*grads), 0) == 0
        assert lib.MXKVStorePull(kv, 4, kkeys, (vp * 4)(*weights), 0) == 0
        return loss

    first = step()
    last = None
    for _ in range(25):
        last = step()
    assert last < first * 0.5, (first, last)
    lib.MXKVStoreFree(kv)
    lib.MXExecutorFree(ex)
    for h in weights + grads:
        capi.MXNDArrayFree(h)


C_TRAIN_PROGRAM = r"""
#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include "mxnet_tpu/c_api.h"

#define B 64
#define D 8
#define H 16
#define CK(x) do { if ((x) != 0) { \
  fprintf(stderr, "%s\n", MXGetLastError()); return 1; } } while (0)

static unsigned lcg = 42u;
static float frand(void) {  /* uniform in [-1, 1) */
  lcg = lcg * 1664525u + 1013904223u;
  return ((lcg >> 8) / 8388608.0f) - 1.0f;
}

int main(void) {
  SymbolHandle data, label, fc1, act, fc2, sm;
  CK(MXSymbolCreateVariable("data", &data));
  CK(MXSymbolCreateVariable("softmax_label", &label));
  const char* kh = "num_hidden"; const char* ka = "act_type";
  const char* v16 = "16"; const char* v2 = "2"; const char* vr = "relu";
  CK(MXSymbolCreateAtomicSymbol("FullyConnected", 1, &kh, &v16, &fc1));
  CK(MXSymbolCompose(fc1, "fc1", 1, NULL, &data));
  CK(MXSymbolCreateAtomicSymbol("Activation", 1, &ka, &vr, &act));
  CK(MXSymbolCompose(act, "act", 1, NULL, &fc1));
  CK(MXSymbolCreateAtomicSymbol("FullyConnected", 1, &kh, &v2, &fc2));
  CK(MXSymbolCompose(fc2, "fc2", 1, NULL, &act));
  CK(MXSymbolCreateAtomicSymbol("SoftmaxOutput", 0, NULL, NULL, &sm));
  SymbolHandle smargs[2]; smargs[0] = fc2; smargs[1] = label;
  CK(MXSymbolCompose(sm, "softmax", 2, NULL, smargs));

  const char* ikeys[2] = {"data", "softmax_label"};
  uint32_t indptr[3] = {0, 2, 3};
  int64_t shp[3] = {B, D, B};
  ExecutorHandle ex;
  CK(MXExecutorSimpleBind(sm, "write", 2, ikeys, indptr, shp, &ex));

  float X[B * D], y[B];
  for (int i = 0; i < B; ++i) {
    for (int j = 0; j < D; ++j) X[i * D + j] = frand();
    y[i] = X[i * D] > 0.0f ? 1.0f : 0.0f;
  }
  NDArrayHandle hx, hy;
  CK(MXExecutorArgArray(ex, "arg", "data", &hx));
  CK(MXExecutorArgArray(ex, "arg", "softmax_label", &hy));
  CK(MXNDArraySyncCopyFromCPU(hx, X, sizeof(X)));
  CK(MXNDArraySyncCopyFromCPU(hy, y, sizeof(y)));

  const char* wn[4] = {"fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"};
  int wsize[4] = {H * D, H, 2 * H, 2};
  NDArrayHandle w[4], g[4];
  for (int i = 0; i < 4; ++i) {
    CK(MXExecutorArgArray(ex, "arg", wn[i], &w[i]));
    CK(MXExecutorArgArray(ex, "grad", wn[i], &g[i]));
    float buf[H * D];
    for (int j = 0; j < wsize[i]; ++j) buf[j] = 0.1f * frand();
    CK(MXNDArraySyncCopyFromCPU(w[i], buf, wsize[i] * sizeof(float)));
  }

  KVStoreHandle kv;
  CK(MXKVStoreCreate("local", &kv));
  const char* ok = "learning_rate"; const char* ov = "0.01";
  CK(MXKVStoreSetOptimizer(kv, "sgd", 1, &ok, &ov));
  int keys[4] = {0, 1, 2, 3};
  CK(MXKVStoreInit(kv, 4, keys, w));

  float first = -1.0f, loss = 0.0f;
  for (int step = 0; step < 25; ++step) {
    CK(MXExecutorForward(ex, 1));
    int nout = 0;
    NDArrayHandle* outs = NULL;
    CK(MXExecutorOutputs(ex, &nout, &outs));
    float probs[B * 2];
    CK(MXNDArraySyncCopyToCPU(outs[0], probs, sizeof(probs)));
    loss = 0.0f;
    for (int i = 0; i < B; ++i)
      loss -= logf(probs[i * 2 + (int)y[i]] + 1e-9f) / B;
    if (first < 0.0f) first = loss;
    CK(MXExecutorBackward(ex));
    CK(MXKVStorePush(kv, 4, keys, g, 0));
    CK(MXKVStorePull(kv, 4, keys, w, 0));
  }
  if (!(loss < first * 0.5f)) {
    fprintf(stderr, "loss did not halve: %f -> %f\n", first, loss);
    return 2;
  }
  printf("C_TRAIN_OK %f -> %f\n", first, loss);
  MXKVStoreFree(kv);
  MXExecutorFree(ex);
  return 0;
}
"""


def test_standalone_c_training_program(capi, tmp_path):
    """A plain C program (no Python source) composes the MLP, binds it,
    and trains with kvstore sgd until the loss halves — the reference's
    'any frontend can train through the C ABI' property."""
    if shutil.which("gcc") is None:
        pytest.skip("no gcc")
    so = build_c_api()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    csrc = tmp_path / "train.c"
    csrc.write_text(C_TRAIN_PROGRAM)
    exe = tmp_path / "ctrain"
    subprocess.run(
        ["gcc", str(csrc), "-o", str(exe), f"-I{repo}/include",
         so, "-lm", f"-Wl,-rpath,{os.path.dirname(so)}"],
        check=True, capture_output=True)
    env = dict(os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run([str(exe)], env=env, capture_output=True,
                          text=True, timeout=300)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "C_TRAIN_OK" in proc.stdout


def test_misc_abi_surface(capi, exported_mlp):
    """MXPredReshape keeps weights; NDArray reshape/slice views; symbol
    attrs; kvstore metadata."""
    lib = _train_argtypes(capi)
    vp, u32, cp, c_int = (ctypes.c_void_p, ctypes.c_uint32, ctypes.c_char_p,
                          ctypes.c_int)
    lib.MXPredReshape.argtypes = [u32, ctypes.POINTER(cp),
                                  ctypes.POINTER(u32), ctypes.POINTER(i64),
                                  vp, ctypes.POINTER(vp)]
    lib.MXNDArrayReshape.argtypes = [vp, c_int, ctypes.POINTER(i64),
                                     ctypes.POINTER(vp)]
    lib.MXNDArraySlice.argtypes = [vp, i64, i64, ctypes.POINTER(vp)]
    lib.MXSymbolGetAttr.argtypes = [vp, cp, ctypes.POINTER(cp),
                                    ctypes.POINTER(c_int)]
    lib.MXSymbolSetAttr.argtypes = [vp, cp, cp]
    lib.MXKVStoreGetType.argtypes = [vp, ctypes.POINTER(cp)]
    lib.MXKVStoreGetRank.argtypes = [vp, ctypes.POINTER(c_int)]
    lib.MXKVStoreGetGroupSize.argtypes = [vp, ctypes.POINTER(c_int)]

    # predictor reshape keeps weights (batch 4 -> 2)
    json_path, params_path, xval, expect = exported_mlp
    with open(json_path) as f:
        sym_json = f.read().encode()
    with open(params_path, "rb") as f:
        param_bytes = f.read()
    keys = (cp * 1)(b"data")
    indptr = (u32 * 2)(0, 2)
    shp = (i64 * 2)(4, 8)
    h = vp()
    assert capi.MXPredCreate(sym_json, param_bytes, len(param_bytes), 1, 0,
                             1, keys, indptr, shp, ctypes.byref(h)) == 0
    shp2 = (i64 * 2)(2, 8)
    h2 = vp()
    assert lib.MXPredReshape(1, keys, indptr, shp2, h,
                             ctypes.byref(h2)) == 0, _err(capi)
    x2 = onp.ascontiguousarray(xval[:2])
    assert capi.MXPredSetInput(
        h2, b"data", x2.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        x2.size) == 0
    assert capi.MXPredForward(h2) == 0
    res = onp.zeros((2, 3), "f")
    assert capi.MXPredGetOutput(
        h2, 0, res.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        res.size) == 0
    onp.testing.assert_allclose(res, expect[:2], rtol=1e-5, atol=1e-6)
    capi.MXPredFree(h2)
    capi.MXPredFree(h)

    # ndarray reshape + slice
    a = vp()
    shape = (i64 * 2)(4, 3)
    assert capi.MXNDArrayCreate(shape, 2, 0, ctypes.byref(a)) == 0
    data = onp.arange(12, dtype="f")
    assert capi.MXNDArraySyncCopyFromCPU(a, data.ctypes.data_as(vp),
                                         data.nbytes) == 0
    r = vp()
    newshape = (i64 * 2)(3, 4)
    assert lib.MXNDArrayReshape(a, 2, newshape, ctypes.byref(r)) == 0
    nd_ = ctypes.c_int()
    oshape = (i64 * 8)()
    assert capi.MXNDArrayGetShape(r, ctypes.byref(nd_), oshape) == 0
    assert tuple(oshape[:2]) == (3, 4)
    s = vp()
    assert lib.MXNDArraySlice(a, 1, 3, ctypes.byref(s)) == 0
    assert capi.MXNDArrayGetShape(s, ctypes.byref(nd_), oshape) == 0
    assert tuple(oshape[:2]) == (2, 3)
    for x in (a, r, s):
        capi.MXNDArrayFree(x)

    # symbol attrs
    sym = vp()
    lib.MXSymbolCreateVariable(b"w", ctypes.byref(sym))
    assert lib.MXSymbolSetAttr(sym, b"__lr_mult__", b"2.5") == 0
    val = cp()
    ok = c_int()
    assert lib.MXSymbolGetAttr(sym, b"__lr_mult__", ctypes.byref(val),
                               ctypes.byref(ok)) == 0
    assert ok.value == 1 and val.value == b"2.5"
    assert lib.MXSymbolGetAttr(sym, b"missing", ctypes.byref(val),
                               ctypes.byref(ok)) == 0
    assert ok.value == 0
    lib.MXSymbolFree(sym)

    # kvstore metadata
    kv = vp()
    assert lib.MXKVStoreCreate(b"local", ctypes.byref(kv)) == 0
    t = cp()
    assert lib.MXKVStoreGetType(kv, ctypes.byref(t)) == 0
    assert t.value == b"local"
    rank = c_int(); size = c_int()
    assert lib.MXKVStoreGetRank(kv, ctypes.byref(rank)) == 0
    assert lib.MXKVStoreGetGroupSize(kv, ctypes.byref(size)) == 0
    assert rank.value == 0 and size.value >= 1
    lib.MXKVStoreFree(kv)


def test_attr_on_uncomposed_atomic_symbol(capi):
    """Reference ordering: SetAttr on an atomic symbol BEFORE Compose;
    the attr must survive composition."""
    lib = _train_argtypes(capi)
    vp, cp, c_int = ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int
    fc = vp()
    lib.MXSymbolCreateAtomicSymbol(b"FullyConnected", 1,
                                   (cp * 1)(b"num_hidden"), (cp * 1)(b"2"),
                                   ctypes.byref(fc))
    assert lib.MXSymbolSetAttr(fc, b"__lr_mult__", b"3.0") == 0, _err(capi)
    val = cp(); ok = c_int()
    assert lib.MXSymbolGetAttr(fc, b"__lr_mult__", ctypes.byref(val),
                               ctypes.byref(ok)) == 0
    assert ok.value == 1 and val.value == b"3.0"
    data = vp()
    lib.MXSymbolCreateVariable(b"data", ctypes.byref(data))
    assert lib.MXSymbolCompose(fc, b"fc", 1, None, (vp * 1)(data)) == 0
    assert lib.MXSymbolGetAttr(fc, b"__lr_mult__", ctypes.byref(val),
                               ctypes.byref(ok)) == 0
    assert ok.value == 1 and val.value == b"3.0"
    lib.MXSymbolFree(fc)
    lib.MXSymbolFree(data)


def test_c_ndarray_save_load_roundtrip(capi, tmp_path):
    """A C frontend can checkpoint what it trained: Save handles with
    names, Load them back, bytes identical (reference MXNDArraySave)."""
    lib = _train_argtypes(capi)
    vp, u32, cp = ctypes.c_void_p, ctypes.c_uint32, ctypes.c_char_p
    lib.MXNDArraySave.argtypes = [cp, u32, ctypes.POINTER(vp),
                                  ctypes.POINTER(cp)]
    lib.MXNDArrayLoad.argtypes = [cp, ctypes.POINTER(u32),
                                  ctypes.POINTER(ctypes.POINTER(vp)),
                                  ctypes.POINTER(u32),
                                  ctypes.POINTER(ctypes.POINTER(cp))]
    a = vp()
    shape = (i64 * 2)(2, 3)
    assert capi.MXNDArrayCreate(shape, 2, 0, ctypes.byref(a)) == 0
    data = onp.arange(6, dtype="f") * 1.5
    assert capi.MXNDArraySyncCopyFromCPU(a, data.ctypes.data_as(vp),
                                         data.nbytes) == 0
    fname = str(tmp_path / "ck.params").encode()
    keys = (cp * 1)(b"arg:w")
    assert lib.MXNDArraySave(fname, 1, (vp * 1)(a), keys) == 0, _err(capi)
    n = u32(); nn = u32()
    arrs = ctypes.POINTER(vp)()
    names = ctypes.POINTER(cp)()
    assert lib.MXNDArrayLoad(fname, ctypes.byref(n), ctypes.byref(arrs),
                             ctypes.byref(nn),
                             ctypes.byref(names)) == 0, _err(capi)
    assert n.value == 1 and nn.value == 1
    assert names[0] == b"arg:w"
    back = onp.zeros(6, "f")
    assert capi.MXNDArraySyncCopyToCPU(arrs[0], back.ctypes.data_as(vp),
                                       back.nbytes) == 0
    onp.testing.assert_allclose(back, data)
    # python side reads the same file (cross-surface interop)
    loaded = nd.load(str(tmp_path / "ck.params"))
    onp.testing.assert_allclose(loaded["arg:w"].asnumpy().ravel(), data)
    capi.MXNDArrayFree(a)


def test_c_ndarray_save_duplicate_keys(capi, tmp_path):
    """Duplicate names write sequentially like the reference list
    container — not silently collapsed through a dict."""
    import struct as _struct

    lib = _train_argtypes(capi)
    vp, cp = ctypes.c_void_p, ctypes.c_char_p
    arrs = []
    for val in (1.0, 2.0):
        a = vp()
        shape = (i64 * 1)(2)
        assert capi.MXNDArrayCreate(shape, 1, 0, ctypes.byref(a)) == 0
        d = onp.full(2, val, "f")
        capi.MXNDArraySyncCopyFromCPU(a, d.ctypes.data_as(vp), d.nbytes)
        arrs.append(a)
    fname = str(tmp_path / "dup.params")
    keys = (cp * 2)(b"w", b"w")
    assert lib.MXNDArraySave(fname.encode(), 2, (vp * 2)(*arrs),
                             keys) == 0, _err(capi)
    with open(fname, "rb") as f:
        buf = f.read()
    (count,) = _struct.unpack_from("<Q", buf, 16)
    assert count == 2  # both entries on disk
    # and MXNDArrayLoad returns BOTH entries (parallel arrays, unlike
    # the python dict view)
    u32 = ctypes.c_uint32
    n = u32(); nn = u32()
    la = ctypes.POINTER(vp)()
    ln = ctypes.POINTER(cp)()
    assert lib.MXNDArrayLoad(fname.encode(), ctypes.byref(n),
                             ctypes.byref(la), ctypes.byref(nn),
                             ctypes.byref(ln)) == 0, _err(capi)
    assert n.value == 2 and nn.value == 2
    assert ln[0] == b"w" and ln[1] == b"w"
    back = onp.zeros(2, "f")
    capi.MXNDArraySyncCopyToCPU(la[0], back.ctypes.data_as(vp), back.nbytes)
    onp.testing.assert_allclose(back, [1.0, 1.0])
    capi.MXNDArraySyncCopyToCPU(la[1], back.ctypes.data_as(vp), back.nbytes)
    onp.testing.assert_allclose(back, [2.0, 2.0])
    for a in arrs:
        capi.MXNDArrayFree(a)


def test_data_iter_c_abi(capi, tmp_path):
    """MXListDataIters + CSVIter through the C handle API (reference:
    c_api.cc MXDataIterCreateIter family)."""
    vp, c_int, u32 = ctypes.c_void_p, ctypes.c_int, ctypes.c_uint32
    lib = capi
    lib.MXListDataIters.argtypes = [
        ctypes.POINTER(u32), ctypes.POINTER(ctypes.POINTER(ctypes.c_char_p))]
    lib.MXDataIterCreateIter.argtypes = [
        ctypes.c_char_p, u32, ctypes.POINTER(ctypes.c_char_p),
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(vp)]
    lib.MXDataIterFree.argtypes = [vp]
    lib.MXDataIterNext.argtypes = [vp, ctypes.POINTER(c_int)]
    lib.MXDataIterBeforeFirst.argtypes = [vp]
    lib.MXDataIterGetData.argtypes = [vp, ctypes.POINTER(vp)]
    lib.MXDataIterGetLabel.argtypes = [vp, ctypes.POINTER(vp)]
    lib.MXDataIterGetPadNum.argtypes = [vp, ctypes.POINTER(c_int)]

    n = u32()
    names = ctypes.POINTER(ctypes.c_char_p)()
    assert lib.MXListDataIters(ctypes.byref(n), ctypes.byref(names)) == 0
    listed = [names[i].decode() for i in range(n.value)]
    assert "CSVIter" in listed and "ImageRecordIter" in listed

    data = onp.arange(24, dtype="f").reshape(8, 3)
    labels = onp.arange(8, dtype="f")
    dcsv = tmp_path / "d.csv"
    lcsv = tmp_path / "l.csv"
    dcsv.write_text("\n".join(",".join(str(v) for v in row)
                              for row in data) + "\n")
    lcsv.write_text("\n".join(str(v) for v in labels) + "\n")

    keys = (ctypes.c_char_p * 4)(b"data_csv", b"data_shape",
                                 b"label_csv", b"batch_size")
    vals = (ctypes.c_char_p * 4)(str(dcsv).encode(), b"(3,)",
                                 str(lcsv).encode(), b"4")
    it = vp()
    rc = lib.MXDataIterCreateIter(b"CSVIter", 4, keys, vals,
                                  ctypes.byref(it))
    assert rc == 0, _err(lib)

    seen_rows = []
    for _epoch in range(2):  # BeforeFirst resets for a second epoch
        while True:
            has = c_int()
            assert lib.MXDataIterNext(it, ctypes.byref(has)) == 0
            if not has.value:
                break
            d = vp()
            assert lib.MXDataIterGetData(it, ctypes.byref(d)) == 0, _err(lib)
            ndim = c_int()
            shape = (i64 * 8)()
            assert lib.MXNDArrayGetShape(d, ctypes.byref(ndim), shape) == 0
            dims = tuple(shape[i] for i in range(ndim.value))
            assert dims == (4, 3)
            buf = (ctypes.c_float * 12)()
            assert lib.MXNDArraySyncCopyToCPU(
                d, ctypes.cast(buf, vp), ctypes.sizeof(buf)) == 0
            seen_rows.append(onp.array(buf).reshape(4, 3).copy())
            lab = vp()
            assert lib.MXDataIterGetLabel(it, ctypes.byref(lab)) == 0, \
                _err(lib)
            pad = c_int()
            assert lib.MXDataIterGetPadNum(it, ctypes.byref(pad)) == 0
            assert pad.value == 0
            lib.MXNDArrayFree(d)
            lib.MXNDArrayFree(lab)
        assert lib.MXDataIterBeforeFirst(it) == 0
    got = onp.concatenate(seen_rows)
    assert got.shape == (16, 3)
    onp.testing.assert_allclose(got[:8], data, rtol=1e-6)
    onp.testing.assert_allclose(got[8:], data, rtol=1e-6)  # epoch 2
    lib.MXDataIterFree(it)


C_HYBRID_TRAIN_PROGRAM = r"""
#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include "mxnet_tpu/c_api.h"

#define B 32
#define D 8
#define H 16
#define NC 2
#define CK(x) do { if ((x) != 0) { \
  fprintf(stderr, "%s\n", MXGetLastError()); return 1; } } while (0)

static unsigned lcg = 7u;
static float frand(void) {
  lcg = lcg * 1664525u + 1013904223u;
  return ((lcg >> 8) / 8388608.0f) - 1.0f;
}

static NDArrayHandle mk(int ndim, const int64_t* shape, const float* src,
                        int n) {
  NDArrayHandle h = NULL;
  if (MXNDArrayCreate(shape, ndim, 0, &h) != 0) return NULL;
  if (src != NULL &&
      MXNDArraySyncCopyFromCPU(h, src, n * sizeof(float)) != 0) return NULL;
  return h;
}

int main(void) {
  /* profiler on from the start (reference: MXSetProcessProfilerConfig) */
  const char* pk[3] = {"filename", "profile_imperative", "aggregate_stats"};
  const char* pv[3] = {"c_hybrid_profile.json", "True", "True"};
  CK(MXSetProcessProfilerConfig(3, pk, pv));
  CK(MXSetProcessProfilerState(1));
  CK(MXRandomSeed(17));

  /* compose the MLP symbol and hybridize it as a CachedOp */
  SymbolHandle data, fc1, act, fc2;
  CK(MXSymbolCreateVariable("data", &data));
  const char* kh = "num_hidden"; const char* ka = "act_type";
  const char* v16 = "16"; const char* v2 = "2"; const char* vr = "relu";
  CK(MXSymbolCreateAtomicSymbol("FullyConnected", 1, &kh, &v16, &fc1));
  CK(MXSymbolCompose(fc1, "fc1", 1, NULL, &data));
  CK(MXSymbolCreateAtomicSymbol("Activation", 1, &ka, &vr, &act));
  CK(MXSymbolCompose(act, "act", 1, NULL, &fc1));
  CK(MXSymbolCreateAtomicSymbol("FullyConnected", 1, &kh, &v2, &fc2));
  CK(MXSymbolCompose(fc2, "fc2", 1, NULL, &act));
  CachedOpHandle cop;
  CK(MXCreateCachedOp(fc2, &cop));

  /* inputs in list_arguments order: data, fc1_w, fc1_b, fc2_w, fc2_b */
  float X[B * D], y[B];
  for (int i = 0; i < B; ++i) {
    float s = 0.0f;
    for (int j = 0; j < D; ++j) { X[i * D + j] = frand(); s += X[i * D + j]; }
    y[i] = s > 0.0f ? 1.0f : 0.0f;
  }
  int64_t shx[2] = {B, D};
  NDArrayHandle hx = mk(2, shx, X, B * D);
  if (hx == NULL) { fprintf(stderr, "%s\n", MXGetLastError()); return 1; }

  int wsize[4] = {H * D, H, NC * H, NC};
  int64_t wsh[4][2] = {{H, D}, {H, 1}, {NC, H}, {NC, 1}};
  int wnd[4] = {2, 1, 2, 1};
  NDArrayHandle w[4], g[4];
  float wbuf[4][H * D];
  for (int i = 0; i < 4; ++i) {
    for (int j = 0; j < wsize[i]; ++j) wbuf[i][j] = 0.2f * frand();
    w[i] = mk(wnd[i], wsh[i], wbuf[i], wsize[i]);
    g[i] = mk(wnd[i], wsh[i], NULL, 0);
    if (w[i] == NULL || g[i] == NULL) {
      fprintf(stderr, "%s\n", MXGetLastError()); return 1;
    }
  }
  uint32_t reqs[4] = {1, 1, 1, 1};  /* write */
  CK(MXAutogradMarkVariables(4, w, reqs, g));

  float first = -1.0f, loss = 0.0f, lr = 0.5f;
  for (int step = 0; step < 80; ++step) {
    int prev_rec = 0, prev_train = 0;
    CK(MXAutogradSetIsRecording(1, &prev_rec));
    CK(MXAutogradSetIsTraining(1, &prev_train));
    NDArrayHandle ins[5] = {hx, w[0], w[1], w[2], w[3]};
    int nout = 0; NDArrayHandle* outs = NULL;
    CK(MXInvokeCachedOp(cop, 5, ins, &nout, &outs));
    if (nout != 1) { fprintf(stderr, "nout=%d\n", nout); return 3; }

    float logits[B * NC], dlogits[B * NC];
    CK(MXNDArraySyncCopyToCPU(outs[0], logits, sizeof(logits)));
    loss = 0.0f;
    for (int i = 0; i < B; ++i) {
      float m = logits[i * NC] > logits[i * NC + 1] ? logits[i * NC]
                                                    : logits[i * NC + 1];
      float e0 = expf(logits[i * NC] - m), e1 = expf(logits[i * NC + 1] - m);
      float z = e0 + e1;
      float p[2] = {e0 / z, e1 / z};
      loss -= logf(p[(int)y[i]] + 1e-9f) / B;
      dlogits[i * NC] = (p[0] - (y[i] < 0.5f ? 1.0f : 0.0f)) / B;
      dlogits[i * NC + 1] = (p[1] - (y[i] < 0.5f ? 0.0f : 1.0f)) / B;
    }
    if (first < 0.0f) first = loss;

    /* recording only needs to cover the forward; stop it before
     * creating host-seeded arrays (in-place fills are untapeable) */
    CK(MXAutogradSetIsRecording(0, &prev_rec));
    CK(MXAutogradSetIsTraining(0, &prev_train));
    int64_t shl[2] = {B, NC};
    NDArrayHandle hg = mk(2, shl, dlogits, B * NC);
    if (hg == NULL) { fprintf(stderr, "%s\n", MXGetLastError()); return 1; }
    NDArrayHandle heads[1] = {outs[0]};
    NDArrayHandle hgs[1] = {hg};
    CK(MXAutogradBackward(1, heads, hgs, 0, 1));
    MXNDArrayFree(hg);

    /* sgd step: pull grads through MXNDArrayGetGrad, update on host */
    for (int i = 0; i < 4; ++i) {
      NDArrayHandle gi = NULL;
      CK(MXNDArrayGetGrad(w[i], &gi));
      float gb[H * D];
      CK(MXNDArraySyncCopyToCPU(gi, gb, wsize[i] * sizeof(float)));
      MXNDArrayFree(gi);
      for (int j = 0; j < wsize[i]; ++j) wbuf[i][j] -= lr * gb[j];
      CK(MXNDArraySyncCopyFromCPU(w[i], wbuf[i],
                                  wsize[i] * sizeof(float)));
    }
  }

  CK(MXSetProcessProfilerState(0));
  const char* stats = NULL;
  CK(MXAggregateProfileStatsPrint(&stats, 0));
  if (stats == NULL || strstr(stats, "fully_connected") == NULL) {
    fprintf(stderr, "profiler stats missing ops:\n%s\n",
            stats ? stats : "(null)");
    return 4;
  }
  CK(MXDumpProcessProfile(1));
  FILE* f = fopen("c_hybrid_profile.json", "r");
  if (f == NULL) { fprintf(stderr, "no profile dump\n"); return 5; }
  fclose(f);

  if (!(loss < first * 0.5f)) {
    fprintf(stderr, "loss did not halve: %f -> %f\n", first, loss);
    return 2;
  }
  printf("C_HYBRID_TRAIN_OK %f -> %f\n", first, loss);
  MXFreeCachedOp(cop);
  return 0;
}
"""


def test_standalone_c_hybridize_train_profile(capi, tmp_path):
    """VERDICT r4 item 7 done-criterion: a C program that hybridizes
    (CachedOp), trains (autograd record/backward over the C ABI), and
    dumps a profile (profiler config/state/dump/stats)."""
    if shutil.which("gcc") is None:
        pytest.skip("no gcc")
    so = build_c_api()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    csrc = tmp_path / "hybrid_train.c"
    csrc.write_text(C_HYBRID_TRAIN_PROGRAM)
    exe = tmp_path / "chybrid"
    subprocess.run(
        ["gcc", str(csrc), "-o", str(exe), f"-I{repo}/include",
         so, "-lm", f"-Wl,-rpath,{os.path.dirname(so)}"],
        check=True, capture_output=True)
    env = dict(os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run([str(exe)], env=env, capture_output=True,
                          text=True, timeout=300, cwd=tmp_path)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "C_HYBRID_TRAIN_OK" in proc.stdout
    assert (tmp_path / "c_hybrid_profile.json").exists()


def test_cached_op_jit_cache_via_ctypes(capi):
    """Outside recording, repeated CachedOp invokes reuse one compiled
    callable per signature (the cache that makes it 'cached')."""
    import mxnet_tpu.c_bridge as cb
    from mxnet_tpu import sym as S

    x = S.var("data")
    net = S.FullyConnected(x, name="cfc", num_hidden=4)
    cop = cb.cached_op_create([net])
    a = nd.array(onp.ones((2, 3), "f"))
    pw = nd.array(onp.ones((4, 3), "f") * 0.1)
    pb = nd.array(onp.zeros((4,), "f"))
    o1 = cop([a, pw, pb])
    assert len(cop._jitted) == 1
    o2 = cop([a, pw, pb])
    assert len(cop._jitted) == 1
    onp.testing.assert_allclose(o1[0].asnumpy(), o2[0].asnumpy())
    b = nd.array(onp.ones((5, 3), "f"))
    cop([b, pw, pb])
    assert len(cop._jitted) == 2


def test_op_introspection_abi(capi):
    """MXListAllOpNames + MXSymbolGetAtomicSymbolInfo — the surface a
    frontend uses to autogenerate its op bindings (reference c_api.cc)."""
    lib = capi
    vp, u32, cp = ctypes.c_void_p, ctypes.c_uint32, ctypes.c_char_p
    lib.MXListAllOpNames.argtypes = [ctypes.POINTER(u32),
                                     ctypes.POINTER(ctypes.POINTER(cp))]
    n = u32()
    arr = ctypes.POINTER(cp)()
    assert lib.MXListAllOpNames(ctypes.byref(n), ctypes.byref(arr)) == 0
    names = [arr[i].decode() for i in range(n.value)]
    assert n.value > 300, n.value
    assert "convolution" in names and "fully_connected" in names

    lib.MXSymbolGetAtomicSymbolInfo.argtypes = [
        cp, ctypes.POINTER(cp), ctypes.POINTER(cp), ctypes.POINTER(u32),
        ctypes.POINTER(ctypes.POINTER(cp)),
        ctypes.POINTER(ctypes.POINTER(cp))]
    nm, desc = cp(), cp()
    na = u32()
    an = ctypes.POINTER(cp)()
    ad = ctypes.POINTER(cp)()
    assert lib.MXSymbolGetAtomicSymbolInfo(
        b"convolution", ctypes.byref(nm), ctypes.byref(desc),
        ctypes.byref(na), ctypes.byref(an), ctypes.byref(ad)) == 0
    assert nm.value == b"convolution"
    args = [an[i].decode() for i in range(na.value)]
    assert "data" in args and "kernel" in args
    defaults = [ad[i].decode() for i in range(na.value)]
    assert defaults[args.index("num_group")] == "1"
    # unknown op errors cleanly
    assert lib.MXSymbolGetAtomicSymbolInfo(
        b"no_such_op", ctypes.byref(nm), ctypes.byref(desc),
        ctypes.byref(na), ctypes.byref(an), ctypes.byref(ad)) == -1


def test_infer_shape_type_abi(capi):
    """MXSymbolInferShape/InferType over a composed MLP."""
    lib = capi
    vp, u32, cp = ctypes.c_void_p, ctypes.c_uint32, ctypes.c_char_p
    lib.MXSymbolInferShape.argtypes = [
        vp, u32, ctypes.POINTER(cp), ctypes.POINTER(u32),
        ctypes.POINTER(i64), ctypes.POINTER(u32),
        ctypes.POINTER(ctypes.POINTER(i64)),
        ctypes.POINTER(ctypes.POINTER(i64)),
        ctypes.POINTER(ctypes.POINTER(i64))]
    lib.MXSymbolInferType.argtypes = [
        vp, u32, ctypes.POINTER(cp), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(u32), ctypes.POINTER(ctypes.POINTER(ctypes.c_int)),
        ctypes.POINTER(ctypes.POINTER(i64))]

    data = vp()
    fc = vp()
    assert capi.MXSymbolCreateVariable(b"data", ctypes.byref(data)) == 0
    kh, v8 = ctypes.c_char_p(b"num_hidden"), ctypes.c_char_p(b"8")
    assert capi.MXSymbolCreateAtomicSymbol(
        b"FullyConnected", 1, ctypes.byref(kh), ctypes.byref(v8),
        ctypes.byref(fc)) == 0
    assert capi.MXSymbolCompose(fc, b"fc", 1, None, ctypes.byref(data)) == 0

    keys = (cp * 1)(b"data")
    indptr = (u32 * 2)(0, 2)
    dims = (i64 * 2)(4, 16)
    total = u32()
    ndims = ctypes.POINTER(i64)()
    ddata = ctypes.POINTER(i64)()
    sect = ctypes.POINTER(i64)()
    assert lib.MXSymbolInferShape(
        fc, 1, keys, indptr, dims, ctypes.byref(total),
        ctypes.byref(ndims), ctypes.byref(ddata),
        ctypes.byref(sect)) == 0, _err(capi)
    n_args, n_outs, n_aux = sect[0], sect[1], sect[2]
    assert n_args == 3 and n_outs == 1 and n_aux == 0
    # walk the flattened dims: data(4,16), fc_weight(8,16), fc_bias(8)
    shapes = []
    off = 0
    for i in range(total.value):
        nd_ = ndims[i]
        if nd_ < 0:
            shapes.append(None)
        else:
            shapes.append(tuple(ddata[off + d] for d in range(nd_)))
            off += nd_
    assert shapes[0] == (4, 16)
    assert shapes[1] == (8, 16)
    assert shapes[2] == (8,)
    assert shapes[3] == (4, 8)  # output

    tkeys = (cp * 1)(b"data")
    tflags = (ctypes.c_int * 1)(0)  # 0 = float32
    ttotal = u32()
    ttypes = ctypes.POINTER(ctypes.c_int)()
    tsect = ctypes.POINTER(i64)()
    assert lib.MXSymbolInferType(
        fc, 1, tkeys, tflags, ctypes.byref(ttotal), ctypes.byref(ttypes),
        ctypes.byref(tsect)) == 0, _err(capi)
    assert ttotal.value == 4
    assert all(ttypes[i] == 0 for i in range(4))  # all float32


def test_nd_at_and_context_abi(capi):
    lib = capi
    vp, u32 = ctypes.c_void_p, ctypes.c_uint32
    lib.MXNDArrayAt.argtypes = [vp, u32, ctypes.POINTER(vp)]
    lib.MXNDArrayGetContext.argtypes = [vp, ctypes.POINTER(ctypes.c_int),
                                        ctypes.POINTER(ctypes.c_int)]
    shape = (i64 * 2)(3, 4)
    h = vp()
    assert capi.MXNDArrayCreate(shape, 2, 0, ctypes.byref(h)) == 0
    buf = onp.arange(12, dtype="f")
    assert capi.MXNDArraySyncCopyFromCPU(
        h, buf.ctypes.data_as(vp), buf.nbytes) == 0
    row = vp()
    assert lib.MXNDArrayAt(h, 1, ctypes.byref(row)) == 0
    out = onp.zeros(4, "f")
    assert capi.MXNDArraySyncCopyToCPU(
        row, out.ctypes.data_as(vp), out.nbytes) == 0
    onp.testing.assert_allclose(out, buf.reshape(3, 4)[1])
    dt, di = ctypes.c_int(), ctypes.c_int()
    assert lib.MXNDArrayGetContext(h, ctypes.byref(dt),
                                   ctypes.byref(di)) == 0
    assert dt.value in (1, 2)
    capi.MXNDArrayFree(row)
    capi.MXNDArrayFree(h)


def test_infer_shape_reports_aux_shapes(capi):
    """Aux states (BN moving stats) must come back with real shapes —
    frontends allocate them from MXSymbolInferShape (r5 review fix)."""
    import mxnet_tpu.c_bridge as cb

    data = cb.sym_var("data")
    bn = cb.sym_create_atomic("BatchNorm", [], [])
    cb.sym_compose(bn, "bn", [], [data])
    args, arg_shapes, out_shapes, auxs, aux_shapes = cb.sym_infer_shape(
        bn, ["data"], [(2, 4)])
    assert auxs == ["bn_moving_mean", "bn_moving_var"]
    assert aux_shapes == [(4,), (4,)], aux_shapes
