"""Expert-parallel MoE (parallel/moe.py): sharded == single-device,
routing respects capacity, aux loss behaves, gradients flow.
Runs on the 8-device virtual CPU mesh from conftest.
"""
import math

import numpy as onp
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu import parallel
from mxnet_tpu.parallel.moe import moe_ffn, switch_router


def _params(rng, E=4, D=8, H=16):
    gate_w = jnp.asarray(rng.randn(D, E).astype("f") * 0.5)
    w1 = jnp.asarray(rng.randn(E, D, H).astype("f") * 0.2)
    b1 = jnp.zeros((E, H), jnp.float32)
    w2 = jnp.asarray(rng.randn(E, H, D).astype("f") * 0.2)
    b2 = jnp.zeros((E, D), jnp.float32)
    return gate_w, w1, b1, w2, b2


def test_moe_sharded_matches_single_device():
    rng = onp.random.RandomState(0)
    B, S, D, E = 8, 4, 8, 4
    x = jnp.asarray(rng.randn(B, S, D).astype("f"))
    params = _params(rng, E=E, D=D)
    # single shard (no mesh axis): reference result
    ref, aux_ref = moe_ffn(x, *params, mesh=None, capacity_factor=4.0)
    # dp2 x ep4 over the 8 virtual devices
    mesh = parallel.make_mesh({"dp": 2, "ep": 4})
    out, aux = moe_ffn(x, *params, mesh=mesh, capacity_factor=4.0)
    # generous capacity -> no token dropped on either path -> identical
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=2e-4, atol=2e-5)
    # aux is the standard per-shard estimator averaged over devices
    # (Switch/GShard do the same): close to but not identical with the
    # global-batch statistic, and bounded by the same [1, E] range
    assert 0.9 <= float(aux) <= 4.0 and 0.9 <= float(aux_ref) <= 4.0


def test_moe_capacity_drops_tokens_to_zero():
    rng = onp.random.RandomState(1)
    D, E = 4, 2
    # all tokens forced to one expert by a huge gate bias
    x = jnp.asarray(rng.randn(1, 6, D).astype("f"))
    gate_w = jnp.zeros((D, E), jnp.float32).at[:, 0].set(10.0)
    w1 = jnp.ones((E, D, 4), jnp.float32)
    b1 = jnp.zeros((E, 4), jnp.float32)
    w2 = jnp.ones((E, 4, D), jnp.float32)
    b2 = jnp.zeros((E, D), jnp.float32)
    out, _ = moe_ffn(x, gate_w, w1, b1, w2, b2, mesh=None,
                     capacity_factor=1.0 / 3.0)  # capacity 1 of 6 tokens
    o = onp.asarray(out).reshape(6, D)
    nonzero_rows = (onp.abs(o) > 1e-7).any(axis=1).sum()
    assert nonzero_rows == 1  # only the first-routed token fits


def test_switch_router_properties():
    rng = onp.random.RandomState(2)
    x = jnp.asarray(rng.randn(32, 8).astype("f"))
    gate_w = jnp.asarray(rng.randn(8, 4).astype("f"))
    disp, comb, aux = switch_router(x, gate_w, 4, capacity=32)
    d = onp.asarray(disp)
    # each token occupies at most one (expert, slot)
    assert (d.sum(axis=(1, 2)) <= 1.0 + 1e-6).all()
    # slots within an expert are unique
    assert (d.sum(axis=0) <= 1.0 + 1e-6).all()
    # aux loss: >= 1 (uniform lower bound), small for random gates
    assert 0.9 < float(aux) < 4.0
    # combine carries the gate probability
    c = onp.asarray(comb)
    assert ((c > 0) <= (d > 0)).all()


def test_moe_gradients_flow_through_experts_and_router():
    rng = onp.random.RandomState(3)
    B, S, D, E = 4, 2, 8, 4
    x = jnp.asarray(rng.randn(B, S, D).astype("f"))
    params = _params(rng, E=E, D=D)

    def loss_fn(ps, xv):
        out, aux = moe_ffn(xv, *ps, mesh=None, capacity_factor=4.0)
        return jnp.sum(out ** 2) + 0.01 * aux

    grads = jax.grad(loss_fn)(params, x)
    for g, name in zip(grads, ["gate_w", "w1", "b1", "w2", "b2"]):
        assert onp.isfinite(onp.asarray(g)).all(), name
    # expert weights receive gradient (at least the used experts)
    assert onp.abs(onp.asarray(grads[1])).sum() > 0


def test_moe_trains_under_jit_on_mesh():
    rng = onp.random.RandomState(4)
    B, S, D, E = 8, 4, 8, 4
    mesh = parallel.make_mesh({"dp": 2, "ep": 4})
    x = jnp.asarray(rng.randn(B, S, D).astype("f"))
    y = jnp.asarray(rng.randn(B, S, D).astype("f"))
    params = list(_params(rng, E=E, D=D))

    @jax.jit
    def step(ps, xv, yv):
        def loss_fn(p):
            out, aux = moe_ffn(xv, p[0], p[1], p[2], p[3], p[4],
                               mesh=mesh, capacity_factor=2.0)
            return jnp.mean((out - yv) ** 2) + 0.01 * aux

        l, g = jax.value_and_grad(loss_fn)(tuple(ps))
        return l, [p - 0.1 * gi for p, gi in zip(ps, g)]

    first = None
    for _ in range(10):
        l, params = step(params, x, y)
        first = first or float(l)
    assert float(l) < first, (first, float(l))


def test_gluon_switch_moe_layer_trains(tmp_path):
    """The Gluon face: SwitchMoE inside a HybridBlock trains under
    gluon.Trainer on an expert-parallel mesh."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon.contrib.nn import SwitchMoE

    mesh = parallel.make_mesh({"dp": 2, "ep": 4})
    mx.random.seed(0)
    moe = SwitchMoE(num_experts=8, hidden_size=16, capacity_factor=2.0,
                    mesh=mesh)
    moe.initialize(mx.init.Xavier())
    rng = onp.random.RandomState(0)
    x = nd.array(rng.rand(8, 4, 8).astype("f"))
    y = nd.array((rng.rand(8, 4, 8) * 0.5).astype("f"))
    out, aux = moe(x)
    assert out.shape == x.shape and aux.shape == ()
    tr = gluon.Trainer(moe.collect_params(), "adam",
                       {"learning_rate": 5e-3})
    first = None
    # eager mesh dispatch costs ~4s/step on one core; 4 steps are
    # enough to show the loss moving under the Trainer
    for _ in range(4):
        with autograd.record():
            o, aux = moe(x)
            loss = nd.mean((x + o - y) ** 2) + 0.01 * aux
        loss.backward()
        tr.step(8)
        first = first or float(loss.asscalar())
    assert float(loss.asscalar()) < first, (first, float(loss.asscalar()))
    # params round-trip like any gluon block
    f = str(tmp_path / "moe.params")
    moe.save_parameters(f)
    moe2 = SwitchMoE(num_experts=8, hidden_size=16, in_units=8,
                     capacity_factor=2.0, mesh=mesh)
    moe2.load_parameters(f)
    o2, _ = moe2(x)
    with_np = onp.asarray(o2.asnumpy())
    assert onp.isfinite(with_np).all()
